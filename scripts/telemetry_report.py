#!/usr/bin/env python
"""Render a run's telemetry JSONL as a per-pass summary table.

Usage: python scripts/telemetry_report.py RUN.jsonl [--events]

Reads the event stream the TelemetryHub's JsonlSink wrote
(FLAGS_telemetry_jsonl=..., or bench.py's BENCH_telemetry.jsonl) and
prints one row per pass: throughput, stage breakdown, queue stalls
(diffed from the cumulative channel counters between consecutive pass
events of the same process), table occupancy and the HBM peak.
``--events`` appends the non-pass events (stragglers, scatter warmups)
at the end. Stdlib only — runs anywhere the JSONL lands.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional


def expand_rotated(path: str) -> List[str]:
    """A rotated JSONL set (obs/sinks.JsonlSink with
    ``FLAGS_telemetry_jsonl_max_mb``) read oldest-first:
    ``path.<K> … path.1`` then the live ``path``. A path with no
    rotated siblings expands to itself."""
    segs = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        segs.append(f"{path}.{i}")
        i += 1
    segs.reverse()               # .N is oldest, .1 newest rotated
    if os.path.exists(path) or not segs:
        segs.append(path)
    return segs


def load_events(path: str) -> List[dict]:
    """All events for ``path``'s rotated segment set, oldest first. A
    torn line (a process killed mid-write leaves a truncated tail —
    and the next append can land after it) is skipped with a warning,
    never a crash: the report must render what survived."""
    events = []
    for seg in expand_rotated(path):
        with open(seg) as fh:
            lines = fh.readlines()
        for ln, line in enumerate(lines, 1):
            stripped = line.strip()
            if not stripped:
                continue
            try:
                events.append(json.loads(stripped))
            except json.JSONDecodeError:
                torn_tail = (ln == len(lines)
                             and not line.endswith("\n"))
                print(f"warning: {seg}:{ln}: "
                      + ("torn final line skipped (writer killed "
                         "mid-write?)" if torn_tail
                         else "bad JSON line skipped"),
                      file=sys.stderr)
    return events


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _stage_cell(stage_sec: Dict[str, float], top: int = 4) -> str:
    items = sorted(stage_sec.items(), key=lambda kv: -kv[1])[:top]
    return " ".join(f"{k}={v:.3f}s" for k, v in items) or "-"


def _chan_blocked(ch: Dict[str, dict]) -> Dict[str, float]:
    return {name: st.get("blocked_put_sec", 0.0)
            + st.get("blocked_get_sec", 0.0)
            for name, st in ch.items()}


#: tiered per-pass begin_stall attribution (ps/tiered.begin_pass →
#: last_pass_stats, riding every pass event as table.last_pass):
#: column label → stats key. Seconds render only when non-zero so
#: resident rows stay compact.
BEGIN_STALL_COLS = (
    ("stage", "stage_wait_sec"),
    ("evS", "evict_scatter_sec"),
    ("evA", "evict_async_sec"),
    ("evE", "evict_emergency_sec"),
    ("ssdW", "ssd_promote_wait_sec"),
)


def _bottleneck_cell(cp: Dict) -> str:
    """Render a pass event's critical_path block (obs/trace): the
    bottleneck verdict plus the stall it names — 'device (+0.012s
    stalls)' or 'build_wait +0.740s'."""
    if not cp or "bottleneck" not in cp:
        return ""
    b = cp["bottleneck"]
    stall = float(cp.get("stall_sec", 0.0) or 0.0)
    if b == "device":
        return f"device (+{stall:.3f}s stalls)"
    return f"{b} +{stall:.3f}s"


def _a2a_cell(ev: Dict) -> str:
    """Per-pass exchange-overlap fraction (ISSUE 11): how much of the
    sharded step's embedding all_to_all the chunked schedule hid behind
    compute (train/a2a_probe, riding the pass event when the sharded
    bench ran the probe; the critical_path's exchange_wait_sec is the
    remainder)."""
    v = ev.get("exchange_overlap_frac")
    if v is None:
        cp = ev.get("critical_path") or {}
        w = cp.get("exchange_wait_sec")
        return f"wait {float(w):.3f}s" if w is not None else ""
    return f"{float(v):.0%}"


def _begin_stall_cell(lp: Dict) -> str:
    """Render a pass event's begin_stall breakdown (tiered runs) —
    the per-stage boundary attribution without jq archaeology."""
    if not lp or "stage_wait_sec" not in lp:
        return ""
    bits = [f"{label}={lp[key]:.3f}s" for label, key in BEGIN_STALL_COLS
            if float(lp.get(key, 0.0) or 0.0) > 5e-4]
    rows = int(lp.get("evict_async_rows", 0) or 0)
    if rows:
        bits.append(f"evA_rows={rows}")
    return " ".join(bits) or "~0"


def _serving_cell(st: Optional[Dict]) -> str:
    """Render the latest ``serving_stats`` event (serving.ReloadLoop)
    seen before this pass: the serving-latency column for
    serve-while-training runs — 'p99 5.99ms @v0000000003 (+2.1s
    stale)'. Empty when the run has no serving model."""
    if not st:
        return ""
    p99 = st.get("predict_p99_ms", st.get("lookup_p99_ms"))
    bits = []
    if p99 is not None:
        bits.append(f"p99 {float(p99):.2f}ms")
    if st.get("adopted"):
        bits.append(f"@{st['adopted']}")
    stale = float(st.get("staleness_sec", 0.0) or 0.0)
    if stale > 0:
        bits.append(f"(+{stale:.1f}s stale)")
    return " ".join(bits)


def build_rows(events: List[dict]) -> List[Dict[str, str]]:
    """Pass events → printable row dicts (the unit tests call this)."""
    rows = []
    prev_blocked: Dict[int, Dict[str, float]] = {}  # per process
    last_serving: Optional[Dict] = None
    any_serving = any(e.get("event") == "serving_stats" for e in events)
    # alert timeline column (obs/alerts): the rules firing as of each
    # pass, tracked from the alert_fired/alert_cleared stream
    any_alerts = any(e.get("event") in ("alert_fired", "alert_cleared")
                     for e in events)
    # feature-lifecycle column (docs/ONLINE.md): the shrink cycle (or
    # loud skip) landing between passes, shown on the next pass row
    any_lifecycle = any(e.get("event") in ("online_shrink",
                                           "online_shrink_skipped")
                        for e in events)
    last_shrink = ""
    last_lag: Optional[int] = None
    firing: List[str] = []
    for ev in events:
        if ev.get("event") == "stream_window" and "lag_files" in ev:
            last_lag = int(ev["lag_files"])
        if ev.get("event") == "alert_fired":
            if ev.get("rule") not in firing:
                firing.append(str(ev.get("rule")))
            continue
        if ev.get("event") == "alert_cleared":
            if ev.get("rule") in firing:
                firing.remove(ev.get("rule"))
            continue
        if ev.get("event") == "serving_stats":
            last_serving = ev
            continue
        if ev.get("event") == "online_shrink":
            last_shrink = (f"w{ev.get('window', '?')}:"
                           f"-{ev.get('freed', 0)}"
                           f" ({ev.get('live_rows', '?')} live)")
            continue
        if ev.get("event") == "online_shrink_skipped":
            last_shrink = f"w{ev.get('window', '?')}:SKIPPED"
            continue
        if ev.get("event") != "pass":
            continue
        proc = int(ev.get("proc", 0))
        stall = ""
        if "channels" in ev:
            cur = _chan_blocked(ev["channels"])
            prev = prev_blocked.get(proc, {})
            delta = sum(v - prev.get(k, 0.0) for k, v in cur.items())
            depth = sum(st.get("depth", 0)
                        for st in ev["channels"].values())
            stall = f"{max(delta, 0.0):.3f}s (depth {int(depth)})"
            prev_blocked[proc] = cur
        tbl = ""
        begin_stall = ""
        if "table" in ev:
            t = ev["table"]
            if "used" in t and "capacity" in t:
                tbl = f"{t['used']}/{t['capacity']}"
            lp = t.get("last_pass")
            if lp:
                tbl += (f" (+{lp.get('staged', 0)} staged,"
                        f" -{lp.get('evicted', 0)} evicted)")
                # tiered begin_stall attribution (ISSUE 9): the
                # boundary's per-stage seconds as their own column
                begin_stall = _begin_stall_cell(lp)
            eps = t.get("endpass")
            if eps and eps.get("jobs_run"):
                # async epilogue (docs/PERFORMANCE.md): cumulative
                # write-back vs the part that never blocked the main
                # thread — ovl ≈ wb means the epilogue is free
                tbl += (f" [wb {eps.get('writeback_sec', 0):.2f}s"
                        f" ovl {eps.get('overlap_sec', 0):.2f}s]")
        hbm = ev.get("hbm", {})
        rows.append({
            "pass": str(ev.get("pass_seq", len(rows) + 1)),
            "proc": str(proc),
            "kind": str(ev.get("kind", "?")),
            "batches": str(ev.get("batches", "?")),
            "examples": str(ev.get("examples", "?")),
            "ex/s": (f"{ev['examples_per_sec']:.0f}"
                     if "examples_per_sec" in ev else "?"),
            "wall": (f"{ev['elapsed_sec']:.3f}s"
                     if "elapsed_sec" in ev else "?"),
            "stages": _stage_cell(ev.get("stage_sec", {})),
            "queue stall": stall or "-",
            "table": tbl or "-",
            "begin stall": begin_stall or "-",
            "bottleneck": _bottleneck_cell(ev.get("critical_path", {}))
            or "-",
            "a2a ovl": _a2a_cell(ev) or "-",
            "hbm peak": _fmt_bytes(hbm.get("peak_bytes_in_use", 0)),
        })
        if any_serving:
            # serving-latency column only when the run served (a
            # training-only JSONL keeps its compact row)
            rows[-1]["serve p99"] = _serving_cell(last_serving) or "-"
        if any_alerts:
            # alert timeline column only when the run alerted: which
            # rules were firing as of this pass
            rows[-1]["alerts"] = ",".join(firing) or "-"
        if any_lifecycle:
            # lifecycle column only when shrink cycles ran: the cycle
            # (rows freed, live rows after) or loud skip since the
            # previous pass row, plus the stream backlog as of the
            # latest window boundary
            cell = last_shrink or "-"
            if last_lag is not None:
                cell += f" lag {last_lag}"
            rows[-1]["lifecycle"] = cell
            last_shrink = ""
    return rows


def render_table(rows: List[Dict[str, str]]) -> str:
    if not rows:
        return "no pass events"
    cols = list(rows[0].keys())
    widths = {c: max(len(c), *(len(r[c]) for r in rows)) for c in cols}
    lines = ["  ".join(c.ljust(widths[c]) for c in cols),
             "  ".join("-" * widths[c] for c in cols)]
    for r in rows:
        lines.append("  ".join(r[c].ljust(widths[c]) for c in cols))
    return "\n".join(lines)


#: preemption / recovery lifecycle events rendered as their own
#: timeline (docs/RESILIENCE.md §Preemption & mid-pass resume)
RECOVERY_EVENTS = ("preempt_requested", "emergency_checkpoint",
                   "inpass_checkpoint", "cursor_resume",
                   "restore_consensus", "pass_retry")


def _fmt_recovery(ev: dict) -> str:
    name = ev.get("event", "?")
    bits = []
    for k in ("reason", "kind", "global_step", "batch_index", "agreed",
              "attempt"):
        if k in ev:
            bits.append(f"{k}={ev[k]}")
    return f"{name}({', '.join(bits)})" if bits else name


def critical_path_summary(events: List[dict]) -> str:
    """Whole-run critical-path verdict from the passes' critical_path
    blocks (obs/trace): the majority verdict plus each minority pass
    called out with its stall — '7/8 passes device-bound, pass 2
    build_wait-bound: +0.740s'. Empty when no pass carried a block."""
    cps = []
    for ev in events:
        if ev.get("event") != "pass":
            continue
        cp = ev.get("critical_path")
        if cp and "bottleneck" in cp:
            cps.append((str(ev.get("pass_seq", len(cps) + 1)), cp))
    if not cps:
        return ""
    counts: Dict[str, int] = {}
    for _, cp in cps:
        counts[cp["bottleneck"]] = counts.get(cp["bottleneck"], 0) + 1
    major = max(counts, key=counts.get)
    bits = [f"{counts[major]}/{len(cps)} passes {major}-bound"]
    for seq, cp in cps:
        if cp["bottleneck"] != major:
            bits.append(f"pass {seq} {cp['bottleneck']}-bound: "
                        f"+{float(cp.get('stall_sec', 0.0)):.3f}s")
    stall_tot = sum(float(cp.get("stall_sec", 0.0) or 0.0)
                    for _, cp in cps if cp["bottleneck"] != "device")
    if stall_tot > 5e-4:
        bits.append(f"non-device stalls total +{stall_tot:.3f}s")
    return "critical path: " + ", ".join(bits)


def serving_summary(events: List[dict]) -> str:
    """Whole-run serving verdict from the serving_* events
    (serving.ReloadLoop; docs/SERVING.md): adoption count, refusals/
    degrades, the final adopted version, peak staleness and the last
    observed p99 — 'serving: 4 reloads → v0000000005, p99 0.21ms, max
    staleness 0.4s'. Empty when the run served nothing."""
    reloads = [e for e in events if e.get("event") == "serving_reload"]
    refused = [e for e in events
               if e.get("event") == "serving_reload_refused"]
    degraded = [e for e in events
                if e.get("event") == "serving_degraded"]
    stats = [e for e in events if e.get("event") == "serving_stats"]
    if not (reloads or refused or degraded or stats):
        return ""
    bits = [f"{len(reloads)} reloads"]
    adopted = (reloads[-1].get("artifact") if reloads
               else stats[-1].get("adopted") if stats else None)
    if adopted:
        bits[-1] += f" → {adopted}"
    if refused:
        bits.append(f"{len(refused)} refused")
    if degraded:
        bits.append(f"{len(degraded)} degraded polls")
    last_p99 = next(
        (e.get("predict_p99_ms", e.get("lookup_p99_ms"))
         for e in reversed(stats)
         if e.get("predict_p99_ms") is not None
         or e.get("lookup_p99_ms") is not None), None)
    if last_p99 is not None:
        bits.append(f"p99 {float(last_p99):.2f}ms")
    stale = max((float(e.get("staleness_sec", 0.0) or 0.0)
                 for e in stats + degraded), default=0.0)
    if stale > 0:
        bits.append(f"max staleness {stale:.1f}s")
    return "serving: " + ", ".join(bits)


def alerts_summary(events: List[dict]) -> str:
    """Whole-run alert timeline (obs/alerts): every fire/clear
    transition in order — 'alerts: stream_lag fired(seq 12) ->
    stream_lag cleared(seq 19); 1 still firing'. Empty when the run
    never alerted."""
    transitions = [e for e in events
                   if e.get("event") in ("alert_fired",
                                         "alert_cleared")]
    if not transitions:
        return ""
    bits = []
    open_rules: List[str] = []
    for e in transitions:
        rule = str(e.get("rule", "?"))
        if e.get("event") == "alert_fired":
            if rule not in open_rules:
                open_rules.append(rule)
            bits.append(f"{rule} fired(seq {e.get('seq', '?')})")
        else:
            if rule in open_rules:
                open_rules.remove(rule)
            bits.append(f"{rule} cleared(seq {e.get('seq', '?')})")
    line = "alerts: " + " -> ".join(bits)
    if open_rules:
        line += f"; still firing: {','.join(open_rules)}"
    return line


def membership_summary(events: List[dict]) -> str:
    """Elastic membership timeline (distributed/elastic +
    train/multihost; docs/RESILIENCE.md §Elastic membership): every
    ``membership_change`` and completed ``reshard`` in order —
    'membership: np=3 (lost h1) -> reshard 4->3 @step 2 -> np=4
    (joined h1)'. Ends with a degraded flag when the run finished below
    its target world size. Empty when the world never changed."""
    rel = [e for e in events
           if e.get("event") in ("membership_change", "reshard")]
    if not rel:
        return ""
    bits = []
    for e in rel:
        if e.get("event") == "membership_change":
            delta = []
            if e.get("lost"):
                delta.append("lost " + ",".join(e["lost"]))
            if e.get("joined"):
                delta.append("joined " + ",".join(e["joined"]))
            bits.append(f"np={e.get('np', '?')}"
                        + (f" ({'; '.join(delta)})" if delta else ""))
        else:
            bits.append(f"reshard {e.get('old_np', '?')}->"
                        f"{e.get('new_np', '?')} @step {e.get('step', '?')}")
    line = "membership: " + " -> ".join(bits)
    changes = [e for e in rel if e.get("event") == "membership_change"]
    if changes:
        last = changes[-1]
        np_, tgt = last.get("np"), last.get("target_np")
        if isinstance(np_, int) and isinstance(tgt, int) and np_ < tgt:
            line += f"; still degraded ({np_}/{tgt})"
    return line


def bundles_summary(events: List[dict]) -> str:
    """Flight-recorder bundle pointers (obs/flightrec): every
    ``blackbox_dump`` the run published, trigger + path — the first
    thing a postmortem reaches for. Empty when nothing triggered."""
    dumps = [e for e in events if e.get("event") == "blackbox_dump"]
    if not dumps:
        return ""
    return "bundles: " + ", ".join(
        f"{e.get('trigger', '?')} -> {e.get('path', '?')}"
        for e in dumps)


def render_report(events: List[dict], show_events: bool = False) -> str:
    rows = build_rows(events)
    out = [render_table(rows)]
    passes = [e for e in events if e.get("event") == "pass"]
    if passes:
        tot_ex = sum(e.get("examples", 0) or 0 for e in passes)
        tot_wall = sum(e.get("elapsed_sec", 0.0) or 0.0 for e in passes)
        out.append("")
        out.append(f"{len(passes)} passes, {tot_ex} examples, "
                   f"{tot_wall:.3f}s inside passes"
                   + (f", {tot_ex / tot_wall:.0f} ex/s overall"
                      if tot_wall > 0 else ""))
    cp_line = critical_path_summary(events)
    if cp_line:
        out.append(cp_line)
    sv_line = serving_summary(events)
    if sv_line:
        out.append(sv_line)
    al_line = alerts_summary(events)
    if al_line:
        out.append(al_line)
    mb_line = membership_summary(events)
    if mb_line:
        out.append(mb_line)
    bx_line = bundles_summary(events)
    if bx_line:
        out.append(bx_line)
    recovery = [e for e in events if e.get("event") in RECOVERY_EVENTS]
    if recovery:
        out.append("recovery: " + " -> ".join(_fmt_recovery(e)
                                              for e in recovery))
    other = [e for e in events if e.get("event") != "pass"]
    if other:
        counts: Dict[str, int] = {}
        for e in other:
            counts[e.get("event", "?")] = counts.get(e.get("event", "?"),
                                                     0) + 1
        out.append("other events: " + ", ".join(
            f"{k}×{v}" for k, v in sorted(counts.items())))
        if show_events:
            out.extend(json.dumps(e) for e in other)
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    show_events = "--events" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(__doc__, file=sys.stderr)
        return 2
    for path in paths:
        if len(paths) > 1:
            print(f"== {path}")
        print(render_report(load_events(path), show_events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
