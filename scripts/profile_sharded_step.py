#!/usr/bin/env python
"""XPlane op-level attribution of the sharded resident step (round 5):
where do the ~32 ms/step go that the single-chip step doesn't pay?

Default mode builds the sharded uniform bench shape, stages one
resident pass, runs it wire-free under jax.profiler, and prints the top
device ops by self-time.

``--a2a-chunks 1,2,4,8`` (ISSUE 11) instead sweeps the chunked
exchange schedule: for each chunk count it builds a grouped routing
plan and prints the PER-CHUNK exchange vs pool seconds plus the
fused-schedule A/B (train/a2a_probe) — chunk-width tuning without a
full bench run. ``--records``/``--batch-size`` shrink the workload for
quick sweeps.
"""
import argparse
import glob
import json
import os
import sys
import tempfile
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
ap.add_argument("--a2a-chunks", default=None,
                help="comma list of chunk counts to sweep (e.g. 1,2,4,8)"
                "; omit for the XPlane op-attribution mode")
ap.add_argument("--records", type=int, default=None,
                help="records per pass (default: 262144, or 32768 in "
                "sweep mode)")
ap.add_argument("--batch-size", type=int, default=None,
                help="per-device batch size (default: 8192, or 2048 in "
                "sweep mode)")
args = ap.parse_args()
sweep = ([int(x) for x in args.a2a_chunks.split(",")]
         if args.a2a_chunks else None)

import jax
import optax

from bench import build_records
from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import SparseSGDConfig
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.train.sharded import ShardedTrainer

FLAGS.log_period_steps = 10 ** 9
FLAGS.auc_device_reduce = True
bs = args.batch_size or (2048 if sweep else 8192)
n_rec = args.records or (32_768 if sweep else 262_144)
slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 13)]
slots += [SlotDef(f"C{i}", "uint64") for i in range(1, 27)]
desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                    key_bucket_min=bs * 26)
ds = InMemoryDataset(desc)
ds.records = build_records(n_rec, num_slots=26, vocab_per_slot=100_000,
                           seed=0)
ds.columnarize()
cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
chips = len(jax.devices())
mesh = make_mesh(chips)
table = ShardedEmbeddingTable(chips, mf_dim=8,
                              capacity_per_shard=(1 << 23) // chips,
                              cfg=cfg, req_bucket_min=1 << 12,
                              serve_bucket_min=1 << 12)
tr = ShardedTrainer(DeepFM(hidden=(512, 256, 128)), table, desc, mesh,
                    tx=optax.adam(1e-3), float_wire="q8")

if sweep:
    # chunk-width sweep: per-chunk exchange vs pool seconds + the
    # fused-schedule A/B, one line per width (train/a2a_probe — the
    # same grouped plans the training step would build)
    from paddlebox_tpu.train.a2a_probe import probe_exchange
    group = next(iter(tr._group_iter(ds.batches())))
    for c in sweep:
        pr = probe_exchange(tr, group=group, chunks=c)
        print(json.dumps({"probe": "a2a_sweep", "chunks": pr["a2a_chunks"],
                          **{k: pr[k] for k in (
                              "a2a_sections", "a2a_pull_sec", "pool_sec",
                              "serve_sec", "dense_sec", "push_sec",
                              "dense_sync_sec", "step_monolithic_sec",
                              "step_chunked_sec", "exchange_sec_total",
                              "exchange_overlap_frac",
                              "exchange_wait_sec")}}), flush=True)
        per = " ".join(
            f"[{g}] a2a={a * 1e3:.2f}ms pool={p * 1e3:.2f}ms"
            for g, (a, p) in enumerate(zip(pr["a2a_pull_sec"],
                                           pr["pool_sec"])))
        print(f"chunks={pr['a2a_chunks']}: {per}  "
              f"step mono={pr['step_monolithic_sec'] * 1e3:.2f}ms "
              f"chunked={pr['step_chunked_sec'] * 1e3:.2f}ms "
              f"overlap={pr['exchange_overlap_frac']:.1%}", flush=True)
    sys.exit(0)

rp = tr.build_resident_pass(ds)
rp.upload(materialize=True)
tr.train_pass_resident(rp)          # warm/compile
t0 = time.perf_counter()
tr.train_pass_resident(rp)          # wire-free
wall = time.perf_counter() - t0
nb = rp.num_batches
print(json.dumps({"probe": "pass", "wall_s": round(wall, 3),
                  "ms_per_step": round(wall / nb * 1000, 2),
                  "n_steps": nb}), flush=True)

d = tempfile.mkdtemp(prefix="pbox_shstep_")
with jax.profiler.trace(d):
    tr.train_pass_resident(rp)
paths = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
if not paths:
    raise FileNotFoundError(f"no xplane.pb under {d} — trace failed?")
pd = jax.profiler.ProfileData.from_file(sorted(paths)[-1])
agg = defaultdict(float)
for plane in pd.planes:
    if not plane.name.startswith("/device:"):
        continue
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            # strip fusion instance suffixes for aggregation
            name = ev.name.split(".")[0]
            agg[name] += float(ev.duration_ns) / 1e6
top = sorted(agg.items(), key=lambda kv: -kv[1])[:20]
total = sum(agg.values())
print(f"total device op ms across pass: {total:.1f} "
      f"({total / nb:.2f} ms/step)")
for name, ms in top:
    print(f"{ms:8.1f} ms  {ms / nb:6.2f} ms/step  {name}")
