#!/usr/bin/env python
"""XPlane op-level attribution of the sharded resident step (round 5):
where do the ~32 ms/step go that the single-chip step doesn't pay?

Builds the sharded uniform bench shape, stages one resident pass, runs
it wire-free under jax.profiler, and prints the top device ops by
self-time.
"""
import glob
import json
import os
import sys
import tempfile
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

from bench import build_records
from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import SparseSGDConfig
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.train.sharded import ShardedTrainer

FLAGS.log_period_steps = 10 ** 9
FLAGS.auc_device_reduce = True
bs, n_rec = 8192, 262_144
slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 13)]
slots += [SlotDef(f"C{i}", "uint64") for i in range(1, 27)]
desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                    key_bucket_min=bs * 26)
ds = InMemoryDataset(desc)
ds.records = build_records(n_rec, num_slots=26, vocab_per_slot=100_000,
                           seed=0)
ds.columnarize()
cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
chips = len(jax.devices())
mesh = make_mesh(chips)
table = ShardedEmbeddingTable(chips, mf_dim=8,
                              capacity_per_shard=(1 << 23) // chips,
                              cfg=cfg, req_bucket_min=1 << 12,
                              serve_bucket_min=1 << 12)
tr = ShardedTrainer(DeepFM(hidden=(512, 256, 128)), table, desc, mesh,
                    tx=optax.adam(1e-3), float_wire="q8")
rp = tr.build_resident_pass(ds)
rp.upload(materialize=True)
tr.train_pass_resident(rp)          # warm/compile
t0 = time.perf_counter()
tr.train_pass_resident(rp)          # wire-free
wall = time.perf_counter() - t0
nb = rp.num_batches
print(json.dumps({"probe": "pass", "wall_s": round(wall, 3),
                  "ms_per_step": round(wall / nb * 1000, 2),
                  "n_steps": nb}), flush=True)

d = tempfile.mkdtemp(prefix="pbox_shstep_")
with jax.profiler.trace(d):
    tr.train_pass_resident(rp)
paths = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
if not paths:
    raise FileNotFoundError(f"no xplane.pb under {d} — trace failed?")
pd = jax.profiler.ProfileData.from_file(sorted(paths)[-1])
agg = defaultdict(float)
for plane in pd.planes:
    if not plane.name.startswith("/device:"):
        continue
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            # strip fusion instance suffixes for aggregation
            name = ev.name.split(".")[0]
            agg[name] += float(ev.duration_ns) / 1e6
top = sorted(agg.items(), key=lambda kv: -kv[1])[:20]
total = sum(agg.values())
print(f"total device op ms across pass: {total:.1f} "
      f"({total / nb:.2f} ms/step)")
for name, ms in top:
    print(f"{ms:8.1f} ms  {ms / nb:6.2f} ms/step  {name}")
