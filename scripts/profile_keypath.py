#!/usr/bin/env python
"""Decompose the ragged-shape device step cost, component by component.

Round-5 measurement harness for the device key-path attack (VERDICT item
1). Loop-shaped probes per DESIGN_NOTES §4h: every probe threads state
through a fori_loop with VARYING indices per iteration — single-shot
probes with repeated identical indices read 100x too fast.

Prints one JSON line per probe: {"probe": ..., "ms_per_iter": ...}.
Run on the real chip (no conftest): python scripts/profile_keypath.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ps.table import (TableState, apply_push,
                                    gather_full_rows, init_table_state)
from paddlebox_tpu.ps.sgd import SparseSGDConfig, opt_ext_width
from paddlebox_tpu.ops.device_unique import dedup_rows
from paddlebox_tpu.ops.pallas_kernels import segment_sum

N_ITER = int(os.environ.get("PROF_ITERS", 16))
SHAPE = os.environ.get("PROF_SHAPE", "ragged")

# ragged bench shape: bs 4096, 26 slots, ~5 keys/slot, vocab 100k/slot
if SHAPE == "ragged":
    B, S, AVG, VOCAB = 4096, 26, 5.0, 100_000
elif SHAPE == "thousand":
    B, S, AVG, VOCAB = 512, 1000, 1.0, 4_000
else:  # uniform
    B, S, AVG, VOCAB = 8192, 26, 1.0, 100_000
MF = 8
CAP = 1 << 23
cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
EXT = opt_ext_width(cfg, MF)
FEAT = 8 + MF + EXT

rng = np.random.default_rng(0)
if AVG > 1.0:
    counts = 1 + rng.poisson(AVG - 1.0, size=(B, S))
else:
    counts = np.ones((B, S), np.int64)
K = int(counts.sum())
from paddlebox_tpu.ps.table import next_bucket_fine
K_pad = next_bucket_fine(4096, K)

# per-iteration index stacks (varying indices per §4h)
def draw_rows(n):
    """Per-key table rows for n iterations: keys are slot-partitioned
    draws (like the bench), mapped to rows within slot arenas."""
    out = np.empty((n, K_pad), np.int32)
    slot_of_key = np.repeat(np.tile(np.arange(S), B), counts.reshape(-1))
    for i in range(n):
        k_ids = rng.integers(0, VOCAB, size=K)
        out[i, :K] = (slot_of_key * VOCAB + k_ids).astype(np.int32) % CAP
        out[i, K:] = CAP  # pads → sentinel
    return out

rows_stack = jnp.asarray(draw_rows(N_ITER))
# segments per key: record*S + slot
rec_of_key = np.repeat(np.arange(B, dtype=np.int32), counts.sum(axis=1))
slot_flat = np.repeat(np.tile(np.arange(S, dtype=np.int32), B),
                      counts.reshape(-1))
segs_np = np.full(K_pad, B * S, np.int32)
segs_np[:K] = rec_of_key * S + slot_flat
segs = jnp.asarray(segs_np)
key_valid = jnp.asarray((np.arange(K_pad) < K).astype(np.float32))

# unique-rows stacks: dedup each iteration's rows on host
uniqs, u_max = [], 0
for i in range(N_ITER):
    u = np.unique(np.asarray(rows_stack[i][:K]))
    uniqs.append(u)
    u_max = max(u_max, len(u))
U_pad = next_bucket_fine(4096, u_max + 1)
uniq_np = np.empty((N_ITER, U_pad), np.int32)
for i, u in enumerate(uniqs):
    uniq_np[i, :len(u)] = u
    uniq_np[i, len(u):] = CAP + 1 + np.arange(U_pad - len(u))
uniq_stack = jnp.asarray(uniq_np)
U_real = u_max

state = init_table_state(CAP, MF, ext=EXT)
grads = jnp.asarray(rng.normal(size=(U_pad, 3 + MF)).astype(np.float32))
vals_k = jnp.asarray(rng.normal(size=(K_pad, 3 + MF)).astype(np.float32))
prng = jax.random.PRNGKey(0)

print(json.dumps({"probe": "shape", "B": B, "S": S, "K": K,
                  "K_pad": K_pad, "U": U_real, "U_pad": U_pad}),
      flush=True)


def timeit(name, fn, *args, **extra):
    """fn: jitted callable taking iteration index array slot; runs a
    warmup call then wall-times N_ITER iterations via fori_loop
    INSIDE one jit (no per-iter dispatch)."""
    r = fn(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = fn(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / N_ITER * 1000
    print(json.dumps({"probe": name, "ms_per_iter": round(dt, 3),
                      **extra}), flush=True)
    return dt


# ---- probe: gather U rows from the big table ----
@jax.jit
def p_gather(state, uniq_stack):
    def body(i, acc):
        rows = gather_full_rows(state, uniq_stack[i])
        return acc + rows[0, 0] + rows[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_U_big", p_gather, state, uniq_stack,
       U_pad=U_pad)

# ---- probe: apply_push U rows ----
@jax.jit
def p_push(state, uniq_stack, grads, prng):
    def body(i, st):
        return apply_push(st, uniq_stack[i], grads, cfg, prng)
    return jax.lax.fori_loop(0, N_ITER, body, state).packed[0, 0]

timeit("push_U", p_push, state, uniq_stack, grads, prng, U_pad=U_pad)

# ---- probe: dedup_rows at K ----
@jax.jit
def p_dedup(rows_stack):
    def body(i, acc):
        u, g = dedup_rows(rows_stack[i], CAP)
        return acc + u[0] + g[-1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros((), jnp.int32))

timeit("dedup_rows_K", p_dedup, rows_stack, K_pad=K_pad)

# ---- probe: expand gather K from [U, 11] ----
gidx_np = rng.integers(0, U_real, size=(N_ITER, K_pad)).astype(np.int32)
gidx_stack = jnp.asarray(gidx_np)
vals_u = jnp.asarray(rng.normal(size=(U_pad, 3 + MF)).astype(np.float32))

@jax.jit
def p_expand(vals_u, gidx_stack):
    def body(i, acc):
        v = vals_u[gidx_stack[i]]
        return acc + v[0, 0] + v[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("expand_K_from_U", p_expand, vals_u, gidx_stack)

# ---- probe: seqpool segment_sum fwd (K→B*S) ----
@jax.jit
def p_segsum(vals_k, segs):
    def body(i, acc):
        pooled = segment_sum(vals_k * (1.0 + acc), segs,
                             num_segments=B * S + 1)
        return acc + pooled[0, 0] + pooled[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("segsum_K", p_segsum, vals_k, segs)

# ---- probe: seqpool bwd (gather K from B*S) ----
pooled_g = jnp.asarray(
    rng.normal(size=(B * S + 1, 3 + MF)).astype(np.float32))

@jax.jit
def p_seg_bwd(pooled_g, segs):
    def body(i, acc):
        v = pooled_g[segs] * (1.0 + acc)
        return acc + v[0, 0] + v[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("seg_bwd_gather_K", p_seg_bwd, pooled_g, segs)

# ---- probe: slot-wire decode (cumsum + searchsorted at K) ----
counts_u16 = jnp.asarray(counts.sum(axis=1).astype(np.int32))

@jax.jit
def p_slotwire(counts_u16):
    def body(i, acc):
        cum = jnp.cumsum(counts_u16 + acc.astype(jnp.int32))
        rec = jnp.searchsorted(cum, jnp.arange(K_pad, dtype=jnp.int32),
                               side="right").astype(jnp.int32)
        return acc + rec[-1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros((), jnp.int32))

timeit("slotwire_decode_K", p_slotwire, counts_u16)

# ---- probe: slot-wire decode via scatter+cumsum (candidate fix) ----
@jax.jit
def p_slotwire2(counts_u16):
    def body(i, acc):
        cum = jnp.cumsum(counts_u16 + acc.astype(jnp.int32))
        marks = jnp.zeros(K_pad, jnp.int32).at[cum].add(
            1, mode="drop")
        rec = jnp.cumsum(marks)
        return acc + rec[-1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros((), jnp.int32))

timeit("slotwire_scatter_cumsum_K", p_slotwire2, counts_u16)

# ---- probe: expand backward (segment_sum K→U, the grad merge) ----
@jax.jit
def p_expand_bwd(vals_k, gidx_stack):
    def body(i, acc):
        g = jax.ops.segment_sum(vals_k * (1.0 + acc), gidx_stack[i],
                                num_segments=U_pad)
        return acc + g[0, 0] + g[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("expand_bwd_segsum_K_to_U", p_expand_bwd, vals_k, gidx_stack)

# ---- probe: gather linearity (half U) ----
half_stack = uniq_stack[:, :U_pad // 2]

@jax.jit
def p_gather_half(state, half_stack):
    def body(i, acc):
        rows = gather_full_rows(state, half_stack[i])
        return acc + rows[0, 0] + rows[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_halfU_big", p_gather_half, state, half_stack,
       U=U_pad // 2)

# ---- probe: per-key direct gather from big table (K-sized) ----
@jax.jit
def p_gather_K_direct(state, rows_stack):
    def body(i, acc):
        rows = gather_full_rows(state, rows_stack[i])
        return acc + rows[0, 0] + rows[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_K_direct_big", p_gather_K_direct, state, rows_stack,
       K_pad=K_pad)

# ---- probe: dense DeepFM fwd+bwd at this B ----
from paddlebox_tpu.models import DeepFM
import optax
model = DeepFM(hidden=(512, 256, 128))
pooled0 = jnp.zeros((B, S, 3 + MF))
dense0 = jnp.zeros((B, 13))
params = model.init(jax.random.PRNGKey(0), pooled0, dense0)
pooled_in = jnp.asarray(rng.normal(size=(B, S, 3 + MF)).astype(np.float32))
dense_in = jnp.asarray(rng.normal(size=(B, 13)).astype(np.float32))
label = jnp.asarray((rng.random(B) < 0.25).astype(np.float32))

@jax.jit
def p_dense(params, pooled_in, dense_in, label):
    def body(i, carry):
        acc, params = carry
        def loss_fn(p):
            lg = model.apply(p, pooled_in * (1 + acc), dense_in)
            return optax.sigmoid_binary_cross_entropy(lg, label).mean()
        l, g = jax.value_and_grad(loss_fn)(params)
        params = jax.tree.map(lambda a, b: a - 1e-9 * b, params, g)
        return acc + l * 1e-9, params
    acc, params = jax.lax.fori_loop(
        0, N_ITER, body, (jnp.zeros(()), params))
    return acc

timeit("dense_fwd_bwd", p_dense, params, pooled_in, dense_in, label)

# ---- hot-tier probes ----
H = int(os.environ.get("PROF_HOT_ROWS", 8192))
hot_packed = jnp.asarray(
    rng.normal(size=(H // 8, 128)).astype(np.float32))
hot_idx = jnp.asarray(
    rng.integers(0, H, size=(N_ITER, K_pad)).astype(np.int32))

@jax.jit
def p_hot_gather(hot_packed, hot_idx):
    """Same packed-line gather, small table: is per-index cost lower
    when the source fits VMEM?"""
    def body(i, acc):
        rows = hot_idx[i]
        lines = hot_packed[rows // 8]
        sub = (rows % 8).astype(jnp.int32)
        grouped = lines.reshape(K_pad, 8, 16)
        v = jnp.take_along_axis(grouped, sub[:, None, None], axis=1)[:, 0]
        return acc + v[0, 0] + v[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("hot_gather_smalltable_K", p_hot_gather, hot_packed, hot_idx, H=H)

# one-hot MXU matmul gather: [K, H] @ [H, 16] for a few H
for Hm in (512, 2048, 8192):
    hot_tab = jnp.asarray(rng.normal(size=(Hm, 16)).astype(np.float32))
    hidx = jnp.asarray(
        rng.integers(0, Hm, size=(N_ITER, K_pad)).astype(np.int32))

    @jax.jit
    def p_onehot(hot_tab, hidx):
        def body(i, acc):
            oh = jax.nn.one_hot(hidx[i], Hm, dtype=jnp.bfloat16)
            v = oh @ hot_tab.astype(jnp.bfloat16)
            return acc + v[0, 0].astype(jnp.float32) \
                + v[-1, -1].astype(jnp.float32)
        return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

    timeit(f"onehot_matmul_gather_H{Hm}", p_onehot, hot_tab, hidx, H=Hm)

    @jax.jit
    def p_onehot_push(hot_tab, hidx, grads16):
        """Push via transposed one-hot: [H, K] @ [K, 16] scatter-add."""
        def body(i, tab):
            oh = jax.nn.one_hot(hidx[i], Hm, dtype=jnp.bfloat16,
                                axis=0)  # [H, K]
            return tab + (oh @ grads16).astype(jnp.float32)
        return jax.lax.fori_loop(0, N_ITER, body, hot_tab)[0, 0]

    grads16 = jnp.asarray(
        rng.normal(size=(K_pad, 16)).astype(np.float32)).astype(
            jnp.bfloat16)
    timeit(f"onehot_matmul_push_H{Hm}", p_onehot_push, hot_tab, hidx,
           grads16, H=Hm)

# sorted vs unsorted gather from the big table
sorted_stack = jnp.asarray(np.sort(uniq_np, axis=1))

@jax.jit
def p_gather_sorted(state, sorted_stack):
    def body(i, acc):
        rows = gather_full_rows(state, sorted_stack[i])
        return acc + rows[0, 0] + rows[-1, -1]
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_U_big_sorted", p_gather_sorted, state, sorted_stack)

# bf16 pull lines: gather from a bf16 copy of the packed table
state_bf = TableState(state.packed.astype(jnp.bfloat16), CAP, FEAT, EXT)

@jax.jit
def p_gather_bf16(state_bf, uniq_stack):
    def body(i, acc):
        rows = gather_full_rows(state_bf, uniq_stack[i])
        return acc + rows[0, 0].astype(jnp.float32)
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_U_big_bf16", p_gather_bf16, state_bf, uniq_stack)

print(json.dumps({"probe": "done"}), flush=True)
