#!/usr/bin/env python
"""Device key-path cost decomposition — ALL the round-5 probe sets in
one harness (the former profile_keypath{,2,3}.py trio, consolidated).

Loop-shaped probes per DESIGN_NOTES §4h: every probe threads state
through a fori_loop with VARYING indices per iteration — single-shot
probes with repeated identical indices read 100x too fast. Prints one
JSON line per probe: {"probe": ..., "ms_per_iter": ...}. Run on the
real chip (no conftest).

Usage:
    python scripts/profile_keypath.py [--set 1|2|3|all]
                                      [--shape ragged|uniform|thousand]
                                      [--iters N]

Probe sets:
    1  step components: table gather/push, dedup, expand, seqpool
       fwd/bwd, slot-wire decode, dense fwd+bwd, hot-tier gathers
       (the original harness — VERDICT item 1)
    2  grad-merge ordering, gather extract form, push variants (the
       levers left after the slot-wire decode fix)
    3  merge form/dtype, packed-line expand, dedup sort form (the
       levers left after the decode + gather-extract fixes)
    kernels  the Pallas embed-pool-CVM family vs the XLA composition
       (ISSUE 12): gather, pool+CVM forward, full fused fwd+bwd — one
       JSON row per probe, and with ``--record`` higher-is-better
       ``kernel.{gather,pool_cvm,fused}.{shape}.{backend}`` rows
       appended to BENCH_trajectory.json for scripts/perf_gate.py
       (--check --ignore-live gates them; interpret-mode CPU rows key
       separately from real-TPU rows via the backend suffix). When a
       trace span sink is attached each probe re-runs once inside a
       ``kernel.*`` span on the ``device.kernels`` lane.
    index  the device-resident key index (ISSUE 19): open-addressing
       insert / lookup / first-seen dedup over RAW 64-bit feature ids,
       device (Pallas/XLA) vs host (python oracle, native C, host kv) —
       with ``--record``, ``kernel.index.*.{shape}.{backend}`` raw
       keys/s rows append the same way.

``PROF_ITERS`` / ``PROF_SHAPE`` env vars keep working (CLI wins).
Sets 2 and 3 probe the ragged shape regardless of --shape (their
question is merge/extract form at the ragged working point).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

MF = 8
CAP = 1 << 23


def shape_dims(shape: str):
    """(B, S, AVG, VOCAB) for a bench shape name."""
    if shape == "ragged":
        return 4096, 26, 5.0, 100_000
    if shape == "thousand":
        return 512, 1000, 1.0, 4_000
    return 8192, 26, 1.0, 100_000


def make_timeit(n_iter: int, fetch_val: bool = False):
    """Warmup call + wall-timed second call / n_iter. ``fetch_val``
    device_gets the result (sets 2/3's anti-DCE discipline) instead of
    block_until_ready."""

    def timeit(name, fn, *args, **extra):
        r = fn(*args)
        if fetch_val:
            v = np.asarray(jax.device_get(r)).ravel()
        else:
            jax.block_until_ready(r)
        t0 = time.perf_counter()
        r = fn(*args)
        if fetch_val:
            v = np.asarray(jax.device_get(r)).ravel()
            extra["val"] = float(v[0])
        else:
            jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / n_iter * 1000
        print(json.dumps({"probe": name, "ms_per_iter": round(dt, 3),
                          **extra}), flush=True)
        return dt

    return timeit


def _ragged_rows(rng, n_iter, counts, k, k_pad, s, vocab):
    """Per-iteration key rows: slot-partitioned draws mapped into slot
    arenas; pads → the CAP sentinel."""
    slot_of_key = np.repeat(np.tile(np.arange(s), counts.shape[0]),
                            counts.reshape(-1))
    out = np.empty((n_iter, k_pad), np.int32)
    for i in range(n_iter):
        k_ids = rng.integers(0, vocab, size=k)
        out[i, :k] = (slot_of_key * vocab + k_ids).astype(np.int32) % CAP
        out[i, k:] = CAP
    return out, slot_of_key


def run_set1(shape: str, n_iter: int) -> None:
    from paddlebox_tpu.ops.device_unique import dedup_rows
    from paddlebox_tpu.ops.pallas_kernels import segment_sum
    from paddlebox_tpu.ps.sgd import SparseSGDConfig, opt_ext_width
    from paddlebox_tpu.ps.table import (TableState, apply_push,
                                        gather_full_rows,
                                        init_table_state,
                                        next_bucket_fine)

    timeit = make_timeit(n_iter)
    b, s, avg, vocab = shape_dims(shape)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    ext = opt_ext_width(cfg, MF)
    feat = 8 + MF + ext

    rng = np.random.default_rng(0)
    if avg > 1.0:
        counts = 1 + rng.poisson(avg - 1.0, size=(b, s))
    else:
        counts = np.ones((b, s), np.int64)
    k = int(counts.sum())
    k_pad = next_bucket_fine(4096, k)
    rows_np, _ = _ragged_rows(rng, n_iter, counts, k, k_pad, s, vocab)
    rows_stack = jnp.asarray(rows_np)
    # segments per key: record*S + slot
    rec_of_key = np.repeat(np.arange(b, dtype=np.int32),
                           counts.sum(axis=1))
    slot_flat = np.repeat(np.tile(np.arange(s, dtype=np.int32), b),
                          counts.reshape(-1))
    segs_np = np.full(k_pad, b * s, np.int32)
    segs_np[:k] = rec_of_key * s + slot_flat
    segs = jnp.asarray(segs_np)

    # unique-rows stacks: dedup each iteration's rows on host
    uniqs, u_max = [], 0
    for i in range(n_iter):
        u = np.unique(rows_np[i][:k])
        uniqs.append(u)
        u_max = max(u_max, len(u))
    u_pad = next_bucket_fine(4096, u_max + 1)
    uniq_np = np.empty((n_iter, u_pad), np.int32)
    for i, u in enumerate(uniqs):
        uniq_np[i, :len(u)] = u
        uniq_np[i, len(u):] = CAP + 1 + np.arange(u_pad - len(u))
    uniq_stack = jnp.asarray(uniq_np)

    state = init_table_state(CAP, MF, ext=ext)
    grads = jnp.asarray(
        rng.normal(size=(u_pad, 3 + MF)).astype(np.float32))
    vals_k = jnp.asarray(
        rng.normal(size=(k_pad, 3 + MF)).astype(np.float32))
    prng = jax.random.PRNGKey(0)

    print(json.dumps({"probe": "shape", "B": b, "S": s, "K": k,
                      "K_pad": k_pad, "U": u_max, "U_pad": u_pad}),
          flush=True)

    @jax.jit
    def p_gather(state, uniq_stack):
        def body(i, acc):
            rows = gather_full_rows(state, uniq_stack[i])
            return acc + rows[0, 0] + rows[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_U_big", p_gather, state, uniq_stack, U_pad=u_pad)

    @jax.jit
    def p_push(state, uniq_stack, grads, prng):
        def body(i, st):
            return apply_push(st, uniq_stack[i], grads, cfg, prng)
        return jax.lax.fori_loop(0, n_iter, body, state).packed[0, 0]

    timeit("push_U", p_push, state, uniq_stack, grads, prng, U_pad=u_pad)

    @jax.jit
    def p_dedup(rows_stack):
        def body(i, acc):
            u, g = dedup_rows(rows_stack[i], CAP)
            return acc + u[0] + g[-1]
        return jax.lax.fori_loop(0, n_iter, body,
                                 jnp.zeros((), jnp.int32))

    timeit("dedup_rows_K", p_dedup, rows_stack, K_pad=k_pad)

    gidx_np = rng.integers(0, u_max, size=(n_iter, k_pad)) \
        .astype(np.int32)
    gidx_stack = jnp.asarray(gidx_np)
    vals_u = jnp.asarray(
        rng.normal(size=(u_pad, 3 + MF)).astype(np.float32))

    @jax.jit
    def p_expand(vals_u, gidx_stack):
        def body(i, acc):
            v = vals_u[gidx_stack[i]]
            return acc + v[0, 0] + v[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("expand_K_from_U", p_expand, vals_u, gidx_stack)

    @jax.jit
    def p_segsum(vals_k, segs):
        def body(i, acc):
            pooled = segment_sum(vals_k * (1.0 + acc), segs,
                                 num_segments=b * s + 1)
            return acc + pooled[0, 0] + pooled[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("segsum_K", p_segsum, vals_k, segs)

    pooled_g = jnp.asarray(
        rng.normal(size=(b * s + 1, 3 + MF)).astype(np.float32))

    @jax.jit
    def p_seg_bwd(pooled_g, segs):
        def body(i, acc):
            v = pooled_g[segs] * (1.0 + acc)
            return acc + v[0, 0] + v[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("seg_bwd_gather_K", p_seg_bwd, pooled_g, segs)

    counts_u16 = jnp.asarray(counts.sum(axis=1).astype(np.int32))

    @jax.jit
    def p_slotwire(counts_u16):
        def body(i, acc):
            cum = jnp.cumsum(counts_u16 + acc.astype(jnp.int32))
            rec = jnp.searchsorted(cum,
                                   jnp.arange(k_pad, dtype=jnp.int32),
                                   side="right").astype(jnp.int32)
            return acc + rec[-1]
        return jax.lax.fori_loop(0, n_iter, body,
                                 jnp.zeros((), jnp.int32))

    timeit("slotwire_decode_K", p_slotwire, counts_u16)

    @jax.jit
    def p_slotwire2(counts_u16):
        def body(i, acc):
            cum = jnp.cumsum(counts_u16 + acc.astype(jnp.int32))
            marks = jnp.zeros(k_pad, jnp.int32).at[cum].add(
                1, mode="drop")
            rec = jnp.cumsum(marks)
            return acc + rec[-1]
        return jax.lax.fori_loop(0, n_iter, body,
                                 jnp.zeros((), jnp.int32))

    timeit("slotwire_scatter_cumsum_K", p_slotwire2, counts_u16)

    @jax.jit
    def p_expand_bwd(vals_k, gidx_stack):
        def body(i, acc):
            g = jax.ops.segment_sum(vals_k * (1.0 + acc),
                                    gidx_stack[i], num_segments=u_pad)
            return acc + g[0, 0] + g[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("expand_bwd_segsum_K_to_U", p_expand_bwd, vals_k, gidx_stack)

    half_stack = uniq_stack[:, :u_pad // 2]

    @jax.jit
    def p_gather_half(state, half_stack):
        def body(i, acc):
            rows = gather_full_rows(state, half_stack[i])
            return acc + rows[0, 0] + rows[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_halfU_big", p_gather_half, state, half_stack,
           U=u_pad // 2)

    @jax.jit
    def p_gather_K_direct(state, rows_stack):
        def body(i, acc):
            rows = gather_full_rows(state, rows_stack[i])
            return acc + rows[0, 0] + rows[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_K_direct_big", p_gather_K_direct, state, rows_stack,
           K_pad=k_pad)

    # ---- dense DeepFM fwd+bwd at this B ----
    import optax

    from paddlebox_tpu.models import DeepFM
    model = DeepFM(hidden=(512, 256, 128))
    pooled0 = jnp.zeros((b, s, 3 + MF))
    dense0 = jnp.zeros((b, 13))
    params = model.init(jax.random.PRNGKey(0), pooled0, dense0)
    pooled_in = jnp.asarray(
        rng.normal(size=(b, s, 3 + MF)).astype(np.float32))
    dense_in = jnp.asarray(rng.normal(size=(b, 13)).astype(np.float32))
    label = jnp.asarray((rng.random(b) < 0.25).astype(np.float32))

    @jax.jit
    def p_dense(params, pooled_in, dense_in, label):
        def body(i, carry):
            acc, params = carry

            def loss_fn(p):
                lg = model.apply(p, pooled_in * (1 + acc), dense_in)
                return optax.sigmoid_binary_cross_entropy(
                    lg, label).mean()

            l, g = jax.value_and_grad(loss_fn)(params)
            params = jax.tree.map(lambda a, b: a - 1e-9 * b, params, g)
            return acc + l * 1e-9, params

        acc, params = jax.lax.fori_loop(
            0, n_iter, body, (jnp.zeros(()), params))
        return acc

    timeit("dense_fwd_bwd", p_dense, params, pooled_in, dense_in, label)

    # ---- hot-tier probes ----
    h = int(os.environ.get("PROF_HOT_ROWS", 8192))
    hot_packed = jnp.asarray(
        rng.normal(size=(h // 8, 128)).astype(np.float32))
    hot_idx = jnp.asarray(
        rng.integers(0, h, size=(n_iter, k_pad)).astype(np.int32))

    @jax.jit
    def p_hot_gather(hot_packed, hot_idx):
        def body(i, acc):
            rows = hot_idx[i]
            lines = hot_packed[rows // 8]
            sub = (rows % 8).astype(jnp.int32)
            grouped = lines.reshape(k_pad, 8, 16)
            v = jnp.take_along_axis(grouped, sub[:, None, None],
                                    axis=1)[:, 0]
            return acc + v[0, 0] + v[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("hot_gather_smalltable_K", p_hot_gather, hot_packed, hot_idx,
           H=h)

    for hm in (512, 2048, 8192):
        hot_tab = jnp.asarray(
            rng.normal(size=(hm, 16)).astype(np.float32))
        hidx = jnp.asarray(
            rng.integers(0, hm, size=(n_iter, k_pad)).astype(np.int32))

        @jax.jit
        def p_onehot(hot_tab, hidx, hm=hm):
            def body(i, acc):
                oh = jax.nn.one_hot(hidx[i], hm, dtype=jnp.bfloat16)
                v = oh @ hot_tab.astype(jnp.bfloat16)
                return acc + v[0, 0].astype(jnp.float32) \
                    + v[-1, -1].astype(jnp.float32)
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

        timeit(f"onehot_matmul_gather_H{hm}", p_onehot, hot_tab, hidx,
               H=hm)

        @jax.jit
        def p_onehot_push(hot_tab, hidx, grads16, hm=hm):
            def body(i, tab):
                oh = jax.nn.one_hot(hidx[i], hm, dtype=jnp.bfloat16,
                                    axis=0)  # [H, K]
                return tab + (oh @ grads16).astype(jnp.float32)
            return jax.lax.fori_loop(0, n_iter, body, hot_tab)[0, 0]

        grads16 = jnp.asarray(
            rng.normal(size=(k_pad, 16)).astype(np.float32)).astype(
                jnp.bfloat16)
        timeit(f"onehot_matmul_push_H{hm}", p_onehot_push, hot_tab,
               hidx, grads16, H=hm)

    sorted_stack = jnp.asarray(np.sort(uniq_np, axis=1))

    @jax.jit
    def p_gather_sorted(state, sorted_stack):
        def body(i, acc):
            rows = gather_full_rows(state, sorted_stack[i])
            return acc + rows[0, 0] + rows[-1, -1]
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_U_big_sorted", p_gather_sorted, state, sorted_stack)

    state_bf = TableState(state.packed.astype(jnp.bfloat16), CAP, feat,
                          ext)

    @jax.jit
    def p_gather_bf16(state_bf, uniq_stack):
        def body(i, acc):
            rows = gather_full_rows(state_bf, uniq_stack[i])
            return acc + rows[0, 0].astype(jnp.float32)
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_U_big_bf16", p_gather_bf16, state_bf, uniq_stack)


def run_set2(n_iter: int) -> None:
    """Grad-merge ordering, gather extract form, push variants (the
    levers left after the slot-wire decode fix). Ragged shape."""
    from paddlebox_tpu.ps.table import (gather_full_rows,
                                        init_table_state,
                                        next_bucket_fine)
    from paddlebox_tpu.ps.sgd import SparseSGDConfig, opt_ext_width

    timeit = make_timeit(n_iter, fetch_val=True)
    b, s, avg, vocab = shape_dims("ragged")
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    ext = opt_ext_width(cfg, MF)

    rng = np.random.default_rng(0)
    counts = 1 + rng.poisson(avg - 1.0, size=(b, s))
    k = int(counts.sum())
    k_pad = next_bucket_fine(4096, k)
    rows_np, _ = _ragged_rows(rng, n_iter, counts, k, k_pad, s, vocab)

    # host-computed dedup per iteration (uniq sorted / gidx / perm /
    # uid_sorted)
    uniqs = [np.unique(rows_np[i][:k], return_inverse=True)
             for i in range(n_iter)]
    u_max = max(len(u) for u, _ in uniqs)
    u_pad = next_bucket_fine(4096, u_max + 1)
    gidx_np = np.zeros((n_iter, k_pad), np.int32)
    for i, (u, inv) in enumerate(uniqs):
        gidx_np[i, :k] = inv
        gidx_np[i, k:] = len(u)  # pad position
    gidx_stack = jnp.asarray(gidx_np)
    # sorted-by-row order: perm sorts keys by row id; uid_sorted
    # nondecreasing
    perm_np = np.empty((n_iter, k_pad), np.int32)
    uid_sorted_np = np.empty((n_iter, k_pad), np.int32)
    for i in range(n_iter):
        p = np.argsort(rows_np[i], kind="stable")
        perm_np[i] = p
        uid_sorted_np[i] = gidx_np[i][p]
    perm_stack = jnp.asarray(perm_np)
    uid_sorted_stack = jnp.asarray(uid_sorted_np)

    g_k = jnp.asarray(rng.normal(size=(k_pad, 3 + MF)).astype(np.float32))
    state = init_table_state(CAP, MF, ext=ext)
    uniq_pad_np = np.empty((n_iter, u_pad), np.int32)
    for i, (u, _) in enumerate(uniqs):
        uniq_pad_np[i, :len(u)] = u
        uniq_pad_np[i, len(u):] = CAP + 1 + np.arange(u_pad - len(u))
    uniq_stack = jnp.asarray(uniq_pad_np)

    print(json.dumps({"probe": "shape", "K": k, "K_pad": k_pad,
                      "U_pad": u_pad}), flush=True)

    @jax.jit
    def p_merge_unsorted(g_k, gidx_stack):
        def body(i, acc):
            g = jax.ops.segment_sum(g_k + acc * 1e-9, gidx_stack[i],
                                    num_segments=u_pad)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_unsorted", p_merge_unsorted, g_k, gidx_stack)

    @jax.jit
    def p_merge_sorted_hint(g_k, perm_stack, uid_sorted_stack):
        def body(i, acc):
            gs = g_k[perm_stack[i]] + acc * 1e-9
            g = jax.ops.segment_sum(gs, uid_sorted_stack[i],
                                    num_segments=u_pad,
                                    indices_are_sorted=True)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_perm_plus_sorted_hint", p_merge_sorted_hint, g_k,
           perm_stack, uid_sorted_stack)

    @jax.jit
    def p_merge_sorted_nohint(g_k, perm_stack, uid_sorted_stack):
        def body(i, acc):
            gs = g_k[perm_stack[i]] + acc * 1e-9
            g = jax.ops.segment_sum(gs, uid_sorted_stack[i],
                                    num_segments=u_pad)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_perm_plus_sorted_nohint", p_merge_sorted_nohint, g_k,
           perm_stack, uid_sorted_stack)

    @jax.jit
    def p_merge_sorted_only(g_k, uid_sorted_stack):
        def body(i, acc):
            g = jax.ops.segment_sum(g_k + acc * 1e-9,
                                    uid_sorted_stack[i],
                                    num_segments=u_pad,
                                    indices_are_sorted=True)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_sorted_ids_only_hint", p_merge_sorted_only, g_k,
           uid_sorted_stack)

    rand_small = jnp.asarray(
        rng.integers(0, b * s, size=(n_iter, k_pad)).astype(np.int32))

    @jax.jit
    def p_segsum_small_random(g_k, rand_small):
        def body(i, acc):
            g = jax.ops.segment_sum(g_k + acc * 1e-9, rand_small[i],
                                    num_segments=b * s + 1)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("segsum_small_random_ids", p_segsum_small_random, g_k,
           rand_small)

    @jax.jit
    def p_gather_take(state, uniq_stack):
        def body(i, acc):
            rows = gather_full_rows(state, uniq_stack[i])
            return acc + rows.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_take_along_axis", p_gather_take, state, uniq_stack)

    @jax.jit
    def p_gather_maskex(state, uniq_stack):
        rpl, fp, _ = state.geometry

        def body(i, acc):
            rows = jnp.minimum(uniq_stack[i], CAP)
            lines = state.packed[rows // rpl]              # [U, 128]
            sub = (rows % rpl).astype(jnp.int32)
            grouped = lines.reshape(-1, rpl, fp)
            oh = (jnp.arange(rpl, dtype=jnp.int32)[None, :]
                  == sub[:, None]).astype(lines.dtype)     # [U, rpl]
            vals = jnp.einsum("urf,ur->uf", grouped, oh)
            return acc + vals.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_maskextract", p_gather_maskex, state, uniq_stack)

    @jax.jit
    def p_gather_lines_only(state, uniq_stack):
        rpl, fp, _ = state.geometry

        def body(i, acc):
            rows = jnp.minimum(uniq_stack[i], CAP)
            lines = state.packed[rows // rpl]
            return acc + lines.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("gather_lines_only", p_gather_lines_only, state, uniq_stack)

    d_lines = jnp.asarray(
        rng.normal(size=(u_pad, 128)).astype(np.float32))

    @jax.jit
    def p_scatter_lines(state, uniq_stack, d_lines):
        rpl, fp, _ = state.geometry

        def body(i, packed):
            return packed.at[uniq_stack[i] // rpl].add(d_lines,
                                                       mode="drop")
        return jax.lax.fori_loop(0, n_iter, body, state.packed)[0, 0]

    timeit("scatter_add_lines_U", p_scatter_lines, state, uniq_stack,
           d_lines)

    # line-dedup'd scatter: merge co-resident rows' deltas first (uniq
    # is sorted, so line ids are nondecreasing → sorted segment_sum),
    # then scatter unique lines
    line_uid_np = np.empty((n_iter, u_pad), np.int32)
    n_ulines = 0
    for i in range(n_iter):
        lines_i = uniq_pad_np[i] // 8
        uid = np.zeros(u_pad, np.int32)
        uid[1:] = np.cumsum(lines_i[1:] != lines_i[:-1])
        line_uid_np[i] = uid
        n_ulines = max(n_ulines, uid[-1] + 1)
    from paddlebox_tpu.ps.table import next_bucket_fine as _nbf
    ul_pad = _nbf(4096, int(n_ulines) + 1)
    line_uid_stack = jnp.asarray(line_uid_np)

    @jax.jit
    def p_scatter_linededup(state, uniq_stack, line_uid_stack, d_lines):
        rpl, fp, _ = state.geometry

        def body(i, packed):
            uid = line_uid_stack[i]
            merged = jax.ops.segment_sum(d_lines, uid,
                                         num_segments=ul_pad,
                                         indices_are_sorted=True)
            first_pos = jnp.full(ul_pad, u_pad - 1, jnp.int32).at[
                uid].min(jnp.arange(u_pad, dtype=jnp.int32),
                         mode="drop")
            tgt_lines = (uniq_stack[i] // rpl)[first_pos]
            return packed.at[tgt_lines].add(merged, mode="drop")
        return jax.lax.fori_loop(0, n_iter, body, state.packed)[0, 0]

    timeit("scatter_add_linededup", p_scatter_linededup, state,
           uniq_stack, line_uid_stack, d_lines, UL_pad=ul_pad)


def run_set3(n_iter: int) -> None:
    """Merge form/dtype, packed-line expand, dedup sort form (the
    levers left after the decode + gather-extract fixes). Ragged."""
    from paddlebox_tpu.ops.device_unique import dedup_rows
    from paddlebox_tpu.ps.table import next_bucket_fine

    timeit = make_timeit(n_iter, fetch_val=True)
    b, s, avg, vocab = shape_dims("ragged")
    rng = np.random.default_rng(0)
    counts = 1 + rng.poisson(avg - 1.0, size=(b, s))
    k = int(counts.sum())
    k_pad = next_bucket_fine(4096, k)
    u_pad = 491520
    u_real = 481763

    gidx_stack = jnp.asarray(
        rng.integers(0, u_real, size=(n_iter, k_pad)).astype(np.int32))
    g_k = jnp.asarray(rng.normal(size=(k_pad, 11)).astype(np.float32))
    rows_np, _ = _ragged_rows(rng, n_iter, counts, k, k_pad, s, vocab)
    rows_stack = jnp.asarray(rows_np)

    print(json.dumps({"probe": "shape", "K_pad": k_pad,
                      "U_pad": u_pad}), flush=True)

    @jax.jit
    def p_merge_f32(g_k, gidx_stack):
        def body(i, acc):
            g = jax.ops.segment_sum(g_k + acc * 1e-9, gidx_stack[i],
                                    num_segments=u_pad)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_f32", p_merge_f32, g_k, gidx_stack)

    @jax.jit
    def p_merge_bf16(g_k, gidx_stack):
        def body(i, acc):
            g = jax.ops.segment_sum(
                (g_k + acc * 1e-9).astype(jnp.bfloat16), gidx_stack[i],
                num_segments=u_pad)
            return acc + g.astype(jnp.float32).sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_bf16", p_merge_bf16, g_k, gidx_stack)

    @jax.jit
    def p_merge_at_add(g_k, gidx_stack):
        def body(i, acc):
            g = jnp.zeros((u_pad, 11), jnp.float32).at[
                gidx_stack[i]].add(g_k + acc * 1e-9)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_at_add", p_merge_at_add, g_k, gidx_stack)

    g_k16 = jnp.asarray(rng.normal(size=(k_pad, 16)).astype(np.float32))

    @jax.jit
    def p_merge_w16(g_k16, gidx_stack):
        def body(i, acc):
            g = jax.ops.segment_sum(g_k16 + acc * 1e-9, gidx_stack[i],
                                    num_segments=u_pad)
            return acc + g.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_w16", p_merge_w16, g_k16, gidx_stack)

    vals_u = jnp.asarray(rng.normal(size=(u_pad, 11)).astype(np.float32))

    @jax.jit
    def p_expand_plain(vals_u, gidx_stack):
        def body(i, acc):
            v = vals_u[gidx_stack[i]] + acc * 1e-9
            return acc + v.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("expand_plain", p_expand_plain, vals_u, gidx_stack)

    vals_packed = jnp.asarray(
        rng.normal(size=(u_pad // 8, 128)).astype(np.float32))

    @jax.jit
    def p_expand_packedlines(vals_packed, gidx_stack):
        def body(i, acc):
            g = gidx_stack[i]
            lines = vals_packed[g // 8]                    # [K, 128]
            sub = (g % 8).astype(jnp.int32)
            grouped = lines.reshape(-1, 8, 16)
            oh = (jnp.arange(8, dtype=jnp.int32)[None, :]
                  == sub[:, None]).astype(lines.dtype)
            v = jnp.einsum("krf,kr->kf", grouped, oh) + acc * 1e-9
            return acc + v.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("expand_packedlines_maskex", p_expand_packedlines,
           vals_packed, gidx_stack)

    @jax.jit
    def p_dedup_current(rows_stack):
        def body(i, acc):
            u, g = dedup_rows(rows_stack[i], CAP)
            return acc + (u.sum() + g.sum())
        return jax.lax.fori_loop(0, n_iter, body,
                                 jnp.zeros((), jnp.int32))

    timeit("dedup_current", p_dedup_current, rows_stack)

    @jax.jit
    def p_dedup_i64pack(rows_stack):
        def body(i, acc):
            rows = rows_stack[i]
            kk = rows.shape[0]
            pos = jnp.arange(kk, dtype=jnp.int64)
            packed = (rows.astype(jnp.int64) << 20) | pos
            sp = jax.lax.sort(packed)
            sr = (sp >> 20).astype(jnp.int32)
            perm = (sp & ((1 << 20) - 1)).astype(jnp.int32)
            is_first = jnp.concatenate([jnp.ones(1, bool),
                                        sr[1:] != sr[:-1]])
            uid_sorted = jnp.cumsum(is_first.astype(jnp.int32)) - 1
            gidx = jnp.zeros(kk, jnp.int32).at[perm].set(
                uid_sorted, unique_indices=True)
            oob = CAP + 1 + jnp.arange(kk, dtype=jnp.int32)
            uniq = oob.at[uid_sorted].set(sr)
            return acc + (uniq.sum() + gidx.sum())
        return jax.lax.fori_loop(0, n_iter, body,
                                 jnp.zeros((), jnp.int32))

    timeit("dedup_i64pack", p_dedup_i64pack, rows_stack)

    @jax.jit
    def p_merge_lines(g_k16, gidx_stack):
        def body(i, acc):
            g = gidx_stack[i]
            sub = (g % 8).astype(jnp.int32)
            oh = (jnp.arange(8, dtype=jnp.int32)[None, :]
                  == sub[:, None]).astype(jnp.float32)     # [K, 8]
            d = (oh[:, :, None] * (g_k16 + acc * 1e-9)[:, None, :]
                 ).reshape(-1, 128)                        # [K, 128]
            out = jnp.zeros((u_pad // 8, 128), jnp.float32).at[
                g // 8].add(d)
            return acc + out.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_lines_f32", p_merge_lines, g_k16, gidx_stack)

    @jax.jit
    def p_merge_bucketed64(g_k, gidx_stack):
        def body(i, acc):
            g = gidx_stack[i]
            col = (g % 64).astype(jnp.int32)
            oh_cols = (col[:, None] * 11
                       + jnp.arange(11, dtype=jnp.int32)[None, :])
            out = jnp.zeros((u_pad // 64, 64 * 11), jnp.float32).at[
                (g // 64)[:, None], oh_cols].add(g_k + acc * 1e-9)
            return acc + out.sum()
        return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

    timeit("merge_bucketed64", p_merge_bucketed64, g_k, gidx_stack)


def _kernel_segments(shape: str, rng, b: int, s: int, k: int,
                     n_iter: int) -> np.ndarray:
    """Stacked nondecreasing segment streams [n_iter, K]: ``uniform``
    draws one key per (ins, slot) bin in order, ``ragged`` Poisson
    lengths, ``zipf`` heavy-tailed lengths (the hot-sequence CTR
    shape); the tail of every stream is batch padding (→ B*S)."""
    out = np.full((n_iter, k), b * s, np.int32)
    for i in range(n_iter):
        if shape == "uniform":
            nk = min(k, b * s)
            out[i, :nk] = np.arange(nk, dtype=np.int32)
            continue
        if shape == "zipf":
            lens = np.minimum(rng.zipf(1.5, size=b * s), 32)
        else:
            lens = 1 + rng.poisson(4.0, size=b * s)
        ids = np.repeat(np.arange(b * s, dtype=np.int32), lens)[:k]
        out[i, :len(ids)] = ids
    return out


def _ctr_probes(probe, n_iter: int, backend: str) -> None:
    """The CTR op family (ISSUE 13): fused rank_attention / batch_fc /
    cross_norm_hadamard vs their XLA compositions, probed THROUGH the
    dispatch seams so the flag routing (and its
    ``pbox_kernel_dispatch_total`` booking) is what gets measured.
    Emits ``kernel.{rank_attention,batch_fc,cross_norm}[_xla]`` rows;
    the per-iter work unit is rows (instances), not keys."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ops import (batch_fc, cross_norm_hadamard,
                                   cross_norm_update,
                                   init_cross_norm_summary,
                                   rank_attention)

    rng = np.random.default_rng(0)
    if backend == "tpu":
        n_ra, d_ra, s_fc, n_fc, io_fc = 4096, 128, 26, 4096, 128
        b_cn, f_cn, d_cn = 4096, 8, 64
    else:
        # interpret-mode round: keep it seconds (gate-history rows)
        n_ra, d_ra, s_fc, n_fc, io_fc = 256, 32, 8, 128, 64
        b_cn, f_cn, d_cn = 256, 4, 16
    mr = 3

    # ---- rank_attention: block-grouped Pallas vs XLA fallback ----
    x = jnp.asarray(rng.normal(size=(n_ra, d_ra)).astype(np.float32))
    param = jnp.asarray(
        rng.normal(size=(mr * mr, d_ra, d_ra)).astype(np.float32))
    ro_np = np.zeros((n_iter, n_ra, 1 + 2 * mr), np.int32)
    for i in range(n_iter):
        ro_np[i, :, 0] = rng.integers(0, mr + 1, size=n_ra)
        for k in range(mr):
            on = rng.random(n_ra) < 0.7
            ro_np[i, :, 1 + 2 * k] = np.where(
                on, rng.integers(1, mr + 1, size=n_ra), 0)
            ro_np[i, :, 2 + 2 * k] = rng.integers(0, n_ra, size=n_ra)
    ro_stack = jnp.asarray(ro_np)

    def make_ra(flag):
        @jax.jit
        def run(x, param, ro_stack):
            def body(i, acc):
                with flags_scope(use_pallas_rank_attention=flag):
                    out = rank_attention(x * (1.0 + acc * 1e-9),
                                         ro_stack[i], param, mr)
                return acc + out[0, 0] + out[-1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))
        return run

    probe("rank_attention", make_ra(True), x, param, ro_stack,
          keys=n_ra, unit="rows/sec")
    probe("rank_attention_xla", make_ra(False), x, param, ro_stack,
          keys=n_ra, unit="rows/sec")

    # ---- batch_fc: fused-bias blocked GEMM vs XLA einsum ----
    xb = jnp.asarray(
        rng.normal(size=(s_fc, n_fc, io_fc)).astype(np.float32))
    wb = jnp.asarray(
        rng.normal(size=(s_fc, io_fc, io_fc)).astype(np.float32))
    bb = jnp.asarray(rng.normal(size=(s_fc, io_fc)).astype(np.float32))

    def make_fc(flag):
        @jax.jit
        def run(xb, wb, bb):
            def body(i, acc):
                with flags_scope(use_pallas_batch_fc=flag):
                    out = batch_fc(xb * (1.0 + acc * 1e-9), wb, bb)
                return acc + out[0, 0, 0] + out[-1, -1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))
        return run

    probe("batch_fc", make_fc(True), xb, wb, bb, keys=s_fc * n_fc,
          unit="rows/sec")
    probe("batch_fc_xla", make_fc(False), xb, wb, bb,
          keys=s_fc * n_fc, unit="rows/sec")

    # ---- cross_norm_hadamard: one-VMEM-pass vs XLA composition ----
    xc = jnp.asarray(
        rng.normal(size=(b_cn, 2 * f_cn * d_cn)).astype(np.float32))
    summ = cross_norm_update(init_cross_norm_summary(f_cn, d_cn), xc,
                             f_cn, d_cn, decay=0.5)

    def make_cn(flag):
        @jax.jit
        def run(xc, summ):
            def body(i, acc):
                with flags_scope(use_pallas_cross_norm=flag):
                    out = cross_norm_hadamard(xc * (1.0 + acc * 1e-9),
                                              summ, f_cn, d_cn)
                return acc + out[0, 0] + out[-1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))
        return run

    probe("cross_norm", make_cn(True), xc, summ, keys=b_cn,
          unit="rows/sec")
    probe("cross_norm_xla", make_cn(False), xc, summ, keys=b_cn,
          unit="rows/sec")


def run_set_kernels(shape: str, n_iter: int, record: bool = False,
                    probes: str = "all") -> None:
    """Per-kernel device cost of the Pallas device-kernel suite vs the
    XLA compositions (ISSUE 12 + 13; docs/PERFORMANCE.md §Device
    kernels). ``probes``: "embed" = the embed-pool-CVM family,
    "ctr" = the rank_attention/batch_fc/cross_norm family, "all" =
    both."""
    import jax.numpy as jnp

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.obs import trace
    from paddlebox_tpu.ops import fused_seqpool_cvm
    from paddlebox_tpu.ops.pallas_kernels import (fused_pool_cvm_forward,
                                                  gather_rows)

    backend = jax.default_backend()
    if backend == "tpu":
        b, s, cap, k = 4096, 26, 1 << 20, 1 << 19
    else:
        # interpret-mode round: the kernel body runs as a python loop
        # per pair — keep it seconds, the row exists for gate HISTORY
        b, s, cap, k = 64, 8, 1 << 12, 1 << 11
    mf = MF
    d = 2 + mf
    rng = np.random.default_rng(0)

    timeit = make_timeit(n_iter)
    rows_out = []

    def probe(name, fn, *args, keys=k, unit="keys/sec"):
        if trace.tracing_active():
            with trace.span(f"kernel.{name}", lane=trace.LANE_KERNELS,
                            shape=shape, backend=backend):
                jax.block_until_ready(fn(*args))
        ms = timeit(f"kernel.{name}.{shape}", fn, *args, backend=backend)
        if record and ms > 0:
            # source="live" (the bench.py convention): a re-run on a
            # slower box appends a row that --check --ignore-live SKIPS
            # — the GATED history is the committed KERNELS_r0*.json
            # round (folded with its artifact name as source).
            # ``keys``/``unit`` name the probe's work item — the CTR
            # probes count rows (instances), not keys.
            rows_out.append({
                "source": "live",
                "metric": f"kernel.{name}.{shape}.{backend}",
                "value": round(keys / ms * 1000.0, 1),
                "unit": unit, "shape": shape,
            })

    print(json.dumps({"probe": "shape", "B": b, "S": s, "K": k,
                      "CAP": cap, "D": d, "backend": backend}),
          flush=True)

    if probes in ("all", "embed"):
        # ---- gather: pallas scalar-prefetch line gather vs XLA take ----
        table = jnp.asarray(rng.normal(size=(cap, 128)).astype(np.float32))
        rows_np = rng.integers(0, cap, size=(n_iter, k)).astype(np.int32)
        rows_stack = jnp.asarray(rows_np)

        @jax.jit
        def p_gather_pallas(table, rows_stack):
            def body(i, acc):
                v = gather_rows(table, rows_stack[i])
                return acc + v[0, 0] + v[-1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

        @jax.jit
        def p_gather_xla(table, rows_stack):
            def body(i, acc):
                v = table[rows_stack[i]]
                return acc + v[0, 0] + v[-1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

        probe("gather", p_gather_pallas, table, rows_stack)
        probe("gather_xla", p_gather_xla, table, rows_stack)

        # ---- pool+CVM forward: fused Pallas pass vs XLA composition ----
        vals = rng.normal(size=(k, d)).astype(np.float32)
        vals[:, :2] = np.abs(vals[:, :2])
        vals_j = jnp.asarray(vals)
        segs_stack = jnp.asarray(_kernel_segments(shape, rng, b, s, k, n_iter))
        sc = jnp.asarray(np.abs(rng.normal(size=(b, 2))).astype(np.float32))

        @jax.jit
        def p_pool_fused(vals_j, segs_stack):
            def body(i, acc):
                out = fused_pool_cvm_forward(vals_j * (1.0 + acc * 1e-9),
                                             segs_stack[i], None, b, s)
                return acc + out[0, 0, 0] + out[-1, -1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

        def _xla_fwd(v, segs):
            with flags_scope(use_pallas_seqpool=False):
                return fused_seqpool_cvm(v, segs, sc, b, s)

        @jax.jit
        def p_pool_xla(vals_j, segs_stack):
            def body(i, acc):
                out = _xla_fwd(vals_j * (1.0 + acc * 1e-9), segs_stack[i])
                return acc + out[0, 0, 0] + out[-1, -1, -1]
            return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))

        probe("pool_cvm", p_pool_fused, vals_j, segs_stack)
        probe("pool_cvm_xla", p_pool_xla, vals_j, segs_stack)

        # ---- full fused fwd+bwd (the train-step shape: pooled loss grad
        # feeding the push path) vs the XLA composition ----
        def make_fwd_bwd(flag):
            def step(v, segs):
                def loss(v):
                    out = fused_seqpool_cvm(v, segs, sc, b, s)
                    return jnp.sum(out * out)
                return jax.grad(loss)(v)

            @jax.jit
            def run(vals_j, segs_stack):
                def body(i, acc):
                    with flags_scope(use_pallas_seqpool=flag):
                        g = step(vals_j * (1.0 + acc * 1e-9), segs_stack[i])
                    return acc + g[0, 0] + g[-1, -1]
                return jax.lax.fori_loop(0, n_iter, body, jnp.zeros(()))
            return run

        probe("fused", make_fwd_bwd(True), vals_j, segs_stack)
        probe("fused_xla", make_fwd_bwd(False), vals_j, segs_stack)

    if probes in ("all", "ctr"):
        _ctr_probes(probe, n_iter, backend)

    if record and rows_out:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import perf_gate
        # bench.py's convention: BENCH_TRAJECTORY=0 disables the live
        # append (the rows still echo below for artifact capture)
        dest = os.environ.get("BENCH_TRAJECTORY", "")
        path = None if dest == "0" \
            else (dest or perf_gate.default_trajectory_path())
        for row in rows_out:
            if path:
                perf_gate.append_row(row, path)
            # echo the row as a bench line so a captured stdout artifact
            # (KERNELS_r0*.json) re-folds via perf_gate --fold
            print(json.dumps(row), flush=True)
        print(json.dumps({"probe": "recorded", "rows": len(rows_out),
                          "path": path or "(disabled)"}), flush=True)


def _index_keys(shape: str, rng, vocab: int, k: int,
                n_iter: int) -> np.ndarray:
    """Raw 64-bit feature-id streams [n_iter, K] for the index probes:
    ``uniform`` all-distinct ids (cold insert), ``zipf`` heavy-tailed
    repeats (the CTR hot-key shape), anything else uniform draws over a
    small vocab (collision-heavy warm stream). Every 7th id gets a
    high-32 bit set so the probe covers ids that collide mod 2^32."""
    out = np.empty((n_iter, k), np.uint64)
    for i in range(n_iter):
        if shape == "uniform":
            ids = (np.arange(k, dtype=np.uint64)
                   + np.uint64(i * k))
        elif shape == "zipf":
            ids = np.minimum(rng.zipf(1.3, size=k),
                             vocab).astype(np.uint64)
        else:
            ids = rng.integers(0, vocab, size=k).astype(np.uint64)
        ids[::7] |= np.uint64(1) << np.uint64(33)
        out[i] = ids
    return out


def run_set_index(shape: str, n_iter: int, record: bool = False) -> None:
    """The device-resident key index (ISSUE 19; ops/pallas_index.py):
    open-addressing insert / lookup / first-seen dedup over RAW feature
    ids, device (Pallas interpret or XLA while-loop) vs the host paths
    (python dedup oracle, native C dedup, host kv assign/lookup). Emits
    one JSON row per probe; with ``--record`` higher-is-better
    ``kernel.index.{insert,lookup,dedup}*.{shape}.{backend}`` raw-keys/s
    rows append to the perf_gate trajectory."""
    from paddlebox_tpu.obs import trace
    from paddlebox_tpu.ops.device_unique import dedup_keys_first_seen
    from paddlebox_tpu.ops.pallas_index import (_pad_to_block, insert,
                                                lookup, split_keys)
    from paddlebox_tpu.ps.kv import dedup_first_seen_native, make_kv
    from paddlebox_tpu.ps.table import (_dedup_first_seen_py,
                                        dedup_first_seen)

    backend = jax.default_backend()
    if backend == "tpu":
        k, vocab, cap = 1 << 17, 1 << 15, 1 << 20
    else:
        # interpret-mode round: the Pallas insert probes each key in a
        # python fori_loop — keep it seconds (the row is gate HISTORY)
        k, vocab, cap = 512, 192, 1 << 13
    n_buckets = 1 << int(2 * cap - 1).bit_length()
    rng = np.random.default_rng(0)
    keys_np = _index_keys(shape, rng, vocab, k, n_iter)

    timeit = make_timeit(n_iter)
    rows_out = []

    def probe(name, fn, *args, keys=k, unit="keys/sec"):
        if trace.tracing_active():
            with trace.span(f"kernel.{name}", lane=trace.LANE_KERNELS,
                            shape=shape, backend=backend):
                jax.block_until_ready(fn(*args))
        ms = timeit(f"kernel.{name}.{shape}", fn, *args, backend=backend)
        if record and ms > 0:
            rows_out.append({
                "source": "live",
                "metric": f"kernel.{name}.{shape}.{backend}",
                "value": round(keys / ms * 1000.0, 1),
                "unit": unit, "shape": shape,
            })

    kp = _pad_to_block(keys_np[0]).shape[0]
    hi_np = np.empty((n_iter, kp), np.int32)
    lo_np = np.empty((n_iter, kp), np.int32)
    for i in range(n_iter):
        hi, lo = split_keys(keys_np[i])
        hi_np[i] = _pad_to_block(hi)
        lo_np[i] = _pad_to_block(lo)
    hi_stack, lo_stack = jnp.asarray(hi_np), jnp.asarray(lo_np)

    print(json.dumps({"probe": "shape", "K": k, "K_pad": kp,
                      "VOCAB": vocab, "CAP": cap,
                      "BUCKETS": n_buckets, "backend": backend}),
          flush=True)

    # ---- insert: open-addressing claim over the whole stream, state
    # (buckets + row cursor) threaded through the loop — iteration 2+
    # measures the warm (mostly-hits) pass shape ----
    def make_insert(up):
        @jax.jit
        def run(hi_stack, lo_stack):
            def body(i, carry):
                bh, bl, br, nxt, acc = carry
                bh, bl, br, rows, new, ovf = insert(
                    bh, bl, br, hi_stack[i], lo_stack[i],
                    jnp.int32(k), nxt, use_pallas=up)
                nxt = nxt + jnp.sum(new[:k]).astype(jnp.int32)
                return (bh, bl, br, nxt, acc + rows[0] + rows[k - 1])
            init = (jnp.zeros(n_buckets, jnp.int32),
                    jnp.zeros(n_buckets, jnp.int32),
                    jnp.full(n_buckets, -1, jnp.int32),
                    jnp.int32(0), jnp.zeros((), jnp.int32))
            return jax.lax.fori_loop(0, n_iter, body, init)[4]
        return run

    probe("index.insert", make_insert(True), hi_stack, lo_stack)
    probe("index.insert_xla", make_insert(False), hi_stack, lo_stack)

    def p_insert_host():
        # the host half of the seam: python first-seen dedup + kv
        # assign (the EmbeddingTable.bulk_assign_unique host path)
        kv = make_kv(cap)
        acc = 0
        for i in range(n_iter):
            uniq, first, inv = dedup_first_seen(keys_np[i])
            rows = kv.assign(uniq)
            acc += int(rows[0])
        return np.int64(acc)

    probe("index.insert_host", p_insert_host)

    # ---- lookup: probe a table warmed with the full key population ----
    all_uniq = np.unique(keys_np.reshape(-1))
    from paddlebox_tpu.ops.pallas_index import DeviceKeyIndex
    dev = DeviceKeyIndex(cap, n_buckets=n_buckets)
    out = dev.assign_unique(all_uniq)
    assert out is not None, "probe table overflowed — raise CAP"

    def make_lookup(up):
        @jax.jit
        def run(bh, bl, br, hi_stack, lo_stack):
            def body(i, acc):
                rows = lookup(bh, bl, br, hi_stack[i], lo_stack[i],
                              jnp.int32(k), use_pallas=up)
                return acc + rows[0] + rows[k - 1]
            return jax.lax.fori_loop(0, n_iter, body,
                                     jnp.zeros((), jnp.int32))
        return run

    probe("index.lookup", make_lookup(True), dev.bh, dev.bl, dev.br,
          hi_stack, lo_stack)
    probe("index.lookup_xla", make_lookup(False), dev.bh, dev.bl,
          dev.br, hi_stack, lo_stack)

    kv_warm = make_kv(cap)
    kv_warm.assign(all_uniq)

    def p_lookup_host():
        acc = 0
        for i in range(n_iter):
            acc += int(kv_warm.lookup(keys_np[i])[0])
        return np.int64(acc)

    probe("index.lookup_host", p_lookup_host)

    # ---- first-seen dedup of raw ids: device sort-based kernel vs the
    # python oracle vs the native C open-addressing pass ----
    @jax.jit
    def p_dedup_dev(hi_stack, lo_stack):
        def body(i, acc):
            uh, ul, first, inv, nu = dedup_keys_first_seen(
                hi_stack[i], lo_stack[i], jnp.int32(k))
            return acc + uh[0] + inv[k - 1] + nu
        return jax.lax.fori_loop(0, n_iter, body,
                                 jnp.zeros((), jnp.int32))

    probe("index.dedup", p_dedup_dev, hi_stack, lo_stack)

    def p_dedup_host():
        # the pure-python oracle, NOT dedup_first_seen (which routes to
        # the native pass when available — probed separately below)
        acc = 0
        for i in range(n_iter):
            uniq, first, inv = _dedup_first_seen_py(keys_np[i])
            acc += len(uniq)
        return np.int64(acc)

    probe("index.dedup_host", p_dedup_host)

    if dedup_first_seen_native(keys_np[0]) is not None:
        def p_dedup_native():
            acc = 0
            for i in range(n_iter):
                uniq, first, inv = dedup_first_seen_native(keys_np[i])
                acc += len(uniq)
            return np.int64(acc)

        probe("index.dedup_native", p_dedup_native)
    else:
        print(json.dumps({"probe": "index.dedup_native",
                          "skipped": "native lib unavailable"}),
              flush=True)

    if record and rows_out:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import perf_gate
        dest = os.environ.get("BENCH_TRAJECTORY", "")
        path = None if dest == "0" \
            else (dest or perf_gate.default_trajectory_path())
        for row in rows_out:
            if path:
                perf_gate.append_row(row, path)
            print(json.dumps(row), flush=True)
        print(json.dumps({"probe": "recorded", "rows": len(rows_out),
                          "path": path or "(disabled)"}), flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="device key-path cost probes")
    ap.add_argument("--set", dest="probe_set", default="1",
                    choices=("1", "2", "3", "all", "kernels", "index"),
                    help="probe set to run (default 1)")
    ap.add_argument("--shape",
                    default=os.environ.get("PROF_SHAPE", "ragged"),
                    choices=("ragged", "uniform", "thousand", "zipf"),
                    help="workload shape for sets 1/kernels")
    ap.add_argument("--iters", type=int,
                    default=int(os.environ.get("PROF_ITERS", 16)),
                    help="fori_loop iterations per probe")
    ap.add_argument("--record", action="store_true",
                    help="(kernels set) append kernel.* rows to the "
                    "perf_gate trajectory (BENCH_TRAJECTORY overrides "
                    "the path)")
    ap.add_argument("--probes", default="all",
                    choices=("all", "embed", "ctr"),
                    help="(kernels set) probe family: the embed-pool-"
                    "CVM suite, the ISSUE 13 CTR op family, or both")
    args = ap.parse_args(argv)
    if args.probe_set == "kernels":
        shape = args.shape if args.shape != "thousand" else "ragged"
        print(json.dumps({"probe": "set", "set": "kernels"}), flush=True)
        run_set_kernels(shape, args.iters, record=args.record,
                        probes=args.probes)
        print(json.dumps({"probe": "done"}), flush=True)
        return 0
    if args.probe_set == "index":
        shape = args.shape if args.shape != "thousand" else "ragged"
        print(json.dumps({"probe": "set", "set": "index"}), flush=True)
        run_set_index(shape, args.iters, record=args.record)
        print(json.dumps({"probe": "done"}), flush=True)
        return 0
    if args.shape == "zipf":
        # shape_dims() has no zipf branch — sets 1-3 would silently run
        # the uniform workload while claiming the heavy-tailed one
        ap.error("--shape zipf is only valid with --set kernels/index")
    sets = ("1", "2", "3") if args.probe_set == "all" \
        else (args.probe_set,)
    for ps in sets:
        print(json.dumps({"probe": "set", "set": int(ps)}), flush=True)
        if ps == "1":
            run_set1(args.shape, args.iters)
        elif ps == "2":
            run_set2(args.iters)
        else:
            run_set3(args.iters)
    print(json.dumps({"probe": "done"}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
