#!/usr/bin/env python
"""Multichip scaling + chunked-parity gate (ISSUE 11).

Two checks, wired into tier-1 by ``tests/test_scaling_check.py``:

1. **Chunked parity end-to-end through train_pass**: on the in-process
   CPU mesh, ``FLAGS.a2a_chunks=2`` reproduces the ``a2a_chunks=1``
   model digest (params + packed table + AUC) BIT-FOR-BIT, and the
   digest is deterministic across two seeded runs — the fused
   computation-collective schedule (train/sharded) changes the
   exchange's shape, never its math.
2. **Multichip trajectory rows**: drive ``BENCH_MODE=multichip``
   (bench.py — one subprocess per chip count) at a tiny workload into a
   temp trajectory and assert the ``sharded.n{N}.{shape}.*`` rows land
   well-formed and pass ``perf_gate`` over them.

Graceful skips (exit 0 with a SKIP note): fewer than 2 visible devices
for parity, or subprocess/device failure for the bench rows — CI boxes
without the virtual-device backend must not fail tier-1 for missing
hardware.

``--record --source rXX`` additionally appends the measured multichip
rows to the committed BENCH_trajectory.json under the given source, so
they gate future rounds via ``perf_gate.py --check --ignore-live``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import subprocess
import sys
import tempfile
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: well-formed multichip gate keys (perf_gate keys on the metric name;
#: the optional ``.c{chunks}`` segment keeps chunked-schedule ladders
#: on their own gate history — BENCH_A2A_CHUNKS)
KEY_RE = re.compile(
    r"^sharded\.n\d+\.[a-z0-9_]+(\.c\d+)?\.(ex_per_sec_per_chip"
    r"|scaling_efficiency)$")


def _load_perf_gate():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO, "scripts", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _digest(trainer) -> str:
    from paddlebox_tpu.train.checkpoint import sharded_state_digest
    return sharded_state_digest(trainer)


def parity_check(rows_per_file: int = 500,
                 chunks: Tuple[int, ...] = (2,)) -> Optional[bool]:
    """a2a_chunks ∈ chunks reproduce the chunks=1 digest through
    train_pass (×2 seeded runs each). None = skipped (no mesh)."""
    import jax
    if len(jax.devices()) < 2:
        print("scaling_check: SKIP parity — fewer than 2 devices "
              "(needs a CPU mesh: XLA_FLAGS="
              "--xla_force_host_platform_device_count=N)")
        return None
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer

    n = min(8, len(jax.devices()))
    mesh = make_mesh(n)
    with tempfile.TemporaryDirectory(prefix="pbox_scaling_") as td:
        files = generate_criteo_files(td, num_files=1,
                                      rows_per_file=rows_per_file,
                                      vocab_per_slot=40, seed=17)
        desc = DataFeedDesc.criteo(batch_size=32)
        desc.key_bucket_min = 1024
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()

        def run(c: int) -> str:
            cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                                  mf_initial_range=0.0,
                                  learning_rate=0.1,
                                  mf_learning_rate=0.1)
            table = ShardedEmbeddingTable(
                n, mf_dim=4, capacity_per_shard=4096, cfg=cfg,
                req_bucket_min=256, serve_bucket_min=256)
            with flags_scope(log_period_steps=10 ** 6, a2a_chunks=c):
                tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table,
                                    desc, mesh, tx=optax.adam(2e-3))
                tr.train_pass(ds)
            return _digest(tr)

        want = run(1)
        if run(1) != want:
            print("scaling_check: FAIL — chunks=1 digest is not "
                  "deterministic across seeded runs", file=sys.stderr)
            return False
        for c in chunks:
            got = run(c)
            if got != want:
                print(f"scaling_check: FAIL — a2a_chunks={c} digest "
                      f"{got[:16]} != monolithic {want[:16]}",
                      file=sys.stderr)
                return False
    print(f"scaling_check: parity OK — a2a_chunks {list(chunks)} "
          f"bit-identical to monolithic on the {n}-way mesh "
          f"(digest {want[:16]})")
    return True


def bench_rows_check(ns: str = "1,2", bs: int = 128, gbatches: int = 2,
                     passes: int = 2, timeout_s: float = 480.0,
                     shape: str = "uniform"
                     ) -> Tuple[str, List[dict]]:
    """Run the multichip bench into a temp trajectory; validate keys.
    Returns ("ok"|"skip"|"fail", rows)."""
    pg = _load_perf_gate()
    with tempfile.TemporaryDirectory(prefix="pbox_scaling_") as td:
        traj = os.path.join(td, "traj.json")
        env = dict(os.environ)
        env.update(BENCH_MODE="multichip", BENCH_SHAPE=shape,
                   BENCH_MULTICHIP_NS=ns, BENCH_MULTICHIP_BS=str(bs),
                   BENCH_MULTICHIP_BATCHES=str(gbatches),
                   BENCH_MULTICHIP_PASSES=str(passes),
                   BENCH_MULTICHIP_TIMEOUT=str(timeout_s / 2),
                   BENCH_TRAJECTORY=traj, BENCH_TELEMETRY_JSONL="0")
        try:
            cp = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, capture_output=True, text=True,
                timeout=timeout_s)
        except (subprocess.TimeoutExpired, OSError) as e:
            print(f"scaling_check: SKIP bench rows — subprocess "
                  f"unavailable ({e})")
            return "skip", []
        data = pg.load_trajectory(traj) if os.path.exists(traj) else None
        if cp.returncode != 0 or not data or not data["rows"]:
            print("scaling_check: SKIP bench rows — multichip bench "
                  f"produced no rows (rc={cp.returncode}): "
                  f"{cp.stderr[-400:]}")
            return "skip", []
        rows = data["rows"]
        n_list = [int(x) for x in ns.split(",")]
        want_keys = {f"sharded.n{n}.{shape}.{m}" for n in n_list
                     for m in ("ex_per_sec_per_chip",
                               "scaling_efficiency")}
        got_keys = {r["metric"] for r in rows}
        bad = [k for k in got_keys if not KEY_RE.match(k)]
        if bad:
            print(f"scaling_check: FAIL — malformed metric keys {bad}",
                  file=sys.stderr)
            return "fail", rows
        missing = want_keys - got_keys
        if missing:
            print(f"scaling_check: FAIL — missing rows {sorted(missing)}",
                  file=sys.stderr)
            return "fail", rows
        failures, _ = pg.check_rows(rows)
        if failures:
            print("\n".join(failures), file=sys.stderr)
            return "fail", rows
        eff = {r["metric"]: r["value"] for r in rows
               if r["metric"].endswith("scaling_efficiency")}
        print(f"scaling_check: multichip rows OK — {sorted(got_keys)}; "
              f"efficiency {eff}")
        return "ok", rows


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--skip-parity", action="store_true")
    ap.add_argument("--skip-bench", action="store_true")
    ap.add_argument("--ns", default="1,2",
                    help="chip counts for the bench rows (default 1,2)")
    ap.add_argument("--bs", type=int, default=128)
    ap.add_argument("--batches", type=int, default=2,
                    help="global batches per pass per child")
    ap.add_argument("--timeout", type=float, default=480.0)
    ap.add_argument("--shape", default="uniform")
    ap.add_argument("--record", action="store_true",
                    help="append the measured rows to the committed "
                    "trajectory under --source")
    ap.add_argument("--source", default=None,
                    help="trajectory source tag for --record")
    ap.add_argument("--trajectory", default=None)
    args = ap.parse_args(argv)
    rc = 0
    if not args.skip_parity:
        ok = parity_check()
        if ok is False:
            rc = 1
    if not args.skip_bench:
        status, rows = bench_rows_check(ns=args.ns, bs=args.bs,
                                        gbatches=args.batches,
                                        timeout_s=args.timeout,
                                        shape=args.shape)
        if status == "fail":
            rc = 1
        if args.record and status == "ok":
            if not args.source:
                print("--record needs --source", file=sys.stderr)
                return 2
            pg = _load_perf_gate()
            path = args.trajectory or pg.default_trajectory_path()
            for r in rows:
                r = dict(r)
                r["source"] = args.source
                r.pop("recorded_at", None)
                pg.append_row(r, path)
            print(f"scaling_check: recorded {len(rows)} rows -> {path} "
                  f"(source {args.source})")
    return rc


if __name__ == "__main__":
    # a standalone run needs the virtual CPU mesh BEFORE jax imports
    # (same trick as tests/conftest.py)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    sys.exit(main())
