#!/usr/bin/env python
"""Serve-while-training gate (ISSUE 15): p99 latency, snapshot
staleness and bit-consistency must hold WHILE a training loop publishes
— and through injected faults.

Four legs, one seeded scenario (``run_serve_check``):

1. **stream-serve** — a ``Trainer.train_stream`` loop (windowed
   QueueDataset, one boundary checkpoint per window) publishes a base
   + ≥3 deltas into an ``ArtifactStore`` while a concurrent serving
   thread (``ServingModel`` + background ``ReloadLoop``) sustains
   lookup/predict queries. Asserted THROUGHOUT the run:

   - every served result is bit-consistent with EXACTLY ONE published
     version (each query pins one snapshot; its lookup digest must
     equal that version's replay oracle — no torn reads across swaps);
   - query p99 latency ≤ ``SERVE_CHECK_P99_MS`` (default 500 ms — an
     intentionally generous CI bound; the bench lane tracks the real
     number) and snapshot staleness ≤ ``SERVE_CHECK_STALENESS_SEC``;
   - ``/readyz`` refuses before the first adoption and passes after.

2. **tiered publisher** — a three-tier (host RAM + SSD segments)
   table publishes base+deltas with spill-manifest refs; the serving
   snapshots must carry the SSD-spilled rows bit-exactly through two
   hot-reload swaps under concurrent readers.

3. **chaos: flipped-byte delta mid-hot-reload** — the reload poll
   refuses the corrupt tip, serving CONTINUES on the prior snapshot
   (queries stay consistent, ``pbox_serving_reload_degraded_total``
   books, staleness gauge rises), and recovers when the tip is
   repaired.

4. **chaos: trainer SIGKILL mid-publish** — a real subprocess
   publisher is SIGKILLed between staging and the atomic rename;
   serving is unaffected (still answering from the last complete
   version), the carcass sweeps, and the next complete publish is
   adopted.

``main()`` runs the whole scenario twice with the same seed and
asserts a byte-identical outcome — serving robustness is provable, not
hoped-for.

Usage::

    JAX_PLATFORMS=cpu python scripts/serve_check.py [--seed 7]

Exit code 0 == all bounds held + deterministic.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: CI-generous SLO bounds (env-overridable); the serve bench lane
#: (BENCH_MODE=serve) tracks the real numbers with a perf gate.
P99_BOUND_MS = float(os.environ.get("SERVE_CHECK_P99_MS", "500"))
STALENESS_BOUND_SEC = float(
    os.environ.get("SERVE_CHECK_STALENESS_SEC", "30"))


def _digest(arr) -> str:
    import numpy as np
    return hashlib.sha256(
        np.ascontiguousarray(arr).tobytes()).hexdigest()[:24]


class QueryWorker(threading.Thread):
    """Sustained serving traffic: each query pins ONE snapshot (the
    fence), reads off it, and records (version, lookup digest, predict
    digest, latency, staleness). Runs until stopped; any exception is
    captured — a reload must never break the query path."""

    def __init__(self, srv, probe, batch=None) -> None:
        super().__init__(daemon=True, name="serve-query")
        self.srv = srv
        self.probe = probe
        self.batch = batch
        self.records = []          # (aid, lookup_digest)
        self.pred_digests = set()  # predict digests seen
        self.latencies = []
        self.max_staleness = 0.0
        self.exc = None
        self._halt = threading.Event()

    def run(self) -> None:
        try:
            while not self._halt.is_set():
                t0 = time.perf_counter()
                snap = self.srv.snapshot()     # THE fence
                out = snap.lookup(self.probe)
                self.latencies.append(time.perf_counter() - t0)
                self.records.append((snap.aid, _digest(out)))
                if self.batch is not None and snap.params is not None:
                    pred = self.srv._predict_on(snap, self.batch,
                                                return_valid=False)
                    self.pred_digests.add(_digest(pred))
                st = self.srv.serving_status()
                self.max_staleness = max(self.max_staleness,
                                         st["staleness_sec"])
                time.sleep(0.002)
        except BaseException as e:   # noqa: BLE001 — reported by leg
            self.exc = e

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=60)
        if self.exc is not None:
            raise AssertionError(
                f"query worker died (the query path must survive "
                f"reloads): {self.exc!r}") from self.exc

    def p99_ms(self) -> float:
        lat = sorted(self.latencies)
        if not lat:
            return 0.0
        return lat[int(0.99 * (len(lat) - 1))] * 1e3


def _srv(desc, capacity=1 << 13):
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.serving import ServingModel
    return ServingModel(CtrDnn(hidden=(8,)), desc, mf_dim=4,
                        capacity=capacity)


def _oracles(store, desc, probe, batch=None, capacity=1 << 13):
    """Per-version replay oracles: a FRESH consumer adopts each
    adoptable version and digests the same probe lookup (and predict)
    the query workers ran — the bit-consistency reference."""
    lookups, preds = {}, {}
    for aid in store.versions():
        if not store.read_manifest(aid,
                                   verify=False).get("adoptable", True):
            continue
        srv = _srv(desc, capacity)
        srv.adopt(store, aid)
        snap = srv.snapshot()
        lookups[aid] = _digest(snap.lookup(probe))
        if batch is not None and snap.params is not None:
            preds[aid] = _digest(srv._predict_on(snap, batch,
                                                 return_valid=False))
        srv.release()
    return lookups, preds


def _run_stream_leg(workdir: str, seed: int) -> dict:
    """Leg 1: train_stream publishes boundary versions while serving
    queries run; bounds + bit-consistency asserted over the whole
    overlap window."""
    import numpy as np
    import optax

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import get_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.serving import ReloadLoop
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    hub = get_hub()
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=5, rows_per_file=120,
                                  vocab_per_slot=40, seed=seed)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 2048
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    store = ArtifactStore(os.path.join(workdir, "registry"))

    # a fixed probe batch for predict consistency (one real batch off
    # the first file — NOT consumed by the stream's own dataset), and
    # REAL probe keys from it (their rows train every window, so each
    # published version answers a DIFFERENT lookup digest — the
    # consistency check cannot pass vacuously on all-zero misses)
    pds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    pds.set_filelist(files[:1])
    pds.load_into_memory()
    probe_batch = next(pds.batches())
    probe = np.unique(probe_batch.keys[:probe_batch.num_keys])[:256]
    probe = np.concatenate(
        [probe, np.array([0xDEAD_BEEF_0001], np.uint64)])  # one miss

    with flags_scope(seed=seed, stream_window_files=1,
                     stream_ckpt_every_windows=1, read_thread_num=1,
                     retry_base_delay_sec=0.01,
                     retry_max_delay_sec=0.05,
                     serving_reload_poll_sec=0.02):
        table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                               unique_bucket_min=2048)
        trainer = Trainer(CtrDnn(hidden=(8,)), table, desc,
                          tx=optax.adam(1e-2), seed=seed)
        cm = CheckpointManager(os.path.join(workdir, "ckpt"),
                               artifacts=store)
        ds = DatasetFactory().create_dataset("QueueDataset", desc)
        ds.set_filelist(files)

        srv = _srv(desc)
        srv.register_health()
        ready_before = hub.readiness()["ready"]

        writer_exc = []

        def train() -> None:
            try:
                trainer.train_stream(ds, cm)
            except BaseException as e:   # noqa: BLE001
                writer_exc.append(e)

        writer = threading.Thread(target=train, daemon=True,
                                  name="serve-writer")
        writer.start()
        # serving comes up as soon as the FIRST boundary publishes
        deadline = time.time() + 120
        while not store.versions() and time.time() < deadline:
            time.sleep(0.01)
        assert store.versions(), "writer never published a version"
        srv.adopt(store)
        ready_after = hub.readiness()["ready"]
        loop = ReloadLoop(srv, store).start()
        worker = QueryWorker(srv, probe, batch=probe_batch)
        worker.start()
        writer.join(timeout=300)
        assert not writer.is_alive(), "train_stream never finished"
        if writer_exc:
            raise writer_exc[0]
        # let the loop catch the final publish, then stop cleanly
        deadline = time.time() + 30
        while srv.adopted_aid != store.latest() \
                and time.time() < deadline:
            time.sleep(0.02)
        worker.stop()
        loop.stop()

    versions = store.versions()
    kinds = [store.read_manifest(a, verify=False)["kind"]
             for a in versions]
    assert kinds.count("base") >= 1 and kinds.count("delta") >= 3, (
        f"stream published {kinds} — want 1 base + >=3 deltas")
    lookup_oracle, pred_oracle = _oracles(store, desc, probe,
                                          batch=probe_batch)
    served_versions = sorted({aid for aid, _ in worker.records})
    consistent = all(lookup_oracle.get(aid) == d
                     for aid, d in worker.records)
    assert consistent, (
        "a served lookup did not match its pinned version's oracle — "
        "torn read across a snapshot swap")
    preds_ok = worker.pred_digests <= set(pred_oracle.values())
    assert preds_ok, (
        f"served predictions {worker.pred_digests} outside the "
        f"published versions' oracles")
    p99 = worker.p99_ms()
    assert p99 <= P99_BOUND_MS, (
        f"serving p99 {p99:.1f}ms broke the {P99_BOUND_MS}ms bound "
        "while training published")
    assert worker.max_staleness <= STALENESS_BOUND_SEC, (
        f"snapshot staleness {worker.max_staleness:.1f}s broke the "
        f"{STALENESS_BOUND_SEC}s bound")
    assert srv.adopted_aid == versions[-1], (
        srv.adopted_aid, versions[-1])
    assert not ready_before and ready_after, (
        "/readyz must refuse before the first adoption and pass after")
    srv.release()
    return {
        "stream_versions": versions,
        "stream_kinds": kinds,
        "stream_lookup_oracle": lookup_oracle,
        "stream_pred_oracle": sorted(pred_oracle.values()),
        "stream_served_all_consistent": bool(consistent),
        "stream_preds_consistent": bool(preds_ok),
        "stream_served_multiple_versions": len(served_versions) >= 1,
        "stream_p99_ok": True,
        "stream_staleness_ok": True,
        "stream_final_aid": srv.adopted_aid,
        "readyz_transition": [ready_before, ready_after],
    }


def _run_tiered_leg(workdir: str, seed: int) -> dict:
    """Leg 2: three-tier (RAM+SSD) publisher → serving snapshots carry
    the spilled rows bit-exactly across hot-reload swaps under
    concurrent readers."""
    import numpy as np

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.data.schema import DataFeedDesc
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELDS, TWO_D_FIELDS
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    from paddlebox_tpu.serving import ReloadLoop

    desc = DataFeedDesc.criteo(batch_size=16)
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    tiered = TieredShardedEmbeddingTable(
        1, mf_dim=4, capacity_per_shard=1024, cfg=cfg,
        host_capacity=256, req_bucket_min=128, serve_bucket_min=128,
        ssd_dir=os.path.join(workdir, "tier"))

    def fill(lo: int, hi: int, scale: float) -> None:
        ks = np.arange(lo, hi, dtype=np.uint64)
        for i in range(0, len(ks), 128):
            chunk = ks[i:i + 128]
            vals = chunk.astype(np.float32)
            tiered.hosts[0].update(chunk, {
                f: (np.tile(vals[:, None], (1, 4)) * 0.01 * scale
                    if f in TWO_D_FIELDS else vals * 0.001 * scale)
                for f in FIELDS})

    fill(1, 401, 1.0)
    assert tiered.hosts[0].demote_cold(count=150) > 0
    store = ArtifactStore(os.path.join(workdir, "registry_tiered"))
    helper = BoxPSHelper(tiered)
    v1 = helper.publish_base(store)
    spill_ref = store.read_manifest(v1)["refs"]["spill_manifest"]
    assert spill_ref["digest"], "no spill-manifest ref on the publish"

    probe = np.array([1, 155, 200, 400, 999999], np.uint64)
    srv = _srv(desc, capacity=1 << 11)
    assert srv.adopt(store) == v1
    got = srv.embed_lookup(probe)
    want = np.array([1, 155, 200, 400], np.float32) * 0.001
    assert np.allclose(got[:4, 2], want), (
        "snapshot lost SSD-spilled rows")
    assert not got[4].any(), "unknown key must read zeros"

    loop = ReloadLoop(srv, store, poll_sec=0.02)
    worker = QueryWorker(srv, probe)
    worker.start()
    fill(300, 451, 3.0)
    v2 = helper.publish_delta(store)
    deadline = time.time() + 30
    while srv.adopted_aid != v2 and time.time() < deadline:
        loop.poll_once()
        time.sleep(0.01)
    fill(420, 481, 7.0)
    v3 = helper.publish_delta(store)
    deadline = time.time() + 30
    while srv.adopted_aid != v3 and time.time() < deadline:
        loop.poll_once()
        time.sleep(0.01)
    worker.stop()
    assert srv.adopted_aid == v3
    lookup_oracle, _ = _oracles(store, desc, probe, capacity=1 << 11)
    consistent = all(lookup_oracle.get(aid) == d
                     for aid, d in worker.records)
    assert consistent, "tiered serving saw a torn/foreign state"
    served = sorted({aid for aid, _ in worker.records})
    assert len(served) >= 2, (
        f"readers never spanned a swap (saw {served}) — widen the "
        "publish window")
    # writer-side completeness: the adopted chain reproduces the
    # writer's OWN full model (SSD-spilled rows included) bit-for-bit,
    # compared through the same single-table fingerprint (a fresh
    # save_base dump of the tier loaded into a plain table)
    replay = _srv(desc, capacity=1 << 11)
    replay.adopt(store)
    dump = os.path.join(workdir, "tier_oracle.npz")
    tiered.save_base(dump, clear_touched=False)
    from paddlebox_tpu.ps import EmbeddingTable
    oracle_t = EmbeddingTable(mf_dim=4, capacity=1 << 11, cfg=cfg)
    oracle_t.load(dump)
    writer_digest = oracle_t.rows_digest()
    replay_digest = replay.table.rows_digest()
    assert writer_digest == replay_digest, (
        "adopted tiered chain diverges from the writer's full model — "
        "spilled rows lost or mutated")
    replay.release()
    srv.release()
    srv.release()   # double-release is a no-op
    return {
        "tiered_chain": [v1, v2, v3],
        "tiered_spill_digest": spill_ref["digest"],
        "tiered_consistent": bool(consistent),
        "tiered_swaps_observed": len(served) >= 2,
        "tiered_writer_digest": writer_digest,
        "tiered_replay_digest": replay_digest,
        "tiered_oracle": lookup_oracle,
    }


def _run_corrupt_tip_leg(workdir: str, seed: int) -> dict:
    """Leg 3: flipped-byte delta mid-hot-reload — degrade loudly, keep
    serving the prior snapshot under live queries, recover on repair."""
    import numpy as np
    import jax

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.data.schema import DataFeedDesc
    from paddlebox_tpu.obs.hub import get_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState
    from paddlebox_tpu.serving import ReloadLoop

    desc = DataFeedDesc.criteo(batch_size=16)
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    helper = BoxPSHelper(t)
    store = ArtifactStore(os.path.join(workdir, "registry_chaos"))

    def write(lo, hi, scale) -> None:
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = t.index.assign(keys)
        data = np.asarray(jax.device_get(t.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = \
            keys.astype(np.float32) * scale
        t.state = TableState.from_logical(data, t.capacity)
        t._touched[rows] = True

    write(1, 101, 2.0)
    v1 = helper.publish_base(store)
    probe = np.arange(1, 101, dtype=np.uint64)
    srv = _srv(desc, capacity=1 << 10)
    assert srv.adopt(store) == v1
    loop = ReloadLoop(srv, store, poll_sec=0.02)
    worker = QueryWorker(srv, probe)
    worker.start()

    hub = get_hub()
    refused0 = hub.counter("pbox_artifact_refused_total").value(
        reason="corrupt")
    write(50, 151, 5.0)
    v2 = helper.publish_delta(store)
    p = os.path.join(store.version_dir(v2), "sparse_delta.npz")
    with open(p, "rb") as fh:
        blob = fh.read()
    flip = 13 % len(blob)
    with open(p, "wb") as fh:
        fh.write(blob[:flip] + bytes([blob[flip] ^ 0xFF])
                 + blob[flip + 1:])
    degraded0 = loop.degraded
    for _ in range(3):     # corrupt tip: every poll degrades loudly
        assert loop.poll_once() is None
        time.sleep(0.01)
    assert srv.adopted_aid == v1, "corrupt tip must not swap in"
    assert loop.degraded > degraded0, "degrade was silent"
    assert hub.counter("pbox_artifact_refused_total").value(
        reason="corrupt") > refused0, "refusal was silent"
    staleness_mid = srv.serving_status()["staleness_sec"]
    assert staleness_mid > 0.0, "staleness gauge stayed zero"
    with open(p, "wb") as fh:     # repair the tip
        fh.write(blob)
    deadline = time.time() + 30
    while srv.adopted_aid != v2 and time.time() < deadline:
        loop.poll_once()
        time.sleep(0.01)
    worker.stop()
    assert srv.adopted_aid == v2, "repaired tip never adopted"
    assert srv.serving_status()["staleness_sec"] == 0.0
    lookup_oracle, _ = _oracles(store, desc, probe, capacity=1 << 10)
    consistent = all(lookup_oracle.get(aid) == d
                     for aid, d in worker.records)
    assert consistent, "queries tore during the degrade window"
    # queries DURING the corrupt window all answered v1
    assert any(aid == v1 for aid, _ in worker.records)
    srv.release()
    return {
        "corrupt_chain": [v1, v2],
        "corrupt_degraded_loud": True,
        "corrupt_served_prior": True,
        "corrupt_recovered": srv.adopted_aid == v2,
        "corrupt_consistent": bool(consistent),
        "corrupt_oracle": lookup_oracle,
    }


_PUBLISHER = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import jax
from paddlebox_tpu.artifacts import ArtifactStore
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.table import FIELD_COL, TableState

root = sys.argv[1]
store = ArtifactStore(root)
cfg = SparseSGDConfig(mf_create_thresholds=1e9)
t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
keys = np.arange(1, 201, dtype=np.uint64)
rows = t.index.assign(keys)
data = np.asarray(jax.device_get(t.state.data)).copy()
data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * 2.0
data[rows, FIELD_COL["show"]] = 1.0
t.state = TableState.from_logical(data, t.capacity)
t._touched[rows] = True
aid = store.publish({{"sparse.npz": lambda p: t.save_base(p)}},
                    kind="base", meta={{"step": 1}})
with open(os.path.join(root, "base_aid.txt"), "w") as fh:
    fh.write(aid)

# second publish: stage the payload, signal the parent, then HANG
# inside the writer — the parent SIGKILLs us mid-publish (the trainer
# dying between staging and the atomic rename)
def hang_writer(p):
    t._touched[rows] = True
    t.save_delta(p)
    with open(os.path.join(root, "STAGED"), "w") as fh:
        fh.write("1")
    time.sleep(600)

store.publish({{"sparse_delta.npz": hang_writer}}, kind="delta",
              parent=aid)
"""


def _run_sigkill_leg(workdir: str, seed: int) -> dict:
    """Leg 4: REAL SIGKILL mid-publish — serving is unaffected, the
    carcass sweeps, the next complete version adopts."""
    import glob

    import numpy as np

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.data.schema import DataFeedDesc
    from paddlebox_tpu.serving import ReloadLoop

    desc = DataFeedDesc.criteo(batch_size=16)
    root = os.path.join(workdir, "registry_kill")
    os.makedirs(root, exist_ok=True)
    proc = subprocess.Popen(
        [sys.executable, "-c", _PUBLISHER.format(repo=REPO), root])
    deadline = time.time() + 120
    base_aid = None
    while time.time() < deadline:
        p = os.path.join(root, "base_aid.txt")
        if os.path.isfile(p):
            with open(p) as fh:
                base_aid = fh.read().strip()
            break
        time.sleep(0.05)
    assert base_aid, "publisher subprocess never published its base"

    store = ArtifactStore(root)
    probe = np.arange(1, 201, dtype=np.uint64)
    srv = _srv(desc, capacity=1 << 10)
    assert srv.adopt(store) == base_aid
    loop = ReloadLoop(srv, store, poll_sec=0.02)
    worker = QueryWorker(srv, probe)
    worker.start()

    deadline = time.time() + 120
    while not os.path.isfile(os.path.join(root, "STAGED")) \
            and time.time() < deadline:
        time.sleep(0.02)
    assert os.path.isfile(os.path.join(root, "STAGED")), \
        "publisher never staged its delta"
    os.kill(proc.pid, signal.SIGKILL)     # the trainer dies mid-publish
    proc.wait()
    for _ in range(5):                    # serving shrugs it off
        loop.poll_once()
        time.sleep(0.01)
    assert srv.adopted_aid == base_aid
    assert store.versions() == [base_aid], (
        "half-publish leaked a version")
    carcass = bool(glob.glob(os.path.join(root, ".stage-*")))
    assert carcass, "SIGKILL left no stage carcass"
    # a fresh store open proves the writer dead and sweeps the carcass
    store2 = ArtifactStore(root)
    assert not glob.glob(os.path.join(root, ".stage-*")), (
        "carcass survived the sweep")
    # the next COMPLETE publish adopts normally
    payload = os.path.join(root, "versions", base_aid, "sparse.npz")
    v2 = store2.publish({"sparse_delta.npz": payload}, kind="delta",
                        parent=base_aid, meta={"step": 2})
    deadline = time.time() + 30
    while srv.adopted_aid != v2 and time.time() < deadline:
        loop.poll_once()
        time.sleep(0.01)
    worker.stop()
    assert srv.adopted_aid == v2, "next complete version never adopted"
    lookup_oracle, _ = _oracles(store2, desc, probe, capacity=1 << 10)
    consistent = all(lookup_oracle.get(aid) == d
                     for aid, d in worker.records)
    assert consistent, "queries tore across the SIGKILL window"
    srv.release()
    return {
        "kill_base": base_aid,
        "kill_carcass_swept": True,
        "kill_serving_unaffected": True,
        "kill_next_adopted": v2,
        "kill_consistent": bool(consistent),
        "kill_oracle": lookup_oracle,
    }


def run_serve_check(workdir: str, seed: int = 7) -> dict:
    """One full scenario; returns the outcome summary (aids, digests,
    booleans — nothing timing-valued, so two seeded runs compare
    byte-identical)."""
    from paddlebox_tpu.obs import MemorySink
    from paddlebox_tpu.obs.hub import get_hub, reset_hub

    reset_hub()
    hub = get_hub()
    hub.add_sink(MemorySink())   # hub.active: serving telemetry live
    out: dict = {}
    out.update(_run_stream_leg(workdir, seed))
    out.update(_run_tiered_leg(workdir, seed))
    out.update(_run_corrupt_tip_leg(workdir, seed))
    out.update(_run_sigkill_leg(workdir, seed))
    # the serving counters booked (values vary with poll timing — the
    # outcome records only their non-zero-ness)
    out["reload_adopted_nonzero"] = hub.counter(
        "pbox_serving_reload_adopted_total").series() != []
    out["reload_degraded_nonzero"] = hub.counter(
        "pbox_serving_reload_degraded_total").value() > 0
    reset_hub()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_serve_")
    outcomes = []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- serve run {run} (seed={args.seed}) ---")
            outcomes.append(run_serve_check(wd, args.seed))
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: serve outcome differs across identically-"
                  "seeded runs")
            return 1
        print("PASS: p99/staleness bounds held while training "
              "published; every served result bit-consistent with "
              "exactly one version; corrupt-tip and SIGKILL chaos legs "
              f"recovered; deterministic across 2 runs "
              f"(seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
