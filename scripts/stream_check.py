#!/usr/bin/env python
"""Seeded end-to-end streaming-ingest check (ISSUE 6 acceptance
criteria).

Proves the streaming survival kit deterministically:

1. **oracle** — an uninterrupted windowed stream (``Trainer.train_stream``
   over a windowed ``QueueDataset``, ``FLAGS.stream_window_files``)
   publishes a stream-boundary checkpoint after every window and records
   its logical state digests.
2. **killed** — the same seeded run under a
   ``preempt.signal:fail:nth=K`` plan (simulated SIGTERM at the K-th
   batch boundary, landing mid-window): the stream raises
   ``PreemptedError`` after an emergency checkpoint whose v2 cursor
   records the completed files + the open window.
3. **resume** — a fresh trainer restores the emergency checkpoint and
   ``train_stream`` continues: completed windows are SKIPPED, the open
   window REPLAYS (at-least-once), and the stream runs to the end.

Asserted, per run:

- record accounting (``Trainer.on_batch_trained``): every input record
  trained at-least-once; completed-window records exactly once; only
  open-window records may train twice,
- replay accounting: exactly the open window's files replayed
  (``QueueDataset.files_replayed`` + the telemetry counter),
- ``state_digest`` of the killed run's checkpoint at the LAST COMMON
  WINDOW BOUNDARY equals the no-kill oracle's at the same step,
- ``supports_cursor_resume`` is True in windowed mode while the legacy
  unwindowed stream still refuses ``start_batch != 0``,

and the whole scenario runs twice with the same seed — outcomes must be
byte-identical (streaming recovery is reproducible, not lucky).

Usage::

    JAX_PLATFORMS=cpu python scripts/stream_check.py [--seed 7]
                                                     [--preempt-at 8]

Exit code 0 == resumed with at-least-once accounting + deterministic.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: stream geometry: WINDOW files per window, FILES files total,
#: ROWS records per file — small enough for the tier-1 wiring
#: (tests/test_stream_check.py), big enough for 3 windows with several
#: batches each
WINDOW, FILES, ROWS, BS = 2, 6, 48, 16


def _record_sigs(batch) -> list:
    """Stable per-record signatures of a trained batch (criteo layout:
    one key per slot, record-major key block) — collision-free for the
    synthetic data's random 26-key rows."""
    import numpy as np
    n = int((batch.show > 0).sum())
    S = batch.num_slots
    keys = batch.keys[:n * S].reshape(n, S)
    return [keys[i].tobytes() + bytes([int(batch.label[i])])
            for i in range(n)]


def _file_sigs(files, desc) -> dict:
    """path -> set of record signatures, built the same way the batch
    side builds them (same parser, same key layout)."""
    from paddlebox_tpu.data.parser import get_parser
    out = {}
    for path in files:
        parser = get_parser(desc)
        sigs = set()
        with open(path) as fh:
            for line in fh:
                rec = parser.parse(line)
                if rec is not None:
                    sigs.add(rec.keys.tobytes()
                             + bytes([int(rec.label)]))
        out[path] = sigs
    return out


def run_scenario(workdir: str, seed: int, preempt_at: int) -> dict:
    """One full streaming preemption round-trip; returns the outcome."""
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import reset_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.resilience.faults import FaultPlan, installed
    from paddlebox_tpu.resilience.preemption import PreemptedError
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import (CheckpointManager,
                                                state_digest)

    reset_hub()
    preemption.clear_stop()
    jsonl = os.path.join(workdir, "telemetry.jsonl")
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=FILES, rows_per_file=ROWS,
                                  vocab_per_slot=40, seed=seed)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)

    # ONE reader thread: the digest comparison needs a deterministic
    # batch order within each window (resume correctness itself — the
    # at-least-once window replay — does not)
    with flags_scope(seed=seed, telemetry_jsonl=jsonl,
                     stream_window_files=WINDOW,
                     stream_ckpt_every_windows=1, read_thread_num=1):
        desc = DataFeedDesc.criteo(batch_size=BS)
        desc.key_bucket_min = 2048
        sigs_by_file = _file_sigs(files, desc)

        def mk() -> Trainer:
            table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                                   unique_bucket_min=2048)
            return Trainer(CtrDnn(hidden=(8,)), table, desc,
                           tx=optax.adam(1e-2), seed=seed)

        def mkds():
            ds = DatasetFactory().create_dataset("QueueDataset", desc)
            ds.set_filelist(files)
            return ds

        # windowed-mode contract (acceptance criterion): cursor resume
        # is advertised ONLY in windowed mode; the legacy stream refuses
        ds_probe = mkds()
        assert ds_probe.supports_cursor_resume, \
            "windowed QueueDataset must support cursor resume"
        with flags_scope(stream_window_files=0):
            assert not ds_probe.supports_cursor_resume
            try:
                next(ds_probe.batches(start_batch=1))
                raise AssertionError("unwindowed stream accepted "
                                     "start_batch != 0")
            except ValueError:
                pass

        def digest_of(root: str, step: int) -> str:
            t = mk()
            assert CheckpointManager(root).restore(t, step=step) == step
            return state_digest(t)

        # (1) oracle: uninterrupted stream, boundary ckpt per window
        oracle_root = os.path.join(workdir, "ckpt_oracle")
        oracle = mk()
        out_oracle = oracle.train_stream(mkds(),
                                         CheckpointManager(oracle_root))
        assert out_oracle["windows"] == FILES // WINDOW, out_oracle
        assert out_oracle["replayed_files"] == 0
        oracle_steps = CheckpointManager(oracle_root).steps()

        # (2) killed run: simulated SIGTERM at the K-th batch boundary
        root = os.path.join(workdir, "ckpt")
        trained = collections.Counter()
        killed = mk()
        killed.on_batch_trained = \
            lambda b: trained.update(_record_sigs(b))
        cm = CheckpointManager(root)
        plan = FaultPlan.parse(f"preempt.signal:fail:nth={preempt_at}",
                               seed=seed)
        preempted = False
        try:
            with installed(plan):
                killed.train_stream(mkds(), cm)
        except PreemptedError as e:
            preempted = True
            assert e.checkpointed, "emergency checkpoint missing"
        assert preempted, "preempt fault never fired"
        cursor = cm.load_cursor()
        assert cursor is not None and "stream" in cursor, cursor
        stream = cursor["stream"]
        # completed-file history older than the last boundary ckpt is
        # FOLDED to a count+fingerprint (cursor compaction, ISSUE 7) —
        # expand it from the known consumption order, checking the
        # chained digest on the way
        fold = stream.get("files_folded") or {}
        nfold = int(fold.get("count", 0) or 0)
        if nfold:
            from paddlebox_tpu.data.dataset import chain_digest
            assert chain_digest("", files[:nfold]) == fold["sha256"], (
                "folded cursor fingerprint does not match the stream's "
                "consumption order")
        completed_at_kill = files[:nfold] + list(
            stream["files_completed"])
        open_window = list(stream["window_files"])
        assert open_window, "kill was meant to land MID-window"
        marker = preemption.read_resume_marker(root)
        assert marker and marker["exit_code"] == preemption.EXIT_RESUME

        # (3) restart: fresh trainer resumes; open window replays
        preemption.clear_stop()
        resumed = mk()
        resumed.on_batch_trained = \
            lambda b: trained.update(_record_sigs(b))
        cm2 = CheckpointManager(root)
        restored = cm2.restore(resumed)
        assert restored == cursor["global_step"], (restored, cursor)
        ds_res = mkds()
        out_res = resumed.train_stream(ds_res, cm2)
        assert preemption.read_resume_marker(root) is None, \
            "resume marker not consumed"
        assert out_res["replayed_files"] == len(open_window), out_res
        assert ds_res.files_completed[-1] == files[-1]  # drained

        # ---- record accounting: at-least-once, completed exactly-once
        done_files = set(completed_at_kill) \
            | (set(files) - set(open_window))
        for path in files:
            for sig in sigs_by_file[path]:
                n = trained[sig]
                assert n >= 1, f"record of {path} never trained"
                if path in done_files:
                    assert n == 1, (f"completed-window record of {path} "
                                    f"trained {n}x")
                else:
                    assert n <= 2, (f"open-window record of {path} "
                                    f"trained {n}x")
        replay_counts = sorted(
            {trained[s] for s in set().union(
                *(sigs_by_file[p] for p in open_window))})
        # the open window holds BOTH replayed-after-training records
        # (2x) and not-yet-reached ones (1x) — the kill landed mid-window
        assert replay_counts == [1, 2], replay_counts

        # ---- digest at the last common window boundary
        common = sorted(set(cm2.steps()) & set(oracle_steps))
        boundary_steps = [s for s in common
                          if s <= int(cursor["global_step"])]
        assert boundary_steps, "no common pre-kill boundary checkpoint"
        last_common = boundary_steps[-1]
        d_oracle = digest_of(oracle_root, last_common)
        d_killed = digest_of(root, last_common)
        assert d_oracle == d_killed, (
            "killed run diverged from the oracle at the last common "
            f"window boundary (step {last_common}):\n"
            f"  oracle {d_oracle}\n  killed {d_killed}")

    with open(jsonl) as fh:
        events = [json.loads(line) for line in fh]
    names = {e["event"] for e in events}
    for want in ("stream_window", "preempt_requested",
                 "emergency_checkpoint", "cursor_resume",
                 "stream_replay"):
        assert want in names, f"telemetry missing {want!r}: {sorted(names)}"
    resumes = [e for e in events if e["event"] == "cursor_resume"
               and e.get("stream")]
    assert resumes and resumes[-1]["replay_files"] == len(open_window)

    return dict(
        ok=True,
        oracle_windows=int(out_oracle["windows"]),
        completed_at_kill=[os.path.basename(p)
                           for p in completed_at_kill],
        open_window=[os.path.basename(p) for p in open_window],
        resumed_windows=int(out_res["windows"]),
        replayed_files=int(out_res["replayed_files"]),
        last_common_boundary=int(last_common),
        boundary_digest=d_oracle,
        fault_stats=plan.stats(),
        events={n: sum(1 for e in events if e["event"] == n)
                for n in ("stream_window", "stream_replay",
                          "emergency_checkpoint", "cursor_resume")},
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--preempt-at", type=int, default=8,
                    help="batch boundary the simulated SIGTERM lands on "
                         "(default 8: mid-window-2 of the 3-window run)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_stream_")
    outcomes = []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- stream run {run} (seed={args.seed}, preempt at "
                  f"batch {args.preempt_at}) ---")
            outcomes.append(run_scenario(wd, args.seed, args.preempt_at))
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: stream outcome differs across "
                  "identically-seeded runs:")
            print(json.dumps(outcomes[0], sort_keys=True))
            print(json.dumps(outcomes[1], sort_keys=True))
            return 1
        print(f"PASS: preempted stream resumed with at-least-once "
              f"accounting (replayed {outcomes[0]['replayed_files']} "
              f"open-window file(s), completed windows exactly once), "
              f"boundary digest matches the oracle; outcome "
              f"deterministic across 2 runs (seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
