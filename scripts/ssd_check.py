#!/usr/bin/env python
"""Deterministic SSD-third-tier gates (ISSUE 7; docs/STORAGE.md).

CORRECTNESS gate (``run_ssd_check``): drives the tiered pass protocol
over an alternating A/B working set with a ``host_store_capacity``
deliberately SMALLER than |A ∪ B| — every pass boundary evicts the old
set to the host tier, the watermark demoter spills the cold half to SSD
segments, and re-staging the old set PROMOTES it back — and asserts:

(a) the final full-model digest (host RAM + SSD tier, via
    ``export_rows``) is IDENTICAL to an UNCAPPED oracle run of the same
    job — demote → segment write → promote round trips are bit-exact
    and no row is ever lost or resurrected stale;
(b) demotion, promotion AND segment compaction actually happened
    (nonzero ``pbox_ssd_{demoted,promoted}_rows_total`` accounting);
(c) the whole capped outcome (digest + tier row accounting) is
    byte-identical across two identically-seeded runs — the async
    demote path is deterministic, not racy.

OVERLAP gate (``run_overlap_check``): the LoadSSD2Mem scheduling
property — with the stage fetch overlapped against the open pass (the
production pre_build_thread shape), the per-pass promote WAIT on the
critical path must fall well below the synchronous control where
``begin_pass`` itself pays the segment reads (the measured 26 s
``begin_stall_shrink`` path). Mirrors the pipeline_check timing gates:
measured up to 3 times, gated on the best attempt (noise only ever
inflates waits).

``python scripts/ssd_check.py`` prints one JSON line per gate;
tests/test_ssd_check.py runs smaller variants in tier-1.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

from scripts.pipeline_check import _train_mutate, host_tier_digest


def _key_sets(keys_per_set: int) -> Tuple[np.ndarray, np.ndarray]:
    a = np.arange(1, 1 + keys_per_set, dtype=np.uint64)
    b = np.arange(100_001, 100_001 + keys_per_set, dtype=np.uint64)
    return a, b


def _run_job(passes: int, shards: int, keys_per_set: int,
             host_capacity: int, ssd_dir: Optional[str],
             window_cap: int, overlap: bool = False,
             train_sleep: float = 0.0) -> Dict:
    """One A/B-alternating tiered job → digest + tier accounting +
    per-pass begin_stall breakdown. ``overlap=False`` stages
    synchronously on the main thread — every fetch barriers on the
    epilogue first, so the demote/promote interleaving is fully
    serialized and the run is deterministic by construction."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    with flags_scope(warmup_pass_scatter=False, ssd_dir="",
                     async_end_pass=True,
                     # small sealed segments + an aggressive live-frac
                     # threshold so the gate exercises compaction too
                     ssd_segment_rows=128, ssd_compact_live_frac=0.6):
        table = TieredShardedEmbeddingTable(
            shards, mf_dim=2, capacity_per_shard=window_cap,
            cfg=SparseSGDConfig(mf_create_thresholds=0.0,
                                mf_initial_range=0.0),
            host_capacity=host_capacity, ssd_dir=ssd_dir)
        a, b = _key_sets(keys_per_set)
        sets = [a if p % 2 == 0 else b for p in range(passes)]
        table.stage(sets[0], background=False)
        table.begin_pass(sets[0])
        waits: List[float] = []
        promos: List[float] = []
        rows_promoted: List[float] = []
        for p in range(passes):
            _train_mutate(table, p)
            if overlap and p + 1 < passes:
                # production shape: the next pass's host fetch (and any
                # SSD promote it needs) rides the open pass's training
                table.stage(sets[p + 1], background=True)
                time.sleep(train_sleep)   # stand-in for device train
            table.end_pass()
            if p + 1 < passes:
                table.begin_pass(sets[p + 1])
                lp = table.last_pass_stats
                waits.append(float(lp.get("ssd_promote_wait_sec", 0.0)))
                promos.append(float(lp.get("ssd_promote_sec", 0.0)))
                rows_promoted.append(
                    float(lp.get("ssd_promoted_rows", 0.0)))
        table.fence()
        st = table.ssd_stats()
        return {
            "digest": host_tier_digest(table),
            "rows": table.feature_count(),
            "ssd": {k: round(float(st.get(k, 0.0)), 6)
                    for k in ("live_rows", "segments", "demoted_rows",
                              "promoted_rows", "compacted_rows")},
            "promote_wait_sec": waits,
            "promote_sec": promos,
            "promoted_rows_per_pass": rows_promoted,
        }


def run_ssd_check(passes: int = 6, shards: int = 2,
                  keys_per_set: int = 512,
                  host_capacity: int = 340,
                  window_cap: int = 300) -> Dict:
    """The correctness gate. Raises AssertionError on any violated
    invariant; returns the evidence record."""
    assert passes >= 4, "the A/B revisit pattern needs >= 4 passes"
    # uncapped oracle: everything stays in host RAM, no tier attached
    oracle = _run_job(passes, shards, keys_per_set,
                      host_capacity=1 << 22, ssd_dir=None,
                      window_cap=window_cap)
    assert oracle["ssd"]["demoted_rows"] == 0, (
        "oracle run unexpectedly touched an SSD tier")
    capped = []
    for run in range(2):   # determinism: identical outcome twice
        with tempfile.TemporaryDirectory(prefix="pbox_ssd_") as td:
            capped.append(_run_job(passes, shards, keys_per_set,
                                   host_capacity=host_capacity,
                                   ssd_dir=td, window_cap=window_cap))
    c = capped[0]
    assert c["ssd"]["demoted_rows"] > 0, (
        f"capped run never demoted — the watermark policy is dead "
        f"({c['ssd']})")
    assert c["ssd"]["promoted_rows"] > 0, (
        f"capped run never promoted (pbox_ssd_promoted_rows_total == "
        f"0) — re-staged working sets came from nowhere ({c['ssd']})")
    # compaction is asserted white-box (tests/test_tiered_sharded.py —
    # this workload's sets promote whole segments dead, which the
    # dead-segment fast path reclaims without a rewrite); the gate
    # still reports compacted_rows for runs whose layout fragments
    assert c["digest"] == oracle["digest"], (
        "capped (demote+promote) run produced a DIFFERENT full-model "
        f"state than the uncapped oracle: {c['digest'][:16]}… != "
        f"{oracle['digest'][:16]}… — rows were lost or resurrected "
        "stale crossing the SSD tier")
    assert capped[1]["digest"] == c["digest"] and (
        capped[1]["ssd"] == c["ssd"]), (
        "capped outcome differs across identically-seeded runs: "
        f"{c['ssd']} vs {capped[1]['ssd']} — the demote/promote path "
        "is nondeterministic")
    return {
        "check": "ssd_check",
        "ok": True,
        "passes": passes,
        "shards": shards,
        "keys_per_set": keys_per_set,
        "host_capacity": host_capacity,
        "digest": c["digest"],
        "rows": c["rows"],
        "ssd": c["ssd"],
    }


def run_overlap_check(passes: int = 5, shards: int = 2,
                      keys_per_set: int = 2048,
                      host_capacity: int = 1300,
                      window_cap: int = 1100,
                      train_sleep: float = 0.15) -> Dict:
    """The promote-overlap gate: steady-state critical-path promote
    wait with overlapped staging must fall below half the synchronous
    control's (which pays the full segment-read time inside
    begin_pass). Timing property — measured up to 3 times, gated on
    the best attempt."""
    best = None
    for attempt in range(3):
        with tempfile.TemporaryDirectory(prefix="pbox_ssd_ov_") as td:
            ov = _run_job(passes, shards, keys_per_set, host_capacity,
                          td, window_cap, overlap=True,
                          train_sleep=train_sleep)
        with tempfile.TemporaryDirectory(prefix="pbox_ssd_sy_") as td:
            sy = _run_job(passes, shards, keys_per_set, host_capacity,
                          td, window_cap, overlap=False)
        # steady state skips the first boundary (cold spill layout)
        wait_ov = sum(ov["promote_wait_sec"][1:])
        wait_sy = sum(sy["promote_wait_sec"][1:])
        rec = {"wait_overlap_sec": round(wait_ov, 4),
               "wait_sync_sec": round(wait_sy, 4),
               "promote_sec_overlap": round(
                   sum(ov["promote_sec"][1:]), 4),
               "promoted_rows": sum(ov["promoted_rows_per_pass"][1:])}
        if best is None or rec["wait_overlap_sec"] < \
                best["wait_overlap_sec"]:
            best = rec
        if (rec["promoted_rows"] > 0 and wait_sy > 0
                and wait_ov <= 0.5 * wait_sy):
            best = rec
            break
    assert best["promoted_rows"] > 0, (
        f"overlap gate never promoted ({best}) — the working set no "
        "longer exceeds the capped host store")
    assert best["wait_sync_sec"] > 0, (
        f"synchronous control shows no promote wait ({best}) — the "
        "gate no longer exercises the LoadSSD2Mem path")
    assert best["wait_overlap_sec"] <= 0.5 * best["wait_sync_sec"], (
        f"overlapped promote wait {best['wait_overlap_sec']}s did not "
        f"drop below half the synchronous control "
        f"{best['wait_sync_sec']}s — LoadSSD2Mem is not riding the "
        f"stage thread ({best})")
    return {"check": "ssd_overlap_check", "ok": True, **best}


def main() -> None:
    print(json.dumps(run_ssd_check()))
    print(json.dumps(run_overlap_check()))


if __name__ == "__main__":
    main()
