#!/usr/bin/env python
"""Always-on online-learning daemon launcher (docs/ONLINE.md).

ONE process composing train→publish→serve over a watched directory:
``--data-dir`` is polled for ``*.txt`` arrivals; completed windows
publish boundary checkpoints into ``<workdir>/registry`` (the artifact
feed); ``--serve`` additionally runs a hot-reloading serving snapshot
off the same registry. Feature lifecycle (``--shrink-every``) ages the
model on the daemon's window clock.

Preemption contract (docs/RESILIENCE.md): SIGTERM/SIGINT triggers a
graceful stop — emergency boundary checkpoint + ``RESUME.json`` — and
the process exits ``EXIT_RESUME`` (75). Relaunching with the same
``--workdir`` consumes the marker and resumes the open window
at-least-once; a SIGKILL resumes from the newest checkpoint the same
way (minus the marker). A launcher loop is one line::

    until python scripts/onlinelearn.py --workdir W --data-dir D; do
        [ $? -eq 75 ] || break
    done

Health: ``--healthz-port`` serves /healthz (train+publish+serve+online
verdict), /readyz, /metrics, /alertz. Exit code 0 = the bounded run
(``--max-windows`` / ``--max-idle-polls``) drained cleanly; 75 = resume
requested; anything else is a real failure.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    ap.add_argument("--workdir", required=True,
                    help="daemon state root: ckpt/, registry/, "
                         "telemetry.jsonl live here")
    ap.add_argument("--data-dir", required=True,
                    help="watched directory; *.txt files are the "
                         "arriving stream")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--window-files", type=int, default=2,
                    help="files per stream window")
    ap.add_argument("--ckpt-every", type=int, default=1,
                    help="boundary checkpoint every N windows")
    ap.add_argument("--shrink-every", type=int, default=0,
                    help="shrink cycle every N windows (0 = off)")
    ap.add_argument("--shrink-threshold", type=float, default=0.0)
    ap.add_argument("--decay", type=float, default=0.98,
                    help="show/click decay per shrink cycle")
    ap.add_argument("--max-windows", type=int, default=None,
                    help="stop after N windows (None = run forever)")
    ap.add_argument("--max-idle-polls", type=int, default=None,
                    help="stop after N consecutive empty polls "
                         "(None = poll forever)")
    ap.add_argument("--serve", action="store_true",
                    help="run the hot-reloading serving leg too")
    ap.add_argument("--healthz-port", type=int, default=-1,
                    help=">=0: serve /healthz //metrics on this port "
                         "(0 = ephemeral)")
    ap.add_argument("--alerts-interval", type=float, default=0.0,
                    help=">0: evaluate default alert rules this often")
    ap.add_argument("--capacity", type=int, default=1 << 12,
                    help="embedding table capacity (rows)")
    ap.add_argument("--mf-dim", type=int, default=4)
    ap.add_argument("--ckpt-keep", type=int, default=3,
                    help="boundary checkpoints retained on disk "
                         "(forensic/audit runs want a deep history)")
    return ap


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.online import OnlineLearner
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.resilience.preemption import (EXIT_RESUME,
                                                     PreemptedError)
    from paddlebox_tpu.serving import ServingModel
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    workdir = os.path.abspath(args.workdir)
    data_dir = os.path.abspath(args.data_dir)
    os.makedirs(workdir, exist_ok=True)
    ckpt_root = os.path.join(workdir, "ckpt")
    with flags_scope(
            seed=args.seed,
            telemetry_jsonl=os.path.join(workdir, "telemetry.jsonl"),
            stream_window_files=args.window_files,
            stream_ckpt_every_windows=args.ckpt_every,
            shrink_every_windows=args.shrink_every,
            shrink_delete_threshold=args.shrink_threshold,
            show_click_decay_rate=args.decay,
            artifact_root=os.path.join(workdir, "registry"),
            alerts_eval_interval_sec=args.alerts_interval,
            graceful_shutdown=True,
            read_thread_num=1):
        desc = DataFeedDesc.criteo(batch_size=args.batch_size)
        desc.key_bucket_min = 2048
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)
        table = EmbeddingTable(mf_dim=args.mf_dim,
                               capacity=args.capacity, cfg=cfg,
                               unique_bucket_min=2048)
        trainer = Trainer(CtrDnn(hidden=(8,)), table, desc,
                          tx=optax.adam(1e-2), seed=args.seed)
        cm = CheckpointManager(ckpt_root, keep=args.ckpt_keep)
        resumed = None
        if cm.latest_step() is not None:
            resumed = cm.restore(trainer)

        def filelist_fn():
            return sorted(glob.glob(os.path.join(data_dir, "*.txt")))

        def mkds():
            ds = DatasetFactory().create_dataset("QueueDataset", desc)
            ds.set_filelist(filelist_fn())
            return ds

        serving = None
        if args.serve:
            serving = ServingModel(CtrDnn(hidden=(8,)), desc,
                                   mf_dim=args.mf_dim,
                                   capacity=args.capacity)
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        server = None
        if args.healthz_port >= 0:
            server = hub.start_prom_http(args.healthz_port)
            # the port line is a CONTRACT: test harnesses parse it
            print(json.dumps({"healthz_port":
                              server.server_address[1]}), flush=True)
        learner = OnlineLearner(
            trainer, mkds, cm, serving=serving,
            store=cm.artifacts if args.serve else None,
            filelist_fn=filelist_fn, max_windows=args.max_windows,
            max_idle_polls=args.max_idle_polls)
        status = {"resumed_step": resumed}
        try:
            totals = learner.run()
        except PreemptedError as e:
            status.update(learner.online_status(),
                          preempted=True, step=e.step,
                          checkpointed=e.checkpointed)
            print(json.dumps(status), flush=True)
            return EXIT_RESUME
        finally:
            if server is not None:
                hub.stop_prom_http()
        status.update(learner.online_status(), preempted=False,
                      totals={k: v for k, v in totals.items()
                              if isinstance(v, (int, float))})
        print(json.dumps(status), flush=True)
        # a clean bounded exit must not leave a stale resume marker
        preemption.clear_resume_marker(ckpt_root)
        return 0


if __name__ == "__main__":
    sys.exit(main())
