#!/usr/bin/env python
"""Multi-chip CTR training over a device mesh — sharded embedding PS +
data-parallel dense net, resident passes.

Run on real chips, or simulate a pod slice on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_multichip.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import optax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import SparseSGDConfig
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.train.sharded import ShardedTrainer


def main() -> None:
    n = len(jax.devices())
    mesh = make_mesh(n)
    work = tempfile.mkdtemp(prefix="pbox_mesh_")
    files = generate_criteo_files(os.path.join(work, "data"), num_files=2,
                                  rows_per_file=4000, vocab_per_slot=500,
                                  seed=0)
    desc = DataFeedDesc.criteo(batch_size=128)  # per device
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.local_shuffle(seed=1)

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.05, mf_learning_rate=0.05)
    # embedding rows shard by key % n across the mesh; pulls/pushes ride
    # two all_to_all collectives inside the jit step
    table = ShardedEmbeddingTable(n, mf_dim=8, capacity_per_shard=1 << 15,
                                  cfg=cfg)
    tr = ShardedTrainer(DeepFM(hidden=(128, 64)), table, desc, mesh,
                        tx=optax.adam(1e-3), zero1=True)  # ZeRO-1 dense
    for p in range(3):
        res = tr.train_pass_resident(ds)  # whole pass on-device
        tr.reset_metrics()
        print(f"pass {p}: auc={res['auc']:.4f} "
              f"features={table.feature_count()}")
    table.save_base(os.path.join(work, "sharded_base.npz"))
    print(f"artifacts in {work}")


if __name__ == "__main__":
    main()
