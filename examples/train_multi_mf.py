#!/usr/bin/env python
"""Per-slot embedding dims (multi_mf_dim) end to end.

Production CTR tables mix embedding widths per slot (a user-id slot may
carry 64 dims while a tiny categorical carries 4 — feature_value.h:42,
ps_gpu_wrapper.cc multi-mf build). This example trains DeepFM-style CTR
with three dim classes through MultiMfEmbeddingTable / MultiMfTrainer,
then saves and reloads the class tables.

Run:  python examples/train_multi_mf.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import MultiMfEmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import MultiMfTrainer


def main() -> None:
    data_dir = tempfile.mkdtemp(prefix="mmf_")
    files = generate_criteo_files(data_dir, num_files=2,
                                  rows_per_file=4000,
                                  vocab_per_slot=200, seed=7)
    desc = DataFeedDesc.criteo(batch_size=256)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()

    # 26 criteo slots: 10 narrow, 10 medium, 6 wide
    slot_dims = [4] * 10 + [8] * 10 + [16] * 6
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    table = MultiMfEmbeddingTable(slot_dims, capacity=1 << 15, cfg=cfg)
    tr = MultiMfTrainer(CtrDnn(hidden=(64, 32)), table, desc,
                        tx=optax.adam(1e-3))

    for p in range(3):
        res = tr.train_pass(ds, log_prefix=f"[pass {p}] ")
    print(f"final auc={res['auc']:.4f} over dim classes "
          f"{table.dims} ({table.feature_count} features)")

    # save one artifact per dim class, reload, spot-check a pull
    path = os.path.join(data_dir, "mmf_base")
    n = table.save_base(path)
    t2 = MultiMfEmbeddingTable(slot_dims, capacity=1 << 15, cfg=cfg)
    assert t2.load(path) == n
    ds.columnarize()   # no-op on the native fast path; builds otherwise
    col = ds.columnar
    keys, slots = col.keys[:8].astype(np.uint64), col.key_slot[:8]
    np.testing.assert_allclose(t2.pull(keys, slots),
                               table.pull(keys, slots), rtol=1e-6)
    print(f"save/load roundtrip ok ({n} rows across "
          f"{len(table.dims)} class files)")


if __name__ == "__main__":
    main()
