#!/usr/bin/env python
"""Beyond-HBM training: a model BIGGER than the device windows, trained
across day-passes on a mesh — the AIBox/BoxPS architecture end to end.

Each key%N HBM shard holds only one pass's working set; the full model
lives in per-shard host stores (RAM + optional disk spill). Per pass:
stage (BuildPull: host fetch) → begin_pass (BuildGPUTask: scatter to
HBM) → train → end_pass (EndPass: write-back). Reference:
ps_gpu_wrapper.cc:337,684,983; box_wrapper.cc:1415 (LoadSSD2Mem).

Run on real chips, or simulate a pod slice on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_tiered.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # env alone may not override a preloaded TPU plugin — force it
    # before the backend initializes (same as tests/conftest.py)
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import optax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import (BoxPSHelper, SparseSGDConfig,
                              TieredShardedEmbeddingTable)
from paddlebox_tpu.train.sharded import ShardedTrainer

VOCAB = 400


def write_day(work: str, day: int, rows: int = 3000) -> str:
    """Day-k criteo files in a SLIDING value range — consecutive days
    share half their feature space (the production CTR pattern: day k+1
    mostly re-touches day k's features while the multi-day union still
    exceeds any pass window), so the persistent window's delta staging
    has real reuse to exploit."""
    return generate_criteo_files(
        os.path.join(work, f"day{day}"), num_files=1, rows_per_file=rows,
        vocab_per_slot=VOCAB, seed=1000 + day,
        value_base=day * VOCAB // 2)[0]


def main() -> None:
    n = len(jax.devices())
    mesh = make_mesh(n)
    work = tempfile.mkdtemp(prefix="pbox_tiered_")
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.05, mf_learning_rate=0.05)
    # HBM window deliberately smaller than the multi-day union: each
    # pass's working set (~10.4k uniques) fits, the 4-day model does not
    cap = (12_000 + n - 1) // n
    table = TieredShardedEmbeddingTable(n, mf_dim=8,
                                        capacity_per_shard=cap, cfg=cfg)
    tr = ShardedTrainer(DeepFM(hidden=(128, 64)), table, desc, mesh,
                        tx=optax.adam(2e-3))
    helper = BoxPSHelper(table, trainer=tr)

    def make_day(day: int):
        """PaddleBoxDataset so day k+1's IO/parse can ALSO overlap day
        k's training (preload_into_memory / wait_feed_pass_done — the
        box_wrapper.h:1142 double-buffering)."""
        d = DatasetFactory().create_dataset("PaddleBoxDataset", desc)
        d.set_filelist([write_day(work, day)])
        return d

    ds = make_day(0)
    helper.read_data_to_memory(ds)
    for day in range(4):
        tr.reset_metrics()                          # per-day AUC
        helper.begin_pass(ds)                       # host → HBM window
        st = dict(table.last_pass_stats)            # delta accounting
        ds_next = make_day(day + 1) if day < 3 else None
        if ds_next is not None:
            helper.preload_into_memory(ds_next)     # IO overlaps epoch 1
        for e in range(3):                          # epochs in the window
            res = tr.train_pass(ds)                 # or train_pass_resident
            if e == 0 and ds_next is not None:
                # the FULL overlap pipeline: day k+1's IO/parse rode
                # epoch 1 in reader threads; its host-tier fetch of
                # MISSING keys (pre_build_thread, ps_gpu_wrapper.cc:913)
                # now rides epochs 2-3 — with the sliding feature
                # space, ~half of day k+1 is already resident and never
                # re-ships
                helper.wait_feed_pass_done(ds_next)
                helper.stage_pass(ds_next)
        helper.end_pass(ds, need_save_delta=True,
                        delta_path=os.path.join(work, f"delta_{day}.npz"))
        print(f"day {day}: auc={res['auc']:.4f} "
              f"staged={st['staged']} resident={st['resident']} "
              f"evicted={st['evicted']} "
              f"window_rows={sum(len(ix) for ix in table.indexes)} "
              f"host_tier_rows={table.feature_count()}")
        ds = ds_next

    hbm_window = n * table.capacity
    total = table.feature_count()
    print(f"\nhost tier holds {total} features vs {hbm_window} HBM window "
          f"rows ({total / hbm_window:.1f}x beyond device memory)")

    # full-model lifecycle runs on the host tier between passes; the
    # threshold ≈ 5 unclicked shows after decay
    # — features seen only a handful of times genuinely age out
    base = os.path.join(work, "base.npz")
    helper.save_base(base)
    freed = helper.shrink_table(delete_threshold=0.5)
    print(f"saved full base ({base}); shrink aged out {freed} rows")


if __name__ == "__main__":
    main()
