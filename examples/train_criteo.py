#!/usr/bin/env python
"""End-to-end Criteo CTR training — the canonical usage walkthrough.

Covers the whole production loop on synthetic Criteo-shaped data:
native-parsed columnar load, device-resident passes with double-buffered
preloading, metric variants, base+delta checkpoints, and a serving-model
consumer. Runs on one TPU chip or CPU (JAX_PLATFORMS=cpu).

    python examples/train_criteo.py [--rows 20000] [--passes 3]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.serving import ServingModel
from paddlebox_tpu.train import (CheckpointManager, PassPreloader, Trainer)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()
    work = args.workdir or tempfile.mkdtemp(prefix="pbox_demo_")

    # 1) data: synthetic criteo files, native C++ parse → columnar store
    files = generate_criteo_files(os.path.join(work, "data"), num_files=4,
                                  rows_per_file=args.rows // 4,
                                  vocab_per_slot=1000, seed=0)
    desc = DataFeedDesc.criteo(batch_size=args.batch_size)
    desc.key_bucket_min = args.batch_size * 26

    def day(seed: int):
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.set_thread(4)
        ds.load_into_memory()
        ds.local_shuffle(seed=seed)
        return ds

    # 2) model + HBM embedding table + trainer
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=8, capacity=1 << 18, cfg=cfg,
                           unique_bucket_min=1 << 12)
    tr = Trainer(DeepFM(hidden=(256, 128)), table, desc,
                 tx=optax.adam(1e-3))
    ckpt = CheckpointManager(os.path.join(work, "ckpt"), keep=3)

    # 3) device-resident passes, pass k+1 preloading while pass k trains
    pre = PassPreloader(iter(day(s) for s in range(args.passes)), table)
    pre.start_next()
    for p in range(args.passes):
        rp = pre.wait()
        pre.start_next()
        res = tr.train_pass_resident(rp)
        print(f"pass {p}: auc={res['auc']:.4f} "
              f"ex/s={res['examples_per_sec']:.0f} "
              f"features={table.feature_count}")
        ckpt.save(tr, delta=p > 0)

    # 4) held-out eval with a registered metric variant
    tr.metrics.init_metric("test_auc", method="auc")
    tr.eval_pass(day(98))
    print(f"eval: {tr.metrics.get_metric_msg('test_auc')}")

    # 5) export → online serving consumer
    base = os.path.join(work, "base.npz")
    tr.sync_table()
    table.save_base(base)
    tr.save(os.path.join(work, "model"))
    srv = ServingModel(DeepFM(hidden=(256, 128)), desc, mf_dim=8,
                       capacity=1 << 18)
    srv.load_base(base)
    srv.load_dense(os.path.join(work, "model.dense.pkl"))
    batch = next(day(99).batches())
    preds, valid = srv.predict(batch, return_valid=True)
    print(f"serving: {int(valid.sum())} predictions, "
          f"mean CTR {preds[valid > 0].mean():.4f}")
    print(f"artifacts in {work}")


if __name__ == "__main__":
    main()
