#!/usr/bin/env python
"""Multi-host CTR training on one machine — launcher + TCP global shuffle.

Spawns N worker processes via the launcher (each sees PBOX_RANK /
PBOX_WORLD_SIZE, like the reference's paddle.distributed.launch ranks),
and each worker:

  1. reads its round-robin shard of the file list,
  2. exchanges records with its peers through the TcpShuffler
     (the PaddleShuffler/ShuffleData role — data_set.cc:2573),
  3. trains DeepFM on its post-shuffle partition and reports AUC.

On a real multi-host pod the same script runs once per host with the
env provided by your scheduler; only the endpoints change.

    python examples/train_multihost.py [--workers 2] [--rows 4000]
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def worker(args) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import optax

    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.distributed.collective import TcpCollective
    from paddlebox_tpu.distributed.shuffle import TcpShuffler
    from paddlebox_tpu.metrics import auc_compute_global
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    rank = int(os.environ["PBOX_RANK"])
    world = int(os.environ["PBOX_WORLD_SIZE"])
    endpoints = os.environ["SHUFFLE_ENDPOINTS"].split(",")

    desc = DataFeedDesc.criteo(batch_size=args.batch_size)
    FLAGS.native_parse = False   # the exchange moves record objects
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    files = sorted(os.path.join(args.data, f)
                   for f in os.listdir(args.data))
    ds.set_filelist(files, shard_by_rank=True)
    ds.load_into_memory()
    loaded = len(ds.records)

    sh = TcpShuffler(rank, world, endpoints, seed=7)
    ds.global_shuffle(sh)        # records route to hash(record) % world
    sh.close()

    table = EmbeddingTable(
        mf_dim=8, capacity=1 << 16,
        cfg=SparseSGDConfig(mf_create_thresholds=0.0))
    tr = Trainer(DeepFM(hidden=(64, 32)), table, desc,
                 tx=optax.adam(1e-2), seed=rank)
    for _ in range(args.passes):
        res = tr.train_pass(ds, log_prefix=f"[rank {rank}] ")
    # ONE global AUC across all workers (metrics.cc:288-304): allreduce
    # the bucket tables over the host collective plane
    coll_eps = os.environ.get("COLLECTIVE_ENDPOINTS")
    global_auc = None
    if coll_eps:
        coll = TcpCollective(rank, world, coll_eps.split(","))
        global_auc = round(float(
            auc_compute_global(tr.state.auc, coll).auc), 4)
        coll.close()
    print(json.dumps(dict(rank=rank, loaded=loaded,
                          after_shuffle=len(ds.records),
                          auc=round(float(res["auc"]), 4),
                          global_auc=global_auc,
                          features=int(table.feature_count))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--rows", type=int, default=4000)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--data", default=None)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal re-exec flag
    args = ap.parse_args()
    if args.worker:
        worker(args)
        return

    from paddlebox_tpu.data.criteo import generate_criteo_files
    data = args.data or os.path.join(tempfile.mkdtemp(prefix="pbox_mh_"),
                                     "data")
    if not os.path.isdir(data) or not os.listdir(data):
        generate_criteo_files(data, num_files=2 * args.workers,
                              rows_per_file=args.rows // (2 * args.workers),
                              vocab_per_slot=200, seed=1)

    socks = [socket.socket() for _ in range(2 * args.workers)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports[:args.workers])
    coll_eps = ",".join(f"127.0.0.1:{p}" for p in ports[args.workers:])
    for s in socks:
        s.close()

    procs = []
    for r in range(args.workers):
        env = dict(os.environ, PBOX_RANK=str(r),
                   PBOX_WORLD_SIZE=str(args.workers),
                   SHUFFLE_ENDPOINTS=endpoints,
                   COLLECTIVE_ENDPOINTS=coll_eps, JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--data", data, "--rows", str(args.rows),
             "--passes", str(args.passes),
             "--batch-size", str(args.batch_size)],
            env=env))
    rc = [p.wait() for p in procs]
    if any(rc):
        raise SystemExit(f"worker failures: {rc}")
    print("all workers done")


if __name__ == "__main__":
    main()
