"""True multi-process training integration: N worker processes on
localhost, each reading its rank's file shard, exchanging records through
the TcpShuffler (global shuffle over "DCN"), training the same model, and
reporting metrics — the reference's ``test_dist_base`` strategy
(SURVEY.md §4: subprocess trainers on localhost endpoints, diff results).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddlebox_tpu.data.criteo import generate_criteo_files

WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import optax

    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.distributed.collective import TcpCollective
    from paddlebox_tpu.distributed.shuffle import TcpShuffler
    from paddlebox_tpu.metrics import auc_compute_global
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    rank = int(os.environ["PBOX_RANK"])
    world = int(os.environ["PBOX_WORLD_SIZE"])
    endpoints = os.environ["SHUFFLE_ENDPOINTS"].split(",")
    coll_eps = os.environ["COLLECTIVE_ENDPOINTS"].split(",")
    data_dir, out_dir = sys.argv[1], sys.argv[2]

    desc = DataFeedDesc.criteo(batch_size=64)
    desc.key_bucket_min = 2048
    FLAGS.native_parse = False  # record objects needed for the exchange

    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    files = sorted(os.path.join(data_dir, f)
                   for f in os.listdir(data_dir))
    ds.set_filelist(files, shard_by_rank=True)   # this rank's slice
    ds.load_into_memory()
    n_loaded = len(ds.records)

    sh = TcpShuffler(rank, world, endpoints, seed=11)
    ds.global_shuffle(sh)                        # cross-process exchange
    sh.close()
    n_after = len(ds.records)

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13,
                           unique_bucket_min=2048, cfg=cfg)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc,
                 tx=optax.adam(1e-2), seed=rank)
    for _ in range(3):
        res = tr.train_pass(ds)

    # ONE global AUC across workers (metrics.cc:288-304 role)
    coll = TcpCollective(rank, world, coll_eps)
    gres = auc_compute_global(tr.state.auc, coll)
    coll.close()

    out = dict(rank=rank, loaded=n_loaded, after_shuffle=n_after,
               auc=float(res["auc"]), global_auc=float(gres.auc),
               global_ins=float(gres.ins_num),
               features=int(table.feature_count))
    with open(os.path.join(out_dir, f"r{rank}.json"), "w") as fh:
        json.dump(out, fh)
    np.savez(os.path.join(out_dir, f"auc_r{rank}.npz"),
             **{f: np.asarray(x) for f, x in
                zip(tr.state.auc._fields, tr.state.auc)})
""")


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.mark.slow
def test_two_process_shuffle_and_train(tmp_path):
    world = 2
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    files = generate_criteo_files(str(data_dir), num_files=4,
                                  rows_per_file=300, vocab_per_slot=40,
                                  seed=3)
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    ports = _free_ports(2 * world)
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports[:world])
    coll_endpoints = ",".join(f"127.0.0.1:{p}" for p in ports[world:])

    procs = []
    for r in range(world):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, PBOX_RANK=str(r),
                   PBOX_WORLD_SIZE=str(world),
                   SHUFFLE_ENDPOINTS=endpoints,
                   COLLECTIVE_ENDPOINTS=coll_endpoints,
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.pop("XLA_FLAGS", None)  # single-device CPU is fine per worker
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(data_dir), str(out_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = [p.communicate(timeout=600)[0] for p in procs]
    if any(p.returncode != 0 for p in procs):
        raise AssertionError("\n\n".join(
            f"--- rank {r} rc={p.returncode} ---\n{o[-1500:]}"
            for r, (p, o) in enumerate(zip(procs, outs))))

    res = [json.load(open(out_dir / f"r{r}.json")) for r in range(world)]
    # every record loaded somewhere, every record landed somewhere
    assert sum(r["loaded"] for r in res) == 1200
    assert sum(r["after_shuffle"] for r in res) == 1200
    # the shuffle actually moved records (both ranks end non-empty and
    # differently sized than their raw shard with overwhelming odds)
    assert all(r["after_shuffle"] > 0 for r in res)
    # both workers trained to something sane on their shard
    for r in res:
        assert np.isfinite(r["auc"]) and r["auc"] > 0.55, res
        assert r["features"] > 0
    # the global AUC is identical on every rank and covers ALL instances
    assert res[0]["global_auc"] == pytest.approx(res[1]["global_auc"],
                                                 abs=1e-9)
    # 3 passes over 1200 records — the allreduced total, on EVERY rank
    for r in res:
        assert r["global_ins"] == 3 * 1200
    # and it equals a single-process AUC over the UNION of both ranks'
    # accumulated prediction histograms (the metrics.cc:288-304 merge)
    from paddlebox_tpu.metrics import AucState, auc_compute
    blobs = [np.load(out_dir / f"auc_r{r}.npz") for r in range(world)]
    merged = AucState(*[
        sum(np.asarray(b[f], np.float64) for b in blobs)
        for f in AucState._fields])
    union = auc_compute(merged)
    assert res[0]["global_auc"] == pytest.approx(union.auc, abs=1e-12)
