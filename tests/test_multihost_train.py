"""True multi-process training integration: N worker processes on
localhost, each reading its rank's file shard, exchanging records through
the TcpShuffler (global shuffle over "DCN"), training the same model, and
reporting metrics — the reference's ``test_dist_base`` strategy
(SURVEY.md §4: subprocess trainers on localhost endpoints, diff results).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddlebox_tpu.data.criteo import generate_criteo_files

WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import optax

    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.distributed.collective import TcpCollective
    from paddlebox_tpu.distributed.shuffle import TcpShuffler
    from paddlebox_tpu.metrics import auc_compute_global
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    rank = int(os.environ["PBOX_RANK"])
    world = int(os.environ["PBOX_WORLD_SIZE"])
    endpoints = os.environ["SHUFFLE_ENDPOINTS"].split(",")
    coll_eps = os.environ["COLLECTIVE_ENDPOINTS"].split(",")
    data_dir, out_dir = sys.argv[1], sys.argv[2]

    desc = DataFeedDesc.criteo(batch_size=64)
    desc.key_bucket_min = 2048
    FLAGS.native_parse = False  # record objects needed for the exchange

    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    files = sorted(os.path.join(data_dir, f)
                   for f in os.listdir(data_dir))
    ds.set_filelist(files, shard_by_rank=True)   # this rank's slice
    ds.load_into_memory()
    n_loaded = len(ds.records)

    sh = TcpShuffler(rank, world, endpoints, seed=11)
    ds.global_shuffle(sh)                        # cross-process exchange
    sh.close()
    n_after = len(ds.records)

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13,
                           unique_bucket_min=2048, cfg=cfg)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc,
                 tx=optax.adam(1e-2), seed=rank)
    for _ in range(3):
        res = tr.train_pass(ds)

    # ONE global AUC across workers (metrics.cc:288-304 role)
    coll = TcpCollective(rank, world, coll_eps)
    gres = auc_compute_global(tr.state.auc, coll)
    coll.close()

    out = dict(rank=rank, loaded=n_loaded, after_shuffle=n_after,
               auc=float(res["auc"]), global_auc=float(gres.auc),
               global_ins=float(gres.ins_num),
               features=int(table.feature_count))
    with open(os.path.join(out_dir, f"r{rank}.json"), "w") as fh:
        json.dump(out, fh)
    np.savez(os.path.join(out_dir, f"auc_r{rank}.npz"),
             **{f: np.asarray(x) for f, x in
                zip(tr.state.auc._fields, tr.state.auc)})
""")


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.mark.slow
def test_two_process_shuffle_and_train(tmp_path):
    world = 2
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    files = generate_criteo_files(str(data_dir), num_files=4,
                                  rows_per_file=300, vocab_per_slot=40,
                                  seed=3)
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    ports = _free_ports(2 * world)
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports[:world])
    coll_endpoints = ",".join(f"127.0.0.1:{p}" for p in ports[world:])

    procs = []
    for r in range(world):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, PBOX_RANK=str(r),
                   PBOX_WORLD_SIZE=str(world),
                   SHUFFLE_ENDPOINTS=endpoints,
                   COLLECTIVE_ENDPOINTS=coll_endpoints,
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.pop("XLA_FLAGS", None)  # single-device CPU is fine per worker
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(data_dir), str(out_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = [p.communicate(timeout=600)[0] for p in procs]
    if any(p.returncode != 0 for p in procs):
        raise AssertionError("\n\n".join(
            f"--- rank {r} rc={p.returncode} ---\n{o[-1500:]}"
            for r, (p, o) in enumerate(zip(procs, outs))))

    res = [json.load(open(out_dir / f"r{r}.json")) for r in range(world)]
    # every record loaded somewhere, every record landed somewhere
    assert sum(r["loaded"] for r in res) == 1200
    assert sum(r["after_shuffle"] for r in res) == 1200
    # the shuffle actually moved records (both ranks end non-empty and
    # differently sized than their raw shard with overwhelming odds)
    assert all(r["after_shuffle"] > 0 for r in res)
    # both workers trained to something sane on their shard
    for r in res:
        assert np.isfinite(r["auc"]) and r["auc"] > 0.55, res
        assert r["features"] > 0
    # the global AUC is identical on every rank and covers ALL instances
    assert res[0]["global_auc"] == pytest.approx(res[1]["global_auc"],
                                                 abs=1e-9)
    # 3 passes over 1200 records — the allreduced total, on EVERY rank
    for r in res:
        assert r["global_ins"] == 3 * 1200
    # and it equals a single-process AUC over the UNION of both ranks'
    # accumulated prediction histograms (the metrics.cc:288-304 merge)
    from paddlebox_tpu.metrics import AucState, auc_compute
    blobs = [np.load(out_dir / f"auc_r{r}.npz") for r in range(world)]
    merged = AucState(*[
        sum(np.asarray(b[f], np.float64) for b in blobs)
        for f in AucState._fields])
    union = auc_compute(merged)
    assert res[0]["global_auc"] == pytest.approx(union.auc, abs=1e-12)


MM_COMMON = textwrap.dedent("""
    import numpy as np
    from paddlebox_tpu.data import DataFeedDesc, SlotDef
    from paddlebox_tpu.data.dataset import InMemoryDataset
    from paddlebox_tpu.data.record import SlotRecord

    def build_dataset(n_dev, B=8, S=4, n_rec=96):
        slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 3)]
        slots += [SlotDef(f"C{i}", "uint64") for i in range(S)]
        desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                            key_bucket_min=B * S)
        rng = np.random.default_rng(7)
        offsets = np.arange(S + 1, dtype=np.int32)
        recs = []
        for j in range(n_rec):
            label = float(rng.integers(0, 2))
            recs.append(SlotRecord(
                keys=rng.integers(0, 200, size=S).astype(np.uint64),
                slot_offsets=offsets,
                dense=rng.normal(size=3).astype(np.float32),
                label=label, show=1.0, clk=label,
                ins_id=f"ins_{j:05d}", uid=j % 7,
                rank=0, cmatch=401 if j % 3 == 0 else 402))
        ds = InMemoryDataset(desc)
        ds.records = recs
        return desc, ds

    def make_trainer(desc, mesh, n_dev):
        import optax
        from paddlebox_tpu.models import DeepFM
        from paddlebox_tpu.ps import SparseSGDConfig
        from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
        from paddlebox_tpu.train.sharded import ShardedTrainer
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)
        table = ShardedEmbeddingTable(n_dev, mf_dim=4,
                                      capacity_per_shard=512, cfg=cfg,
                                      req_bucket_min=16,
                                      serve_bucket_min=16)
        tr = ShardedTrainer(DeepFM(hidden=(16, 8)), table, desc, mesh,
                            tx=optax.adam(1e-2))
        tr.metrics.init_metric("q_auc", "auc")
        tr.metrics.init_metric("cm_auc", "cmatch_rank_auc",
                               cmatch_rank_group="401:0",
                               ignore_rank=True)
        tr.metrics.init_metric("wu", "wuauc")
        return tr
""")

DUMP_METRIC_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()
    rank = info["rank"]
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from mm_common import build_dataset, make_trainer
    from paddlebox_tpu.train.multihost import global_mesh, globalize_state
    from paddlebox_tpu.utils.dump import DumpConfig

    out_dir = sys.argv[1]
    n = jax.device_count()
    assert n == 4, n
    mesh = global_mesh()
    desc, ds = build_dataset(n)
    tr = make_trainer(desc, mesh, n)
    tr.state = globalize_state(mesh, tr.state, tr.step_fn.state_spec)
    tr.set_dump(DumpConfig(os.path.join(out_dir, "pod/preds"),
                           fields=("pred", "label", "show", "clk")))
    res = tr.train_pass(ds)
    # every process calls get_metric_msg in lockstep (collective gather)
    msgs = {nm: tr.metrics.get_metric_msg(nm)
            for nm in ("q_auc", "cm_auc", "wu")}
    with open(os.path.join(out_dir, f"pod_r{rank}.json"), "w") as fh:
        json.dump({"auc": res["auc"], "batches": res["batches"],
                   "last_loss": res["last_loss"], "msgs": msgs}, fh)
    print(f"rank={rank} dumpmetrics ok", flush=True)
""")


@pytest.mark.slow
def test_two_process_dump_and_metric_variants(tmp_path):
    """Per-worker dump + registry metric variants at pod scale
    (VERDICT r4 item 2): each process dumps its ADDRESSABLE device rows
    into its own part file and feeds its rows to its registry; the
    rank-dump concatenation equals the single-controller dump
    line-for-line, and every metric variant matches the
    single-controller value after the pod reduce."""
    import importlib.util

    import jax
    import optax  # noqa: F401  (mm_common imports it lazily)

    from tests.test_multihost_jax import _run_two_workers
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.utils.dump import DumpConfig

    common = tmp_path / "mm_common.py"
    common.write_text(MM_COMMON)
    spec = importlib.util.spec_from_file_location("mm_common", str(common))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    # oracle: single-controller, 4 local devices
    n = 4
    desc, ds = mod.build_dataset(n)
    tr = mod.make_trainer(desc, make_mesh(n), n)
    tr.set_dump(DumpConfig(str(tmp_path / "oracle/preds"),
                           fields=("pred", "label", "show", "clk")))
    res = tr.train_pass(ds)
    oracle_msgs = {nm: tr.metrics.get_metric_msg(nm)
                   for nm in ("q_auc", "cm_auc", "wu")}
    oracle_lines = [ln for d in range(n) for ln in open(
        tmp_path / f"oracle/preds.part-{d:05d}").read().splitlines()]
    assert len(oracle_lines) == 96

    outs = _run_two_workers(tmp_path, DUMP_METRIC_WORKER, "w_dm.py",
                            argv=[str(tmp_path)])
    for r, o in enumerate(outs):
        assert f"rank={r} dumpmetrics ok" in o, o

    # per-device part files are keyed by device row, so the pod run
    # (rank 0 writes rows 0-1, rank 1 rows 2-3) reproduces the
    # single-controller dump line-for-line when concatenated in device
    # order
    pod_lines = [ln for d in range(n) for ln in open(
        tmp_path / f"pod/preds.part-{d:05d}").read().splitlines()]
    assert pod_lines == oracle_lines

    # per-rank registry partials reduce to the single-controller values
    pod = [json.load(open(tmp_path / f"pod_r{r}.json")) for r in range(2)]
    for r in range(2):
        assert pod[r]["batches"] == res["batches"]
        assert pod[r]["auc"] == pytest.approx(res["auc"], abs=1e-6)
        assert pod[r]["last_loss"] == pytest.approx(res["last_loss"],
                                                    abs=1e-6)
        for nm, want in oracle_msgs.items():
            got = pod[r]["msgs"][nm]
            for k, v in want.items():
                assert got[k] == pytest.approx(v, abs=1e-6), (nm, k, got)
