"""FileMgr tests (reference: BoxFileMgr, pybind/box_helper_py.cc:167-216)."""

import os

import pytest

from paddlebox_tpu.utils.file_mgr import (CommandBackend, FileMgr,
                                          split_scheme)


def test_split_scheme():
    assert split_scheme("/a/b") == ("file", "/a/b")
    assert split_scheme("file:///a") == ("file", "/a")
    assert split_scheme("hdfs://nn/a") == ("hdfs", "nn/a")


def test_local_roundtrip(tmp_path):
    mgr = FileMgr()
    root = tmp_path / "store"
    assert mgr.makedir(str(root))
    src = tmp_path / "model.bin"
    src.write_bytes(b"x" * 128)

    remote = str(root / "day1" / "model.bin")
    assert mgr.upload(str(src), remote)
    assert mgr.exists(remote)
    assert mgr.file_size(remote) == 128
    assert mgr.count(str(root)) == 1
    assert mgr.dus(str(root)) == 128
    assert mgr.list_dir(str(root / "day1")) == ["model.bin"]
    assert mgr.list_info(str(root / "day1")) == [("model.bin", 128)]

    back = tmp_path / "restored.bin"
    assert mgr.download(remote, str(back))
    assert back.read_bytes() == b"x" * 128

    renamed = str(root / "day1" / "model_v2.bin")
    assert mgr.rename(remote, renamed)
    assert not mgr.exists(remote)
    assert mgr.exists(renamed)

    assert mgr.truncate(renamed, 16)
    assert mgr.file_size(renamed) == 16
    assert mgr.touch(str(root / "marker"))
    assert mgr.exists(str(root / "marker"))

    assert mgr.remove(str(root))
    assert not mgr.exists(str(root))


def test_unknown_scheme_raises(tmp_path):
    mgr = FileMgr()
    with pytest.raises(KeyError):
        mgr.exists("afs://cluster/path")


def test_command_backend_registration(tmp_path):
    """A CommandBackend registered for a scheme is dispatched to; here the
    'CLI' is a tiny shim emulating `hadoop fs -test/-put`."""
    shim = tmp_path / "fsshim.py"
    shim.write_text(
        "import os, shutil, sys\n"
        "def strip(p):\n"
        "    # CLIs receive the full afs:// URI (wants_full_uri)\n"
        "    assert p.startswith('afs://'), p\n"
        "    return p[len('afs://'):]\n"
        "args = sys.argv[1:]\n"
        "if args[0] == '-test':\n"
        "    sys.exit(0 if os.path.exists(strip(args[2])) else 1)\n"
        "if args[0] == '-put':\n"
        "    dst = strip(args[2])\n"
        "    os.makedirs(os.path.dirname(dst), exist_ok=True)\n"
        "    shutil.copy(args[1], dst); sys.exit(0)\n"
        "if args[0] == '-mv':\n"
        "    os.replace(strip(args[1]), strip(args[2])); sys.exit(0)\n"
        "sys.exit(2)\n")
    import sys

    mgr = FileMgr()
    mgr.init(scheme="afs", command=[sys.executable, str(shim)])

    src = tmp_path / "f.txt"
    src.write_text("hi")
    dst = tmp_path / "remote" / "f.txt"
    assert mgr.upload(str(src), f"afs://{dst}")
    assert mgr.exists(f"afs://{dst}")
    assert not mgr.exists(f"afs://{tmp_path}/nope")
    with pytest.raises(NotImplementedError):
        mgr.truncate(f"afs://{dst}", 1)


def test_finalize_resets(tmp_path):
    mgr = FileMgr()
    mgr.init(scheme="afs", command=["true"])
    mgr.finalize()
    with pytest.raises(KeyError):
        mgr.list_dir("afs://x")
