"""seqpool variant semantics vs numpy references (reference CUDA kernels:
fused_seqpool_cvm_{with_diff_thres,tradew,with_credit,with_pcoc}_op.cu)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ops import (
    fused_seq_tensor, fused_seqpool_cvm_tradew,
    fused_seqpool_cvm_with_credit, fused_seqpool_cvm_with_diff_thres,
    fused_seqpool_cvm_with_pcoc,
)


def make_inputs(k=60, b=4, s=3, e=5, extra=0, seed=0):
    rng = np.random.default_rng(seed)
    vals = np.abs(rng.normal(size=(k, 2 + extra + e))).astype(np.float32)
    segs = np.sort(rng.integers(0, b * s, size=k)).astype(np.int32)
    return vals, segs, rng


def np_pool(vals, segs, n_seg, keep=None):
    out = np.zeros((n_seg, vals.shape[1]), np.float64)
    for i, sg in enumerate(segs):
        if keep is None or keep[i]:
            out[sg] += vals[i]
    return out


def test_diff_thres_per_slot_threshold():
    b, s, e = 4, 3, 5
    vals, segs, rng = make_inputs(b=b, s=s, e=e)
    thr = np.array([0.3, 5.0, 0.0], np.float32)  # slot1 filters everything
    sc = np.abs(rng.normal(size=(b, 2))).astype(np.float32)
    out = fused_seqpool_cvm_with_diff_thres(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(sc),
        jnp.asarray(thr), b, s, show_coeff=0.2, clk_coeff=1.0)
    slot = segs % s
    score = (vals[:, 0] - vals[:, 1]) * 0.2 + vals[:, 1] * 1.0
    keep = score >= thr[slot]
    pooled = np_pool(vals, segs, b * s, keep).reshape(b, s, -1)
    want_show = np.log1p(pooled[..., 0])
    np.testing.assert_allclose(np.asarray(out)[..., 0], want_show, rtol=1e-4, atol=1e-6)
    ctr = np.log1p(pooled[..., 1]) - np.log1p(pooled[..., 0])
    np.testing.assert_allclose(np.asarray(out)[..., 1], ctr, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out)[..., 2:], pooled[..., 2:],
                               rtol=1e-4, atol=1e-6)
    # slot 1 fully filtered → zero pools → log1p(0)=0 head
    np.testing.assert_allclose(np.asarray(out)[:, 1, :], 0.0, atol=1e-6)


def test_tradew_normal_and_trade_id():
    b, s, e, tn = 3, 2, 4, 2
    vals, segs, rng = make_inputs(k=40, b=b, s=s, e=e, extra=tn, seed=1)
    sc = np.abs(rng.normal(size=(b, 2))).astype(np.float32)

    out = fused_seqpool_cvm_tradew(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(sc), b, s, tn)
    v_sel = np.concatenate([vals[:, :2], vals[:, 2 + tn:]], 1)
    pooled = np_pool(v_sel, segs, b * s).reshape(b, s, -1)
    np.testing.assert_allclose(np.asarray(out)[..., 2:], pooled[..., 2:],
                               rtol=1e-4, atol=1e-6)

    out_t = fused_seqpool_cvm_tradew(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(sc), b, s, tn,
        trade_id=1)
    v_w = np.concatenate(
        [vals[:, :2], vals[:, 2 + tn:] * vals[:, 3:4]], 1)
    pooled_w = np_pool(v_w, segs, b * s).reshape(b, s, -1)
    np.testing.assert_allclose(np.asarray(out_t)[..., 2:], pooled_w[..., 2:],
                               rtol=1e-4, atol=1e-6)

    # trade_id backward: cvm cols 0, chosen trade col gets Σ g·embed_in,
    # embeds scaled by the trade weight (kernel :295-345)
    g = jax.grad(lambda v: fused_seqpool_cvm_tradew(
        v, jnp.asarray(segs), jnp.asarray(sc), b, s, tn, trade_id=1
    ).sum())(jnp.asarray(vals))
    g = np.asarray(g)
    np.testing.assert_allclose(g[:, :2], 0.0)
    np.testing.assert_allclose(g[:, 2], 0.0)  # non-chosen trade col
    np.testing.assert_allclose(g[:, 3], vals[:, 2 + tn:].sum(1), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(g[:, 2 + tn:],
                               np.repeat(vals[:, 3:4], e, 1), rtol=1e-4, atol=1e-6)


def test_credit_heads():
    b, s, e = 3, 2, 4
    vals, segs, rng = make_inputs(k=30, b=b, s=s, e=e, extra=2, seed=2)
    cvm4 = np.abs(rng.normal(size=(b, 4))).astype(np.float32)
    pooled = np_pool(vals, segs, b * s).reshape(b, s, -1)

    out = fused_seqpool_cvm_with_credit(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(cvm4), b, s)
    np.testing.assert_allclose(np.asarray(out)[..., :4],
                               np.log1p(pooled[..., :4]), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out)[..., 4:], pooled[..., 4:],
                               rtol=1e-4, atol=1e-6)

    out_ns = fused_seqpool_cvm_with_credit(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(cvm4), b, s,
        show_filter=True)
    assert out_ns.shape[-1] == out.shape[-1] - 1
    np.testing.assert_allclose(np.asarray(out_ns)[..., :3],
                               np.log1p(pooled[..., 1:4]), rtol=1e-4, atol=1e-6)

    # backward: cvm cols carry batch cvm, embeds broadcast
    g = jax.grad(lambda v: fused_seqpool_cvm_with_credit(
        v, jnp.asarray(segs), jnp.asarray(cvm4), b, s).sum()
    )(jnp.asarray(vals))
    ins = np.minimum(segs // s, b - 1)
    np.testing.assert_allclose(np.asarray(g)[:, :4], cvm4[ins], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g)[:, 4:], 1.0)


def test_pcoc_head_and_backward():
    b, s, e, p = 2, 2, 3, 2
    used = 4 + p
    vals, segs, rng = make_inputs(k=24, b=b, s=s, e=e, extra=used - 2, seed=3)
    cvm = np.abs(rng.normal(size=(b, used))).astype(np.float32)
    q = np.abs(rng.normal(size=(b, p))).astype(np.float32)
    pooled = np_pool(vals, segs, b * s).reshape(b, s, -1)
    lg = np.log1p(pooled[..., :used])

    out = np.asarray(fused_seqpool_cvm_with_pcoc(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(cvm),
        jnp.asarray(q), b, s))
    assert out.shape[-1] == 2 + 2 * p + e
    np.testing.assert_allclose(out[..., 0], lg[..., 0], rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(out[..., 1], lg[..., 1] - lg[..., 0],
                               rtol=1e-4, atol=1e-6)
    for i in range(p):
        np.testing.assert_allclose(out[..., 2 + i],
                                   lg[..., 4 + i] - lg[..., 2], rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(out[..., 2 + p + i],
                                   lg[..., 4 + i] - lg[..., 3], rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(out[..., 2 + 2 * p:], pooled[..., used:],
                               rtol=1e-4, atol=1e-6)

    g = np.asarray(jax.grad(lambda v: fused_seqpool_cvm_with_pcoc(
        v, jnp.asarray(segs), jnp.asarray(cvm), jnp.asarray(q), b, s).sum()
    )(jnp.asarray(vals)))
    ins = np.minimum(segs // s, b - 1)
    np.testing.assert_allclose(g[:, :4], cvm[ins, :4], rtol=1e-6)
    np.testing.assert_allclose(g[:, 4:used], q[ins], rtol=1e-6)
    np.testing.assert_allclose(g[:, used:], 1.0)


def test_fused_seq_tensor_shapes_and_din():
    rng = np.random.default_rng(4)
    ins, bc, S, L, d = 3, 2, 5, 4, 2
    adS, adOff = 2, 1
    sideS, sideOff = 1, 3
    x = rng.normal(size=(ins, bc * S * L * d)).astype(np.float32)
    ad = rng.normal(size=(ins, bc * adS * d)).astype(np.float32)
    din, mask, side, sess = fused_seq_tensor(
        jnp.asarray(x), jnp.asarray(ad), bc, L, S, d, adS, adOff,
        sideS, sideOff)
    assert din.shape == (bc, ins, L, 4 * adS * d)
    assert mask.shape == (bc, ins, L)
    assert side.shape == (bc, ins, L, sideS * d)
    assert sess.shape == (bc, ins, L, adS * d)
    # check one din element: [in, ad, in-ad, in*ad] layout
    x5 = x.reshape(ins, bc, S, L, d)
    ad4 = ad.reshape(ins, bc, adS, d)
    i, b_, l, sl = 1, 0, 2, 1
    inv = x5[i, b_, adOff + sl, l]
    adv = ad4[i, b_, sl]
    got = np.asarray(din)[b_, i, l].reshape(4, adS, d)
    np.testing.assert_allclose(got[0, sl], inv, rtol=1e-6)
    np.testing.assert_allclose(got[1, sl], adv, rtol=1e-6)
    np.testing.assert_allclose(got[2, sl], inv - adv, rtol=1e-6)
    np.testing.assert_allclose(got[3, sl], inv * adv, rtol=1e-6)
    # mask: zero out one position entirely
    x5z = x5.copy()
    x5z[:, :, :, 3, :] = 0.0
    _, mask2, _, _ = fused_seq_tensor(
        jnp.asarray(x5z.reshape(ins, -1)), jnp.asarray(ad), bc, L, S, d,
        adS, adOff, sideS, sideOff)
    np.testing.assert_allclose(np.asarray(mask2)[:, :, 3], 0.0)


def test_replica_cache_and_input_table():
    from paddlebox_tpu.ps import InputTable, ReplicaCache
    rc = ReplicaCache(emb_dim=4)
    first = rc.add_items(np.ones((3, 4)))
    assert first == 0 and rc.size == 3
    rc.add_items(np.full((2, 4), 2.0))
    out = np.asarray(rc.pull(jnp.asarray([0, 3, 4])))
    np.testing.assert_allclose(out[0], 1.0)
    np.testing.assert_allclose(out[1], 2.0)

    it = InputTable(dim=3)
    it.add_input("adv_1", [1.0, 2.0, 3.0])
    it.add_input("adv_2", [4.0, 5.0, 6.0])
    got = np.asarray(it.lookup(["adv_2", "missing", "adv_1"]))
    np.testing.assert_allclose(got[0], [4, 5, 6])
    np.testing.assert_allclose(got[1], 0.0)
    np.testing.assert_allclose(got[2], [1, 2, 3])


def test_input_index_feed_loads_filelist(tmp_path):
    """InputIndexDataFeed (data_feed.h:2289, data_feed.cc:4637): index
    files of key→vector rows load into the InputTable through a
    reader-thread pool with a pluggable parser; bad lines skip."""
    from paddlebox_tpu.ps import InputTable
    f1 = tmp_path / "idx1.txt"
    f1.write_text("adv_1\t1 2 3\nadv_2\t4,5,6\nBADLINE\nadv_3\t7 8 9\n")
    f2 = tmp_path / "idx2.txt"
    f2.write_text("adv_4\t-1 -2 -3\n")
    it = InputTable(dim=3)
    n = it.load_index_filelist([str(f1), str(f2)], thread_num=2)
    assert n == 4 and len(it) == 4
    got = np.asarray(it.lookup(["adv_2", "adv_4"]))
    np.testing.assert_allclose(got[0], [4, 5, 6])
    np.testing.assert_allclose(got[1], [-1, -2, -3])

    # pluggable parser (the ParseIndexData hook)
    f3 = tmp_path / "idx3.txt"
    f3.write_text("k9|9;9;9\n")
    it2 = InputTable(dim=3)
    it2.load_index_filelist(
        [str(f3)],
        parse_index_line=lambda ln: (
            (p := ln.strip().split("|"))[0],
            [float(v) for v in p[1].split(";")]))
    np.testing.assert_allclose(np.asarray(it2.lookup(["k9"]))[0], 9.0)

    # a wrong-width vector skips the ROW; a missing FILE raises (no
    # silent partial loads)
    f4 = tmp_path / "idx4.txt"
    f4.write_text("short\t1 2\nok\t1 2 3\n")
    it3 = InputTable(dim=3)
    assert it3.load_index_filelist([str(f4)]) == 1
    import pytest as _pytest
    with _pytest.raises(FileNotFoundError):
        it3.load_index_filelist([str(tmp_path / "nope.txt"), str(f4)],
                                thread_num=1)

    # duplicate keys across files: LAST file in filelist order wins,
    # deterministically, regardless of reader-thread completion order
    fa = tmp_path / "dup_a.txt"
    fa.write_text("k\t1 1 1\n")
    fb = tmp_path / "dup_b.txt"
    fb.write_text("k\t2 2 2\n")
    it4 = InputTable(dim=3)
    assert it4.load_index_filelist([str(fa), str(fb)], thread_num=2) == 2
    assert len(it4) == 1
    np.testing.assert_allclose(np.asarray(it4.lookup(["k"]))[0], 2.0)


def test_extended_embedding_table():
    from paddlebox_tpu.data.batch import SlotBatch
    from paddlebox_tpu.ps import ExtendedEmbeddingTable, SparseSGDConfig
    t = ExtendedEmbeddingTable(mf_dim=4, extend_mf_dim=8, capacity=128,
                               cfg=SparseSGDConfig(mf_create_thresholds=0.0),
                               unique_bucket_min=64)
    keys = np.array([5, 9, 5, 33], np.uint64)
    batch = SlotBatch(
        keys=keys, num_keys=4, segments=np.arange(4, dtype=np.int32),
        dense=np.zeros((2, 1), np.float32), label=np.zeros(2, np.float32),
        show=np.ones(2, np.float32), clk=np.zeros(2, np.float32),
        batch_size=2, num_slots=2)
    idx = t.prepare(batch)
    v, ve = t.pull(idx)
    assert v.shape == (4, 3 + 4) and ve.shape == (4, 3 + 8)
    t.push(idx, jnp.ones((4, 7)) * 0.1, jnp.ones((4, 11)) * 0.1)
    v2, ve2 = t.pull(idx)
    assert not np.allclose(np.asarray(v), np.asarray(v2))
    assert not np.allclose(np.asarray(ve), np.asarray(ve2))
    assert t.feature_count == 3


def test_extended_table_skip_slots():
    from paddlebox_tpu.data.batch import SlotBatch
    from paddlebox_tpu.ps import ExtendedEmbeddingTable, SparseSGDConfig
    t = ExtendedEmbeddingTable(mf_dim=4, extend_mf_dim=4, capacity=128,
                               cfg=SparseSGDConfig(mf_create_thresholds=0.0),
                               unique_bucket_min=64, skip_extend_slots=[1])
    keys = np.array([5, 9, 7, 33], np.uint64)
    # segments: ins0 slots 0,1; ins1 slots 0,1 → keys 9 and 33 in slot 1
    batch = SlotBatch(
        keys=keys, num_keys=4,
        segments=np.array([0, 1, 2, 3], np.int32),
        dense=np.zeros((2, 1), np.float32), label=np.zeros(2, np.float32),
        show=np.ones(2, np.float32), clk=np.zeros(2, np.float32),
        batch_size=2, num_slots=2)
    idx_b, idx_e = t.prepare(batch)
    _, ve = t.pull((idx_b, idx_e))
    # slot-1 keys pull zero expand values
    np.testing.assert_allclose(np.asarray(ve)[[1, 3]], 0.0)
    assert idx_e.key_valid[1] == 0.0 and idx_e.key_valid[3] == 0.0
    # pushes for skipped keys train nothing in the expand space
    t.push((idx_b, idx_e), jnp.ones((4, 7)) * 0.1, jnp.ones((4, 7)) * 0.1)
    assert t.extend.feature_count == 2  # only slot-0 keys allocated
    assert t.base.feature_count == 4
