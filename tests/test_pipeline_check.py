"""Tier-1 wiring of scripts/pipeline_check.py — the deterministic
pass-pipeline gates: the async-epilogue gate (ISSUE 4: async==sync
host-tier digest over a 3-pass tiered job with overlapped staging, and
measured end_pass overlap > 0) and the depth-N preload prologue gate
(ISSUE 5: steady-state preload wait drops >=50% vs depth-1 on the
deterministic sleep-timed smoke, and a depth-N resident training run
reproduces the depth-1 logical-state digest exactly). The standalone
script runs bigger variants; these are the fast non-slow gates."""

import numpy as np

from scripts.pipeline_check import (host_tier_digest, run_check,
                                    run_prologue_check,
                                    run_tiered_prologue_check)


def test_pipeline_check_gate():
    out = run_check(passes=3, shards=4, keys_per_pass=256,
                    capacity_per_shard=512)
    assert out["ok"]
    assert out["rows"] > 0
    eps = out["async_endpass"]
    assert eps["jobs_run"] >= 3
    assert eps["overlap_sec"] > 0.0
    assert eps["pending"] == 0


def test_prologue_gate():
    out = run_prologue_check(passes=7, train_sec=0.08,
                             build_secs=(0.02, 0.14),
                             real_passes=3, real_records=128)
    assert out["ok"]
    assert out["wait_drop_frac"] >= 0.5
    assert out["digest"]


def test_tiered_prologue_gate():
    """ISSUE 9: the depth-2 tiered pass pipeline (queued stages on the
    preloader worker + async capacity eviction) reproduces the
    sequential oracle's host-tier digest bit-for-bit across 2 seeded
    runs, and the steady-state begin_delta boundary stall drops ≥50%
    vs the no-overlap control."""
    out = run_tiered_prologue_check(passes=4, keys_per_pass=256,
                                    capacity_per_shard=512,
                                    build_delay=0.04, train_sec=0.08)
    assert out["ok"]
    assert out["stall_drop_frac"] >= 0.5
    assert out["runs"] >= 4          # ≥2 seeded pipeline runs agreed
    assert out["digest"]


def test_host_tier_digest_is_order_insensitive():
    """The digest must hash logical content, not insertion order —
    async and sync runs may land rows in different row ids."""
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable

    def mk(order):
        t = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=64,
            cfg=SparseSGDConfig(mf_create_thresholds=0.0,
                                mf_initial_range=0.0))
        for ks in order:
            f = {"show": np.ones(len(ks), np.float32),
                 "clk": np.zeros(len(ks), np.float32),
                 "delta_score": np.zeros(len(ks), np.float32),
                 "slot": np.zeros(len(ks), np.float32),
                 "embed_w": ks.astype(np.float32),
                 "embed_g2sum": np.zeros(len(ks), np.float32),
                 "embedx_w": np.zeros((len(ks), 2), np.float32),
                 "embedx_g2sum": np.zeros(len(ks), np.float32),
                 "mf_size": np.zeros(len(ks), np.float32)}
            for s in range(2):
                sel = ks[ks % np.uint64(2) == s]
                t.hosts[s].update(sel, {k: (v[ks % np.uint64(2) == s]
                                            if v.ndim else v)
                                        for k, v in f.items()})
        return t

    a = np.arange(1, 9, dtype=np.uint64)
    b = np.arange(9, 17, dtype=np.uint64)
    d1 = host_tier_digest(mk([a, b]))
    d2 = host_tier_digest(mk([b, a]))
    assert d1 == d2
