"""True multi-process JAX runtime over the launcher: two processes
rendezvous at the coordinator (``jax.distributed.initialize`` via
``init_runtime_env``), form ONE global mesh spanning both, and run
cross-process collectives — the DCN comm-backend story (SURVEY §2.6:
NCCL/MPI/Gloo collapse into XLA collectives on one mesh; rendezvous via
the JAX coordinator)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()          # jax.distributed.initialize inside
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    n = jax.device_count()             # GLOBAL devices across processes
    nl = jax.local_device_count()
    assert n == info["world_size"] * nl, (n, nl, info)

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def f(x):
        return jax.lax.psum(x, "dp")

    y = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("dp"), out_specs=P()))(
        jnp.arange(n, dtype=jnp.float32))
    got = float(np.ravel(np.asarray(
        y.addressable_shards[0].data))[0])
    assert got == n * (n - 1) / 2, got   # psum crossed the process gap
    print(f"rank={info['rank']} ok global={n} psum={got}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_two_process_global_mesh_psum(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "w.py"
    worker.write_text(WORKER)
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for r in range(2):
        env = dict(os.environ, PBOX_RANK=str(r), PBOX_WORLD_SIZE="2",
                   PBOX_COORDINATOR=coord, PBOX_JAX_DISTRIBUTED="1",
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        # two local devices per process -> 4 global
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=300)[0] for p in procs]
    if any(p.returncode != 0 for p in procs):
        raise AssertionError("\n\n".join(
            f"--- rank {r} rc={p.returncode} ---\n{o[-1500:]}"
            for r, (p, o) in enumerate(zip(procs, outs))))
    for r, o in enumerate(outs):
        assert f"rank={r} ok global=4" in o, o
