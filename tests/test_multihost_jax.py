"""True multi-process JAX runtime over the launcher: two processes
rendezvous at the coordinator (``jax.distributed.initialize`` via
``init_runtime_env``), form ONE global mesh spanning both, and run
cross-process collectives — the DCN comm-backend story (SURVEY §2.6:
NCCL/MPI/Gloo collapse into XLA collectives on one mesh; rendezvous via
the JAX coordinator)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()          # jax.distributed.initialize inside
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    n = jax.device_count()             # GLOBAL devices across processes
    nl = jax.local_device_count()
    assert n == info["world_size"] * nl, (n, nl, info)

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def f(x):
        return jax.lax.psum(x, "dp")

    y = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("dp"), out_specs=P()))(
        jnp.arange(n, dtype=jnp.float32))
    got = float(np.ravel(np.asarray(
        y.addressable_shards[0].data))[0])
    assert got == n * (n - 1) / 2, got   # psum crossed the process gap
    print(f"rank={info['rank']} ok global={n} psum={got}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p

def _run_two_workers(tmp_path, script: str, name: str, extra_env=None,
                     local_devices: int = 2, argv=None):
    """Launch the script as a 2-process PBOX gang (coordinator env,
    per-process virtual CPU devices); kill stragglers on timeout and
    report every rank's output on failure."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / name
    worker.write_text(script)
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for r in range(2):
        env = dict(os.environ, PBOX_RANK=str(r), PBOX_WORLD_SIZE="2",
                   PBOX_COORDINATOR=coord, PBOX_JAX_DISTRIBUTED="1",
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count="
                   f"{local_devices}",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)] + list(argv or []), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    timed_out = False
    try:
        for p in procs:
            outs.append(p.communicate(timeout=300)[0])
    except subprocess.TimeoutExpired:
        timed_out = True
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if timed_out:
            # drain the pipes AFTER the kill so the hanging rank's last
            # output makes it into the failure report
            while len(outs) < len(procs):
                outs.append(procs[len(outs)].communicate()[0])
    if timed_out or any(p.returncode != 0 for p in procs):
        raise AssertionError(
            ("TIMED OUT\n" if timed_out else "") + "\n\n".join(
                f"--- rank {r} rc={p.returncode} ---\n{o[-2000:]}"
                for r, (p, o) in enumerate(zip(procs, outs))))
    return outs



TRAIN_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()
    import numpy as np
    import optax
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.multihost import (global_mesh,
                                               globalize_state,
                                               stage_global_batch)
    from paddlebox_tpu.train.sharded import (ShardedTrainer,
                                             make_global_arrays)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from mh_common import build_case

    n = jax.device_count()
    assert n == 4, n
    mesh = global_mesh()
    desc, batches = build_case(n)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    table = ShardedEmbeddingTable(n, mf_dim=4, capacity_per_shard=512,
                                  cfg=cfg, req_bucket_min=16,
                                  serve_bucket_min=16)
    tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                        tx=optax.adam(1e-3))
    host = make_global_arrays(batches, table.prepare_global(batches))
    gb = stage_global_batch(mesh, host)
    state = globalize_state(mesh, tr.state, tr.step_fn.state_spec)
    losses = []
    for i in range(2):
        state, stats = tr.step_fn(state, gb, jax.random.PRNGKey(i))
        l = stats["loss"]
        l = (np.asarray(jax.device_get(l.addressable_shards[0].data))
             if hasattr(l, "addressable_shards") else np.asarray(l))
        losses.append(float(np.ravel(l)[0]))
    want = [float(x) for x in os.environ["ORACLE_LOSSES"].split(",")]
    for got, w in zip(losses, want):
        assert abs(got - w) < 1e-6, (losses, want)
    print(f"rank={info['rank']} train ok losses={losses}", flush=True)
""")

MH_COMMON = textwrap.dedent("""
    import numpy as np
    from paddlebox_tpu.data import DataFeedDesc, SlotDef
    from paddlebox_tpu.data.batch import BatchBuilder
    from paddlebox_tpu.data.record import SlotRecord

    def build_case(n, B=4, S=6):
        slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 3)]
        slots += [SlotDef(f"C{i}", "uint64") for i in range(S)]
        desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                            key_bucket_min=32)
        rng = np.random.default_rng(0)
        builder = BatchBuilder(desc)
        offsets = np.arange(S + 1, dtype=np.int32)
        batches = []
        for d in range(n):
            recs = [SlotRecord(
                keys=rng.integers(0, 300, size=S).astype(np.uint64),
                slot_offsets=offsets,
                dense=rng.normal(size=3).astype(np.float32),
                label=float(rng.integers(0, 2)), show=1.0, clk=0.0)
                for _ in range(B)]
            batches.append(builder.build(recs))
        return desc, batches
""")


@pytest.mark.slow
def test_two_process_sharded_train_matches_single_process(tmp_path):
    """THE pod execution proof: the full sharded CTR train step
    (embedding all_to_all pull/push, in-table optimizer, dense psum,
    AUC) over a GLOBAL mesh spanning 2 processes reproduces the
    single-process 4-device run of the same batch (losses within 1e-6
    of the oracle, identical on both ranks)."""
    import jax
    import numpy as np
    import optax

    # oracle: single-process, 4 of this process's virtual devices
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import (ShardedTrainer,
                                             make_global_batch)
    import importlib.util
    common = tmp_path / "mh_common.py"
    common.write_text(MH_COMMON)
    spec = importlib.util.spec_from_file_location("mh_common", str(common))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    n = 4
    desc, batches = mod.build_case(n)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    table = ShardedEmbeddingTable(n, mf_dim=4, capacity_per_shard=512,
                                  cfg=cfg, req_bucket_min=16,
                                  serve_bucket_min=16)
    tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc,
                        make_mesh(n), tx=optax.adam(1e-3))
    gb = make_global_batch(batches, table.prepare_global(batches))
    state = tr.state
    oracle = []
    for i in range(2):
        state, stats = tr.step_fn(state, gb, jax.random.PRNGKey(i))
        oracle.append(float(stats["loss"]))

    outs = _run_two_workers(
        tmp_path, TRAIN_WORKER, "w_train.py",
        extra_env={"ORACLE_LOSSES": ",".join(f"{x:.9f}" for x in oracle)})
    for r, o in enumerate(outs):
        assert f"rank={r} train ok" in o, o


SHARD_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()
    rank, world = info["rank"], info["world_size"]
    import numpy as np
    import optax
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.distributed.shuffle import TcpShuffler
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.multihost import (global_mesh,
                                               globalize_state,
                                               stage_global_batch)
    from paddlebox_tpu.train.sharded import (ShardedTrainer,
                                             group_batches,
                                             make_global_arrays)

    # THIS host's own data shard (different per rank)
    FLAGS.native_parse = False      # record objects for the exchange
    desc = DataFeedDesc.criteo(batch_size=16)
    desc.key_bucket_min = 512
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    files = generate_criteo_files(os.path.join(sys.argv[1], f"r{rank}"),
                                  num_files=1, rows_per_file=200,
                                  vocab_per_slot=40, seed=50 + rank)
    ds.set_filelist(files)
    ds.load_into_memory()
    n_local = len(ds.records)

    # host data plane: allgather the shards so every process holds the
    # identical global record stream
    sh = TcpShuffler(rank, world,
                     os.environ["SHUFFLE_ENDPOINTS"].split(","))
    ds.records = sh.allgather(ds.records)
    sh.close()
    ds.columnarize()
    n_global = len(ds)

    n = jax.device_count()
    mesh = global_mesh()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = ShardedEmbeddingTable(n, mf_dim=4, capacity_per_shard=2048,
                                  cfg=cfg, req_bucket_min=64,
                                  serve_bucket_min=64)
    tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                        tx=optax.adam(2e-3))
    state = globalize_state(mesh, tr.state, tr.step_fn.state_spec)
    nb = 0
    for group in group_batches(ds.batches(), n):
        host = make_global_arrays(group, table.prepare_global(group))
        gb = stage_global_batch(mesh, host)
        state, stats = tr.step_fn(state, gb, jax.random.PRNGKey(nb))
        nb += 1
    l = stats["loss"]
    l = (np.asarray(jax.device_get(l.addressable_shards[0].data))
         if hasattr(l, "addressable_shards") else np.asarray(l))
    loss = float(np.ravel(l)[0])
    print(f"rank={rank} shardtrain ok local={n_local} global={n_global} "
          f"batches={nb} loss={loss:.7f}", flush=True)
""")


@pytest.mark.slow
def test_two_process_per_host_shards_train(tmp_path):
    """The full pod data story: each process reads ONLY its own file
    shard, allgathers records over the TCP host plane (identical global
    stream on every process — the SPMD host contract), then trains the
    sharded step over the global mesh. Both ranks must report the same
    loss over all records of both shards."""
    import re
    ports = [_free_port(), _free_port()]
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports)
    outs = _run_two_workers(
        tmp_path, SHARD_WORKER, "w_shard.py",
        extra_env={"SHUFFLE_ENDPOINTS": endpoints},
        argv=[str(tmp_path)])
    lines = []
    for r, o in enumerate(outs):
        m = re.search(rf"rank={r} shardtrain ok local=(\d+) global=(\d+) "
                      rf"batches=(\d+) loss=([0-9.]+)", o)
        assert m, o
        lines.append(m.groups())
    # every record landed on every process; losses identical across ranks
    assert int(lines[0][1]) == int(lines[1][1]) == \
        int(lines[0][0]) + int(lines[1][0]) == 400
    assert lines[0][3] == lines[1][3], lines


@pytest.mark.slow
def test_two_process_global_mesh_psum(tmp_path):
    outs = _run_two_workers(tmp_path, WORKER, "w.py")
    for r, o in enumerate(outs):
        assert f"rank={r} ok global=4" in o, o
