"""Tiered sharded PS (ps/tiered.py): HostStore-backed pass windows per
HBM shard on the 8-device CPU mesh — capacity beyond HBM composed with
the mesh trainer (BuildPull/BuildGPUTask/EndPass, ps_gpu_wrapper.cc:337,
684,983; LoadSSD2Mem, box_wrapper.cc:1415)."""

import time

import numpy as np
import jax
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import (BoxPSHelper, SparseSGDConfig,
                              TieredShardedEmbeddingTable)
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.train.sharded import ShardedTrainer

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N
    return make_mesh(N)


def _cfg(**kw):
    kw.setdefault("mf_create_thresholds", 0.0)
    kw.setdefault("mf_initial_range", 0.0)
    kw.setdefault("learning_rate", 0.1)
    kw.setdefault("mf_learning_rate", 0.1)
    return SparseSGDConfig(**kw)


def _make_ds(tmp_path, seed, vocab=40, rows=1200, name="p"):
    files = generate_criteo_files(str(tmp_path / f"{name}{seed}"),
                                  num_files=2, rows_per_file=rows,
                                  vocab_per_slot=vocab, seed=seed)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def _write_offset_pass(tmp_path, pass_id, vocab=60, rows=800):
    """Criteo-format files whose categorical values live in a PER-PASS
    disjoint range [pass_id*vocab, (pass_id+1)*vocab) — models day-k data
    with fresh features, so pass windows are disjoint key sets."""
    import os
    rng = np.random.default_rng(100 + pass_id)
    d = tmp_path / f"off{pass_id}"
    os.makedirs(str(d), exist_ok=True)
    path = str(d / "part.txt")
    base = pass_id * vocab
    with open(path, "w") as fh:
        for _ in range(rows):
            dense = rng.integers(0, 100, size=13)
            cats = base + rng.integers(0, vocab, size=26)
            label = int(rng.random() < 0.5)
            dense_s = "\t".join(str(int(v)) for v in dense)
            cat_s = "\t".join(format(int(c), "x") for c in cats)
            fh.write(f"{label}\t{dense_s}\t{cat_s}\n")
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist([path])
    ds.load_into_memory()
    return ds, desc


def test_tiered_window_smaller_than_model(mesh, tmp_path):
    """Train 3 passes over DIFFERENT datasets with capacity_per_shard far
    below the total feature count: each pass window fits, the union does
    not — the host tier must carry the full model across windows."""
    built = [_write_offset_pass(tmp_path, p) for p in range(3)]
    datasets = [b[0] for b in built]
    desc = built[0][1]
    # each pass touches ≤ 26*60 = 1560 uniques (≈195/shard);
    # capacity_per_shard=256 cannot hold the 3-pass union (disjoint
    # per-pass value ranges)
    table = TieredShardedEmbeddingTable(
        N, mf_dim=4, capacity_per_shard=256, cfg=_cfg(),
        req_bucket_min=256, serve_bucket_min=256)
    with flags_scope(log_period_steps=10000):
        tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                            tx=optax.adam(2e-3))
    helper = BoxPSHelper(table, trainer=tr)
    for ds in datasets:
        helper.begin_pass(ds)
        tr.train_pass(ds)
        helper.end_pass(ds)
    total = table.feature_count()
    assert total > N * table.capacity, (
        f"host tier must exceed HBM window: {total} <= {N * table.capacity}")
    # a pass window only ever held its own working set
    for s in range(N):
        assert len(table.indexes[s]) <= table.capacity


def test_tiered_matches_untired_sharded(mesh, tmp_path):
    """Tiering must be TRANSPARENT: when everything happens to fit, a
    tiered table trained over 2 pass windows equals a plain
    ShardedEmbeddingTable trained straight through — same AUC, same dense
    params, same per-key embeddings."""
    ds, desc = _make_ds(tmp_path, 13)

    with flags_scope(log_period_steps=10000):
        plain = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=4096,
                                      cfg=_cfg(), req_bucket_min=256,
                                      serve_bucket_min=256)
        tr_a = ShardedTrainer(DeepFM(hidden=(32, 32)), plain, desc, mesh,
                              tx=optax.adam(2e-3))
        tiered = TieredShardedEmbeddingTable(
            N, mf_dim=4, capacity_per_shard=4096, cfg=_cfg(),
            req_bucket_min=256, serve_bucket_min=256)
        tr_b = ShardedTrainer(DeepFM(hidden=(32, 32)), tiered, desc, mesh,
                              tx=optax.adam(2e-3))
    helper = BoxPSHelper(tiered, trainer=tr_b)
    ra = rb = None
    for _ in range(2):
        ra = tr_a.train_pass(ds)
        helper.begin_pass(ds)
        rb = tr_b.train_pass(ds)
        helper.end_pass(ds)
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=1e-6), (rb["auc"], ra["auc"])
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-7)
    # per-key embed_w parity: read via host tier vs plain device rows
    for s in range(N):
        keys, rows = plain.indexes[s].items()
        w_plain = np.asarray(plain.state.embed_w)[s][rows]
        got = tiered.hosts[s].fetch(keys)["embed_w"]
        np.testing.assert_allclose(got, w_plain, rtol=1e-5, atol=1e-7)


def test_tiered_resident_matches_streaming(mesh, tmp_path):
    """Resident mesh passes inside tiered windows == streaming passes."""
    ds, desc = _make_ds(tmp_path, 17)

    def mk():
        t = TieredShardedEmbeddingTable(
            N, mf_dim=4, capacity_per_shard=4096, cfg=_cfg(),
            req_bucket_min=256, serve_bucket_min=256)
        with flags_scope(log_period_steps=10000):
            tr = ShardedTrainer(DeepFM(hidden=(32, 32)), t, desc, mesh,
                                tx=optax.adam(2e-3))
        return t, tr, BoxPSHelper(t, trainer=tr)

    ta, tr_a, ha = mk()
    tb, tr_b, hb = mk()
    ra = rb = None
    for _ in range(2):
        ha.begin_pass(ds)
        ra = tr_a.train_pass(ds)
        ha.end_pass(ds)
        hb.begin_pass(ds)
        rb = tr_b.train_pass_resident(ds)
        hb.end_pass(ds)
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3), (rb["auc"], ra["auc"])
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-2, atol=2e-3)


def test_tiered_save_load_roundtrips_through_tiers(mesh, tmp_path):
    """save_base after a spill to the disk tier still exports the
    COMPLETE model; a fresh tiered table restores it and continues."""
    ds, desc = _make_ds(tmp_path, 23, vocab=30, rows=600)
    table = TieredShardedEmbeddingTable(
        N, mf_dim=4, capacity_per_shard=1024, cfg=_cfg(),
        req_bucket_min=256, serve_bucket_min=256)
    with flags_scope(log_period_steps=10000):
        tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                            tx=optax.adam(2e-3))
    helper = BoxPSHelper(table, trainer=tr)
    helper.begin_pass(ds)
    tr.train_pass(ds)
    helper.end_pass(ds)
    n_feat = table.feature_count()

    delta = str(tmp_path / "delta.npz")
    nd = table.save_delta(delta)
    assert nd == n_feat  # everything written back this window

    # spill EVERYTHING cold (threshold high), then save_base: the export
    # must still carry the full model (spilled rows merge in)
    spilled = table.spill_cold(str(tmp_path / "spill"), threshold=1e9)
    assert spilled > 0
    base = str(tmp_path / "base.npz")
    assert table.save_base(base) == n_feat

    t2 = TieredShardedEmbeddingTable(
        N, mf_dim=4, capacity_per_shard=1024, cfg=_cfg(),
        req_bucket_min=256, serve_bucket_min=256)
    assert t2.load(base) == n_feat
    for s in range(N):
        keys, _ = table.hosts[s].index.items()
        if len(keys) == 0:
            continue
        a = table.hosts[s].fetch(keys)
        b = t2.hosts[s].fetch(keys)
        np.testing.assert_allclose(b["embed_w"], a["embed_w"],
                                   rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(b["show"], a["show"], rtol=1e-6)
    # restored table trains another window
    with flags_scope(log_period_steps=10000):
        tr2 = ShardedTrainer(DeepFM(hidden=(16, 16)), t2, desc, mesh,
                             tx=optax.adam(2e-3))
    h2 = BoxPSHelper(t2, trainer=tr2)
    h2.begin_pass(ds)
    r = tr2.train_pass(ds)
    h2.end_pass(ds)
    assert np.isfinite(r["last_loss"])


def test_tiered_spilled_rows_promote_on_stage(mesh, tmp_path):
    """A key whose row lives only in a disk-tier spill file must come
    back with its trained value when a later pass stages it
    (LoadSSD2Mem, box_wrapper.cc:1415)."""
    ds, desc = _make_ds(tmp_path, 29, vocab=20, rows=400)
    table = TieredShardedEmbeddingTable(
        N, mf_dim=4, capacity_per_shard=1024, cfg=_cfg(),
        req_bucket_min=256, serve_bucket_min=256)
    with flags_scope(log_period_steps=10000):
        tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                            tx=optax.adam(2e-3))
    helper = BoxPSHelper(table, trainer=tr)
    helper.begin_pass(ds)
    tr.train_pass(ds)
    helper.end_pass(ds)
    # snapshot one trained key's value, spill everything, re-stage
    s0 = next(s for s in range(N) if len(table.hosts[s]) > 0)
    keys0, _ = table.hosts[s0].index.items()
    probe = keys0[:5]
    before = table.hosts[s0].fetch(probe)["embed_w"].copy()
    assert np.any(before != 0)
    table.save_base(str(tmp_path / "b.npz"))  # spill requires saved rows
    assert table.spill_cold(str(tmp_path / "sp"), threshold=1e9) > 0
    assert len(table.hosts[s0]) == 0  # gone from RAM
    # drop HBM residency so the next stage MUST go through the disk
    # tier (with the persistent window the keys would otherwise still
    # serve from HBM and never exercise promotion)
    table.drop_window()
    helper.begin_pass(ds)  # stage promotes from the disk tier
    rows = table.indexes[s0].lookup(probe)
    assert (rows >= 0).all()
    w = np.asarray(jax.device_get(table.state.embed_w))[s0][rows]
    np.testing.assert_allclose(w, before, rtol=1e-6)
    helper.end_pass(ds)


def test_tiered_lifecycle_shrink_and_merge(mesh, tmp_path):
    """shrink ages the host tier; merge_model folds a single-table-format
    save (split by key%N) with stat accumulation."""
    table = TieredShardedEmbeddingTable(
        N, mf_dim=2, capacity_per_shard=64, cfg=_cfg())
    # seed host rows directly through a pass-less write-back
    keys = np.arange(1, 41, dtype=np.uint64)
    per = table._split_by_owner(keys)
    for s in range(N):
        ks = per[s]
        f = {"show": np.full(len(ks), 4.0, np.float32),
             "clk": np.full(len(ks), 2.0, np.float32),
             "delta_score": np.zeros(len(ks), np.float32),
             "slot": np.zeros(len(ks), np.float32),
             "embed_w": ks.astype(np.float32),
             "embed_g2sum": np.zeros(len(ks), np.float32),
             "embedx_w": np.zeros((len(ks), 2), np.float32),
             "embedx_g2sum": np.zeros(len(ks), np.float32),
             "mf_size": np.zeros(len(ks), np.float32)}
        table.hosts[s].update(ks, f)
    assert table.feature_count() == 40

    # merge a single-table-format file: 20 overlapping keys (stats
    # accumulate, embed_w keeps live), 10 new (insert wholesale)
    mkeys = np.arange(21, 51, dtype=np.uint64)
    np.savez(str(tmp_path / "m.npz"), keys=mkeys,
             show=np.full(30, 10.0, np.float32),
             clk=np.full(30, 5.0, np.float32),
             delta_score=np.zeros(30, np.float32),
             slot=np.zeros(30, np.float32),
             embed_w=np.full(30, -7.0, np.float32),
             embed_g2sum=np.zeros(30, np.float32),
             embedx_w=np.zeros((30, 2), np.float32),
             embedx_g2sum=np.zeros(30, np.float32),
             mf_size=np.zeros(30, np.float32))
    assert table.merge_model(str(tmp_path / "m.npz")) == 30
    assert table.feature_count() == 50
    s21 = int(21) % N
    got = table.hosts[s21].fetch(np.array([21], np.uint64))
    assert got["show"][0] == 14.0          # 4 + 10 accumulated
    assert got["embed_w"][0] == 21.0       # live weight kept
    s50 = int(50) % N
    got = table.hosts[s50].fetch(np.array([50], np.uint64))
    assert got["embed_w"][0] == -7.0       # new key inserted wholesale

    # shrink: decay 0.5 → score of old-only keys (show 4→2) drops below
    # threshold while merged keys survive
    freed = table.shrink(delete_threshold=3.0, decay=0.5)
    assert freed > 0
    assert table.feature_count() < 50
    assert table.hosts[s21].index.lookup(
        np.array([21], np.uint64))[0] >= 0  # hot key survives


def test_tiered_adam_opt_ext_roundtrips(mesh):
    """SparseAdam per-row state (opt_ext block) survives the pass window:
    begin_pass → device mutation → end_pass → host store → next window
    (the reviewer-found embedx/opt_ext slicing hazard)."""
    from paddlebox_tpu.ps.sgd import SparseAdamConfig
    from paddlebox_tpu.ps.table import NUM_FIXED
    cfg = SparseAdamConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = TieredShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=32,
                                        cfg=cfg)
    assert table.opt_ext > 0
    keys = np.arange(1, 25, dtype=np.uint64)
    table.begin_pass(keys)
    # simulate a jit update: plant distinct embedx and opt_ext values,
    # and mark the rows touched as the trainer's prepare/mark_trained
    # paths do (end_pass writes back only touched rows)
    mf_end = NUM_FIXED + table.mf_dim
    data = np.asarray(jax.device_get(table.state.data)).copy()
    for s in range(N):
        _, rows = table.indexes[s].items()
        data[s][rows, NUM_FIXED:mf_end] = 2.0
        data[s][rows, mf_end:] = 0.5
        table._touched[s][rows] = True
    table.state = type(table.state).from_logical(data, table.capacity,
                                                 ext=table.opt_ext)
    table.end_pass()
    # embedx stayed mf_dim-wide and opt_ext persisted separately
    for s in range(N):
        ks, _ = table.hosts[s].index.items()
        if not len(ks):
            continue
        got = table.hosts[s].fetch(ks)
        assert got["embedx_w"].shape[1] == 2
        np.testing.assert_allclose(got["embedx_w"], 2.0)
        np.testing.assert_allclose(got["opt_ext"], 0.5)
    # next window sees both back
    table.begin_pass(keys)
    d2 = np.asarray(jax.device_get(table.state.data))
    for s in range(N):
        _, rows = table.indexes[s].items()
        np.testing.assert_allclose(d2[s][rows, NUM_FIXED:mf_end], 2.0)
        np.testing.assert_allclose(d2[s][rows, mf_end:], 0.5)
    table.end_pass()


def _write_overlap_pass(tmp_path, pass_id, vocab=100, step=10, rows=600):
    """Criteo-format files whose categorical values live in a SLIDING
    range [pass_id*step, pass_id*step + vocab) — consecutive passes
    share ~(vocab-step)/vocab of their key range (the CTR workload:
    day k+1 mostly re-touches day k's features)."""
    import os
    rng = np.random.default_rng(500 + pass_id)
    d = tmp_path / f"ovl{pass_id}"
    os.makedirs(str(d), exist_ok=True)
    path = str(d / "part.txt")
    base = pass_id * step
    with open(path, "w") as fh:
        for _ in range(rows):
            dense = rng.integers(0, 100, size=13)
            cats = base + rng.integers(0, vocab, size=26)
            label = int(rng.random() < 0.5)
            dense_s = "\t".join(str(int(v)) for v in dense)
            cat_s = "\t".join(format(int(c), "x") for c in cats)
            fh.write(f"{label}\t{dense_s}\t{cat_s}\n")
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist([path])
    ds.load_into_memory()
    return ds, desc


def test_delta_staging_equals_full_staging(mesh, tmp_path):
    """THE delta-staging contract (box_wrapper.cc:129-186): with ~90%
    overlapping pass working sets, a table reusing its resident window
    (delta staging, the default) must match a table that re-stages the
    full working set every pass (drop_window between passes) — same AUC,
    same dense params, bit-identical host-tier values. And the staged
    row count per pass must equal the working-set DELTA, not its size."""
    built = [_write_overlap_pass(tmp_path, p) for p in range(4)]
    datasets = [b[0] for b in built]
    desc = built[0][1]

    def mk():
        t = TieredShardedEmbeddingTable(
            N, mf_dim=4, capacity_per_shard=2048, cfg=_cfg(),
            req_bucket_min=256, serve_bucket_min=256)
        with flags_scope(log_period_steps=10000):
            tr = ShardedTrainer(DeepFM(hidden=(16, 16)), t, desc, mesh,
                                tx=optax.adam(2e-3))
        return t, tr, BoxPSHelper(t, trainer=tr)

    ta, tr_a, ha = mk()   # delta (default)
    tb, tr_b, hb = mk()   # forced full re-staging
    resident: set = set()
    for p, ds in enumerate(datasets):
        want = set(ds.pass_keys().tolist())
        ha.begin_pass(ds)
        st = ta.last_pass_stats
        # staged == |want \ resident|: wire ∝ working-set delta
        assert st["staged"] == len(want - resident), (p, st)
        assert st["resident"] == len(want & resident), (p, st)
        assert st["evicted"] == 0
        resident |= want
        ra = tr_a.train_pass(ds)
        ha.end_pass(ds)

        tb.drop_window()  # forces full staging: everything re-fetched
        hb.begin_pass(ds)
        assert tb.last_pass_stats["staged"] == len(want)
        rb = tr_b.train_pass(ds)
        hb.end_pass(ds)
        assert np.isclose(ra["auc"], rb["auc"], atol=1e-9)
    # pass 2+ staged a small fraction of the working set
    assert st["staged"] < 0.25 * (st["staged"] + st["resident"])
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for s in range(N):
        keys, _ = ta.hosts[s].index.items()
        keys = np.sort(keys)
        kb, _ = tb.hosts[s].index.items()
        np.testing.assert_array_equal(keys, np.sort(kb))
        a = ta.hosts[s].fetch(keys)
        b = tb.hosts[s].fetch(keys)
        for f in ta.hosts[s].fields:
            np.testing.assert_array_equal(a[f], b[f], err_msg=f"s{s} {f}")


def test_async_epilogue_parity_bit_identical(mesh, tmp_path):
    """ISSUE 4 parity suite: overlapped end_pass/begin_pass (async
    epilogue ON, the default) over 4 passes with ~90% key overlap must
    be BIT-IDENTICAL to the synchronous path — same dense params, same
    host-tier values, same staged-delta accounting — and the async run
    must actually run background write-back jobs."""
    built = [_write_overlap_pass(tmp_path, p, vocab=100, step=10)
             for p in range(4)]
    datasets = [b[0] for b in built]
    desc = built[0][1]

    def run(async_mode):
        with flags_scope(async_end_pass=async_mode):
            t = TieredShardedEmbeddingTable(
                N, mf_dim=4, capacity_per_shard=2048, cfg=_cfg(),
                req_bucket_min=256, serve_bucket_min=256)
            with flags_scope(log_period_steps=10000):
                tr = ShardedTrainer(DeepFM(hidden=(16, 16)), t, desc,
                                    mesh, tx=optax.adam(2e-3))
            h = BoxPSHelper(t, trainer=tr)
            staged = []
            for i, ds in enumerate(datasets):
                h.begin_pass(ds)
                staged.append(t.last_pass_stats["staged"])
                if i + 1 < len(datasets):
                    h.stage_pass(datasets[i + 1])  # overlapped fetch
                tr.train_pass(ds)
                h.end_pass(ds)  # async: returns before write-back lands
            t.fence()
            return t, tr, staged

    ta, tr_a, staged_a = run(False)   # synchronous oracle
    tb, tr_b, staged_b = run(True)    # async epilogue (default)
    assert staged_b == staged_a, (staged_b, staged_a)
    assert tb.endpass_stats()["jobs_run"] >= len(datasets)
    assert ta.endpass_stats()["jobs_run"] == 0  # sync ran inline
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for s in range(N):
        ka, fa = ta.hosts[s].export_rows()
        kb, fb = tb.hosts[s].export_rows()
        oa, ob = np.argsort(ka), np.argsort(kb)
        np.testing.assert_array_equal(ka[oa], kb[ob])
        assert np.abs(fa["embed_w"]).sum() > 0  # actually trained
        for f in ta.hosts[s].fields:
            np.testing.assert_array_equal(fa[f][oa], fb[f][ob],
                                          err_msg=f"s{s} {f}")


def test_async_writeback_failure_surfaces_at_fence(mesh):
    """A mid-write-back failure (endpass.writeback seam) must surface
    LOUDLY at the fence — through an explicit fence(), AND through the
    implicit read barrier on any host-tier access — never as silent
    row loss; once surfaced, the error is consumed."""
    from paddlebox_tpu.ps.epilogue import EndPassWritebackError
    from paddlebox_tpu.resilience.faults import FaultPlan, installed

    def check(surface):
        """One failing end_pass; ``surface(table)`` must raise the held
        error. The plan stays installed until the background job ran
        (the surface call fences)."""
        table = TieredShardedEmbeddingTable(
            N, mf_dim=2, capacity_per_shard=64, cfg=_cfg())
        keys = np.arange(1, 33, dtype=np.uint64)
        table.begin_pass(keys)
        from paddlebox_tpu.ps.table import FIELD_COL
        data = np.asarray(jax.device_get(table.state.data)).copy()
        with table.host_lock:
            for s in range(N):
                _, rows = table.indexes[s].items()
                data[s][rows, FIELD_COL["embed_w"]] = 3.0
                table._touched[s][rows] = True
        data[:, table.capacity, :] = 0.0
        table.state = type(table.state).from_logical(
            data, table.capacity, ext=table.opt_ext)
        with installed(FaultPlan.parse(
                "endpass.writeback:fail:nth=1,exc=crash")):
            table.end_pass()       # submit succeeds; the JOB fails
            with pytest.raises(EndPassWritebackError):
                surface(table)
        return table

    t1 = check(lambda t: t.fence())          # explicit fence
    t1.fence()                               # surfaced once — consumed
    check(lambda t: t.feature_count())       # implicit read barrier
    check(lambda t: t.save_delta("/tmp/never_epilogue.npz"))  # capture


def test_overlap_stage_reconciles_mid_pass_assign(mesh):
    """The overlap race, resolved by the begin_pass reconcile: key K is
    staged for pass 2 while pass 1 is open (host value fetched), then
    pass 1's streaming training assigns K mid-pass (outside its staged
    set) and trains it. The stale fetched value must be DROPPED — the
    resident row (written back at end_pass 1) wins."""
    from paddlebox_tpu.ps.table import FIELD_COL, FIELDS
    table = TieredShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=64,
                                        cfg=_cfg())
    K = np.uint64(200)
    s = int(K) % N
    # host tier knows K with embed_w = -5
    f0 = {f: np.zeros((1, 2), np.float32) if f == "embedx_w"
          else np.zeros(1, np.float32) for f in FIELDS}
    f0["embed_w"] = np.array([-5.0], np.float32)
    table.hosts[s].update(np.array([K]), f0)

    k1 = np.arange(1, 17, dtype=np.uint64)
    table.begin_pass(k1)
    # overlap: stage pass 2 (includes K, missing from the window → its
    # host value -5 is fetched) while pass 1 is open
    k2 = np.concatenate([np.arange(9, 17, dtype=np.uint64), [K]])
    table.stage(k2, background=False)
    assert np.any(np.concatenate(table._stage.new_keys) == K)
    # pass 1's streaming step assigns K mid-pass and trains it to 7
    with table.host_lock:
        row = int(table.indexes[s].assign(np.array([K]))[0])
        table._touched[s][row] = True
    data = np.asarray(jax.device_get(table.state.data)).copy()
    data[s][row, FIELD_COL["embed_w"]] = 7.0
    table.state = type(table.state).from_logical(data, table.capacity,
                                                 ext=table.opt_ext)
    table.end_pass()
    assert table.hosts[s].fetch(np.array([K]))["embed_w"][0] == 7.0
    table.begin_pass(k2)
    st = table.last_pass_stats
    # K was reconciled away: resident, not staged
    row2 = int(table.indexes[s].lookup(np.array([K]))[0])
    w = float(np.asarray(jax.device_get(
        table.state.data[s][row2, FIELD_COL["embed_w"]])))
    assert w == 7.0, f"stale staged value overwrote the trained row: {w}"
    table.end_pass()


def test_eviction_writes_back_touched_rows(mesh):
    """Capacity-pressure eviction: clean rows evict silently (host tier
    already has their values), rows touched since the last write-back
    are written back before release."""
    from paddlebox_tpu.ps.table import FIELD_COL
    cap = 16
    table = TieredShardedEmbeddingTable(N, mf_dim=2,
                                        capacity_per_shard=cap, cfg=_cfg())
    k1 = np.arange(0, N * cap, dtype=np.uint64)       # fills every shard
    table.begin_pass(k1)
    # train every row, write back, window stays full and clean
    for s in range(N):
        _, rows = table.indexes[s].items()
        table._touched[s][rows] = True
    data = np.asarray(jax.device_get(table.state.data)).copy()
    data[:, :, FIELD_COL["embed_w"]] = 3.0
    data[:, table.capacity, :] = 0.0  # keep the sentinel row zero
    table.state = type(table.state).from_logical(data, table.capacity,
                                                 ext=table.opt_ext)
    table.end_pass()
    # between passes, one row is dirtied again (streaming use outside
    # the pass protocol): its eviction must write back
    s0 = 0
    keys0, rows0 = table.indexes[s0].items()
    probe_key, probe_row = keys0[0], rows0[0]
    data = np.asarray(jax.device_get(table.state.data)).copy()
    data[s0][probe_row, FIELD_COL["embed_w"]] = 9.0
    table.state = type(table.state).from_logical(data, table.capacity,
                                                 ext=table.opt_ext)
    table._touched[s0][probe_row] = True
    # pass 2: disjoint working set, full capacity → evicts everything
    k2 = np.arange(N * cap, 2 * N * cap, dtype=np.uint64)
    table.begin_pass(k2)
    st = table.last_pass_stats
    assert st["evicted"] > 0
    assert st["evicted_writeback"] == 1  # only the dirtied row
    got = table.hosts[s0].fetch(np.array([probe_key]))["embed_w"][0]
    assert got == 9.0, "touched evicted row lost its update"
    # clean evicted rows kept their pass-1 write-back values
    other = keys0[1]
    assert table.hosts[s0].fetch(
        np.array([other]))["embed_w"][0] == 3.0
    table.end_pass()


def test_drop_window_discards_pending_stage(mesh):
    """drop_window (auto-run by load/merge_model/shrink) must discard a
    pending stage — its fetched values and resident/missing split
    predate the host-tier mutation — and zero the device rows so
    released rows read as fresh zero rows."""
    from paddlebox_tpu.ps.table import FIELDS
    table = TieredShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=32,
                                        cfg=_cfg())
    k1 = np.arange(1, 17, dtype=np.uint64)
    # seed host values and make keys resident once
    table.begin_pass(k1)
    for s in range(N):
        _, rows = table.indexes[s].items()
        table._touched[s][rows] = True
    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    data[:, :, FIELD_COL["show"]] = 5.0
    data[:, table.capacity, :] = 0.0
    table.state = type(table.state).from_logical(data, table.capacity,
                                                 ext=table.opt_ext)
    table.end_pass()
    # stage k2 (all resident → nothing fetched), then mutate the host
    # tier: the stale stage must not survive
    table.stage(k1, background=False)
    assert table._stage is not None
    table.shrink(delete_threshold=0.0, decay=0.5)  # decays show 5→2.5
    assert table._stage is None, "drop_window kept a stale stage"
    assert not np.any(np.asarray(jax.device_get(table.state.packed))), (
        "drop_window left stale values in released device rows")
    # next pass re-fetches everything, with post-shrink values
    table.begin_pass(k1)
    assert table.last_pass_stats["staged"] == len(k1)
    assert table.last_pass_stats["resident"] == 0
    for s in range(N):
        keys, rows = table.indexes[s].items()
        if not len(keys):
            continue
        show = np.asarray(jax.device_get(table.state.data))[s][
            rows, FIELD_COL["show"]]
        np.testing.assert_allclose(show, 2.5)
    table.end_pass()


def test_tiered_guards(mesh):
    table = TieredShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=16)
    with pytest.raises(RuntimeError):
        table.end_pass()
    table.begin_pass(np.arange(8, dtype=np.uint64))
    with pytest.raises(RuntimeError):
        table.begin_pass(np.arange(8, dtype=np.uint64))
    with pytest.raises(RuntimeError):
        table.save_base("/tmp/never.npz")
    with pytest.raises(RuntimeError):
        table.drop_window()
    # staging DURING an open pass is the overlap contract — legal; but a
    # second concurrent stage is not
    table.stage(np.arange(8, 16, dtype=np.uint64), background=False)
    with pytest.raises(RuntimeError):
        table.stage(np.arange(8, dtype=np.uint64))
    table.end_pass()
    table.begin_pass(np.arange(8, 16, dtype=np.uint64))  # consumes stage
    table.end_pass()
    # per-shard capacity guard
    with pytest.raises(ValueError):
        table.stage(np.arange(N * 64, dtype=np.uint64), background=False)


def test_tiered_preloader_overlapped_plan_build(mesh, tmp_path):
    """PassPreloader(build_fn=trainer.build_resident_pass) over a tiered
    table (VERDICT r4 item 3, preload_into_memory box_wrapper.h:1142):
    pass k+1's ROUTING PLAN builds during pass k (plan_scope pending
    rows), its host values stage overlapped, and begin_pass scatters the
    staged values into the plan-baked rows instead of keeping zeros —
    the model matches the build-after-begin oracle."""
    from paddlebox_tpu.train.device_pass import PassPreloader

    ds_a, desc = _make_ds(tmp_path, 31)
    # ds_b draws from an OFFSET value range → a real key delta vs ds_a
    files_b = generate_criteo_files(str(tmp_path / "q32"), num_files=2,
                                    rows_per_file=1200, vocab_per_slot=40,
                                    seed=32, value_base=1000)
    ds_b = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds_b.set_filelist(files_b)
    ds_b.load_into_memory()
    datasets = [ds_a, ds_b, ds_a, ds_b]

    def mk():
        t = TieredShardedEmbeddingTable(
            N, mf_dim=4, capacity_per_shard=4096, cfg=_cfg(),
            req_bucket_min=256, serve_bucket_min=256)
        with flags_scope(log_period_steps=10000):
            tr = ShardedTrainer(DeepFM(hidden=(16, 16)), t, desc, mesh,
                                tx=optax.adam(2e-3))
        return t, tr, BoxPSHelper(t, trainer=tr)

    # oracle: the sequential order (begin_pass, THEN build+train)
    ta, tr_a, ha = mk()
    staged_a = []
    for ds in datasets:
        ha.begin_pass(ds)
        staged_a.append(ta.last_pass_stats["staged"])
        tr_a.train_pass_resident(ds)
        ha.end_pass(ds)

    # overlapped: the preloader builds pass k+1's plan while k trains
    tb, tr_b, hb = mk()
    pre = PassPreloader(iter(datasets), build_fn=tr_b.build_resident_pass)
    pre.start_next()
    staged_b = []
    pending_seen = 0
    for i, ds in enumerate(datasets):
        rp = pre.wait()
        assert rp is not None
        hb.begin_pass(ds)     # staged values win over plan zero rows
        staged_b.append(tb.last_pass_stats["staged"])
        if pre.start_next() and i + 1 < len(datasets):
            hb.stage_pass(datasets[i + 1])   # host fetch overlaps too
        tr_b.train_pass_resident(rp)         # the PREBUILT pass
        with tb.host_lock:  # consolidated view (plan assigns append
            pending_seen = max(  # O(1) chunks; _pending_of merges them)
                pending_seen,
                sum(len(tb._pending_of(s)) for s in range(tb.n)))
        hb.end_pass(ds)
    # the mechanism actually engaged: some future-pass keys were
    # plan-assigned as pending before their begin_pass
    assert pending_seen > 0
    # begin_pass staged the same deltas as the sequential oracle
    assert staged_b == staged_a, (staged_b, staged_a)
    assert staged_b[1] > 0          # ds_b's keys were a real delta
    # model parity: dense params and per-key host-tier values (row ids
    # differ — plan-order vs promote-order assignment — so reductions
    # reorder; values agree to float-drift tolerance)
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-2, atol=2e-3)
    for s in range(N):
        ka, fa = ta.hosts[s].export_rows()
        kb, fb = tb.hosts[s].export_rows()
        oa, ob = np.argsort(ka), np.argsort(kb)
        np.testing.assert_array_equal(ka[oa], kb[ob])
        assert np.abs(fa["embed_w"][oa]).sum() > 0  # actually trained
        np.testing.assert_allclose(fa["embed_w"][oa], fb["embed_w"][ob],
                                   rtol=2e-2, atol=2e-3)


# ---- SSD third tier (ps/ssd.py, ISSUE 7): spill × async-epilogue ----


def test_ssd_demote_fences_inflight_endpass(tmp_path):
    """Demotion racing an in-flight end_pass write-back must FENCE
    first: the write-back lands (marking its rows touched) before the
    demote selects victims, so a pass's freshly written rows never
    spill while colder candidates exist."""
    from paddlebox_tpu.ps.host_store import HostStore
    from paddlebox_tpu.ps.table import FIELDS

    def mk_fields(n, v):
        return {f: (np.full((n, 2), v, np.float32) if f == "embedx_w"
                    else np.full(n, v, np.float32)) for f in FIELDS}

    hs = HostStore(mf_dim=2, capacity=64,
                   ssd_dir=str(tmp_path / "tier"))
    cold = np.arange(1, 41, dtype=np.uint64)
    hs.update(cold, mk_fields(40, 1.0))
    hs.export_rows()            # clear touched: cold rows are spillable
    hot = np.arange(101, 111, dtype=np.uint64)

    barrier_calls = []

    def inflight_writeback():
        # stands in for PassEpilogue.fence draining an end_pass job:
        # the job lands the hot rows (update marks them touched)
        if not barrier_calls:
            hs.update(hot, mk_fields(10, 9.0))
        barrier_calls.append(1)

    hs.read_barrier = inflight_writeback
    with flags_scope(host_demote_watermark=0.5, host_demote_target=0.25):
        n = hs.demote_to_watermark(barrier=True)
    assert barrier_calls, "demote never fenced the epilogue"
    assert n > 0
    # every hot (just-written-back, touched) key stayed in RAM …
    assert (hs.index.lookup(hot) >= 0).all()
    assert not hs.ssd.contains(hot).any()
    # … and the spilled set is cold keys only
    assert hs.ssd.contains(cold).sum() == n


def test_ssd_promote_under_plan_rollback_releases_rows(tmp_path):
    """A promote landing under a plan_scope that ROLLS BACK releases
    its plan-assigned window rows (no leaked pending pins), while the
    promoted host rows keep their trained values — the next real pass
    stages them normally."""
    import sys
    sys.path.insert(0, "scripts")
    from pipeline_check import _train_mutate

    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=256, cfg=_cfg(),
            host_capacity=1 << 12, ssd_dir=str(tmp_path / "tier"))
        keys = np.arange(1, 65, dtype=np.uint64)
        table.stage(keys, background=False)
        table.begin_pass(keys)
        _train_mutate(table, 0)           # embed_w = key*0.001 + 1
        table.end_pass()
        table.fence()
        table.drop_window()
        # force the whole trained set to the SSD tier
        for h in table.hosts:
            h.demote_cold()
        assert table.has_spilled_rows()
        assert sum(len(h) for h in table.hosts) == 0

        with pytest.raises(RuntimeError, match="boom"):
            with table.plan_scope():
                # a preloader build: plan-assign the keys as pending …
                for s, ks in enumerate(table._split_by_owner(keys)):
                    with table.host_lock:
                        table.indexes[s].assign(ks)
                        table._note_plan_assigned(s, ks)
                # … promote their spilled values host-ward …
                assert table.prefetch_promote(keys) == len(keys)
                raise RuntimeError("boom")   # … and the build dies

        # rollback released the plan's window rows and pending pins
        assert table.obs_stats()["pending"] == 0
        for s, ks in enumerate(table._split_by_owner(keys)):
            assert (table.indexes[s].lookup(ks) == -1).all()
        # the promote itself is NOT rolled back: rows live in host RAM
        # with their trained values (RAM is authoritative; the tier
        # copy was consumed exactly once)
        assert not table.has_spilled_rows()
        for s, ks in enumerate(table._split_by_owner(keys)):
            got = table.hosts[s].fetch(ks)["embed_w"]
            np.testing.assert_allclose(
                got, ks.astype(np.float64) * 0.001 + 1, rtol=1e-6)
        # and a real pass over the same keys stages cleanly
        table.stage(keys, background=False)
        assert table.begin_pass(keys) == len(keys)
        table.end_pass()
        table.fence()


def test_ssd_segment_compaction(tmp_path):
    """Compaction rewrites a sealed segment whose live fraction fell
    below the threshold: live rows re-append bit-identically, the dead
    file unlinks, and ONLY the compaction accounting books the rewrite
    — the real demote/promote counters (and the promote-wait
    critical-path attribution) stay untouched."""
    import os

    from paddlebox_tpu.ps.ssd import SsdTier
    tier = SsdTier(str(tmp_path / "t"), width=4, segment_rows=8,
                   compact_live_frac=0.9)
    keys = np.arange(1, 9, dtype=np.uint64)
    rows = np.arange(32, dtype=np.float32).reshape(8, 4)
    tier.append(keys, rows)                    # fills + seals segment 0
    path0 = tier.segment_paths()[0]
    assert tier.discard(keys[:6]) == 6         # live 2/8 < 0.9
    moved = tier.maybe_compact()
    assert moved == 2
    st = tier.stats()
    assert st["compacted_rows"] == 2
    assert st["demoted_rows"] == 8 and st["promoted_rows"] == 0, st
    assert st["promote_sec"] == 0.0 and st["promote_wait_sec"] == 0.0
    assert not os.path.exists(path0)           # dead segment unlinked
    fk, frows, _ = tier.take(keys[6:])
    np.testing.assert_array_equal(np.sort(fk), keys[6:])
    order = np.argsort(fk)
    np.testing.assert_array_equal(frows[order], rows[6:])
    assert len(tier) == 0


def test_ssd_tier_sweeps_leftover_segments(tmp_path):
    """A restarted process reusing the same tier directory must NOT
    append into the dead process's segment files (offsets would address
    the old content — silent wrong rows); leftovers are swept at init
    (the tier is a capacity cache; checkpoints are self-contained)."""
    import os

    from paddlebox_tpu.ps.ssd import SsdTier
    root = str(tmp_path / "t")
    t1 = SsdTier(root, width=4, segment_rows=8)
    keys = np.arange(1, 5, dtype=np.uint64)
    t1.append(keys, np.full((4, 4), 7.0, np.float32))
    old = t1.segment_paths()
    assert old and all(os.path.exists(p) for p in old)
    t2 = SsdTier(root, width=4, segment_rows=8)   # "restart"
    assert len(t2) == 0
    assert not any(os.path.exists(p) for p in old)  # swept
    t2.append(keys, np.full((4, 4), 42.0, np.float32))
    fk, rows, _ = t2.take(keys)
    assert len(fk) == 4
    np.testing.assert_array_equal(rows, np.full((4, 4), 42.0, np.float32))


def test_ssd_take_deduplicates_keys(tmp_path):
    """A key duplicated in one take() promotes (and leaves the index)
    exactly once — no KeyError, no double-counted row."""
    from paddlebox_tpu.ps.ssd import SsdTier
    tier = SsdTier(str(tmp_path / "t"), width=4)
    keys = np.arange(1, 4, dtype=np.uint64)
    tier.append(keys, np.tile(keys.astype(np.float32)[:, None], (1, 4)))
    dup = np.array([2, 2, 1, 2], np.uint64)
    fk, rows, _ = tier.take(dup)
    np.testing.assert_array_equal(np.sort(fk), [1, 2])
    assert len(tier) == 1
    assert tier.stats()["promoted_rows"] == 2


def test_ssd_touched_bit_preserves_delta(tmp_path):
    """A row demoted with an un-exported update carries its touched bit
    through the tier: save_delta/export_rows(delta=True) still emit it
    exactly once — demotion never loses a pending delta row."""
    from paddlebox_tpu.ps.host_store import HostStore
    from paddlebox_tpu.ps.table import FIELDS

    hs = HostStore(mf_dim=2, capacity=1 << 10,
                   ssd_dir=str(tmp_path / "tier"))
    keys = np.arange(1, 11, dtype=np.uint64)
    data = {f: (np.full((10, 2), 5.0, np.float32) if f == "embedx_w"
                else np.arange(10, dtype=np.float32)) for f in FIELDS}
    hs.update(keys, data)                      # touched
    assert hs.demote_cold(include_touched=True) == 10
    assert len(hs) == 0 and len(hs.ssd) == 10
    dk, dfields = hs.export_rows(delta=True)   # tier-touched rows merge
    order = np.argsort(dk)
    np.testing.assert_array_equal(dk[order], keys)
    np.testing.assert_allclose(dfields["embed_w"][order],
                               data["embed_w"])
    dk2, _ = hs.export_rows(delta=True)        # … exactly once
    assert len(dk2) == 0
    # the full export still carries the (now clean) tier rows
    fk, _ = hs.export_rows()
    assert len(fk) == 10


# ---- unified pass pipeline (ISSUE 9): queued stages × async eviction ----


def _plant_window_values(table, value: float) -> None:
    """Write ``value`` into every resident row's embed_w and mark the
    rows touched (a deterministic stand-in for a trained pass)."""
    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    with table.host_lock:
        for s in range(table.n):
            _, rows = table.indexes[s].items()
            if not len(rows):
                continue
            data[s][rows, FIELD_COL["embed_w"]] = value
            table._touched[s][rows] = True
        data[:, table.capacity, :] = 0.0
        table.state = type(table.state).from_logical(
            data, table.capacity, ext=table.opt_ext)


def test_async_evict_orders_behind_writeback():
    """Async capacity eviction vs the in-flight end_pass write-back:
    the lane's _evict_ahead runs in the SAME epilogue job strictly
    after the write-back lands, so a freshly-written row is never
    evicted ahead of its write-back — after the fence, every evicted
    key's host value carries the pass's update, and the next begin_pass
    finds its eviction already done (no inline emergency)."""
    from paddlebox_tpu.config import flags_scope
    cap = 16
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=cap, cfg=_cfg())
        k1 = np.arange(0, 2 * cap, dtype=np.uint64)   # fills both shards
        table.stage(k1, background=False)
        table.begin_pass(k1)
        _plant_window_values(table, 5.0)
        # the NEXT pass's stage is queued (disjoint keys → full
        # pressure) BEFORE end_pass, the pipeline shape
        k2 = np.arange(2 * cap, 4 * cap, dtype=np.uint64)
        table.stage(k2, background=False, queue=True)
        table.end_pass()      # lane: write-back k1 → evict ahead for k2
        table.fence()
        # every k1 value landed in the host tier BEFORE its eviction
        for s, ks in enumerate(table._split_by_owner(k1)):
            got = table.hosts[s].fetch(ks)["embed_w"]
            np.testing.assert_allclose(got, 5.0)
        # the lane actually freed the window for k2
        with table.host_lock:
            for s in range(2):
                assert len(table.indexes[s]) == 0
        table.begin_pass(k2)
        st = table.last_pass_stats
        assert st["evict_async_rows"] == 2 * cap
        assert st["evicted"] == 0, (
            f"begin_pass still evicted inline: {st}")
        assert st["staged"] == 2 * cap
        table.end_pass()
        table.fence()


def test_async_evict_skips_dirty_rows():
    """The clean-only rule: a row dirtied AFTER the end_pass snapshot
    (its write-back hasn't landed) is never evicted by the lane — it
    survives _evict_ahead and falls to the emergency inline path at
    begin_pass, which writes it back before release."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps.table import FIELD_COL
    cap = 16
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=cap, cfg=_cfg())
        k1 = np.arange(0, 2 * cap, dtype=np.uint64)
        table.stage(k1, background=False)
        table.begin_pass(k1)
        _plant_window_values(table, 5.0)
        table.end_pass()
        table.fence()          # k1 clean, host has 5.0
        k2 = np.arange(2 * cap, 4 * cap, dtype=np.uint64)
        table.stage(k2, background=False, queue=True)
        # dirty ONE row after the snapshot: its newest value (9.0) is
        # only on device — the lane must not evict it
        s0 = 0
        keys0, rows0 = table.indexes[s0].items()
        probe_key, probe_row = keys0[0], rows0[0]
        data = np.asarray(jax.device_get(table.state.data)).copy()
        data[s0][probe_row, FIELD_COL["embed_w"]] = 9.0
        table.state = type(table.state).from_logical(
            data, table.capacity, ext=table.opt_ext)
        table._touched[s0][probe_row] = True
        freed = table._evict_ahead()   # what the lane would run
        assert freed == 2 * cap - 1, freed
        with table.host_lock:          # the dirty row survived the lane
            assert int(table.indexes[s0].lookup(
                np.array([probe_key]))[0]) == probe_row
        # host still has the OLD value — the lane wrote nothing
        assert table.hosts[s0].fetch(
            np.array([probe_key]))["embed_w"][0] == 5.0
        # begin_pass: the emergency inline path evicts it WITH its
        # write-back (the fence + dirty-evictee discipline)
        table.begin_pass(k2)
        st = table.last_pass_stats
        assert st["evicted"] == 1 and st["evicted_writeback"] == 1, st
        assert st["evict_emergency_sec"] > 0.0
        assert table.hosts[s0].fetch(
            np.array([probe_key]))["embed_w"][0] == 9.0, (
            "dirty evictee lost its update")
        table.end_pass()
        table.fence()


def test_async_evict_never_unpins_queued_promote(tmp_path):
    """Eviction vs prefetch_promote: a row plan-assigned (pending) for
    a QUEUED pass — its value just promoted SSD→host by the preloader —
    cannot be evicted out from under its pin, even when the overflow
    wants more rows than the unpinned candidates can supply; its
    promoted value survives to its own begin_pass."""
    from paddlebox_tpu.config import flags_scope
    with flags_scope(warmup_pass_scatter=False):
        cap = 12
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=cap, cfg=_cfg(),
            ssd_dir=str(tmp_path / "tier"))
        # pass 1: 8 rows/shard, trained to 5.0, written back, clean
        k1 = np.arange(0, 16, dtype=np.uint64)
        table.stage(k1, background=False)
        table.begin_pass(k1)
        _plant_window_values(table, 5.0)
        table.end_pass()
        table.fence()
        # pass 2's keys: 4/shard whose values live ONLY on SSD + 8/shard
        # genuinely new
        pend = np.arange(100, 108, dtype=np.uint64)
        new = np.arange(200, 216, dtype=np.uint64)
        k2 = np.concatenate([pend, new])
        from paddlebox_tpu.ps.table import FIELDS
        for s, ks in enumerate(table._split_by_owner(pend)):
            f = {f_: (np.full((len(ks), 2), 7.0, np.float32)
                      if f_ == "embedx_w"
                      else np.full(len(ks), 7.0, np.float32))
                 for f_ in FIELDS}
            table.hosts[s].update(ks, f)
        table.fence()
        for h in table.hosts:
            h.demote_cold()
        assert table.has_spilled_rows()
        # the preloader build: plan-assign k2's pending subset + promote
        # their spilled values, then queue the stage (PassPipeline shape)
        with table.plan_scope():
            for s, ks in enumerate(table._split_by_owner(pend)):
                with table.host_lock:
                    pre = table.indexes[s].lookup(ks)
                    table.indexes[s].assign(ks)
                    table._note_plan_assigned(s, ks[pre < 0])
            assert table.prefetch_promote(pend) == len(pend)
            table.stage(k2, background=False, queue=True)
        # pressure: index 12/shard (8 k1 + 4 pending) + 8 new > cap 12;
        # overflow (8) equals the ONLY unpinned candidates (k1) — the
        # pinned pending rows must all survive
        freed = table._evict_ahead()
        assert freed == 16, freed       # all of k1, both shards
        with table.host_lock:
            for s, ks in enumerate(table._split_by_owner(pend)):
                assert (table.indexes[s].lookup(ks) >= 0).all(), (
                    "a pinned pending row was evicted from under its "
                    "promote")
        table.begin_pass(k2)
        st = table.last_pass_stats
        assert st["evicted"] == 0, st
        # the promoted values reached the window through the reconcile
        for s, ks in enumerate(table._split_by_owner(pend)):
            rows = table.indexes[s].lookup(ks)
            from paddlebox_tpu.ps.table import FIELD_COL
            w = np.asarray(jax.device_get(
                table.state.data))[s][rows, FIELD_COL["embed_w"]]
            np.testing.assert_allclose(w, 7.0)
        table.end_pass()
        table.fence()


def test_pipeline_plan_rollback_on_abort():
    """Preloader-staged tiered pass rollback under plan_scope abort: a
    build that dies AFTER plan-assigning its keys (the abort-between-
    stages poll) rolls its pending rows back — nothing stays pinned, no
    stage is queued, and the table runs a normal pass afterwards."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.train.device_pass import (PassPipeline,
                                                 PreloadBuildAborted)
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=256, cfg=_cfg())
        k1 = np.arange(0, 32, dtype=np.uint64)
        k2 = np.arange(100, 132, dtype=np.uint64)
        built = []

        class _Tok:
            def upload(self, materialize=False):
                pass

            def nbytes(self):
                return 0

        def build(ks):
            for s, sub in enumerate(table._split_by_owner(ks)):
                with table.host_lock:
                    pre = table.indexes[s].lookup(sub)
                    table.indexes[s].assign(sub)
                    table._note_plan_assigned(s, sub[pre < 0])
            built.append(ks[0])
            if len(built) == 2:
                # the second build observes a stop between stages
                raise PreloadBuildAborted("stop between build stages")
            return _Tok()

        pipe = PassPipeline(iter([k1, k2]), build_fn=build,
                            window_table=table, keys_of=lambda k: k)
        pipe.start_next()
        rp = pipe.wait()
        assert rp is not None
        pipe.begin_pass()
        pipe.end_pass()
        assert pipe.wait() is None       # the aborted build never lands
        pipe.drain()
        table.fence()
        # k2's plan rows rolled back: no pins, no rows, no queued stage
        assert table.obs_stats()["pending"] == 0
        for s, sub in enumerate(table._split_by_owner(k2)):
            assert (table.indexes[s].lookup(sub) == -1).all()
        assert len(table._stage_q) == 0
        # and the table still runs a normal pass over those keys
        table.stage(k2, background=False)
        assert table.begin_pass(k2) == len(k2)
        table.end_pass()
        table.fence()


def test_pipeline_drain_discards_queued_stages():
    """PassPipeline.drain() with built-but-never-begun passes: queued
    stages are discarded and their plan-pending pins released
    (discard_queued_stages) — abandoned stages never pin window
    capacity; keys shared with the open window stay resident."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.train.device_pass import PassPipeline
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=256, cfg=_cfg())
        k1 = np.arange(0, 32, dtype=np.uint64)
        k2 = np.arange(100, 132, dtype=np.uint64)    # disjoint from k1
        k3 = np.arange(116, 148, dtype=np.uint64)    # overlaps k2

        class _Tok:
            def upload(self, materialize=False):
                pass

            def nbytes(self):
                return 0

        def build(ks):
            for s, sub in enumerate(table._split_by_owner(ks)):
                with table.host_lock:
                    pre = table.indexes[s].lookup(sub)
                    table.indexes[s].assign(sub)
                    table._note_plan_assigned(s, sub[pre < 0])
            return _Tok()

        pipe = PassPipeline(iter([k1, k2, k3]), build_fn=build,
                            window_table=table, depth=3,
                            keys_of=lambda k: k)
        pipe.start_next()
        rp = pipe.wait()
        pipe.begin_pass()                 # consume k1 only
        # let the worker finish building+staging k2 and k3
        for _ in range(200):
            with table.host_lock:
                q = len(table._stage_q)
            if q == 2:
                break
            time.sleep(0.01)
        assert q == 2
        pipe.end_pass()
        pipe.drain()                      # k2/k3 will never begin
        table.fence()
        assert table.obs_stats()["pending"] == 0
        assert len(table._stage_q) == 0
        with table.host_lock:
            for s, sub in enumerate(table._split_by_owner(
                    np.setdiff1d(np.concatenate([k2, k3]), k1))):
                assert (table.indexes[s].lookup(sub) == -1).all(), (
                    "an abandoned stage left plan rows pinning the "
                    "window")
            # the open pass's rows are untouched by the discard
            for s, sub in enumerate(table._split_by_owner(k1)):
                assert (table.indexes[s].lookup(sub) >= 0).all()


def test_async_evict_pins_inflight_stage():
    """The in-flight stage pin (review finding): a queued stage's
    missing-split is computed BEFORE its lock-free host fetch, so the
    whole working set must be pinned from that moment — _evict_ahead
    firing mid-fetch must not evict a key the stage classified as
    resident (it would never be re-inserted at that pass's begin)."""
    from paddlebox_tpu.config import flags_scope
    cap = 16
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=cap, cfg=_cfg())
        k1 = np.arange(0, 2 * cap, dtype=np.uint64)
        table.stage(k1, background=False)
        table.begin_pass(k1)
        _plant_window_values(table, 5.0)
        table.end_pass()
        table.fence()                      # k1 resident, clean
        # head queued stage: disjoint keys → full capacity pressure
        kb = np.arange(100, 100 + 2 * cap, dtype=np.uint64)
        table.stage(kb, background=False, queue=True)
        # next stage re-uses k1 (classified resident at split time);
        # the lane fires _evict_ahead DURING its host fetch
        fired = []
        orig = table._fetch_stage_values

        def hook(s, new_keys, table=table):
            if not fired:
                fired.append(table._evict_ahead())
            return orig(s, new_keys)

        table._fetch_stage_values = hook
        try:
            table.stage(k1, background=False, queue=True)
        finally:
            table._fetch_stage_values = orig
        assert fired, "the mid-fetch eviction never ran"
        # the in-flight stage's resident keys survived the lane
        assert fired[0] == 0, (
            f"_evict_ahead evicted {fired[0]} rows out from under the "
            "in-flight stage's missing-split")
        with table.host_lock:
            for s, ks in enumerate(table._split_by_owner(k1)):
                assert (table.indexes[s].lookup(ks) >= 0).all(), (
                    "an in-flight stage's resident key was evicted "
                    "mid-fetch")
            assert table._staging_keys is None   # pin released
        table.discard_queued_stages()
        table.fence()


def test_begin_failure_restores_queued_stage():
    """A begin_pass that fails AFTER consuming a queued stage (e.g.
    window overflow with every candidate pinned) restores the stage to
    the queue head and drops the open-pass pin — the pipeline's queues
    stay aligned and drain/discard still release every pin."""
    from paddlebox_tpu.config import flags_scope
    cap = 8
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=cap, cfg=_cfg())
        k1 = np.arange(0, 2 * cap, dtype=np.uint64)
        table.stage(k1, background=False)
        table.begin_pass(k1)
        _plant_window_values(table, 5.0)
        table.end_pass()
        table.fence()                       # window full of clean k1
        kb = np.arange(100, 100 + 2 * cap, dtype=np.uint64)
        table.stage(kb, background=False, queue=True)
        # the NEXT queued stage re-stages k1 — pinning it, so kb's
        # begin has zero evictable candidates and must overflow
        table.stage(k1, background=False, queue=True)
        with pytest.raises(Exception):
            table.begin_pass(kb)
        assert not table.in_pass
        with table.host_lock:
            # the failed pass's stage is back at the queue head …
            assert len(table._stage_q) == 2
            assert np.array_equal(
                np.concatenate(table._stage_q[0].keys),
                np.concatenate(table._split_by_owner(kb)))
            # … and nothing stays pinned as "open"
            assert all(len(a) == 0 for a in table._open_keys)
        assert table.discard_queued_stages() == 2
        table.fence()
        # the table still runs a normal (evicting) pass afterwards
        table.stage(kb, background=False)
        assert table.begin_pass(kb) == len(kb)
        table.end_pass()
        table.fence()


def test_pin_working_set_covers_plan_build():
    """The pre-build pin (review finding): a plan build bakes row ids
    for RESIDENT keys too, so the pass's working set must be pinned
    from the first row lookup — _evict_ahead firing between plan build
    and stage() must not evict a resident key the plan already
    addresses."""
    from paddlebox_tpu.config import flags_scope
    cap = 16
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=cap, cfg=_cfg())
        k1 = np.arange(0, 2 * cap, dtype=np.uint64)
        table.stage(k1, background=False)
        table.begin_pass(k1)
        _plant_window_values(table, 5.0)
        table.end_pass()
        table.fence()                      # k1 resident, clean
        kb = np.arange(100, 100 + 2 * cap, dtype=np.uint64)
        table.stage(kb, background=False, queue=True)   # pressure head
        # the PassPipeline order: pin → plan build (bakes k1's rows) →
        # lane eviction fires → stage. The pin must hold throughout.
        table.pin_working_set(k1)
        rows_baked = [table.indexes[s].lookup(ks) for s, ks in
                      enumerate(table._split_by_owner(k1))]
        freed = table._evict_ahead()       # the lane firing mid-build
        assert freed == 0, (
            f"_evict_ahead evicted {freed} rows the in-build plan "
            "already baked")
        table.stage(k1, background=False, queue=True)   # same-keys pin ok
        with table.host_lock:
            assert table._staging_keys is None          # handed over
            for s, ks in enumerate(table._split_by_owner(k1)):
                np.testing.assert_array_equal(
                    table.indexes[s].lookup(ks), rows_baked[s])
        table.discard_queued_stages()
        table.fence()


def test_discard_rejects_straddling_fetch():
    """discard_queued_stages racing an in-flight queued fetch: the
    fetch that straddled the discard must NOT append a zombie stage
    afterwards (its plan pins would leak forever) — it raises, and the
    queue stays empty."""
    from paddlebox_tpu.config import flags_scope
    with flags_scope(warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            2, mf_dim=2, capacity_per_shard=64, cfg=_cfg())
        k1 = np.arange(0, 32, dtype=np.uint64)
        orig = table._fetch_stage_values
        fired = []

        def hook(s, new_keys):
            if not fired:     # the discard lands mid-fetch
                fired.append(table.discard_queued_stages())
            return orig(s, new_keys)

        table._fetch_stage_values = hook
        try:
            with pytest.raises(RuntimeError, match="discarded"):
                table.stage(k1, background=False, queue=True)
        finally:
            table._fetch_stage_values = orig
        with table.host_lock:
            assert len(table._stage_q) == 0
            assert table._staging_keys is None
        # the table still stages and begins normally afterwards
        table.stage(k1, background=False, queue=True)
        assert table.begin_pass(k1) == len(k1)
        table.end_pass()
        table.fence()
