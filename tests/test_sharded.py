"""Sharded embedding PS + multi-chip train step on the 8-device CPU mesh —
the heter_ps/test_comm.cu analogue (single-process multi-device, no cluster)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.train import Trainer
from paddlebox_tpu.train.sharded import (ShardedTrainer, make_global_batch)

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N, "conftest must provide 8 CPU devices"
    return make_mesh(N)


def make_batches(n, bs=8, S=3, k_pad=32, seed=0):
    """n local SlotBatch with random keys across a shared key space."""
    from paddlebox_tpu.data.batch import SlotBatch
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        nk = int(rng.integers(S, k_pad // 2))
        keys = rng.integers(1, 500, size=nk).astype(np.uint64)
        kp = np.zeros(k_pad, np.uint64)
        kp[:nk] = keys
        segs = np.full(k_pad, bs * S, np.int32)
        segs[:nk] = rng.integers(0, bs * S, size=nk).astype(np.int32)
        segs[:nk].sort()
        out.append(SlotBatch(
            keys=kp, segments=segs, num_keys=nk,
            dense=rng.normal(size=(bs, 4)).astype(np.float32),
            label=rng.integers(0, 2, bs).astype(np.float32),
            show=np.ones(bs, np.float32),
            clk=rng.integers(0, 2, bs).astype(np.float32),
            batch_size=bs, num_slots=S))
    return out


def test_prepare_global_routing():
    table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                                  req_bucket_min=8, serve_bucket_min=8)
    batches = make_batches(N)
    idx = table.prepare_global(batches)
    A, A2 = idx.req_capacity, idx.serve_capacity
    assert idx.resp_idx.shape == (N, N, A)
    assert idx.serve_rows.shape == (N, A2)
    # every key's owner shard is key % N and its value row exists there
    for d, b in enumerate(batches):
        for k in b.keys[:b.num_keys]:
            s = int(k) % N
            assert table.indexes[s].lookup(
                np.array([k], np.uint64))[0] >= 0
    # serve rows are unique per owner (dedup across requesters)
    for s in range(N):
        valid = idx.serve_rows[s][idx.serve_valid[s] > 0]
        assert len(valid) == len(np.unique(valid))


def test_sharded_pull_matches_single_table(mesh):
    """Pull through the mesh == pull from one big table with same rows."""
    from paddlebox_tpu.train.sharded import ShardedTrainStep
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                                  cfg=cfg, req_bucket_min=8,
                                  serve_bucket_min=8)
    batches = make_batches(N, seed=3)
    idx = table.prepare_global(batches)
    # plant distinctive embed_w = key value into each shard (AoS col 4)
    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    for s in range(N):
        keys, rows = table.indexes[s].items()
        data[s][rows, FIELD_COL["embed_w"]] = keys.astype(np.float32)
    table.state = type(table.state).from_logical(data, table.capacity)

    gb = make_global_batch(batches, idx)
    from jax.sharding import PartitionSpec as P
    from paddlebox_tpu.parallel.mesh import DATA_AXIS
    from paddlebox_tpu.ps.table import pull_rows, TableState

    def pull_blk(table_st, resp_idx, serve_rows, gather_idx):
        t = table_st.with_packed(table_st.packed[0])
        vals = pull_rows(t, serve_rows[0])
        resp = vals[resp_idx[0]]
        recv = jax.lax.all_to_all(resp, DATA_AXIS, 0, 0, tiled=True)
        flat = recv.reshape(-1, recv.shape[-1])
        return flat[gather_idx[0]][None]

    f = jax.jit(jax.shard_map(
        pull_blk, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS), check_vma=False))
    got = np.asarray(f(table.state, gb.resp_idx, gb.serve_rows,
                       gb.gather_idx))
    for d, b in enumerate(batches):
        np.testing.assert_allclose(
            got[d, :b.num_keys, 2], b.keys[:b.num_keys].astype(np.float32),
            rtol=1e-6, err_msg=f"device {d} pulled wrong embed_w")
        np.testing.assert_array_equal(got[d, b.num_keys:], 0)


def test_sharded_training_learns(mesh, tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=2,
                                  rows_per_file=1500, vocab_per_slot=40,
                                  seed=11)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.local_shuffle(seed=1)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=4096,
                                  cfg=cfg, req_bucket_min=256,
                                  serve_bucket_min=256)
    with flags_scope(log_period_steps=10000):
        tr = ShardedTrainer(DeepFM(hidden=(32, 32)), table, desc, mesh,
                            tx=optax.adam(2e-3))
        r1 = tr.train_pass(ds)
        tr.reset_metrics()
        r2 = tr.train_pass(ds)
    assert np.isfinite(r2["last_loss"])
    assert r2["ins_num"] == 3000  # every record counted exactly once
    assert r2["auc"] > 0.58, f"sharded AUC too low: {r2['auc']}"
    assert table.feature_count() > 100


def test_sharded_save_load_roundtrip(mesh, tmp_path):
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=128,
                                  cfg=cfg, req_bucket_min=8,
                                  serve_bucket_min=8)
    batches = make_batches(N, seed=5)
    table.prepare_global(batches)
    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    for s in range(N):
        keys, rows = table.indexes[s].items()
        data[s][rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * 2
    table.state = type(table.state).from_logical(data, table.capacity)
    path = str(tmp_path / "sharded.npz")
    n_saved = table.save_base(path)
    assert n_saved == table.feature_count() > 0

    t2 = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=128, cfg=cfg)
    assert t2.load(path) == n_saved
    for s in range(N):
        keys, rows = t2.indexes[s].items()
        np.testing.assert_allclose(
            np.asarray(t2.state.embed_w)[s][rows],
            keys.astype(np.float32) * 2)


def test_sharded_shrink_ages_features(mesh):
    """ShrinkTable on the stacked shards: decay + threshold drop, same
    accessor rules as EmbeddingTable.shrink (box_wrapper.h:638)."""
    from paddlebox_tpu.ps.table import FIELD_COL
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    table = ShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=64,
                                  cfg=cfg, req_bucket_min=8,
                                  serve_bucket_min=8)
    batches = make_batches(N, seed=31)
    table.prepare_global(batches)
    before = table.feature_count()
    assert before > 0
    # plant heat on HALF the keys of shard 0; rest stay cold (show=0)
    data = np.asarray(jax.device_get(table.state.data)).copy()
    hot_per_shard = {}
    for s in range(N):
        keys, rows = table.indexes[s].items()
        half = rows[: len(rows) // 2]
        data[s][half, FIELD_COL["show"]] = 10.0
        data[s][half, FIELD_COL["clk"]] = 5.0
        hot_per_shard[s] = keys[: len(rows) // 2]
    table.state = type(table.state).from_logical(data, table.capacity)
    freed = table.shrink(delete_threshold=0.5, decay=0.9)
    assert freed == before - sum(len(v) for v in hot_per_shard.values())
    for s in range(N):
        keys, rows = table.indexes[s].items()
        assert set(keys.tolist()) == set(hot_per_shard[s].tolist())
        # decay applied to survivors
        np.testing.assert_allclose(
            np.asarray(table.state.data)[s][rows, FIELD_COL["show"]], 9.0)


def test_sharded_merge_model_and_merge_models(mesh, tmp_path):
    """merge_model accumulates stats for shared keys / inserts new ones;
    merge_models folds multiple files; single-table-format files split by
    key%N (box_wrapper.h:801-815)."""
    from paddlebox_tpu.ps.table import FIELD_COL
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)

    def seeded_table(keys, w):
        t = ShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=64,
                                  cfg=cfg, req_bucket_min=8,
                                  serve_bucket_min=8)
        data = np.asarray(jax.device_get(t.state.data)).copy()
        owners = (keys % np.uint64(N)).astype(np.int64)
        for s in range(N):
            ks = keys[owners == s]
            rows = t.indexes[s].assign(ks)
            data[s][rows, FIELD_COL["embed_w"]] = w
            data[s][rows, FIELD_COL["show"]] = 3.0
            data[s][rows, FIELD_COL["clk"]] = 1.0
        t.state = type(t.state).from_logical(data, t.capacity)
        return t

    live = seeded_table(np.arange(1, 33, dtype=np.uint64), 1.0)
    other = seeded_table(np.arange(17, 49, dtype=np.uint64), -5.0)
    p1 = str(tmp_path / "other.npz")
    other.save_base(p1)

    assert live.merge_model(p1) == 32
    assert live.feature_count() == 48
    data = np.asarray(jax.device_get(live.state.data))
    # shared key 17: stats accumulate, live weight kept
    s17 = 17 % N
    r = live.indexes[s17].lookup(np.array([17], np.uint64))[0]
    assert data[s17][r, FIELD_COL["show"]] == 6.0
    assert data[s17][r, FIELD_COL["embed_w"]] == 1.0
    # new key 48: inserted wholesale
    s48 = 48 % N
    r = live.indexes[s48].lookup(np.array([48], np.uint64))[0]
    assert data[s48][r, FIELD_COL["embed_w"]] == -5.0

    # merge_models overwrite mode: later file wins on shared keys
    live2 = seeded_table(np.arange(1, 33, dtype=np.uint64), 1.0)
    assert live2.merge_models([p1], update_type="overwrite") == 32
    data2 = np.asarray(jax.device_get(live2.state.data))
    r = live2.indexes[s17].lookup(np.array([17], np.uint64))[0]
    assert data2[s17][r, FIELD_COL["embed_w"]] == -5.0

    # single-table-format file (no "n" block) splits by key%N
    st_keys = np.arange(100, 110, dtype=np.uint64)
    np.savez(str(tmp_path / "single.npz"), keys=st_keys,
             show=np.ones(10, np.float32), clk=np.zeros(10, np.float32),
             delta_score=np.zeros(10, np.float32),
             slot=np.zeros(10, np.float32),
             embed_w=np.full(10, 9.0, np.float32),
             embed_g2sum=np.zeros(10, np.float32),
             embedx_w=np.zeros((10, 2), np.float32),
             embedx_g2sum=np.zeros(10, np.float32),
             mf_size=np.zeros(10, np.float32))
    assert live.merge_model(str(tmp_path / "single.npz")) == 10
    s100 = 100 % N
    r = live.indexes[s100].lookup(np.array([100], np.uint64))[0]
    assert np.asarray(jax.device_get(
        live.state.data))[s100][r, FIELD_COL["embed_w"]] == 9.0


def test_sharded_opt_ext_survives_save_load(mesh, tmp_path):
    """SparseAdam per-row state (opt_ext block) persists through sharded
    save_base/load — the optimizer resumes, not restarts."""
    from paddlebox_tpu.ps.sgd import SparseAdamConfig
    cfg = SparseAdamConfig(mf_create_thresholds=1e9)
    table = ShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=64,
                                  cfg=cfg, req_bucket_min=8,
                                  serve_bucket_min=8)
    assert table.opt_ext > 0
    batches = make_batches(N, seed=41)
    table.prepare_global(batches)
    from paddlebox_tpu.ps.table import NUM_FIXED
    mf_end = NUM_FIXED + table.mf_dim
    data = np.asarray(jax.device_get(table.state.data)).copy()
    for s in range(N):
        _, rows = table.indexes[s].items()
        data[s][rows, mf_end:] = 0.25 * (s + 1)
    table.state = type(table.state).from_logical(data, table.capacity,
                                                 ext=table.opt_ext)
    path = str(tmp_path / "adam.npz")
    n = table.save_base(path)
    t2 = ShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=64,
                               cfg=cfg, req_bucket_min=8,
                               serve_bucket_min=8)
    assert t2.load(path) == n
    d2 = np.asarray(jax.device_get(t2.state.data))
    for s in range(N):
        _, rows = t2.indexes[s].items()
        if len(rows):
            np.testing.assert_allclose(d2[s][rows, mf_end:],
                                       0.25 * (s + 1))


def test_sharded_save_delta_and_reset_load(mesh, tmp_path):
    """load(merge=False) must reset device rows not covered by the dump;
    save_delta only dumps touched-since-last-save rows."""
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    table = ShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=64,
                                  cfg=cfg, req_bucket_min=8,
                                  serve_bucket_min=8)
    b1 = make_batches(N, seed=21)
    table.prepare_global(b1)
    base = str(tmp_path / "b.npz")
    n1 = table.save_base(base)
    # new keys after the base save → delta contains only those shards' rows
    b2 = make_batches(N, seed=22)
    table.prepare_global(b2)
    delta = str(tmp_path / "d.npz")
    nd = table.save_delta(delta)
    assert 0 < nd <= table.feature_count()
    # plant junk in a row, then reset-load the base: junk must be gone
    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    data[0][:, FIELD_COL["embed_w"]] = 99.0
    table.state = type(table.state).from_logical(data, table.capacity)
    got = table.load(base)  # merge=False resets everything first
    assert got == n1
    w0 = np.asarray(table.state.embed_w)[0]
    keys0, rows0 = table.indexes[0].items()
    mask = np.ones(len(w0), bool)
    mask[rows0] = False
    assert np.all(w0[mask] == 0.0), "stale device rows survived reset load"


@pytest.mark.slow  # seed-broken (no jax.shard_map) until the
# jax_compat shim; recovered, but heavy on the virtual-CPU mesh —
# out of the tier-1 wall budget, runs in the slow tier (zero1 parity
# is also pinned by the lr_map zero1 variant there)
def test_zero1_matches_replicated_dense_update(mesh):
    """ZeRO-1 (opt-state sharded over flat param chunks, reference
    boxps_worker.cc:601 sharding stage) must produce the same params as
    the replicated optimizer path."""
    cfg = SparseSGDConfig(mf_create_thresholds=1e9, learning_rate=0.05)
    batches = make_batches(N, seed=7)

    results = []
    for zero1 in (False, True):
        table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                                      cfg=cfg, req_bucket_min=8,
                                      serve_bucket_min=8)
        desc = type("D", (), {"batch_size": 8, "sparse_slots": [0, 1, 2],
                              "dense_dim": 4})()
        tr = ShardedTrainer(DeepFM(hidden=(8, 8)), table, desc, mesh,
                            tx=optax.adam(1e-2), zero1=zero1)
        state = tr.state
        idx = table.prepare_global(batches)
        gb = make_global_batch(batches, idx)
        for i in range(3):
            state, stats = tr.step_fn(state, gb, jax.random.PRNGKey(i))
        results.append(jax.device_get(state.params))

    flat_a = jax.tree_util.tree_leaves(results[0])
    flat_b = jax.tree_util.tree_leaves(results[1])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)


@pytest.mark.slow  # same budget rationale — the resident mesh path
# stays covered in tier-1 by test_sharded_resident_matches_streaming
def test_sharded_resident_non_trivial_segments(mesh):
    """Mesh resident pass with MULTI-KEY slots (non-trivial segments —
    the wire ships a segment stream instead of deriving from meta):
    must match the streaming mesh pass exactly."""
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.data.record import SlotRecord
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=16,
                        key_bucket_min=128)
    rng = np.random.default_rng(61)
    recs = []
    for i in range(N * 16 * 4):
        counts = rng.integers(0, 3, size=4)
        counts[rng.integers(0, 4)] += 1
        offs = np.zeros(5, np.int32)
        np.cumsum(counts, out=offs[1:])
        keys = np.concatenate([
            rng.integers(s * 1000, (s + 1) * 1000, size=counts[s])
            for s in range(4)]).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offs,
            dense=rng.normal(size=3).astype(np.float32),
            label=float(i % 2), show=1.0, clk=float(i % 2)))

    def mk():
        ds = InMemoryDataset(desc)
        ds.records = list(recs)
        ds.columnarize()
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0,
                              learning_rate=0.05, mf_learning_rate=0.05)
        table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=512,
                                      cfg=cfg, req_bucket_min=32,
                                      serve_bucket_min=32)
        with flags_scope(log_period_steps=10000):
            tr = ShardedTrainer(DeepFM(hidden=(8, 8)), table, desc, mesh,
                                tx=optax.adam(1e-2), seed=5)
        return tr, ds

    tr_a, ds_a = mk()
    tr_b, ds_b = mk()
    for _ in range(2):
        ra = tr_a.train_pass(ds_a)
        rb = tr_b.train_pass_resident(ds_b)
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=1e-6), (ra["auc"],
                                                         rb["auc"])
    for a, b in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_repad_plan_equals_reroute():
    """_repad_plan (host-side array surgery) must produce exactly the
    plan prepare_global would build with the same forced capacities —
    both shrink (fine < pow2) and growth (tail group) directions."""
    from paddlebox_tpu.train.sharded import ShardedResidentPass
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    for forced_a, forced_a2 in ((24, 40), (96, 104)):
        table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                                      cfg=cfg, req_bucket_min=64,
                                      serve_bucket_min=64)
        batches = make_batches(N, seed=51)
        p1 = table.prepare_global(batches)
        if forced_a < p1.req_need or forced_a2 < p1.serve_need:
            forced_a = max(forced_a, p1.req_need)
            forced_a2 = max(forced_a2, p1.serve_need)
        got = ShardedResidentPass._repad_plan(
            p1, forced_a, forced_a2, N, table.capacity)
        assert got is not None
        want = table.prepare_global(batches, req_capacity=forced_a,
                                    serve_capacity=forced_a2)
        np.testing.assert_array_equal(got.resp_idx, want.resp_idx)
        np.testing.assert_array_equal(got.serve_rows, want.serve_rows)
        np.testing.assert_array_equal(got.serve_valid, want.serve_valid)
        np.testing.assert_array_equal(got.serve_slot, want.serve_slot)
        np.testing.assert_array_equal(got.gather_idx, want.gather_idx)
        assert got.req_capacity == want.req_capacity == forced_a
        assert got.serve_capacity == want.serve_capacity == forced_a2

    # the ambiguous-full-bucket guard: when the OLD request bucket is
    # exactly full (req_need == req_capacity), the gather pad sentinel
    # aliases a real position — _repad_plan must refuse (build() then
    # re-routes via prepare_global)
    from paddlebox_tpu.train.sharded import ShardedResidentPass as SRP
    p_full = p1._replace(req_need=p1.req_capacity)
    assert SRP._repad_plan(p_full, p1.req_capacity + 512,
                           p1.serve_capacity, N, table.capacity) is None


def test_sharded_resident_matches_streaming(mesh, tmp_path):
    """Device-resident mesh pass == streaming mesh pass (same data, same
    init; mf_initial_range=0 so rng paths don't diverge)."""
    files = generate_criteo_files(str(tmp_path), num_files=2,
                                  rows_per_file=1200, vocab_per_slot=40,
                                  seed=13)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    def mk():
        cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                              learning_rate=0.1, mf_learning_rate=0.1)
        table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=4096,
                                      cfg=cfg, req_bucket_min=256,
                                      serve_bucket_min=256)
        with flags_scope(log_period_steps=10000):
            return ShardedTrainer(DeepFM(hidden=(32, 32)), table, desc, mesh,
                                  tx=optax.adam(2e-3)), table

    tr_a, _ = mk()
    ra = tr_a.train_pass(ds)
    tr_b, table_b = mk()
    rb = tr_b.train_pass_resident(ds)
    assert rb["batches"] == ra["batches"]
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3), (rb["auc"], ra["auc"])
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-2, atol=2e-3)
    # second resident pass continues training
    tr_b.reset_metrics()
    rb2 = tr_b.train_pass_resident(ds)
    assert rb2["auc"] > rb["auc"] - 0.02


def test_sharded_pass_preloader(mesh, tmp_path):
    """PassPreloader double-buffers mesh resident passes via build_fn."""
    from paddlebox_tpu.train import PassPreloader
    files = generate_criteo_files(str(tmp_path), num_files=1,
                                  rows_per_file=600, vocab_per_slot=30,
                                  seed=17)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = ShardedEmbeddingTable(N, mf_dim=2, capacity_per_shard=2048,
                                  cfg=cfg, req_bucket_min=128,
                                  serve_bucket_min=128)
    with flags_scope(log_period_steps=10000):
        tr = ShardedTrainer(DeepFM(hidden=(16,)), table, desc, mesh,
                            tx=optax.adam(1e-3))
        pre = PassPreloader(iter([ds, ds]),
                            build_fn=tr.build_resident_pass)
        pre.start_next()
        results = []
        while True:
            rp = pre.wait()
            if rp is None:
                break
            more = pre.start_next()
            results.append(tr.train_pass_resident(rp))
            if not more:
                break
    assert len(results) == 2
    assert all(np.isfinite(r["auc"]) for r in results)


@pytest.mark.slow  # same budget rationale as above
def test_sharded_eval_pass_and_checkpoint(mesh, tmp_path):
    """Forward-only mesh eval + CheckpointManager save/restore round trip
    on the sharded trainer."""
    from paddlebox_tpu.train import CheckpointManager
    files = generate_criteo_files(str(tmp_path / "d"), num_files=1,
                                  rows_per_file=1200, vocab_per_slot=40,
                                  seed=23)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    def mk():
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0, learning_rate=0.1,
                              mf_learning_rate=0.1)
        table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=4096,
                                      cfg=cfg, req_bucket_min=256,
                                      serve_bucket_min=256)
        with flags_scope(log_period_steps=10000):
            return ShardedTrainer(DeepFM(hidden=(32, 32)), table, desc,
                                  mesh, tx=optax.adam(2e-3))

    tr = mk()
    tr.train_pass(ds)
    tr.train_pass(ds)
    ev = tr.eval_pass(ds)
    assert ev["ins_num"] == 1200
    assert ev["auc"] > 0.6, ev["auc"]

    cm = CheckpointManager(str(tmp_path / "ck"))
    cm.save(tr)
    tr2 = mk()
    assert cm.restore(tr2) == tr.global_step
    ev2 = tr2.eval_pass(ds)   # restored state predicts identically
    assert np.isclose(ev2["auc"], ev["auc"], atol=1e-6)
    # restored trainer keeps training
    r = tr2.train_pass(ds)
    assert np.isfinite(r["last_loss"])


@pytest.mark.slow
def test_sharded_resident_scale(mesh, tmp_path):
    """Scale validation (VERDICT r1 weak #3): realistic routing-bucket
    growth — wide key space (little cross-shard dedup), per-device batch
    128, multiple preloaded passes — streaming == resident parity holds
    at sizes where A/A2/K buckets actually grow across passes, and the
    routing plans keep every key."""
    from paddlebox_tpu.train import PassPreloader
    files = generate_criteo_files(str(tmp_path), num_files=4,
                                  rows_per_file=2500,
                                  vocab_per_slot=3000, seed=21)
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.columnar.num_records == 10_000

    def mk():
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0,
                              learning_rate=0.05, mf_learning_rate=0.05)
        table = ShardedEmbeddingTable(N, mf_dim=4,
                                      capacity_per_shard=1 << 15,
                                      cfg=cfg, req_bucket_min=1024,
                                      serve_bucket_min=1024)
        with flags_scope(log_period_steps=10 ** 6):
            return ShardedTrainer(DeepFM(hidden=(32, 16)), table, desc,
                                  mesh, tx=optax.adam(2e-3)), table

    tr_a, _ = mk()
    ra = tr_a.train_pass(ds)
    tr_b, table_b = mk()
    pre = PassPreloader(iter([ds, ds, ds]), table=None,
                        build_fn=tr_b.build_resident_pass)
    pre.start_next()
    results = []
    while True:
        rp = pre.wait()
        if rp is None:
            break
        pre.start_next()
        results.append(tr_b.train_pass_resident(rp))
    assert len(results) == 3
    rb = results[0]
    # pass 1 parity vs streaming (same init, same data, same order)
    assert rb["batches"] == ra["batches"]
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3), (rb["auc"],
                                                        ra["auc"])
    # the wide key space really landed across all shards
    counts = [len(ix) for ix in table_b.indexes]
    assert min(counts) > 0 and sum(counts) > 20_000, counts
    # continued passes keep learning with finite metrics
    assert all(np.isfinite(r["auc"]) for r in results)
    assert results[-1]["auc"] > 0.55


@pytest.mark.slow  # same budget rationale as above
def test_sharded_resident_q8_wire_learns(mesh, tmp_path):
    """The sharded q8 float wire (dense int8 affine + u8 lsc, decoded in
    _decode_wire_step) trains and tracks the f32 wire's AUC."""
    files = generate_criteo_files(str(tmp_path), num_files=2,
                                  rows_per_file=1200, vocab_per_slot=40,
                                  seed=5)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    def mk(wire):
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0,
                              learning_rate=0.1, mf_learning_rate=0.1)
        table = ShardedEmbeddingTable(N, mf_dim=4,
                                      capacity_per_shard=4096, cfg=cfg,
                                      req_bucket_min=256,
                                      serve_bucket_min=256)
        with flags_scope(log_period_steps=10 ** 6):
            return ShardedTrainer(DeepFM(hidden=(32, 32)), table, desc,
                                  mesh, tx=optax.adam(2e-3),
                                  float_wire=wire)

    tr_a = mk("f32")
    tr_b = mk("q8")
    for _ in range(3):
        ra = tr_a.train_pass_resident(ds)
        rb = tr_b.train_pass_resident(ds)
    assert rb["batches"] == ra["batches"]
    assert np.isclose(rb["auc"], ra["auc"], atol=5e-3), (rb["auc"],
                                                         ra["auc"])
    assert rb["auc"] > 0.55


# ---- fused computation-collective sharded step (ISSUE 11) --------------
def _model_digest(tr):
    """Raw-bytes identity (params + packed table + AUC) — the shared
    chunk-parity digest (scripts/scaling_check.py uses the same one)."""
    from paddlebox_tpu.train.checkpoint import sharded_state_digest
    return sharded_state_digest(tr)


@pytest.fixture(scope="module")
def chunk_parity_ds(tmp_path_factory):
    d = tmp_path_factory.mktemp("chunkds")
    files = generate_criteo_files(str(d), num_files=1, rows_per_file=500,
                                  vocab_per_slot=40, seed=29)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def _chunk_trainer(mesh, desc, chunks, zero1=False):
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=4096,
                                  cfg=cfg, req_bucket_min=256,
                                  serve_bucket_min=256)
    with flags_scope(log_period_steps=10 ** 6, a2a_chunks=chunks):
        return ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                              tx=optax.adam(2e-3), zero1=zero1)


def test_a2a_chunked_digest_parity(mesh, chunk_parity_ds):
    """a2a_chunks ∈ {2, 4} reproduce the monolithic (=1) model digest
    BIT-FOR-BIT through train_pass, deterministically across 2 seeded
    runs. chunks=4 over criteo's 26 slots is the uneven-group case
    (26 % 4 != 0: groups of 7/7/6/6)."""
    ds, desc = chunk_parity_ds

    def run(chunks):
        tr = _chunk_trainer(mesh, desc, chunks)
        tr.train_pass(ds)
        return _model_digest(tr)

    want = run(1)
    assert run(1) == want, "monolithic digest not deterministic"
    for chunks in (2, 4):
        got = run(chunks)
        assert got == want, \
            f"a2a_chunks={chunks} diverged from the monolithic schedule"


def test_a2a_chunked_resident_digest_parity(mesh, chunk_parity_ds):
    """The chunked RESIDENT pass (uniform forced sections, grouped wire
    encode, per-schedule fori_loop runner) matches the monolithic
    resident digest bit-for-bit."""
    ds, desc = chunk_parity_ds

    def run(chunks):
        tr = _chunk_trainer(mesh, desc, chunks)
        rp = tr.build_resident_pass(ds)
        if chunks > 1:
            assert rp.sections, "chunked build lost its sections"
        tr.train_pass_resident(rp)
        return _model_digest(tr)

    assert run(2) == run(1)


def test_a2a_chunked_zero1_digest_parity(mesh, chunk_parity_ds):
    """ZeRO-1 variant: the chunked schedule interleaves the push
    exchange with the reduce-scatter/update/all-gather — still
    bit-identical to the monolithic order."""
    ds, desc = chunk_parity_ds

    def run(chunks):
        tr = _chunk_trainer(mesh, desc, chunks, zero1=True)
        tr.train_pass(ds)
        return _model_digest(tr)

    assert run(2) == run(1)


def test_a2a_chunked_fallback_non_qualified_keys(mesh):
    """make_batches keys are NOT slot-qualified (random ids across
    slots): the grouped plan builder must detect it before mutating the
    index and fall back to the monolithic layout — same plan bytes as
    groups=1."""
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    t1 = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                               cfg=cfg, req_bucket_min=8,
                               serve_bucket_min=8)
    t2 = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                               cfg=cfg, req_bucket_min=8,
                               serve_bucket_min=8)
    batches = make_batches(N, seed=71)
    p1 = t1.prepare_global(batches)
    p2 = t2.prepare_global(batches, groups=2)
    assert p2.a2a_sections == () and p2.key_segments is None
    np.testing.assert_array_equal(p1.resp_idx, p2.resp_idx)
    np.testing.assert_array_equal(p1.gather_idx, p2.gather_idx)
    np.testing.assert_array_equal(p1.serve_rows, p2.serve_rows)


def test_a2a_grouped_plan_layout():
    """Grouped plan invariants on slot-qualified batches: sections sum
    to the A/K axes, every key's gather position lands inside its
    group's section, and each section keeps the pad slack."""
    from paddlebox_tpu.data.batch import SlotBatch
    from paddlebox_tpu.ops.seqpool_cvm import slot_group_bounds
    rng = np.random.default_rng(3)
    bs, S, k_pad = 8, 5, 40
    batches = []
    for _ in range(N):
        nk = int(rng.integers(S, k_pad // 2))
        slots = rng.integers(0, S, size=nk)
        keys = (slots * 1000 + rng.integers(1, 200, size=nk)).astype(
            np.uint64)
        segs = np.full(k_pad, bs * S, np.int32)
        ins = np.sort(rng.integers(0, bs, size=nk))
        segs[:nk] = (ins * S + slots).astype(np.int32)
        kp = np.zeros(k_pad, np.uint64)
        kp[:nk] = keys
        batches.append(SlotBatch(
            keys=kp, segments=segs, num_keys=nk,
            dense=rng.normal(size=(bs, 4)).astype(np.float32),
            label=rng.integers(0, 2, bs).astype(np.float32),
            show=np.ones(bs, np.float32),
            clk=np.zeros(bs, np.float32),
            batch_size=bs, num_slots=S))
    table = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=256,
                                  req_bucket_min=8, serve_bucket_min=8)
    c = 2
    p = table.prepare_global(batches, groups=c)
    assert len(p.a2a_sections) == c
    assert sum(p.a2a_sections) == p.req_capacity
    assert sum(p.key_sections) == p.gather_idx.shape[1]
    assert p.slot_sections == tuple(hi - lo for lo, hi
                                    in slot_group_bounds(S, c))
    assert p.key_segments is not None \
        and p.key_segments.shape == p.gather_idx.shape
    a_lo = np.concatenate([[0], np.cumsum(p.a2a_sections)])
    k_lo = np.concatenate([[0], np.cumsum(p.key_sections)])
    s_lo = np.concatenate([[0], np.cumsum(p.slot_sections)])
    for g in range(c):
        sec_gi = p.gather_idx[:, k_lo[g]:k_lo[g + 1]]
        j = sec_gi % p.req_capacity
        assert (j >= a_lo[g]).all() and (j < a_lo[g + 1]).all(), \
            f"group {g} gathers outside its A section"
        sec_seg = p.key_segments[:, k_lo[g]:k_lo[g + 1]]
        real = sec_seg < bs * S
        slots = sec_seg[real] % S
        assert (slots >= s_lo[g]).all() and (slots < s_lo[g + 1]).all()
        # pad slack: the last j of each pair's section serves the
        # sentinel (resp pad), so in-section pad keys read zeros
        assert (p.resp_idx[:, :, a_lo[g + 1] - 1]
                == p.serve_capacity - 1).all()


def test_a2a_probe_reports_and_spans(mesh, chunk_parity_ds):
    """train/a2a_probe: per-chunk a2a/pool seconds with the right
    arity, a sane overlap fraction, the exchange_wait critical-path
    part, and a2a.pull.*/a2a.push spans on the device.a2a lane when a
    trace sink is attached."""
    from paddlebox_tpu.obs import trace
    from paddlebox_tpu.obs.hub import get_hub
    from paddlebox_tpu.obs.trace import ChromeLaneTraceSink
    from paddlebox_tpu.train.a2a_probe import probe_exchange
    from paddlebox_tpu.utils.profiler import ChromeTraceWriter
    ds, desc = chunk_parity_ds
    tr = _chunk_trainer(mesh, desc, 2)
    tr.train_pass(ds)
    w = ChromeTraceWriter()
    sink = ChromeLaneTraceSink(w)
    hub = get_hub()
    hub.add_sink(sink)
    try:
        trace.reset()
        pr = probe_exchange(tr, dataset=ds, reps=1)
    finally:
        hub.remove_sink(sink)
    assert pr["a2a_chunks"] == 2
    assert len(pr["a2a_pull_sec"]) == 2 and len(pr["pool_sec"]) == 2
    assert all(t > 0 for t in pr["a2a_pull_sec"] + pr["pool_sec"])
    assert 0.0 <= pr["exchange_overlap_frac"] <= 1.0
    assert pr["exchange_wait_sec"] >= 0.0
    # the wait part rides the next pass event's critical_path — unless
    # the measured wait was exactly 0 (CPU timing noise can make the
    # monolithic step read slower than chunked by more than the whole
    # exchange; note_pass_part skips zero parts by design)
    parts = trace.consume_pass_parts()
    assert "exchange_wait" in parts or pr["exchange_wait_sec"] == 0.0
    names = {e.get("name") for e in w._events}
    assert {"a2a.pull.0", "a2a.pull.1", "pool.0", "pool.1",
            "a2a.push"} <= names
    lanes = {e.get("args", {}).get("lane") for e in w._events
             if e.get("name", "").startswith("a2a.")}
    assert lanes == {trace.LANE_DEVICE}
