"""ServingModel: base+delta consumption and prediction parity with the
trainer's eval path (the xbox-server role)."""

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.serving import ServingModel
from paddlebox_tpu.train import Trainer


@pytest.fixture()
def trained(tmp_path):
    files = generate_criteo_files(str(tmp_path / "d"), num_files=1,
                                  rows_per_file=600, vocab_per_slot=40,
                                  seed=4)
    desc = DataFeedDesc.criteo(batch_size=64)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg)
    tr = Trainer(CtrDnn(hidden=(16,)), table, desc, tx=optax.adam(1e-2))
    tr.train_pass(ds)
    base = str(tmp_path / "base.npz")
    tr.sync_table()
    table.save_base(base)
    tr.train_pass(ds)
    delta = str(tmp_path / "delta.npz")
    tr.sync_table()
    table.save_delta(delta)
    dense = str(tmp_path / "m")
    tr.save(dense)   # writes m.dense.pkl + m.sparse.npz
    return tr, ds, desc, base, delta, dense + ".dense.pkl"


def test_serving_predicts_like_trainer(trained):
    tr, ds, desc, base, delta, dense = trained
    srv = ServingModel(CtrDnn(hidden=(16,)), desc, mf_dim=4,
                       capacity=1 << 13)
    n_base = srv.load_base(base)
    n_delta = srv.apply_delta(delta)
    assert n_base > 0 and n_delta > 0
    srv.load_dense(dense)

    batch = next(ds.batches())
    preds = srv.predict(batch)
    assert preds.shape == (desc.batch_size,)
    assert np.isfinite(preds).all()

    # oracle: the trainer's own eval forward on the same batch
    from paddlebox_tpu.metrics import init_auc_state
    from paddlebox_tpu.train.step import make_device_batch
    idx = tr.table.prepare_eval(batch)
    dev = make_device_batch(batch, idx)
    _, pred_ref = tr.step_fn.eval(tr.state.table, tr.state.params,
                                  init_auc_state(), dev)
    np.testing.assert_allclose(preds, np.asarray(pred_ref),
                               rtol=1e-4, atol=1e-5)


def test_embed_lookup_known_and_unknown(trained):
    tr, ds, desc, base, delta, dense = trained
    srv = ServingModel(CtrDnn(hidden=(16,)), desc, mf_dim=4,
                       capacity=1 << 13)
    srv.load_base(base)
    srv.apply_delta(delta)
    keys, rows = srv.table.index.items()
    some = keys[:7]
    vals = srv.embed_lookup(np.concatenate(
        [some, np.array([0xDEAD_BEEF_0001], np.uint64)]))
    assert vals.shape == (8, 3 + 4)
    assert np.abs(vals[:7]).sum() > 0       # known keys carry state
    np.testing.assert_array_equal(vals[7], 0)  # unknown → zeros
    # duplicate keys map to identical values
    v2 = srv.embed_lookup(np.array([some[0], some[0]], np.uint64))
    np.testing.assert_array_equal(v2[0], v2[1])


def test_serving_consumes_sharded_save(tmp_path):
    """A pod-trained model (ShardedEmbeddingTable save: per-shard blocks)
    loads into the single-table serving consumer — per-key values match
    the sharded host pull."""
    import numpy as np
    import jax as _jax
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.ps.table import FIELD_COL
    N = 8
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    sh = ShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=64, cfg=cfg)
    keys = np.arange(1, 101, dtype=np.uint64)
    owners = (keys % np.uint64(N)).astype(np.int64)
    data = np.asarray(_jax.device_get(sh.state.data)).copy()
    for s in range(N):
        ks = keys[owners == s]
        rows = sh.indexes[s].assign(ks)
        data[s][rows, FIELD_COL["embed_w"]] = ks.astype(np.float32) * 3
        data[s][rows, FIELD_COL["show"]] = 2.0
    sh.state = type(sh.state).from_logical(data, sh.capacity)
    path = str(tmp_path / "pod.npz")
    n = sh.save_base(path)
    assert n == 100

    t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    assert t.load(path) == 100
    vals = t.host_pull(keys)
    np.testing.assert_allclose(vals[:, 2], keys.astype(np.float32) * 3)
    np.testing.assert_allclose(vals[:, 0], 2.0)
    # unknown key reads zeros after a sharded-format load too
    assert not np.any(t.host_pull(np.array([999999], np.uint64)))
    # merge_model accepts the sharded format as well (stat accumulate)
    t2 = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    r2 = t2.index.assign(keys[:10])
    d2 = np.asarray(_jax.device_get(t2.state.data)).copy()
    d2[r2, 0] = 1.0  # show
    from paddlebox_tpu.ps.table import TableState
    t2.state = TableState.from_logical(d2, t2.capacity)
    assert t2.merge_model(path) == 100
    got = t2.host_pull(keys[:1])
    assert got[0, 0] == 3.0  # 1 + 2 accumulated


# ---------------------------------------------------------------------------
# artifact-layer consumption (artifacts.py, ISSUE 14)
# ---------------------------------------------------------------------------

def _published_chain(tmp_path):
    """A base + two deltas published through BoxPSHelper → ArtifactStore
    from a directly-written table (no training — keys carry their value
    in embed_w so reads are checkable)."""
    import os
    import jax as _jax
    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState

    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)

    def write(lo, hi, scale):
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = t.index.assign(keys)
        data = np.asarray(_jax.device_get(t.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * scale
        data[rows, FIELD_COL["show"]] = 1.0
        t.state = TableState.from_logical(data, t.capacity)
        t._touched[rows] = True

    store = ArtifactStore(str(tmp_path / "registry"))
    helper = BoxPSHelper(t)
    write(1, 51, 2.0)
    v1 = helper.publish_base(store)
    write(40, 61, 3.0)
    v2 = helper.publish_delta(store)
    write(55, 71, 5.0)
    v3 = helper.publish_delta(store)
    return t, store, (v1, v2, v3)


def _srv():
    from paddlebox_tpu.data.schema import DataFeedDesc
    return ServingModel(CtrDnn(hidden=(4,)),
                        DataFeedDesc.criteo(batch_size=16), mf_dim=4,
                        capacity=1 << 10)


def test_apply_delta_verifies_artifact_lineage(tmp_path):
    """Satellite: apply_delta on a managed (published) payload verifies
    parent id + sha256 BEFORE applying — out-of-order, wrong-parent,
    unmanaged-after-adoption, and bit-flipped deltas all refuse
    loudly instead of silently merging."""
    import os
    import pytest as _pytest
    from paddlebox_tpu.artifacts import (ArtifactCorruptError,
                                         ArtifactLineageError)
    t, store, (v1, v2, v3) = _published_chain(tmp_path)
    base = os.path.join(store.version_dir(v1), "sparse.npz")
    d2 = os.path.join(store.version_dir(v2), "sparse_delta.npz")
    d3 = os.path.join(store.version_dir(v3), "sparse_delta.npz")

    srv = _srv()
    srv.load_base(base)
    with _pytest.raises(ArtifactLineageError):
        srv.apply_delta(d3)          # skips v2: out-of-order
    srv.apply_delta(d2)              # lineage order: fine
    srv.apply_delta(d3)
    v = srv.embed_lookup(np.array([1, 45, 70], np.uint64))
    np.testing.assert_allclose(v[:, 2], [2.0, 135.0, 350.0])
    # an unmanaged (manifest-less) delta cannot extend artifact lineage
    raw = str(tmp_path / "raw_delta.npz")
    t._touched[:] = True
    t.save_delta(raw, clear_touched=False)
    with _pytest.raises(ArtifactLineageError):
        srv.apply_delta(raw)
    # a bit-flipped managed delta refuses on sha256
    srv2 = _srv()
    srv2.load_base(base)
    with open(d2, "rb") as fh:
        blob = fh.read()
    with open(d2, "wb") as fh:
        fh.write(blob[:9] + bytes([blob[9] ^ 0xFF]) + blob[10:])
    with _pytest.raises(ArtifactCorruptError):
        srv2.apply_delta(d2)
    # legacy raw-path flow (no adoption, no manifests) stays available
    srv3 = _srv()
    srv3.load_base(raw)                 # raw npz, no MANIFEST beside it
    assert srv3._adopted_aid is None


def test_predict_many_micro_batches_match_predict(trained):
    """predict_many (ISSUE 15): a record stream micro-batched through
    ONE pinned snapshot returns exactly the per-record predictions the
    full-batch forward gives — chunk size capped by
    FLAGS.serving_batch_max, padding filtered out."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data.batch import BatchBuilder
    from paddlebox_tpu.data.record import SlotRecord

    tr, ds, desc, base, delta, dense = trained
    srv = ServingModel(CtrDnn(hidden=(16,)), desc, mf_dim=4,
                       capacity=1 << 13)
    srv.load_base(base)
    srv.apply_delta(delta)
    srv.load_dense(dense)
    keys, _ = srv.table.index.items()
    S = len(desc.sparse_slots)
    rng = np.random.default_rng(3)
    recs = [SlotRecord(
        keys=rng.choice(keys, size=S).astype(np.uint64),
        slot_offsets=np.arange(S + 1, dtype=np.int32),
        dense=rng.normal(size=desc.dense_dim).astype(np.float32),
        label=float(i % 2), show=1.0, clk=float(i % 2))
        for i in range(150)]   # not a multiple of any chunk size
    with flags_scope(serving_batch_max=48):
        got = srv.predict_many(recs)
    assert got.shape == (150,)
    # oracle: full-bucket batches through the plain predict path
    builder = BatchBuilder(desc)
    want = []
    for i in range(0, len(recs), desc.batch_size):
        chunk = recs[i:i + desc.batch_size]
        pred = srv.predict(builder.build(chunk))
        want.append(pred[:len(chunk)])
    np.testing.assert_allclose(got, np.concatenate(want),
                               rtol=1e-5, atol=1e-6)
    # the SlotBatch flavor concatenates per-batch predictions
    b0 = builder.build(recs[:desc.batch_size])
    got_b, valid = srv.predict_many([b0, b0], return_valid=True)
    assert got_b.shape == valid.shape == (2 * desc.batch_size,)
    np.testing.assert_allclose(got_b[:desc.batch_size],
                               srv.predict(b0), rtol=1e-6)


def test_dense_only_reload_reaches_queries(trained):
    """Regression (review): a second load_dense on a model whose
    snapshot already carries params must swap the NEW params into the
    serving snapshot (params-only swap — same frozen table), not serve
    the stale dense net forever."""
    import pickle

    tr, ds, desc, base, delta, dense = trained
    srv = ServingModel(CtrDnn(hidden=(16,)), desc, mf_dim=4,
                       capacity=1 << 13)
    srv.load_base(base)
    srv.load_dense(dense)
    batch = next(ds.batches())
    p1 = srv.predict(batch)
    snap1 = srv.snapshot()
    # perturb the dense params on disk and reload JUST them
    with open(dense, "rb") as fh:
        params, opt = pickle.load(fh)
    import jax
    bumped = jax.tree_util.tree_map(lambda a: a * 1.5, params)
    dense2 = dense + ".v2"
    with open(dense2, "wb") as fh:
        pickle.dump((bumped, opt), fh)
    srv.load_dense(dense2)
    snap2 = srv.snapshot()
    assert snap2 is not snap1
    assert snap2.table is snap1.table  # params-only swap
    p2 = srv.predict(batch)
    assert not np.allclose(p1, p2), (
        "refreshed dense params never reached the query path")


def test_concurrent_readers_across_snapshot_swaps(tmp_path):
    """ISSUE 15 satellite stress test: N query threads hammer the
    serving model while the main thread hot-reloads across ≥2 snapshot
    swaps. Every result must bit-match ONE published version's oracle
    digest (no torn reads), and release()/double-release() stays
    idempotent under concurrent readers."""
    import hashlib
    import threading

    import time

    from paddlebox_tpu.ps.box_helper import BoxPSHelper

    t, store, (v1, v2, v3) = _published_chain(tmp_path)
    probe = np.arange(1, 121, dtype=np.uint64)

    def digest(arr):
        return hashlib.sha256(
            np.ascontiguousarray(arr).tobytes()).hexdigest()

    srv = _srv()
    assert srv.adopt(store, v1) == v1
    stop = threading.Event()
    results, errors = [], []

    def reader():
        try:
            seen = []
            while not stop.is_set():
                snap = srv.snapshot()        # the one fence
                seen.append((snap.aid, digest(snap.lookup(probe))))
            results.append(seen)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(4)]
    for th in threads:
        th.start()
    # swap 1: advance to the tip (v3) under live readers
    time.sleep(0.05)
    assert srv.hot_reload(store) == v3
    time.sleep(0.05)
    # swap 2: a NEW version published mid-traffic, adopted incrementally
    helper = BoxPSHelper(t)
    helper._published_tip = v3
    t._touched[:] = False
    keys = np.arange(100, 121, dtype=np.uint64)
    t.index.assign(keys)
    t._touched[t.index.lookup(keys)] = True
    v4 = helper.publish_delta(store)
    assert srv.hot_reload(store) == v4
    time.sleep(0.05)
    srv.release()      # lease drop mid-traffic: readers keep serving
    srv.release()
    time.sleep(0.05)
    stop.set()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors
    # oracle digests per published version (fresh replay consumers)
    oracle = {}
    for aid in (v1, v2, v3, v4):
        o = _srv()
        o.adopt(store, aid)
        oracle[aid] = digest(o.snapshot().lookup(probe))
        o.release()
    flat = [rec for seen in results for rec in seen]
    assert len(flat) > 100, "stress test barely ran"
    assert all(oracle[aid] == d for aid, d in flat), (
        "a reader saw a state matching NO published version — torn "
        "read across a swap")
    served = {aid for aid, _ in flat}
    assert v1 in served, "readers never saw the pre-swap snapshot"
    assert v4 in served, "readers never reached the final snapshot"
    # concurrent double-release from many threads: idempotent, silent
    rel = [threading.Thread(target=srv.release) for _ in range(6)]
    for th in rel:
        th.start()
    for th in rel:
        th.join()
    assert store.leased_versions() == []
    # and the model still answers (in-memory snapshot outlives leases)
    assert digest(srv.snapshot().lookup(probe)) == oracle[v4]


def test_adopt_and_hot_reload_chain(tmp_path):
    """Store adoption verifies the whole chain, holds the lease, and
    hot_reload applies ONLY the new deltas (or fully re-adopts on a
    diverged lineage)."""
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    t, store, (v1, v2, v3) = _published_chain(tmp_path)
    srv = _srv()
    assert srv.adopt(store) == v3
    assert store.leased_versions() == [v3]
    assert srv.hot_reload(store) is None     # already current
    # publish one more delta; hot reload advances incrementally
    helper = BoxPSHelper(t)
    helper._published_tip = v3
    t._touched[:] = False
    keys = np.arange(100, 111, dtype=np.uint64)
    t.index.assign(keys)
    t._touched[t.index.lookup(keys)] = True
    v4 = helper.publish_delta(store)
    assert srv.hot_reload(store) == v4
    assert store.leased_versions() == [v4]   # old lease swapped out
    srv.release()
    assert store.leased_versions() == []
