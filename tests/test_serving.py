"""ServingModel: base+delta consumption and prediction parity with the
trainer's eval path (the xbox-server role)."""

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.serving import ServingModel
from paddlebox_tpu.train import Trainer


@pytest.fixture()
def trained(tmp_path):
    files = generate_criteo_files(str(tmp_path / "d"), num_files=1,
                                  rows_per_file=600, vocab_per_slot=40,
                                  seed=4)
    desc = DataFeedDesc.criteo(batch_size=64)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg)
    tr = Trainer(CtrDnn(hidden=(16,)), table, desc, tx=optax.adam(1e-2))
    tr.train_pass(ds)
    base = str(tmp_path / "base.npz")
    tr.sync_table()
    table.save_base(base)
    tr.train_pass(ds)
    delta = str(tmp_path / "delta.npz")
    tr.sync_table()
    table.save_delta(delta)
    dense = str(tmp_path / "m")
    tr.save(dense)   # writes m.dense.pkl + m.sparse.npz
    return tr, ds, desc, base, delta, dense + ".dense.pkl"


def test_serving_predicts_like_trainer(trained):
    tr, ds, desc, base, delta, dense = trained
    srv = ServingModel(CtrDnn(hidden=(16,)), desc, mf_dim=4,
                       capacity=1 << 13)
    n_base = srv.load_base(base)
    n_delta = srv.apply_delta(delta)
    assert n_base > 0 and n_delta > 0
    srv.load_dense(dense)

    batch = next(ds.batches())
    preds = srv.predict(batch)
    assert preds.shape == (desc.batch_size,)
    assert np.isfinite(preds).all()

    # oracle: the trainer's own eval forward on the same batch
    from paddlebox_tpu.metrics import init_auc_state
    from paddlebox_tpu.train.step import make_device_batch
    idx = tr.table.prepare_eval(batch)
    dev = make_device_batch(batch, idx)
    _, pred_ref = tr.step_fn.eval(tr.state.table, tr.state.params,
                                  init_auc_state(), dev)
    np.testing.assert_allclose(preds, np.asarray(pred_ref),
                               rtol=1e-4, atol=1e-5)


def test_embed_lookup_known_and_unknown(trained):
    tr, ds, desc, base, delta, dense = trained
    srv = ServingModel(CtrDnn(hidden=(16,)), desc, mf_dim=4,
                       capacity=1 << 13)
    srv.load_base(base)
    srv.apply_delta(delta)
    keys, rows = srv.table.index.items()
    some = keys[:7]
    vals = srv.embed_lookup(np.concatenate(
        [some, np.array([0xDEAD_BEEF_0001], np.uint64)]))
    assert vals.shape == (8, 3 + 4)
    assert np.abs(vals[:7]).sum() > 0       # known keys carry state
    np.testing.assert_array_equal(vals[7], 0)  # unknown → zeros
    # duplicate keys map to identical values
    v2 = srv.embed_lookup(np.array([some[0], some[0]], np.uint64))
    np.testing.assert_array_equal(v2[0], v2[1])
