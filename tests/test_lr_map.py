"""Per-param dense learning rates (lr_map).

Reference: ``InitializeGPUAndLoadModel`` carries a param-name→lr map
(box_wrapper.cc:1303-1335) consumed per parameter by the async dense
table (boxps_worker.cc:199-204). Ours: per-leaf update multipliers
(dense_modes.build_lr_scales / lr_map_transform), native in
AsyncDenseTable, Trainer, and ShardedTrainer (psum + zero1 chunks).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from paddlebox_tpu.train.dense_modes import (AsyncDenseTable,
                                             build_lr_scales,
                                             lr_map_transform)


def _leaf_path(params, idx=0):
    paths = [jax.tree_util.keystr(p) for p, _ in
             jax.tree_util.tree_leaves_with_path(params)]
    return paths[idx]


@pytest.fixture(scope="module")
def ctr_dataset(tmp_path_factory):
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    tmp = str(tmp_path_factory.mktemp("lrmap"))
    files = generate_criteo_files(tmp, num_files=1, rows_per_file=512,
                                  vocab_per_slot=40, seed=41)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def test_lr_pattern_segment_boundaries():
    """'Dense_1' must not match 'Dense_10' (bare substring over-match):
    the rule requires non-identifier boundaries, shared by
    build_lr_scales and AsyncDenseTable."""
    from paddlebox_tpu.train.dense_modes import lr_pattern_matches
    assert lr_pattern_matches("Dense_1", "['params']['Dense_1']['kernel']")
    assert not lr_pattern_matches("Dense_1",
                                  "['params']['Dense_10']['kernel']")
    assert lr_pattern_matches("['Dense_1']['kernel']",
                              "['params']['Dense_1']['kernel']")
    params = {"Dense_1": jnp.ones(2), "Dense_10": jnp.ones(2)}
    scales = build_lr_scales(params, {"Dense_1": 0.0}, 1.0)
    assert scales["Dense_1"] == 0.0 and scales["Dense_10"] == 1.0
    # AsyncDenseTable goes through the same matcher
    t = AsyncDenseTable({"Dense_1": np.ones(2, np.float32),
                         "Dense_10": np.ones(2, np.float32)},
                        lr=1e-3, lr_map={"Dense_1": 0.0})
    t.start()
    t.push({"Dense_1": np.ones(2, np.float32),
            "Dense_10": np.ones(2, np.float32)})
    t.drain()
    t.stop()
    out = t.pull()
    np.testing.assert_array_equal(out["Dense_1"], 1.0)   # frozen
    assert (out["Dense_10"] != 1.0).all()                # trains


def test_lr_map_transform_scales_updates_exactly():
    params = {"w_0": jnp.ones(4), "b_0": jnp.ones(2), "other": jnp.ones(3)}
    base = 0.1
    scales = build_lr_scales(params, {"w_0": 0.0, "b_0": 1.0}, base)
    assert scales["w_0"] == 0.0 and scales["b_0"] == 10.0
    assert scales["other"] == 1.0
    tx = optax.chain(optax.sgd(base), lr_map_transform(scales))
    st = tx.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    upd, _ = tx.update(g, st, params)
    np.testing.assert_allclose(np.asarray(upd["w_0"]), 0.0)
    np.testing.assert_allclose(np.asarray(upd["b_0"]), -1.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(upd["other"]), -0.1, rtol=1e-6)


def test_async_dense_table_lr_map():
    """Frozen param holds exactly; boosted param moves ~10x the default
    (Adam step magnitude ≈ lr on the first update)."""
    params = {"w_0": np.ones(4, np.float32), "b_0": np.ones(2, np.float32),
              "fc": np.ones(3, np.float32)}
    t = AsyncDenseTable(params, lr=1e-3,
                        lr_map={"w_0": 0.0, "b_0": 1e-2})
    t.start()
    g = {"w_0": np.full(4, 0.5, np.float32),
         "b_0": np.full(2, 0.5, np.float32),
         "fc": np.full(3, 0.5, np.float32)}
    t.push(g)
    t.drain()
    t.stop()
    out = t.pull()
    np.testing.assert_array_equal(out["w_0"], 1.0)          # frozen
    d_b = 1.0 - out["b_0"][0]
    d_fc = 1.0 - out["fc"][0]
    assert d_fc > 0
    np.testing.assert_allclose(d_b / d_fc, 10.0, rtol=1e-4)  # boosted 10x


def test_trainer_lr_map_freezes_param(ctr_dataset):
    """Single-chip Trainer: a frozen-lr param stays at init through a
    full pass while the rest train."""
    ds, desc = ctr_dataset
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer
    cfg = SparseSGDConfig(mf_create_thresholds=0.0)

    probe = Trainer(CtrDnn(hidden=(8,)),
                    EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg),
                    desc, tx=optax.adam(1e-2))
    frozen = _leaf_path(probe.state.params)
    tr = Trainer(CtrDnn(hidden=(8,)),
                 EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg),
                 desc, tx=optax.adam(1e-2),
                 lr_map={frozen: 0.0}, lr_map_base=1e-2)
    init = jax.tree_util.tree_leaves_with_path(
        jax.tree.map(np.asarray, tr.state.params))
    tr.train_pass(ds)
    moved = 0
    for (path, before) in init:
        after = np.asarray(dict(jax.tree_util.tree_leaves_with_path(
            tr.state.params))[path])
        if jax.tree_util.keystr(path) == frozen:
            np.testing.assert_array_equal(after, before)
        elif not np.array_equal(after, before):
            moved += 1
    assert moved > 0


@pytest.mark.slow  # seed-broken (no jax.shard_map) until the
# jax_compat shim; recovered, but heavy on the virtual-CPU mesh —
# out of the tier-1 wall budget, runs in the slow tier
@pytest.mark.parametrize("zero1", [False, True])
def test_sharded_trainer_lr_map(ctr_dataset, zero1):
    """Mesh trainer (psum and zero1 flat chunks): frozen param holds at
    init; a boosted param moves farther than under the global lr."""
    ds, desc = ctr_dataset
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer
    assert len(jax.devices()) >= 8
    cfg = SparseSGDConfig(mf_create_thresholds=0.0)

    def mk(lr_map=None):
        t = ShardedEmbeddingTable(8, mf_dim=4, capacity_per_shard=2048,
                                  cfg=cfg, req_bucket_min=128,
                                  serve_bucket_min=128)
        return ShardedTrainer(CtrDnn(hidden=(8,)), t, desc, make_mesh(8),
                              tx=optax.adam(1e-2), seed=3, zero1=zero1,
                              lr_map=lr_map, lr_map_base=1e-2)

    paths = [jax.tree_util.keystr(p) for p, _ in
             jax.tree_util.tree_leaves_with_path(mk().state.params)]
    frozen, boosted = paths[0], paths[-1]
    assert frozen != boosted
    tr = mk({frozen: 0.0, boosted: 5e-2})
    tr_plain = mk()
    init = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
            jax.tree_util.tree_leaves_with_path(tr.state.params)}
    tr.train_pass(ds)
    tr_plain.train_pass(ds)
    after = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
             jax.tree_util.tree_leaves_with_path(tr.state.params)}
    after_plain = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
                   jax.tree_util.tree_leaves_with_path(
                       tr_plain.state.params)}
    np.testing.assert_array_equal(after[frozen], init[frozen])
    assert not np.array_equal(after_plain[frozen], init[frozen])
    d_boost = np.abs(after[boosted] - init[boosted]).mean()
    d_plain = np.abs(after_plain[boosted] - init[boosted]).mean()
    assert d_boost > 2.0 * d_plain, (d_boost, d_plain)
