"""Tier-1 wiring of scripts/obs_check.py — the black-box observability
gate (ISSUE 16): each injected anomaly (NaN rollback with a boundary
checkpoint, corrupt reload tip, pipeline hang, SLO breach via the
alert engine, manual operator dump) yields exactly ONE debounced,
schema-complete postmortem bundle; every default alert rule fires AND
clears with matching events and gauge transitions; JSONL rotation +
torn-tail tolerance hold; the /alertz route serves; and the flags-off
black-box layer stays inert and cheap — deterministic across two
identically-seeded runs. The standalone script prints the full outcome
and exits nonzero on any divergence."""

import os

from scripts.obs_check import BUNDLE_KEYS, run_obs_check


def test_obs_check_gate_deterministic(tmp_path):
    outs = []
    for run in (1, 2):
        wd = str(tmp_path / f"run{run}")
        os.makedirs(wd)
        outs.append(run_obs_check(wd, seed=7))
    out = outs[0]
    # quality leg: a window event per pass, all mirrors present
    assert out["quality_windows"] == 3
    assert out["quality_degraded_flag_seen"]
    assert "pbox_quality_auc_trend" in out["quality_instruments"]
    assert "pbox_quality_key_churn_frac" in out["quality_instruments"]
    # NaN leg: rolled back once, recovered, counter booked
    assert out["nan_retried_and_recovered"]
    assert out["nan_rollbacks_total"] == 1.0
    # corrupt tip: never adopted, degrade was loud
    assert out["corrupt_tip_not_adopted"] and out["corrupt_refused_loud"]
    # hang leg
    assert out["hang_raised"]
    # bundle audit: exactly one bundle per trigger, in seq order, all
    # six anomaly classes represented (the membership rules route to
    # their own membership_change bundle), every bundle schema-complete
    assert out["one_bundle_per_trigger"] and out["bundles_schema_ok"]
    assert out["bundle_triggers"] == [
        "nan_rollback", "reload_degrade", "pipeline_hang",
        "slo_breach", "membership_change", "manual"]
    assert out["bundles"] == sorted(out["bundles"])
    assert out["slo_breach_suppressed"] >= 1.0  # debounce ate the storm
    # alerts: quiet baseline, every default rule fired AND cleared,
    # nothing left firing
    assert out["alerts_baseline_clean"]
    assert out["alerts_all_fired_and_cleared"]
    assert out["alerts_none_left_firing"]
    assert all(v >= 1.0 for v in out["alerts_fired_total"].values())
    # rotation + torn tail
    assert len(out["rotated_set"]) == 3  # live + keep-2
    assert out["rotation_oldest_first"] and out["torn_tail_skipped"]
    # debug routes
    assert out["alertz_ok"] and out["healthz_alerts_block"]
    assert out["metrics_expose_alerts"] and out["metrics_expose_bundles"]
    # flags-off: inert and bounded
    assert out["inert_hub_inactive"] and out["still_inactive_after"]
    assert out["inert_no_recorder"] and out["overhead_ok"]
    # seeded anomalies are reproducible: outcome identical across runs
    assert outs[0] == outs[1]


def test_bundle_keys_frozen():
    # the postmortem bundle contract the gate checks against — a
    # schema drift must be a deliberate, visible change here
    assert BUNDLE_KEYS == frozenset((
        "schema", "trigger", "reason", "ctx", "ts", "run", "health",
        "ring", "instruments", "critical_path", "flags", "threads"))
