"""MoE gate/dispatch tests (reference:
python/paddle/incubate/distributed/models/moe — naive/switch/gshard gates,
alltoall dispatch), run on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.parallel.moe import (moe_forward_local,
                                        moe_forward_sharded, naive_gating,
                                        top1_gating, top2_gating)


def expert_identity_scale(x, scale):
    # x: [C, D]; scale: scalar per expert
    return x * scale


def test_top1_gating_routes_and_caps():
    # 4 tokens all preferring expert 1, capacity 2 → 2 dropped
    logits = jnp.array([[0.0, 5.0]] * 4)
    disp, comb, aux, metrics = top1_gating(logits, capacity=2)
    assert disp.shape == (4, 2, 2)
    assert float(metrics["dropped"]) == 2.0
    # kept tokens occupy distinct capacity slots of expert 1
    kept = np.asarray(disp[:, 1, :]).sum(axis=0)
    np.testing.assert_array_equal(kept, [1.0, 1.0])
    assert float(aux) > 0


def test_top2_gating_weights_sum_to_one():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    disp, comb, aux, _ = top2_gating(logits, capacity=16)
    # with ample capacity every token keeps both choices; combine weights
    # per token sum to 1
    w = np.asarray(comb).sum(axis=(1, 2))
    np.testing.assert_allclose(w, 1.0, rtol=1e-5)


def test_naive_gate_no_drops_no_aux():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    disp, comb, aux, metrics = naive_gating(logits)
    assert float(aux) == 0.0
    assert float(metrics["dropped"]) == 0.0


def test_moe_local_identity_experts_reconstruct():
    """With identity experts (scale=1) and ample capacity, MoE output ==
    input (combine weights sum to 1)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 6)).astype(np.float32))
    gate_w = jnp.asarray(rng.normal(size=(6, 4)).astype(np.float32))
    scales = jnp.ones((4,))
    y, aux = moe_forward_local(x, gate_w, expert_identity_scale, scales,
                               capacity=8, gate="gshard")
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5)


def test_moe_sharded_matches_local():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from jax.sharding import Mesh

    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("ep",))
    E, D, T = 8, 6, 16
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    gate_w = jnp.asarray(rng.normal(size=(D, E)).astype(np.float32))
    scales = jnp.arange(1.0, E + 1.0)

    # drop-free capacities so per-shard routing equals global routing:
    # local sees all T tokens, each shard sees T/n
    y_local, aux_local = moe_forward_local(
        x, gate_w, expert_identity_scale, scales, capacity=T, gate="switch")

    fwd = moe_forward_sharded(mesh, "ep", expert_identity_scale,
                              capacity=T // n, gate="switch")
    with mesh:
        y_sh, aux_sh = jax.jit(fwd)(x, gate_w, scales)
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_local),
                               rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux_sh))
