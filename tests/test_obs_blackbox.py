"""Black-box observability layer (ISSUE 16): flight-recorder bundle
contract (ring, debounce, retention, schema), alert-engine rule
semantics (threshold/absence/trend, hysteresis, events + gauges),
quality-monitor windows, sink fault isolation (quarantine after N
consecutive failures), JSONL size rotation + torn-tail tolerance in
telemetry_report, and a strict Prometheus text-format round-trip over
every instrument family including ``_quantile`` siblings and escaped
label values."""

import glob
import json
import os
import re
import time

import pytest

from paddlebox_tpu.config import FLAGS, flags_scope
from paddlebox_tpu.obs import (AlertEngine, FlightRecorder, JsonlSink,
                               MemorySink, Rule, default_rules, get_hub,
                               reset_hub)
from paddlebox_tpu.obs import flightrec
from paddlebox_tpu.obs.instruments import (SERVING_LATENCY_BUCKETS,
                                           escape_label_value)


@pytest.fixture()
def fresh_hub():
    hub = reset_hub()
    yield hub
    reset_hub()


# ---- flight recorder ---------------------------------------------------
def test_bundle_schema_and_ring(fresh_hub, tmp_path):
    rec = FlightRecorder(str(tmp_path), ring_events=4,
                         debounce_sec=600.0)
    flightrec.install_recorder(rec)
    hub = get_hub()
    for i in range(10):          # ring keeps only the newest 4
        hub.emit("tick", i=i)
    path = flightrec.trigger("manual", reason="unit", extra=7)
    assert path and os.path.isfile(path)
    b = json.load(open(path))
    assert b["schema"] == 1 and b["trigger"] == "manual"
    assert b["reason"] == "unit" and b["ctx"]["extra"] == 7
    ring = [e for e in b["ring"] if e.get("event") == "tick"]
    assert [e["i"] for e in ring] == [6, 7, 8, 9]
    assert b["threads"], "no live thread stacks captured"
    assert "flightrec_ring_events" in b["flags"]
    assert "passes_total" in b["health"]


def test_debounce_and_retention(fresh_hub, tmp_path):
    rec = FlightRecorder(str(tmp_path), debounce_sec=600.0, keep=2)
    flightrec.install_recorder(rec)
    hub = get_hub()
    assert flightrec.trigger("manual", reason="first")
    assert flightrec.trigger("manual", reason="storm") is None
    assert hub.counter("pbox_flightrec_suppressed_total",
                       "").value(trigger="manual") == 1.0
    # distinct triggers debounce independently
    assert flightrec.trigger("pipeline_hang", reason="x")
    assert flightrec.trigger("nan_rollback", reason="y")
    # keep=2: the oldest bundle was swept
    names = [os.path.basename(p) for p in rec.bundles()]
    assert len(names) == 2
    assert names == sorted(names)  # lexical order == age order
    assert "manual" not in "".join(names)


def test_unknown_trigger_rejected(fresh_hub, tmp_path):
    rec = FlightRecorder(str(tmp_path))
    with pytest.raises(ValueError, match="unknown flight-recorder"):
        rec.trigger("not_a_trigger")
    # the MODULE seam never raises — anomaly paths call it bare
    flightrec.install_recorder(rec)
    assert flightrec.trigger("not_a_trigger") is None


def test_trigger_without_recorder_is_noop(fresh_hub):
    assert flightrec.get_recorder() is None
    assert flightrec.trigger("manual", reason="nobody home") is None
    assert not fresh_hub.active


def test_configure_from_flags_installs_once(fresh_hub, tmp_path):
    with flags_scope(flightrec_dir=str(tmp_path)):
        rec = flightrec.configure_from_flags()
        assert rec is not None and flightrec.get_recorder() is rec
        assert flightrec.configure_from_flags() is rec  # idempotent
        assert fresh_hub.active  # recorder sink activates the hub
    reset_hub()
    assert flightrec.get_recorder() is None  # reset detaches


def test_hub_dump_blackbox(fresh_hub, tmp_path):
    rec = FlightRecorder(str(tmp_path))
    flightrec.install_recorder(rec)
    fresh_hub.dump_blackbox("operator said so")
    names = [os.path.basename(p) for p in rec.bundles()]
    assert names == ["blackbox-00001-manual.json"]
    mem = MemorySink()
    fresh_hub.add_sink(mem)
    fresh_hub.dump_blackbox("again")  # debounced: no second bundle
    assert len(rec.bundles()) == 1


# ---- alert engine ------------------------------------------------------
def test_threshold_rule_hysteresis(fresh_hub):
    clk = [100.0]
    eng = AlertEngine(fresh_hub, clock=lambda: clk[0])
    eng.add_rule(Rule(name="lag", metric="lag_files", kind="threshold",
                      op=">", value=10.0, for_count=2, clear_count=2))
    g = fresh_hub.gauge("lag_files", "")
    mem = MemorySink()
    fresh_hub.add_sink(mem)
    g.set(50.0)
    assert eng.evaluate_once() == []      # for_count=2: not yet
    trs = eng.evaluate_once()             # second breach fires
    assert [(t["rule"], t["to"]) for t in trs] == [("lag", "fired")]
    assert fresh_hub.gauge("pbox_alerts_active", "").value(
        rule="lag", severity="warn") == 1.0
    g.set(0.0)
    assert eng.evaluate_once() == []      # clear_count=2: not yet
    trs = eng.evaluate_once()
    assert [(t["rule"], t["to"]) for t in trs] == [("lag", "cleared")]
    assert fresh_hub.gauge("pbox_alerts_active", "").value(
        rule="lag", severity="warn") == 0.0
    evs = [e["event"] for e in mem.events
           if e["event"].startswith("alert_")]
    assert evs == ["alert_fired", "alert_cleared"]
    assert fresh_hub.counter("pbox_alerts_fired_total",
                             "").value(rule="lag") == 1.0


def test_absence_rule(fresh_hub):
    eng = AlertEngine(fresh_hub)
    eng.add_rule(Rule(name="gone", metric="heartbeat_ts",
                      kind="absence"))
    trs = eng.evaluate_once()             # metric never booked → fires
    assert [(t["rule"], t["to"]) for t in trs] == [("gone", "fired")]
    fresh_hub.gauge("heartbeat_ts", "").set(1.0)
    trs = eng.evaluate_once()
    assert [(t["rule"], t["to"]) for t in trs] == [("gone", "cleared")]


def test_trend_rule_on_counter(fresh_hub):
    eng = AlertEngine(fresh_hub)
    eng.add_rule(Rule(name="hangs", metric="hangs_total", kind="trend",
                      op=">", value=0.0, trend_window=2))
    c = fresh_hub.counter("hangs_total", "")
    c.inc(n=0)
    assert eng.evaluate_once() == []      # flat baseline
    c.inc(stage="endpass")
    trs = eng.evaluate_once()             # delta over window > 0
    assert [(t["rule"], t["to"]) for t in trs] == [("hangs", "fired")]
    trs = eng.evaluate_once()             # flat again → clears
    assert [(t["rule"], t["to"]) for t in trs] == [("hangs", "cleared")]


def test_histogram_quantile_rule(fresh_hub):
    eng = AlertEngine(fresh_hub)
    eng.add_rule(Rule(name="p99", metric="lat_seconds",
                      kind="threshold", op=">", value=0.5,
                      quantile=0.99, labels={"op": "predict"}))
    h = fresh_hub.histogram("lat_seconds", "",
                            buckets=SERVING_LATENCY_BUCKETS)
    for _ in range(10):
        h.observe(0.9, op="predict")
    assert [t["to"] for t in eng.evaluate_once()] == ["fired"]
    for _ in range(5000):
        h.observe(0.0002, op="predict")
    assert [t["to"] for t in eng.evaluate_once()] == ["cleared"]


def test_label_subset_sampling(fresh_hub):
    # a rule with labels {"stage": "x"} sums only matching series
    eng = AlertEngine(fresh_hub)
    eng.add_rule(Rule(name="sx", metric="work_total", kind="threshold",
                      op=">", value=5.0, labels={"stage": "x"}))
    c = fresh_hub.counter("work_total", "")
    c.inc(100, stage="y")                 # non-matching series only
    assert eng.evaluate_once() == []
    c.inc(6, stage="x", shard="0")        # superset labels DO match
    assert [t["rule"] for t in eng.evaluate_once()] == ["sx"]


def test_alert_fire_triggers_blackbox(fresh_hub, tmp_path):
    rec = FlightRecorder(str(tmp_path), debounce_sec=600.0)
    flightrec.install_recorder(rec)
    eng = AlertEngine(fresh_hub)
    eng.add_rule(Rule(name="a", metric="m1", kind="threshold", op=">",
                      value=1.0))
    eng.add_rule(Rule(name="b", metric="m2", kind="threshold", op=">",
                      value=1.0))
    fresh_hub.gauge("m1", "").set(9.0)
    fresh_hub.gauge("m2", "").set(9.0)
    eng.evaluate_once()                   # both fire in one sweep
    names = [os.path.basename(p) for p in rec.bundles()]
    assert names == ["blackbox-00001-slo_breach.json"]  # debounced


def test_duplicate_rule_rejected(fresh_hub):
    eng = AlertEngine(fresh_hub)
    eng.add_rule(Rule(name="r", metric="m", kind="threshold"))
    with pytest.raises(ValueError, match="duplicate"):
        eng.add_rule(Rule(name="r", metric="m", kind="threshold"))
    with pytest.raises(ValueError):
        Rule(name="bad", metric="m", kind="nope")
    with pytest.raises(ValueError):
        Rule(name="bad", metric="m", kind="threshold", op="!=")


def test_default_rules_cover_issue_slos():
    names = {r.name for r in default_rules()}
    assert names == {"serving_staleness", "serving_p99", "stream_lag",
                     "pipeline_hang", "nan_rollback",
                     "auc_degradation", "shrink_overdue",
                     "backlog_growth", "rank_dead", "world_degraded"}


def test_alertz_route_and_healthz_block(fresh_hub):
    import urllib.request
    eng = AlertEngine(fresh_hub, rules=default_rules())
    fresh_hub.set_alerts_probe(eng.status)
    fresh_hub.gauge("pbox_serving_staleness_sec", "").set(1e4)
    eng.evaluate_once()
    srv = fresh_hub.start_prom_http(0)
    try:
        port = srv.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/alertz")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 503       # firing alert → 503
        az = json.loads(ei.value.read())
        assert az["firing"] == 1
        assert az["active"][0]["rule"] == "serving_staleness"
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        assert hz["alerts"]["firing"] == 1
        fresh_hub.gauge("pbox_serving_staleness_sec", "").set(0.0)
        eng.evaluate_once()
        az = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/alertz", timeout=5).read())
        assert az["firing"] == 0 and len(az["rules"]) == 10
    finally:
        srv.shutdown()


# ---- sink fault isolation ----------------------------------------------
class _CrashingSink:
    def __init__(self, after=0):
        self.after = after
        self.calls = 0

    def emit(self, ev):
        self.calls += 1
        if self.calls > self.after:
            raise RuntimeError("sink exploded")


def test_crashing_sink_is_isolated_and_quarantined(fresh_hub):
    good = MemorySink()
    bad = _CrashingSink()
    fresh_hub.add_sink(good)
    fresh_hub.add_sink(bad)
    limit = FLAGS.telemetry_sink_errors_max
    for i in range(limit + 5):
        fresh_hub.emit("tick", i=i)
    # the good sink saw EVERY event despite the crashing neighbour
    assert len([e for e in good.events if e["event"] == "tick"]) \
        == limit + 5
    assert fresh_hub.counter("pbox_sink_errors_total", "").value(
        sink="_CrashingSink") == float(limit)
    assert fresh_hub.counter("pbox_sinks_quarantined_total", "").value(
        sink="_CrashingSink") == 1.0
    assert bad.calls == limit             # removed after N failures


def test_sink_failure_count_resets_on_success(fresh_hub):
    flaky = _CrashingSink(after=0)
    fresh_hub.add_sink(flaky)
    limit = FLAGS.telemetry_sink_errors_max
    for i in range(limit - 1):            # one short of quarantine
        fresh_hub.emit("tick", i=i)
    flaky.after = 10 ** 9                 # heals
    fresh_hub.emit("tick", i=-1)          # success resets the streak
    flaky.after = 0                       # breaks again
    for i in range(limit - 1):
        fresh_hub.emit("tick", i=i)
    assert fresh_hub.counter("pbox_sinks_quarantined_total", "").value(
        sink="_CrashingSink") == 0.0      # never hit N CONSECUTIVE


# ---- JSONL rotation + torn tail ----------------------------------------
def test_jsonl_rotation_keeps_k_and_reads_in_order(fresh_hub, tmp_path):
    from scripts.telemetry_report import expand_rotated, load_events
    path = str(tmp_path / "ev.jsonl")
    sink = JsonlSink(path, max_bytes=1500, keep=2)
    for i in range(120):
        sink.emit({"event": "tick", "i": i, "pad": "x" * 40})
    sink.close()
    files = sorted(os.path.basename(f) for f in glob.glob(path + "*"))
    assert files == ["ev.jsonl", "ev.jsonl.1", "ev.jsonl.2"]
    assert expand_rotated(path) == [path + ".2", path + ".1", path]
    seq = [e["i"] for e in load_events(path)]
    assert seq == sorted(seq)             # oldest-first across segments
    assert seq[-1] == 119                 # newest event survives


def test_rotation_via_flags(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with flags_scope(telemetry_jsonl=path, telemetry_jsonl_max_mb=0.001,
                     telemetry_jsonl_keep=2):
        from paddlebox_tpu.obs import hub as hub_mod
        hub = hub_mod.configure_from_flags()
        for i in range(2000):
            hub.emit("tick", i=i, pad="y" * 50)
    reset_hub()
    assert os.path.exists(path + ".1"), "flag-driven rotation inert"


def test_report_tolerates_torn_final_line(tmp_path, capsys):
    from scripts.telemetry_report import load_events
    path = str(tmp_path / "ev.jsonl")
    with open(path, "w") as fh:
        fh.write('{"event": "a", "ts": 1}\n')
        fh.write('{"event": "b", "ts"')   # writer killed mid-write
    evs = load_events(path)
    assert [e["event"] for e in evs] == ["a"]
    assert "torn" in capsys.readouterr().err.lower()
    # a torn line in the MIDDLE (append landed after it) is also
    # skipped, and the events around it survive
    with open(path, "a") as fh:
        fh.write('\n{"event": "c", "ts": 3}\n')
    evs = load_events(path)
    assert [e["event"] for e in evs] == ["a", "c"]


# ---- strict Prometheus round-trip --------------------------------------
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*",?)*)\})?'
    r' (-?(?:[0-9.]+(?:e[+-]?[0-9]+)?|inf)|nan)$', re.IGNORECASE)
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')


def _strict_parse(text):
    """A deliberately strict text-format parser: every sample line must
    match the exposition grammar exactly (escaped label values only),
    every sample must belong to a declared # TYPE family, and no series
    may repeat. Returns {family: {(suffix_name, labelset): value}}."""
    types, samples = {}, {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            continue
        if ln.startswith("# TYPE "):
            _, _, name, kind = ln.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram", "untyped")
            assert name not in types, f"family {name} declared twice"
            types[name] = kind
            continue
        assert not ln.startswith("#"), f"junk comment line: {ln!r}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        name, labels_raw, val = m.groups()
        labels = tuple(_LABEL_RE.findall(labels_raw or ""))
        fam = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[:-len(suf)] in types:
                fam = name[:-len(suf)]
        assert fam in types, f"sample {name} has no # TYPE declaration"
        if types[fam] == "histogram":
            assert fam != name, \
                f"bare sample {name} inside histogram family"
        key = (name, labels)
        assert key not in samples.get(fam, {}), f"dup series {key}"
        samples.setdefault(fam, {})[key] = float(val)
    return types, samples


def test_prom_round_trip_all_families(fresh_hub):
    hub = fresh_hub
    hub.counter("rt_total", "a counter").inc(3, shard="0")
    hub.counter("rt_total", "").inc(2, shard="1")
    hub.gauge("rt_depth", "a gauge").set(7.5, queue="q\\weird\"n\nv")
    h = hub.histogram("rt_lat_seconds", "a histogram",
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 2.0):
        h.observe(v, op="predict")
    # the alert gauge family the dashboards scrape
    eng = AlertEngine(hub)
    eng.add_rule(Rule(name="r1", metric="rt_depth", kind="threshold",
                      op=">", value=1.0))
    eng.evaluate_once()
    from paddlebox_tpu.utils.monitor import STATS
    STATS.add("legacy \"stat\"", 4)       # pbox_stat bridge escaping
    types, samples = _strict_parse(hub.snapshot_prom())

    assert types["rt_total"] == "counter"
    assert types["rt_depth"] == "gauge"
    assert types["rt_lat_seconds"] == "histogram"
    assert types["rt_lat_seconds_quantile"] == "gauge"
    assert types["pbox_alerts_active"] == "gauge"
    # counter series survive with labels intact
    vals = {lbls: v for (n, lbls), v in samples["rt_total"].items()}
    assert vals[(("shard", "0"),)] == 3.0
    assert vals[(("shard", "1"),)] == 2.0
    # the hostile label value round-trips through escaping
    (key, v), = samples["rt_depth"].items()
    assert v == 7.5
    assert dict(key[1])["queue"] == 'q\\\\weird\\"n\\nv'
    # histogram: buckets cumulative, +Inf == count, sum preserved
    hs = samples["rt_lat_seconds"]
    bkt = {dict(lbls)["le"]: v for (n, lbls), v in hs.items()
           if n.endswith("_bucket")}
    assert bkt["0.01"] == 1.0 and bkt["0.1"] == 2.0
    assert bkt["1.0"] == 3.0 and bkt["+Inf"] == 4.0
    (cnt,) = [v for (n, _), v in hs.items() if n.endswith("_count")]
    assert cnt == 4.0
    # _quantile sibling family carries p50/p90/p99 for the labelset
    qs = {dict(lbls)["quantile"]
          for (n, lbls), v in samples["rt_lat_seconds_quantile"].items()}
    assert qs == {"0.5", "0.9", "0.99"}
    # alert gauge exposes rule + severity labels
    (akey, av), = samples["pbox_alerts_active"].items()
    assert dict(akey[1]) == {"rule": "r1", "severity": "warn"}
    assert av == 1.0
    # legacy bridge escaped the hostile stat name
    stat_lbls = [dict(lbls)["name"]
                 for (n, lbls), v in samples["pbox_stat"].items()]
    assert 'legacy \\"stat\\"' in stat_lbls


def test_escape_label_value():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    assert escape_label_value("plain") == "plain"


# ---- quality monitor ---------------------------------------------------
def test_quality_auc_trend_and_degraded_verdict(fresh_hub):
    from paddlebox_tpu.obs.quality import QualityMonitor
    mon = QualityMonitor(window=4, auc_drop=0.01)
    mem = MemorySink()
    fresh_hub.add_sink(mem)
    out = None
    for p, auc in enumerate((0.80, 0.80, 0.70, 0.70)):
        out = mon.note_pass({"kind": "train_pass", "pass_id": p,
                             "auc": auc}, hub=fresh_hub)
    assert out["degraded"] is True        # trailing half clearly worse
    assert out["auc_trend"] == pytest.approx(-0.10)
    assert fresh_hub.gauge("pbox_quality_degraded", "").value() == 1.0
    for p, auc in enumerate((0.70, 0.70, 0.70, 0.70), start=4):
        out = mon.note_pass({"kind": "train_pass", "pass_id": p,
                             "auc": auc}, hub=fresh_hub)
    assert out["degraded"] is False       # flat window: verdict clears
    assert len([e for e in mem.events
                if e["event"] == "quality_window"]) == 8


def test_quality_calibration_buckets(fresh_hub):
    import jax.numpy as jnp
    from paddlebox_tpu.metrics import auc_add_batch, init_auc_state
    from paddlebox_tpu.obs.quality import QualityMonitor
    mon = QualityMonitor(window=2, calib_buckets=4)
    st = init_auc_state()
    preds = jnp.asarray([0.1] * 50 + [0.9] * 50, dtype=jnp.float32)
    labels = jnp.asarray([0.0] * 50 + [1.0] * 50, dtype=jnp.float32)
    st = auc_add_batch(st, preds, labels, jnp.ones(100))
    out = mon.note_pass({"kind": "train_pass", "pass_id": 0,
                         "auc": 0.9, "actual_ctr": 0.5,
                         "predicted_ctr": 0.5},
                        auc_state=st, hub=fresh_hub)
    calib = {c["bucket"]: c for c in out["calibration"]}
    lo = min(calib), max(calib)
    # the low-pred bucket observed ~0 CTR, the high-pred bucket ~1
    assert calib[lo[0]]["observed_ctr"] == pytest.approx(0.0)
    assert calib[lo[1]]["observed_ctr"] == pytest.approx(1.0)
    assert calib[lo[1]]["pred_ctr"] > calib[lo[0]]["pred_ctr"]


def test_quality_pass_seam_inert_when_off(fresh_hub):
    from paddlebox_tpu.obs import quality
    from paddlebox_tpu.obs.hub import emit_pass_event
    mem = MemorySink()
    fresh_hub.add_sink(mem)
    assert FLAGS.quality_window_passes == 0  # the default
    emit_pass_event("train_pass", {"auc": 0.8, "batches": 1,
                                   "examples": 32})
    assert quality.get_monitor() is None
    assert not [e for e in mem.events if e["event"] == "quality_window"]
    with flags_scope(quality_window_passes=2):
        emit_pass_event("train_pass", {"auc": 0.8, "batches": 1,
                                       "examples": 32})
        emit_pass_event("eval_pass", {"auc": 0.8})  # wrong kind: no-op
    assert len([e for e in mem.events
                if e["event"] == "quality_window"]) == 1
