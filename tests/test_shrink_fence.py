"""Feature-lifecycle shrink vs the async end_pass epilogue
(docs/ONLINE.md): aging must never score a row on pre-write-back
counters, and the SSD tier must age alongside host RAM."""

import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.config import FLAGS, flags_scope
from paddlebox_tpu.ps import (EmbeddingTable, HostStore, PassScopedTable,
                              SparseSGDConfig)
from paddlebox_tpu.ps.ssd import SsdTier
from paddlebox_tpu.ps.table import FIELD_COL


def _rows(n, v, mf_dim=2):
    return {f: np.full((n, mf_dim) if f == "embedx_w" else (n,), v,
                       np.float32) for f in
            ("show", "clk", "delta_score", "slot", "embed_w",
             "embed_g2sum", "embedx_w", "embedx_g2sum", "mf_size")}


def test_shrink_fences_draining_epilogue():
    """Regression (PassScopedTable.shrink): a row refreshed by a
    draining async end_pass write-back must not be aged on its stale
    host counters. The shrink fences the epilogue lane first, so the
    write-back lands before any score is computed."""
    with flags_scope(async_end_pass=True):
        hs = HostStore(mf_dim=2, capacity=1 << 12)
        t = PassScopedTable(hs, pass_capacity=64, cfg=SparseSGDConfig())
        key = np.array([7], np.uint64)
        # stale host counters: show=0 scores 0.0 -> below threshold
        hs.update(key, _rows(1, 0.0))

        gate = threading.Event()
        landed = threading.Event()
        orig = hs.update_rows

        def gated_update_rows(*a, **k):
            gate.wait(10)
            orig(*a, **k)
            landed.set()

        hs.update_rows = gated_update_rows
        t.begin_pass(key)
        # a mid-pass shrink is a protocol error, not a silent no-op
        with pytest.raises(RuntimeError):
            t.shrink(delete_threshold=0.5, decay=1.0)
        # train the row hot: show=10 scores 1.0 -> survives threshold
        rows = t.index.lookup(key)
        d = np.asarray(t.state.data).copy()
        d[rows, FIELD_COL["show"]] = 10.0
        t.state = type(t.state).from_logical(d, t.state.capacity)
        t._touched[rows] = True
        t.end_pass()  # dispatches the write-back, blocked on the gate

        out = {}

        def run_shrink():
            out["freed"] = t.shrink(delete_threshold=0.5, decay=1.0)

        th = threading.Thread(target=run_shrink)
        th.start()
        time.sleep(0.2)
        # the fence holds shrink behind the in-flight write-back; had it
        # proceeded, show=0 scores 0.0 < 0.5 and key 7 would be freed
        assert th.is_alive(), "shrink ran past a draining epilogue job"
        gate.set()
        th.join(10)
        assert not th.is_alive()
        assert landed.is_set(), "shrink finished before the write-back"
        assert out["freed"] == 0
        got = hs.fetch(key)
        np.testing.assert_allclose(got["show"], 10.0)


def test_embedding_table_shrink_calls_fence():
    """Base-class audit: EmbeddingTable.shrink drains an attached
    epilogue fence before mutating rows."""
    table = EmbeddingTable(mf_dim=2, capacity=256,
                          cfg=SparseSGDConfig(), unique_bucket_min=64)
    calls = []
    table.fence = lambda: calls.append("fence")
    table.shrink(delete_threshold=0.0, decay=1.0)
    assert calls == ["fence"]


def test_ssd_tier_shrink(tmp_path):
    """SsdTier.shrink decays show/clk/delta_score in place, drops rows
    whose decayed score falls below the threshold, preserves survivors'
    touched bits, and frees fully-dead segments from disk."""
    with flags_scope(ssd_segment_rows=4):
        tier = SsdTier(str(tmp_path / "tier"), width=8)
        keys = np.arange(1, 9, dtype=np.uint64)
        rows = np.zeros((8, 8), np.float32)
        rows[:, 0] = np.arange(8, dtype=np.float32)  # show = 0..7
        rows[:, 4] = 3.5                             # a payload column
        touched = np.zeros(8, bool)
        touched[::2] = True
        tier.append(keys, rows, touched=touched)
        assert len(tier) == 8
        bytes_before = tier.stats()["bytes"]
        # decay 0.5 halves show; score = 0.1 * decayed show = 0.05*show,
        # so threshold 0.2 drops show 0..3 and keeps show 4..7
        dropped = tier.shrink(delete_threshold=0.2, decay=0.5)
        assert dropped == 4
        assert len(tier) == 4
        fk, sub, tch = tier.take(keys)
        order = np.argsort(fk)
        fk, sub, tch = fk[order], sub[order], tch[order]
        np.testing.assert_array_equal(fk, keys[4:])
        np.testing.assert_allclose(sub[:, 0],
                                   np.arange(4, 8, dtype=np.float32) * 0.5)
        np.testing.assert_allclose(sub[:, 4], 3.5)  # payload untouched
        np.testing.assert_array_equal(tch, touched[4:])
        tier.maybe_compact()
        assert tier.stats()["bytes"] <= bytes_before


def test_host_store_shrink_reaches_ssd(tmp_path):
    """HostStore.shrink ages the disk tier too — including when every
    row has been demoted and host RAM is empty (regression: the old
    early-return skipped the tier entirely)."""
    hs = HostStore(mf_dim=2, capacity=1 << 10,
                   ssd_dir=str(tmp_path / "tier"))
    keys = np.arange(10, 20, dtype=np.uint64)
    data = _rows(10, 0.0)
    data["show"] = np.where(keys >= 15, 10.0, 0.0).astype(np.float32)
    hs.update(keys, data)
    assert hs.demote_cold() == 10 and len(hs) == 0
    assert len(hs.ssd) == 10
    # RAM empty: the tier must still age. score(show=0)=0 < 0.5 drops 5
    freed = hs.shrink(delete_threshold=0.5, decay=1.0)
    assert freed == 5
    assert len(hs.ssd) == 5
    got = hs.fetch(np.arange(15, 20, dtype=np.uint64))
    np.testing.assert_allclose(got["show"], 10.0)
