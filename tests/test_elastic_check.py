"""Tier-1 wiring of scripts/elastic_check.py — the elastic membership
churn gate (ISSUE 18): a 4-host virtual-device stream job loses a host
at window 1, regains it at window 3, and loses another to the watchdog
shrink-and-continue rung at window 6; each transition is a coordinated
stop -> survivor consensus -> key%N re-shard -> resume, with
``digest_after == digest`` proving the re-import lossless, a scripted
schedule oracle proving the detection machinery is a training-math
no-op, and a REAL SIGKILL'd peer confirmed by genuine lease TTL. The
standalone script additionally runs the whole scenario twice and
asserts the outcome dict is identical across identically-seeded runs."""

import jax
import pytest

from scripts.elastic_check import (NUM_WINDOWS, RESHARD_AT,
                                   WORLD_SCHEDULE, run_scenario)


@pytest.fixture(scope="module")
def outcome(tmp_path_factory):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (conftest sets "
                    "xla_force_host_platform_device_count)")
    root = tmp_path_factory.mktemp("elastic_gate")
    # 96 rows/window = 1 global step per window at BOTH world sizes —
    # the reduced-N leg; the standalone gate defaults to 192
    return run_scenario(str(root), seed=7, rows=96)


def test_world_follows_membership_schedule(outcome):
    assert outcome["ok"]
    assert outcome["world_schedule"] == WORLD_SCHEDULE
    assert outcome["reshard_count"] == len(RESHARD_AT)


def test_reshards_exactly_at_churn_boundaries(outcome):
    by_window = {r["window"]: r for r in outcome["windows"]}
    for widx, (old_np, new_np) in RESHARD_AT.items():
        rs = by_window[widx]["reshard"]
        assert (rs["old_np"], rs["new_np"]) == (old_np, new_np)
        # lossless re-import: the re-sharded world's digest equals the
        # boundary digest the old world published
        assert rs["digest_after"] == by_window[widx]["digest"]
        assert rs["agreed_step"] == by_window[widx]["step"]
    quiet = set(range(NUM_WINDOWS)) - set(RESHARD_AT)
    assert all("reshard" not in by_window[w] for w in quiet), \
        "spurious re-shard on a false-dead / quiet window"


def test_stream_never_skips_or_repeats_a_window(outcome):
    assert outcome["dataset_order"] == list(range(NUM_WINDOWS))
    assert outcome["restart_pointer_pass"] == NUM_WINDOWS - 1


def test_oracles_and_fault_legs(outcome):
    # unchurned oracle matches through the first re-shard boundary;
    # the scripted schedule oracle matches at EVERY boundary
    assert outcome["oracle_prefix_match"] == [
        w for w in range(NUM_WINDOWS) if w <= min(RESHARD_AT)]
    assert outcome["schedule_oracle_match"] == NUM_WINDOWS
    assert outcome["kv_fault_fired"] == 1
    assert outcome["rendezvous_fault_fired"] == 1


def test_watchdog_and_sigkill_legs(outcome):
    assert outcome["watchdog_evicted"] == [["h3", "stale"]] or \
        outcome["watchdog_evicted"] == [("h3", "stale")]
    assert outcome["sigkill_lost"] == ["px"]
    assert outcome["sigkill_survivors"] == ["m0"]
    assert outcome["sigkill_hysteresis_held"]
