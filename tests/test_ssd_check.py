"""Tier-1 wiring of scripts/ssd_check.py — the SSD third-tier gates
(ISSUE 7): a capped-host tiered job whose working set exceeds
``host_store_capacity`` demotes and promotes rows through the SSD
segment tier and still reproduces the uncapped oracle's full-model
digest bit-for-bit (deterministic across two runs), and the overlapped
stage keeps the LoadSSD2Mem promote wait off the begin_pass critical
path. The standalone script runs bigger variants; these are the fast
non-slow gates."""

from scripts.ssd_check import run_overlap_check, run_ssd_check


def test_ssd_check_gate():
    out = run_ssd_check(passes=5, shards=2, keys_per_set=384,
                        host_capacity=260, window_cap=224)
    assert out["ok"]
    assert out["ssd"]["demoted_rows"] > 0
    assert out["ssd"]["promoted_rows"] > 0
    assert out["ssd"]["live_rows"] > 0   # the model genuinely exceeds RAM
    assert out["digest"]


def test_ssd_overlap_gate():
    out = run_overlap_check(passes=4, keys_per_set=1536,
                            host_capacity=1000, window_cap=850,
                            train_sleep=0.12)
    assert out["ok"]
    assert out["wait_overlap_sec"] <= 0.5 * out["wait_sync_sec"]
    assert out["promoted_rows"] > 0
