"""TCP global-shuffle transport (distributed/shuffle.py) — the
PaddleShuffler/ShuffleData analogue, tested multi-rank on localhost
(the reference's own strategy for distributed tests, SURVEY.md §4)."""

import threading

import numpy as np

from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.distributed.shuffle import (TcpShuffler, default_route,
                                               deserialize_records,
                                               serialize_records)


def rec(i: int, uid: int = 0, ins: str = "") -> SlotRecord:
    return SlotRecord(
        keys=np.array([i, i + 100], np.uint64),
        slot_offsets=np.array([0, 1, 2], np.int32),
        dense=np.array([i * 0.5, 1.0], np.float32),
        label=float(i % 2), show=1.0, clk=float(i % 2),
        ins_id=ins, uid=uid, search_id=i, timestamp=1000 + i,
        rank=i % 3, cmatch=222)


def test_serialize_roundtrip():
    recs = [rec(i, uid=i * 7, ins=f"ins{i}") for i in range(5)]
    recs.append(SlotRecord(keys=np.empty(0, np.uint64),
                           slot_offsets=np.array([0], np.int32),
                           dense=np.empty(0, np.float32)))
    out = deserialize_records(serialize_records(recs))
    assert len(out) == 6
    for a, b in zip(recs, out):
        np.testing.assert_array_equal(a.keys, b.keys)
        np.testing.assert_array_equal(a.slot_offsets, b.slot_offsets)
        np.testing.assert_allclose(a.dense, b.dense)
        assert (a.label, a.show, a.clk) == (b.label, b.show, b.clk)
        assert (a.ins_id, a.uid, a.search_id) == (b.ins_id, b.uid,
                                                  b.search_id)
        assert (a.timestamp, a.rank, a.cmatch) == (b.timestamp, b.rank,
                                                   b.cmatch)


def test_route_deterministic_and_uid_sticky():
    a, b = rec(1, uid=42), rec(2, uid=42)
    assert default_route(a, 4, 0) == default_route(b, 4, 0)
    c = rec(3, ins="same"), rec(4, ins="same")
    assert default_route(c[0], 4, 7) == default_route(c[1], 4, 7)
    # seed changes placement for at least some records
    recs = [rec(i, uid=i) for i in range(64)]
    r0 = [default_route(r, 4, 0) for r in recs]
    r1 = [default_route(r, 4, 1) for r in recs]
    assert r0 != r1


def _mk_shufflers(world):
    shs = []
    for r in range(world):
        shs.append(TcpShuffler(r, world,
                               ["127.0.0.1:0"] * world, seed=3))
    eps = [("127.0.0.1", s.bound_port) for s in shs]
    for s in shs:
        s.endpoints = eps
    return shs


def test_tcp_exchange_three_ranks():
    world = 3
    shs = _mk_shufflers(world)
    per_rank = [[rec(100 * r + i, uid=100 * r + i) for i in range(40)]
                for r in range(world)]
    results = [None] * world
    errs = []

    def run(r):
        try:
            results[r] = shs[r].exchange(list(per_rank[r]))
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for s in shs:
        s.close()
    assert not errs
    # every record landed exactly once, on the rank its hash names
    seen = {}
    for r in range(world):
        for x in results[r]:
            assert default_route(x, world, 3) == r
            key = int(x.search_id)
            assert key not in seen
            seen[key] = r
    assert len(seen) == world * 40


def test_tcp_exchange_rounds_without_barrier():
    """A fast rank may enter round r+1 while a slow peer still collects
    round r — the early payload must be buffered, not fatal."""
    import time
    world = 3
    shs = _mk_shufflers(world)
    totals = [0] * world
    errs = []

    def run(r):
        try:
            for rnd in range(3):
                if r == 2 and rnd == 0:
                    time.sleep(0.3)  # rank 2 lags; 0/1 finish + advance
                out = shs[r].exchange(
                    [rec(10_000 * rnd + 100 * r + i, uid=100 * r + i)
                     for i in range(30)])
                totals[r] += len(out)
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for s in shs:
        s.close()
    assert not errs, errs
    assert sum(totals) == 3 * world * 30


def test_tcp_exchange_two_rounds_reuse():
    world = 2
    shs = _mk_shufflers(world)
    for rnd in range(2):
        results = [None] * world
        def run(r):
            results[r] = shs[r].exchange(
                [rec(1000 * rnd + 10 * r + i, uid=i) for i in range(10)])
        ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sum(len(x) for x in results) == 20
    for s in shs:
        s.close()
