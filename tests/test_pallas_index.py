"""Device-resident key index gates (ISSUE 19).

Parity matrix for the ops/pallas_index open-addressing hash table and
the ``FLAGS.use_pallas_index`` dispatch seam:

- split/join 64-bit key halves roundtrip, including ids >= 2**32;
- device first-seen dedup (ops/device_unique) is BITWISE against the
  pure-python oracle (_dedup_first_seen_py) across collision-heavy,
  zipf, uniform-distinct and hi-bits-collide-mod-2^32 streams;
- the native one-pass dedup (kv_dedup_first_seen) matches the same
  oracle (skipped when the library isn't buildable);
- insert's Pallas and XLA formulations return identical rows/new/
  overflow and each can read the other's bucket arrays;
- probe and capacity overflow return None with the index state
  UNCHANGED (functional rollback) — the seam's host fallback never
  sees a half-committed table;
- scatter_add_update Pallas vs XLA parity, including dropped -1/OOB
  rows;
- EmbeddingTable.bulk_assign_unique flag-on reproduces flag-off rows/
  inverse/slot metadata exactly over multiple passes, overflow
  degrades LOUDLY (warning + host dispatch booked) without changing
  results, and ShardedEmbeddingTable.prepare_global/_eval flag parity
  holds including the free-list-hole degrade path.
"""

import logging

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.ops import pallas_index as pix
from paddlebox_tpu.ops.device_unique import dedup_keys_first_seen
from paddlebox_tpu.ps import EmbeddingTable
from paddlebox_tpu.ps.kv import dedup_first_seen_native, make_kv
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.ps.table import _dedup_first_seen_py, dedup_first_seen


def _make_streams():
    rng = np.random.default_rng(7)
    base = rng.integers(1, 2 ** 31, size=200).astype(np.uint64)
    return {
        # small vocab -> heavy duplicate + hash-collision pressure
        "collision_heavy": rng.integers(1, 40, size=400).astype(np.uint64),
        "zipf": np.minimum(rng.zipf(1.3, size=500), 4000).astype(np.uint64),
        "uniform_distinct": rng.choice(
            np.arange(1, 1 << 20, dtype=np.uint64), 300, replace=False),
        # ids identical mod 2^32 — a 32-bit-truncating hash or compare
        # would alias every pair
        "hi64_collide_mod32": np.concatenate(
            [base, base | (np.uint64(1) << np.uint64(33))]),
    }


STREAMS = _make_streams()


# ---------------------------------------------------------------------------
# key split/join + device dedup vs the python oracle
# ---------------------------------------------------------------------------

def test_split_join_roundtrip():
    vals = np.array([0, 1, (1 << 32) - 1, 1 << 32, (1 << 33) | 5,
                     0x8000000000000000, (1 << 64) - 1], np.uint64)
    hi, lo = pix.split_keys(vals)
    np.testing.assert_array_equal(pix.join_keys(hi, lo), vals)


@pytest.mark.parametrize("name", sorted(STREAMS))
def test_device_dedup_matches_oracle(name):
    keys = STREAMS[name]
    uniq_o, first_o, inv_o = _dedup_first_seen_py(keys)
    hi, lo = pix.split_keys(keys)
    uh, ul, first, inv, nu = dedup_keys_first_seen(
        jnp.asarray(pix._pad_to_block(hi)),
        jnp.asarray(pix._pad_to_block(lo)), jnp.int32(len(keys)))
    u = int(nu)
    assert u == len(uniq_o)
    np.testing.assert_array_equal(
        pix.join_keys(np.asarray(uh[:u]), np.asarray(ul[:u])), uniq_o)
    np.testing.assert_array_equal(np.asarray(first[:u]), first_o)
    np.testing.assert_array_equal(np.asarray(inv[:len(keys)]), inv_o)


def test_device_dedup_empty():
    z = jnp.zeros(pix._BK, jnp.int32)
    *_, nu = dedup_keys_first_seen(z, z, jnp.int32(0))
    assert int(nu) == 0


def test_native_dedup_matches_oracle():
    if dedup_first_seen_native(STREAMS["zipf"]) is None:
        pytest.skip("native kv library unavailable")
    for name, keys in STREAMS.items():
        uniq_o, first_o, inv_o = _dedup_first_seen_py(keys)
        uniq, first, inv = dedup_first_seen_native(keys)
        np.testing.assert_array_equal(uniq, uniq_o, err_msg=name)
        np.testing.assert_array_equal(first, first_o, err_msg=name)
        np.testing.assert_array_equal(inv, inv_o, err_msg=name)


def test_dedup_first_seen_public_route_matches_oracle():
    """The seam everyone calls (native when buildable, python
    otherwise) is bitwise against the oracle either way."""
    for name, keys in STREAMS.items():
        uniq_o, first_o, inv_o = _dedup_first_seen_py(keys)
        uniq, first, inv = dedup_first_seen(keys)
        np.testing.assert_array_equal(uniq, uniq_o, err_msg=name)
        np.testing.assert_array_equal(first, first_o, err_msg=name)
        np.testing.assert_array_equal(inv, inv_o, err_msg=name)


# ---------------------------------------------------------------------------
# insert/lookup: Pallas vs XLA formulations
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(STREAMS))
def test_insert_pallas_vs_xla_identical(name):
    uniq, _, _ = _dedup_first_seen_py(STREAMS[name])
    n = len(uniq)
    hi, lo = pix.split_keys(uniq)
    kh = jnp.asarray(pix._pad_to_block(hi))
    kl = jnp.asarray(pix._pad_to_block(lo))
    nb = max(pix._BK * 2, 1 << int(2 * n - 1).bit_length())
    outs = {}
    for up in (True, False):
        bh = jnp.zeros(nb, jnp.int32)
        bl = jnp.zeros(nb, jnp.int32)
        br = jnp.full(nb, -1, jnp.int32)
        bh, bl, br, rows, new, ovf = pix.insert(
            bh, bl, br, kh, kl, jnp.int32(n), jnp.int32(0), use_pallas=up)
        outs[up] = (np.asarray(rows[:n]), np.asarray(new[:n]), bool(ovf),
                    (bh, bl, br))
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    np.testing.assert_array_equal(outs[True][1], outs[False][1])
    assert not outs[True][2] and not outs[False][2]
    np.testing.assert_array_equal(outs[True][0], np.arange(n))
    # cross-impl: a table built by one formulation is readable by the
    # other (same hash, same probe order, same layout)
    for built, probed in ((True, False), (False, True)):
        bh, bl, br = outs[built][3]
        rows = pix.lookup(bh, bl, br, kh, kl, jnp.int32(n),
                          use_pallas=probed)
        np.testing.assert_array_equal(np.asarray(rows[:n]), outs[built][0])


# ---------------------------------------------------------------------------
# DeviceKeyIndex: raw-id front door, misses, overflow rollback
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(STREAMS))
def test_assign_raw_matches_oracle(name):
    keys = STREAMS[name]
    uniq_o, first_o, inv_o = _dedup_first_seen_py(keys)
    dev = pix.DeviceKeyIndex(len(uniq_o) + 8)
    uniq, first, inv, rows, new = dev.assign_raw(keys)
    np.testing.assert_array_equal(uniq, uniq_o)
    np.testing.assert_array_equal(first, first_o)
    np.testing.assert_array_equal(inv, inv_o)
    np.testing.assert_array_equal(rows, np.arange(len(uniq_o)))
    assert new.all() and dev.next_row == len(uniq_o)
    # re-assign is stable: same rows, nothing new
    _, _, _, rows2, new2 = dev.assign_raw(keys)
    np.testing.assert_array_equal(rows2, rows)
    assert not new2.any() and dev.next_row == len(uniq_o)
    # lookup agrees; unseen keys (and pads) miss with -1
    np.testing.assert_array_equal(dev.lookup_rows(uniq_o),
                                  np.arange(len(uniq_o)))
    miss = np.array([1 << 60, (1 << 60) + 1], np.uint64)
    np.testing.assert_array_equal(dev.lookup_rows(miss), [-1, -1])


def test_assign_raw_empty():
    dev = pix.DeviceKeyIndex(16)
    uniq, first, inv, rows, new = dev.assign_raw(np.zeros(0, np.uint64))
    assert (len(uniq), len(first), len(inv), len(rows), len(new)) == \
        (0, 0, 0, 0, 0)
    assert dev.next_row == 0
    assert len(dev.lookup_rows(np.zeros(0, np.uint64))) == 0


def test_probe_overflow_rolls_back():
    # 600 distinct keys cannot fit 512 buckets: insert must flag
    # overflow and assign_unique must leave the index UNTOUCHED
    dev = pix.DeviceKeyIndex(1024, n_buckets=512)
    before = np.asarray(dev.br).copy()
    assert dev.assign_unique(np.arange(1, 601, dtype=np.uint64)) is None
    assert dev.next_row == 0
    np.testing.assert_array_equal(np.asarray(dev.br), before)
    # the untouched state still serves a small assign
    out = dev.assign_unique(np.arange(1, 9, dtype=np.uint64))
    assert out is not None and dev.next_row == 8


def test_capacity_overflow_rolls_back():
    dev = pix.DeviceKeyIndex(4)
    assert dev.assign_raw(np.arange(1, 11, dtype=np.uint64)) is None
    assert dev.next_row == 0
    out = dev.assign_raw(np.array([5, 6], np.uint64))
    assert out is not None and dev.next_row == 2


def test_seed_from_kv_dense_vs_holes():
    kv = make_kv(64)
    keys = np.array([11, 22, 33, 44, 55], np.uint64)
    kv.assign(keys)
    dev = pix.DeviceKeyIndex(64)
    assert dev.seed_from_kv(kv)
    k, r = kv.items()
    np.testing.assert_array_equal(dev.lookup_rows(k), r.astype(np.int64))
    # a free-list hole (released non-terminal row) kills density — no
    # fresh mirror can reproduce the kv's row layout by insertion order
    kv.release(np.array([22], np.uint64))
    assert not pix.DeviceKeyIndex(64).seed_from_kv(kv)


# ---------------------------------------------------------------------------
# scatter_add_update
# ---------------------------------------------------------------------------

def test_scatter_add_update_parity():
    rng = np.random.default_rng(11)
    C, D, U = 70, 8, 33
    vals = rng.normal(size=(C, D)).astype(np.float32)
    deltas = rng.normal(size=(U, D)).astype(np.float32)
    # duplicate-free rows spanning negative, in-range, and >= C —
    # out-of-range rows must DROP on both impls
    rows = (rng.choice(C + 20, size=U, replace=False).astype(np.int32)
            - 10)
    ref = vals.copy()
    for i, r in enumerate(rows):
        if 0 <= r < C:
            ref[r] += deltas[i]
    got_p = np.asarray(pix.scatter_add_update(
        jnp.asarray(vals), jnp.asarray(rows), jnp.asarray(deltas),
        use_pallas=True))
    got_x = np.asarray(pix.scatter_add_update(
        jnp.asarray(vals), jnp.asarray(rows), jnp.asarray(deltas),
        use_pallas=False))
    np.testing.assert_array_equal(got_p, ref)
    np.testing.assert_array_equal(got_x, ref)


# ---------------------------------------------------------------------------
# EmbeddingTable seam: flag parity, loud degrade, lifecycle reset
# ---------------------------------------------------------------------------

def _bulk_stream(rng, n, vocab):
    keys = rng.integers(1, vocab, size=n).astype(np.uint64)
    return keys, (keys % 7).astype(np.int64)


def test_table_bulk_assign_flag_parity():
    rng = np.random.default_rng(5)
    passes = [_bulk_stream(rng, 400, 900) for _ in range(3)]

    def run(flag):
        t = EmbeddingTable(mf_dim=4, capacity=1 << 11,
                           unique_bucket_min=64)
        outs = []
        with flags_scope(use_pallas_index=flag):
            for keys, slots in passes:
                rows, inv = t.bulk_assign_unique(keys, slots)
                outs.append((rows.copy(), inv.copy()))
        return t, outs

    t0, o0 = run(False)
    t1, o1 = run(True)
    for (r0, i0), (r1, i1) in zip(o0, o1):
        np.testing.assert_array_equal(r1, r0)
        np.testing.assert_array_equal(i1, i0)
    np.testing.assert_array_equal(t1.slot_host, t0.slot_host)
    k0, v0 = t0.index.items()
    k1, v1 = t1.index.items()
    s0, s1 = np.argsort(k0), np.argsort(k1)
    np.testing.assert_array_equal(k1[s1], k0[s0])
    np.testing.assert_array_equal(v1[s1], v0[s0])
    # the device mirror tracked the host kv exactly
    dev = t1._dev_index
    assert dev is not None and not dev.degraded
    assert dev.next_row == len(t1.index)
    np.testing.assert_array_equal(dev.lookup_rows(k1),
                                  v1.astype(np.int64))


def test_table_seam_overflow_degrades_loudly():
    from paddlebox_tpu.obs import MemorySink
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    rng = np.random.default_rng(9)
    keys = rng.choice(np.arange(1, 1 << 30, dtype=np.uint64), 700,
                      replace=False)
    slots = (keys % 5).astype(np.int64)
    t0 = EmbeddingTable(mf_dim=4, capacity=1 << 11)
    with flags_scope(use_pallas_index=False):
        r0, i0 = t0.bulk_assign_unique(keys, slots)

    reset_hub()
    hub = get_hub()
    hub.add_sink(MemorySink())
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logging.getLogger("paddlebox_tpu").addHandler(handler)
    try:
        t1 = EmbeddingTable(mf_dim=4, capacity=1 << 11)
        # plant a crippled mirror: 512 buckets cannot hold 700 uniques,
        # so the first bulk assign probe-overflows
        t1._dev_index = pix.DeviceKeyIndex(t1.capacity, n_buckets=512)
        with flags_scope(use_pallas_index=True):
            r1, i1 = t1.bulk_assign_unique(keys, slots)
            r2, _ = t1.bulk_assign_unique(keys, slots)  # sticky
        c = hub.counter("pbox_kernel_dispatch_total")
        assert c.value(kernel="index.assign", impl="host") >= 2
    finally:
        logging.getLogger("paddlebox_tpu").removeHandler(handler)
        reset_hub()
    np.testing.assert_array_equal(r1, r0)
    np.testing.assert_array_equal(i1, i0)
    np.testing.assert_array_equal(r2, r0)
    assert t1._dev_index.degraded
    assert "overflow" in t1._dev_index.degrade_reason
    assert any("degraded" in rec.getMessage() for rec in records), \
        "degrade was silent — must warn"


def test_table_reset_dev_index_reseeds():
    t = EmbeddingTable(mf_dim=4, capacity=1 << 10)
    keys = np.arange(1, 301, dtype=np.uint64)
    slots = np.zeros(300, np.int64)
    with flags_scope(use_pallas_index=True):
        r1, _ = t.bulk_assign_unique(keys, slots)
        assert t._dev_index is not None and not t._dev_index.degraded
        # lifecycle mutation hook (load/merge/shrink call this): the
        # mirror drops and re-seeds from the dense kv on next use
        t._reset_dev_index()
        assert t._dev_index is None
        r2, _ = t.bulk_assign_unique(keys, slots)
    np.testing.assert_array_equal(r2, r1)
    dev = t._dev_index
    assert dev is not None and not dev.degraded
    assert dev.next_row == len(t.index)


# ---------------------------------------------------------------------------
# ShardedEmbeddingTable seam
# ---------------------------------------------------------------------------

def _sharded_batches(n, bs=8, S=3, k_pad=32, seed=0):
    from paddlebox_tpu.data.batch import SlotBatch
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        nk = int(rng.integers(S, k_pad // 2))
        keys = rng.choice(np.arange(1, 2000, dtype=np.uint64), nk,
                          replace=False)
        kp = np.zeros(k_pad, np.uint64)
        kp[:nk] = keys
        segs = np.full(k_pad, bs * S, np.int32)
        segs[:nk] = np.sort(rng.integers(0, bs * S, size=nk)
                            .astype(np.int32))
        out.append(SlotBatch(
            keys=kp, segments=segs, num_keys=nk,
            dense=rng.normal(size=(bs, 4)).astype(np.float32),
            label=rng.integers(0, 2, bs).astype(np.float32),
            show=np.ones(bs, np.float32),
            clk=rng.integers(0, 2, bs).astype(np.float32),
            batch_size=bs, num_slots=S))
    return out


def _fields(x):
    if hasattr(x, "_asdict"):
        return x._asdict()
    return vars(x)


def _assert_plan_equal(got, want):
    vg, vw = _fields(got), _fields(want)
    assert vg.keys() == vw.keys()
    for k in vg:
        g, w = vg[k], vw[k]
        if isinstance(w, np.ndarray) or hasattr(w, "dtype"):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w),
                                          err_msg=k)
        elif isinstance(w, (list, tuple)):
            assert len(g) == len(w), k
            for a, b in zip(g, w):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b), err_msg=k)
        else:
            assert g == w, k


def _mk_sharded():
    return ShardedEmbeddingTable(2, mf_dim=4, capacity_per_shard=256,
                                 req_bucket_min=8, serve_bucket_min=8)


def test_sharded_prepare_flag_parity():
    def run(flag):
        t = _mk_sharded()
        with flags_scope(use_pallas_index=flag):
            plan = t.prepare_global(_sharded_batches(2, seed=3))
            # eval/read-only path: lookups only, misses stay misses
            ev = t.prepare_global_eval(_sharded_batches(2, seed=4))
        return t, plan, ev

    t0, p0, e0 = run(False)
    t1, p1, e1 = run(True)
    _assert_plan_equal(p1, p0)
    _assert_plan_equal(e1, e0)
    for s in range(2):
        k0, r0 = t0.indexes[s].items()
        k1, r1 = t1.indexes[s].items()
        o0, o1 = np.argsort(k0), np.argsort(k1)
        np.testing.assert_array_equal(k1[o1], k0[o0])
        np.testing.assert_array_equal(r1[o1], r0[o0])
        np.testing.assert_array_equal(t1._touched[s], t0._touched[s])
        dev = t1._dev_indexes[s]
        assert dev is not None and not dev.degraded
        assert dev.next_row == len(t1.indexes[s])


def test_sharded_holes_degrade_loudly():
    def run(flag):
        t = _mk_sharded()
        with flags_scope(use_pallas_index=flag):
            t.prepare_global(_sharded_batches(2, seed=5))
            # punch free-list holes behind the mirrors' back: release
            # the EARLIEST row in each shard so the kv stops being dense
            for s in range(2):
                keys, rows = t.indexes[s].items()
                victim = keys[np.argsort(rows)[0]]
                t.indexes[s].release(np.array([victim], np.uint64))
            plan = t.prepare_global(_sharded_batches(2, seed=6))
        return t, plan

    t0, p0 = run(False)
    t1, p1 = run(True)
    _assert_plan_equal(p1, p0)
    for s in range(2):
        k0, r0 = t0.indexes[s].items()
        k1, r1 = t1.indexes[s].items()
        o0, o1 = np.argsort(k0), np.argsort(k1)
        np.testing.assert_array_equal(k1[o1], k0[o0])
        np.testing.assert_array_equal(r1[o1], r0[o0])
    assert any(
        t1._dev_indexes[s] is not None and t1._dev_indexes[s].degraded
        for s in range(2)), "no shard mirror degraded after kv holes"
