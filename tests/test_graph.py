"""Graph store / sampling tests (reference: heter_ps graph PS —
gpu_graph_node.h:35, graph_gpu_ps_table.h:128, test_graph.cu)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.graph import (GraphDataGenerator, GraphStore,
                                 random_walk, sample_neighbors)


@pytest.fixture(scope="module")
def mesh8():
    from paddlebox_tpu.parallel import make_mesh
    assert len(jax.devices()) >= 8, "conftest provides 8 CPU devices"
    return make_mesh(8)


def star_graph():
    # 0 -> {1,2,3}; 1 -> {0}; 2 -> {0}; 3 -> {0}; 4 isolated
    src = np.array([0, 0, 0, 1, 2, 3])
    dst = np.array([1, 2, 3, 0, 0, 0])
    return GraphStore.from_edges(src, dst, n_nodes=5)


def test_from_edges_csr():
    g = star_graph()
    assert g.n_nodes == 5
    np.testing.assert_array_equal(g.degree(), [3, 1, 1, 1, 0])
    np.testing.assert_array_equal(
        sorted(g.indices[g.indptr[0]:g.indptr[1]]), [1, 2, 3])


def test_symmetric_edges():
    g = GraphStore.from_edges(np.array([0]), np.array([1]), n_nodes=2,
                              symmetric=True)
    np.testing.assert_array_equal(g.degree(), [1, 1])


def test_sample_neighbors_valid_and_padded():
    g = star_graph()
    indptr, indices = g.to_device()
    nodes = jnp.array([0, 1, 4], dtype=jnp.int32)
    out = np.asarray(sample_neighbors(indptr, indices, nodes, 8,
                                      jax.random.PRNGKey(0)))
    assert out.shape == (3, 8)
    assert set(out[0]).issubset({1, 2, 3})   # node 0's neighbors
    assert (out[1] == 0).all()               # node 1 -> only 0
    assert (out[2] == -1).all()              # isolated -> padded


def test_sample_neighbors_jits():
    g = star_graph()
    indptr, indices = g.to_device()
    f = jax.jit(sample_neighbors, static_argnums=(3,))
    out = f(indptr, indices, jnp.array([0, 1]), 4, jax.random.PRNGKey(1))
    assert out.shape == (2, 4)


def test_random_walk_follows_edges():
    g = star_graph()
    indptr, indices = g.to_device()
    walks = np.asarray(random_walk(indptr, indices,
                                   jnp.array([0, 4], dtype=jnp.int32), 6,
                                   jax.random.PRNGKey(2)))
    assert walks.shape == (2, 7)
    # star graph: walk from 0 alternates 0 <-> leaf
    w = walks[0]
    for t in range(6):
        if w[t] == 0:
            assert w[t + 1] in (1, 2, 3)
        else:
            assert w[t + 1] == 0
    # isolated node stalls
    assert (walks[1] == 4).all()


def test_generator_batches_static_shapes():
    g = star_graph()
    gen = GraphDataGenerator(g, walk_len=3, batch_size=4, seed=0)
    batches = list(gen.batches(epochs=1))
    assert len(batches) == 2  # ceil(5/4)
    for b in batches:
        assert b.shape == (4, 4)
        assert (np.asarray(b) >= 0).all()


def _chain_graph(n=20):
    """0->1->...->n-1 plus self-ish extras for degree variety."""
    src = np.arange(n - 1)
    dst = np.arange(1, n)
    return src, dst


def test_weighted_sampling_proportional():
    """Weight-proportional draws: a 1:3 weighted pair converges to a
    ~25/75 split (with replacement, one searchsorted per draw)."""
    from paddlebox_tpu.graph import GraphStore, sample_neighbors_weighted
    src = np.array([0, 0])
    dst = np.array([1, 2])
    w = np.array([1.0, 3.0], np.float32)
    g = GraphStore.from_edges(src, dst, n_nodes=3, weights=w)
    indptr, indices, cumw = g.to_device_weighted()
    nodes = jnp.zeros(2000, jnp.int32)
    out = np.asarray(sample_neighbors_weighted(
        indptr, indices, cumw, nodes, 1, jax.random.PRNGKey(0)))[:, 0]
    frac = (out == 2).mean()
    assert 0.70 < frac < 0.80, frac
    # isolated node → -1
    iso = np.asarray(sample_neighbors_weighted(
        indptr, indices, cumw, jnp.ones(4, jnp.int32) * 2, 3,
        jax.random.PRNGKey(1)))
    assert (iso == -1).all()


def test_without_replacement_no_duplicates():
    from paddlebox_tpu.graph import (GraphStore,
                                     sample_neighbors_without_replacement)
    rng = np.random.default_rng(0)
    n = 30
    src = np.repeat(np.arange(4), 6)
    dst = rng.choice(n, size=24, replace=False).astype(np.int64)
    g = GraphStore.from_edges(src, dst, n_nodes=n)
    indptr, indices = g.to_device()
    nodes = jnp.asarray(np.array([0, 1, 2, 3], np.int32))
    for k in (3, 6, 9):
        out = np.asarray(sample_neighbors_without_replacement(
            indptr, indices, nodes, k, jax.random.PRNGKey(2),
            max_degree=16))
        assert out.shape == (4, k)
        for row in out:
            real = row[row >= 0]
            assert len(set(real.tolist())) == len(real)  # no dupes
            assert len(real) == min(k, 6)  # degree 6 each


def test_without_replacement_weighted_prefers_heavy():
    from paddlebox_tpu.graph import (GraphStore,
                                     sample_neighbors_without_replacement)
    # node 0 with 8 neighbors, one of weight 50 vs seven of weight 1
    src = np.zeros(8, np.int64)
    dst = np.arange(1, 9)
    w = np.ones(8, np.float32)
    w[3] = 50.0
    g = GraphStore.from_edges(src, dst, n_nodes=9, weights=w)
    indptr, indices, cumw = (jnp.asarray(g.indptr),
                             jnp.asarray(g.indices),
                             jnp.asarray(g.cumw))
    hits = 0
    for t in range(200):
        out = np.asarray(sample_neighbors_without_replacement(
            indptr, indices, jnp.zeros(1, jnp.int32), 1,
            jax.random.PRNGKey(t), max_degree=8, cumw=cumw))
        hits += int(out[0, 0] == 4)
    assert hits > 150  # ~50/57 probability of the heavy edge first


def test_without_replacement_hub_tail_reachable():
    """Hub nodes with degree > max_degree: the sampling window offset is
    randomized per call, so edges beyond the first max_degree CSR entries
    are NOT permanently unsampleable (advisor r2 finding)."""
    from paddlebox_tpu.graph import (GraphStore,
                                     sample_neighbors_without_replacement)
    deg = 64
    src = np.zeros(deg, np.int64)
    dst = np.arange(1, deg + 1)
    g = GraphStore.from_edges(src, dst, n_nodes=deg + 1)
    indptr, indices = g.to_device()
    seen = set()
    for t in range(60):
        out = np.asarray(sample_neighbors_without_replacement(
            indptr, indices, jnp.zeros(1, jnp.int32), 8,
            jax.random.PRNGKey(t), max_degree=16))
        real = out[out >= 0]
        assert len(set(real.tolist())) == len(real)
        seen.update(real.tolist())
    # the tail beyond the first 16 CSR entries must appear
    assert any(v > 16 for v in seen), sorted(seen)
    # and coverage should span most of the neighborhood
    assert len(seen) > deg * 0.8, sorted(seen)


def test_metapath_walk_follows_types():
    from paddlebox_tpu.graph import GraphStore, HeteroGraphStore
    # type "a": i -> i+10; type "b": i -> i+100 (deterministic chains)
    a = GraphStore.from_edges(np.arange(10), np.arange(10) + 10,
                              n_nodes=200)
    b = GraphStore.from_edges(np.arange(10, 20), np.arange(10, 20) + 100,
                              n_nodes=200)
    h = HeteroGraphStore({"a": a, "b": b})
    starts = jnp.asarray(np.arange(5, dtype=np.int32))
    walks = np.asarray(h.metapath_walk(["a", "b"], starts,
                                       jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(walks[:, 1], np.arange(5) + 10)
    np.testing.assert_array_equal(walks[:, 2], np.arange(5) + 110)
    # dead end stalls: following "a" from a node with no "a" edges
    walks2 = np.asarray(h.metapath_walk(["b", "a"], starts,
                                        jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(walks2[:, 1], np.arange(5))  # stall
    np.testing.assert_array_equal(walks2[:, 2], np.arange(5) + 10)


def test_sharded_graph_sampler_matches_single(mesh8):
    """Mesh-sharded (node % S) sampling through all_to_all routing
    returns neighbors of the right node for every query — validated
    against the single-store adjacency."""
    from paddlebox_tpu.graph import GraphStore, ShardedGraphStore
    rng = np.random.default_rng(7)
    n = 64
    src = rng.integers(0, n, size=400)
    dst = rng.integers(0, n, size=400)
    g = GraphStore.from_edges(src, dst, n_nodes=n)
    S = 8
    sg = ShardedGraphStore(g, S)
    q_per_shard = 16
    k = 4
    sampler = sg.make_sampler(mesh8, k=k, q_per_shard=q_per_shard,
                              axis="dp")
    queries = rng.integers(0, n, size=(S, q_per_shard)).astype(np.int32)
    keys = np.stack([
        jax.random.key_data(jax.random.PRNGKey(s)) for s in range(S)])
    out = np.asarray(sampler(jnp.asarray(sg.indptr),
                             jnp.asarray(sg.indices),
                             jnp.asarray(queries), jnp.asarray(keys)))
    assert out.shape == (S, q_per_shard, k)
    adj = {int(u): set() for u in range(n)}
    for u, v in zip(src, dst):
        adj[int(u)].add(int(v))
    for srow, qrow in zip(out, queries):
        for got, q in zip(srow, qrow):
            if not adj[int(q)]:
                assert (got == -1).all()
            else:
                assert all(int(x) in adj[int(q)] for x in got), (q, got)


def test_features_for_nodes_pulls_embedding_rows():
    from paddlebox_tpu.graph import features_for_nodes
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    t = EmbeddingTable(mf_dim=4, capacity=256,
                       cfg=SparseSGDConfig(mf_create_thresholds=0.0))
    keys = np.array([5, 9], np.uint64)
    rows = t.index.assign(keys)
    import jax as _jax
    data = np.asarray(_jax.device_get(t.state.data)).copy()
    data[rows, 0] = 7.0   # show
    data[rows, 4] = 0.25  # embed_w
    from paddlebox_tpu.ps.table import TableState
    t.state = TableState.from_logical(data, t.capacity)
    out = features_for_nodes(t, np.array([5, 9, 77], np.uint64))
    assert out.shape == (3, 7)
    np.testing.assert_allclose(out[:2, 0], 7.0)
    np.testing.assert_allclose(out[:2, 2], 0.25)
    np.testing.assert_allclose(out[2], 0.0)  # unknown node reads zeros


def _two_cliques(m=20, cross=3, seed=0):
    from paddlebox_tpu.graph import GraphStore
    rng = np.random.default_rng(seed)
    src, dst = [], []
    for base in (0, m):
        for i in range(m):
            for j in range(m):
                if i != j:
                    src.append(base + i)
                    dst.append(base + j)
    for _ in range(cross):
        a, b = rng.integers(0, m), m + rng.integers(0, m)
        src += [a, b]
        dst += [b, a]
    return GraphStore.from_edges(np.array(src), np.array(dst),
                                 n_nodes=2 * m)


def test_bfs_sampler_levels_and_edges():
    """BfsSampler (BasicBfsGraphSampler role): sampled edges are true
    graph edges; each level's nodes were sampled from the previous."""
    from paddlebox_tpu.graph import BfsSampler
    g = _two_cliques()
    adj = {u: set(g.indices[g.indptr[u]:g.indptr[u + 1]].tolist())
           for u in range(g.n_nodes)}
    s = BfsSampler(g, k_per_level=(5, 3), node_budget=64)
    out = s.sample(np.array([0, 1, 25], np.int32), jax.random.PRNGKey(0))
    assert len(out["levels"]) == 3
    src, dst = out["edges"]
    assert len(src) > 0
    for u, v in zip(src, dst):
        assert int(v) in adj[int(u)], (u, v)
    lvl_sets = [set(l[l >= 0].tolist()) for l in out["levels"]]
    for u in lvl_sets[1]:
        assert any(u in adj[s0] for s0 in lvl_sets[0])


def test_sampler_service_rate_control_and_feed():
    """GraphSamplerService: background thread feeds the channel; the
    sample-rate knob bounds production (test_sample_rate.cu role)."""
    import time
    from paddlebox_tpu.graph import GraphSamplerService
    g = _two_cliques()
    svc = GraphSamplerService(g, mode="walk", batch_size=8, walk_len=3,
                              rate=20.0, capacity=64, seed=1)
    svc.start()
    it = svc.batches()
    first = next(it)                     # absorbs the jit compile
    assert first.shape == (8, 4)
    t0 = time.monotonic()
    base = svc.produced
    got = 0
    for walks in it:
        assert walks.shape == (8, 4)
        got += 1
        if time.monotonic() - t0 > 1.0:
            break
    produced_window = svc.produced - base
    elapsed = time.monotonic() - t0
    svc.stop()
    # it actually produced (>=1 even on a heavily loaded box — the first
    # batch already arrived before the window opened)
    assert got >= 1
    # rate control: production in the window stays within the budget
    assert produced_window <= 20 * elapsed + 3, (produced_window, elapsed)


def test_gnn_trains_from_service():
    """E2e: a small GraphSAGE-style classifier trained CONTINUOUSLY from
    the background BFS service separates two communities."""
    import jax.numpy as jnp
    import optax
    from paddlebox_tpu.graph import GraphSamplerService
    m = 20
    g = _two_cliques(m=m)
    rng = np.random.default_rng(3)
    feats = rng.normal(size=(2 * m, 8)).astype(np.float32)
    labels = (np.arange(2 * m) >= m).astype(np.float32)

    svc = GraphSamplerService(g, mode="bfs", batch_size=16,
                              k_per_level=(6,), capacity=16, seed=2)
    svc.start(max_batches=120)

    w = jnp.asarray(rng.normal(size=(16,)) * 0.1)
    tx = optax.adam(5e-2)
    opt = tx.init(w)

    @jax.jit
    def step(w, opt, x_seed, x_neigh, y):
        def loss_fn(w):
            h = jnp.concatenate([x_seed, x_neigh], axis=1)
            logit = h @ w
            return jnp.mean(optax.sigmoid_binary_cross_entropy(logit, y))
        loss, gr = jax.value_and_grad(loss_fn)(w)
        up, opt = tx.update(gr, opt, w)
        return optax.apply_updates(w, up), opt, loss

    nb = 0
    for batch in svc.batches():
        seeds = batch["levels"][0]
        src, dst = batch["edges"]
        x_seed = feats[seeds]
        x_neigh = np.zeros_like(x_seed)
        for i, sd in enumerate(seeds):
            nb_mask = src == sd
            if nb_mask.any():
                x_neigh[i] = feats[dst[nb_mask]].mean(axis=0)
        w, opt, loss = step(w, opt, jnp.asarray(x_seed),
                            jnp.asarray(x_neigh),
                            jnp.asarray(labels[seeds]))
        nb += 1
    svc.stop()
    assert nb == 120
    # accuracy over all nodes using full-neighborhood means
    x_neigh_all = np.stack([
        feats[g.indices[g.indptr[u]:g.indptr[u + 1]]].mean(axis=0)
        for u in range(2 * m)])
    logits = np.concatenate([feats, x_neigh_all], axis=1) @ np.asarray(w)
    acc = ((logits > 0) == (labels > 0.5)).mean()
    assert acc > 0.9, acc
