"""Graph store / sampling tests (reference: heter_ps graph PS —
gpu_graph_node.h:35, graph_gpu_ps_table.h:128, test_graph.cu)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.graph import (GraphDataGenerator, GraphStore,
                                 random_walk, sample_neighbors)


def star_graph():
    # 0 -> {1,2,3}; 1 -> {0}; 2 -> {0}; 3 -> {0}; 4 isolated
    src = np.array([0, 0, 0, 1, 2, 3])
    dst = np.array([1, 2, 3, 0, 0, 0])
    return GraphStore.from_edges(src, dst, n_nodes=5)


def test_from_edges_csr():
    g = star_graph()
    assert g.n_nodes == 5
    np.testing.assert_array_equal(g.degree(), [3, 1, 1, 1, 0])
    np.testing.assert_array_equal(
        sorted(g.indices[g.indptr[0]:g.indptr[1]]), [1, 2, 3])


def test_symmetric_edges():
    g = GraphStore.from_edges(np.array([0]), np.array([1]), n_nodes=2,
                              symmetric=True)
    np.testing.assert_array_equal(g.degree(), [1, 1])


def test_sample_neighbors_valid_and_padded():
    g = star_graph()
    indptr, indices = g.to_device()
    nodes = jnp.array([0, 1, 4], dtype=jnp.int32)
    out = np.asarray(sample_neighbors(indptr, indices, nodes, 8,
                                      jax.random.PRNGKey(0)))
    assert out.shape == (3, 8)
    assert set(out[0]).issubset({1, 2, 3})   # node 0's neighbors
    assert (out[1] == 0).all()               # node 1 -> only 0
    assert (out[2] == -1).all()              # isolated -> padded


def test_sample_neighbors_jits():
    g = star_graph()
    indptr, indices = g.to_device()
    f = jax.jit(sample_neighbors, static_argnums=(3,))
    out = f(indptr, indices, jnp.array([0, 1]), 4, jax.random.PRNGKey(1))
    assert out.shape == (2, 4)


def test_random_walk_follows_edges():
    g = star_graph()
    indptr, indices = g.to_device()
    walks = np.asarray(random_walk(indptr, indices,
                                   jnp.array([0, 4], dtype=jnp.int32), 6,
                                   jax.random.PRNGKey(2)))
    assert walks.shape == (2, 7)
    # star graph: walk from 0 alternates 0 <-> leaf
    w = walks[0]
    for t in range(6):
        if w[t] == 0:
            assert w[t + 1] in (1, 2, 3)
        else:
            assert w[t + 1] == 0
    # isolated node stalls
    assert (walks[1] == 4).all()


def test_generator_batches_static_shapes():
    g = star_graph()
    gen = GraphDataGenerator(g, walk_len=3, batch_size=4, seed=0)
    batches = list(gen.batches(epochs=1))
    assert len(batches) == 2  # ceil(5/4)
    for b in batches:
        assert b.shape == (4, 4)
        assert (np.asarray(b) >= 0).all()
