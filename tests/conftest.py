"""Test harness: force an 8-device virtual CPU platform so multi-chip
sharding paths (mesh/pjit/shard_map/all_to_all) are exercised without TPUs.
Mirrors the reference's strategy of testing its distributed PS
single-process multi-device (SURVEY.md §4, heter_ps/test_comm.cu).

Note: this environment preloads a TPU plugin via sitecustomize and pins
JAX_PLATFORMS; plain env vars in conftest are too late, so we override
through jax.config before any backend is initialized."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # chaos: seeded fault-injection recovery tests (tests/test_resilience,
    # scripts/chaos_check). Fast ones run in tier-1; long soak variants
    # carry `slow` as well and stay out of the default selection.
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection / recovery tests (resilience)")
