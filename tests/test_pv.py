"""PV merge + rank_offset tests (reference: data_feed.cc:1855 GetRankOffset,
data_set.cc:2825 PreprocessInstance)."""

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedDesc, SlotDef
from paddlebox_tpu.data.pv import (PvBatchBuilder, build_rank_offset,
                                   group_by_search_id, group_by_uid)
from paddlebox_tpu.data.record import SlotRecord


def rec(sid, rank, cmatch, uid=0, nslots=2):
    # one key per sparse slot
    return SlotRecord(
        keys=np.arange(nslots, dtype=np.uint64),
        slot_offsets=np.arange(nslots + 1, dtype=np.int32),
        dense=np.zeros(0, np.float32), label=1.0, show=1.0, clk=0.0,
        search_id=sid, rank=rank, cmatch=cmatch, uid=uid)


def reference_rank_offset(pvs, max_rank=3):
    """Direct transliteration of the reference CPU loop semantics."""
    ins_num = sum(len(p) for p in pvs)
    col = 2 * max_rank + 1
    mat = np.full((ins_num, col), -1, dtype=np.int32)
    index = 0
    for pv in pvs:
        start = index
        for j, ins in enumerate(pv):
            rank = -1
            if ins.cmatch in (222, 223) and 0 < ins.rank <= max_rank:
                rank = ins.rank
            mat[index, 0] = rank
            if rank > 0:
                for k, cur in enumerate(pv):
                    fr = -1
                    if cur.cmatch in (222, 223) and 0 < cur.rank <= max_rank:
                        fr = cur.rank
                    if fr > 0:
                        m = fr - 1
                        mat[index, 2 * m + 1] = cur.rank
                        mat[index, 2 * m + 2] = start + k
            index += 1
    return mat


def test_group_by_search_id_merges_consecutive():
    rs = [rec(7, 1, 222), rec(3, 2, 222), rec(7, 3, 223), rec(3, 1, 0)]
    pvs = group_by_search_id(rs)
    assert [len(p) for p in pvs] == [2, 2]
    assert {p[0].search_id for p in pvs} == {3, 7}


def test_group_by_uid():
    rs = [rec(1, 1, 222, uid=5), rec(2, 1, 222, uid=6), rec(3, 1, 222, uid=5)]
    groups = group_by_uid(rs)
    sizes = sorted(len(g) for g in groups)
    assert sizes == [1, 2]


def test_rank_offset_matches_reference_semantics():
    rng = np.random.default_rng(0)
    pvs = []
    for sid in range(6):
        n = int(rng.integers(1, 5))
        pvs.append([
            rec(sid, int(rng.integers(0, 5)),
                int(rng.choice([0, 111, 222, 223])))
            for _ in range(n)
        ])
    got = build_rank_offset(pvs)
    want = reference_rank_offset(pvs)
    np.testing.assert_array_equal(got, want)


def test_rank_offset_pads_with_minus_one():
    pvs = [[rec(1, 1, 222), rec(1, 2, 222)]]
    mat = build_rank_offset(pvs, max_rank=3, pad_to=5)
    assert mat.shape == (5, 7)
    assert (mat[2:] == -1).all()
    # row 0: own rank 1; co-shown ranks 1,2 at cols (1,2) and (3,4)
    assert mat[0, 0] == 1 and mat[0, 1] == 1 and mat[0, 2] == 0
    assert mat[0, 3] == 2 and mat[0, 4] == 1


def test_pv_batch_builder_feeds_rank_attention():
    import jax.numpy as jnp

    from paddlebox_tpu.ops import rank_attention

    S, B = 2, 8
    slots = [SlotDef("label", "float", 1)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                        pv_batch_size=2, key_bucket_min=32)
    rs = [rec(sid, r + 1, 222, nslots=S) for sid in range(4) for r in range(2)]
    pairs = PvBatchBuilder(desc, max_rank=3).batches(rs)
    assert len(pairs) == 2
    batch, ro = pairs[0]
    assert ro.shape == (B, 7)
    x = jnp.ones((B, 4))
    param = jnp.ones((3 * 3 * 4, 5))
    out = rank_attention(x, jnp.asarray(ro), param, max_rank=3)
    assert out.shape == (B, 5)
    # padding rows (own rank -1) contribute zero
    valid_ads = sum(len(p) for p in group_by_search_id(rs[:4]))
    np.testing.assert_allclose(np.asarray(out[valid_ads:]), 0.0)


def test_pv_chunk_overflow_raises():
    slots = [SlotDef("label", "float", 1), SlotDef("C0", "uint64")]
    desc = DataFeedDesc(slots=slots, batch_size=2, label_slot="label",
                        pv_batch_size=2, key_bucket_min=32)
    rs = [rec(0, 1, 222, nslots=1), rec(0, 2, 222, nslots=1),
          rec(1, 1, 222, nslots=1), rec(1, 2, 222, nslots=1)]
    with pytest.raises(ValueError):
        PvBatchBuilder(desc).batches(rs)


def test_compute_split_num_and_mask_invariant():
    """Port-parity with data_set.cc:2783: windows tile the timeline, every
    record trains exactly once, context prefixes are seq-train long."""
    from paddlebox_tpu.data.pv import compute_split_num_and_mask
    for n, seq, train in [(10, 4, 2), (17, 6, 3), (9, 4, 4), (25, 8, 2)]:
        offs, zmask = compute_split_num_and_mask(n, seq, train)
        assert offs[0][0] == 0 and offs[-1][1] == n
        assert zmask[0] == 0
        assert all(z == seq - train for z in zmask[1:])
        # each window after the first is seq long
        assert all(b - a == seq for (a, b) in offs[1:])
        trained = sum((b - a) - z for (a, b), z in zip(offs, zmask))
        assert trained == n


def test_split_uid_groups_methods():
    from paddlebox_tpu.data.pv import build_train_mask, split_uid_groups
    g = [rec(1, 1, 222, uid=5) for _ in range(10)]

    whole = split_uid_groups([g], method=0)
    assert len(whole) == 1 and len(whole[0][0]) == 10 and whole[0][1] == 0

    # direct split, chunks aligned to the END (reference j>0 &&
    # (count-j)%size==0): 10 into size-4 → [2, 4, 4]
    direct = split_uid_groups([g], method=1, split_size=4)
    assert [len(c) for c, _ in direct] == [2, 4, 4]
    assert all(z == 0 for _, z in direct)

    # windowed split with train mask: seq=4, train=2 over 10 records
    win = split_uid_groups([g], method=2, split_size=4, train_size=2)
    sizes = [len(c) for c, _ in win]
    zmask = [z for _, z in win]
    assert zmask[0] == 0 and all(z == 2 for z in zmask[1:])
    assert sum(s - z for s, z in zip(sizes, zmask)) == 10
    mask = build_train_mask(win, pad_to=32)
    assert mask.shape == (32,)
    assert int(mask.sum()) == 10          # every record trains exactly once
    assert (mask[sum(sizes):] == 0).all()  # padding rows masked out

    # short timelines fall back to whole-chunk
    short = split_uid_groups([g[:3]], method=2, split_size=4, train_size=2)
    assert len(short) == 1 and short[0][1] == 0


def test_timestamp_plumbing_and_range_mask():
    """timestamp flows record → batch/columnar; uid timelines sort by it;
    the test-phase range mask selects [lo, hi)."""
    from paddlebox_tpu.data import DataFeedDesc, SlotDef
    from paddlebox_tpu.data.batch import BatchBuilder
    from paddlebox_tpu.data.columnar import ColumnarRecords
    from paddlebox_tpu.data.pv import timestamp_range_mask

    recs = [rec(1, 1, 222, uid=5) for _ in range(4)]
    for i, r in enumerate(recs):
        r.timestamp = 100 - i * 10    # out of order on purpose
    groups = group_by_uid(recs)
    assert [r.timestamp for r in groups[0]] == [70, 80, 90, 100]

    desc = DataFeedDesc(
        slots=[SlotDef("label", "float", 1)]
        + [SlotDef(f"C{i}", "uint64") for i in range(2)],
        batch_size=8, label_slot="label")
    b = BatchBuilder(desc).build(recs)
    np.testing.assert_array_equal(b.timestamp[:4], [100, 90, 80, 70])
    col = ColumnarRecords.from_records(recs, 0)
    np.testing.assert_array_equal(col.timestamp, [100, 90, 80, 70])
    cb = col.batch(0, 4, desc, 2)
    np.testing.assert_array_equal(cb.timestamp[:4], [100, 90, 80, 70])

    m = timestamp_range_mask(b.timestamp, 75, 95)
    np.testing.assert_array_equal(m[:4], [0, 1, 1, 0])


def test_shard_filelist_round_robin():
    from paddlebox_tpu.data.dataset import shard_filelist
    files = [f"f{i}" for i in range(10)]
    assert shard_filelist(files, rank=0, world=4) == ["f0", "f4", "f8"]
    assert shard_filelist(files, rank=3, world=4) == ["f3", "f7"]
    # union over ranks covers everything exactly once
    got = sum((shard_filelist(files, r, 4) for r in range(4)), [])
    assert sorted(got) == files
    assert shard_filelist(files, rank=0, world=1) == files
    import pytest as _pt
    with _pt.raises(ValueError):
        shard_filelist(files, rank=5, world=4)
