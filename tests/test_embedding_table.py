"""Embedding store tests: pull/push/dedup/optimizer math vs numpy reference
(mirrors heter_ps/test_comm.cu's insert→pull→push→verify pattern)."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.table import HostKV


def mkbatch(keys, k_pad=16, B=2, S=2):
    keys = np.asarray(keys, np.uint64)
    kp = np.zeros(k_pad, np.uint64)
    kp[:len(keys)] = keys
    segs = np.full(k_pad, B * S, np.int32)
    segs[:len(keys)] = np.arange(len(keys)) % (B * S)
    return SlotBatch(keys=kp, segments=segs, num_keys=len(keys),
                     dense=np.zeros((B, 1), np.float32),
                     label=np.zeros(B, np.float32),
                     show=np.ones(B, np.float32), clk=np.zeros(B, np.float32),
                     batch_size=B, num_slots=S)


def test_hostkv_assign_reuse_release():
    kv = HostKV(capacity=4)
    r1 = kv.assign(np.array([10, 20, 30], np.uint64))
    assert len(set(r1.tolist())) == 3
    r2 = kv.assign(np.array([20, 10], np.uint64))
    np.testing.assert_array_equal(r2, [r1[1], r1[0]])
    kv.release(np.array([10], np.uint64))
    r3 = kv.assign(np.array([99], np.uint64))
    assert r3[0] == r1[0]  # row reused
    kv.assign(np.array([1], np.uint64))  # row 3: now 4/4 used
    with pytest.raises(RuntimeError):
        kv.assign(np.array([2], np.uint64))  # capacity 4 exhausted


def test_pull_new_keys_zero_and_dedup():
    t = EmbeddingTable(mf_dim=4, capacity=64, unique_bucket_min=8)
    b = mkbatch([5, 7, 5, 9])
    idx = t.prepare(b)
    assert idx.num_unique == 3
    vals = np.asarray(t.pull(idx))
    assert vals.shape == (16, 7)  # K_pad x (3 + mf_dim)
    np.testing.assert_array_equal(vals[:4], 0)  # fresh rows are zero
    # duplicate keys share a unique slot
    assert idx.gather_idx[0] == idx.gather_idx[2]
    # pad positions map to a slot whose row clamps to the zero sentinel
    # (pads hold distinct OOB rows > capacity — unique-scatter contract)
    assert np.all(idx.unique_rows[idx.gather_idx[4:]] >= t.capacity)
    np.testing.assert_array_equal(vals[4:], 0)  # padded keys pull zeros


def test_push_updates_counters_and_weights():
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)  # no mf creation yet
    t = EmbeddingTable(mf_dim=2, capacity=32, cfg=cfg, unique_bucket_min=8)
    b = mkbatch([5, 7, 5], k_pad=8)
    idx = t.prepare(b)
    # grads: [g_show, g_clk, g_embed, g_embedx x2]
    kg = np.zeros((8, 5), np.float32)
    kg[0] = [1, 0, 0.5, 0.1, 0.1]
    kg[1] = [1, 1, 0.2, 0.2, 0.2]
    kg[2] = [1, 0, 0.3, 0.1, 0.1]
    t.push(idx, jnp.asarray(kg))
    st = t.state
    rows = t.index.lookup(np.array([5, 7], np.uint64))
    show = np.asarray(st.show)[rows]
    clk = np.asarray(st.clk)[rows]
    np.testing.assert_allclose(show, [2.0, 1.0])  # key 5 hit twice
    np.testing.assert_allclose(clk, [0.0, 1.0])
    # embed update (reference math): g=0.8 for key5, scale=g_show=2,
    # ratio = lr*sqrt(g0/(g0+0)) = 0.05; w = 0 + (0.8/2)*0.05
    w5 = np.asarray(st.embed_w)[rows[0]]
    np.testing.assert_allclose(w5, 0.4 * 0.05, rtol=1e-5)
    g2 = np.asarray(st.embed_g2sum)[rows[0]]
    np.testing.assert_allclose(g2, 0.4 ** 2, rtol=1e-5)
    # delta_score: nonclk*.1*(2-0)+1*0 = 0.2
    np.testing.assert_allclose(np.asarray(st.delta_score)[rows[0]], 0.2,
                               rtol=1e-5)
    # mf not created (threshold huge) → embedx still zero, mf_size 0
    assert np.all(np.asarray(st.mf_size)[rows] == 0)
    assert np.all(np.asarray(st.embedx_w)[rows] == 0)
    # sentinel row stays zero
    assert np.all(np.asarray(st.show)[t.capacity] == 0)


def test_lazy_mf_creation_threshold():
    cfg = SparseSGDConfig(mf_create_thresholds=0.5, mf_initial_range=0.01)
    t = EmbeddingTable(mf_dim=4, capacity=16, cfg=cfg, unique_bucket_min=8)
    b = mkbatch([3], k_pad=8)
    idx = t.prepare(b)
    kg = np.zeros((8, 7), np.float32)
    kg[0] = [1, 1, 0.1, 0, 0, 0, 0]  # score = .1*(1-1) + 1*1 = 1 >= 0.5
    t.push(idx, jnp.asarray(kg))
    row = t.index.lookup(np.array([3], np.uint64))[0]
    assert np.asarray(t.state.mf_size)[row] == 1
    mf = np.asarray(t.state.embedx_w)[row]
    assert np.all(mf >= 0) and np.all(mf <= 0.01) and mf.std() > 0
    # second push: now a normal adagrad step on embedx
    idx2 = t.prepare(b)
    kg2 = np.zeros((8, 7), np.float32)
    kg2[0] = [1, 0, 0.0, 0.4, 0.4, 0.4, 0.4]
    t.push(idx2, jnp.asarray(kg2))
    mf2 = np.asarray(t.state.embedx_w)[row]
    expect = np.clip(mf + (0.4 / 1.0) * 0.05 * np.sqrt(3.0 / 3.0), -10, 10)
    np.testing.assert_allclose(mf2, expect, rtol=1e-5)


def test_save_base_delta_load(tmp_path):
    t = EmbeddingTable(mf_dim=2, capacity=32, unique_bucket_min=8)
    b = mkbatch([11, 22], k_pad=8)
    idx = t.prepare(b)
    kg = np.zeros((8, 5), np.float32)
    kg[0] = [1, 0, 0.5, 0, 0]
    kg[1] = [1, 1, 0.1, 0, 0]
    t.push(idx, jnp.asarray(kg))
    base = str(tmp_path / "base.npz")
    assert t.save_base(base) == 2

    # touch only key 11 → delta has 1 row
    idx2 = t.prepare(mkbatch([11], k_pad=8))
    kg2 = np.zeros((8, 5), np.float32)
    kg2[0] = [1, 0, 0.2, 0, 0]
    t.push(idx2, jnp.asarray(kg2))
    delta = str(tmp_path / "delta.npz")
    assert t.save_delta(delta) == 1

    # fresh table: load base then apply delta → equals live table
    t2 = EmbeddingTable(mf_dim=2, capacity=32, unique_bucket_min=8)
    t2.load(base)
    t2.load(delta, merge=True)
    for k in (11, 22):
        r_live = t.index.lookup(np.array([k], np.uint64))[0]
        r_new = t2.index.lookup(np.array([k], np.uint64))[0]
        np.testing.assert_allclose(
            np.asarray(t2.state.embed_w)[r_new],
            np.asarray(t.state.embed_w)[r_live], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(t2.state.show)[r_new],
            np.asarray(t.state.show)[r_live], rtol=1e-6)


def test_shrink_frees_low_score_rows():
    t = EmbeddingTable(mf_dim=2, capacity=16, unique_bucket_min=8)
    idx = t.prepare(mkbatch([1, 2], k_pad=8))
    kg = np.zeros((8, 5), np.float32)
    kg[0] = [20, 15, 0, 0, 0]   # high score: .1*5 + 15 = 15.5
    kg[1] = [1, 0, 0, 0, 0]     # low score: .1*1 = 0.1
    t.push(idx, jnp.asarray(kg))
    freed = t.shrink(delete_threshold=1.0, decay=1.0)
    assert freed == 1
    assert t.index.lookup(np.array([2], np.uint64))[0] == -1
    r1 = t.index.lookup(np.array([1], np.uint64))[0]
    assert r1 >= 0 and np.asarray(t.state.show)[r1] == 20.0
    # freed row is zeroed on device
    st = np.asarray(t.state.show)
    assert (st > 0).sum() == 1


def test_packed_gather_oob_pads_read_zero():
    """Regression: with capacity % rows_per_line == rpl-1 the first OOB
    pad id lands past the last storage line; a naive line-index clamp
    then aliases a REAL row. Pads must read the sentinel's zeros."""
    import jax.numpy as jnp
    from paddlebox_tpu.ps.table import (TableState, gather_full_rows,
                                        pack_geometry)
    cap = 999           # rpl=8 for F=16 → (cap+1) % 8 == 0, the bad case
    mf = 8
    rpl, fp, nl = pack_geometry(cap, 16)
    assert (cap + 1) % rpl == 0
    logical = np.zeros((cap + 1, 16), np.float32)
    logical[:cap, 4] = 7.0  # every real row has embed_w = 7
    st = TableState.from_logical(logical, cap)
    # sentinel (cap), first OOB pad (cap+1), far OOB pads
    rows = jnp.asarray(np.array([0, cap, cap + 1, cap + 8, cap + 4096],
                                np.int32))
    got = np.asarray(gather_full_rows(st, rows))
    assert got[0, 4] == 7.0            # real row reads its value
    np.testing.assert_array_equal(got[1:], 0.0)  # sentinel + pads → zeros


def test_slot_host_recorded_on_all_paths(tmp_path):
    """Saved slot metadata must be populated by every prepare/push path:
    EmbeddingTable.prepare, push(slot_of_key=...), and the
    ExtendedEmbeddingTable pair (regression: the extended path once
    saved slot=0 for every row)."""
    import jax.numpy as jnp

    # prepare path: keys 1..4 land in slots 0,1,0,1 (mkbatch: pos % S)
    t = EmbeddingTable(mf_dim=2, capacity=32, unique_bucket_min=8)
    idx = t.prepare(mkbatch([1, 2, 3, 4], k_pad=8))
    t.push(idx, jnp.zeros((8, 5)))
    p = str(tmp_path / "b.npz")
    t.save_base(p)
    blob = np.load(p)
    by_key = dict(zip(blob["keys"].tolist(), blob["slot"].tolist()))
    assert by_key == {1: 0.0, 2: 1.0, 3: 0.0, 4: 1.0}

    # eager push(slot_of_key) path on a fresh table (no prepare slots)
    t2 = EmbeddingTable(mf_dim=2, capacity=32, unique_bucket_min=8)
    b = mkbatch([7, 8], k_pad=8)
    with t2.host_lock:
        rows, inv = t2.index.assign_unique(b.keys[:2])
        t2._touched[rows] = True
    idx2 = t2._build_index(b, rows, inv)
    t2.push(idx2, jnp.zeros((8, 5)),
            slot_of_key=jnp.asarray(np.array([0, 1] + [0] * 6, np.float32)))
    assert t2.slot_host[t2.index.lookup(np.array([8], np.uint64))[0]] == 1

    # extended pair records slots for BOTH tables
    from paddlebox_tpu.ps.extended import ExtendedEmbeddingTable
    te = ExtendedEmbeddingTable(mf_dim=2, extend_mf_dim=2, capacity=32,
                                unique_bucket_min=8,
                                skip_extend_slots=(0,))
    te.prepare(mkbatch([11, 12], k_pad=8))
    rb = te.base.index.lookup(np.array([12], np.uint64))[0]
    assert te.base.slot_host[rb] == 1
    re_ = te.extend.index.lookup(np.array([12], np.uint64))[0]
    assert re_ >= 0 and te.extend.slot_host[re_] == 1


def test_merge_model_accumulates_stats(tmp_path):
    """merge_model (box_wrapper.h:801): overlapping keys accumulate
    show/clk/delta_score and keep live weights; new keys insert
    wholesale — unlike load(merge=True), which overwrites."""
    import jax
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    a = EmbeddingTable(mf_dim=2, capacity=256, cfg=cfg)
    b = EmbeddingTable(mf_dim=2, capacity=256, cfg=cfg)

    def seed(table, keys, show, w):
        rows = table.index.assign(keys)
        data = np.asarray(jax.device_get(table.state.data)).copy()
        data[rows, 0] = show       # show
        data[rows, 1] = show / 2   # clk
        data[rows, 4] = w          # embed_w
        from paddlebox_tpu.ps.table import TableState
        table.state = TableState.from_logical(data, table.capacity)
        table.slot_host[rows] = 1

    k_a = np.array([1, 2, 3], np.uint64)
    k_b = np.array([2, 3, 4], np.uint64)
    seed(a, k_a, 10.0, 0.5)
    seed(b, k_b, 4.0, 0.9)
    path = str(tmp_path / "other.npz")
    b.save_base(path)
    merged = a.merge_model(path)
    assert merged == 3
    data = np.asarray(jax.device_get(a.state.data))
    rows = a.index.lookup(np.array([1, 2, 3, 4], np.uint64))
    assert (rows >= 0).all()          # key 4 inserted
    np.testing.assert_allclose(data[rows, 0], [10.0, 14.0, 14.0, 4.0])
    np.testing.assert_allclose(data[rows, 1], [5.0, 7.0, 7.0, 2.0])
    # overlapping keys KEEP live weights; the new key takes the file's
    np.testing.assert_allclose(data[rows, 4], [0.5, 0.5, 0.5, 0.9])
    assert a.slot_host[rows[3]] == 1
    # merged rows are flagged for the next delta save (key 1 was not in
    # the merge file, so it stays unflagged)
    assert a._touched[rows[1:]].all()
    assert not a._touched[rows[0]]


def test_zero1_rejects_non_elementwise_tx():
    import optax
    from paddlebox_tpu.train.sharded import _assert_elementwise_tx
    _assert_elementwise_tx(optax.adam(1e-3))       # fine
    _assert_elementwise_tx(optax.sgd(0.1))         # fine
    with pytest.raises(ValueError, match="ELEMENTWISE"):
        _assert_elementwise_tx(optax.chain(
            optax.clip_by_global_norm(1.0), optax.sgd(0.1)))


def test_merge_multi_models(tmp_path):
    """MergeMultiModels (box_wrapper.h:812): several files fold in order;
    update_type selects stat-merge vs delta-overwrite."""
    import jax
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.table import TableState
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)

    def seed(keys, show):
        t = EmbeddingTable(mf_dim=2, capacity=256, cfg=cfg)
        rows = t.index.assign(keys)
        d = np.asarray(jax.device_get(t.state.data)).copy()
        d[rows, 0] = show
        t.state = TableState.from_logical(d, t.capacity)
        return t

    a = seed(np.array([1, 2], np.uint64), 10.0)
    b = seed(np.array([2, 3], np.uint64), 4.0)
    c = seed(np.array([3, 4], np.uint64), 2.0)
    pb, pc = str(tmp_path / "b.npz"), str(tmp_path / "c.npz")
    b.save_base(pb)
    c.save_base(pc)
    assert a.merge_models([pb, pc]) == 4
    data = np.asarray(jax.device_get(a.state.data))
    rows = a.index.lookup(np.array([1, 2, 3, 4], np.uint64))
    # stats accumulate: key2 10+4, key3 4+2 (b inserted, c merged), key4 2
    np.testing.assert_allclose(data[rows, 0], [10.0, 14.0, 6.0, 2.0])
    with pytest.raises(ValueError):
        a.merge_models([pb], update_type="bogus")
    # overwrite mode applies files as deltas
    a2 = seed(np.array([2], np.uint64), 10.0)
    a2.merge_models([pb], update_type="overwrite")
    d2 = np.asarray(jax.device_get(a2.state.data))
    r2 = a2.index.lookup(np.array([2], np.uint64))
    np.testing.assert_allclose(d2[r2, 0], 4.0)  # overwritten, not summed


def test_nan_row_isolated_to_its_lane_span():
    """A diverging row's NaN must NOT bleed into healthy rows sharing
    its 128-lane storage line (the lane-packed gather/expand/push sites
    select with ``where``, not a 0*NaN multiply) — this is what lets
    telemetry localize a NaN to ONE key (ISSUE 1 satellite)."""
    import jax
    from paddlebox_tpu.ps.table import (TableState, expand_pull,
                                        gather_full_rows, merge_rows)
    cap, mf = 15, 8                      # feat 16 → 8 rows per line
    data = np.zeros((cap + 1, 16), np.float32)
    data[0, :] = np.nan                  # diverged row 0
    data[1, 4] = 3.25                    # healthy neighbor, same line
    ts = TableState.from_logical(data, cap)
    healthy = np.asarray(gather_full_rows(ts, jnp.array([1], jnp.int32)))
    assert np.isfinite(healthy).all()
    assert healthy[0, 4] == 3.25
    sick = np.asarray(gather_full_rows(ts, jnp.array([0], jnp.int32)))
    assert np.isnan(sick[0]).all()       # the NaN row still reads NaN

    # expand_pull fwd + transpose: u=16 uniques of D=8 (16 rows/line)
    vals = np.zeros((16, 8), np.float32)
    vals[3] = np.nan
    vals[4] = 7.0
    gi = jnp.array([4, 4, 3])
    out = np.asarray(expand_pull(jnp.asarray(vals), gi))
    assert np.isfinite(out[:2]).all() and np.isnan(out[2]).all()

    def loss(v):
        return expand_pull(v, gi)[:2].sum()   # healthy keys only

    g = np.asarray(jax.grad(loss)(jnp.asarray(
        np.where(np.isfinite(vals), vals, 0.0))))
    assert np.isfinite(g).all()
    assert g[4].sum() == 16.0            # 2 occurrences × 8 dims

    # merge_rows line form: a NaN contribution stays in its segment
    m = 4
    big = 1 << 18                        # above the line-form crossover
    mvals = np.ones((m, 8), np.float32)
    mvals[0] = np.nan
    idx = jnp.array([0, 1, 1, 2])        # rows 0..2 share a line
    merged = np.asarray(merge_rows(jnp.asarray(mvals), idx, big))
    assert np.isnan(merged[0]).all()
    np.testing.assert_allclose(merged[1], 2.0)
    np.testing.assert_allclose(merged[2], 1.0)


def test_push_with_nan_neighbor_keeps_healthy_rows_finite():
    """apply_push write-back: an untouched NaN row must not poison the
    touched rows' scatter deltas on the shared line."""
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.ps.table import (TableState, apply_push)
    from paddlebox_tpu.ps.sgd import SparseSGDConfig
    import jax
    cap, mf = 15, 8
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    data = np.zeros((cap + 1, 16), np.float32)
    data[2, :] = np.nan                  # poisoned row on line 0
    ts = TableState.from_logical(data, cap)
    rows = jnp.array([1], jnp.int32)     # touch only the healthy row
    grads = jnp.ones((1, 3 + mf), jnp.float32)
    new = apply_push(ts, rows, grads, cfg, jax.random.PRNGKey(0))
    out = np.asarray(new.data)
    assert np.isfinite(out[1]).all(), "healthy touched row went NaN"
    assert np.isnan(out[2]).any(), "NaN row should persist until shrink"
    assert np.isfinite(out[0]).all() and np.isfinite(out[3:]).all()
