"""AdsRank model: end-to-end PV training — pull → seqpool_cvm → rank
attention net → push, over PvBatchBuilder batches (the production BoxPS
ads pattern: PV merge + rank_offset + rank_attention + sparse PS)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.data import DataFeedDesc, SlotDef
from paddlebox_tpu.data.pv import PvBatchBuilder
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.metrics import auc_compute, auc_add_batch, init_auc_state
from paddlebox_tpu.models import AdsRank
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.table import merge_push

S = 4          # sparse slots
MAX_RANK = 3


def make_pv_records(n_pvs=300, seed=0):
    """Synthetic search pages: 2-3 ads each; click prob depends on the ad's
    own key AND the rank of co-shown ads (so rank attention carries
    signal)."""
    rng = np.random.default_rng(seed)
    recs = []
    for sid in range(n_pvs):
        n_ads = int(rng.integers(2, 4))
        ranks = rng.permutation(n_ads)[:n_ads] + 1
        for a in range(n_ads):
            keys = (rng.integers(0, 50, S)
                    + np.arange(S) * 50).astype(np.uint64)
            base = 0.15 + 0.55 * ((keys[0] % 5) == 0)
            # co-shown penalty: a rank-1 neighbor steals clicks
            if any(r == 1 for j, r in enumerate(ranks) if j != a):
                base *= 0.5
            label = float(rng.random() < base)
            recs.append(SlotRecord(
                keys=keys, slot_offsets=np.arange(S + 1, dtype=np.int32),
                dense=rng.normal(size=2).astype(np.float32), label=label,
                show=1.0, clk=label, search_id=sid,
                rank=int(ranks[a]), cmatch=222))
    return recs


@pytest.fixture(scope="module")
def pv_setup():
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 2)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=64, label_slot="label",
                        pv_batch_size=16, key_bucket_min=512)
    recs = make_pv_records()
    return desc, recs


def test_ads_rank_trains_on_pv_batches(pv_setup):
    desc, recs = pv_setup
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=512)
    model = AdsRank(d_model=16, max_rank=MAX_RANK, hidden=(32,))
    bs = desc.batch_size
    d = 3 + table.mf_dim

    pvb = PvBatchBuilder(desc, max_rank=MAX_RANK)
    batches = pvb.batches(recs)
    assert len(batches) > 5

    b0, ro0 = batches[0]
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((bs, S, d)), jnp.zeros((bs, 2)),
                        jnp.asarray(ro0))
    tx = optax.adam(5e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, values_k, segments, show_clk, dense,
             label, ro, ins_w):
        def loss_fn(params, values_k):
            pooled = fused_seqpool_cvm(values_k, segments, show_clk, bs, S)
            logits = model.apply(params, pooled, dense, ro)
            ls = optax.sigmoid_binary_cross_entropy(logits, label)
            return jnp.sum(ls * ins_w) / jnp.maximum(ins_w.sum(), 1.0), logits
        (loss, logits), (gp, gk) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, values_k)
        upd, opt = tx.update(gp, opt, params)
        params = optax.apply_updates(params, upd)
        return params, opt, loss, jax.nn.sigmoid(logits), gk

    def run_epoch(params, opt, auc):
        for batch, ro in batches:
            idx = table.prepare(batch)
            values_k = table.pull(idx)
            show_clk = jnp.stack([jnp.asarray(batch.show),
                                  jnp.asarray(batch.clk)], axis=1)
            ins_w = (batch.show > 0).astype(np.float32)
            params, opt, loss, pred, gk = step(
                params, opt, values_k, jnp.asarray(batch.segments),
                show_clk, jnp.asarray(batch.dense),
                jnp.asarray(batch.label), jnp.asarray(ro),
                jnp.asarray(ins_w))
            # push: negate+scale per PushCopy convention, then dedup-merge
            gk = jnp.concatenate(
                [gk[:, :2], gk[:, 2:] * (-1.0 * bs)], axis=1)
            slot_of_key = (batch.segments % S).astype(np.float32)
            table.push(idx, gk, jnp.asarray(slot_of_key))
            auc = auc_add_batch(auc, pred, jnp.asarray(batch.label),
                                jnp.asarray(ins_w))
        return params, opt, auc

    auc = init_auc_state()
    params, opt, auc = run_epoch(params, opt, auc)
    first = auc_compute(auc).auc
    for _ in range(3):
        params, opt, auc2 = run_epoch(params, opt, init_auc_state())
    final = auc_compute(auc2).auc
    assert np.isfinite(final)
    assert final > max(first, 0.62), f"AdsRank failed to learn: {final}"
    # rank attention params actually moved
    rp0 = np.asarray(
        model.init(jax.random.PRNGKey(0), jnp.zeros((bs, S, d)),
                   jnp.zeros((bs, 2)), jnp.asarray(ro0))
        ["params"]["rank_param"])
    rp1 = np.asarray(params["params"]["rank_param"])
    assert np.abs(rp1 - rp0).max() > 1e-4
