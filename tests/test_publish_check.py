"""Tier-1 wiring of scripts/publish_check.py — the artifact-layer
publish/adopt gate (ISSUE 14): a seeded writer/reader pair where a
simulated crash mid-publish, a flipped byte in a published delta, and a
retention sweep concurrent with a held lease each leave the reader on a
complete, checksum-verified version — deterministic across two
identically-seeded runs. The standalone script prints the full outcome
and exits nonzero on any divergence."""

import os

from scripts.publish_check import run_publish_check


def test_publish_check_gate_deterministic(tmp_path):
    outs = []
    for run in (1, 2):
        wd = str(tmp_path / f"run{run}")
        os.makedirs(wd)
        outs.append(run_publish_check(wd, seed=7))
    out = outs[0]
    assert out["ok"]
    # every scenario left the reader on a complete, verified version
    assert out["crash_reader_aid"] == out["chain"][1]
    assert out["corrupt_fallback_aid"] == out["chain"][1]
    assert out["final_aid"] == out["chain"][-1]
    assert out["crash_fault"]["artifact.publish:fail"]["fired"] == 1
    # a held lease deferred the sweep; release reclaimed the versions
    assert out["removed_while_leased"] == []
    assert out["removed_after_release"] == out["chain"][:3]
    assert out["counters"]["refused_corrupt"] >= 1
    # the artifact's spill-manifest reference names the tier state
    assert out["tiered"]["spill_digest"]
    # seeded chaos is reproducible: outcome byte-identical across runs
    assert outs[0] == outs[1]
