import numpy as np
import pytest

from paddlebox_tpu.data import (
    BatchBuilder, CriteoParser, DataFeedDesc, DatasetFactory, InMemoryDataset,
    PaddleBoxDataset, QueueDataset, SlotDef, SlotTextParser, get_parser,
)
from paddlebox_tpu.data.criteo import generate_criteo_files


def small_desc(bs=4):
    return DataFeedDesc(
        slots=[
            SlotDef("label", "float", 1),
            SlotDef("s1", "uint64"),
            SlotDef("s2", "uint64"),
            SlotDef("d1", "float", 2),
        ],
        batch_size=bs, parser="slot_text", label_slot="label",
        key_bucket_min=8,
    )


def test_slot_text_parser_roundtrip():
    p = SlotTextParser(small_desc())
    rec = p.parse("1 1.0 2 11 12 1 21 2 0.5 0.25")
    assert rec is not None
    assert rec.label == 1.0
    np.testing.assert_array_equal(rec.slot_keys(0), np.array([11, 12], np.uint64))
    np.testing.assert_array_equal(rec.slot_keys(1), np.array([21], np.uint64))
    np.testing.assert_allclose(rec.dense, [0.5, 0.25])
    # malformed lines dropped, not raised
    assert p.parse("garbage") is None
    assert p.parse("1 1.0 5 1 2") is None


def test_criteo_parser_slot_salting():
    desc = DataFeedDesc.criteo(batch_size=2)
    p = CriteoParser(desc)
    line = "1\t" + "\t".join(str(i) for i in range(13)) + "\t" + "\t".join("ab" for _ in range(26))
    rec = p.parse(line)
    assert rec is not None and rec.num_keys == 26
    # same hex value in different slots must map to different keys
    assert len(np.unique(rec.keys)) == 26
    assert rec.label == 1.0
    # missing dense + missing categorical tolerated
    line2 = "0\t" + "\t".join("" for _ in range(13)) + "\t" + "\t".join("" for _ in range(26))
    rec2 = p.parse(line2)
    assert rec2 is not None and np.all(rec2.dense == 0)


def test_batch_builder_layout():
    desc = small_desc(bs=3)
    p = get_parser(desc)
    recs = [
        p.parse("1 0.0 2 11 12 1 21 2 0.5 0.25"),
        p.parse("1 1.0 1 13 2 22 23 2 0.1 0.2"),
    ]
    b = BatchBuilder(desc).build(recs)
    S = 2
    assert b.num_slots == S and b.batch_size == 3
    assert b.num_keys == 6
    assert b.key_capacity == 8  # bucket_min
    # segments: rec0 slot0 x2 =0,0; rec0 slot1 x1 =1; rec1 slot0 x1 =2; rec1 slot1 x2 =3,3
    np.testing.assert_array_equal(b.segments[:6], [0, 0, 1, 2, 3, 3])
    assert np.all(b.segments[6:] == b.pad_segment)
    np.testing.assert_array_equal(b.keys[:6], [11, 12, 21, 13, 22, 23])
    # short batch: padding instances have show == 0
    assert b.show[2] == 0.0 and b.show[0] == 1.0


def test_key_bucket_ladder():
    desc = small_desc()
    assert desc.key_capacity(1) == 8
    assert desc.key_capacity(9) == 16
    assert desc.key_capacity(16) == 16
    assert desc.key_capacity(100) == 128


def test_in_memory_dataset_end_to_end(tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=2, rows_per_file=200)
    desc = DataFeedDesc.criteo(batch_size=64)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    assert len(ds) == 400
    ds.local_shuffle(seed=1)
    keys = ds.pass_keys()
    assert keys.dtype == np.uint64 and len(keys) == len(np.unique(keys)) > 0
    batches = list(ds.batches())
    assert sum(b.show.sum() for b in batches) == 400  # every record counted once
    assert all(b.keys.shape[0] == b.key_capacity for b in batches)


def test_queue_dataset_streams(tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=1, rows_per_file=100)
    desc = DataFeedDesc.criteo(batch_size=32)
    ds = DatasetFactory().create_dataset("QueueDataset", desc)
    ds.set_filelist(files)
    total = 0
    nb = 0
    for b in ds.batches():
        total += int(b.show.sum())
        nb += 1
    assert total == 100 and nb == 4  # 3 full + 1 tail


def test_paddlebox_dataset_pass_lifecycle(tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=1, rows_per_file=50)
    ds = DatasetFactory().create_dataset("PaddleBoxDataset", DataFeedDesc.criteo(16))
    ds.set_filelist(files)
    ds.set_date("20260729")
    events = []
    ds.on_begin_pass = lambda d: events.append(("begin", d.pass_id))
    ds.on_end_pass = lambda d, save: events.append(("end", d.pass_id, save))
    ds.preload_into_memory()
    ds.wait_preload_done()
    assert len(ds) == 50
    ds.begin_pass()
    ds.end_pass(need_save_delta=True)
    assert events == [("begin", 1), ("end", 1, True)]
    assert len(ds) == 0  # released

    # preload error surfaces at wait
    ds.set_filelist(["/nonexistent/file.txt"])
    ds.preload_into_memory()
    with pytest.raises(FileNotFoundError):
        ds.wait_preload_done()


def test_factory_rejects_unknown():
    with pytest.raises(KeyError):
        DatasetFactory().create_dataset("NoSuchDataset")


def test_columnar_batches_match_record_batches(tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=1,
                                  rows_per_file=150)
    desc = DataFeedDesc.criteo(batch_size=64)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    rec_batches = list(ds.batches())
    ds.columnarize()
    col_batches = list(ds.batches())
    assert len(ds) == 150
    assert len(rec_batches) == len(col_batches)
    for rb, cb in zip(rec_batches, col_batches):
        np.testing.assert_array_equal(rb.keys, cb.keys)
        np.testing.assert_array_equal(rb.segments, cb.segments)
        np.testing.assert_allclose(rb.dense, cb.dense)
        np.testing.assert_allclose(rb.label, cb.label)
        np.testing.assert_allclose(rb.show, cb.show)
        np.testing.assert_allclose(rb.clk, cb.clk)
        np.testing.assert_array_equal(rb.uid, cb.uid)
        np.testing.assert_array_equal(rb.rank, cb.rank)
        np.testing.assert_array_equal(rb.cmatch, cb.cmatch)
    # shuffle on columnar keeps the multiset of labels/keys
    keys_before = np.sort(ds.columnar.keys)
    ds.local_shuffle(seed=3)
    np.testing.assert_array_equal(np.sort(ds.columnar.keys), keys_before)
