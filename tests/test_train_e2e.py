"""End-to-end: synthetic criteo → DeepFM/CtrDnn training learns signal
(parity checkpoint #1 of SURVEY.md §7 Phase 2, run on CPU)."""

import numpy as np
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.metrics import auc_compute
from paddlebox_tpu.models import CtrDnn, DeepFM
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=2000,
                                 vocab_per_slot=50, seed=7)


def make_trainer(model, files, bs=128, mf_dim=8):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    ds.local_shuffle(seed=0)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0,  # create mf immediately
                          mf_initial_range=1e-3,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = EmbeddingTable(mf_dim=mf_dim, capacity=1 << 14, cfg=cfg,
                           unique_bucket_min=4096)
    tr = Trainer(model, table, desc, tx=optax.adam(2e-3))
    return tr, ds


def test_deepfm_learns(criteo_files):
    with flags_scope(log_period_steps=1000):
        tr, ds = make_trainer(DeepFM(hidden=(64, 64)), criteo_files)
        r1 = tr.train_pass(ds)
        tr.reset_metrics()
        r2 = tr.train_pass(ds)  # second epoch
    assert np.isfinite(r1["last_loss"])
    assert r2["auc"] > 0.60, f"AUC too low: {r2['auc']}"
    assert r2["auc"] > r1["auc"] - 0.02
    assert 0.0 < r2["predicted_ctr"] < 1.0
    labels = (ds.columnar.label if ds.columnar is not None
              else np.array([rec.label for rec in ds.records]))
    assert abs(r2["actual_ctr"] - float(np.mean(labels))) < 1e-3
    # table grew and created mf vectors
    assert tr.table.feature_count > 100
    assert float(np.asarray(tr.state.table.mf_size).sum()) > 100


def test_ctr_dnn_smoke(criteo_files):
    with flags_scope(log_period_steps=1000):
        tr, ds = make_trainer(CtrDnn(hidden=(32, 32)), criteo_files)
        tr.train_pass(ds)
        tr.reset_metrics()
        res = tr.train_pass(ds)
    assert np.isfinite(res["last_loss"])
    assert res["auc"] > 0.55


def test_checkpoint_roundtrip(criteo_files, tmp_path):
    with flags_scope(log_period_steps=1000):
        tr, ds = make_trainer(DeepFM(hidden=(32,)), criteo_files)
        tr.train_pass(ds)
        prefix = str(tmp_path / "ckpt")
        tr.save(prefix)

        tr2, _ = make_trainer(DeepFM(hidden=(32,)), criteo_files)
        tr2.load(prefix)
        # same feature count and identical embed weights for a sample key
        assert tr2.table.feature_count == tr.table.feature_count
        ks, rs = tr.table.index.items()
        k = ks[:5]
        r_old = tr.table.index.lookup(k)
        r_new = tr2.table.index.lookup(k)
        np.testing.assert_allclose(
            np.asarray(tr2.table.state.embed_w)[r_new],
            np.asarray(tr.table.state.embed_w)[r_old], rtol=1e-6)


@pytest.mark.parametrize("model_name", ["wide_deep", "dcn_v2"])
def test_model_zoo_learns(criteo_files, model_name):
    from paddlebox_tpu.models import MODEL_REGISTRY
    cls = MODEL_REGISTRY[model_name]
    model = cls(hidden=(32, 32)) if model_name == "wide_deep" else \
        cls(num_cross_layers=2, hidden=(32,))
    with flags_scope(log_period_steps=1000):
        tr, ds = make_trainer(model, criteo_files)
        tr.train_pass(ds)
        tr.reset_metrics()
        res = tr.train_pass(ds)
    assert np.isfinite(res["last_loss"])
    assert res["auc"] > 0.58, f"{model_name} AUC too low: {res['auc']}"


def test_queue_dataset_streaming_train(criteo_files):
    """train_from_dataset over a QueueDataset: batches stream off reader
    threads straight into the jit step (no pass materialization)."""
    from paddlebox_tpu.data import DatasetFactory
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("QueueDataset", desc)
    ds.set_filelist(criteo_files)
    ds.set_thread(2)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 14, cfg=cfg,
                           unique_bucket_min=4096)
    with flags_scope(log_period_steps=10000):
        tr = Trainer(CtrDnn(hidden=(32,)), table, desc, tx=optax.adam(2e-3))
        r1 = tr.train_pass(ds)     # stream epoch 1
        tr.reset_metrics()
        r2 = tr.train_pass(ds)     # re-streams the files
    assert r1["batches"] > 0 and np.isfinite(r2["last_loss"])
    assert r2["auc"] > max(r1["auc"] - 0.02, 0.55)
    assert table.feature_count > 100
