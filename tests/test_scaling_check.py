"""scripts/scaling_check.py tier-1 wiring (ISSUE 11): chunked parity
end-to-end through train_pass on the in-process CPU mesh, and the
multichip bench rows landing well-formed in a trajectory (graceful skip
when subprocess devices are unavailable)."""

import importlib.util
import os

import pytest

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def sc():
    return _load("scaling_check", os.path.join("scripts",
                                               "scaling_check.py"))


def test_key_regex_wellformed(sc):
    ok = ("sharded.n1.uniform.ex_per_sec_per_chip",
          "sharded.n8.zipf.scaling_efficiency",
          # chunked-schedule ladders gate under their own keys
          "sharded.n4.uniform.c2.ex_per_sec_per_chip")
    bad = ("sharded.uniform.ex_per_sec_per_chip",
           "sharded.n2.uniform.examples",
           "deepfm_ctr_examples_per_sec_per_chip")
    for k in ok:
        assert sc.KEY_RE.match(k), k
    for k in bad:
        assert not sc.KEY_RE.match(k), k


def test_chunked_parity_through_train_pass(sc):
    """a2a_chunks=2 == a2a_chunks=1 digest, bit for bit, ×2 seeded
    runs — on this process's 8-device mesh (conftest)."""
    ok = sc.parity_check(rows_per_file=400)
    if ok is None:
        pytest.skip("no multi-device mesh in this process")
    assert ok is True


def test_multichip_rows_land_in_trajectory(sc):
    """BENCH_MODE=multichip subprocesses (1 and 2 virtual devices, tiny
    workload) emit well-formed sharded.n{N}.{shape}.* rows that pass
    the perf gate; SKIP (not fail) when the subprocess backend is
    unavailable."""
    status, rows = sc.bench_rows_check(ns="1,2", bs=128, gbatches=2,
                                       passes=2, timeout_s=300.0)
    if status == "skip":
        pytest.skip("multichip bench subprocesses unavailable")
    assert status == "ok"
    metrics = {r["metric"] for r in rows}
    assert "sharded.n2.uniform.scaling_efficiency" in metrics
    for r in rows:
        assert sc.KEY_RE.match(r["metric"])
        assert isinstance(r["value"], (int, float))
        assert r.get("n_chips") in (1, 2)
