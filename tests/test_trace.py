"""Causal pass tracing (obs/trace): span nesting/lanes, the Chrome
lane sink's tid rows + flow arrows, the critical-path block math, and
the cross-thread span contract over a REAL depth-2 tiered pipeline job
(ISSUE 10 acceptance surface)."""

import json
import threading

import jax
import numpy as np
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.obs import (ChromeLaneTraceSink, JsonlSink, MemorySink,
                               get_hub, reset_hub)
from paddlebox_tpu.obs import trace
from paddlebox_tpu.utils.profiler import ChromeTraceWriter

N = 8


@pytest.fixture()
def fresh_hub():
    hub = reset_hub()
    trace.reset()
    yield hub
    reset_hub()
    trace.reset()


# ---- span layer --------------------------------------------------------
def test_span_inert_without_sinks(fresh_hub):
    assert not trace.tracing_active()
    with trace.span("x") as h:
        assert h is trace.NULL_SPAN
        assert h.span_id == 0
    assert fresh_hub.snapshot() == {}  # no instrument was created


def test_span_nesting_and_parent_ids(fresh_hub):
    w = ChromeTraceWriter()
    fresh_hub.add_sink(ChromeLaneTraceSink(w))
    assert trace.tracing_active()
    with trace.span("outer") as ho:
        assert trace.current_span_id() == ho.span_id
        with trace.span("inner") as hi:
            assert hi.span_id != ho.span_id
            assert trace.current_span_id() == hi.span_id
        assert trace.current_span_id() == ho.span_id
    assert trace.current_span_id() == 0
    evs = {e["name"]: e for e in w._events if e["ph"] == "X"}
    assert evs["inner"]["args"]["parent_id"] == ho.span_id
    assert "parent_id" not in evs["outer"]["args"]
    # only the TOP-LEVEL span books lane-busy seconds (children are
    # contained in the parent's wall)
    busy = fresh_hub.counter("pbox_lane_busy_seconds_total", "x")
    assert busy.value(lane="main") > 0


def test_lane_scope_and_set_lane(fresh_hub):
    fresh_hub.add_sink(ChromeLaneTraceSink(ChromeTraceWriter()))
    assert trace.current_lane() == trace.LANE_MAIN
    with trace.lane_scope("ssd.compact"):
        assert trace.current_lane() == "ssd.compact"
        with trace.span("inside") as h:
            assert h.lane == "ssd.compact"
    assert trace.current_lane() == trace.LANE_MAIN
    seen = {}

    def worker():
        seen["default"] = trace.current_lane()
        trace.set_lane("preload.worker")
        seen["set"] = trace.current_lane()

    t = threading.Thread(target=worker, name="pbox-t")
    t.start()
    t.join()
    assert seen["default"] == "pbox-t"      # thread name fallback
    assert seen["set"] == "preload.worker"


def test_chrome_lane_sink_rows_and_flow(fresh_hub):
    """Per-lane tid rows with thread_name metadata; a link_from span
    draws a flow arrow from source end to destination start."""
    w = ChromeTraceWriter()
    fresh_hub.add_sink(ChromeLaneTraceSink(w))
    with trace.span("pass.build", lane="preload.worker") as hb:
        pass
    with trace.span("pass.consume", lane="main",
                    link_from=hb.span_id):
        pass
    metas = [e for e in w._events
             if e["ph"] == "M" and e["name"] == "thread_name"]
    names = {e["args"]["name"]: e["tid"] for e in metas}
    assert set(names) == {"preload.worker", "main"}
    assert names["preload.worker"] != names["main"]
    flows = [e for e in w._events if e["ph"] in ("s", "f")]
    assert len(flows) == 2
    start = next(e for e in flows if e["ph"] == "s")
    end = next(e for e in flows if e["ph"] == "f")
    assert start["id"] == end["id"] == hb.span_id
    assert start["tid"] == names["preload.worker"]
    assert end["tid"] == names["main"]
    assert end.get("bp") == "e"
    assert start["ts"] <= end["ts"]
    # the trace JSON round-trips
    spans = [e for e in w._events if e["ph"] == "X"]
    assert {e["name"] for e in spans} == {"pass.build", "pass.consume"}
    json.dumps(w._events)


def test_cross_thread_span_links(fresh_hub):
    """The producer stashes its span id; a consumer on another thread
    links — the real PassPreloader hand-off shape."""
    w = ChromeTraceWriter()
    fresh_hub.add_sink(ChromeLaneTraceSink(w))
    box = {}

    def producer():
        trace.set_lane("preload.worker")
        with trace.span("pass.build") as h:
            pass
        box["sid"] = h.span_id

    t = threading.Thread(target=producer)
    t.start()
    t.join()
    with trace.span("pass.consume", link_from=box["sid"]):
        pass
    flows = [e for e in w._events if e["ph"] in ("s", "f")]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert all(e["id"] == box["sid"] for e in flows)


def test_plain_span_sinks_receive_causal_spans(fresh_hub):
    """A sink with only the PR 1 span(name, start, dur, attrs) surface
    still receives causal spans (lane/pass_seq folded into attrs) —
    the add_sink dual/kind semantics themselves are covered in
    tests/test_obs.py."""

    class PlainSink:
        def __init__(self):
            self.spans = []

        def span(self, name, start, dur, attrs):
            self.spans.append((name, attrs))

        def close(self):
            pass

    sink = PlainSink()
    fresh_hub.add_sink(sink)
    with fresh_hub.span("stage_y"):
        pass
    with trace.span("causal_z", pass_seq=3):
        pass
    assert [n for n, _ in sink.spans] == ["stage_y", "causal_z"]
    attrs = sink.spans[1][1]
    assert attrs["lane"] == "main" and attrs["pass_seq"] == 3
    with pytest.raises(TypeError):
        fresh_hub.add_sink(sink.spans, kind="span")  # list: no span()


# ---- critical-path math ------------------------------------------------
def test_critical_path_block_sums_and_verdicts(fresh_hub):
    # device-bound: train dominates
    blk = trace.critical_path_block(1.0, {"build_wait": 0.2,
                                          "stage_wait": 0.1})
    assert blk["bottleneck"] == "device"
    assert blk["wall_sec"] == pytest.approx(1.3)
    assert blk["train_sec"] == pytest.approx(1.0)
    assert blk["stall_sec"] == pytest.approx(0.3)
    # build-bound: the largest stall beats train
    blk = trace.critical_path_block(0.5, {"build_wait": 0.74,
                                          "fence_wait": 0.1})
    assert blk["bottleneck"] == "build_wait"
    assert blk["stall_sec"] == pytest.approx(0.74)
    assert blk["wall_sec"] == pytest.approx(0.5 + 0.74 + 0.1)
    # no parts at all → trivially device-bound, wall == train
    blk = trace.critical_path_block(2.0, {})
    assert blk["bottleneck"] == "device"
    assert blk["wall_sec"] == pytest.approx(2.0)
    # zero/negative parts are dropped
    blk = trace.critical_path_block(1.0, {"stage_wait": 0.0,
                                          "end_submit": -1.0})
    assert blk["wall_sec"] == pytest.approx(1.0)


def test_note_and_consume_pass_parts(fresh_hub):
    fresh_hub.add_sink(MemorySink())
    trace.note_pass_part("build_wait", 0.5)
    trace.note_pass_part("build_wait", 0.25)
    trace.note_pass_part("stage_wait", 0.1)
    trace.note_pass_part("fence_wait", 0.0)   # dropped
    parts = trace.consume_pass_parts()
    assert parts == {"build_wait": 0.75, "stage_wait": 0.1}
    assert trace.consume_pass_parts() == {}   # consumed exactly once


def test_parts_inert_without_sinks(fresh_hub):
    trace.note_pass_part("build_wait", 1.0)
    assert trace.consume_pass_parts() == {}


def test_pass_event_carries_critical_path(fresh_hub):
    from paddlebox_tpu.obs.hub import emit_pass_event
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    trace.note_pass_part("build_wait", 0.74)
    emit_pass_event("train_pass_resident",
                    {"batches": 4, "elapsed_sec": 0.5})
    ev = next(e for e in sink.events if e["event"] == "pass")
    cp = ev["critical_path"]
    assert cp["bottleneck"] == "build_wait"
    assert cp["wall_sec"] == pytest.approx(1.24)
    assert fresh_hub.counter("pbox_pass_bottleneck_total", "x").value(
        stage="build_wait") == 1


# ---- the real thing: depth-2 tiered pipeline --------------------------
@pytest.fixture(scope="module")
def mesh():
    from paddlebox_tpu.parallel import make_mesh
    assert len(jax.devices()) >= N
    return make_mesh(N)


def _mk_ds(tmp_path, seed):
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    files = generate_criteo_files(str(tmp_path / f"tr{seed}"),
                                  num_files=1, rows_per_file=600,
                                  vocab_per_slot=50, seed=seed)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def test_depth2_tiered_job_emits_linked_lane_spans(mesh, tmp_path):
    """ISSUE 10 satellite: a depth-2 tiered job emits linked
    build/stage/consume/epilogue spans with correct lane labels, and
    each pass event's critical-path block sums (within tolerance) to
    the measured pass wall."""
    import time as _time

    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer
    hub = reset_hub()
    trace.reset()
    writer = ChromeTraceWriter()
    sink = ChromeLaneTraceSink(writer)
    mem = MemorySink()
    hub.add_sink(sink)
    hub.add_sink(mem)
    try:
        built = [_mk_ds(tmp_path, s) for s in range(2)]
        datasets = [built[0][0], built[1][0], built[0][0]]
        desc = built[0][1]
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)
        table = TieredShardedEmbeddingTable(
            N, mf_dim=4, capacity_per_shard=512, cfg=cfg,
            req_bucket_min=256, serve_bucket_min=256,
            ssd_dir=str(tmp_path / "ssd"))
        with flags_scope(log_period_steps=10000):
            tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc,
                                mesh, tx=optax.adam(2e-3))
        pipe = tr.tiered_pass_pipeline(iter(datasets), depth=2)
        pipe.start_next()
        walls = []
        while True:
            t0 = _time.perf_counter()
            rp = pipe.wait()
            if rp is None:
                break
            pipe.begin_pass()
            pipe.start_next()
            tr.train_pass_resident(rp)
            pipe.end_pass()
            walls.append(_time.perf_counter() - t0)
        pipe.drain()
        table.fence()
    finally:
        reset_hub()
        trace.reset()

    spans = [e for e in writer._events if e["ph"] == "X"]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    # the four pipeline span kinds, one per pass
    for name in ("pass.build", "pass.stage", "pass.consume",
                 "pass.begin", "pass.end_submit",
                 "endpass.writeback"):
        assert len(by_name.get(name, [])) >= 3, \
            f"missing spans for {name}: {sorted(by_name)}"
    # lane labels are correct per span kind
    metas = {e["tid"]: e["args"]["name"] for e in writer._events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    lane_of = lambda e: metas[e["tid"]]
    assert all(lane_of(e) == "preload.worker"
               for e in by_name["pass.build"])
    assert all(lane_of(e) == "preload.worker"
               for e in by_name["pass.stage"])
    assert all(lane_of(e) == "main" for e in by_name["pass.consume"])
    assert all(lane_of(e) == "epilogue.lane"
               for e in by_name["endpass.writeback"])
    # stage is a CHILD of its build (same worker, nested)
    build_ids = {e["args"]["span_id"] for e in by_name["pass.build"]}
    assert all(e["args"].get("parent_id") in build_ids
               for e in by_name["pass.stage"])
    # the ssd maintenance lane rode the epilogue jobs
    assert any(lane_of(e) == "ssd.compact"
               for e in by_name.get("ssd.maintain", [])), \
        "ssd.maintain spans missing or mislabeled"
    # ≥4 distinct lanes in one trace
    assert {"main", "preload.worker", "epilogue.lane",
            "ssd.compact"} <= set(metas.values())
    # flow links: every consume links back to a build span id
    flows = [e for e in writer._events if e["ph"] in ("s", "f")]
    consume_links = {e["id"] for e in flows}
    assert build_ids & consume_links, \
        "no build→consume flow arrows recorded"
    # per-pass critical_path blocks sum (within tolerance) to the
    # measured pass wall: sum over passes to absorb the end_submit /
    # fence parts booking into the NEXT pass's block
    cps = [e["critical_path"] for e in mem.events
           if e.get("event") == "pass" and "critical_path" in e]
    assert len(cps) == len(walls) == 3
    block_total = sum(cp["wall_sec"] for cp in cps)
    wall_total = sum(walls)
    assert block_total <= wall_total * 1.05 + 0.05
    assert block_total >= wall_total * 0.5 - 0.05, \
        (block_total, wall_total, cps)
    for cp in cps:
        parts = sum(v for k, v in cp.items()
                    if k.endswith("_sec") and k not in ("wall_sec",
                                                        "train_sec",
                                                        "stall_sec"))
        assert cp["wall_sec"] == pytest.approx(
            cp["train_sec"] + parts, rel=1e-6, abs=1e-6)
        assert cp["bottleneck"] in ("device", "build_wait",
                                    "stage_wait", "fence_wait",
                                    "ssd_promote", "evict_emergency",
                                    "evict_scatter", "end_submit")


def test_jsonl_report_renders_bottleneck_column(tmp_path, fresh_hub):
    """telemetry_report renders the per-pass bottleneck column and the
    whole-run critical-path summary from synthetic events."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    events = []
    for i in range(8):
        # pass 2: the build stall (0.74s) exceeds its train (0.5s) —
        # the one build-bound pass of the run
        train = 0.5 if i == 1 else 1.0
        cp = (trace.critical_path_block(train, {"build_wait": 0.74})
              if i == 1 else
              trace.critical_path_block(train, {"build_wait": 0.01}))
        events.append({"event": "pass", "ts": i, "seq": i, "proc": 0,
                       "kind": "train_pass_resident", "pass_seq": i + 1,
                       "batches": 4, "examples": 100,
                       "elapsed_sec": train,
                       "examples_per_sec": 100.0 / train,
                       "critical_path": cp})
    report = mod.render_report(events)
    assert "bottleneck" in report
    assert "7/8 passes device-bound" in report
    assert "pass 2 build_wait-bound: +0.740s" in report
