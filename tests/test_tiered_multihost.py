"""Tiered sharded PS on a multi-controller mesh (ps/tiered_multihost.py):
per-process host tiers behind a global table — the pod topology where
each AIBox node owns its PS slice (box_wrapper.h:446-450, SURVEY §2.6).

Single-process test proves the mechanics (owned = all shards must equal
the plain tiered table bit-for-bit); the 2-process test proves the pod
split (each process's host tiers hold exactly its shards, training
matches the single-process oracle)."""

import os
import textwrap

import numpy as np
import jax
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import (BoxPSHelper, SparseSGDConfig,
                              TieredShardedEmbeddingTable)
from paddlebox_tpu.ps.tiered_multihost import MultihostTieredShardedTable
from paddlebox_tpu.train.sharded import ShardedTrainer

N = 8


def _cfg():
    return SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                           learning_rate=0.1, mf_learning_rate=0.1)


def _ds(tmp_path, seed=71):
    files = generate_criteo_files(str(tmp_path / f"mh{seed}"), num_files=1,
                                  rows_per_file=800, vocab_per_slot=40,
                                  seed=seed)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def test_multihost_tiered_single_process_matches_plain(tmp_path):
    """With one process owning every shard, the multihost table's
    local-scatter/reassembly path must reproduce the plain tiered table
    exactly (same AUC, same dense params, same host-tier content)."""
    assert len(jax.devices()) >= N
    mesh = make_mesh(N)
    ds, desc = _ds(tmp_path)

    def run(table):
        with flags_scope(log_period_steps=10000):
            tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc,
                                mesh, tx=optax.adam(2e-3), seed=5)
        helper = BoxPSHelper(table, trainer=tr)
        r = None
        for _ in range(2):
            helper.begin_pass(ds)
            r = tr.train_pass(ds)
            helper.end_pass(ds)
        return tr, r

    ta = TieredShardedEmbeddingTable(N, mf_dim=4, capacity_per_shard=2048,
                                     cfg=_cfg(), req_bucket_min=256,
                                     serve_bucket_min=256)
    tb = MultihostTieredShardedTable(mesh, mf_dim=4,
                                     capacity_per_shard=2048, cfg=_cfg(),
                                     req_bucket_min=256,
                                     serve_bucket_min=256)
    assert tb.owned == set(range(N))
    tra, ra = run(ta)
    trb, rb = run(tb)
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=1e-9), (ra["auc"],
                                                         rb["auc"])
    for x, y in zip(jax.tree.leaves(tra.state.params),
                    jax.tree.leaves(trb.state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for s in range(N):
        ka, _ = ta.hosts[s].index.items()
        kb, _ = tb.hosts[s].index.items()
        np.testing.assert_array_equal(np.sort(ka), np.sort(kb))
        a = ta.hosts[s].fetch(np.sort(ka))
        b = tb.hosts[s].fetch(np.sort(ka))
        np.testing.assert_array_equal(a["embed_w"], b["embed_w"])
        np.testing.assert_array_equal(a["show"], b["show"])
    # delta staging engaged on pass 2 identically
    assert tb.last_pass_stats["resident"] > 0
    assert tb.last_pass_stats["staged"] == ta.last_pass_stats["staged"]


MH_TIERED_WORKER = textwrap.dedent("""
    import os, sys, json
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()
    rank = info["rank"]
    import numpy as np
    import optax
    from paddlebox_tpu.config import FLAGS
    FLAGS.log_period_steps = 10 ** 9
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered_multihost import MultihostTieredShardedTable
    from paddlebox_tpu.train.multihost import (global_mesh, stage_global,
                                               stage_global_batch)
    from paddlebox_tpu.train.sharded import (ShardedTrainer,
                                             ShardedStepState,
                                             make_global_arrays)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from mh_common import build_case

    n = jax.device_count()
    assert n == 4, n
    mesh = global_mesh()
    desc, batches = build_case(n)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = MultihostTieredShardedTable(mesh, mf_dim=4,
                                        capacity_per_shard=512, cfg=cfg,
                                        req_bucket_min=16,
                                        serve_bucket_min=16)
    tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                        tx=optax.adam(1e-3))

    # the pass working set: all batch keys (identical on every process)
    keys = np.unique(np.concatenate(
        [b.keys[:b.num_keys] for b in batches]))
    table.begin_pass(keys)
    host = make_global_arrays(batches, table.prepare_global(batches))
    gb = stage_global_batch(mesh, host)
    st0 = tr.state
    state = ShardedStepState(
        table=table.state,
        params=jax.tree.map(lambda l: stage_global(
            mesh, np.asarray(jax.device_get(l)), shard_dim0=False),
            st0.params),
        opt_state=jax.tree.map(lambda l: stage_global(
            mesh, np.asarray(jax.device_get(l)), shard_dim0=False),
            st0.opt_state),
        auc=type(st0.auc)(*[stage_global(
            mesh, np.asarray(jax.device_get(l)), shard_dim0=True)
            for l in st0.auc]),
        step=stage_global(mesh, np.asarray(jax.device_get(st0.step)),
                          shard_dim0=False))
    losses = []
    for i in range(2):
        state, stats = tr.step_fn(state, gb, jax.random.PRNGKey(i))
        l = stats["loss"]
        l = (np.asarray(jax.device_get(l.addressable_shards[0].data))
             if hasattr(l, "addressable_shards") else np.asarray(l))
        losses.append(float(np.ravel(l)[0]))
    table.state = state.table
    table.end_pass()

    want = [float(x) for x in os.environ["ORACLE_LOSSES"].split(",")]
    for got, w in zip(losses, want):
        assert abs(got - w) < 1e-6, (losses, want)
    # each process's host tiers hold exactly its owned shards
    fp = {}
    for s in sorted(table.owned):
        ks, _ = table.hosts[s].index.items()
        ks = np.sort(ks)
        vals = table.hosts[s].fetch(ks)
        fp[str(s)] = [ks.tolist(),
                      np.round(vals["embed_w"], 6).tolist()]
    assert all(table.hosts[s] is None
               for s in range(n) if s not in table.owned)
    with open(os.path.join(os.environ["OUT_DIR"],
                           f"host_r{rank}.json"), "w") as fh:
        json.dump(fp, fh)
    print(f"rank={rank} tiered-mh ok losses={losses} "
          f"owned={sorted(table.owned)}", flush=True)
""")


@pytest.mark.slow
def test_two_process_tiered_matches_single_process(tmp_path):
    """The pod split: 2 processes × 2 devices form one 4-shard global
    mesh; each process's host tiers carry exactly its 2 shards. Step
    losses and every shard's written-back host values must match a
    single-process 4-shard tiered run of the same batches."""
    from test_multihost_jax import MH_COMMON, _run_two_workers
    import importlib.util
    import json

    common = tmp_path / "mh_common.py"
    common.write_text(MH_COMMON)
    spec = importlib.util.spec_from_file_location("mh_common", str(common))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    n = 4
    desc, batches = mod.build_case(n)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    oracle_table = TieredShardedEmbeddingTable(
        n, mf_dim=4, capacity_per_shard=512, cfg=cfg,
        req_bucket_min=16, serve_bucket_min=16)
    with flags_scope(log_period_steps=10 ** 9):
        tr = ShardedTrainer(DeepFM(hidden=(16, 16)), oracle_table, desc,
                            make_mesh(n), tx=optax.adam(1e-3))
    keys = np.unique(np.concatenate(
        [b.keys[:b.num_keys] for b in batches]))
    oracle_table.begin_pass(keys)
    from paddlebox_tpu.train.sharded import make_global_batch
    gb = make_global_batch(batches, oracle_table.prepare_global(batches))
    state = tr.state
    oracle = []
    for i in range(2):
        state, stats = tr.step_fn(state, gb, jax.random.PRNGKey(i))
        oracle.append(float(stats["loss"]))
    oracle_table.state = state.table
    oracle_table.end_pass()

    out_dir = tmp_path / "out"
    out_dir.mkdir()
    outs = _run_two_workers(
        tmp_path, MH_TIERED_WORKER, "w_tiered.py",
        extra_env={"ORACLE_LOSSES": ",".join(f"{x:.9f}" for x in oracle),
                   "OUT_DIR": str(out_dir)})
    for r, o in enumerate(outs):
        assert f"rank={r} tiered-mh ok" in o, o

    # union of the two processes' host tiers == the oracle's, shard by
    # shard, value for value
    seen = set()
    for r in range(2):
        fp = json.load(open(out_dir / f"host_r{r}.json"))
        for s_str, (ks, ws) in fp.items():
            s = int(s_str)
            assert s not in seen  # each shard owned by exactly one rank
            seen.add(s)
            ka, _ = oracle_table.hosts[s].index.items()
            ka = np.sort(ka)
            np.testing.assert_array_equal(np.asarray(ks, np.uint64), ka)
            want = oracle_table.hosts[s].fetch(ka)["embed_w"]
            np.testing.assert_allclose(np.asarray(ws), want, atol=2e-6)
    assert seen == set(range(n))


MH_TIERED_ELASTIC_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.distributed.launch import init_runtime_env
    info = init_runtime_env()
    rank = info["rank"]
    import numpy as np
    import optax
    from paddlebox_tpu.config import FLAGS
    FLAGS.log_period_steps = 10 ** 9
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import BoxPSHelper, SparseSGDConfig
    from paddlebox_tpu.ps.tiered_multihost import MultihostTieredShardedTable
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    from paddlebox_tpu.train.multihost import global_mesh
    from paddlebox_tpu.train.sharded import ShardedTrainer

    out_dir = sys.argv[1]
    kill_after = os.environ.get("KILL_AFTER_PASS")
    resume = os.environ.get("RESUME") == "1"
    n_passes = int(os.environ["N_PASSES"])

    n = jax.device_count()
    assert n == 4, n
    mesh = global_mesh()

    # identical datasets on every process (the SPMD host contract);
    # two "days" with offset value ranges exercise the delta chain
    dss = []
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    for i, base in enumerate((0, 700)):
        files = generate_criteo_files(
            os.path.join(out_dir, f"data{i}"), num_files=1,
            rows_per_file=400, vocab_per_slot=25, seed=60 + i,
            value_base=base)
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()
        dss.append(ds)

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = MultihostTieredShardedTable(mesh, mf_dim=4,
                                        capacity_per_shard=2048, cfg=cfg,
                                        req_bucket_min=128,
                                        serve_bucket_min=128)
    tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                        tx=optax.adam(2e-3))
    tr.globalize_dense_state()   # table leaf is already a global array
    helper = BoxPSHelper(table, trainer=tr)
    nb_per_pass = sum(1 for _ in tr._group_iter(dss[0].batches()))

    # PER-PROCESS checkpoint dir: each rank's base+delta chain carries
    # its OWNED shards' host tiers (the per-node SaveBase convention)
    cm = CheckpointManager(os.path.join(out_dir, f"ckpt_r{rank}"),
                           keep=10)
    start_pass = 0
    if resume:
        restored = cm.restore(tr)   # LoadSSD2Mem role: rebuilds owned
        assert restored is not None # host tiers + drop_window + dense
        start_pass = restored // nb_per_pass
        print(f"rank {rank}: resumed at pass {start_pass}", flush=True)

    res = None
    for p in range(start_pass, n_passes):
        ds = dss[p % 2]
        helper.begin_pass(ds)
        res = tr.train_pass(ds)
        helper.end_pass(ds)
        if kill_after is not None and not resume \\
                and p == int(kill_after):
            # the gang dies WITHOUT saving this pass (its work is lost;
            # the restarted gang replays it from the chain)
            os._exit(1)
        cm.save(tr, delta=(p > 0))

    params = np.concatenate([np.asarray(l).ravel()
                             for l in jax.tree.leaves(tr.state.params)])
    fp = {}
    for s in sorted(table.owned):
        ks, _ = table.hosts[s].index.items()
        ks = np.sort(ks)
        vals = table.hosts[s].fetch(ks)
        fp[str(s)] = [int(len(ks)),
                      float(np.abs(vals["embed_w"]).sum()),
                      float(np.abs(vals["embedx_w"]).sum())]
    out = dict(rank=rank, auc=float(res["auc"]),
               step=int(tr.global_step),
               param_sum=float(np.abs(params).sum()), hosts=fp)
    with open(os.path.join(out_dir, f"final_r{rank}.json"), "w") as fh:
        json.dump(out, fh)
    np.save(os.path.join(out_dir, f"params_r{rank}.npy"), params)
    print(f"rank={rank} elastic-mh ok step={tr.global_step}", flush=True)
""")


@pytest.mark.slow
def test_pod_topology_elastic_recovery(tmp_path):
    """Elastic recovery of the POD topology (VERDICT r4 item 4): a
    2-process global-mesh gang over MultihostTieredShardedTable dies
    mid-run WITHOUT saving its in-flight pass; the restarted gang's
    ranks rebuild their OWNED shards' host tiers from their per-process
    save_base + delta chains (LoadSSD2Mem on recovery,
    box_wrapper.cc:1415; load → drop_window is the recovery entry),
    resume at the last pass boundary, and the final params + per-shard
    host-tier content match an uninterrupted run."""
    import json

    from test_multihost_jax import _run_two_workers

    n_passes = 4

    def run(sub, kill, resume):
        out = tmp_path / sub
        out.mkdir(exist_ok=True)
        env = {"N_PASSES": str(n_passes)}
        if kill is not None:
            env["KILL_AFTER_PASS"] = str(kill)
        if resume:
            env["RESUME"] = "1"
        try:
            _run_two_workers(tmp_path, MH_TIERED_ELASTIC_WORKER,
                             f"w_el_{sub}_{resume}.py", extra_env=env,
                             argv=[str(out)])
            return True
        except AssertionError:
            return False

    # attempt 1 dies after pass 1 (unsaved); the "replacement" gang
    # resumes from the per-rank chains and completes
    assert not run("killed", kill=1, resume=False)
    assert run("killed", kill=None, resume=True)
    # uninterrupted oracle
    assert run("clean", kill=None, resume=False)

    for r in range(2):
        a = json.load(open(tmp_path / "killed" / f"final_r{r}.json"))
        b = json.load(open(tmp_path / "clean" / f"final_r{r}.json"))
        assert a["step"] == b["step"]
        assert np.isclose(a["auc"], b["auc"], atol=1e-6), (a, b)
        assert a["hosts"].keys() == b["hosts"].keys()
        for s in a["hosts"]:
            na, wa, xa = a["hosts"][s]
            nb_, wb, xb = b["hosts"][s]
            assert na == nb_, (s, a, b)
            assert np.isclose(wa, wb, rtol=1e-6), (s, a, b)
            assert np.isclose(xa, xb, rtol=1e-6), (s, a, b)
        pa = np.load(tmp_path / "killed" / f"params_r{r}.npy")
        pb = np.load(tmp_path / "clean" / f"params_r{r}.npy")
        np.testing.assert_allclose(pa, pb, rtol=1e-6, atol=1e-7)
