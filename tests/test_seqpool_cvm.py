"""Numpy-reference tests for fused_seqpool_cvm / cvm — the OpTest pattern
(reference: python/paddle/fluid/tests/unittests/test_cvm_op.py,
test_fusion_seqpool_cvm_concat_op.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.ops import cvm, fused_seqpool_cvm


def make_batch(B=3, S=2, D=4, max_len=3, seed=0):
    """Random ragged batch in the flattened segment layout."""
    rng = np.random.default_rng(seed)
    lens = rng.integers(0, max_len + 1, size=(B, S))
    segs, vals = [], []
    for i in range(B):
        for s in range(S):
            for _ in range(lens[i, s]):
                segs.append(i * S + s)
                vals.append(rng.uniform(0, 2, size=D))
    K = len(segs)
    cap = 1 << max(3, (K - 1).bit_length())
    values = np.zeros((cap, D), np.float32)
    segments = np.full(cap, B * S, np.int32)
    if K:
        values[:K] = np.array(vals, np.float32)
        segments[:K] = np.array(segs, np.int32)
    return values, segments, lens


def ref_seqpool_cvm(values, segments, B, S, use_cvm=True, cvm_offset=2,
                    need_filter=False, show_coeff=0.2, clk_coeff=1.0,
                    threshold=0.96, quant_ratio=0):
    # accumulate in f32: the reference CUDA kernel sums in double
    # (fused_seqpool_cvm_op.cu:50 `double val`), but f32 is the TPU-native
    # accumulator; deviation is ~1e-4 relative, below AUC-affecting scale.
    D = values.shape[1]
    pooled = np.zeros((B * S, D), np.float32)
    for k in range(values.shape[0]):
        seg = segments[k]
        if seg >= B * S:
            continue
        v = values[k].astype(np.float32)
        if need_filter:
            show, clk = v[0], v[1]
            if (show - clk) * show_coeff + clk * clk_coeff < threshold:
                continue
        if quant_ratio > 0:
            q = np.floor(v * quant_ratio + 0.5) / quant_ratio
            v = np.concatenate([v[:cvm_offset], q[cvm_offset:]])
        pooled[seg] += v
    pooled = pooled.reshape(B, S, D)
    if use_cvm:
        out = pooled.copy()
        out[..., 0] = np.log1p(pooled[..., 0])
        out[..., 1] = np.log1p(pooled[..., 1]) - np.log1p(pooled[..., 0])
        return out
    return pooled[..., cvm_offset:]


@pytest.mark.parametrize("use_cvm", [True, False])
@pytest.mark.parametrize("need_filter,quant_ratio", [(False, 0), (True, 128)])
def test_fused_seqpool_cvm_forward(use_cvm, need_filter, quant_ratio):
    B, S, D = 4, 3, 5
    values, segments, _ = make_batch(B, S, D, seed=1)
    bsc = np.ones((B, 2), np.float32)
    out = fused_seqpool_cvm(
        jnp.asarray(values), jnp.asarray(segments), jnp.asarray(bsc),
        B, S, use_cvm, 2, 0.0, need_filter, 0.2, 1.0, 0.96, quant_ratio)
    ref = ref_seqpool_cvm(values, segments, B, S, use_cvm,
                          need_filter=need_filter, quant_ratio=quant_ratio)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_fused_seqpool_cvm_empty_slots_zero():
    # zero-length slots must pool to zeros (log1p(0)=0) — the PaddingZeros
    # contract (pull_box_sparse_op.h:31)
    B, S, D = 2, 2, 4
    values = np.zeros((8, D), np.float32)
    segments = np.full(8, B * S, np.int32)  # everything is padding
    out = fused_seqpool_cvm(
        jnp.asarray(values), jnp.asarray(segments),
        jnp.ones((B, 2), jnp.float32), B, S, True, 2)
    np.testing.assert_allclose(np.asarray(out), np.zeros((B, S, D)), atol=1e-7)


def test_fused_seqpool_cvm_backward_contract():
    """Embedx dims: upstream grad broadcast to every item; cvm dims: batch
    show/clk values; padding/filtered rows: zero."""
    B, S, D = 2, 2, 4
    values, segments, _ = make_batch(B, S, D, seed=2)
    bsc = np.tile(np.array([[3.0, 1.5]], np.float32), (B, 1))

    def loss(v):
        out = fused_seqpool_cvm(v, jnp.asarray(segments), jnp.asarray(bsc),
                                B, S, True, 2)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    g = jax.grad(loss)(jnp.asarray(values))
    g = np.asarray(g)
    w = np.arange(B * S * D).reshape(B, S, D).astype(np.float32)
    for k in range(values.shape[0]):
        seg = segments[k]
        if seg >= B * S:
            np.testing.assert_array_equal(g[k], 0)
            continue
        i, s = divmod(seg, S)
        np.testing.assert_allclose(g[k, 2:], w[i, s, 2:], rtol=1e-6)
        np.testing.assert_allclose(g[k, :2], bsc[i], rtol=1e-6)


def test_fused_seqpool_cvm_filter_zeroes_grad():
    B, S, D = 1, 1, 4
    values = np.array([[0.1, 0.0, 5.0, 5.0],      # filtered out
                       [1.0, 1.0, 2.0, 2.0]], np.float32)  # kept
    segments = np.array([0, 0], np.int32)
    bsc = np.ones((1, 2), np.float32)

    def loss(v):
        return jnp.sum(fused_seqpool_cvm(
            v, jnp.asarray(segments), jnp.asarray(bsc), B, S,
            True, 2, 0.0, True, 0.2, 1.0, 0.96, 0))

    g = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    np.testing.assert_array_equal(g[0], 0)
    assert np.all(g[1, 2:] == 1.0)


def test_cvm_op():
    B, D = 3, 5
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 3, size=(B, D)).astype(np.float32)
    bcvm = rng.uniform(0, 2, size=(B, 2)).astype(np.float32)
    y = np.asarray(cvm(jnp.asarray(x), jnp.asarray(bcvm), True))
    np.testing.assert_allclose(y[:, 0], np.log1p(x[:, 0]), rtol=1e-6)
    np.testing.assert_allclose(
        y[:, 1], np.log1p(x[:, 1]) - np.log1p(x[:, 0]), rtol=1e-6)
    np.testing.assert_allclose(y[:, 2:], x[:, 2:])
    y2 = np.asarray(cvm(jnp.asarray(x), jnp.asarray(bcvm), False))
    np.testing.assert_allclose(y2, x[:, 2:])
    # backward: dx[:, :2] = CVM values; dx[:, 2:] = upstream
    g = np.asarray(jax.grad(
        lambda v: jnp.sum(cvm(v, jnp.asarray(bcvm), True)))(jnp.asarray(x)))
    np.testing.assert_allclose(g[:, :2], bcvm, rtol=1e-6)
    np.testing.assert_allclose(g[:, 2:], 1.0)


def ref_full_attrs(values, segments, lens, B, S, use_cvm, cvm_offset=2,
                   need_filter=False, show_coeff=0.2, clk_coeff=1.0,
                   threshold=0.96, clk_filter=False,
                   embed_threshold_filter=False, embed_threshold=0.0,
                   embed_thres_size=0, embedx_concate_size=1,
                   embedx_concate_filter=False):
    """Numpy transcription of the attr-complete kernels
    (fused_seqpool_cvm_op.cu:134-176 filter, :301-352 WithShow[Concate],
    :355-405 NoCVM[Concate])."""
    D = values.shape[1]
    kk = embedx_concate_size

    def keep_of(v):
        ok = True
        if need_filter or embed_threshold_filter:
            ok = (v[0] - v[1]) * show_coeff + v[1] * clk_coeff >= threshold
        if ok and embed_threshold_filter:
            ets = embed_thres_size if embed_thres_size > 0 else D - cvm_offset
            e = v[cvm_offset:cvm_offset + ets]
            score = np.sqrt((e[1:] ** 2).sum()) + abs(e[0])
            ok = score >= embed_threshold
        return ok

    # group keys per (ins, slot) in order
    groups = [[] for _ in range(B * S)]
    ki = 0
    for i in range(B):
        for s in range(S):
            for _ in range(lens[i, s]):
                groups[i * S + s].append(values[ki])
                ki += 1
    if use_cvm and not clk_filter:
        kk = 1  # reference has no concate kernel for plain CVM
    pooled = np.zeros((B * S, kk, D), np.float32)
    for gidx, grp in enumerate(groups):
        if kk == 1:
            for v in grp:
                if keep_of(v):
                    pooled[gidx, 0] += v
        else:
            for j in range(min(kk, len(grp))):
                v = grp[j]
                if embedx_concate_filter and not keep_of(v):
                    continue
                pooled[gidx, j] += v
    if use_cvm:
        show_l = np.log1p(pooled[..., 0:1])
        if clk_filter:
            out = np.concatenate([show_l, pooled[..., cvm_offset:]], axis=-1)
        else:
            ctr = np.log1p(pooled[..., 1:2]) - show_l
            out = np.concatenate([show_l, ctr, pooled[..., cvm_offset:]],
                                 axis=-1)
    else:
        out = pooled[..., cvm_offset + embed_thres_size:]
    return out.reshape(B, S, -1)


@pytest.mark.parametrize("use_cvm,clk_filter,ets,kk", [
    (True, True, 0, 1),      # clk_filter output head
    (False, False, 1, 1),    # embed_thres_size no-cvm width cut
    (True, False, 0, 2),     # concate IGNORED in plain-CVM mode
    (True, True, 0, 3),      # clk_filter + concate
    (False, False, 1, 2),    # no-cvm + thres + concate
])
def test_seqpool_new_attrs_forward(use_cvm, clk_filter, ets, kk):
    B, S, D = 3, 2, 5
    values, segments, lens = make_batch(B, S, D, max_len=4, seed=7)
    show_clk = np.random.default_rng(1).uniform(
        0, 2, size=(B, 2)).astype(np.float32)
    out = fused_seqpool_cvm(
        jnp.asarray(values), jnp.asarray(segments), jnp.asarray(show_clk),
        B, S, use_cvm, 2, 0.0, False, 0.2, 1.0, 0.96, 0,
        clk_filter, False, 0.0, ets, kk, False)
    ref = ref_full_attrs(values[:int(lens.sum())], segments, lens, B, S,
                         use_cvm, clk_filter=clk_filter,
                         embed_thres_size=ets, embedx_concate_size=kk)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_seqpool_embed_threshold_filter():
    B, S, D = 2, 2, 5
    values, segments, lens = make_batch(B, S, D, max_len=3, seed=3)
    nk = int(lens.sum())
    # make every key pass the show/clk test, differ on embed magnitude
    values[:nk, 0] = 5.0
    values[:nk, 1] = 1.0
    show_clk = np.ones((B, 2), np.float32)
    thr = 1.5
    out = fused_seqpool_cvm(
        jnp.asarray(values), jnp.asarray(segments), jnp.asarray(show_clk),
        B, S, True, 2, 0.0, False, 0.2, 1.0, 0.0, 0,
        False, True, thr, 0, 1, False)
    ref = ref_full_attrs(values[:nk], segments, lens, B, S, True,
                         embed_threshold_filter=True, embed_threshold=thr,
                         threshold=0.0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_seqpool_concate_backward_contract():
    """Concate mode (clk_filter head — the combination the reference
    kernels support): only the first k keys of a sequence receive embedx
    grads (their own block); cvm dims still carry batch show/clk."""
    B, S, D, kk = 2, 2, 4, 2
    values, segments, lens = make_batch(B, S, D, max_len=3, seed=9)
    nk = int(lens.sum())
    show_clk = np.arange(B * 2, dtype=np.float32).reshape(B, 2) + 1

    def f(v):
        out = fused_seqpool_cvm(
            v, jnp.asarray(segments), jnp.asarray(show_clk),
            B, S, True, 2, 0.0, False, 0.2, 1.0, 0.96, 0,
            True, False, 0.0, 0, kk, False)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    g = np.asarray(jax.grad(f)(jnp.asarray(values)))
    # per-key rank within its group
    ranks = []
    for i in range(B):
        for s in range(S):
            ranks += list(range(lens[i, s]))
    up = np.asarray(jax.grad(f)(jnp.asarray(values)))  # determinism
    np.testing.assert_allclose(g, up)
    for ki in range(nk):
        seg = segments[ki]
        ins = seg // S
        if ranks[ki] >= kk:
            np.testing.assert_allclose(g[ki], 0.0)
        else:
            # cvm dims = batch show/clk (the push-counters contract)
            np.testing.assert_allclose(g[ki, :2], show_clk[ins])
    # padding rows get zero grads
    np.testing.assert_allclose(g[nk:], 0.0)


# ---------------------------------------------------------------------------
# Pallas dispatch-seam parity (ISSUE 12): with use_pallas_seqpool=True
# every variant must reproduce the XLA composition — forward within f32
# tolerance (different summation order on the MXU matmul), grads
# BITWISE (the transposed one-hot backward is exactly a gather).
# ---------------------------------------------------------------------------

def _parity_case(kind, B=4, S=3, D=6, seed=0):
    rng = np.random.default_rng(seed)
    if kind == "zipf":
        lens = np.minimum(rng.zipf(1.5, size=B * S), 16)
    elif kind == "empty":
        lens = np.zeros(B * S, np.int64)
    elif kind == "partial":
        lens = np.ones(B * S, np.int64)
        lens[-S:] = 0  # last instance empty (partial final batch)
    else:  # "uniform" ragged-lite
        lens = rng.integers(0, 4, size=B * S)
    K = int(lens.sum())
    cap = max(8, 1 << max(3, (max(K, 1) - 1).bit_length()))
    values = np.zeros((cap, D), np.float32)
    segments = np.full(cap, B * S, np.int32)
    if K:
        values[:K] = rng.uniform(0, 2, size=(K, D))
        segments[:K] = np.repeat(np.arange(B * S, dtype=np.int32), lens)
    sc = np.abs(rng.normal(size=(B, 2))).astype(np.float32) + 0.5
    return values, segments, sc


@pytest.mark.parametrize("kind", ["uniform", "zipf", "empty", "partial"])
@pytest.mark.parametrize("use_cvm,need_filter,pad_value,clk_filter", [
    (True, False, 0.0, False),
    (True, True, 0.0, False),
    (False, False, 0.0, False),
    (True, False, 0.7, False),
    (False, True, 0.3, False),
    (True, False, 0.0, True),      # clk_filter head
])
def test_seqpool_pallas_flag_parity(kind, use_cvm, need_filter, pad_value,
                                    clk_filter):
    from paddlebox_tpu.config import flags_scope
    B, S, D = 4, 3, 6
    values, segments, sc = _parity_case(kind)

    def fwd(v):
        return fused_seqpool_cvm(
            v, jnp.asarray(segments), jnp.asarray(sc), B, S, use_cvm, 2,
            pad_value, need_filter, 0.2, 1.0, 0.96, 0, clk_filter)

    def loss(v):
        out = fwd(v)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    with flags_scope(use_pallas_seqpool=False):
        o0 = np.asarray(fwd(jnp.asarray(values)))
        g0 = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    with flags_scope(use_pallas_seqpool=True):
        o1 = np.asarray(fwd(jnp.asarray(values)))
        g1 = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    assert o0.shape == o1.shape
    np.testing.assert_allclose(o1, o0, rtol=3e-5, atol=3e-5)
    np.testing.assert_array_equal(g1, g0)


def test_seqpool_pallas_flag_parity_trivial_and_key_valid():
    """Trivial layout (segments=None) under the flag: the reshape fast
    path stays (nothing to fuse) and key_valid pad masking holds —
    forward AND grads byte-for-byte the default path."""
    from paddlebox_tpu.config import flags_scope
    B, S, D = 2, 2, 4
    k_pad = 8
    values = np.random.default_rng(0).uniform(
        0, 1, size=(k_pad, D)).astype(np.float32)
    sc = np.ones((B, 2), np.float32)
    kv = np.zeros(k_pad, np.float32)
    kv[:3] = 1.0

    def loss(v):
        out = fused_seqpool_cvm(
            v, None, jnp.asarray(sc), B, S, True, 2, 0.0,
            False, 0.2, 1.0, 0.96, 0, False, False, 0.0, 0, 1, False,
            jnp.asarray(kv))
        return jnp.sum(out)

    with flags_scope(use_pallas_seqpool=False):
        o0 = np.asarray(fused_seqpool_cvm(
            jnp.asarray(values), None, jnp.asarray(sc), B, S,
            key_valid=jnp.asarray(kv)))
        g0 = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    with flags_scope(use_pallas_seqpool=True):
        o1 = np.asarray(fused_seqpool_cvm(
            jnp.asarray(values), None, jnp.asarray(sc), B, S,
            key_valid=jnp.asarray(kv)))
        g1 = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    np.testing.assert_array_equal(o1, o0)
    np.testing.assert_array_equal(g1, g0)
    np.testing.assert_allclose(g1[3:], 0.0)


def test_seqpool_pallas_flag_parity_concate():
    """kk>1 (embedx concate) under the flag: the −1 drop-marker remap
    keeps the MXU pair grid's nondecreasing contract while matching the
    historical n2-discard-bin composition exactly in value."""
    from paddlebox_tpu.config import flags_scope
    B, S, D, kk = 3, 2, 5, 2
    values, segments, lens = make_batch(B, S, D, max_len=4, seed=13)
    sc = np.abs(np.random.default_rng(1).normal(
        size=(B, 2))).astype(np.float32)

    def fwd(v):
        return fused_seqpool_cvm(
            v, jnp.asarray(segments), jnp.asarray(sc), B, S, True, 2,
            0.0, False, 0.2, 1.0, 0.96, 0, True, False, 0.0, 0, kk, False)

    def loss(v):
        out = fwd(v)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    with flags_scope(use_pallas_seqpool=False):
        o0, g0 = np.asarray(fwd(jnp.asarray(values))), \
            np.asarray(jax.grad(loss)(jnp.asarray(values)))
    with flags_scope(use_pallas_seqpool=True):
        o1, g1 = np.asarray(fwd(jnp.asarray(values))), \
            np.asarray(jax.grad(loss)(jnp.asarray(values)))
    np.testing.assert_allclose(o1, o0, rtol=3e-5, atol=3e-5)
    np.testing.assert_array_equal(g1, g0)


@pytest.mark.parametrize("use_cvm,show_filter", [
    (True, False), (True, True), (False, False)])
def test_seqpool_conv_pallas_flag_parity(use_cvm, show_filter):
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ops import fused_seqpool_cvm_with_conv
    B, S, D = 3, 2, 7
    rng = np.random.default_rng(3)
    values, segments, sc2 = _parity_case("zipf", B, S, D, seed=3)
    sc = np.abs(rng.normal(size=(B, 3))).astype(np.float32) + 0.5

    def fwd(v):
        return fused_seqpool_cvm_with_conv(
            v, jnp.asarray(segments), jnp.asarray(sc), B, S, use_cvm,
            show_filter, 0.0, True, 0.2, 1.0, 0.5)

    def loss(v):
        out = fwd(v)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    with flags_scope(use_pallas_seqpool=False):
        o0, g0 = np.asarray(fwd(jnp.asarray(values))), \
            np.asarray(jax.grad(loss)(jnp.asarray(values)))
    with flags_scope(use_pallas_seqpool=True):
        o1, g1 = np.asarray(fwd(jnp.asarray(values))), \
            np.asarray(jax.grad(loss)(jnp.asarray(values)))
    assert o0.shape == o1.shape
    np.testing.assert_allclose(o1, o0, rtol=3e-5, atol=3e-5)
    np.testing.assert_array_equal(g1, g0)


def test_seqpool_wide_cvm_offset_backward():
    """use_cvm with cvm_offset > 2: the output head is still the TWO
    transformed CVM columns, so the backward slices at 2 — regression
    for the head-width crash (both flag states)."""
    from paddlebox_tpu.config import flags_scope
    B, S, D, co = 2, 2, 6, 3
    values, segments, sc2 = _parity_case("uniform", B, S, D, seed=17)
    sc = np.abs(np.random.default_rng(17).normal(
        size=(B, co))).astype(np.float32)

    def loss(v):
        return jnp.sum(fused_seqpool_cvm(
            v, jnp.asarray(segments), jnp.asarray(sc), B, S, True, co))

    out = fused_seqpool_cvm(jnp.asarray(values), jnp.asarray(segments),
                            jnp.asarray(sc), B, S, True, co)
    assert out.shape == (B, S, 2 + D - co)
    with flags_scope(use_pallas_seqpool=False):
        g0 = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    with flags_scope(use_pallas_seqpool=True):
        g1 = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    assert g0.shape == (values.shape[0], D)
    np.testing.assert_array_equal(g1, g0)
    # real keys: head carries batch show/clk, embedx the upstream ones
    nk = int((segments < B * S).sum())
    ins = np.minimum(segments[:nk] // S, B - 1)
    np.testing.assert_allclose(g0[:nk, :co], sc[ins])
    np.testing.assert_allclose(g0[:nk, co:], 1.0)


def test_seqpool_trivial_backward_masks_pads_with_key_valid():
    """ADVICE fix: the trivial (segments=None) backward must mask batch
    padding locally when key_valid is given, instead of relying on the
    caller's gather-idx invariant."""
    B, S, D = 2, 2, 4
    n = B * S
    k_pad = 8  # > n: positions [n, 8) are key pads
    values = np.random.default_rng(0).uniform(
        0, 1, size=(k_pad, D)).astype(np.float32)
    show_clk = np.ones((B, 2), np.float32)
    key_valid = np.zeros(k_pad, np.float32)
    key_valid[:3] = 1.0  # only 3 real keys; position 3 is padding too

    def f(v):
        out = fused_seqpool_cvm(
            v, None, jnp.asarray(show_clk), B, S, True, 2, 0.0,
            False, 0.2, 1.0, 0.96, 0, False, False, 0.0, 0, 1, False,
            jnp.asarray(key_valid))
        return jnp.sum(out)

    g = np.asarray(jax.grad(f)(jnp.asarray(values)))
    np.testing.assert_allclose(g[3:], 0.0)   # ALL pads masked
    assert (np.abs(g[:3]).sum(axis=1) > 0).all()
