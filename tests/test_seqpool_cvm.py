"""Numpy-reference tests for fused_seqpool_cvm / cvm — the OpTest pattern
(reference: python/paddle/fluid/tests/unittests/test_cvm_op.py,
test_fusion_seqpool_cvm_concat_op.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.ops import cvm, fused_seqpool_cvm


def make_batch(B=3, S=2, D=4, max_len=3, seed=0):
    """Random ragged batch in the flattened segment layout."""
    rng = np.random.default_rng(seed)
    lens = rng.integers(0, max_len + 1, size=(B, S))
    segs, vals = [], []
    for i in range(B):
        for s in range(S):
            for _ in range(lens[i, s]):
                segs.append(i * S + s)
                vals.append(rng.uniform(0, 2, size=D))
    K = len(segs)
    cap = 1 << max(3, (K - 1).bit_length())
    values = np.zeros((cap, D), np.float32)
    segments = np.full(cap, B * S, np.int32)
    if K:
        values[:K] = np.array(vals, np.float32)
        segments[:K] = np.array(segs, np.int32)
    return values, segments, lens


def ref_seqpool_cvm(values, segments, B, S, use_cvm=True, cvm_offset=2,
                    need_filter=False, show_coeff=0.2, clk_coeff=1.0,
                    threshold=0.96, quant_ratio=0):
    # accumulate in f32: the reference CUDA kernel sums in double
    # (fused_seqpool_cvm_op.cu:50 `double val`), but f32 is the TPU-native
    # accumulator; deviation is ~1e-4 relative, below AUC-affecting scale.
    D = values.shape[1]
    pooled = np.zeros((B * S, D), np.float32)
    for k in range(values.shape[0]):
        seg = segments[k]
        if seg >= B * S:
            continue
        v = values[k].astype(np.float32)
        if need_filter:
            show, clk = v[0], v[1]
            if (show - clk) * show_coeff + clk * clk_coeff < threshold:
                continue
        if quant_ratio > 0:
            q = np.floor(v * quant_ratio + 0.5) / quant_ratio
            v = np.concatenate([v[:cvm_offset], q[cvm_offset:]])
        pooled[seg] += v
    pooled = pooled.reshape(B, S, D)
    if use_cvm:
        out = pooled.copy()
        out[..., 0] = np.log1p(pooled[..., 0])
        out[..., 1] = np.log1p(pooled[..., 1]) - np.log1p(pooled[..., 0])
        return out
    return pooled[..., cvm_offset:]


@pytest.mark.parametrize("use_cvm", [True, False])
@pytest.mark.parametrize("need_filter,quant_ratio", [(False, 0), (True, 128)])
def test_fused_seqpool_cvm_forward(use_cvm, need_filter, quant_ratio):
    B, S, D = 4, 3, 5
    values, segments, _ = make_batch(B, S, D, seed=1)
    bsc = np.ones((B, 2), np.float32)
    out = fused_seqpool_cvm(
        jnp.asarray(values), jnp.asarray(segments), jnp.asarray(bsc),
        B, S, use_cvm, 2, 0.0, need_filter, 0.2, 1.0, 0.96, quant_ratio)
    ref = ref_seqpool_cvm(values, segments, B, S, use_cvm,
                          need_filter=need_filter, quant_ratio=quant_ratio)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_fused_seqpool_cvm_empty_slots_zero():
    # zero-length slots must pool to zeros (log1p(0)=0) — the PaddingZeros
    # contract (pull_box_sparse_op.h:31)
    B, S, D = 2, 2, 4
    values = np.zeros((8, D), np.float32)
    segments = np.full(8, B * S, np.int32)  # everything is padding
    out = fused_seqpool_cvm(
        jnp.asarray(values), jnp.asarray(segments),
        jnp.ones((B, 2), jnp.float32), B, S, True, 2)
    np.testing.assert_allclose(np.asarray(out), np.zeros((B, S, D)), atol=1e-7)


def test_fused_seqpool_cvm_backward_contract():
    """Embedx dims: upstream grad broadcast to every item; cvm dims: batch
    show/clk values; padding/filtered rows: zero."""
    B, S, D = 2, 2, 4
    values, segments, _ = make_batch(B, S, D, seed=2)
    bsc = np.tile(np.array([[3.0, 1.5]], np.float32), (B, 1))

    def loss(v):
        out = fused_seqpool_cvm(v, jnp.asarray(segments), jnp.asarray(bsc),
                                B, S, True, 2)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    g = jax.grad(loss)(jnp.asarray(values))
    g = np.asarray(g)
    w = np.arange(B * S * D).reshape(B, S, D).astype(np.float32)
    for k in range(values.shape[0]):
        seg = segments[k]
        if seg >= B * S:
            np.testing.assert_array_equal(g[k], 0)
            continue
        i, s = divmod(seg, S)
        np.testing.assert_allclose(g[k, 2:], w[i, s, 2:], rtol=1e-6)
        np.testing.assert_allclose(g[k, :2], bsc[i], rtol=1e-6)


def test_fused_seqpool_cvm_filter_zeroes_grad():
    B, S, D = 1, 1, 4
    values = np.array([[0.1, 0.0, 5.0, 5.0],      # filtered out
                       [1.0, 1.0, 2.0, 2.0]], np.float32)  # kept
    segments = np.array([0, 0], np.int32)
    bsc = np.ones((1, 2), np.float32)

    def loss(v):
        return jnp.sum(fused_seqpool_cvm(
            v, jnp.asarray(segments), jnp.asarray(bsc), B, S,
            True, 2, 0.0, True, 0.2, 1.0, 0.96, 0))

    g = np.asarray(jax.grad(loss)(jnp.asarray(values)))
    np.testing.assert_array_equal(g[0], 0)
    assert np.all(g[1, 2:] == 1.0)


def test_cvm_op():
    B, D = 3, 5
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 3, size=(B, D)).astype(np.float32)
    bcvm = rng.uniform(0, 2, size=(B, 2)).astype(np.float32)
    y = np.asarray(cvm(jnp.asarray(x), jnp.asarray(bcvm), True))
    np.testing.assert_allclose(y[:, 0], np.log1p(x[:, 0]), rtol=1e-6)
    np.testing.assert_allclose(
        y[:, 1], np.log1p(x[:, 1]) - np.log1p(x[:, 0]), rtol=1e-6)
    np.testing.assert_allclose(y[:, 2:], x[:, 2:])
    y2 = np.asarray(cvm(jnp.asarray(x), jnp.asarray(bcvm), False))
    np.testing.assert_allclose(y2, x[:, 2:])
    # backward: dx[:, :2] = CVM values; dx[:, 2:] = upstream
    g = np.asarray(jax.grad(
        lambda v: jnp.sum(cvm(v, jnp.asarray(bcvm), True)))(jnp.asarray(x)))
    np.testing.assert_allclose(g[:, :2], bcvm, rtol=1e-6)
    np.testing.assert_allclose(g[:, 2:], 1.0)
