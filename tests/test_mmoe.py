"""MMoE multi-task model + chrome-trace profiler additions."""

import json

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.models import MMoE, MMoESingle, MODEL_REGISTRY


def test_mmoe_shapes_and_grads():
    m = MMoE(num_experts=3, num_tasks=2, expert_hidden=(16, 8),
             tower_hidden=(8,))
    pooled = jnp.ones((4, 5, 6))
    dense = jnp.ones((4, 3))
    params = m.init(jax.random.PRNGKey(0), pooled, dense)
    out = m.apply(params, pooled, dense)
    assert out.shape == (4, 2)
    assert np.isfinite(np.asarray(out)).all()

    def loss(p):
        o = m.apply(p, pooled, dense)
        return jnp.mean(o ** 2)

    g = jax.grad(loss)(params)
    norms = [float(jnp.linalg.norm(x)) for x in jax.tree.leaves(g)]
    assert all(np.isfinite(n) for n in norms)
    assert any(n > 0 for n in norms)


def test_mmoe_single_trains_e2e():
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.data.record import SlotRecord
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    rng = np.random.default_rng(0)
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 2)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=32,
                        key_bucket_min=256)
    ds = InMemoryDataset(desc)
    recs = []
    for i in range(256):
        keys = rng.integers(0, 50, size=4).astype(np.uint64)
        label = float(keys[0] % 2)  # learnable signal in slot 0
        recs.append(SlotRecord(
            keys=keys, slot_offsets=np.arange(5, dtype=np.int32),
            dense=rng.normal(size=2).astype(np.float32),
            label=label, show=1.0, clk=label))
    ds.records = recs
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 10,
                           unique_bucket_min=256, cfg=cfg)
    tr = Trainer(MMoESingle(num_experts=2, expert_hidden=(16,),
                            tower_hidden=(8,)),
                 table, desc, tx=optax.adam(5e-3))
    first = tr.train_pass(ds)
    tr.reset_metrics()
    for _ in range(4):
        last = tr.train_pass(ds)
    assert last["auc"] > max(first["auc"], 0.7), (first, last)


def test_model_registry_has_mmoe():
    assert MODEL_REGISTRY["mmoe"] is MMoESingle


def test_chrome_trace_writer(tmp_path):
    from paddlebox_tpu.utils.profiler import (ChromeTraceWriter,
                                              StageTimers,
                                              set_chrome_trace)
    w = ChromeTraceWriter()
    set_chrome_trace(w)
    try:
        st = StageTimers()
        with st.stage("build"):
            pass
        with st.stage("train"):
            with w.event("inner", batch=3):
                pass
        w.instant("pass_done", pass_id=1)
    finally:
        set_chrome_trace(None)
    out = tmp_path / "trace.json"
    n = w.save(str(out))
    assert n == 4
    data = json.load(open(out))
    names = [e["name"] for e in data["traceEvents"]]
    assert set(names) == {"build", "train", "inner", "pass_done"}
    inner = next(e for e in data["traceEvents"] if e["name"] == "inner")
    assert inner["args"] == {"batch": 3}
    assert all("ts" in e for e in data["traceEvents"])
