"""Device-resident pass mode: on-device dedup correctness and equivalence
with the streaming (per-batch H2D) trainer path."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ops.device_unique import dedup_rows
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import PassPreloader, ResidentPass, Trainer


def test_dedup_rows_matches_numpy():
    rng = np.random.default_rng(0)
    cap = 500
    for trial in range(5):
        rows = rng.integers(0, cap, size=300).astype(np.int32)
        rows[rng.random(300) < 0.1] = cap  # sentinel (invalid keys)
        uniq, gidx = jax.jit(dedup_rows, static_argnums=1)(
            jnp.asarray(rows), cap)
        uniq, gidx = np.asarray(uniq), np.asarray(gidx)
        # expansion reconstructs every key's row
        np.testing.assert_array_equal(uniq[gidx], rows)
        ref = np.unique(rows)
        u = len(ref)
        np.testing.assert_array_equal(uniq[:u], ref)  # ascending, compact
        assert (uniq[u:] > cap).all()         # OOB pads (gathers clamp,
        assert len(set(uniq.tolist())) == len(uniq)  # scatters drop, unique


def test_dedup_rows_all_sentinel():
    cap = 64
    rows = jnp.full(16, cap, jnp.int32)
    uniq, gidx = dedup_rows(rows, cap)
    assert int(uniq[0]) == cap and (np.asarray(gidx) == 0).all()


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_dp")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=1500,
                                 vocab_per_slot=40, seed=11)


def _make(files, bs=128):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    # mf_initial_range=0 → no rng in lazy-mf init, so the streaming and
    # resident paths are numerically comparable
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=4096)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def test_resident_matches_streaming(criteo_files):
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    ra = [tr_a.train_pass(ds) for _ in range(2)][-1]
    rb = [tr_b.train_pass_resident(ds) for _ in range(2)][-1]
    assert rb["batches"] == ra["batches"]
    assert tr_b.table.feature_count == tr_a.table.feature_count
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3)
    # dense params track closely (order-of-reduction float drift only)
    pa = jax.tree.leaves(tr_a.state.params)
    pb = jax.tree.leaves(tr_b.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-3)
    # sparse table rows agree for the keys both saw
    keys, rows_a = tr_a.table.index.items()
    rows_b = tr_b.table.index.lookup(keys)
    st_a = jax.device_get(tr_a.state.table)
    st_b = jax.device_get(tr_b.state.table)
    np.testing.assert_allclose(np.asarray(st_a.embed_w)[rows_a],
                               np.asarray(st_b.embed_w)[rows_b],
                               rtol=2e-2, atol=2e-3)


def test_resident_learns(criteo_files):
    tr, ds = _make(criteo_files)
    first = tr.train_pass_resident(ds)
    tr.reset_metrics()
    for _ in range(3):
        last = tr.train_pass_resident(ds)
    assert last["auc"] > max(first["auc"], 0.55)
    assert np.isfinite(last["auc"])


def test_resident_chunked_equals_whole(criteo_files):
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    rp_a = ResidentPass.build(ds, tr_a.table)
    tr_a.train_pass_resident(rp_a)
    from paddlebox_tpu.train.device_pass import ResidentPassRunner
    rp_b = ResidentPass.build(ds, tr_b.table)
    runner = ResidentPassRunner(tr_b.step_fn, tr_b.table.capacity,
                                rp_b.segs is None, chunk=3)
    tr_b.state = runner.run_pass(tr_b.state, rp_b, tr_b._rng)
    tr_b.sync_table()
    pa = jax.tree.leaves(tr_a.state.params)
    pb = jax.tree.leaves(tr_b.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def _rand_records(n, num_slots=4, seed=0, trivial=False):
    """trivial=True → exactly one key per slot (slot-ordered layout);
    False → variable keys per slot (non-trivial segments)."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        if trivial:
            counts = np.ones(num_slots, np.int64)
        else:
            counts = rng.integers(0, 3, size=num_slots)
            counts[rng.integers(0, num_slots)] += 1  # ≥1 key somewhere
        offs = np.zeros(num_slots + 1, np.int32)
        np.cumsum(counts, out=offs[1:])
        keys = rng.integers(0, 5000, size=int(offs[-1])).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offs,
            dense=rng.normal(size=3).astype(np.float32),
            label=float(i % 2), show=1.0, clk=float(i % 2)))
    return recs


@pytest.mark.parametrize("trivial", [True, False])
def test_build_columnar_matches_record_path(trivial):
    """The vectorized columnar packer must produce byte-identical passes
    to the per-batch record path (incl. a partial tail batch)."""
    from paddlebox_tpu.data import InMemoryDataset, SlotDef
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=64,
                        key_bucket_min=512)
    recs = _rand_records(300, num_slots=4, seed=5, trivial=trivial)

    ds_rec = InMemoryDataset(desc)
    ds_rec.records = list(recs)
    ds_col = InMemoryDataset(desc)
    ds_col.records = list(recs)
    ds_col.columnarize()

    mk = lambda: EmbeddingTable(mf_dim=4, capacity=1 << 13,
                                unique_bucket_min=512)
    ta, tb = mk(), mk()
    rp_rec = ResidentPass.build(ds_rec, ta)   # record path (columnar=None)
    rp_col = ResidentPass.build(ds_col, tb)   # vectorized path
    assert rp_rec.num_batches == rp_col.num_batches
    assert rp_rec.num_records == rp_col.num_records
    np.testing.assert_array_equal(rp_rec.uniq, rp_col.uniq)
    np.testing.assert_array_equal(rp_rec.gidx, rp_col.gidx)
    np.testing.assert_array_equal(ta.slot_host, tb.slot_host)
    assert ta.slot_host.max() > 0  # slots were recorded host-side
    np.testing.assert_array_equal(rp_rec.meta, rp_col.meta)
    np.testing.assert_allclose(rp_rec.floats, rp_col.floats)
    if rp_rec.segs is None:
        assert rp_col.segs is None
    else:
        np.testing.assert_array_equal(rp_rec.segs, rp_col.segs)
    # the pull-index invariants the step relies on: duplicate-free rows,
    # OOB pads after the real block, gather idx within [0, u]
    for i in range(rp_col.num_batches):
        u = rp_col.meta[i, 2]
        assert len(np.unique(rp_col.uniq[i])) == rp_col.uniq.shape[1]
        assert (rp_col.uniq[i, :u] <= ta.capacity).all()
        assert (rp_col.uniq[i, u:] > ta.capacity).all()
        assert (rp_col.gidx[i] <= u).all()


def _decode_uniq(rp, runner):
    """Decode every batch's uniq through the runner's traced view."""
    rp.upload()
    uniq_t, gidx_t = rp.dev[0], rp.dev[1]
    out = []
    for i in range(rp.num_batches):
        view = runner._make_view(
            tuple(jnp.asarray(a[i]) for a in uniq_t),
            tuple(jnp.asarray(a[i]) for a in gidx_t),
            jnp.asarray(rp.floats[i]), jnp.asarray(rp.meta[i]),
            jnp.zeros((1,), jnp.int32) if rp.segs is None
            else jnp.asarray(rp.segs[i]))
        out.append((np.asarray(view.unique_rows),
                    np.asarray(view.gather_idx)))
    return out


def test_uniq_wire_roundtrip_dense():
    """u16-delta wire: dense row sets (the common case) reconstruct the
    exact pull index through the runner's traced decode."""
    from paddlebox_tpu.data import InMemoryDataset, SlotDef
    from paddlebox_tpu.train.device_pass import ResidentPassRunner
    recs = _rand_records(300, num_slots=4, seed=7, trivial=True)
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=64,
                        key_bucket_min=512)
    ds = InMemoryDataset(desc)
    ds.records = recs
    ds.columnarize()
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13,
                           unique_bucket_min=64)
    rp = ResidentPass.build(ds, table)
    runner = ResidentPassRunner(None, table.capacity, rp.segs is None)
    decoded = _decode_uniq(rp, runner)
    assert len(rp.dev[0]) == 3  # the delta encoding was chosen
    for i, (du, dg) in enumerate(decoded):
        u = rp.meta[i, 2]
        np.testing.assert_array_equal(du[:u], rp.uniq[i, :u])
        assert (du[u:] > table.capacity).all()
        np.testing.assert_array_equal(dg, rp.gidx[i])


@pytest.mark.parametrize("n_rows,expect_delta", [(20, True), (100, False)])
def test_uniq_wire_roundtrip_sparse_gaps(n_rows, expect_delta):
    """Huge row gaps (sparse occupancy of a big table): few gaps ride the
    u16 wire's exception correction; many gaps fall back to u24 halves.
    Built directly (the hash index assigns rows densely in practice)."""
    from paddlebox_tpu.train.device_pass import (ResidentPass,
                                                 ResidentPassRunner)
    from paddlebox_tpu.ps.table import fill_oob_pads
    cap = 1 << 23
    rng = np.random.default_rng(3)
    rows = np.sort(rng.choice(cap - 1, size=n_rows, replace=False)
                   .astype(np.int32))
    u_pad = 64 if n_rows <= 64 else 512
    uniq = np.empty((1, u_pad), np.int32)
    uniq[0, :n_rows] = rows
    fill_oob_pads(uniq[0], n_rows, cap)
    k = 128
    gidx = rng.integers(0, n_rows, size=(1, k)).astype(np.int32)
    floats = np.zeros((1, 4, 7), np.float32)
    meta = np.array([[k, 8, n_rows, int(rows[0])]], np.int32)
    rp = ResidentPass(uniq, gidx, floats, meta, None, 4)
    runner = ResidentPassRunner(None, cap, True)
    decoded = _decode_uniq(rp, runner)
    assert (len(rp.dev[0]) == 3) == expect_delta
    du, dg = decoded[0]
    np.testing.assert_array_equal(du[:n_rows], rows)
    assert (du[n_rows:] > cap).all()
    np.testing.assert_array_equal(dg, gidx[0])


def test_pass_preloader(criteo_files):
    tr, ds = _make(criteo_files)
    datasets = iter([ds, ds, ds])
    pre = PassPreloader(datasets, tr.table)
    assert pre.start_next()
    results = []
    while True:
        rp = pre.wait()
        if rp is None:
            break
        has_more = pre.start_next()  # overlap next build with training
        results.append(tr.train_pass_resident(rp))
        if not has_more:
            break
    assert len(results) == 3
    assert all(np.isfinite(r["auc"]) for r in results)
