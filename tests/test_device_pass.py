"""Device-resident pass mode: on-device dedup correctness and equivalence
with the streaming (per-batch H2D) trainer path."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ops.device_unique import dedup_rows
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import PassPreloader, ResidentPass, Trainer


def test_dedup_rows_matches_numpy():
    rng = np.random.default_rng(0)
    cap = 500
    for trial in range(5):
        rows = rng.integers(0, cap, size=300).astype(np.int32)
        rows[rng.random(300) < 0.1] = cap  # sentinel (invalid keys)
        uniq, gidx = jax.jit(dedup_rows, static_argnums=1)(
            jnp.asarray(rows), cap)
        uniq, gidx = np.asarray(uniq), np.asarray(gidx)
        # expansion reconstructs every key's row
        np.testing.assert_array_equal(uniq[gidx], rows)
        ref = np.unique(rows)
        u = len(ref)
        np.testing.assert_array_equal(uniq[:u], ref)  # ascending, compact
        assert (uniq[u:] > cap).all()         # OOB pads (gathers clamp,
        assert len(set(uniq.tolist())) == len(uniq)  # scatters drop, unique


def test_dedup_rows_all_sentinel():
    cap = 64
    rows = jnp.full(16, cap, jnp.int32)
    uniq, gidx = dedup_rows(rows, cap)
    assert int(uniq[0]) == cap and (np.asarray(gidx) == 0).all()


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_dp")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=1500,
                                 vocab_per_slot=40, seed=11)


def _make(files, bs=128):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    # mf_initial_range=0 → no rng in lazy-mf init, so the streaming and
    # resident paths are numerically comparable
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=4096)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def test_resident_matches_streaming(criteo_files):
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    ra = [tr_a.train_pass(ds) for _ in range(2)][-1]
    rb = [tr_b.train_pass_resident(ds) for _ in range(2)][-1]
    assert rb["batches"] == ra["batches"]
    assert tr_b.table.feature_count == tr_a.table.feature_count
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3)
    # dense params track closely (order-of-reduction float drift only)
    pa = jax.tree.leaves(tr_a.state.params)
    pb = jax.tree.leaves(tr_b.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-3)
    # sparse table rows agree for the keys both saw
    keys, rows_a = tr_a.table.index.items()
    rows_b = tr_b.table.index.lookup(keys)
    st_a = jax.device_get(tr_a.state.table)
    st_b = jax.device_get(tr_b.state.table)
    np.testing.assert_allclose(np.asarray(st_a.embed_w)[rows_a],
                               np.asarray(st_b.embed_w)[rows_b],
                               rtol=2e-2, atol=2e-3)


def test_resident_learns(criteo_files):
    tr, ds = _make(criteo_files)
    first = tr.train_pass_resident(ds)
    tr.reset_metrics()
    for _ in range(3):
        last = tr.train_pass_resident(ds)
    assert last["auc"] > max(first["auc"], 0.55)
    assert np.isfinite(last["auc"])


def test_resident_chunked_equals_whole(criteo_files):
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    rp_a = ResidentPass.build(ds, tr_a.table)
    tr_a.train_pass_resident(rp_a)
    from paddlebox_tpu.train.device_pass import ResidentPassRunner
    rp_b = ResidentPass.build(ds, tr_b.table)
    runner = ResidentPassRunner(tr_b.step_fn, tr_b.table.capacity,
                                rp_b.segs is None, chunk=3)
    tr_b.state, _ = runner.run_pass(tr_b.state, rp_b, tr_b._rng)
    tr_b.sync_table()
    pa = jax.tree.leaves(tr_a.state.params)
    pb = jax.tree.leaves(tr_b.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def _rand_records(n, num_slots=4, seed=0, trivial=False):
    """trivial=True → exactly one key per slot (slot-ordered layout);
    False → variable keys per slot (non-trivial segments)."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        if trivial:
            counts = np.ones(num_slots, np.int64)
        else:
            counts = rng.integers(0, 3, size=num_slots)
            counts[rng.integers(0, num_slots)] += 1  # ≥1 key somewhere
        offs = np.zeros(num_slots + 1, np.int32)
        np.cumsum(counts, out=offs[1:])
        keys = rng.integers(0, 5000, size=int(offs[-1])).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offs,
            dense=rng.normal(size=3).astype(np.float32),
            label=float(i % 2), show=1.0, clk=float(i % 2)))
    return recs


@pytest.mark.parametrize("trivial", [True, False])
def test_build_columnar_matches_record_path(trivial):
    """The vectorized columnar packer must produce byte-identical passes
    to the per-batch record path (incl. a partial tail batch)."""
    from paddlebox_tpu.data import InMemoryDataset, SlotDef
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=64,
                        key_bucket_min=512)
    recs = _rand_records(300, num_slots=4, seed=5, trivial=trivial)

    ds_rec = InMemoryDataset(desc)
    ds_rec.records = list(recs)
    ds_col = InMemoryDataset(desc)
    ds_col.records = list(recs)
    ds_col.columnarize()

    mk = lambda: EmbeddingTable(mf_dim=4, capacity=1 << 13,
                                unique_bucket_min=512)
    ta, tb = mk(), mk()
    rp_rec = ResidentPass.build(ds_rec, ta)   # record path (columnar=None)
    rp_col = ResidentPass.build(ds_col, tb)   # vectorized path
    assert rp_rec.num_batches == rp_col.num_batches
    assert rp_rec.num_records == rp_col.num_records
    np.testing.assert_array_equal(rp_rec.uniq, rp_col.uniq)
    np.testing.assert_array_equal(rp_rec.gidx, rp_col.gidx)
    np.testing.assert_array_equal(ta.slot_host, tb.slot_host)
    assert ta.slot_host.max() > 0  # slots were recorded host-side
    np.testing.assert_array_equal(rp_rec.meta, rp_col.meta)
    np.testing.assert_allclose(rp_rec.floats, rp_col.floats)
    if rp_rec.segs is None:
        assert rp_col.segs is None
    else:
        np.testing.assert_array_equal(rp_rec.segs, rp_col.segs)
    # the pull-index invariants the step relies on: duplicate-free rows,
    # OOB pads after the real block, gather idx within [0, u]
    for i in range(rp_col.num_batches):
        u = rp_col.meta[i, 2]
        assert len(np.unique(rp_col.uniq[i])) == rp_col.uniq.shape[1]
        assert (rp_col.uniq[i, :u] <= ta.capacity).all()
        assert (rp_col.uniq[i, u:] > ta.capacity).all()
        assert (rp_col.gidx[i] <= u).all()


def _decode_uniq(rp, runner):
    """Decode every batch's uniq through the runner's traced view."""
    rp.upload()
    uniq_t, gidx_t = rp.dev[0], rp.dev[1]
    out = []
    for i in range(rp.num_batches):
        view = runner._make_view(
            tuple(jnp.asarray(a[i]) for a in uniq_t),
            tuple(jnp.asarray(a[i]) for a in gidx_t),
            jnp.asarray(rp.floats[i]), jnp.asarray(rp.meta[i]),
            jnp.zeros((1,), jnp.int32) if rp.segs is None
            else jnp.asarray(rp.segs[i]),
            jnp.zeros((2, 0), jnp.float32))
        out.append((np.asarray(view.unique_rows),
                    np.asarray(view.gather_idx)))
    return out


def test_uniq_wire_roundtrip_dense():
    """u16-delta wire: dense row sets (the common case) reconstruct the
    exact pull index through the runner's traced decode."""
    from paddlebox_tpu.data import InMemoryDataset, SlotDef
    from paddlebox_tpu.train.device_pass import ResidentPassRunner
    recs = _rand_records(300, num_slots=4, seed=7, trivial=True)
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=64,
                        key_bucket_min=512)
    ds = InMemoryDataset(desc)
    ds.records = recs
    ds.columnarize()
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13,
                           unique_bucket_min=64)
    rp = ResidentPass.build(ds, table)
    runner = ResidentPassRunner(None, table.capacity, rp.segs is None)
    decoded = _decode_uniq(rp, runner)
    assert len(rp.dev[0]) == 3  # the delta encoding was chosen
    for i, (du, dg) in enumerate(decoded):
        u = rp.meta[i, 2]
        np.testing.assert_array_equal(du[:u], rp.uniq[i, :u])
        assert (du[u:] > table.capacity).all()
        np.testing.assert_array_equal(dg, rp.gidx[i])


@pytest.mark.parametrize("n_rows,expect_delta", [(20, True), (100, False)])
def test_uniq_wire_roundtrip_sparse_gaps(n_rows, expect_delta):
    """Huge row gaps (sparse occupancy of a big table): few gaps ride the
    u16 wire's exception correction; many gaps fall back to u24 halves.
    Built directly (the hash index assigns rows densely in practice)."""
    from paddlebox_tpu.train.device_pass import (ResidentPass,
                                                 ResidentPassRunner)
    from paddlebox_tpu.ps.table import fill_oob_pads
    cap = 1 << 23
    rng = np.random.default_rng(3)
    rows = np.sort(rng.choice(cap - 1, size=n_rows, replace=False)
                   .astype(np.int32))
    u_pad = 64 if n_rows <= 64 else 512
    uniq = np.empty((1, u_pad), np.int32)
    uniq[0, :n_rows] = rows
    fill_oob_pads(uniq[0], n_rows, cap)
    k = 128
    gidx = rng.integers(0, n_rows, size=(1, k)).astype(np.int32)
    floats = np.zeros((1, 4, 7), np.float32)
    meta = np.array([[k, 8, n_rows, int(rows[0])]], np.int32)
    rp = ResidentPass(uniq, gidx, floats, meta, None, 4)
    runner = ResidentPassRunner(None, cap, True)
    decoded = _decode_uniq(rp, runner)
    assert (len(rp.dev[0]) == 3) == expect_delta
    du, dg = decoded[0]
    np.testing.assert_array_equal(du[:n_rows], rows)
    assert (du[n_rows:] > cap).all()
    np.testing.assert_array_equal(dg, gidx[0])


def test_pass_preloader(criteo_files):
    tr, ds = _make(criteo_files)
    datasets = iter([ds, ds, ds])
    pre = PassPreloader(datasets, tr.table)
    assert pre.start_next()
    results = []
    while True:
        rp = pre.wait()
        if rp is None:
            break
        has_more = pre.start_next()  # overlap next build with training
        results.append(tr.train_pass_resident(rp))
        if not has_more:
            break
    assert len(results) == 3
    assert all(np.isfinite(r["auc"]) for r in results)


def test_pass_preloader_depth2_bit_identical_to_depth1(criteo_files):
    """Deep pipeline invariant (ISSUE 5): depth only changes
    scheduling, never results — the depth-2 pipeline's 4 overlapped
    passes produce the exact logical state (params + table rows by
    key + AUC) of the depth-1 run."""
    from paddlebox_tpu.train.checkpoint import state_digest

    def run(depth):
        tr, ds = _make(criteo_files)
        res = tr.train_passes_resident([ds, ds, ds, ds], depth=depth)
        assert len(res) == 4
        return tr, state_digest(tr)

    tr1, d1 = run(1)
    tr2, d2 = run(2)
    assert d1 == d2
    for a, b in zip(jax.tree.leaves(tr1.state.params),
                    jax.tree.leaves(tr2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_preloader_hbm_budget_clamps(criteo_files):
    """An oversized pass degrades the pipeline to depth 1 — loudly,
    never stacking staged passes until HBM OOMs."""
    tr, ds = _make(criteo_files)
    pre = PassPreloader(iter([ds, ds, ds]), tr.table, depth=3,
                        hbm_budget_bytes=1)  # any real pass overflows
    pre.start_next()
    results = []
    while True:
        rp = pre.wait()
        if rp is None:
            break
        results.append(tr.train_pass_resident(rp))
    assert len(results) == 3           # degraded, but never starved
    assert pre.depth_clamped
    assert pre._effective_depth == 1
    pre.drain()


def test_bulk_assign_matches_serial(criteo_files):
    """Whole-pass bulk key assignment (one host_lock round-trip)
    produces the same per-batch index as the serial per-batch path:
    key→row decode agrees with the index either way, and on the
    native (first-occurrence) index the builds are row-for-row
    identical."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.native import load_native
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    with flags_scope(bulk_pass_assign=True):
        rp_a = ResidentPass.build(ds, tr_a.table)
    with flags_scope(bulk_pass_assign=False):
        rp_b = ResidentPass.build(ds, tr_b.table)
    assert rp_a.num_batches == rp_b.num_batches
    np.testing.assert_array_equal(rp_a.meta[:, (0, 1, 2)],
                                  rp_b.meta[:, (0, 1, 2)])
    # both builds registered the same key set, and each build's wire
    # decodes every key to the row its own index assigned
    keys_a, rows_a = tr_a.table.index.items()
    keys_b, _ = tr_b.table.index.items()
    np.testing.assert_array_equal(np.sort(keys_a), np.sort(keys_b))
    for rp, tr in ((rp_a, tr_a), (rp_b, tr_b)):
        batches = list(ds.batches())
        for i, b in enumerate(batches):
            nk = b.num_keys
            rows_wire = rp.uniq[i][rp.gidx[i][:nk]]
            rows_idx = tr.table.index.lookup(b.keys[:nk])
            np.testing.assert_array_equal(rows_wire, rows_idx)
    if load_native() is not None:
        # native assign_unique is first-occurrence — bulk first-seen
        # allocation reproduces the serial walk row for row
        np.testing.assert_array_equal(rp_a.uniq, rp_b.uniq)
        np.testing.assert_array_equal(rp_a.gidx, rp_b.gidx)
        np.testing.assert_array_equal(rp_a.meta, rp_b.meta)


def test_preloader_error_mid_queue(criteo_files):
    """A mid-queue build failure surfaces on the wait() that would
    have consumed the broken pass; passes built before it stay valid,
    and waits after the raise return None."""
    tr, ds = _make(criteo_files)
    calls = {"n": 0}

    def build(d):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("boom at build 2")
        return ResidentPass.build_streamed(d, tr.table, block=False)

    pre = PassPreloader(iter([ds, ds, ds]), build_fn=build, depth=2)
    pre.start_next()
    rp1 = pre.wait()
    assert rp1 is not None             # build 1 is valid and served
    with pytest.raises(RuntimeError, match="boom at build 2"):
        pre.wait()
    assert pre.wait() is None          # pipeline is dead after a raise
    assert calls["n"] == 2             # build 3 never started


def test_preloader_stops_on_request_stop(criteo_files):
    """Graceful preemption: the pipeline stops building within one
    stage poll of request_stop and drain() leaves no build running —
    a long build can't eat the SIGTERM grace window."""
    from paddlebox_tpu.resilience import preemption
    tr, ds = _make(criteo_files)
    pre = PassPreloader(iter([ds] * 6), tr.table, depth=1)
    try:
        pre.start_next()
        rp = pre.wait()
        assert rp is not None
        preemption.request_stop("test")
        served = 0
        while pre.wait() is not None:  # staged passes stay consumable
            served += 1
        assert served <= 1             # depth 1 → at most one staged
        pre.drain(timeout=30)
        assert not pre._worker.is_alive()
        assert pre.builds < 6
    finally:
        preemption.clear_stop()
        pre.drain()


def _q8_records_dataset(num_records=96, seed=3, bad_label=False):
    """Small NON-columnar in-memory dataset (records path) for the q8
    streaming front."""
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 5)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(1, 5)]
    desc = DataFeedDesc(slots=slots, batch_size=32, label_slot="label",
                        key_bucket_min=128)
    offs = np.arange(5, dtype=np.int32)
    ds = InMemoryDataset(desc)
    for i in range(num_records):
        label = 0.5 if bad_label else float(rng.random() < 0.3)
        ds.records.append(SlotRecord(
            keys=(rng.integers(0, 64, size=4)
                  + np.arange(4) * 64).astype(np.uint64),
            slot_offsets=offs,
            dense=(rng.normal(size=5) * np.array(
                [1, 10, 0.1, 100, 1])).astype(np.float32),
            label=label, show=1.0, clk=label))
    return ds, desc


def test_q8_streaming_front_matches_staged():
    """The streaming (two-phase, min/max) q8 front reproduces the
    staged whole-pass quantization bit for bit when the winsorize
    branch is idle (< 1000 valid rows, the formulas coincide) — while
    never holding a full-pass f32 float block."""
    from paddlebox_tpu.train.device_pass import ResidentPass as RP
    from paddlebox_tpu.train.step import pack_floats, quantize_floats
    ds, _ = _q8_records_dataset()
    assert ds.columnar is None and ds.supports_reiteration
    per_batch, floats, qmeta, trivial, nrec, side = RP._front(ds, "q8")
    assert floats.dtype == np.uint8
    # reference: the staged path's whole-pass quantize
    blocks = [pack_floats(b.dense, b.label, b.show, b.clk)
              for b in ds.batches()]
    ref = np.stack(blocks)
    nb, bsz, d3 = ref.shape
    flat = ref.reshape(nb * bsz, d3)
    rblock, rqmeta = quantize_floats(flat[:, :-3], flat[:, -3],
                                     flat[:, -2], flat[:, -1],
                                     valid=flat[:, -2] > 0)
    np.testing.assert_array_equal(qmeta, rqmeta)
    np.testing.assert_array_equal(floats, rblock.reshape(nb, bsz, d3))


def test_q8_streaming_front_bf16_fallback():
    """Data outside the exact-u8 wire falls back to bf16, matching
    _encode_floats' contract."""
    from paddlebox_tpu.train.device_pass import ResidentPass as RP
    ds, _ = _q8_records_dataset(bad_label=True)  # label 0.5 ≠ rint
    per_batch, floats, qmeta, *_ = RP._front(ds, "q8")
    assert qmeta is None
    assert floats.dtype == jnp.bfloat16


def test_quantize_floats_roundtrip():
    """q8 float wire: affine dequant error bounded by scale/2 per column;
    label/show/clk ride exactly; out-of-range data falls back (None)."""
    from paddlebox_tpu.train.step import dequantize_floats, quantize_floats
    rng = np.random.default_rng(5)
    dense = rng.normal(size=(64, 5)).astype(np.float32) * \
        np.array([1, 10, 0.1, 100, 1], np.float32)
    label = (rng.random(64) < 0.3).astype(np.float32)
    show = np.ones(64, np.float32)
    clk = label.copy()
    block, qmeta = quantize_floats(dense, label, show, clk)
    d, l, s, c = dequantize_floats(jnp.asarray(block), jnp.asarray(qmeta))
    span = dense.max(axis=0) - dense.min(axis=0)
    assert (np.abs(np.asarray(d) - dense) <= span / 255.0 * 0.51 + 1e-7).all()
    np.testing.assert_array_equal(np.asarray(l), label)
    np.testing.assert_array_equal(np.asarray(s), show)
    np.testing.assert_array_equal(np.asarray(c), clk)
    # constant column: scale clamps to 1, roundtrips exactly
    const = np.full((8, 2), 3.5, np.float32)
    blk2, qm2 = quantize_floats(const, label[:8], show[:8], clk[:8])
    d2 = np.asarray(dequantize_floats(jnp.asarray(blk2),
                                      jnp.asarray(qm2))[0])
    np.testing.assert_allclose(d2, const)
    # fallbacks
    assert quantize_floats(np.array([[np.nan]], np.float32),
                           label[:1], show[:1], clk[:1]) is None
    assert quantize_floats(const[:1], np.array([0.5], np.float32),
                           show[:1], clk[:1]) is None


def test_resident_q8_wire_learns(criteo_files):
    """The q8 wire trains end-to-end and tracks the f32 wire's AUC."""
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    for _ in range(3):
        ra = tr_a.train_pass_resident(ResidentPass.build(ds, tr_a.table))
        rb = tr_b.train_pass_resident(
            ResidentPass.build(ds, tr_b.table, floats_dtype="q8"))
    assert rb["auc"] > 0.55
    assert np.isclose(rb["auc"], ra["auc"], atol=5e-3)


def test_build_streamed_equals_build(criteo_files):
    """Streamed (chunked, overlapped-upload) build produces the exact
    same staged pass as the plain builder."""
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    rp_a = ResidentPass.build(ds, tr_a.table, floats_dtype="q8")
    rp_a.upload()
    rp_b = ResidentPass.build_streamed(ds, tr_b.table, floats_dtype="q8")
    np.testing.assert_array_equal(rp_a.uniq, rp_b.uniq)
    np.testing.assert_array_equal(rp_a.gidx, rp_b.gidx)
    np.testing.assert_array_equal(rp_a.meta, rp_b.meta)
    np.testing.assert_array_equal(rp_a.floats, rp_b.floats)
    if rp_a.segs is None:
        assert rp_b.segs is None
    else:
        np.testing.assert_array_equal(rp_a.segs, rp_b.segs)
    assert rp_b.dev is not None
    for a, b in zip(jax.tree.leaves(rp_a.dev), jax.tree.leaves(rp_b.dev)):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and it trains
    tr_b.train_pass_resident(rp_b)


def test_uniq_wire_d8(criteo_files):
    """Warm tables produce small row gaps → the u8 delta wire engages."""
    tr, ds = _make(criteo_files)
    ResidentPass.build(ds, tr.table)          # warm the index
    rp = ResidentPass.build(ds, tr.table)     # steady state
    rp.upload()
    assert len(rp.dev[0]) == 3 and rp.dev[0][0].dtype == jnp.uint8
    from paddlebox_tpu.train.device_pass import ResidentPassRunner
    runner = ResidentPassRunner(None, tr.table.capacity, rp.segs is None)
    decoded = _decode_uniq(rp, runner)
    for i, (du, dg) in enumerate(decoded):
        u = rp.meta[i, 2]
        np.testing.assert_array_equal(du[:u], rp.uniq[i, :u])
        assert (du[u:] > tr.table.capacity).all()


def test_q8_range_excludes_padding():
    """Batch-padding rows (zero-filled, show=0) must not widen the q8
    range: a column living far from 0 keeps its tight scale."""
    from paddlebox_tpu.train.step import quantize_floats
    dense = np.full((10, 2), 1000.0, np.float32)
    dense[:, 1] = np.linspace(1000.0, 1010.0, 10)
    dense[8:] = 0.0  # zero-filled pad rows
    show = np.ones(10, np.float32)
    show[8:] = 0.0
    label = np.zeros(10, np.float32)
    block, qmeta = quantize_floats(dense, label, show, label,
                                   valid=show > 0)
    scale, zp = qmeta
    assert zp[1] == 1000.0 and scale[1] <= 10.0 / 255.0 + 1e-6
    # pad rows clip instead of wrapping
    assert (block[8:, :2] == 0).all()


def test_q8_outlier_does_not_collapse_precision():
    """One extreme value must not flatten a column to a single bucket:
    the range winsorizes to the [0.1, 99.9] percentiles and the outlier
    saturates with bounded error."""
    from paddlebox_tpu.train.step import dequantize_floats, quantize_floats
    rng = np.random.default_rng(7)
    n = 4096
    dense = rng.uniform(0, 100, size=(n, 1)).astype(np.float32)
    dense[17, 0] = 1e6  # heavy-tail outlier
    label = np.zeros(n, np.float32)
    show = np.ones(n, np.float32)
    block, qmeta = quantize_floats(dense, label, show, label)
    d = np.asarray(dequantize_floats(jnp.asarray(block),
                                     jnp.asarray(qmeta))[0])
    body = np.delete(np.arange(n), 17)
    err = np.abs(d[body, 0] - dense[body, 0])
    assert err.max() < 1.0          # body keeps ~100/255 resolution
    assert d[17, 0] >= d[body, 0].max()  # outlier saturates high


def _make_arena(files, bs=128):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=4096, arena_slots=26,
                           arena_chunk_bits=6)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def test_compact_wire_matches_dedup_wire(criteo_files):
    """The compact (slot-arena local rows + device dedup) wire must train
    identically to the host-dedup wire — same per-key embeddings, same
    dense params — despite a completely different row layout."""
    tr_a, ds = _make(criteo_files)          # dedup wire
    tr_b, _ = _make_arena(criteo_files)     # compact wire
    for _ in range(2):
        rp_a = ResidentPass.build_streamed(ds, tr_a.table)
        assert rp_a.wire == "dedup"
        ra = tr_a.train_pass_resident(rp_a)
        rp_b = ResidentPass.build_streamed(ds, tr_b.table)
        assert rp_b.wire == "compact"
        rb = tr_b.train_pass_resident(rp_b)
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3)
    pa = jax.tree.leaves(tr_a.state.params)
    pb = jax.tree.leaves(tr_b.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-3)
    keys, rows_a = tr_a.table.index.items()
    rows_b = tr_b.table.index.lookup(keys)
    assert (rows_b >= 0).all()
    st_a = jax.device_get(tr_a.state.table)
    st_b = jax.device_get(tr_b.state.table)
    np.testing.assert_allclose(np.asarray(st_a.embed_w)[rows_a],
                               np.asarray(st_b.embed_w)[rows_b],
                               rtol=2e-2, atol=2e-3)


def test_compact_wire_q8_learns(criteo_files):
    tr, ds = _make_arena(criteo_files)
    first = tr.train_pass_resident(
        ResidentPass.build_streamed(ds, tr.table, floats_dtype="q8"))
    for _ in range(3):
        last = tr.train_pass_resident(
            ResidentPass.build_streamed(ds, tr.table, floats_dtype="q8"))
    assert last["auc"] > max(first["auc"], 0.55)


def test_compact_falls_back_after_slotless_assign(criteo_files):
    """Keys that entered through a slotless path poison the compact wire
    for passes touching them — it must fall back to the dedup wire and
    still train correctly."""
    tr, ds = _make_arena(criteo_files)
    some = ds.columnar.keys[:10].astype(np.uint64)
    tr.table.index.assign(some)  # slotless → default arena
    rp = ResidentPass.build_streamed(ds, tr.table)
    assert rp.wire == "dedup"
    res = tr.train_pass_resident(rp)
    assert np.isfinite(res["auc"])


def test_slot_wire_roundtrips_segments():
    """The SLOT segment wire (u8 slots + u16 per-record counts) must
    reconstruct the exact u18 segment stream, pads included."""
    from paddlebox_tpu.train.device_pass import (ResidentPass,
                                                 ResidentPassRunner)
    rng = np.random.default_rng(5)
    nb, B, S, K = 3, 16, 7, 128
    pad_seg = B * S
    segs = np.full((nb, K), pad_seg, np.int32)
    meta = np.zeros((nb, 4), np.int32)
    for i in range(nb):
        counts = rng.integers(0, 4, size=B)
        nk = int(counts.sum())
        rec = np.repeat(np.arange(B), counts)
        slot = rng.integers(0, S, size=nk)
        segs[i, :nk] = rec * S + slot
        meta[i, :2] = (nk, pad_seg)
    enc = ResidentPass._encode_segs_slotwire(segs, meta, B)
    assert enc is not None and enc[0].dtype == np.uint8
    runner = ResidentPassRunner(None, 64, False)  # no num_slots needed:
    # the decode derives S from meta (pad_segment // B)
    enc_j = tuple(jnp.asarray(a) for a in enc)
    for i in range(nb):
        got = np.asarray(runner._decode_segs(
            tuple(a[i] for a in enc_j), jnp.asarray(meta[i])))
        np.testing.assert_array_equal(got, segs[i])
    # violation: keys not grouped by record → falls back (None).
    # Construct a guaranteed record-order inversion: put a key of the
    # LAST record first.
    bad = segs.copy()
    nk0 = int(meta[0, 0])
    assert nk0 >= 2
    bad[0, 0] = (B - 1) * S  # record B-1, slot 0 ahead of everything
    assert ResidentPass._encode_segs_slotwire(bad, meta, B) is None


def test_compact_wire_sentinel_row_stays_zero(criteo_files):
    """The compact wire maps pad keys to the sentinel row (== capacity)
    and device dedup emits it as an in-bounds unique entry. With lazy mf
    creation active (mf_create_thresholds<=0) and a nonzero
    mf_initial_range, the in-table optimizer must NOT seed the sentinel's
    embedx from RNG — unknown keys read zeros (host_pull / ServingModel
    contract)."""
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(criteo_files)
    ds.set_thread(2)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.5,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=4096, arena_slots=26,
                           arena_chunk_bits=6)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    rp = ResidentPass.build_streamed(ds, tr.table)
    assert rp.wire == "compact"
    tr.train_pass_resident(rp)
    from paddlebox_tpu.ps.table import gather_full_rows
    sent = np.asarray(jax.device_get(gather_full_rows(
        tr.state.table, jnp.asarray([table.capacity], jnp.int32))))
    assert not np.any(sent), sent
    # and host_pull of an unknown key reads zeros
    vals = tr.table.host_pull(np.array([0xdeadbeefcafe], dtype=np.uint64))
    assert not np.any(vals)


def test_resident_metric_registry_accumulates(criteo_files):
    """Registry metric variants now accumulate in RESIDENT mode too: the
    runner collects per-batch predictions and the trainer replays the
    AddAucMonitor feed from the dataset's columnar side channels —
    matching the streaming pass's registry results."""
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    for tr in (tr_a, tr_b):
        tr.metrics.init_metric("auc2", method="auc")
        tr.metrics.init_metric("wu", method="wuauc")
    ra = tr_a.train_pass(ds)
    rb = tr_b.train_pass_resident(ds)
    ma = tr_a.metrics.get_metric_msg("auc2")
    mb = tr_b.metrics.get_metric_msg("auc2")
    assert np.isclose(mb["auc"], ma["auc"], atol=2e-3), (ma, mb)
    wa = tr_a.metrics.get_metric_msg("wu")
    wb = tr_b.metrics.get_metric_msg("wu")
    assert np.isclose(wb["wuauc"], wa["wuauc"], atol=5e-3), (wa, wb)


def test_compact_wire_non_trivial_segments():
    """Compact wire with multi-key slots (non-trivial segments): the
    wire ships segments and the device derives slots from segment % S —
    must match the dedup wire's training exactly."""
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=64,
                        key_bucket_min=512)
    # slot-DISJOINT key spaces (CTR feasigns are globally unique, so a
    # key's slot is stable — the arena relies on that)
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(11)
    recs = []
    for i in range(512):
        counts = rng.integers(0, 3, size=4)
        counts[rng.integers(0, 4)] += 1
        offs = np.zeros(5, np.int32)
        np.cumsum(counts, out=offs[1:])
        keys = np.concatenate([
            rng.integers(s * 1000, (s + 1) * 1000, size=counts[s])
            for s in range(4)]).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offs,
            dense=rng.normal(size=3).astype(np.float32),
            label=float(i % 2), show=1.0, clk=float(i % 2)))

    def mk(arena):
        ds = InMemoryDataset(desc)
        ds.records = list(recs)
        ds.columnarize()
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0,
                              learning_rate=0.05, mf_learning_rate=0.05)
        table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                               unique_bucket_min=512,
                               arena_slots=4 if arena else None,
                               arena_chunk_bits=6)
        tr = Trainer(DeepFM(hidden=(16, 8)), table, desc,
                     tx=optax.adam(1e-2), seed=3)
        return tr, ds

    tr_a, ds_a = mk(False)
    tr_b, ds_b = mk(True)
    for _ in range(2):
        rp_a = ResidentPass.build_streamed(ds_a, tr_a.table)
        assert rp_a.wire == "dedup" and rp_a.segs is not None
        ra = tr_a.train_pass_resident(rp_a)
        rp_b = ResidentPass.build_streamed(ds_b, tr_b.table)
        assert rp_b.wire == "compact" and rp_b.segs is not None
        rb = tr_b.train_pass_resident(rp_b)
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3), (ra["auc"],
                                                         rb["auc"])
    pa = jax.tree.leaves(tr_a.state.params)
    pb = jax.tree.leaves(tr_b.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-3)


def test_u12_locals_wire_roundtrip_and_selection():
    """u12 byte-pair wire (ops/bitpack): exact roundtrip, and
    _encode_locals picks it exactly when locals fit 12 bits (the
    thousand-slot wire diet — VERDICT r4 item 7)."""
    import jax.numpy as jnp

    from paddlebox_tpu.ops.bitpack import pack_u12, unpack_u12

    rng = np.random.default_rng(4)
    v = rng.integers(0, 1 << 12, size=(6, 512)).astype(np.int32)
    (b,) = pack_u12(v)
    assert b.dtype == np.uint8 and b.shape == (6, 768)  # 1.5 B/value
    np.testing.assert_array_equal(np.asarray(unpack_u12(jnp.asarray(b))),
                                  v)
    # boundary values survive
    edge = np.array([[0, 4095, 1, 4094]], np.int32)
    np.testing.assert_array_equal(
        np.asarray(unpack_u12(jnp.asarray(pack_u12(edge)[0]))), edge)

    enc = ResidentPass._encode_locals(v, bits=12)
    assert len(enc) == 1 and enc[0].dtype == np.uint8
    enc16 = ResidentPass._encode_locals(v, bits=13)
    assert enc16[0].dtype == np.uint16
    # odd K cannot pair-pack → u16
    enc_odd = ResidentPass._encode_locals(v[:, :511], bits=12)
    assert enc_odd[0].dtype == np.uint16


def test_compact_wire_u12_matches_u16(criteo_files):
    """A small-vocab arena (locals ≤ 12 bits) trains identically through
    the u12 and u16 local wires."""
    import jax

    def run(force16):
        tr, ds = _make_arena(criteo_files)
        if force16:
            orig = ResidentPass._encode_locals

            def enc16(locs, bits):
                return orig(locs, max(bits, 13))
            ResidentPass._encode_locals = staticmethod(enc16)
        try:
            for _ in range(2):
                out = tr.train_pass_resident(ds)
        finally:
            if force16:
                ResidentPass._encode_locals = staticmethod(orig)
        return out, tr

    (ra, tr_a), (rb, tr_b) = run(False), run(True)
    assert np.isclose(ra["auc"], rb["auc"], atol=1e-9)
    for a, b in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_grid_segment_wire_roundtrip_and_selection():
    """GRID segment wire (per-(record,slot) u8 counts): picked exactly
    when keys are (record, slot)-ordered, decodes to the same segments
    as the u18 wire; slot-disordered batches fall back to the SLOT wire
    (u8 slots + u16 counts)."""
    import jax.numpy as jnp

    from paddlebox_tpu.train.device_pass import ResidentPassRunner

    rng = np.random.default_rng(6)
    B, S = 8, 5
    counts = rng.integers(0, 3, size=(2, B, S))
    k_real = counts.sum(axis=(1, 2))
    k_pad = int(k_real.max()) + 8
    segs = np.full((2, k_pad), B * S, np.int32)
    for i in range(2):
        seg_list = np.repeat(np.arange(B * S), counts[i].reshape(-1))
        segs[i, :len(seg_list)] = seg_list
    meta = np.zeros((2, 4), np.int32)
    meta[:, 0] = k_real
    meta[:, 1] = B * S
    enc = ResidentPass._encode_segs_slotwire(segs, meta, B)
    assert len(enc) == 1 and enc[0].dtype == np.uint8
    assert enc[0].shape == (2, B, S)          # ~S B/record, not 1 B/key
    for i in range(2):
        got = np.asarray(ResidentPassRunner._decode_segs(
            (jnp.asarray(enc[0][i]),), jnp.asarray(meta[i]), k_pad=k_pad))
        np.testing.assert_array_equal(got, segs[i])

    # slot-disordered (but record-grouped) → SLOT wire fallback:
    # construct a GUARANTEED inversion (swap record 0's slots S-1, 0)
    bad = segs.copy()
    nk0 = int(meta[0, 0])
    bad[0, :nk0] = np.sort(bad[0, :nk0])
    r0 = bad[0, :nk0] // S
    first_rec = bad[0, :nk0][r0 == r0[0]]
    assert len(first_rec) >= 1
    bad[0, 0] = r0[0] * S + (S - 1)           # slot S-1 first
    bad[0, 1:nk0] = np.sort(bad[0, 1:nk0])    # rest still grouped
    enc2 = ResidentPass._encode_segs_slotwire(bad, meta, B)
    assert len(enc2) == 2
    for i in range(2):
        got = np.asarray(ResidentPassRunner._decode_segs(
            (jnp.asarray(enc2[0][i]), jnp.asarray(enc2[1][i])),
            jnp.asarray(meta[i])))
        np.testing.assert_array_equal(got, bad[i])
