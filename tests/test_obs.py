"""Observability layer (paddlebox_tpu/obs): instrument semantics, JSONL
event round-trip, Prometheus exposition + HTTP endpoint, channel gauge
wiring under producer/consumer load, straggler watchdog detection, and
the trainer pass-event integration (ISSUE 1 acceptance surface)."""

import importlib.util
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu.obs import (DirHeartbeatStore, JsonlSink,
                               LocalHeartbeatStore, MemorySink,
                               StragglerTimeout, StragglerWatchdog,
                               TelemetryHub, get_hub, reset_hub)
from paddlebox_tpu.obs.hub import emit_pass_event
from paddlebox_tpu.obs.instruments import Counter, Gauge, Histogram
from paddlebox_tpu.utils.channel import (Channel, channel_stats_snapshot,
                                         reset_channel_stats)


@pytest.fixture()
def fresh_hub():
    hub = reset_hub()
    yield hub
    reset_hub()


# ---- instruments -------------------------------------------------------
def test_counter_semantics():
    c = Counter("req_total")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    c.inc(1, shard=0)
    c.inc(2, shard=0)
    c.inc(5, shard=1)
    assert c.value(shard=0) == 3 and c.value(shard=1) == 5
    assert c.value() == 3.5  # labelless series is independent
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_semantics():
    g = Gauge("depth")
    g.set(7)
    assert g.value() == 7
    g.set(3)
    assert g.value() == 3
    g.set_max(1)   # watermark keeps the max
    assert g.value() == 3
    g.set_max(10)
    assert g.value() == 10
    g.inc(2, host=1)
    g.inc(3, host=1)
    assert g.value(host=1) == 5


def test_histogram_semantics():
    h = Histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 5
    assert s["sum"] == pytest.approx(56.05)
    # cumulative le semantics; 50.0 only lands in +Inf (== count)
    assert s["buckets"][0.1] == 1
    assert s["buckets"][1.0] == 3
    assert s["buckets"][10.0] == 4


def test_histogram_quantiles():
    """Bucket-interpolated p50/p90/p99 (ISSUE 15 satellite): the
    serving-latency SLO surface. Linear interpolation inside the
    target bucket; ranks past the last finite bucket clamp to it."""
    h = Histogram("lat", buckets=(0.001, 0.01, 0.1, 1.0))
    for _ in range(50):
        h.observe(0.0005)
    for _ in range(40):
        h.observe(0.005)
    for _ in range(10):
        h.observe(0.05)
    # rank 50 lands exactly at the first bucket's upper bound
    assert h.quantile(0.5) == pytest.approx(0.001)
    # rank 90 at the second bucket's bound; rank 99 interpolates 9/10
    # into the third bucket [0.01, 0.1)
    assert h.quantile(0.9) == pytest.approx(0.01)
    assert h.quantile(0.99) == pytest.approx(0.01 + 0.09 * 0.9)
    # labeled series are independent (one sample in [0.1, 1.0):
    # rank q interpolates q of the way through its bucket); empty
    # series read 0
    h.observe(0.5, op="predict")
    assert h.quantile(0.5, op="predict") == pytest.approx(0.55)
    assert h.quantile(0.99, op="predict") == pytest.approx(0.991)
    assert h.quantile(0.5, op="nope") == 0.0
    with pytest.raises(ValueError):
        h.quantile(0.0)
    with pytest.raises(ValueError):
        h.quantile(1.5)
    # +Inf overflow clamps to the last finite bucket
    h2 = Histogram("of", buckets=(0.1, 1.0))
    for _ in range(10):
        h2.observe(50.0)
    assert h2.quantile(0.99) == pytest.approx(1.0)


def test_histogram_prom_quantile_lines():
    """The text exposition carries scrapeable p50/p90/p99 quantile
    lines per series alongside the buckets and _count/_sum."""
    from paddlebox_tpu.obs.instruments import iter_prom_lines
    h = Histogram("pbox_lat_seconds", "latency",
                  buckets=(0.001, 0.01, 0.1))
    for _ in range(99):
        h.observe(0.005, op="lookup")
    h.observe(0.05, op="lookup")
    text = "\n".join(iter_prom_lines(h))
    assert "# TYPE pbox_lat_seconds histogram" in text
    assert 'pbox_lat_seconds_bucket{op="lookup",le="0.01"} 99' in text
    assert 'pbox_lat_seconds_bucket{op="lookup",le="+Inf"} 100' in text
    # quantiles live in a SIBLING declared gauge family — bare-name
    # quantile samples inside a histogram family are invalid exposition
    assert "# TYPE pbox_lat_seconds_quantile gauge" in text
    q50 = h.quantile(0.5, op="lookup")
    q99 = h.quantile(0.99, op="lookup")
    assert (f'pbox_lat_seconds_quantile{{op="lookup",quantile="0.5"}} '
            f"{q50:g}") in text
    assert (f'pbox_lat_seconds_quantile{{op="lookup",quantile="0.99"}} '
            f"{q99:g}") in text
    assert 'pbox_lat_seconds_count{op="lookup"} 100' in text
    assert "pbox_lat_seconds_sum" in text
    # the quantile family declaration comes after the histogram block
    assert text.index("# TYPE pbox_lat_seconds_quantile gauge") \
        > text.index("pbox_lat_seconds_count")


def test_instrument_kind_collision(fresh_hub):
    fresh_hub.counter("x_total")
    with pytest.raises(TypeError):
        fresh_hub.gauge("x_total")
    # idempotent get-or-create returns the same instance
    assert fresh_hub.counter("x_total") is fresh_hub.counter("x_total")


# ---- sinks + events ----------------------------------------------------
def test_jsonl_sink_roundtrip(tmp_path, fresh_hub):
    path = str(tmp_path / "run.jsonl")
    fresh_hub.add_sink(JsonlSink(path))
    assert fresh_hub.active
    for i in range(5):
        fresh_hub.emit("tick", i=i, note="x" * i)
    fresh_hub.close_sinks()
    assert not fresh_hub.active
    lines = open(path).read().splitlines()
    assert len(lines) == 5
    evs = [json.loads(l) for l in lines]  # every line is valid JSON
    assert [e["i"] for e in evs] == list(range(5))
    ts = [e["ts"] for e in evs]
    seqs = [e["seq"] for e in evs]
    assert ts == sorted(ts), "timestamps must be monotone"
    assert seqs == sorted(seqs) and len(set(seqs)) == 5
    assert all(e["event"] == "tick" and "run" in e for e in evs)


def test_no_sink_fast_path(fresh_hub):
    assert not fresh_hub.active
    # emit_pass_event must return before creating any instrument
    emit_pass_event("train_pass", {"batches": 1, "elapsed_sec": 1.0})
    assert fresh_hub.snapshot() == {}


def test_prom_exposition(fresh_hub):
    fresh_hub.counter("pbox_req_total", "requests").inc(3, kind="a")
    fresh_hub.gauge("pbox_depth").set(2.5)
    h = fresh_hub.histogram("pbox_lat_seconds", buckets=(0.5, 1.0))
    h.observe(0.2)
    h.observe(0.7)
    h.observe(7.0)
    text = fresh_hub.snapshot_prom()
    assert "# TYPE pbox_req_total counter" in text
    assert 'pbox_req_total{kind="a"} 3' in text
    assert "# TYPE pbox_depth gauge" in text
    assert "pbox_depth 2.5" in text
    assert 'pbox_lat_seconds_bucket{le="0.5"} 1' in text
    assert 'pbox_lat_seconds_bucket{le="1.0"} 2' in text
    assert 'pbox_lat_seconds_bucket{le="+Inf"} 3' in text
    assert "pbox_lat_seconds_count 3" in text
    # legacy StatRegistry bridges as pbox_stat gauges
    from paddlebox_tpu.utils.monitor import STATS
    STATS.set("obs_test_stat", 42)
    try:
        assert 'pbox_stat{name="obs_test_stat"} 42' \
            in fresh_hub.snapshot_prom()
    finally:
        STATS.reset("obs_test_stat")


def test_prom_http_endpoint(fresh_hub):
    fresh_hub.counter("pbox_http_total").inc(7)
    srv = fresh_hub.start_prom_http(0)
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "pbox_http_total 7" in body
    finally:
        fresh_hub.stop_prom_http()


def test_healthz_route(fresh_hub):
    """/healthz on the prom endpoint (ISSUE 10 satellite): run_id,
    uptime, and last-pass age — the serving/streaming liveness probe."""
    srv = fresh_hub.start_prom_http(0)
    try:
        port = srv.server_address[1]
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5)
        assert resp.headers["Content-Type"] == "application/json"
        h = json.loads(resp.read().decode())
        assert h["status"] == "ok"
        assert h["run_id"] == fresh_hub.run_id
        assert h["uptime_sec"] >= 0
        # no pass yet: age is null, count 0
        assert h["passes_total"] == 0
        assert h["last_pass_age_sec"] is None
        emit_pass_event("train_pass", {"batches": 1, "elapsed_sec": 0.1})
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
            ).read().decode())
        assert h["passes_total"] == 1
        assert h["last_pass_age_sec"] is not None
        assert 0 <= h["last_pass_age_sec"] < 60
        # /metrics still serves exposition on the same port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
        assert "pbox_passes_total" in body
    finally:
        fresh_hub.stop_prom_http()


def test_readyz_route_and_serving_block(fresh_hub):
    """/readyz (ISSUE 15 satellite): 503 until the serving probe
    reports a first snapshot adoption; /healthz grows the ``serving``
    block once a probe registers."""
    srv = fresh_hub.start_prom_http(0)
    try:
        port = srv.server_address[1]

        def get(route):
            try:
                resp = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{route}", timeout=5)
                return resp.status, json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read().decode())

        # no serving model in the process: unready, no serving block
        code, body = get("/readyz")
        assert code == 503 and body["ready"] is False
        assert "serving" not in fresh_hub.health()
        # a registered probe with no adoption yet: still 503, but the
        # health endpoint now shows the serving state
        state = {"adopted": None, "epoch": None,
                 "last_reload_ts": None, "staleness_sec": 0.0,
                 "stale": False}
        fresh_hub.set_serving_probe(lambda: dict(state))
        code, body = get("/readyz")
        assert code == 503
        assert body["reason"] == "no snapshot adopted yet"
        h = get("/healthz")[1]
        assert h["serving"]["adopted"] is None
        # first adoption flips readiness; the block carries the id
        state.update(adopted="v0000000007", epoch=7,
                     last_reload_ts=123.0, staleness_sec=1.5)
        code, body = get("/readyz")
        assert code == 200 and body["ready"] is True
        assert body["serving"]["adopted"] == "v0000000007"
        h = get("/healthz")[1]
        assert h["serving"]["staleness_sec"] == 1.5
        # a crashing probe degrades the block, never the endpoint
        def boom():
            raise RuntimeError("probe died")
        fresh_hub.set_serving_probe(boom)
        code, body = get("/readyz")
        assert code == 503
        assert get("/healthz")[0] == 200
    finally:
        fresh_hub.stop_prom_http()


def test_serving_report_column():
    """telemetry_report renders the serving-latency column + summary
    line from serving_stats/serving_reload events (ISSUE 15
    satellite); training-only JSONLs keep their compact rows."""
    spec = importlib.util.spec_from_file_location(
        "telemetry_report_sv",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    events = [
        {"event": "serving_stats", "adopted": "v0000000001",
         "staleness_sec": 0.0, "lookup_p99_ms": 0.21, "queries": 10},
        {"event": "pass", "kind": "train_pass", "batches": 4,
         "elapsed_sec": 1.0, "examples": 128,
         "examples_per_sec": 128.0, "proc": 0},
        {"event": "serving_reload", "artifact": "v0000000002"},
        {"event": "serving_stats", "adopted": "v0000000002",
         "staleness_sec": 2.1, "predict_p99_ms": 5.99, "queries": 30},
        {"event": "pass", "kind": "train_pass", "batches": 4,
         "elapsed_sec": 1.0, "examples": 128,
         "examples_per_sec": 128.0, "proc": 0},
        {"event": "serving_degraded", "tip": "v0000000003",
         "adopted": "v0000000002", "staleness_sec": 4.0},
    ]
    rows = mod.build_rows(events)
    assert rows[0]["serve p99"] == "p99 0.21ms @v0000000001"
    assert rows[1]["serve p99"] \
        == "p99 5.99ms @v0000000002 (+2.1s stale)"
    rep = mod.render_report(events)
    assert "serving: 1 reloads → v0000000002" in rep
    assert "1 degraded polls" in rep and "max staleness 4.0s" in rep
    # training-only runs: no serving column
    rows = mod.build_rows([e for e in events if e["event"] == "pass"])
    assert "serve p99" not in rows[0]


def test_add_sink_dual_capability_registers_both(fresh_hub):
    """Regression (ISSUE 10 satellite): a sink exposing BOTH emit and
    span used to be silently registered span-only — its events were
    dropped. It must land in both lists; kind= narrows explicitly."""

    class Dual:
        def __init__(self):
            self.events, self.spans = [], []

        def emit(self, ev):
            self.events.append(ev)

        def span(self, name, start, dur, attrs):
            self.spans.append(name)

        def close(self):
            pass

    d = Dual()
    fresh_hub.add_sink(d)
    assert d in fresh_hub.event_sinks()
    assert d in fresh_hub.span_sinks()
    fresh_hub.emit("tick")
    with fresh_hub.span("s1"):
        pass
    assert [e["event"] for e in d.events] == ["tick"]
    assert d.spans == ["s1"]
    # explicit kinds narrow; impossible kinds are loud
    only_ev = Dual()
    fresh_hub.add_sink(only_ev, kind="event")
    assert only_ev in fresh_hub.event_sinks()
    assert only_ev not in fresh_hub.span_sinks()
    with pytest.raises(ValueError):
        fresh_hub.add_sink(Dual(), kind="bogus")
    with pytest.raises(TypeError):
        fresh_hub.add_sink(object())
    # close_sinks closes a dual sink exactly once
    closes = []

    class CountingDual(Dual):
        def close(self):
            closes.append(1)

    fresh_hub.add_sink(CountingDual())
    fresh_hub.close_sinks()
    assert len(closes) == 1


def test_chrome_span_sink(fresh_hub):
    from paddlebox_tpu.obs import ChromeSpanSink
    from paddlebox_tpu.utils.profiler import ChromeTraceWriter
    w = ChromeTraceWriter()
    fresh_hub.add_sink(ChromeSpanSink(w))
    with fresh_hub.span("stage_x", pass_id=3):
        pass
    assert w._events and w._events[0]["name"] == "stage_x"
    assert w._events[0]["args"] == {"pass_id": 3}


# ---- channel gauges ----------------------------------------------------
def test_channel_blocked_put_and_watermark():
    reset_channel_stats()
    ch = Channel(capacity=2, name="t.full")
    done = threading.Event()

    def slow_consumer():
        while True:
            try:
                ch.get(timeout=5)
            except Exception:
                break
            time.sleep(0.02)
        done.set()

    th = threading.Thread(target=slow_consumer, daemon=True)
    th.start()
    for i in range(10):
        ch.put(i)
    m = ch.metrics()
    assert m["high_watermark"] == 2
    assert m["blocked_put_sec"] > 0.01
    assert m["puts"] == 10
    ch.close()
    done.wait(5)
    snap = channel_stats_snapshot()
    assert "t.full" in snap
    assert snap["t.full"]["blocked_put_sec"] > 0.01
    assert snap["t.full"]["high_watermark"] == 2


def test_channel_blocked_get_under_starvation():
    reset_channel_stats()
    ch = Channel(capacity=8, name="t.starved")

    def slow_producer():
        for i in range(3):
            time.sleep(0.03)
            ch.put(i)
        ch.close()

    threading.Thread(target=slow_producer, daemon=True).start()
    got = list(ch)  # batched get path
    assert got == [0, 1, 2]
    snap = channel_stats_snapshot()
    assert snap["t.starved"]["blocked_get_sec"] > 0.02
    assert snap["t.starved"]["gets"] == 3


def test_anonymous_channel_not_registered():
    reset_channel_stats()
    ch = Channel(capacity=4)
    ch.put(1)
    ch.close()
    assert channel_stats_snapshot() == {}


# ---- straggler watchdog ------------------------------------------------
class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_wd(store, clock, **kw):
    kw.setdefault("step_lag", 10)
    kw.setdefault("heartbeat_timeout", 30.0)
    return StragglerWatchdog(store, process_index=0, num_processes=2,
                             clock=clock, hub=TelemetryHub(), **kw)


def test_watchdog_silent_on_healthy():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    wd = make_wd(store, clock)
    for step in range(0, 50, 5):
        store.publish(0, step, clock())
        store.publish(1, step - 3, clock())  # within lag
        clock.t += 5
        assert wd.check() == []


def test_watchdog_fires_on_step_lag():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    wd = make_wd(store, clock)
    store.publish(0, 100, clock())
    store.publish(1, 50, clock())  # 50 behind > lag 10
    reps = wd.check()
    assert len(reps) == 1
    r = reps[0]
    assert r.process == 1 and r.reason == "step_lag" and r.behind == 50


def test_watchdog_fires_on_stale_heartbeat():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    wd = make_wd(store, clock)
    store.publish(0, 10, clock())
    store.publish(1, 10, clock())
    clock.t += 100  # both stale, but proc publishing again recovers
    store.publish(0, 11, clock())
    reps = wd.check()
    assert [r.process for r in reps] == [1]
    assert reps[0].reason == "stale"
    assert reps[0].age_sec == pytest.approx(100.0)


def test_watchdog_missing_process_after_grace():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    wd = make_wd(store, clock)
    store.publish(0, 5, clock())
    assert wd.check() == []  # inside the startup grace window
    clock.t += 60
    store.publish(0, 6, clock())
    reps = wd.check()
    assert [r.reason for r in reps] == ["missing"]
    assert reps[0].process == 1 and reps[0].step == -1


def test_watchdog_ignores_prior_run_leftovers():
    """A reused heartbeat dir (restart/elastic downsize) must not let
    the old run's files define the front-runner or report stragglers."""
    clock = FakeClock(2000.0)
    store = LocalHeartbeatStore()
    store.publish(1, 120_000, 100.0)   # old run, huge step, stale ts
    store.publish(7, 120_000, 100.0)   # rank beyond this 2-process mesh
    wd = make_wd(store, clock)
    store.publish(0, 3, clock())
    store.publish(1, 2, clock())       # fresh beat replaces the leftover
    assert wd.check() == []


def test_watchdog_abort_arms_and_beat_raises():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    seen = []
    wd = make_wd(store, clock, abort_after=20.0,
                 on_straggler=lambda reps: seen.append(reps))
    store.publish(0, 100, clock())
    store.publish(1, 0, clock())
    wd.poll_once()              # detection; stall clock starts
    assert seen and not wd._abort_exc
    wd.beat(101)                # still fine before the deadline
    clock.t += 25
    wd.poll_once()              # past abort_after → abort armed
    with pytest.raises(StragglerTimeout):
        wd.beat(102)


def test_watchdog_emits_events():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    hub = TelemetryHub()
    sink = MemorySink()
    hub.add_sink(sink)
    wd = StragglerWatchdog(store, 0, 2, step_lag=10, clock=clock, hub=hub)
    store.publish(0, 100, clock())
    store.publish(1, 0, clock())
    wd.poll_once()
    evs = [e for e in sink.events if e["event"] == "straggler"]
    assert evs and evs[0]["stragglers"][0]["process"] == 1
    assert hub.counter("pbox_straggler_events_total").value() == 1


def test_watchdog_background_thread_detects():
    store = LocalHeartbeatStore()
    fired = threading.Event()
    wd = StragglerWatchdog(store, 0, 2, step_lag=5, poll_interval=0.02,
                           hub=TelemetryHub(),
                           on_straggler=lambda reps: fired.set())
    store.publish(0, 100, time.time())
    store.publish(1, 1, time.time())
    wd.start()
    try:
        assert fired.wait(5), "watchdog thread never fired"
    finally:
        wd.stop()


def test_dir_heartbeat_store_roundtrip(tmp_path):
    store = DirHeartbeatStore(str(tmp_path / "hb"))
    store.publish(0, 12, 100.0)
    store.publish(3, 7, 101.5)
    store.publish(0, 13, 102.0)  # overwrite
    beats = store.read()
    assert beats == {0: (13, 102.0), 3: (7, 101.5)}
    # torn/foreign files are skipped, not fatal
    with open(tmp_path / "hb" / "hb_9.json", "w") as fh:
        fh.write("{not json")
    assert store.read() == beats


def test_make_straggler_watchdog_single_process(tmp_path):
    from paddlebox_tpu.train.multihost import make_straggler_watchdog
    wd = make_straggler_watchdog(start=False)
    assert isinstance(wd.store, LocalHeartbeatStore)
    wd2 = make_straggler_watchdog(heartbeat_dir=str(tmp_path / "hb"),
                                  start=False)
    assert isinstance(wd2.store, DirHeartbeatStore)
    wd2.beat(5)
    assert wd2.store.read()[wd2.process_index][0] == 5


# ---- scatter warmup (AOT, no device allocation) ------------------------
def test_scatter_warmup_emits_event(fresh_hub):
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps.table import init_table_state, \
        start_scatter_warmup
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    st = init_table_state(63, 8)
    with flags_scope(scatter_chunk_rows=64, warmup_pass_scatter=True):
        start_scatter_warmup(st, sharded=False)
        deadline = time.time() + 60
        while time.time() < deadline:
            if any(e["event"] == "scatter_warmup" for e in sink.events):
                break
            time.sleep(0.05)
    evs = [e for e in sink.events if e["event"] == "scatter_warmup"]
    assert evs, "warmup never reported"
    assert evs[0]["outcome"] == "ok"
    assert fresh_hub.counter("pbox_scatter_warmup_total").value(
        outcome="ok") == 1


# ---- trainer integration (pass events end to end) ----------------------
@pytest.fixture(scope="module")
def tiny_trainer_run(tmp_path_factory):
    """One streaming + one resident pass with the JSONL sink attached;
    yields (events, report_text)."""
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    d = tmp_path_factory.mktemp("obs_run")
    files = generate_criteo_files(str(d), num_files=1, rows_per_file=400,
                                  vocab_per_slot=40, seed=11)
    path = str(d / "run.jsonl")
    hub = reset_hub()
    hub.add_sink(JsonlSink(path))
    try:
        desc = DataFeedDesc.criteo(batch_size=128)
        desc.key_bucket_min = 4096
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.set_thread(2)
        ds.load_into_memory()
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=1e-3)
        table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                               unique_bucket_min=4096)
        with flags_scope(log_period_steps=10000):
            tr = Trainer(CtrDnn(hidden=(16,)), table, desc,
                         tx=optax.adam(1e-3))
            tr.train_pass(ds)
            tr.train_pass_resident(ds)
    finally:
        reset_hub()
    events = [json.loads(l) for l in open(path)]
    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return events, mod.render_report(events)


def test_pass_events_schema(tiny_trainer_run):
    events, _ = tiny_trainer_run
    passes = [e for e in events if e["event"] == "pass"]
    kinds = [e["kind"] for e in passes]
    assert kinds == ["train_pass", "train_pass_resident"]
    for e in passes:
        json.dumps(e)  # round-trips
        assert e["batches"] >= 1 and e["elapsed_sec"] > 0
        assert "step" in e["stage_sec"], "new 'step' stage must be timed"
        assert e["stage_count"]["step"] >= 1
        assert set(e["hbm"]) == {"bytes_in_use", "peak_bytes_in_use",
                                 "bytes_limit"}
        assert e["table"]["used"] > 0
        assert e["table"]["capacity"] == 1 << 13
        assert "channels" in e
    stream = passes[0]
    # prefetch pipeline gauges present with put/get accounting
    assert stream["channels"]["trainer.prepare"]["puts"] >= 1
    assert "blocked_put_sec" in stream["channels"]["trainer.prepare"]
    assert "trainer.h2d" in stream["channels"]
    # streaming pass timed prepare/h2d/step/(metrics when registered)
    assert stream["stage_sec"]["prepare"] >= 0
    seqs = [e["ts"] for e in events]
    assert seqs == sorted(seqs)


def test_report_renders(tiny_trainer_run):
    _, report = tiny_trainer_run
    assert "train_pass_resident" in report
    assert "queue stall" in report
    assert "2 passes" in report


def test_trainer_without_sinks_stays_inert(tmp_path_factory):
    """Default-off contract: no sink → no events, no instruments."""
    hub = reset_hub()
    assert not hub.active
    emit_pass_event("train_pass", {"batches": 1})
    assert hub.snapshot() == {}
    reset_hub()
