"""Ring / Ulysses sequence-parallel attention vs the single-device oracle
on the 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.parallel.mesh import DATA_AXIS
from paddlebox_tpu.parallel.ring_attention import (
    make_context_parallel_attention, reference_attention)

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N
    return make_mesh(N)


def _qkv(b=2, t=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(mesh, kind, causal):
    # ulysses reshards heads across the axis → needs H % N == 0
    q, k, v = _qkv(h=8 if kind == "ulysses" else 4)
    want = reference_attention(q, k, v, causal=causal)
    attn = make_context_parallel_attention(mesh, DATA_AXIS, kind=kind,
                                           causal=causal)
    got = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_grad_matches_reference(mesh):
    """Backward pass through the ring (ppermute transposes) must match."""
    q, k, v = _qkv(t=32, h=2, d=8, seed=1)
    attn = make_context_parallel_attention(mesh, DATA_AXIS, kind="ring",
                                           causal=True)

    def loss_par(q, k, v):
        return (attn(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    gp = jax.grad(loss_par, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ring_long_sequence_blocks(mesh):
    """T_global larger than any single block; non-divisible head count
    still fine for ring (no head reshard)."""
    q, k, v = _qkv(b=1, t=128, h=3, d=8, seed=2)
    attn = make_context_parallel_attention(mesh, DATA_AXIS, kind="ring")
    got = attn(q, k, v)
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
