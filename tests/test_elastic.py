"""Unit coverage for the hardened elastic membership layer (ISSUE 18):
``distributed/elastic.py`` lifecycle + hysteresis + eviction, the
reversible key escaping, touch-not-rewrite heartbeats, rendezvous
timeout diagnostics, consensus participant narrowing
(``resilience/consensus.py``), the watchdog shrink-and-continue rung
(``obs/watchdog.py``), and the cross-shard-count checkpoint re-import
(``ps/sharded.py`` ``_file_per_shard`` / ``ps/tiered_multihost.py``
``load_reshard``) that makes an elastic re-shard a deterministic
re-import."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from paddlebox_tpu.distributed.elastic import (ElasticLevel,
                                               ElasticManager,
                                               FileKVStore)

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _age(store: FileKVStore, key: str, by_sec: float) -> None:
    old = time.time() - by_sec
    os.utime(store._path(key), (old, old))


def _lease(store: FileKVStore, job: str, host: str) -> str:
    key = f"paddlebox/{job}/nodes/{host}"
    store.put(key, json.dumps({"host": host}).encode())
    return key


# ---- FileKVStore hardening ------------------------------------------------

def test_key_escaping_roundtrips_hostile_names(tmp_path):
    """Percent-encoding is reversible: hosts containing the old ``__``
    separator (or slashes) survive list_prefix intact — the lossy
    ``__`` -> ``/`` unescape would have mangled them."""
    store = FileKVStore(str(tmp_path))
    for host in ("plain", "tpu__pod__3", "rack/7"):
        store.put(f"paddlebox/j/nodes/{host}", b"x")
    got = sorted(store.list_prefix("paddlebox/j/nodes"))
    assert got == sorted(f"paddlebox/j/nodes/{h}"
                         for h in ("plain", "tpu__pod__3", "rack/7"))
    # membership parsing takes the key's last path segment, so only
    # slash-free hosts (every real hostname) appear under their own name
    store.delete("paddlebox/j/nodes/rack/7")
    mgr = ElasticManager(store, "j", "plain", 2, ttl=60.0)
    assert mgr.alive_hosts() == ["plain", "tpu__pod__3"]


def test_touch_refreshes_without_rewriting_payload(tmp_path):
    store = FileKVStore(str(tmp_path))
    store.put("k", b"payload-v1")
    _age(store, "k", 120.0)
    assert store.touch("k") is True
    assert time.time() - store.mtime("k") < 60.0
    assert store.get("k") == b"payload-v1"  # touch never rewrites bytes
    assert store.touch("missing") is False


def test_list_prefix_skips_inflight_tmp_files(tmp_path):
    store = FileKVStore(str(tmp_path))
    store.put("paddlebox/j/nodes/a", b"x")
    with open(os.path.join(str(tmp_path),
                           store._escape("paddlebox/j/nodes/b")
                           + ".tmp.123"), "wb") as fh:
        fh.write(b"torn")
    assert list(store.list_prefix("paddlebox/j/nodes")) == \
        ["paddlebox/j/nodes/a"]


# ---- lifecycle + hysteresis ----------------------------------------------

def test_heartbeat_keeps_lease_fresh_and_deregister_stops(tmp_path):
    store = FileKVStore(str(tmp_path))
    mgr = ElasticManager(store, "j", "h0", 1, ttl=0.6,
                         heartbeat_period=0.1)
    mgr.register(payload={"slot": 3})
    key = f"paddlebox/j/nodes/h0"
    assert json.loads(store.get(key))["slot"] == 3
    time.sleep(0.9)  # > TTL: only the heartbeat keeps it alive
    assert mgr.alive_hosts() == ["h0"]
    mgr.deregister()
    assert store.get(key) is None
    assert not mgr._hb_thread


def test_dead_checks_hysteresis_absorbs_one_missed_poll(tmp_path):
    """A single aged lease (delayed heartbeat / NFS hiccup) must NOT
    fire a scale event at dead_checks=2; a recovery resets the count;
    two consecutive misses confirm the death."""
    store = FileKVStore(str(tmp_path))
    for h in ("h0", "h1"):
        _lease(store, "j", h)
    mgr = ElasticManager(store, "j", "h0", 2, ttl=30.0, dead_checks=2)
    assert mgr.scale_event() is None        # baseline {h0, h1}
    key1 = f"paddlebox/j/nodes/h1"
    _age(store, key1, 120.0)
    assert mgr.scale_event() is None        # miss 1: absorbed
    store.touch(key1)
    assert mgr.scale_event() is None        # recovered: count reset
    _age(store, key1, 120.0)
    assert mgr.scale_event() is None        # miss 1 again (fresh count)
    assert mgr.scale_event() == ["h0"]      # miss 2: confirmed dead
    assert mgr.last_event["lost"] == ["h1"]
    # rejoin is admitted on the FIRST poll that sees it
    store.touch(key1)
    assert mgr.scale_event() == ["h0", "h1"]
    assert mgr.last_event["joined"] == ["h1"]


def test_evict_host_bypasses_hysteresis_and_stops_heartbeat(tmp_path):
    store = FileKVStore(str(tmp_path))
    victim = ElasticManager(store, "j", "h1", 2, ttl=30.0,
                            heartbeat_period=0.05)
    victim.register()
    observer = ElasticManager(store, "j", "h0", 2, ttl=30.0,
                              dead_checks=3)
    _lease(store, "j", "h0")
    assert observer.scale_event() is None   # baseline {h0, h1}
    observer.evict_host("h1", "wedged")
    # lease deleted -> the victim's next beat sees it gone and stops
    # WITHOUT resurrecting the lease
    deadline = time.time() + 5.0
    while victim._hb_thread.is_alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not victim._hb_thread.is_alive(), \
        "evicted heartbeat thread kept running"
    assert store.get("paddlebox/j/nodes/h1") is None, \
        "evicted lease was resurrected by the heartbeat"
    # forced-dead bypasses dead_checks=3: confirmed on the next poll
    assert observer.scale_event() == ["h0"]
    assert observer.last_event["lost"] == ["h1"]


def test_wait_for_np_timeout_names_missing_hosts(tmp_path):
    store = FileKVStore(str(tmp_path))
    for h in ("h0", "h1"):
        _lease(store, "j", h)
    mgr = ElasticManager(store, "j", "h0", 2, ttl=30.0,
                         heartbeat_period=0.05)
    assert mgr.scale_event() is None        # members = {h0, h1}
    store.delete("paddlebox/j/nodes/h1")
    with pytest.raises(TimeoutError) as ei:
        mgr.wait_for_np(timeout=0.3)
    assert "h1" in str(ei.value), str(ei.value)


def test_fault_tolerance_vs_elastic_world_ok(tmp_path):
    store = FileKVStore(str(tmp_path))
    for h in ("h0", "h1", "h2"):
        _lease(store, "j", h)
    ft = ElasticManager(store, "j", "h0", 3, ttl=30.0)
    assert ft.level == ElasticLevel.FAULT_TOLERANCE
    el = ElasticManager(store, "j", "h0", 3, min_np=2, max_np=3,
                        ttl=30.0)
    assert el.level == ElasticLevel.ELASTIC
    assert ft.world_ok() and el.world_ok()
    store.delete("paddlebox/j/nodes/h2")
    assert not ft.world_ok()   # fixed np: 2 != 3
    assert el.world_ok()       # floats in [2, 3]
    store.delete("paddlebox/j/nodes/h1")
    assert not el.world_ok()   # below min_np


def test_checkpoint_pointer_roundtrip_and_status(tmp_path):
    store = FileKVStore(str(tmp_path))
    mgr = ElasticManager(store, "j", "h0", 2, min_np=1, max_np=2,
                         ttl=30.0)
    assert mgr.latest_checkpoint() is None
    mgr.publish_checkpoint("/ckpt/root", pass_id=4)
    assert mgr.latest_checkpoint() == {"path": "/ckpt/root",
                                       "pass_id": 4}
    st = mgr.membership_status()
    assert st["host"] == "h0" and st["level"] == "ELASTIC"
    assert st["target_np"] == 2 and st["reshard_count"] == 0
    mgr.note_reshard(2, 1, step=7)
    assert mgr.membership_status()["reshard_count"] == 1


def test_membership_probe_feeds_healthz_block(tmp_path):
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    reset_hub()
    try:
        store = FileKVStore(str(tmp_path))
        _lease(store, "j", "h0")
        mgr = ElasticManager(store, "j", "h0", 1, ttl=30.0)
        assert mgr.scale_event() is None
        get_hub().set_membership_probe(mgr.membership_status)
        block = get_hub().health()["membership"]
        assert block["alive"] == ["h0"] and block["np"] == 1
    finally:
        reset_hub()


# ---- real 2-process heartbeat leg ----------------------------------------

_PEER = """
import sys, time
from paddlebox_tpu.distributed.elastic import ElasticManager, FileKVStore
root, ttl = sys.argv[1], float(sys.argv[2])
m = ElasticManager(FileKVStore(root), "j2", "peer", 2,
                   ttl=ttl, heartbeat_period=ttl / 5.0)
m.register()
print("up", flush=True)
time.sleep(600)
"""


def test_two_process_heartbeat_sigkill_detection(tmp_path):
    """A REAL peer process heartbeats the shared dir; SIGKILL makes its
    lease expire by genuine TTL and the survivor confirms the death
    (hysteresis honored: never on the first expired poll)."""
    ttl = 0.8
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.abspath(REPO))
    proc = subprocess.Popen([sys.executable, "-c", _PEER,
                             str(tmp_path), str(ttl)],
                            env=env, stdout=subprocess.PIPE, text=True)
    try:
        assert "up" in proc.stdout.readline()
        mgr = ElasticManager(FileKVStore(str(tmp_path)), "j2", "m0", 2,
                             ttl=ttl, heartbeat_period=0.1,
                             dead_checks=2)
        mgr.register()
        assert mgr.scale_event() is None
        assert mgr.alive_hosts() == ["m0", "peer"]
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        deadline = time.time() + 20.0
        event = None
        while event is None and time.time() < deadline:
            time.sleep(ttl / 2.0)
            event = mgr.scale_event()
        assert event == ["m0"], "SIGKILL'd peer never detected"
        assert mgr.last_event["lost"] == ["peer"]
        mgr.deregister()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# ---- consensus participant narrowing -------------------------------------

def test_consensus_participants_narrow_to_survivors(tmp_path):
    from paddlebox_tpu.resilience.consensus import RestoreConsensus
    c0 = RestoreConsensus(str(tmp_path), 0, 2, timeout=10.0,
                          poll_interval=0.01)
    c1 = RestoreConsensus(str(tmp_path), 1, 2, timeout=10.0,
                          poll_interval=0.01)
    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault("r1", c1.agree_restore_step(12)))
    t.start()
    assert c0.agree_restore_step(10) == 10  # full mesh: min(10, 12)
    t.join(timeout=10.0)
    assert out["r1"] == 10
    # rank 1 died: the survivor narrows and agrees ALONE — no timeout
    # waiting on the dead rank's publish
    c0.set_participants([0])
    assert c0.participants == [0]
    assert c0.agree_restore_step(20) == 20
    with pytest.raises(ValueError):
        c0.set_participants([])
    with pytest.raises(ValueError):
        c0.set_participants([1])  # a world that excludes self


# ---- watchdog shrink-and-continue rung -----------------------------------

def test_shrink_and_continue_rung_evicts_wedged_rank():
    from paddlebox_tpu.obs.watchdog import (LocalHeartbeatStore,
                                            StragglerWatchdog,
                                            shrink_and_continue_action)
    evicted = []
    action = shrink_and_continue_action(
        lambda reports: evicted.extend(r.process for r in reports))
    assert action.escalation_name == "shrink_and_continue"
    tvar = [1000.0]
    hb = LocalHeartbeatStore()
    wd = StragglerWatchdog(hb, 0, 3, step_lag=100,
                           heartbeat_timeout=30.0,
                           clock=lambda: tvar[0],
                           escalations=[(0.0, action)])
    hb.publish(2, 50, 1005.0)   # rank 2 wedged long ago
    tvar[0] = 1040.0
    hb.publish(0, 50, tvar[0])
    hb.publish(1, 50, tvar[0])
    reports = wd.poll_once()
    assert [r.process for r in reports] == [2]
    assert reports[0].reason == "stale"
    assert evicted == [2]
    # the rung fires once per stall episode, not once per poll
    wd.poll_once()
    assert evicted == [2]


def test_telemetry_report_membership_timeline():
    from scripts.telemetry_report import membership_summary
    events = [
        {"event": "pass"},
        {"event": "membership_change", "hosts": ["h0", "h2", "h3"],
         "lost": ["h1"], "joined": [], "np": 3, "target_np": 4},
        {"event": "reshard", "old_np": 4, "new_np": 3, "step": 2,
         "count": 1},
        {"event": "membership_change", "hosts": ["h0", "h1", "h2", "h3"],
         "lost": [], "joined": ["h1"], "np": 4, "target_np": 4},
    ]
    assert membership_summary(events) == (
        "membership: np=3 (lost h1) -> reshard 4->3 @step 2 -> "
        "np=4 (joined h1)")
    # a run that ENDS below target carries the degraded flag
    assert "still degraded (3/4)" in membership_summary(events[:3])
    assert membership_summary([{"event": "pass"}]) == ""


# ---- cross-shard-count checkpoint re-import ------------------------------

def _synth_npz(path: str, keys: np.ndarray, mf_dim: int = 4) -> None:
    from paddlebox_tpu.ps.table import FIELDS, TWO_D_FIELDS
    base = keys.astype(np.float32)
    fields = {f: (np.tile(base[:, None], (1, mf_dim)) * 0.01
                  if f in TWO_D_FIELDS else base * 0.001)
              for f in FIELDS}
    np.savez(path, keys=keys, **fields)


def _logical_rows(table) -> dict:
    """key -> row bytes, shard layout cancelled out."""
    data = np.asarray(jax.device_get(table.state.data))
    out = {}
    for s in range(table.n):
        keys, rows = table.indexes[s].items()
        for k, r in zip(keys, rows):
            out[int(k)] = data[s][r].tobytes()
    return out


def test_sharded_load_resplits_foreign_shard_count(tmp_path):
    """An n=4 save re-imports into an n=3 table losslessly via the
    key%N re-split — the property that makes the elastic re-shard a
    deterministic re-import (ISSUE 18)."""
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    keys = np.arange(1, 201, dtype=np.uint64)
    src = os.path.join(str(tmp_path), "src.npz")
    _synth_npz(src, keys)

    def mk(n):
        return ShardedEmbeddingTable(n, mf_dim=4, capacity_per_shard=512,
                                     cfg=cfg, req_bucket_min=64,
                                     serve_bucket_min=64)
    t4 = mk(4)
    assert t4.load(src) == len(keys)
    saved = os.path.join(str(tmp_path), "n4.npz")
    t4.save_base(saved)
    t3 = mk(3)
    assert t3.load(saved) == len(keys)
    assert _logical_rows(t3) == _logical_rows(t4)


def test_file_per_shard_tolerates_partial_files(tmp_path):
    """A multihost per-process save holds only SOME shards; the
    re-split path must concatenate what is present instead of KeyError
    on the absent ones."""
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.ps.table import FIELDS, TWO_D_FIELDS
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    k0 = np.array([4, 8], dtype=np.uint64)      # owner 0 of 4
    k2 = np.array([2, 6], dtype=np.uint64)      # owner 2 of 4
    blobs = {}
    for s, ks in ((0, k0), (2, k2)):
        base = ks.astype(np.float32)
        blobs[f"keys_{s}"] = ks
        for f in FIELDS:
            blobs[f"{f}_{s}"] = (np.tile(base[:, None], (1, 4)) * 0.01
                                 if f in TWO_D_FIELDS else base * 0.001)
    partial = os.path.join(str(tmp_path), "partial.npz")
    np.savez(partial, n=4, **blobs)
    t2 = ShardedEmbeddingTable(2, mf_dim=4, capacity_per_shard=256,
                               cfg=cfg, req_bucket_min=64,
                               serve_bucket_min=64)
    assert t2.load(partial) == 4
    assert sorted(_logical_rows(t2)) == [2, 4, 6, 8]


def test_tiered_multihost_load_reshard(tmp_path):
    """``MultihostTieredShardedTable.load_reshard`` re-imports a
    4-shard save epoch into a 2-shard world: every row lands on its
    key%2 owner, untouched shards reset, values bit-identical."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered_multihost import \
        MultihostTieredShardedTable
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)

    def mk(n):
        return MultihostTieredShardedTable(
            make_mesh(n), mf_dim=4, capacity_per_shard=256, cfg=cfg,
            req_bucket_min=64, serve_bucket_min=64)

    src = os.path.join(str(tmp_path), "src.npz")
    keys = np.arange(1, 97, dtype=np.uint64)
    _synth_npz(src, keys)
    t4 = mk(4)
    # per-process load() refuses foreign saves; the re-shard entry point
    # is the one that accepts a single-table file
    assert t4.load_reshard([src]) == len(keys)
    saved = os.path.join(str(tmp_path), "epoch4.npz")
    t4.save_base(saved)

    t2 = mk(2)
    # pre-existing junk must be wiped by the merge=False re-import
    t2.hosts[0].update(np.array([999], np.uint64),
                       {f: v for f, v in _junk_fields().items()})
    assert t2.load_reshard([saved]) == len(keys)
    want = {}
    for s in range(4):
        ks, _ = t4.hosts[s].index.items()
        got = t4.hosts[s].fetch(np.sort(ks))
        for i, k in enumerate(np.sort(ks)):
            want[int(k)] = got["embed_w"][i].tobytes()
    have = {}
    for s in range(2):
        ks, _ = t2.hosts[s].index.items()
        owners = ks % np.uint64(2)
        assert (owners == s).all(), "row landed on a non-owner shard"
        got = t2.hosts[s].fetch(ks)
        for i, k in enumerate(ks):
            have[int(k)] = got["embed_w"][i].tobytes()
    assert 999 not in have, "merge=False re-import kept stale rows"
    assert have == want


def _junk_fields(mf_dim: int = 4) -> dict:
    from paddlebox_tpu.ps.table import FIELDS, TWO_D_FIELDS
    return {f: (np.full((1, mf_dim), 7.0, np.float32)
                if f in TWO_D_FIELDS else np.full(1, 7.0, np.float32))
            for f in FIELDS}
