"""Dataset extension points: pipe_command readers, slots_shuffle
(feature-importance eval), and the custom-parser plugin loader.

Reference behaviors covered: LoadIntoMemoryByCommand (data_feed.h:1674),
MultiSlotDataset::SlotsShuffle/GetRandomData (data_set.cc:1713-1881),
DLManager/CustomParser plugin parsers (data_feed.h:450,:698).
"""

import os
import textwrap

import numpy as np
import pytest

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
from paddlebox_tpu.data.dataset import QueueDataset, _slots_shuffle_columnar
from paddlebox_tpu.data.parser import get_parser, load_parser_plugin


def _desc(**kw) -> DataFeedDesc:
    slots = [SlotDef("label", "float", 1), SlotDef("a", "uint64"),
             SlotDef("b", "uint64"), SlotDef("d", "float", 2)]
    return DataFeedDesc(slots=slots, label_slot="label", batch_size=4, **kw)


def _write_slot_text(path, rows):
    # one line per record: label grp, a grp, b grp, dense grp(dim 2)
    with open(path, "w") as fh:
        for label, a_keys, b_keys, dense in rows:
            toks = ["1", str(label)]
            toks += [str(len(a_keys))] + [str(k) for k in a_keys]
            toks += [str(len(b_keys))] + [str(k) for k in b_keys]
            toks += ["2"] + [str(v) for v in dense]
            fh.write(" ".join(toks) + "\n")


ROWS = [(1.0, [11, 12], [21], [0.5, 1.5]),
        (0.0, [13], [22, 23], [2.5, 3.5]),
        (1.0, [14], [24], [4.5, 5.5]),
        (0.0, [15, 16, 17], [25], [6.5, 7.5])]


def test_pipe_command_transforms_input(tmp_path):
    # raw file is comma-separated; pipe_command rewrites it to slot_text
    raw = tmp_path / "raw.txt"
    _write_slot_text(str(raw), ROWS)
    csv = tmp_path / "data.csv"
    csv.write_text(raw.read_text().replace(" ", ","))

    ds = InMemoryDataset(_desc(pipe_command="tr ',' ' '"))
    ds.set_filelist([str(csv)])
    ds.load_into_memory()
    assert len(ds.records) == 4
    got = sorted(float(r.label) for r in ds.records)
    assert got == [0.0, 0.0, 1.0, 1.0]
    rec = next(r for r in ds.records if len(r.slot_keys(0)) == 3)
    assert list(rec.slot_keys(0)) == [15, 16, 17]


def test_pipe_command_failure_raises(tmp_path):
    f = tmp_path / "x.txt"
    _write_slot_text(str(f), ROWS)
    ds = InMemoryDataset(_desc(pipe_command="false"))
    ds.set_filelist([str(f)])
    with pytest.raises(RuntimeError, match="pipe_command"):
        ds.load_into_memory()


def test_pipe_command_queue_dataset(tmp_path):
    f = tmp_path / "x.txt"
    _write_slot_text(str(f), ROWS)
    ds = QueueDataset(_desc(pipe_command="cat"))
    ds.set_filelist([str(f)])
    ds.set_thread(1)
    batches = list(ds.batches())
    assert sum(int((b.show > 0).sum()) for b in batches) == 4


def _make_inmem(records_rows, columnar: bool) -> InMemoryDataset:
    ds = InMemoryDataset(_desc())
    parser = get_parser(ds.desc)
    lines = []
    for label, a_keys, b_keys, dense in records_rows:
        toks = ["1", str(label),
                str(len(a_keys)), *map(str, a_keys),
                str(len(b_keys)), *map(str, b_keys),
                "2", *map(str, dense)]
        lines.append(" ".join(toks))
    ds.records = [parser.parse(l) for l in lines]
    if columnar:
        ds.columnarize()
    return ds


@pytest.mark.parametrize("columnar", [False, True])
def test_slots_shuffle_preserves_marginals(columnar):
    ds = _make_inmem(ROWS, columnar)
    with pytest.raises(RuntimeError):
        ds.slots_shuffle(["a"])
    ds.set_fea_eval(100, True)
    if columnar:
        before_a = np.sort(ds.columnar.keys[ds.columnar.key_slot == 0])
        before_b_per_rec = [sorted(
            ds.columnar.keys[ds.columnar.offsets[i]:ds.columnar.offsets[i+1]]
            [ds.columnar.key_slot[ds.columnar.offsets[i]:
                                  ds.columnar.offsets[i+1]] == 1])
            for i in range(4)]
    else:
        before_a = np.sort(np.concatenate(
            [r.slot_keys(0) for r in ds.records]))
        before_b_per_rec = [sorted(r.slot_keys(1)) for r in ds.records]
    ds.slots_shuffle(["a"])
    if columnar:
        col = ds.columnar
        after_a = np.sort(col.keys[col.key_slot == 0])
        after_b_per_rec = [sorted(
            col.keys[col.offsets[i]:col.offsets[i+1]]
            [col.key_slot[col.offsets[i]:col.offsets[i+1]] == 1])
            for i in range(4)]
        # keys stay slot-grouped within each record
        for i in range(4):
            ks = col.key_slot[col.offsets[i]:col.offsets[i + 1]]
            assert (np.diff(ks) >= 0).all()
    else:
        after_a = np.sort(np.concatenate(
            [r.slot_keys(0) for r in ds.records]))
        after_b_per_rec = [sorted(r.slot_keys(1)) for r in ds.records]
    # shuffled slot: global multiset preserved
    np.testing.assert_array_equal(before_a, after_a)
    # untouched slot: per-record values preserved
    assert before_b_per_rec == after_b_per_rec


def test_slots_shuffle_capped_candidates():
    """record_candidate_size < pass size → donors come from a capped
    pool (reservoir semantics), not the whole pass."""
    ds = _make_inmem(ROWS * 16, True)   # 64 records
    ds.set_fea_eval(record_candidate_size=4)
    before_b = ds.columnar.keys[ds.columnar.key_slot == 1].copy()
    ds.slots_shuffle(["a"])
    col = ds.columnar
    # untouched slot preserved; shuffled slot values all come from the
    # original value set (marginal support preserved)
    np.testing.assert_array_equal(
        np.sort(col.keys[col.key_slot == 1]), np.sort(before_b))
    a_vals = set(col.keys[col.key_slot == 0].tolist())
    assert a_vals <= {11, 12, 13, 14, 15, 16, 17}


def test_slots_shuffle_columnar_matches_batching():
    ds = _make_inmem(ROWS * 8, True)
    ds.set_fea_eval()
    ds.slots_shuffle([0])
    batches = list(ds.batches())
    assert sum(int((b.show > 0).sum()) for b in batches) == 32


def test_merge_by_insid():
    from paddlebox_tpu.data.pv import merge_by_insid
    from paddlebox_tpu.data.record import SlotRecord

    def rec(ins_id, a_keys, b_keys, label=1.0):
        keys = np.array(a_keys + b_keys, np.uint64)
        offs = np.array([0, len(a_keys), len(a_keys) + len(b_keys)],
                        np.int32)
        return SlotRecord(keys=keys, slot_offsets=offs,
                          dense=np.array([label], np.float32),
                          label=label, ins_id=ins_id)

    recs = [rec("x", [1], [10]), rec("x", [2, 3], [20]),
            rec("y", [4], [40]), rec("z", [5], [50]), rec("z", [6], [60])]
    merged, dropped = merge_by_insid(recs, merge_size=2, num_slots=2)
    # group y has size 1 != merge_size → dropped
    assert dropped == 1
    assert sorted(m.ins_id for m in merged) == ["x", "z"]
    mx = next(m for m in merged if m.ins_id == "x")
    assert sorted(mx.slot_keys(0)) == [1, 2, 3]   # slot a concatenated
    assert sorted(mx.slot_keys(1)) == [10, 20]    # slot b concatenated
    # merge_size=0: keep all groups, singletons pass through
    merged0, dropped0 = merge_by_insid(recs, merge_size=0, num_slots=2)
    assert dropped0 == 0 and len(merged0) == 3


def test_dataset_merge_by_lineid(tmp_path):
    f = tmp_path / "x.txt"
    _write_slot_text(str(f), ROWS)
    ds = InMemoryDataset(_desc())
    ds.set_filelist([str(f)])
    ds.set_merge_by_lineid(0)  # ins_id empty for text loads → one group
    ds.load_into_memory()
    assert len(ds.records) == 1
    assert len(ds.records[0].slot_keys(0)) == 7  # all slot-a keys merged


def test_device_mem_used():
    from paddlebox_tpu.utils.monitor import device_mem_used, log_device_mem
    m = device_mem_used()
    assert set(m) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}
    out = log_device_mem("test")
    from paddlebox_tpu.utils import STATS
    assert STATS.get("hbm_test_bytes_in_use") == out["bytes_in_use"]


def test_parser_plugin_python_module(tmp_path):
    plug = tmp_path / "my_parser.py"
    plug.write_text(textwrap.dedent("""
        from paddlebox_tpu.data.parser import SlotTextParser

        class UpperParser(SlotTextParser):
            pass

        PARSERS = {"my_custom": UpperParser}
    """))
    names = load_parser_plugin(str(plug))
    assert "my_custom" in names
    d = _desc()
    d.parser = "my_custom"
    assert get_parser(d).__class__.__name__ == "UpperParser"


def test_parser_plugin_so(tmp_path):
    # the framework's own native lib doubles as a plugin .so — it exposes
    # the documented bulk columnar ABI under `slot_text_parse`
    from paddlebox_tpu.native import _SO, load_native
    if load_native() is None:
        pytest.skip("no native toolchain")
    names = load_parser_plugin(_SO + ":slot_text_parse", name="plug_native")
    assert names == ["plug_native"]
    f = tmp_path / "x.txt"
    _write_slot_text(str(f), ROWS)
    d = _desc()
    d.parser = "plug_native"
    out = get_parser(d).parse_file_columnar(str(f))
    assert out is not None and len(out["label"]) == 4
    np.testing.assert_allclose(np.sort(out["label"]), [0, 0, 1, 1])
