"""Multi-mf × sharded: per-slot embedding dims on the 8-device CPU mesh
(feature_value.h:42-185 — the dy-mf accessor as the sharded PS layout;
ps_gpu_wrapper.cc multi-mf BuildGPUTask)."""

import numpy as np
import jax
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import MultiMfEmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.multi_mf_sharded import MultiMfShardedTable
from paddlebox_tpu.train import MultiMfTrainer
from paddlebox_tpu.train.multi_mf_sharded import MultiMfShardedTrainer

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N
    return make_mesh(N)


def _dims():
    return [2] * 10 + [4] * 10 + [8] * 6   # three dim classes


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_mmfs")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=1500,
                                 vocab_per_slot=40, seed=19)


def _ds(files, bs=32):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def _cfg():
    return SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                           learning_rate=0.05, mf_learning_rate=0.05)


def test_mmf_sharded_routing_and_slot_field(mesh, criteo_files):
    """Keys route to their slot's class table and, inside it, to their
    key%N owner shard; serve_slot carries GLOBAL slot ids."""
    ds, desc = _ds(criteo_files)
    table = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                cfg=_cfg(), req_bucket_min=64,
                                serve_bucket_min=64)
    group = []
    for b in ds.batches():
        group.append(b)
        if len(group) == N:
            break
    plans = table.prepare_global(group)
    assert len(plans) == 3
    dims = np.asarray(_dims())
    for d, b in enumerate(group):
        segs = b.segments[:b.num_keys]
        slots = segs % b.num_slots
        for k, sl in zip(b.keys[:b.num_keys], slots):
            c = table.class_of_slot[sl]
            s = int(k) % N
            assert table.tables[c].indexes[s].lookup(
                np.array([k], np.uint64))[0] >= 0
    # serve_slot values are valid GLOBAL slot ids of the right class
    for c, p in enumerate(plans):
        valid = p.serve_slot[p.serve_valid > 0].astype(int)
        assert np.isin(valid, table.class_slots[c]).all()


_LEGACY_JAX = tuple(int(v) for v in
                    jax.__version__.split(".")[:2]) < (0, 6)


@pytest.mark.skipif(_LEGACY_JAX, reason=(
    "single-chip parity drifts on the legacy jax.experimental.shard_map "
    "line (pre-existing seed failure; passes on jax >= 0.6)"))
def test_mmf_sharded_e2e_learns_and_matches_single_chip(
        mesh, criteo_files):
    """8-dev mesh multi-mf training with 3 dim classes learns the same
    planted signal as the single-chip MultiMfTrainer on the same data,
    and per-key pulled values keep per-slot widths."""
    ds, desc = _ds(criteo_files)
    with flags_scope(log_period_steps=10000):
        sh_table = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                       cfg=_cfg(), req_bucket_min=256,
                                       serve_bucket_min=256)
        tr_m = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), sh_table,
                                     desc, mesh, tx=optax.adam(1e-2),
                                     seed=3)
        sc_table = MultiMfEmbeddingTable(_dims(), capacity=1 << 12,
                                         cfg=_cfg(),
                                         unique_bucket_min=1024)
        tr_s = MultiMfTrainer(CtrDnn(hidden=(16, 8)), sc_table, desc,
                              tx=optax.adam(1e-2), seed=3)
    rm = rs = None
    for _ in range(4):
        rs = tr_s.train_pass(ds)
    # the mesh takes N-batch global steps (12/pass vs 94/pass single
    # chip) — give it more passes to reach the same optimizer-step count
    for _ in range(8):
        rm = tr_m.train_pass(ds)
    assert np.isfinite(rm["last_loss"])
    # both learn the planted signal; mesh quality tracks single-chip
    assert rs["auc"] > 0.60, rs["auc"]
    assert rm["auc"] > 0.60, rm["auc"]
    # one-sided: the mesh must not trail the single chip by much (it may
    # LEAD it — 8 passes of N-batch global steps see more data-epochs)
    assert rm["auc"] > rs["auc"] - 0.08, (rm["auc"], rs["auc"])
    # every class table holds features on the mesh
    assert all(t.feature_count() > 0 for t in sh_table.tables)
    # per-slot width contract on the mesh pull
    col = ds.columnar
    keys = col.keys[:100].astype(np.uint64)
    slots = col.key_slot[:100]
    vals = sh_table.pull(keys, slots)
    assert vals.shape == (100, 3 + 8)
    dims = np.asarray(_dims())
    for i in range(100):
        np.testing.assert_allclose(vals[i, 3 + dims[slots[i]]:], 0.0)
    assert (vals[:, 0] > 0).all()  # show counters accumulated


@pytest.mark.slow  # seed-broken (no jax.shard_map) until the
# jax_compat shim; recovered, but heavy on the virtual-CPU mesh —
# out of the tier-1 wall budget, runs in the slow tier
def test_mmf_sharded_save_load_roundtrip(mesh, criteo_files, tmp_path):
    ds, desc = _ds(criteo_files)
    with flags_scope(log_period_steps=10000):
        table = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                    cfg=_cfg(), req_bucket_min=256,
                                    serve_bucket_min=256)
        tr = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), table, desc,
                                   mesh, tx=optax.adam(1e-2))
        tr.train_pass(ds)
    path = str(tmp_path / "mmf_sharded")
    n = table.save_base(path)
    assert n == table.feature_count() > 0
    t2 = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                             cfg=_cfg())
    assert t2.load(path) == n
    col = ds.columnar
    keys = col.keys[:50].astype(np.uint64)
    slots = col.key_slot[:50]
    np.testing.assert_allclose(t2.pull(keys, slots),
                               table.pull(keys, slots), rtol=1e-6)


def _write_offset_pass_mmf(tmp_path, pass_id, vocab=40, rows=600):
    """Criteo files with per-pass disjoint value ranges (fresh features
    each pass — the day-k workload for the tiered window tests)."""
    import os
    rng = np.random.default_rng(300 + pass_id)
    d = tmp_path / f"mmfoff{pass_id}"
    os.makedirs(str(d), exist_ok=True)
    path = str(d / "part.txt")
    base = pass_id * vocab
    with open(path, "w") as fh:
        for _ in range(rows):
            dense = rng.integers(0, 100, size=13)
            cats = base + rng.integers(0, vocab, size=26)
            label = int(rng.random() < 0.5)
            fh.write(f"{label}\t" + "\t".join(str(int(v)) for v in dense)
                     + "\t" + "\t".join(format(int(c), "x") for c in cats)
                     + "\n")
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist([path])
    ds.load_into_memory()
    return ds, desc


@pytest.mark.slow  # same budget rationale; the tiered fence/epilogue
# surface stays covered in tier-1 by test_mmf_tiered_matches_untired
# and test_mmf_tiered_overlap_stage_and_delta
def test_mmf_tiered_full_cross_product(mesh, tmp_path):
    """Per-slot dims x beyond-HBM tiering x mesh sharding: 3 dim classes,
    3 disjoint day-passes, per-class capacity_per_shard far below the
    union — the host tiers carry the full model across pass windows, and
    save/load round-trips the whole thing."""
    from paddlebox_tpu.ps import BoxPSHelper
    from paddlebox_tpu.ps.multi_mf_sharded import MultiMfTieredShardedTable
    built = [_write_offset_pass_mmf(tmp_path, p) for p in range(3)]
    desc = built[0][1]
    table = MultiMfTieredShardedTable(
        N, _dims(), capacity_per_shard=128, cfg=_cfg(),
        req_bucket_min=64, serve_bucket_min=64)
    with flags_scope(log_period_steps=10000):
        tr = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), table, desc,
                                   mesh, tx=optax.adam(1e-2))
    helper = BoxPSHelper(table, trainer=tr)
    for ds, _ in built:
        helper.begin_pass(ds)
        r = tr.train_pass(ds)
        assert np.isfinite(r["last_loss"])
        helper.end_pass(ds)
    total = table.feature_count()
    # union exceeds any single class's HBM window by construction:
    # 3 passes x 26 slots x 40 vocab of mostly-disjoint keys
    assert total > 2000, total
    for t in table.tables:
        for s in range(N):
            assert len(t.indexes[s]) <= t.capacity
    # host-tier pull serves per-slot widths for keys from EVERY pass
    ds0 = built[0][0]
    col = ds0.columnar
    keys = col.keys[:60].astype(np.uint64)
    slots = col.key_slot[:60]
    vals = table.pull(keys, slots)
    dims = np.asarray(_dims())
    assert (vals[:, 0] > 0).all()  # show counters from pass 0 persisted
    for i in range(60):
        np.testing.assert_allclose(vals[i, 3 + dims[slots[i]]:], 0.0)
    # full save/load round-trip through the tiers
    path = str(tmp_path / "mmf_tiered")
    n = table.save_base(path)
    assert n == total
    t2 = MultiMfTieredShardedTable(
        N, _dims(), capacity_per_shard=128, cfg=_cfg())
    assert t2.load(path) == n
    np.testing.assert_allclose(t2.pull(keys, slots),
                               table.pull(keys, slots), rtol=1e-6)


def test_mmf_tiered_overlap_stage_and_delta(mesh, tmp_path):
    """Overlapped staging × multi-mf: stage_pass during an OPEN pass
    fans out per dim class (keys route by their slot's class), and the
    next begin_pass consumes a pure per-class delta when working sets
    repeat — the round-4 persistent-window contract composed with the
    dim-class routing."""
    from paddlebox_tpu.ps import BoxPSHelper
    from paddlebox_tpu.ps.multi_mf_sharded import MultiMfTieredShardedTable
    ds, desc = _ds(generate_criteo_files(
        str(tmp_path / "ovl"), num_files=1, rows_per_file=800,
        vocab_per_slot=40, seed=77))
    table = MultiMfTieredShardedTable(
        N, _dims(), capacity_per_shard=2048, cfg=_cfg(),
        req_bucket_min=64, serve_bucket_min=64)
    with flags_scope(log_period_steps=10000):
        tr = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), table, desc,
                                   mesh, tx=optax.adam(1e-2))
    helper = BoxPSHelper(table, trainer=tr)
    helper.begin_pass(ds)
    assert sum(t.last_pass_stats["staged"] for t in table.tables) > 0
    helper.stage_pass(ds)  # overlap: stage the SAME keys mid-pass
    r1 = tr.train_pass(ds)
    helper.end_pass(ds)
    helper.begin_pass(ds)  # consumes the overlapped per-class stages
    for t in table.tables:
        st = t.last_pass_stats
        assert st["staged"] == 0, st       # pure delta: all resident
        assert st["resident"] > 0, st
    r2 = tr.train_pass(ds)
    helper.end_pass(ds)
    assert np.isfinite(r1["last_loss"]) and np.isfinite(r2["last_loss"])


def test_mmf_tiered_matches_untired(mesh, tmp_path):
    """Tiering stays TRANSPARENT under multi-mf: when everything fits,
    the tiered cross-product equals the plain multi-mf sharded table
    trained straight through."""
    from paddlebox_tpu.ps import BoxPSHelper
    from paddlebox_tpu.ps.multi_mf_sharded import MultiMfTieredShardedTable
    ds, desc = _ds(generate_criteo_files(
        str(tmp_path / "flat"), num_files=1, rows_per_file=800,
        vocab_per_slot=30, seed=23))
    with flags_scope(log_period_steps=10000):
        plain = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                    cfg=_cfg(), req_bucket_min=128,
                                    serve_bucket_min=128)
        tr_a = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), plain, desc,
                                     mesh, tx=optax.adam(1e-2))
        tiered = MultiMfTieredShardedTable(
            N, _dims(), capacity_per_shard=2048, cfg=_cfg(),
            req_bucket_min=128, serve_bucket_min=128)
        tr_b = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), tiered, desc,
                                     mesh, tx=optax.adam(1e-2))
    helper = BoxPSHelper(tiered, trainer=tr_b)
    ra = rb = None
    for _ in range(2):
        ra = tr_a.train_pass(ds)
        helper.begin_pass(ds)
        rb = tr_b.train_pass(ds)
        helper.end_pass(ds)
    assert np.isclose(rb["auc"], ra["auc"], atol=1e-6), (rb["auc"], ra["auc"])
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-7)
    col = ds.columnar
    keys = col.keys[:80].astype(np.uint64)
    slots = col.key_slot[:80]
    np.testing.assert_allclose(tiered.pull(keys, slots),
                               plain.pull(keys, slots),
                               rtol=1e-5, atol=1e-7)
