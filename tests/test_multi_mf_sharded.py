"""Multi-mf × sharded: per-slot embedding dims on the 8-device CPU mesh
(feature_value.h:42-185 — the dy-mf accessor as the sharded PS layout;
ps_gpu_wrapper.cc multi-mf BuildGPUTask)."""

import numpy as np
import jax
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import MultiMfEmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.multi_mf_sharded import MultiMfShardedTable
from paddlebox_tpu.train import MultiMfTrainer
from paddlebox_tpu.train.multi_mf_sharded import MultiMfShardedTrainer

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N
    return make_mesh(N)


def _dims():
    return [2] * 10 + [4] * 10 + [8] * 6   # three dim classes


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_mmfs")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=1500,
                                 vocab_per_slot=40, seed=19)


def _ds(files, bs=32):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def _cfg():
    return SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                           learning_rate=0.05, mf_learning_rate=0.05)


def test_mmf_sharded_routing_and_slot_field(mesh, criteo_files):
    """Keys route to their slot's class table and, inside it, to their
    key%N owner shard; serve_slot carries GLOBAL slot ids."""
    ds, desc = _ds(criteo_files)
    table = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                cfg=_cfg(), req_bucket_min=64,
                                serve_bucket_min=64)
    group = []
    for b in ds.batches():
        group.append(b)
        if len(group) == N:
            break
    plans = table.prepare_global(group)
    assert len(plans) == 3
    dims = np.asarray(_dims())
    for d, b in enumerate(group):
        segs = b.segments[:b.num_keys]
        slots = segs % b.num_slots
        for k, sl in zip(b.keys[:b.num_keys], slots):
            c = table.class_of_slot[sl]
            s = int(k) % N
            assert table.tables[c].indexes[s].lookup(
                np.array([k], np.uint64))[0] >= 0
    # serve_slot values are valid GLOBAL slot ids of the right class
    for c, p in enumerate(plans):
        valid = p.serve_slot[p.serve_valid > 0].astype(int)
        assert np.isin(valid, table.class_slots[c]).all()


def test_mmf_sharded_e2e_learns_and_matches_single_chip(
        mesh, criteo_files):
    """8-dev mesh multi-mf training with 3 dim classes learns the same
    planted signal as the single-chip MultiMfTrainer on the same data,
    and per-key pulled values keep per-slot widths."""
    ds, desc = _ds(criteo_files)
    with flags_scope(log_period_steps=10000):
        sh_table = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                       cfg=_cfg(), req_bucket_min=256,
                                       serve_bucket_min=256)
        tr_m = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), sh_table,
                                     desc, mesh, tx=optax.adam(1e-2),
                                     seed=3)
        sc_table = MultiMfEmbeddingTable(_dims(), capacity=1 << 12,
                                         cfg=_cfg(),
                                         unique_bucket_min=1024)
        tr_s = MultiMfTrainer(CtrDnn(hidden=(16, 8)), sc_table, desc,
                              tx=optax.adam(1e-2), seed=3)
    rm = rs = None
    for _ in range(4):
        rs = tr_s.train_pass(ds)
    # the mesh takes N-batch global steps (12/pass vs 94/pass single
    # chip) — give it more passes to reach the same optimizer-step count
    for _ in range(8):
        rm = tr_m.train_pass(ds)
    assert np.isfinite(rm["last_loss"])
    # both learn the planted signal; mesh quality tracks single-chip
    assert rs["auc"] > 0.60, rs["auc"]
    assert rm["auc"] > 0.60, rm["auc"]
    assert abs(rm["auc"] - rs["auc"]) < 0.08, (rm["auc"], rs["auc"])
    # every class table holds features on the mesh
    assert all(t.feature_count() > 0 for t in sh_table.tables)
    # per-slot width contract on the mesh pull
    col = ds.columnar
    keys = col.keys[:100].astype(np.uint64)
    slots = col.key_slot[:100]
    vals = sh_table.pull(keys, slots)
    assert vals.shape == (100, 3 + 8)
    dims = np.asarray(_dims())
    for i in range(100):
        np.testing.assert_allclose(vals[i, 3 + dims[slots[i]]:], 0.0)
    assert (vals[:, 0] > 0).all()  # show counters accumulated


def test_mmf_sharded_save_load_roundtrip(mesh, criteo_files, tmp_path):
    ds, desc = _ds(criteo_files)
    with flags_scope(log_period_steps=10000):
        table = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                                    cfg=_cfg(), req_bucket_min=256,
                                    serve_bucket_min=256)
        tr = MultiMfShardedTrainer(CtrDnn(hidden=(16, 8)), table, desc,
                                   mesh, tx=optax.adam(1e-2))
        tr.train_pass(ds)
    path = str(tmp_path / "mmf_sharded")
    n = table.save_base(path)
    assert n == table.feature_count() > 0
    t2 = MultiMfShardedTable(N, _dims(), capacity_per_shard=2048,
                             cfg=_cfg())
    assert t2.load(path) == n
    col = ds.columnar
    keys = col.keys[:50].astype(np.uint64)
    slots = col.key_slot[:50]
    np.testing.assert_allclose(t2.pull(keys, slots),
                               table.pull(keys, slots), rtol=1e-6)
