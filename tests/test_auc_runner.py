"""AucRunner slot-replacement eval (box_wrapper.h:908-1009 semantics)."""

import numpy as np
import optax
import pytest

from paddlebox_tpu.auc_runner import AucRunner, RecordCandidateList
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer


def make_records(n, num_slots=4, seed=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        keys = rng.integers(0, 50, size=num_slots).astype(np.uint64)
        keys += np.arange(num_slots, dtype=np.uint64) * 100
        recs.append(SlotRecord(
            keys=keys, slot_offsets=np.arange(num_slots + 1, dtype=np.int32),
            dense=np.zeros(2, np.float32), label=float(i % 2)))
    return recs


def test_candidate_reservoir():
    rng = np.random.default_rng(0)
    cl = RecordCandidateList(capacity=10, slots=[0, 2])
    cl.add_all(make_records(100), rng)
    assert cl.size == 10
    v = cl.sample(0, rng)
    assert v.dtype == np.uint64 and 0 <= int(v[0]) < 100


def test_record_replace_and_back():
    recs = make_records(20, seed=1)
    runner = AucRunner(slots_to_replace=[1], pool_size=50, seed=2)
    runner.init_pass(recs)
    replaced = runner.record_replace(recs)
    assert runner.phase == 0
    # untouched slots identical; replaced slot drawn from other records
    diff = 0
    for a, b in zip(recs, replaced):
        np.testing.assert_array_equal(a.slot_keys(0), b.slot_keys(0))
        np.testing.assert_array_equal(a.slot_keys(2), b.slot_keys(2))
        np.testing.assert_array_equal(a.slot_keys(3), b.slot_keys(3))
        assert 100 <= int(b.slot_keys(1)[0]) < 200  # still slot-1 vocab
        diff += int(a.slot_keys(1)[0] != b.slot_keys(1)[0])
    assert diff > 5  # replacement actually shuffled most records
    back = runner.record_replace_back()
    assert back is not replaced and back[0] is recs[0]
    assert runner.phase == 1
    with pytest.raises(RuntimeError):
        runner.record_replace_back()


def _informative_setup(batch_size):
    """Slot 0 determines the label; slot 3 is pure noise — shared by the
    single-chip and mesh slot-importance tests."""
    from paddlebox_tpu.data import SlotDef
    rng = np.random.default_rng(5)
    n, num_slots = 4000, 4
    recs = []
    for i in range(n):
        k0 = int(rng.integers(0, 20))
        keys = np.array(
            [k0,
             100 + int(rng.integers(0, 20)),
             200 + int(rng.integers(0, 20)),
             300 + int(rng.integers(0, 20))], np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=np.arange(num_slots + 1, dtype=np.int32),
            dense=np.zeros(1, np.float32), label=float(k0 < 10),
            clk=float(k0 < 10)))
    desc = DataFeedDesc(
        slots=[SlotDef(name=f"s{i}") for i in range(num_slots)]
        + [SlotDef(name="d0", type="float", dim=1)],
        batch_size=batch_size)
    desc.key_bucket_min = 2048
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.1, mf_learning_rate=0.1)
    return recs, desc, cfg


def _assert_slot_importance(tr, recs, desc):
    """Train 3 passes, then slot-replacement importance: destroying the
    label-defining slot collapses AUC; the noise slot does not."""
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.records = recs
    for _ in range(3):
        tr.train_pass(ds)

    def eval_fn(records):
        ds2 = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds2.records = records
        return tr.eval_pass(ds2)["auc"]

    runner = AucRunner(slots_to_replace=[0, 3], pool_size=2000, seed=3)
    runner.init_pass(recs)
    imp = runner.slot_importance(eval_fn, recs)
    assert imp[0] > 0.2, imp        # label-defining slot: big AUC drop
    assert abs(imp[3]) < 0.05, imp  # noise slot: no real drop


def test_slot_importance_detects_informative_slot():
    """Slot 0 determines the label; slot 3 is pure noise. Destroying
    slot 0 must collapse AUC; destroying slot 3 must not."""
    recs, desc, cfg = _informative_setup(batch_size=256)
    table = EmbeddingTable(mf_dim=8, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=2048)
    tr = Trainer(CtrDnn(hidden=(32, 32)), table, desc, tx=optax.adam(5e-3))
    _assert_slot_importance(tr, recs, desc)


@pytest.mark.slow  # seed-broken (no jax.shard_map) until the
# jax_compat shim; recovered, but the 8-dev virtual-CPU mesh run is
# heavy (~20 s) — out of the tier-1 wall budget, runs in the slow tier
def test_slot_importance_on_mesh_trainer():
    """AucRunner composes with the MESH trainer unchanged (it is
    dataset-level — the reference embeds the same machinery in
    BoxWrapper, box_wrapper.h:908-1009, available to every worker
    mode): slot importance via ShardedTrainer.eval_pass on the
    8-device mesh finds the same informative slot."""
    import jax
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer
    assert len(jax.devices()) >= 8
    recs, desc, cfg = _informative_setup(batch_size=64)
    table = ShardedEmbeddingTable(8, mf_dim=8, capacity_per_shard=1 << 10,
                                  cfg=cfg, req_bucket_min=128,
                                  serve_bucket_min=128)
    tr = ShardedTrainer(CtrDnn(hidden=(32, 32)), table, desc, make_mesh(8),
                        tx=optax.adam(5e-3))
    _assert_slot_importance(tr, recs, desc)
