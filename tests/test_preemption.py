"""Preemption survival kit (ISSUE 3 acceptance surface): graceful
shutdown (stop flag, SIGTERM), emergency checkpoints with mid-pass
resume cursors, cursor-aware ``run_pass`` recovery, checkpoint
crash-consistency hardening (meta sidecar, half-deleted dirs), and
multihost-consistent recovery (restore-step consensus + shared
quarantine)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.obs import MemorySink, get_hub, reset_hub
from paddlebox_tpu.resilience import preemption
from paddlebox_tpu.resilience.consensus import (ConsensusTimeout,
                                                DirConsensusStore,
                                                RestoreConsensus,
                                                sync_shared_quarantine)
from paddlebox_tpu.resilience.faults import FaultPlan, installed
from paddlebox_tpu.resilience.preemption import PreemptedError
from paddlebox_tpu.train.checkpoint import (CheckpointCorruptError,
                                            CheckpointManager,
                                            state_digest)
from paddlebox_tpu.train.trainer import NanInfError


@pytest.fixture(autouse=True)
def clean_preempt_state():
    preemption.clear_stop()
    yield
    preemption.clear_stop()
    preemption.uninstall_signal_handlers()


@pytest.fixture()
def fresh_hub():
    hub = reset_hub()
    yield hub
    reset_hub()


# ---- stop flag / marker API -------------------------------------------
def test_request_stop_roundtrip(fresh_hub):
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    assert not preemption.stop_requested()
    preemption.request_stop("unit-test")
    assert preemption.stop_requested()
    assert preemption.stop_reason() == "unit-test"
    preemption.request_stop("second")  # first reason wins
    assert preemption.stop_reason() == "unit-test"
    preemption.clear_stop()
    assert not preemption.stop_requested()
    evs = [e for e in sink.events if e["event"] == "preempt_requested"]
    assert len(evs) == 1 and evs[0]["reason"] == "unit-test"
    assert fresh_hub.counter("pbox_preempt_requests_total").value() == 1


def test_injected_fault_becomes_stop_request():
    plan = FaultPlan.parse("preempt.signal:fail:nth=3")
    with installed(plan):
        assert not preemption.stop_requested()   # call 1
        assert not preemption.stop_requested()   # call 2
        assert preemption.stop_requested()       # call 3: fault -> stop
    assert "injected" in preemption.stop_reason()
    assert plan.stats()["preempt.signal:fail"]["fired"] == 1


def test_signal_handler_is_lock_free(fresh_hub):
    """The handler runs on the main thread between bytecodes and may
    interrupt code HOLDING the telemetry/logging/module locks — it must
    not acquire any itself (a deadlock there burns the whole grace
    window). The real work happens at the next poll."""
    import paddlebox_tpu.resilience.preemption as pre
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    with fresh_hub._lock:          # simulate: interrupted mid-emit
        pre._handler(signal.SIGTERM.value, None)   # must not block
        assert pre._SIG_PENDING == "signal:SIGTERM"
        assert not [e for e in sink.events
                    if e["event"] == "preempt_requested"]
    assert preemption.stop_pending()               # drained at poll
    assert preemption.stop_reason() == "signal:SIGTERM"
    assert [e for e in sink.events if e["event"] == "preempt_requested"]


def test_resume_marker_roundtrip(tmp_path):
    root = str(tmp_path / "ckpt")
    assert preemption.read_resume_marker(root) is None
    preemption.write_resume_marker(root, step=42, batch_index=7,
                                   reason="signal:SIGTERM")
    m = preemption.read_resume_marker(root)
    assert m["step"] == 42 and m["batch_index"] == 7
    assert m["exit_code"] == preemption.EXIT_RESUME == 75
    assert preemption.clear_resume_marker(root)
    assert preemption.read_resume_marker(root) is None
    assert not preemption.clear_resume_marker(root)  # already gone


# ---- batch skipping (cursor substrate) --------------------------------
def _mini_files(tmp_path, n=2, rows=80, seed=11):
    return generate_criteo_files(str(tmp_path / "data"), num_files=n,
                                 rows_per_file=rows, vocab_per_slot=40,
                                 seed=seed)


def _batches_equal(a, b):
    return (np.array_equal(a.keys, b.keys)
            and np.array_equal(a.label, b.label)
            and np.array_equal(a.dense, b.dense))


@pytest.mark.parametrize("native", [False, True])
def test_start_batch_skips_exact_prefix(tmp_path, native):
    files = _mini_files(tmp_path)
    desc = DataFeedDesc.criteo(batch_size=16)
    with flags_scope(native_parse=native):
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()
        if not native:
            assert ds.columnar is None  # exercise the record path
        full = list(ds.batches())
        tail = list(ds.batches(start_batch=3))
    assert len(tail) == len(full) - 3
    assert all(_batches_equal(x, y) for x, y in zip(full[3:], tail))


def test_threaded_record_load_disables_cursor_resume(tmp_path):
    """Multi-thread per-line loads have timing-dependent record order —
    a cursor over them would splice two different streams, so resume
    support must reflect load determinism."""
    files = _mini_files(tmp_path)
    desc = DataFeedDesc.criteo(batch_size=16)

    def load(native, threads):
        with flags_scope(native_parse=native, read_thread_num=threads):
            ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
            ds.set_filelist(files)
            ds.load_into_memory()
            return ds

    assert load(native=True, threads=8).supports_cursor_resume
    assert load(native=False, threads=1).supports_cursor_resume
    assert not load(native=False, threads=8).supports_cursor_resume


def test_queue_dataset_refuses_cursor_resume(tmp_path):
    files = _mini_files(tmp_path)
    desc = DataFeedDesc.criteo(batch_size=16)
    ds = DatasetFactory().create_dataset("QueueDataset", desc)
    ds.set_filelist(files)
    assert not ds.supports_cursor_resume
    with pytest.raises(ValueError, match="deterministic"):
        next(ds.batches(start_batch=1))


def test_filelist_fingerprint_is_order_sensitive(tmp_path):
    files = _mini_files(tmp_path)
    desc = DataFeedDesc.criteo(batch_size=16)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    fp = ds.filelist_fingerprint()
    ds.set_filelist(list(reversed(files)))
    assert ds.filelist_fingerprint() != fp
    ds.set_filelist(files)
    assert ds.filelist_fingerprint() == fp


# ---- trainer fixtures --------------------------------------------------
@pytest.fixture()
def trainer_setup(tmp_path):
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig

    files = generate_criteo_files(str(tmp_path / "data"), num_files=2,
                                  rows_per_file=160, vocab_per_slot=30,
                                  seed=3)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 2048
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)

    def mk():
        from paddlebox_tpu.train import Trainer
        t = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=2048)
        return Trainer(CtrDnn(hidden=(8,)), t, desc, tx=optax.adam(1e-2),
                       seed=0)

    def mkds(filelist=None):
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(filelist or files)
        ds.load_into_memory()
        return ds

    return files, mk, mkds, str(tmp_path / "ckpt")


# ---- preemption e2e ----------------------------------------------------
@pytest.mark.chaos
def test_preempt_writes_emergency_ckpt_and_is_not_retried(trainer_setup,
                                                          fresh_hub):
    files, mk, mkds, root = trainer_setup
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root)
    plan = FaultPlan.parse("preempt.signal:fail:nth=4")
    with installed(plan):
        with pytest.raises(PreemptedError) as ei:
            # max_retries high on purpose: a graceful stop must NOT be
            # treated as a recoverable pass failure
            tr.run_pass(ds, checkpoint=cm, max_retries=5)
    assert ei.value.checkpointed and ei.value.batch_index == 4
    cur = cm.load_cursor()
    assert cur is not None
    assert cur["batch_index"] == 4
    assert cur["global_step"] == tr.global_step == 4
    assert cur["fingerprint"] == ds.filelist_fingerprint()
    marker = preemption.read_resume_marker(root)
    assert marker and marker["exit_code"] == preemption.EXIT_RESUME
    names = [e["event"] for e in sink.events]
    assert "preempt_requested" in names
    assert "emergency_checkpoint" in names
    assert "pass_retry" not in names  # never retried
    assert fresh_hub.counter("pbox_inpass_checkpoints_total").value(
        reason="preempt") == 1


@pytest.mark.chaos
def test_resume_from_cursor_matches_uninterrupted_run(trainer_setup,
                                                      fresh_hub):
    """THE acceptance criterion: preempt mid-pass -> restart -> resume
    from the cursor replays ONLY the remaining batches, and the final
    sparse + dense state is byte-identical to an uninterrupted run."""
    files, mk, mkds, root = trainer_setup
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    ds = mkds()

    baseline = mk()
    out = baseline.train_pass(ds)
    want_digest = state_digest(baseline)
    total = int(out["batches"])

    preemption.clear_stop()
    with flags_scope(ckpt_every_batches=3):
        tr = mk()
        cm = CheckpointManager(root)
        plan = FaultPlan.parse("preempt.signal:fail:nth=5")
        with installed(plan):
            with pytest.raises(PreemptedError):
                tr.run_pass(ds, checkpoint=cm)

        # "restarted process": fresh trainer + manager + dataset
        preemption.clear_stop()
        tr2 = mk()
        cm2 = CheckpointManager(root)
        restored = cm2.restore(tr2)
        assert restored == 5
        ds2 = mkds()
        out2 = tr2.run_pass(ds2, checkpoint=cm2)
    assert int(out2["batches"]) == total - 5  # prefix skipped, not replayed
    assert tr2.global_step == baseline.global_step
    assert state_digest(tr2) == want_digest
    assert preemption.read_resume_marker(root) is None  # consumed
    assert any(e["event"] == "cursor_resume" for e in sink.events)
    # the resumed pass ended cleanly: newest checkpoint is pass-boundary
    assert cm2.load_cursor() is None


@pytest.mark.chaos
def test_periodic_inpass_ckpt_bounds_replay_after_crash(trainer_setup):
    """A HARD kill (no graceful window) between periodic cursor saves
    replays only the tail since the last one."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    baseline = mk()
    out = baseline.train_pass(ds)
    want_digest = state_digest(baseline)
    total = int(out["batches"])

    with flags_scope(ckpt_every_batches=2):
        tr = mk()
        cm = CheckpointManager(root)
        # stop after batch 7: periodic cursor saves exist at 2/4/6 plus
        # the emergency save at 7
        try:
            with installed(FaultPlan.parse("preempt.signal:fail:nth=7")):
                tr.run_pass(ds, checkpoint=cm)
        except PreemptedError:
            pass
        # simulate the kill arriving before the emergency save finished:
        # restart from the PERIODIC checkpoint instead
        preemption.clear_stop()
        tr2 = mk()
        cm2 = CheckpointManager(root)
        steps = cm2.steps()
        periodic = steps[-2]  # last periodic save before the emergency
        assert cm2.restore(tr2, step=periodic) == periodic
        cur = cm2.load_cursor(periodic)
        assert cur is not None and cur["batch_index"] == periodic
        ds2 = mkds()
        out2 = tr2.train_pass(ds2, start_cursor=cur)
    assert int(out2["batches"]) == total - cur["batch_index"]
    assert state_digest(tr2) == want_digest


@pytest.mark.chaos
def test_run_pass_retry_resumes_from_cursor(trainer_setup):
    """A recoverable mid-pass failure with in-pass checkpoints rolls
    back to the cursor and replays the tail, not the whole pass."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    baseline = mk()
    out = baseline.train_pass(ds)
    want_digest = state_digest(baseline)

    with flags_scope(ckpt_every_batches=3):
        tr = mk()
        cm = CheckpointManager(root)
        # second attempt only: the first attempt trains 10 batches with
        # periodic saves, then the injected transient kills attempt 1 at
        # its very end via the trainer.pass seam of attempt 2's entry...
        # simpler: fail the FIRST attempt entry after priming a cursor
        # checkpoint by preempting a primer run
        plan = FaultPlan.parse("preempt.signal:fail:nth=6")
        with installed(plan):
            with pytest.raises(PreemptedError):
                tr.run_pass(ds, checkpoint=cm)
        preemption.clear_stop()
        # now a transient failure on the next attempt: run_pass restores
        # the emergency checkpoint and adopts its cursor
        tr2 = mk()
        cm2 = CheckpointManager(root)
        assert cm2.restore(tr2) == 6
        ds2 = mkds()
        plan2 = FaultPlan.parse("trainer.pass:fail:nth=1")
        with installed(plan2):
            out2 = tr2.run_pass(ds2, checkpoint=cm2, max_retries=1)
    assert int(out2["batches"]) == int(out["batches"]) - 6
    assert state_digest(tr2) == want_digest


@pytest.mark.chaos
def test_cursor_mismatch_rolls_back_to_pass_boundary(trainer_setup):
    """A cursor that does not match the dataset (different file list)
    must NOT be resumed into — the trainer rolls back to the latest
    pass-boundary checkpoint and replays the full pass."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.run_pass(ds, checkpoint=cm)
    cm.save(tr)                      # pass-boundary checkpoint
    boundary = tr.global_step
    plan = FaultPlan.parse("preempt.signal:fail:nth=3")
    with installed(plan):
        with pytest.raises(PreemptedError):
            tr.run_pass(ds, checkpoint=cm)   # mid-pass ckpt @ boundary+3
    preemption.clear_stop()

    tr2 = mk()
    cm2 = CheckpointManager(root, keep=10)
    assert cm2.restore(tr2) == boundary + 3
    other = mkds([files[0]])         # DIFFERENT file list
    out = tr2.run_pass(other, checkpoint=cm2)
    # rolled back to the boundary, then trained other's full pass
    assert tr2.global_step == boundary + int(out["batches"])
    assert int(out["batches"]) == 5  # 160 rows / 32


@pytest.mark.chaos
def test_stop_honored_between_passes_and_for_resident(trainer_setup):
    """The stop flag must also stop runs whose passes cannot stop at a
    batch boundary (resident mode = one device program) — run_pass
    checks it before every dispatch and snapshots the pass-boundary
    state."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root)
    tr.run_pass(ds, checkpoint=cm)
    step = tr.global_step
    preemption.request_stop("scheduler notice")
    with pytest.raises(PreemptedError) as ei:
        tr.run_pass(ds, checkpoint=cm, resident=True)
    assert ei.value.checkpointed and ei.value.step == step
    assert cm.latest_step() == step          # boundary snapshot written
    assert cm.load_cursor() is None
    assert preemption.read_resume_marker(root) is not None


@pytest.mark.chaos
def test_resident_restart_on_cursor_rolls_back_to_boundary(
        trainer_setup):
    """A resident run restarted onto a mid-pass cursor checkpoint must
    not train a full pass from mid-pass state — it rolls back to the
    pass boundary (resident passes have no mid-pass entry point)."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.run_pass(ds, checkpoint=cm)
    cm.save(tr)                                    # boundary @ 10
    boundary = tr.global_step
    with installed(FaultPlan.parse("preempt.signal:fail:nth=3")):
        with pytest.raises(PreemptedError):
            tr.run_pass(ds, checkpoint=cm)         # cursor @ 13
    preemption.clear_stop()
    tr2 = mk()
    cm2 = CheckpointManager(root, keep=10)
    assert cm2.restore(tr2) == boundary + 3
    ran = []
    tr2.train_pass_resident = lambda d, lp="": (ran.append(1)
                                                or {"batches": 10})
    out = tr2.run_pass(ds, checkpoint=cm2, resident=True)
    assert ran and out == {"batches": 10}
    assert tr2.global_step == boundary             # rolled back first
    # without any boundary checkpoint it refuses instead
    tr3 = mk()
    cm3 = CheckpointManager(root + "_nb")
    with installed(FaultPlan.parse("preempt.signal:fail:nth=3")):
        with pytest.raises(PreemptedError):
            tr3.run_pass(ds, checkpoint=cm3)
    preemption.clear_stop()
    tr4 = mk()
    cm4 = CheckpointManager(root + "_nb")
    cm4.restore(tr4)
    with pytest.raises(RuntimeError, match="resident"):
        tr4.run_pass(ds, checkpoint=cm4, resident=True)


@pytest.mark.chaos
def test_preempt_on_periodic_save_boundary_reuses_checkpoint(
        trainer_setup):
    """Preemption landing on the SAME boundary as a periodic save must
    not re-save (a delta re-save over a fresh base would be refused) —
    the periodic checkpoint already holds the cursor."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    with flags_scope(ckpt_every_batches=4):
        tr = mk()
        cm = CheckpointManager(root)
        # nth=4 == the first periodic cadence: both fire at batch 4
        with installed(FaultPlan.parse("preempt.signal:fail:nth=4")):
            with pytest.raises(PreemptedError) as ei:
                tr.run_pass(ds, checkpoint=cm)
    assert ei.value.checkpointed and ei.value.batch_index == 4
    cur = cm.load_cursor()
    assert cur is not None and cur["batch_index"] == 4
    # resume still works end to end
    preemption.clear_stop()
    tr2 = mk()
    cm2 = CheckpointManager(root)
    assert cm2.restore(tr2) == 4
    out = tr2.run_pass(mkds(), checkpoint=cm2)
    assert int(out["batches"]) == 6


@pytest.mark.chaos
def test_boundary_save_when_cadence_hits_pass_length(trainer_setup):
    """Cadence dividing the pass length exactly: the end-of-pass
    boundary publish lands on the same step as the final periodic save
    (which may be the first BASE) and must supersede it, not crash."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    with flags_scope(ckpt_every_batches=5):   # 10 batches: saves at 5, 10
        tr = mk()
        cm = CheckpointManager(root)
        out = tr.run_pass(ds, checkpoint=cm)
    assert int(out["batches"]) == 10
    assert cm.load_cursor() is None           # boundary superseded 10's cursor
    tr2 = mk()
    assert cm.restore(tr2) == 10


@pytest.mark.chaos
def test_emergency_cursor_superseded_without_cadence(trainer_setup):
    """ckpt_every_batches=0: a preempted pass leaves only the emergency
    cursor checkpoint; after the resumed pass completes, the newest
    checkpoint must be cursor-free — a LATER pass's rollback must not
    resume into the finished pass (discarding its training)."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root)
    with installed(FaultPlan.parse("preempt.signal:fail:nth=3")):
        with pytest.raises(PreemptedError):
            tr.run_pass(ds, checkpoint=cm)
    preemption.clear_stop()
    tr2 = mk()
    cm2 = CheckpointManager(root)
    cm2.restore(tr2)
    tr2.run_pass(mkds(), checkpoint=cm2)      # resumes, completes
    assert cm2.load_cursor() is None          # cursor superseded
    # a transient failure in the NEXT pass must replay that pass fully
    with installed(FaultPlan.parse("trainer.pass:fail:nth=1")):
        out = tr2.run_pass(mkds(), checkpoint=cm2, max_retries=1)
    assert int(out["batches"]) == 10


@pytest.mark.chaos
def test_preempt_at_final_batch_resumes_to_clean_boundary(trainer_setup):
    """SIGTERM at the LAST batch boundary: the cursor covers the whole
    pass, so the resumed 'pass' trains zero batches — it must still
    publish a cursor-free boundary checkpoint (a later pass's rollback
    must not re-adopt the stale cursor and train nothing / roll back
    past the finished pass)."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    baseline = mk()
    out = baseline.train_pass(ds)
    want = state_digest(baseline)
    tr = mk()
    cm = CheckpointManager(root)
    nth = int(out["batches"])  # stop poll at the final boundary
    with installed(FaultPlan.parse(f"preempt.signal:fail:nth={nth}")):
        with pytest.raises(PreemptedError) as ei:
            tr.run_pass(ds, checkpoint=cm)
    assert ei.value.batch_index == nth
    preemption.clear_stop()
    tr2 = mk()
    cm2 = CheckpointManager(root)
    cm2.restore(tr2)
    out2 = tr2.run_pass(mkds(), checkpoint=cm2)
    assert int(out2["batches"]) == 0           # nothing left to replay
    assert state_digest(tr2) == want
    assert cm2.load_cursor() is None           # stale cursor superseded
    # and the NEXT pass trains fully even through a transient retry
    with installed(FaultPlan.parse("trainer.pass:fail:nth=1")):
        out3 = tr2.run_pass(mkds(), checkpoint=cm2, max_retries=1)
    assert int(out3["batches"]) == nth


@pytest.mark.chaos
def test_nondeterministic_restart_rolls_back_not_splices(trainer_setup):
    """A restart whose dataset CANNOT resume (non-deterministic load)
    while the trainer sits on mid-pass state must not silently replay a
    full pass on top of it (double-training the prefix): with no
    boundary checkpoint it refuses; with one it rolls back."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    with installed(FaultPlan.parse("preempt.signal:fail:nth=3")):
        with pytest.raises(PreemptedError):
            tr.run_pass(ds, checkpoint=cm)
    preemption.clear_stop()
    tr2 = mk()
    cm2 = CheckpointManager(root, keep=10)
    assert cm2.restore(tr2) == 3
    # restarted process loads via the THREADED record path: order is
    # not reproducible, so the cursor cannot be applied
    with flags_scope(native_parse=False):   # read_thread_num default 8
        nd = mkds()
    assert not nd.supports_cursor_resume
    with pytest.raises(RuntimeError, match="cannot be resumed"):
        tr2.run_pass(nd, checkpoint=cm2)    # no boundary ckpt -> refuse
    # with a boundary checkpoint it rolls back instead
    tr3 = mk()
    cm3 = CheckpointManager(root + "_b", keep=10)
    tr3.run_pass(ds, checkpoint=cm3)
    cm3.save(tr3)                           # boundary at step 10
    with installed(FaultPlan.parse("preempt.signal:fail:nth=3")):
        with pytest.raises(PreemptedError):
            tr3.run_pass(ds, checkpoint=cm3)
    preemption.clear_stop()
    tr4 = mk()
    cm4 = CheckpointManager(root + "_b", keep=10)
    assert cm4.restore(tr4) == 13
    with flags_scope(native_parse=False):
        nd2 = mkds()
    out = tr4.run_pass(nd2, checkpoint=cm4)
    assert tr4.global_step == 10 + int(out["batches"])  # from boundary


def test_preempt_fault_os_exc_still_graceful():
    """Every exc= variant of a preempt.signal fail fault must become a
    stop request — including exc=os, whose OSError is not an
    InjectedFault subclass."""
    plan = FaultPlan.parse("preempt.signal:fail:nth=1,exc=os")
    with installed(plan):
        assert preemption.stop_requested()
    assert "injected" in preemption.stop_reason()


def test_consensus_restore_survives_drifted_retention(trainer_setup,
                                                      tmp_path):
    """Ranks whose newest checkpoints drifted apart (crash timing /
    corruption) agree on the newest step that exists on BOTH — not a
    min() that one rank may no longer hold."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    roots = [str(tmp_path / "r0"), str(tmp_path / "r1")]
    cms = []
    for r in roots:
        t = mk()
        cm = CheckpointManager(r, keep=10)
        t.train_pass(ds)
        cm.save(t)            # step 10 on both
        t.train_pass(ds)
        cm.save(t)            # step 20 on both
        cms.append(cm)
    # rank 1's newest checkpoint is corrupt -> its verified set is {10}
    target = os.path.join(cms[1]._dir(20), "dense.pkl")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(target, "wb") as fh:
        fh.write(bytes(blob))
    assert cms[0].verified_steps() == [10, 20]
    assert cms[1].verified_steps() == [10]

    from paddlebox_tpu.resilience.consensus import consensus_restore
    store = DirConsensusStore(str(tmp_path / "consensus"))
    fresh = [mk(), mk()]
    got = _run_ranks([
        lambda: consensus_restore(cms[0], fresh[0],
                                  RestoreConsensus(store, 0, 2,
                                                   timeout=20)),
        lambda: consensus_restore(cms[1], fresh[1],
                                  RestoreConsensus(store, 1, 2,
                                                   timeout=20)),
    ])
    assert got == [10, 10]
    assert fresh[0].global_step == fresh[1].global_step == 10


def test_shared_quarantine_refuses_streaming_dataset(tmp_path):
    desc = DataFeedDesc.criteo(batch_size=16)
    ds = DatasetFactory().create_dataset("QueueDataset", desc)
    store = DirConsensusStore(str(tmp_path / "c"))
    with pytest.raises(TypeError, match="in-memory"):
        sync_shared_quarantine(ds, RestoreConsensus(store, 0, 1,
                                                    timeout=5))


# ---- satellite: NaN recoverability ------------------------------------
def test_nan_without_checkpoint_raises_immediately(trainer_setup):
    """A NanInfError with no checkpoint manager must not be retried:
    the live state is already poisoned and a retry would train garbage
    (ISSUE 3 satellite — trainer.py:241)."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    calls = []

    def poisoned(*a, **kw):
        calls.append(1)
        raise NanInfError("nan/inf loss at step 3")

    tr.train_pass = poisoned
    with pytest.raises(NanInfError):
        tr.run_pass(ds, max_retries=3)
    assert len(calls) == 1  # no retry without a rollback target

    # an EMPTY manager is not a rollback target either: restore() would
    # be a no-op and every retry would replay from the poisoned state
    tr_e = mk()
    cm_empty = CheckpointManager(root + "_empty")
    calls_e = []

    def poisoned_e(*a, **kw):
        calls_e.append(1)
        raise NanInfError("nan/inf loss")

    tr_e.train_pass = poisoned_e
    with pytest.raises(NanInfError):
        tr_e.run_pass(ds, checkpoint=cm_empty, max_retries=3)
    assert len(calls_e) == 1

    # WITH a checkpoint the rollback makes NaN recoverable (PR 2
    # semantics preserved)
    tr2 = mk()
    cm = CheckpointManager(root)
    tr2.run_pass(ds)
    cm.save(tr2)
    calls2 = []
    real2 = tr2.train_pass

    def poisoned_once(*a, **kw):
        calls2.append(1)
        if len(calls2) == 1:
            raise NanInfError("nan/inf loss")
        return real2(*a, **kw)

    tr2.train_pass = poisoned_once
    out = tr2.run_pass(ds, checkpoint=cm, max_retries=1)
    assert len(calls2) == 2 and np.isfinite(out["last_loss"])


# ---- satellite: checkpoint hardening ----------------------------------
def test_meta_sidecar_detects_torn_meta(trainer_setup):
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root)
    tr.train_pass(ds)
    path = cm.save(tr)
    assert os.path.isfile(os.path.join(path, "meta.sha256"))
    # tamper with meta.json (a torn/partial write) — restore must refuse
    mp = os.path.join(path, "meta.json")
    meta = json.load(open(mp))
    meta["sparse_rows"] = 0
    with open(mp, "w") as fh:
        json.dump(meta, fh)
    tr2 = mk()
    with pytest.raises(CheckpointCorruptError, match="meta.json"):
        cm.restore(tr2)


def test_half_deleted_ckpt_dir_is_skipped(trainer_setup):
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds)
    cm.save(tr)
    good = tr.global_step
    tr.train_pass(ds)
    cm.save(tr)
    # half-delete the NEWER checkpoint (rmtree died after meta.json)
    os.unlink(os.path.join(cm._dir(tr.global_step), "meta.json"))
    cm2 = CheckpointManager(root, keep=10)
    assert cm2.steps() == [good]
    assert cm2.latest_step() == good          # LATEST pointer bypassed
    assert cm2._latest_base() == good
    tr2 = mk()
    assert cm2.restore(tr2) == good
    # another save still works: _retain walks past the carcass
    tr2.train_pass(ds)
    cm2.save(tr2)
    assert good in cm2.steps()


def test_delta_after_rollback_links_to_restored_step(trainer_setup,
                                                     tmp_path):
    """After a rollback-restore to an older step, the next delta must
    chain to THAT step — not to a newer checkpoint of the abandoned
    timeline (which would replay abandoned state into any restore)."""
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.run_pass(ds, checkpoint=cm)
    cm.save(tr)                                  # boundary base @ 10
    with installed(FaultPlan.parse("preempt.signal:fail:nth=3")):
        with pytest.raises(PreemptedError):
            tr.run_pass(ds, checkpoint=cm)       # cursor delta @ 13
    preemption.clear_stop()

    # restart; a SHORTER dataset (2 batches) changes the fingerprint ->
    # rollback to boundary 10, then train to step 12 (< abandoned 13)
    short = generate_criteo_files(str(tmp_path / "short"), num_files=1,
                                  rows_per_file=64, vocab_per_slot=30,
                                  seed=4)
    tr2 = mk()
    cm2 = CheckpointManager(root, keep=10)
    assert cm2.restore(tr2) == 13
    other = mkds(short)
    out = tr2.run_pass(other, checkpoint=cm2)    # rolls back to 10
    assert tr2.global_step == 12 and int(out["batches"]) == 2
    cm2.save(tr2, delta=True)
    meta = cm2._meta(12)
    assert meta["prev_step"] == 10               # NOT the abandoned 13
    tr3 = mk()
    assert cm2.restore(tr3, step=12) == 12
    assert tr3.global_step == 12


def test_latest_verified_step_skips_corrupt_chain(trainer_setup):
    files, mk, mkds, root = trainer_setup
    ds = mkds()
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds)
    cm.save(tr)
    good = tr.global_step
    tr.train_pass(ds)
    cm.save(tr)
    bad = tr.global_step
    # corrupt the newest checkpoint's payload
    target = os.path.join(cm._dir(bad), "sparse.npz")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(target, "wb") as fh:
        fh.write(bytes(blob))
    assert cm.latest_verified_step() == good


# ---- multihost-consistent recovery ------------------------------------
def _run_ranks(fns, timeout=30.0):
    """Run one callable per rank concurrently (the consensus gathers
    block until the full mesh publishes)."""
    out = {}
    errs = []

    def runner(i, fn):
        try:
            out[i] = fn()
        except BaseException as e:  # surfaced below
            errs.append(e)

    threads = [threading.Thread(target=runner, args=(i, fn), daemon=True)
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), "consensus deadlocked"
    if errs:
        raise errs[0]
    return [out[i] for i in range(len(fns))]


def test_consensus_restore_agrees_on_min_step(trainer_setup, tmp_path,
                                              fresh_hub):
    """2-process consensus: ranks with different newest checkpoints both
    restore the same agreed (min) step."""
    files, mk, mkds, root = trainer_setup
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    ds = mkds()
    roots = [str(tmp_path / "ckpt_r0"), str(tmp_path / "ckpt_r1")]
    trainers, cms = [], []
    for r in roots:
        t = mk()
        cm = CheckpointManager(r, keep=10)
        t.train_pass(ds)
        cm.save(t)
        trainers.append(t)
        cms.append(cm)
    common = trainers[0].global_step
    assert trainers[1].global_step == common
    # rank 0 got one more save in before the crash; rank 1 did not
    trainers[0].train_pass(ds)
    cms[0].save(trainers[0])

    from paddlebox_tpu.resilience.consensus import consensus_restore
    store = DirConsensusStore(str(tmp_path / "consensus"))
    fresh = [mk(), mk()]

    def restore_rank(i):
        c = RestoreConsensus(store, i, 2, timeout=20)
        return consensus_restore(cms[i], fresh[i], c)

    got = _run_ranks([lambda: restore_rank(0), lambda: restore_rank(1)])
    assert got == [common, common]
    assert fresh[0].global_step == fresh[1].global_step == common
    evs = [e for e in sink.events if e["event"] == "restore_consensus"]
    assert len(evs) == 2 and all(e["agreed"] == common for e in evs)


def test_consensus_fresh_start_when_any_rank_empty(tmp_path):
    store = DirConsensusStore(str(tmp_path / "c"))

    def rank(i, step):
        return RestoreConsensus(store, i, 2,
                                timeout=20).agree_restore_step(step)

    got = _run_ranks([lambda: rank(0, None), lambda: rank(1, 7)])
    assert got == [None, None]


def test_consensus_timeout_names_missing_rank(tmp_path):
    store = DirConsensusStore(str(tmp_path / "c"))
    c = RestoreConsensus(store, 0, 2, timeout=0.2, poll_interval=0.01)
    with pytest.raises(ConsensusTimeout, match=r"\[1\]"):
        c.agree_restore_step(3)


@pytest.mark.chaos
def test_shared_quarantine_preserves_batch_identity(tmp_path, fresh_hub):
    """2-process quarantine consensus: a file fault on ONE process's
    load ends with BOTH processes dropping the same file — batch streams
    stay byte-identical (the SPMD contract)."""
    files = generate_criteo_files(str(tmp_path / "data"), num_files=3,
                                  rows_per_file=48, vocab_per_slot=30,
                                  seed=9)
    desc = DataFeedDesc.criteo(batch_size=16)

    def mkds():
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        return ds

    # ONE reader thread: the record-path load order is then a pure
    # function of the filelist, so the non-reloading originator and the
    # reloading peer must produce identical streams
    with flags_scope(native_parse=False, poison_budget_files=1,
                     poison_budget_records=0, read_thread_num=1):
        ds0, ds1 = mkds(), mkds()
        target = os.path.basename(files[1])
        plan = FaultPlan.parse(
            f"parser.record:corrupt:match=*{target}*,times=0", seed=5)
        with installed(plan):
            ds0.load_into_memory()   # only "rank 0" hits the fault
        ds1.load_into_memory()
        assert [p for p, _ in ds0.quarantined_files] == [files[1]]
        assert ds1.quarantined_files == []
        assert len(ds0) != len(ds1)  # contract broken before the sync

        store = DirConsensusStore(str(tmp_path / "consensus"))
        got = _run_ranks([
            lambda: sync_shared_quarantine(
                ds0, RestoreConsensus(store, 0, 2, timeout=20)),
            lambda: sync_shared_quarantine(
                ds1, RestoreConsensus(store, 1, 2, timeout=20)),
        ])
    assert got[0] == got[1] == [files[1]]
    assert [p for p, _ in ds0.quarantined_files] == [files[1]]
    assert [p for p, _ in ds1.quarantined_files] == [files[1]]
    b0, b1 = list(ds0.batches()), list(ds1.batches())
    assert len(b0) == len(b1) > 0
    assert all(_batches_equal(x, y) for x, y in zip(b0, b1))


def test_shared_quarantine_noop_when_all_healthy(tmp_path):
    files = generate_criteo_files(str(tmp_path / "data"), num_files=2,
                                  rows_per_file=32, vocab_per_slot=30,
                                  seed=9)
    desc = DataFeedDesc.criteo(batch_size=16)

    def load():
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()
        return ds

    ds0, ds1 = load(), load()
    n0 = len(ds0)
    store = DirConsensusStore(str(tmp_path / "consensus"))
    got = _run_ranks([
        lambda: sync_shared_quarantine(
            ds0, RestoreConsensus(store, 0, 2, timeout=20)),
        lambda: sync_shared_quarantine(
            ds1, RestoreConsensus(store, 1, 2, timeout=20)),
    ])
    assert got == [[], []]
    assert len(ds0) == n0  # converged in one round, nothing reloaded


# ---- real SIGTERM, real process ----------------------------------------
_WORKER = textwrap.dedent("""
    import json, os, signal, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    import optax

    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.resilience.preemption import PreemptedError
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import (CheckpointManager,
                                                state_digest)

    phase, data_dir, ckpt_root, out_path, beacon = sys.argv[1:6]
    FLAGS.graceful_shutdown = True       # Trainer init installs handlers
    FLAGS.ckpt_every_batches = 4

    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 2048
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)

    def mk():
        table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                               unique_bucket_min=2048)
        return Trainer(CtrDnn(hidden=(8,)), table, desc,
                       tx=optax.adam(1e-2), seed=0)

    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    files = sorted(os.path.join(data_dir, f)
                   for f in os.listdir(data_dir))
    ds.set_filelist(files)
    ds.load_into_memory()

    if phase == "run":
        # baseline digest first (uninterrupted, same seed/state zero)
        base = mk()
        out_base = base.train_pass(ds)
        with open(out_path, "w") as fh:
            json.dump({"baseline_digest": state_digest(base),
                       "total_batches": out_base["batches"]}, fh)
        # now the preemptable run: slow the pass down and beacon the
        # parent so its SIGTERM lands mid-pass
        orig = ds.batches
        def slow_batches(start_batch=0):
            for i, b in enumerate(orig(start_batch=start_batch)):
                if i == 1:
                    open(beacon, "w").write("mid-pass")
                time.sleep(0.05)
                yield b
        ds.batches = slow_batches
        trainer = mk()
        cm = CheckpointManager(ckpt_root)
        try:
            trainer.run_pass(ds, checkpoint=cm)
        except PreemptedError as e:
            assert e.checkpointed, "no emergency checkpoint"
            sys.exit(preemption.EXIT_RESUME)
        sys.exit(3)  # pass finished before the signal landed

    if phase == "resume":
        marker = preemption.read_resume_marker(ckpt_root)
        trainer = mk()
        cm = CheckpointManager(ckpt_root)
        restored = cm.restore(trainer)
        out = trainer.run_pass(ds, checkpoint=cm)
        with open(out_path, "w") as fh:
            json.dump({"digest": state_digest(trainer),
                       "restored": restored,
                       "had_marker": marker is not None,
                       "marker_cleared":
                           preemption.read_resume_marker(ckpt_root)
                           is None,
                       "replayed_batches": out["batches"],
                       "global_step": trainer.global_step}, fh)
        sys.exit(0)
""")


@pytest.mark.chaos
def test_real_sigterm_graceful_shutdown_and_resume(tmp_path):
    """A real SIGTERM to a real training process: the handler converts
    it to a graceful stop, the process exits EXIT_RESUME with an
    emergency checkpoint, and a restarted process resumes to the exact
    uninterrupted state."""
    data_dir = str(tmp_path / "data")
    generate_criteo_files(data_dir, num_files=2, rows_per_file=320,
                          vocab_per_slot=40, seed=3)
    ckpt_root = str(tmp_path / "ckpt")
    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as fh:
        fh.write(_WORKER)
    beacon = str(tmp_path / "beacon")
    run_out = str(tmp_path / "run.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))

    proc = subprocess.Popen(
        [sys.executable, worker, "run", data_dir, ckpt_root, run_out,
         beacon],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    deadline = time.monotonic() + 120
    while not os.path.exists(beacon):
        assert proc.poll() is None, \
            f"worker died early:\n{proc.stdout.read()}"
        assert time.monotonic() < deadline, "beacon never appeared"
        time.sleep(0.02)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == preemption.EXIT_RESUME, \
        f"rc={proc.returncode}\n{out}"
    baseline = json.load(open(run_out))
    marker = json.load(open(os.path.join(ckpt_root, "RESUME.json")))
    assert marker["exit_code"] == preemption.EXIT_RESUME

    res_out = str(tmp_path / "resume.json")
    rc = subprocess.run(
        [sys.executable, worker, "resume", data_dir, ckpt_root, res_out,
         beacon],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=180)
    assert rc.returncode == 0, rc.stdout
    resumed = json.load(open(res_out))
    assert resumed["had_marker"] and resumed["marker_cleared"]
    assert resumed["replayed_batches"] < baseline["total_batches"]
    assert resumed["global_step"] == baseline["total_batches"]
    assert resumed["digest"] == baseline["baseline_digest"]
