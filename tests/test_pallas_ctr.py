"""Parity matrix for the device-side CTR op family (ISSUE 13):
fused Pallas rank_attention / batch_fc / cross_norm_hadamard vs the XLA
compositions, through the dispatch seams, interpret mode on CPU.

Contract being gated (docs/PERFORMANCE.md §Device kernels): forward
within f32 tolerance (the MXU one-hot matmuls sum in a different
order), grads BITWISE where the formulation is exact — the fused
backwards are hand-written jnp mirroring the XLA compositions' autodiff
ops, so given the same upstream cotangent rank_attention and batch_fc
grads match exactly; cross_norm's dX carries reassociation-level f32
drift (the composition's add ordering differs) and gates with rtol."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.ops import (
    batch_fc, cross_norm_hadamard, cross_norm_update,
    init_cross_norm_summary, rank_attention, rank_attention2,
)
from paddlebox_tpu.ops.pallas_ctr import (batch_fc_fits, cross_norm_fits,
                                          rank_attention_fits)

MR = 3


def _rank_case(n=37, d=12, p=7, seed=0, all_invalid=False):
    """rank_offset with the full validity matrix: invalid own ranks
    (col 0 = 0), missing co-shown entries (rank 0 → faster = −1), and
    optionally every row invalid."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    param = rng.normal(size=(MR * MR, d, p)).astype(np.float32)
    ro = np.zeros((n, 1 + 2 * MR), np.int32)
    if not all_invalid:
        ro[:, 0] = rng.integers(0, MR + 1, size=n)
        for k in range(MR):
            on = rng.random(n) < 0.7
            ro[:, 1 + 2 * k] = np.where(
                on, rng.integers(1, MR + 1, size=n), 0)
            ro[:, 2 + 2 * k] = rng.integers(0, n, size=n)
    return jnp.asarray(x), jnp.asarray(ro), jnp.asarray(param)


@pytest.mark.parametrize("param_2d", [False, True])
@pytest.mark.parametrize("all_invalid", [False, True])
def test_rank_attention_forward_parity(param_2d, all_invalid):
    x, ro, param = _rank_case(all_invalid=all_invalid)
    if param_2d:
        param = param.reshape(MR * MR * x.shape[1], -1)
    ref = np.asarray(rank_attention(x, ro, param, MR))
    with flags_scope(use_pallas_rank_attention=True):
        got = np.asarray(rank_attention(x, ro, param, MR))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    if all_invalid:
        np.testing.assert_array_equal(ref, 0.0)


@pytest.mark.parametrize("param_2d", [False, True])
@pytest.mark.parametrize("enable_input_bp", [False, True])
def test_rank_attention_grads_bitwise(param_2d, enable_input_bp):
    """Same upstream cotangent ⇒ the fused custom_vjp's grads match the
    XLA composition's autodiff EXACTLY (the backward einsums/scatter
    are the same ops); dX is exactly zero without enable_input_bp."""
    x, ro, param = _rank_case(seed=3)
    if param_2d:
        param = param.reshape(MR * MR * x.shape[1], -1)
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(x.shape[0], 7)).astype(np.float32))

    def grads(flag):
        def f(xx, pp):
            with flags_scope(use_pallas_rank_attention=flag):
                return jnp.sum(rank_attention(
                    xx, ro, pp, MR, enable_input_bp=enable_input_bp) * w)
        return jax.grad(f, argnums=(0, 1))(x, param)

    gx0, gp0 = grads(False)
    gx1, gp1 = grads(True)
    np.testing.assert_array_equal(np.asarray(gx1), np.asarray(gx0))
    np.testing.assert_array_equal(np.asarray(gp1), np.asarray(gp0))
    assert np.asarray(gp1).shape == param.shape  # cotangent keeps layout
    if not enable_input_bp:
        np.testing.assert_array_equal(np.asarray(gx1), 0.0)
    else:
        assert np.abs(np.asarray(gx1)).max() > 0


def test_rank_attention2_param_only_under_flag():
    """rank_attention2 (param-only grads) through the Pallas seam: X
    grads exactly zero, param grads bitwise vs the XLA path."""
    x, ro, param = _rank_case(seed=5)

    def grads(flag):
        def f(xx, pp):
            with flags_scope(use_pallas_rank_attention=flag):
                return jnp.sum(rank_attention2(xx, ro, pp, MR) ** 2)
        return jax.grad(f, argnums=(0, 1))(x, param)

    gx0, gp0 = grads(False)
    gx1, gp1 = grads(True)
    np.testing.assert_array_equal(np.asarray(gx1), 0.0)
    # forward order differs (MXU block grouping), so the ²-loss
    # cotangent differs at f32 lsb — param grads gate with tolerance
    np.testing.assert_allclose(np.asarray(gp1), np.asarray(gp0),
                               rtol=1e-4, atol=1e-6)


def test_rank_attention_overflow_falls_back():
    """A shape past the VMEM residency budget must route to the XLA
    fallback under the flag (and produce identical results trivially)."""
    assert not rank_attention_fits(max_rank=5, d=1024, p=1024)
    assert rank_attention_fits(max_rank=3, d=128, p=128)
    n, d, p = 8, 1024, 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    param = jnp.asarray(
        rng.normal(size=(25, d, p)).astype(np.float32) * 0.01)
    ro = jnp.asarray(np.tile(
        np.array([[1, 1, 0] + [0] * 8], np.int32), (n, 1)))
    ref = np.asarray(rank_attention(x, ro, param, 5))
    with flags_scope(use_pallas_rank_attention=True):
        got = np.asarray(rank_attention(x, ro, param, 5))
    np.testing.assert_array_equal(got, ref)  # same program — fallback


@pytest.mark.parametrize("mode", ["default", "batchcount", "transpose"])
def test_batch_fc_parity_forward_and_grads(mode):
    """All three batch_fc modes: fused forward bitwise (same dot
    ordering, bias added in-VMEM), grads bitwise (mirrored einsums)."""
    rng = np.random.default_rng(1)
    s, n, i_dim, o_dim = 3, 5, 4, 2
    x3 = rng.normal(size=(s, n, i_dim)).astype(np.float32)
    w = rng.normal(size=(s, i_dim, o_dim)).astype(np.float32)
    b = rng.normal(size=(s, o_dim)).astype(np.float32)
    if mode == "default":
        args = (jnp.asarray(x3), jnp.asarray(w), jnp.asarray(b))
        kw = {}
    elif mode == "batchcount":
        args = (jnp.asarray(x3.reshape(s * n, i_dim)), jnp.asarray(w),
                jnp.asarray(b))
        kw = dict(batchcount=s)
    else:
        wt = np.swapaxes(w, 1, 2).copy()
        args = (jnp.asarray(x3.reshape(s * n, i_dim)), jnp.asarray(wt),
                jnp.asarray(b))
        kw = dict(batchcount=s, transpose_weight=True)

    ref = np.asarray(batch_fc(*args, **kw))
    with flags_scope(use_pallas_batch_fc=True):
        got = np.asarray(batch_fc(*args, **kw))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)

    def grads(flag):
        def f(xx, ww, bb):
            with flags_scope(use_pallas_batch_fc=flag):
                return jnp.sum(batch_fc(xx, ww, bb, **kw) * 0.7)
        return jax.grad(f, argnums=(0, 1, 2))(*args)

    for g_ref, g_got in zip(grads(False), grads(True)):
        np.testing.assert_array_equal(np.asarray(g_got),
                                      np.asarray(g_ref))


def test_batch_fc_overflow_falls_back():
    assert not batch_fc_fits(2048, 2048)
    assert batch_fc_fits(128, 128)


@pytest.mark.parametrize("flag", [False, True])
def test_batch_fc_transpose_without_batchcount_raises(flag):
    """transpose_weight is a batchcount-mode attr (the reference op);
    default mode must fail loudly on BOTH paths instead of contracting
    an [S, O, I] weight on the wrong axis."""
    x = jnp.ones((2, 4, 3), jnp.float32)
    w = jnp.ones((2, 3, 3), jnp.float32)
    b = jnp.ones((2, 3), jnp.float32)
    with flags_scope(use_pallas_batch_fc=flag):
        with pytest.raises(ValueError, match="transpose_weight"):
            batch_fc(x, w, b, transpose_weight=True)


def test_cross_norm_parity_forward_and_grads():
    """Fused one-VMEM-pass cross block: forward bitwise (same
    elementwise math + exact zero-padded dot), dX within f32
    reassociation tolerance (the composition's autodiff groups the
    three a-contributions differently)."""
    rng = np.random.default_rng(2)
    b, n, d = 9, 2, 5
    x = jnp.asarray(rng.normal(size=(b, 2 * n * d)).astype(np.float32))
    summ = cross_norm_update(init_cross_norm_summary(n, d), x, n, d,
                             decay=0.5)
    ref = np.asarray(cross_norm_hadamard(x, summ, n, d))
    with flags_scope(use_pallas_cross_norm=True):
        got = np.asarray(cross_norm_hadamard(x, summ, n, d))
    np.testing.assert_array_equal(got, ref)

    def grads(flag):
        def f(xx):
            with flags_scope(use_pallas_cross_norm=flag):
                return jnp.sum(cross_norm_hadamard(xx, summ, n, d) ** 2)
        return jax.grad(f)(x)

    np.testing.assert_allclose(np.asarray(grads(True)),
                               np.asarray(grads(False)),
                               rtol=1e-4, atol=1e-6)
    assert cross_norm_fits(128) and not cross_norm_fits(1 << 20)


def test_cross_norm_summary_grads_both_paths():
    """The summary cotangent chain survives the seam: the fused path
    derives mean/scale OUTSIDE the kernel, so d loss / d summary stays
    defined and close to the composition's."""
    rng = np.random.default_rng(6)
    b, n, d = 6, 1, 4
    x = jnp.asarray(rng.normal(size=(b, 2 * n * d)).astype(np.float32))
    summ = cross_norm_update(init_cross_norm_summary(n, d), x, n, d,
                             decay=0.5)

    def grads(flag):
        def f(s):
            with flags_scope(use_pallas_cross_norm=flag):
                return jnp.sum(cross_norm_hadamard(x, s, n, d) ** 2)
        return jax.grad(f)(summ)

    g0, g1 = grads(False), grads(True)
    for a, c in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_cross_norm_sync_stats_psum_two_device_mesh():
    """sync_stats under a 2-device mesh: per-shard
    ``cross_norm_update(..., sync_axis=...)`` folds the GLOBAL batch
    stats (bit-identical summaries on every shard, equal to the
    single-host update over the concatenated batch), and the forward
    with the synced summary is Pallas-vs-XLA exact."""
    from jax.sharding import Mesh, PartitionSpec as P
    n, d = 2, 4
    rng = np.random.default_rng(7)
    xg = rng.normal(size=(8, 2 * n * d)).astype(np.float32)
    summ = init_cross_norm_summary(n, d)
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def upd(x_blk):
        return cross_norm_update(summ, x_blk, n, d, decay=0.5,
                                 sync_axis="data")

    f = jax.jit(jax.shard_map(upd, mesh=mesh, in_specs=P("data"),
                              out_specs=P(), check_vma=False))
    synced = f(jnp.asarray(xg))
    want = cross_norm_update(summ, jnp.asarray(xg), n, d, decay=0.5)
    for a, c in zip(jax.tree.leaves(want), jax.tree.leaves(synced)):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=1e-6, atol=1e-6)

    ref = np.asarray(cross_norm_hadamard(jnp.asarray(xg), synced, n, d))
    with flags_scope(use_pallas_cross_norm=True):
        got = np.asarray(cross_norm_hadamard(jnp.asarray(xg), synced,
                                             n, d))
    np.testing.assert_array_equal(got, ref)


def test_ads_rank_full_tower_parity():
    """AdsRank with slot_fc + cross_norm (the PV bench configuration):
    one forward+backward, all three flags on vs off — logits within
    f32 tolerance, and every param grad finite and close."""
    from paddlebox_tpu.models import AdsRank
    b, s, d, dm = 16, 4, 6, 8
    rng = np.random.default_rng(8)
    pooled = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    dense = jnp.asarray(rng.normal(size=(b, 2)).astype(np.float32))
    ro = np.zeros((b, 1 + 2 * MR), np.int32)
    ro[:, 0] = rng.integers(0, MR + 1, size=b)
    ro[:, 1] = 1
    ro[:, 2] = rng.integers(0, b, size=b)
    ro = jnp.asarray(ro)
    summ = init_cross_norm_summary(1, dm)
    model = AdsRank(d_model=dm, max_rank=MR, hidden=(8,), slot_fc=True,
                    cross_norm=True)
    params = model.init(jax.random.PRNGKey(0), pooled, dense, ro, summ)

    def run(flag):
        with flags_scope(use_pallas_rank_attention=flag,
                         use_pallas_batch_fc=flag,
                         use_pallas_cross_norm=flag):
            out = model.apply(params, pooled, dense, ro, summ)
            g = jax.grad(lambda p: jnp.sum(model.apply(
                p, pooled, dense, ro, summ) ** 2))(params)
        return np.asarray(out), g

    o0, g0 = run(False)
    o1, g1 = run(True)
    np.testing.assert_allclose(o1, o0, rtol=1e-4, atol=1e-5)
    for a, c in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        assert np.all(np.isfinite(np.asarray(c)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=5e-3, atol=1e-4)
