"""SparseAdam / SparseAdamShared in-table optimizers: numeric parity with
a numpy transcription of the reference CUDA math (optimizer.cuh.h:148-477)
plus e2e training and save/load of the optimizer extension block."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ps import EmbeddingTable, SparseAdamConfig
from paddlebox_tpu.ps.sgd import RowState, adam_update, opt_ext_width
from paddlebox_tpu.train import Trainer


def _np_adam_dir(w, m1, m2, b1p, b2p, g, scale, cfg):
    """update_lr/update_mf (optimizer.cuh.h:159-236), numpy, one row."""
    eps = cfg.ada_epsilon
    ratio = cfg.learning_rate * np.sqrt(1.0 - b2p) / (1.0 - b1p)
    w, m1, m2 = w.copy(), m1.copy(), m2.copy()
    for i in range(len(w)):
        scaled = g[i] / scale
        m1[i] = cfg.beta1_decay_rate * m1[i] \
            + (1 - cfg.beta1_decay_rate) * scaled
        m2[i] = cfg.beta2_decay_rate * m2[i] \
            + (1 - cfg.beta2_decay_rate) * scaled * scaled
        w[i] = np.clip(w[i] + ratio * (m1[i] / (np.sqrt(m2[i]) + eps)),
                       cfg.mf_min_bound, cfg.mf_max_bound)
    return w, m1, m2, b1p * cfg.beta1_decay_rate, \
        b2p * cfg.beta2_decay_rate


def _np_adam_shared_dir(w, m1s, m2s, b1p, b2p, g, scale, cfg):
    """update_value_work (optimizer.cuh.h:340-386), numpy, one row —
    scalar moments shared across dims, stored value = mean of new."""
    eps = cfg.ada_epsilon
    ratio = cfg.learning_rate * np.sqrt(1.0 - b2p) / (1.0 - b1p)
    w = w.copy()
    n = len(w)
    sum1 = sum2 = 0.0
    for i in range(n):
        scaled = g[i] / scale
        nm1 = cfg.beta1_decay_rate * m1s + (1 - cfg.beta1_decay_rate) * scaled
        nm2 = cfg.beta2_decay_rate * m2s \
            + (1 - cfg.beta2_decay_rate) * scaled * scaled
        w[i] = np.clip(w[i] + ratio * (nm1 / (np.sqrt(nm2) + eps)),
                       cfg.mf_min_bound, cfg.mf_max_bound)
        sum1 += nm1
        sum2 += nm2
    return w, sum1 / n, sum2 / n, b1p * cfg.beta1_decay_rate, \
        b2p * cfg.beta2_decay_rate


def _row_state(mf, ext, u=3):
    z = lambda *s: jnp.zeros(s, jnp.float32)
    return RowState(show=z(u), clk=z(u), delta_score=z(u),
                    embed_w=z(u), embed_g2sum=z(u),
                    embedx_w=z(u, mf), embedx_g2sum=z(u),
                    mf_size=jnp.ones(u), opt_ext=z(u, ext))


@pytest.mark.parametrize("shared", [False, True])
def test_adam_update_matches_numpy(shared):
    mf = 4
    cfg = SparseAdamConfig(shared=shared, mf_create_thresholds=1e9,
                           learning_rate=0.01)
    ext = opt_ext_width(cfg, mf)
    rng = np.random.default_rng(0)
    u = 3
    rows = _row_state(mf, ext, u)
    # pre-seeded state: nonzero weights/moments/pows (a mid-training row)
    embed_w = rng.normal(size=u).astype(np.float32)
    embedx_w = rng.normal(size=(u, mf)).astype(np.float32)
    ext0 = np.zeros((u, ext), np.float32)
    ext0[:, 0] = rng.normal(size=u) * 0.1          # embed gsum (m1)
    eg2 = np.abs(rng.normal(size=u)).astype(np.float32) * 0.1  # embed m2
    ext0[:, 1] = 0.9 ** 3                          # embed b1p
    ext0[:, 2] = 0.999 ** 3                        # embed b2p
    ext0[:, 3] = 0.9 ** 2                          # embedx b1p
    ext0[:, 4] = 0.999 ** 2                        # embedx b2p
    if shared:
        ext0[:, 5] = rng.normal(size=u) * 0.1
        ext0[:, 6] = np.abs(rng.normal(size=u)) * 0.1
    else:
        ext0[:, 5:5 + mf] = rng.normal(size=(u, mf)) * 0.1
        ext0[:, 5 + mf:] = np.abs(rng.normal(size=(u, mf))) * 0.1
    rows = rows._replace(
        show=jnp.asarray(rng.uniform(1, 5, u).astype(np.float32)),
        embed_w=jnp.asarray(embed_w), embed_g2sum=jnp.asarray(eg2),
        embedx_w=jnp.asarray(embedx_w), opt_ext=jnp.asarray(ext0))
    g_show = rng.uniform(1, 3, u).astype(np.float32)
    g_clk = rng.uniform(0, 1, u).astype(np.float32)
    g_embed = rng.normal(size=u).astype(np.float32)
    g_embedx = rng.normal(size=(u, mf)).astype(np.float32)
    out = adam_update(rows, jnp.asarray(g_show), jnp.asarray(g_clk),
                      jnp.asarray(g_embed), jnp.asarray(g_embedx),
                      jnp.ones(u, bool), cfg, jax.random.PRNGKey(0))
    out = jax.device_get(out)
    for r in range(u):
        # embed direction (n=1); g2sum column doubles as adam m2
        w_ref, m1_ref, m2_ref, b1p_ref, b2p_ref = _np_adam_dir(
            np.array([embed_w[r]]), np.array([ext0[r, 0]]),
            np.array([eg2[r]]), ext0[r, 1], ext0[r, 2],
            np.array([g_embed[r]]), g_show[r], cfg)
        np.testing.assert_allclose(out.embed_w[r], w_ref[0], rtol=2e-5)
        np.testing.assert_allclose(out.opt_ext[r, 0], m1_ref[0], rtol=2e-5)
        np.testing.assert_allclose(out.embed_g2sum[r], m2_ref[0],
                                   rtol=2e-5)
        np.testing.assert_allclose(out.opt_ext[r, 1], b1p_ref, rtol=1e-6)
        np.testing.assert_allclose(out.opt_ext[r, 2], b2p_ref, rtol=1e-6)
        # embedx direction
        if shared:
            xw, xm1, xm2, xb1, xb2 = _np_adam_shared_dir(
                embedx_w[r], ext0[r, 5], ext0[r, 6], ext0[r, 3],
                ext0[r, 4], g_embedx[r], g_show[r], cfg)
            np.testing.assert_allclose(out.opt_ext[r, 5], xm1, rtol=2e-5)
            np.testing.assert_allclose(out.opt_ext[r, 6], xm2, rtol=2e-5)
        else:
            xw, xm1, xm2, xb1, xb2 = _np_adam_dir(
                embedx_w[r], ext0[r, 5:5 + mf], ext0[r, 5 + mf:],
                ext0[r, 3], ext0[r, 4], g_embedx[r], g_show[r], cfg)
            np.testing.assert_allclose(out.opt_ext[r, 5:5 + mf], xm1,
                                       rtol=2e-5)
            np.testing.assert_allclose(out.opt_ext[r, 5 + mf:], xm2,
                                       rtol=2e-5)
        np.testing.assert_allclose(out.embedx_w[r], xw, rtol=2e-5)
        np.testing.assert_allclose(out.opt_ext[r, 3], xb1, rtol=1e-6)
        np.testing.assert_allclose(out.opt_ext[r, 4], xb2, rtol=1e-6)


def test_adam_fresh_row_uses_creation_pows():
    """A never-touched row (show == 0, pows == 0) behaves as if its beta
    powers were initialized to the decay rates; mf creation writes the
    decay rates into the embedx pows (optimizer.cuh.h:285-289)."""
    mf = 2
    cfg = SparseAdamConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    ext = opt_ext_width(cfg, mf)
    rows = _row_state(mf, ext, u=1)._replace(mf_size=jnp.zeros(1))
    out = adam_update(rows, jnp.ones(1), jnp.ones(1), jnp.ones(1) * 0.5,
                      jnp.ones((1, mf)), jnp.ones(1, bool), cfg,
                      jax.random.PRNGKey(1))
    b1, b2 = cfg.beta1_decay_rate, cfg.beta2_decay_rate
    np.testing.assert_allclose(out.opt_ext[0, 1], b1 * b1, rtol=1e-6)
    np.testing.assert_allclose(out.opt_ext[0, 2], b2 * b2, rtol=1e-6)
    # mf was created this step: pows = decay rates, moments untouched
    assert float(out.mf_size[0]) == 1.0
    np.testing.assert_allclose(out.opt_ext[0, 3], b1, rtol=1e-6)
    np.testing.assert_allclose(out.opt_ext[0, 4], b2, rtol=1e-6)
    np.testing.assert_allclose(out.opt_ext[0, 5:], 0.0)


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_adam")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=1500,
                                 vocab_per_slot=40, seed=11)


def _make(files, cfg):
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=4096)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


@pytest.mark.parametrize("shared", [False, True])
def test_adam_e2e_learns(criteo_files, shared):
    cfg = SparseAdamConfig(shared=shared, mf_create_thresholds=0.0,
                           mf_initial_range=0.0, learning_rate=0.02)
    tr, ds = _make(criteo_files, cfg)
    first = tr.train_pass(ds)
    tr.reset_metrics()
    for _ in range(3):
        last = tr.train_pass(ds)
    assert np.isfinite(last["auc"])
    assert last["auc"] > max(first["auc"], 0.55)


def test_adam_resident_matches_streaming(criteo_files):
    cfg = SparseAdamConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                           learning_rate=0.02)
    tr_a, ds = _make(criteo_files, cfg)
    tr_b, _ = _make(criteo_files, cfg)
    ra = [tr_a.train_pass(ds) for _ in range(2)][-1]
    rb = [tr_b.train_pass_resident(ds) for _ in range(2)][-1]
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3)


def test_adam_save_load_roundtrip(criteo_files, tmp_path):
    cfg = SparseAdamConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    tr, ds = _make(criteo_files, cfg)
    tr.train_pass(ds)
    path = str(tmp_path / "adam_base.npz")
    tr.table.save_base(path)
    t2 = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                        unique_bucket_min=4096)
    t2.load(path)
    keys, rows1 = tr.table.index.items()
    rows2 = t2.index.lookup(keys)
    d1 = np.asarray(jax.device_get(tr.table.state.data))
    d2 = np.asarray(jax.device_get(t2.state.data))
    # full row parity including the optimizer extension block (slot col
    # lives host-side)
    cols = [c for c in range(d1.shape[1]) if c != 3]
    np.testing.assert_allclose(d1[np.ix_(rows1, cols)],
                               d2[np.ix_(rows2, cols)], rtol=1e-6)
    np.testing.assert_array_equal(tr.table.slot_host[rows1],
                                  t2.slot_host[rows2])
