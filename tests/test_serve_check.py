"""Tier-1 wiring of scripts/serve_check.py — the serve-while-training
gate (ISSUE 15): a ``train_stream`` loop publishes a base + ≥3 boundary
deltas while a concurrent serving thread (snapshot-isolated
``ServingModel`` + background ``ReloadLoop``) sustains queries; p99
latency and snapshot-staleness bounds hold throughout, every served
result is bit-consistent with exactly one published version, and both
chaos legs (flipped-byte delta mid-hot-reload → degrade-and-recover;
trainer SIGKILL mid-publish → serving unaffected) pass — deterministic
across two identically-seeded runs. The standalone script prints the
full outcome and exits nonzero on any divergence."""

import os

from scripts.serve_check import run_serve_check


def test_serve_check_gate_deterministic(tmp_path):
    outs = []
    for run in (1, 2):
        wd = str(tmp_path / f"run{run}")
        os.makedirs(wd)
        outs.append(run_serve_check(wd, seed=7))
    out = outs[0]
    # stream leg: 1 base + >=3 deltas published while serving held its
    # bounds; served results matched exactly one version's oracle
    assert out["stream_kinds"].count("base") == 1
    assert out["stream_kinds"].count("delta") >= 3
    assert out["stream_served_all_consistent"]
    assert out["stream_preds_consistent"]
    assert out["stream_p99_ok"] and out["stream_staleness_ok"]
    assert out["stream_final_aid"] == out["stream_versions"][-1]
    # every published version answers a DISTINCT lookup digest — the
    # consistency check cannot pass vacuously
    oracle = out["stream_lookup_oracle"]
    assert len(set(oracle.values())) == len(oracle)
    # /readyz: refused before the first adoption, passed after
    assert out["readyz_transition"] == [False, True]
    # tiered leg: SSD-spilled rows served bit-exactly across >=2 swaps
    assert out["tiered_consistent"] and out["tiered_swaps_observed"]
    assert out["tiered_writer_digest"] == out["tiered_replay_digest"]
    assert out["tiered_spill_digest"]
    # chaos legs
    assert out["corrupt_degraded_loud"] and out["corrupt_recovered"]
    assert out["corrupt_served_prior"] and out["corrupt_consistent"]
    assert out["kill_carcass_swept"] and out["kill_serving_unaffected"]
    assert out["kill_consistent"]
    assert out["reload_adopted_nonzero"]
    assert out["reload_degraded_nonzero"]
    # seeded chaos is reproducible: outcome byte-identical across runs
    assert outs[0] == outs[1]
