"""Dense-param mode tests (reference: boxps_worker.cc SyncParam :1191,
BoxPSAsynDenseTable :61-370)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.train.dense_modes import AsyncDenseTable, KStepParamSync


def test_k_step_sync_stacked_mean():
    # 4 replicas of a 2-leaf pytree, distinct values
    params = {
        "w": jnp.arange(8, dtype=jnp.float32).reshape(4, 2),
        "b": jnp.array([[1.0], [3.0], [5.0], [7.0]]),
    }
    sync = KStepParamSync(k=3)
    p, did = sync.maybe_sync(params)
    assert not did
    p, did = sync.maybe_sync(p)
    assert not did
    p, did = sync.maybe_sync(p)
    assert did
    np.testing.assert_allclose(np.asarray(p["b"]),
                               np.full((4, 1), 4.0))
    want_w = np.tile(np.asarray(params["w"]).mean(0), (4, 1))
    np.testing.assert_allclose(np.asarray(p["w"]), want_w)


def test_k_step_sync_on_mesh():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("dp",))
    params = {"w": jnp.arange(8, dtype=jnp.float32).reshape(4, 2)}
    sync = KStepParamSync(k=1, mesh=mesh, axis="dp")
    p, did = sync.maybe_sync(params)
    assert did
    want = np.tile(np.arange(8, dtype=np.float32).reshape(4, 2).mean(0),
                   (4, 1))
    np.testing.assert_allclose(np.asarray(p["w"]), want)


def test_k_step_rejects_bad_k():
    with pytest.raises(ValueError):
        KStepParamSync(k=0)


def test_async_dense_table_adam_converges():
    # minimize ||p||^2 via grads 2p: async Adam should shrink the params
    params = {"w": jnp.full((4,), 10.0), "b": jnp.full((2,), -10.0)}
    table = AsyncDenseTable(params, lr=0.5)
    table.start()
    try:
        for _ in range(200):
            cur = table.pull()
            grads = jax.tree.map(lambda x: 2.0 * x, cur)
            table.push(grads)
        applied = table.drain()
    finally:
        table.stop()
    assert applied == 200
    final = table.pull()
    assert np.abs(np.asarray(final["w"])).max() < 1.0
    assert np.abs(np.asarray(final["b"])).max() < 1.0


def test_async_dense_table_summary_accumulates():
    params = {"fc": jnp.zeros((3,)), "data_norm_summary": jnp.zeros((2,))}
    table = AsyncDenseTable(params, lr=0.1)
    table.start()
    try:
        g = {"fc": jnp.ones((3,)), "data_norm_summary": jnp.array([1.0, 2.0])}
        table.push(g)
        table.push(g)
        table.drain()
    finally:
        table.stop()
    final = table.pull()
    # summary leaves accumulate ps += grad (twice)
    np.testing.assert_allclose(np.asarray(final["data_norm_summary"]),
                               [2.0, 4.0])
    # adam leaves move opposite the gradient
    assert (np.asarray(final["fc"]) < 0).all()
