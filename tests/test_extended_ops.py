"""Numpy-reference tests for the extended CTR op set (mirrors the
reference's OpTest pattern: test_rank_attention_op.py, test_batch_fc_op.py,
test_shuffle_batch_op.py, …)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.ops import (
    batch_fc, cross_norm_hadamard, cross_norm_update, data_norm,
    data_norm_update, fused_seqpool_cvm_with_conv, init_cross_norm_summary,
    init_data_norm_summary, partial_concat, partial_sum, rank_attention,
    rank_attention2, scaled_fc, scaled_int8fc, shuffle_batch,
    unshuffle_batch,
)


def ref_rank_attention(x, rank_offset, param, max_rank):
    n, d = x.shape
    p = param.shape[-1]
    param3 = param.reshape(max_rank * max_rank, d, p)
    out = np.zeros((n, p), np.float32)
    for i in range(n):
        own = rank_offset[i, 0] - 1
        if own < 0:
            continue
        for k in range(max_rank):
            faster = rank_offset[i, 1 + 2 * k] - 1
            idx = rank_offset[i, 2 + 2 * k]
            if faster < 0:
                continue
            blk = param3[own * max_rank + faster]
            out[i] += x[idx] @ blk
    return out


def test_rank_attention_matches_reference():
    rng = np.random.default_rng(0)
    n, d, p, mr = 6, 4, 3, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    param = rng.normal(size=(mr * mr * d, p)).astype(np.float32)
    ro = np.zeros((n, 1 + 2 * mr), np.int32)
    for i in range(n):
        ro[i, 0] = rng.integers(0, mr + 1)  # 0 = invalid
        for k in range(mr):
            if rng.random() < 0.7:
                ro[i, 1 + 2 * k] = rng.integers(1, mr + 1)
                ro[i, 2 + 2 * k] = rng.integers(0, n)
    got = np.asarray(rank_attention(jnp.asarray(x), jnp.asarray(ro),
                                    jnp.asarray(param), mr))
    np.testing.assert_allclose(got, ref_rank_attention(x, ro, param, mr),
                               rtol=1e-5, atol=1e-6)


def test_rank_attention2_param_only_grads():
    """rank_attention2 (rank_attention_op.cc:179): forward identical to
    v1; gradients flow ONLY to RankParam (kernel_rank_back_propagate
    accumulates out_para_grad, X gets none)."""
    rng = np.random.default_rng(4)
    n, d, p, mr = 6, 4, 3, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    param = rng.normal(size=(mr * mr * d, p)).astype(np.float32)
    ro = np.zeros((n, 1 + 2 * mr), np.int32)
    for i in range(n):
        ro[i, 0] = rng.integers(0, mr + 1)
        for k in range(mr):
            if rng.random() < 0.7:
                ro[i, 1 + 2 * k] = rng.integers(1, mr + 1)
                ro[i, 2 + 2 * k] = rng.integers(0, n)
    got = np.asarray(rank_attention2(jnp.asarray(x), jnp.asarray(ro),
                                     jnp.asarray(param), mr))
    np.testing.assert_allclose(got, ref_rank_attention(x, ro, param, mr),
                               rtol=1e-5, atol=1e-6)

    def loss(xx, pp):
        return jnp.sum(rank_attention2(xx, jnp.asarray(ro), pp, mr) ** 2)

    gx, gp = jax.grad(loss, argnums=(0, 1))(jnp.asarray(x),
                                            jnp.asarray(param))
    np.testing.assert_allclose(np.asarray(gx), 0.0)  # X gets NO grads
    # param grads match the transcription of kernel_rank_back_propagate
    out = ref_rank_attention(x, ro, param, mr)
    g_out = 2.0 * out
    ref_gp = np.zeros_like(param.reshape(mr * mr, d, p))
    for i in range(n):
        own = ro[i, 0] - 1
        if own < 0:
            continue
        for k in range(mr):
            faster = ro[i, 1 + 2 * k] - 1
            idx = ro[i, 2 + 2 * k]
            if faster < 0:
                continue
            ref_gp[own * mr + faster] += np.outer(x[idx], g_out[i])
    np.testing.assert_allclose(np.asarray(gp).reshape(mr * mr, d, p),
                               ref_gp, rtol=1e-4, atol=1e-5)


def _einsum_rank_attention(x, ro, rank_param, max_rank,
                           enable_input_bp=False):
    """The HISTORICAL einsum formulation (pre-ISSUE 13): gathers
    ``param[block]`` into an [N, K, D, P] tensor — kept here as the
    numeric reference the block-grouped fallback is pinned against."""
    n, d = x.shape
    if rank_param.ndim == 2:
        p = rank_param.shape[-1]
        param = rank_param.reshape(max_rank * max_rank, d, p)
    else:
        param = rank_param
    if not enable_input_bp:
        x = jax.lax.stop_gradient(x)
    own = ro[:, 0] - 1
    ks = jnp.arange(max_rank)
    faster = ro[:, 1 + 2 * ks] - 1
    idx = ro[:, 2 + 2 * ks]
    valid = (own[:, None] >= 0) & (faster >= 0)
    x_k = jnp.where(valid[..., None], x[jnp.clip(idx, 0, n - 1)], 0.0)
    block = jnp.clip(own[:, None], 0, max_rank - 1) * max_rank \
        + jnp.clip(faster, 0, max_rank - 1)
    return jnp.einsum("nkd,nkdp->np", x_k, param[block])


def test_rank_attention_block_grouped_matches_old_einsum():
    """ISSUE 13 satellite: the rewritten block-grouped XLA fallback is
    numerically pinned to the historical einsum (forward AND grads) —
    the memory-blowup fix must not move the math."""
    rng = np.random.default_rng(10)
    n, d, p, mr = 41, 9, 6, 3
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    param = jnp.asarray(
        rng.normal(size=(mr * mr * d, p)).astype(np.float32))
    ro = np.zeros((n, 1 + 2 * mr), np.int32)
    ro[:, 0] = rng.integers(0, mr + 1, size=n)
    for k in range(mr):
        on = rng.random(n) < 0.6
        ro[:, 1 + 2 * k] = np.where(on, rng.integers(1, mr + 1, size=n),
                                    0)
        ro[:, 2 + 2 * k] = rng.integers(0, n, size=n)
    ro = jnp.asarray(ro)
    got = np.asarray(rank_attention(x, ro, param, mr))
    want = np.asarray(_einsum_rank_attention(x, ro, param, mr))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    w = jnp.asarray(rng.normal(size=(n, p)).astype(np.float32))
    g_new = jax.grad(lambda xx, pp: jnp.sum(rank_attention(
        xx, ro, pp, mr, enable_input_bp=True) * w), argnums=(0, 1))(
            x, param)
    g_old = jax.grad(lambda xx, pp: jnp.sum(_einsum_rank_attention(
        xx, ro, pp, mr, enable_input_bp=True) * w), argnums=(0, 1))(
            x, param)
    for a, b in zip(g_new, g_old):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_rank_attention_fallback_never_builds_nkdp():
    """The blowup fix itself, pinned in HLO: at a production-ish shape
    the compiled default (flag-off) program contains NO [N, K, D, P]
    tensor (the old ``param[block]`` gather materialized f32[N,3,D,P] —
    ~800 MB at the real N=4096, D=P=128)."""
    n, d, p, mr = 512, 64, 32, 3
    nkdp = f"tensor<{n}x{mr}x{d}x{p}xf32>"  # StableHLO shape spelling

    def lowered(fn):
        return jax.jit(fn).lower(
            jax.ShapeDtypeStruct((n, d), jnp.float32),
            jax.ShapeDtypeStruct((n, 1 + 2 * mr), jnp.int32),
            jax.ShapeDtypeStruct((mr * mr, d, p), jnp.float32)).as_text()

    txt = lowered(lambda x, ro, pm: rank_attention(x, ro, pm, mr))
    assert nkdp not in txt, \
        "rank_attention fallback still materializes the [N,K,D,P] gather"
    # the historical einsum DOES build it — prove the probe detects it
    txt_old = lowered(
        lambda x, ro, pm: _einsum_rank_attention(x, ro, pm, mr))
    assert nkdp in txt_old


def test_batch_fc_modes():
    rng = np.random.default_rng(1)
    s, n, i, o = 3, 5, 4, 2
    x = rng.normal(size=(s, n, i)).astype(np.float32)
    w = rng.normal(size=(s, i, o)).astype(np.float32)
    b = rng.normal(size=(s, o)).astype(np.float32)
    got = np.asarray(batch_fc(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)))
    ref = np.einsum("sni,sio->sno", x, w) + b[:, None, :]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    # batchcount mode with transposed weights
    xf = x.reshape(s * n, i)
    wt = np.swapaxes(w, 1, 2).copy()
    got2 = np.asarray(batch_fc(jnp.asarray(xf), jnp.asarray(wt),
                               jnp.asarray(b), batchcount=s,
                               transpose_weight=True))
    np.testing.assert_allclose(got2, ref.reshape(s * n, o), rtol=1e-5)


def test_shuffle_roundtrip_and_grad():
    x = jnp.arange(12.0).reshape(6, 2)
    y, idx = shuffle_batch(x, jax.random.PRNGKey(0))
    assert sorted(np.asarray(y)[:, 0].tolist()) == \
        sorted(np.asarray(x)[:, 0].tolist())
    np.testing.assert_allclose(np.asarray(unshuffle_batch(y, idx)),
                               np.asarray(x))
    # grad of sum(w*shuffled) lands back on the right rows
    w = jnp.arange(6.0)[:, None]

    def loss(x):
        y, _ = shuffle_batch(x, jax.random.PRNGKey(0))
        return jnp.sum(y * w)

    g = np.asarray(jax.grad(loss)(x))
    inv = np.argsort(np.asarray(idx))
    np.testing.assert_allclose(g, np.asarray(w)[inv].repeat(2, axis=1))


def test_partial_ops():
    a = jnp.arange(12.0).reshape(3, 4)
    b = a * 10
    got = np.asarray(partial_concat([a, b], 1, 2))
    np.testing.assert_allclose(got, np.concatenate(
        [np.asarray(a)[:, 1:3], np.asarray(b)[:, 1:3]], axis=1))
    got2 = np.asarray(partial_sum([a, b], 1, 2))
    np.testing.assert_allclose(got2, np.asarray(a)[:, 1:3] * 11)
    # length -1 = to end; negative start
    np.testing.assert_allclose(np.asarray(partial_concat([a], -2, -1)),
                               np.asarray(a)[:, 2:])


def test_data_norm_forward_and_update():
    rng = np.random.default_rng(2)
    x = rng.normal(2.0, 3.0, size=(50, 4)).astype(np.float32)
    s = init_data_norm_summary(4)
    y = np.asarray(data_norm(jnp.asarray(x), s))
    mean = np.asarray(s.batch_sum) / np.asarray(s.batch_size)
    scale = np.sqrt(np.asarray(s.batch_size) /
                    np.asarray(s.batch_square_sum))
    np.testing.assert_allclose(y, (x - mean) * scale, rtol=1e-5)
    # after many updates the normalized output approaches zero-mean/unit-var
    for _ in range(200):
        s = data_norm_update(s, jnp.asarray(x), decay=0.9)
    y2 = np.asarray(data_norm(jnp.asarray(x), s))
    assert abs(y2.mean()) < 0.1
    assert 0.5 < y2.std() < 1.5


def test_data_norm_slot_dim_skips_no_show():
    s = init_data_norm_summary(4)
    x = np.array([[0.0, 5.0, 1.0, 7.0],   # slot0 show=0 → passthrough
                  [1.0, 5.0, 0.0, 7.0]], np.float32)  # slot1 show=0
    # bias the summary so normalization actually changes values
    s = data_norm_update(s, jnp.asarray(np.full((10, 4), 3.0, np.float32)),
                         decay=0.5)
    y = np.asarray(data_norm(jnp.asarray(x), s, slot_dim=2))
    np.testing.assert_allclose(y[0, :2], x[0, :2])  # skipped
    np.testing.assert_allclose(y[1, 2:], x[1, 2:])  # skipped
    assert not np.allclose(y[1, :2], x[1, :2])      # normalized


def test_cross_norm_hadamard_layout():
    rng = np.random.default_rng(3)
    b, n, d = 4, 2, 3
    x = rng.normal(size=(b, 2 * n * d)).astype(np.float32)
    s = init_cross_norm_summary(n, d)
    y = np.asarray(cross_norm_hadamard(jnp.asarray(x), s, n, d))
    assert y.shape == (b, n * (3 * d + 1))
    # with identity summary (mean 0, scale 1): block = [a, b, a*b, a.b]
    pairs = x.reshape(b, n, 2, d)
    blk0 = y[:, :3 * d + 1]
    np.testing.assert_allclose(blk0[:, :d], pairs[:, 0, 0], rtol=1e-5)
    np.testing.assert_allclose(blk0[:, d:2 * d], pairs[:, 0, 1], rtol=1e-5)
    np.testing.assert_allclose(blk0[:, 2 * d:3 * d],
                               pairs[:, 0, 0] * pairs[:, 0, 1], rtol=1e-5)
    np.testing.assert_allclose(
        blk0[:, 3 * d], np.sum(pairs[:, 0, 0] * pairs[:, 0, 1], -1),
        rtol=1e-5)
    s2 = cross_norm_update(s, jnp.asarray(x), n, d, decay=0.5)
    assert float(np.asarray(s2.batch_size)[0]) > float(
        np.asarray(s.batch_size)[0]) * 0.5


def test_scaled_fc_matches_fp32():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(8, 16)).astype(np.float32)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    b = rng.normal(size=(8,)).astype(np.float32)
    got = np.asarray(scaled_fc(jnp.asarray(x), jnp.asarray(w),
                               jnp.asarray(b), 8.0, 8.0))
    ref = x @ w + b[None, :]
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)  # bf16
    got8 = np.asarray(scaled_int8fc(jnp.asarray(x), jnp.asarray(w),
                                    jnp.asarray(b), 16.0, 16.0))
    np.testing.assert_allclose(got8, ref, rtol=0.2, atol=0.5)  # int8


def test_seqpool_cvm_with_conv():
    b_sz, s_num, d = 2, 2, 5  # 3 cvm + 2 embed
    vals = np.zeros((8, d), np.float32)
    vals[0] = [2, 1, 1, 0.5, 0.5]
    vals[1] = [1, 0, 0, 0.3, 0.3]
    segs = np.full(8, b_sz * s_num, np.int32)
    segs[0], segs[1] = 0, 3
    bcvm = np.ones((b_sz, 3), np.float32)
    out = np.asarray(fused_seqpool_cvm_with_conv(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(bcvm),
        b_sz, s_num, True, False))
    assert out.shape == (b_sz, s_num, d)
    np.testing.assert_allclose(out[0, 0, 0], np.log1p(2), rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, 1], np.log1p(1), rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, 2], np.log1p(1) - np.log1p(1),
                               rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, 3:], [0.5, 0.5], rtol=1e-5)
    # show_filter drops the show column
    out2 = np.asarray(fused_seqpool_cvm_with_conv(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(bcvm),
        b_sz, s_num, True, True))
    assert out2.shape == (b_sz, s_num, d - 1)
    np.testing.assert_allclose(out2[0, 0, 0], np.log1p(1), rtol=1e-5)
    # backward: cvm dims get batch values, embed dims broadcast
    def loss(v):
        return jnp.sum(fused_seqpool_cvm_with_conv(
            v, jnp.asarray(segs), jnp.asarray(bcvm), b_sz, s_num, True,
            False))
    g = np.asarray(jax.grad(loss)(jnp.asarray(vals)))
    np.testing.assert_allclose(g[0, :3], bcvm[0], rtol=1e-6)
    np.testing.assert_allclose(g[0, 3:], 1.0)
    np.testing.assert_array_equal(g[2], 0)  # padding
