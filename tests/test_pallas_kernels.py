"""Pallas kernel correctness vs XLA references (interpret mode on CPU)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         os.pardir))

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.ops.pallas_kernels import (
    CVM_CONV, CVM_FULL, CVM_NONE, CVM_SHOW, fused_embed_pool_cvm,
    fused_pool_cvm_forward, gather_rows, scatter_rows, segment_gather_mxu,
    segment_sum_mxu,
)


def test_gather_rows_matches_take():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 64, size=37).astype(np.int32))
    out = gather_rows(table, rows)
    np.testing.assert_allclose(np.asarray(out), np.asarray(table)[rows])


def test_gather_rows_wide():
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 256, size=500).astype(np.int32))
    out = gather_rows(table, rows)
    np.testing.assert_allclose(np.asarray(out), np.asarray(table)[rows])


def test_scatter_rows_matches_set():
    rng = np.random.default_rng(2)
    table = rng.normal(size=(64, 16)).astype(np.float32)
    rows = rng.permutation(64)[:20].astype(np.int32)
    vals = rng.normal(size=(20, 16)).astype(np.float32)
    out = scatter_rows(jnp.asarray(table), jnp.asarray(rows),
                       jnp.asarray(vals))
    want = table.copy()
    want[rows] = vals
    np.testing.assert_allclose(np.asarray(out), want)


def test_scatter_rows_under_jit():
    rng = np.random.default_rng(3)
    table = rng.normal(size=(32, 8)).astype(np.float32)
    rows = np.array([5, 9, 31], np.int32)
    vals = rng.normal(size=(3, 8)).astype(np.float32)
    f = jax.jit(scatter_rows)
    out = f(jnp.asarray(table), jnp.asarray(rows), jnp.asarray(vals))
    want = table.copy()
    want[rows] = vals
    np.testing.assert_allclose(np.asarray(out), want)


@pytest.mark.parametrize("k,s", [(100, 40), (700, 200), (7, 3), (1500, 3000)])
def test_segment_sum_mxu(k, s):
    rng = np.random.default_rng(4)
    vals = rng.normal(size=(k, 11)).astype(np.float32)
    # contract: segments nondecreasing (batch builder order); s > k cases
    # leave whole output blocks with no keys (must read back zero)
    segs = np.sort(rng.integers(0, s, size=k)).astype(np.int32)
    got = segment_sum_mxu(jnp.asarray(vals), jnp.asarray(segs), s)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(segs),
                               num_segments=s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_segment_sum_mxu_gap_blocks_zero():
    # keys only in the last segment range → earlier output blocks unvisited
    vals = jnp.ones((8, 4), jnp.float32)
    segs = jnp.full((8,), 999, jnp.int32)
    got = np.asarray(segment_sum_mxu(vals, segs, 1000))
    assert got[999].sum() == 32.0
    np.testing.assert_allclose(got[:999], 0.0)


def test_segment_sum_mxu_drop_negative():
    vals = jnp.ones((4, 3), jnp.float32)
    segs = jnp.asarray([0, 1, -1, -1], jnp.int32)
    got = segment_sum_mxu(vals, segs, 2)
    np.testing.assert_allclose(np.asarray(got), np.ones((2, 3)))


def test_segment_sum_mxu_leading_and_interleaved_drops():
    vals = jnp.asarray(np.arange(20, dtype=np.float32).reshape(5, 4))
    segs = jnp.asarray([-1, 0, -1, 0, 1], jnp.int32)
    got = segment_sum_mxu(vals, segs, 2)
    want = jax.ops.segment_sum(
        jnp.where(jnp.asarray([0, 1, 0, 1, 1], bool)[:, None], vals, 0),
        jnp.asarray([0, 0, 0, 0, 1], jnp.int32), num_segments=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_segment_sum_mxu_grad():
    rng = np.random.default_rng(6)
    vals = jnp.asarray(rng.normal(size=(50, 5)).astype(np.float32))
    segs = jnp.asarray(np.sort(rng.integers(0, 12, size=50)).astype(np.int32))
    w = jnp.asarray(rng.normal(size=(12, 5)).astype(np.float32))
    f = lambda v: (segment_sum_mxu(v, segs, 12) * w).sum()
    g = jax.grad(f)(vals)
    want = jax.grad(
        lambda v: (jax.ops.segment_sum(v, segs, num_segments=12) * w).sum()
    )(vals)
    np.testing.assert_allclose(np.asarray(g), np.asarray(want), rtol=1e-5)


def test_fused_seqpool_concat_grad_with_pallas():
    from paddlebox_tpu.ops import fused_seqpool_concat
    rng = np.random.default_rng(7)
    B, S, K = 3, 4, 30
    vals = jnp.asarray(rng.normal(size=(K, 6)).astype(np.float32))
    segs = jnp.asarray(np.sort(rng.integers(0, B * S, size=K)).astype(np.int32))
    f = lambda v: fused_seqpool_concat(v, segs, B, S).sum()
    want = jax.grad(f)(vals)
    with flags_scope(use_pallas_seqpool=True):
        got = jax.grad(f)(vals)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_seqpool_cvm_pallas_backend_matches():
    from paddlebox_tpu.ops import fused_seqpool_cvm
    rng = np.random.default_rng(5)
    B, S, MF, K = 4, 3, 8, 50
    vals = jnp.asarray(rng.normal(size=(K, 3 + MF)).astype(np.float32))
    segs = jnp.asarray(rng.integers(0, B * S, size=K).astype(np.int32))
    sc = jnp.asarray(np.abs(rng.normal(size=(B, 2))).astype(np.float32))
    ref = fused_seqpool_cvm(vals, segs, sc, B, S)
    with flags_scope(use_pallas_seqpool=True):
        got = fused_seqpool_cvm(vals, segs, sc, B, S)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_table_pull_push_with_pallas_flags():
    from paddlebox_tpu.data.batch import SlotBatch
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig

    def run(**flags):
        with flags_scope(**flags):
            t = EmbeddingTable(mf_dim=8, capacity=256,
                               cfg=SparseSGDConfig(), seed=7)
            keys = np.array([3, 9, 3, 77, 9, 1024], np.uint64)
            batch = SlotBatch(
                keys=keys, num_keys=len(keys),
                segments=np.arange(len(keys), dtype=np.int32),
                dense=np.zeros((2, 1), np.float32),
                label=np.zeros(2, np.float32),
                show=np.ones(2, np.float32), clk=np.zeros(2, np.float32),
                batch_size=2, num_slots=3)
            idx = t.prepare(batch)
            vals = t.pull(idx)
            g = jnp.ones((len(keys), 3 + 8), jnp.float32) * 0.1
            t.push(idx, g)
            return np.asarray(vals), np.asarray(t.pull(idx))

    v0, p0 = run()
    v1, p1 = run(use_pallas_gather=True)
    np.testing.assert_allclose(v0, v1, rtol=1e-6)
    np.testing.assert_allclose(p0, p1, rtol=1e-6)


# ---------------------------------------------------------------------------
# segment_gather_mxu (transposed one-hot backward kernel — ISSUE 12)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,k", [(40, 300), (12, 50), (200, 700), (5, 4)])
def test_segment_gather_mxu_matches_take(n, k):
    rng = np.random.default_rng(8)
    src = rng.normal(size=(n, 9)).astype(np.float32)
    ids = np.sort(rng.integers(0, n, size=k)).astype(np.int32)
    got = np.asarray(segment_gather_mxu(jnp.asarray(src),
                                        jnp.asarray(ids)))
    np.testing.assert_array_equal(got, src[ids])  # bitwise — a gather


def test_segment_gather_mxu_drops_and_oob_zero():
    rng = np.random.default_rng(9)
    src = rng.normal(size=(16, 5)).astype(np.float32)
    ids = np.sort(np.concatenate(
        [rng.integers(0, 16, size=20), [16, 40, 1000]])).astype(np.int32)
    ids[0] = -1  # drop marker anywhere
    got = np.asarray(segment_gather_mxu(jnp.asarray(src),
                                        jnp.asarray(ids)))
    ok = (ids >= 0) & (ids < 16)
    want = np.where(ok[:, None], src[np.clip(ids, 0, 15)], 0.0)
    np.testing.assert_array_equal(got, want)


def test_segment_gather_mxu_under_jit_and_empty():
    src = jnp.ones((8, 3), jnp.float32)
    ids = jnp.full((12,), -1, jnp.int32)  # all drops
    got = jax.jit(segment_gather_mxu)(src, ids)
    np.testing.assert_array_equal(np.asarray(got), np.zeros((12, 3)))


# ---------------------------------------------------------------------------
# fused_embed_pool_cvm (pool + CVM in one Pallas pass — the tentpole)
# ---------------------------------------------------------------------------

def _fused_case(k=700, B=5, S=3, mf=6, seed=0, zipf=False, pads=30):
    from paddlebox_tpu.ops import fused_seqpool_cvm
    rng = np.random.default_rng(seed)
    d = 2 + mf
    vals = rng.normal(size=(k, d)).astype(np.float32)
    vals[:, :2] = np.abs(vals[:, :2])  # show/clk columns nonnegative
    if zipf:
        lens = np.minimum(rng.zipf(1.5, size=B * S), 24)
        ids = np.repeat(np.arange(B * S, dtype=np.int32), lens)[:k - pads]
        segs = np.full(k, B * S, np.int32)
        segs[:len(ids)] = ids
    else:
        segs = np.sort(rng.integers(0, B * S, size=k)).astype(np.int32)
        if pads:
            segs[-pads:] = B * S  # partial-batch tail padding
    sc = np.abs(rng.normal(size=(B, 2))).astype(np.float32)
    return (jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(sc),
            fused_seqpool_cvm)


@pytest.mark.parametrize("zipf", [False, True])
@pytest.mark.parametrize("use_cvm,need_filter,pad_value", [
    (True, False, 0.0), (True, True, 0.0), (False, False, 0.0),
    (True, False, 0.25), (False, True, 0.5),
])
def test_fused_embed_pool_cvm_matches_composition(use_cvm, need_filter,
                                                  pad_value, zipf):
    B, S = 5, 3
    vals, segs, sc, composition = _fused_case(zipf=zipf)
    ref = composition(vals, segs, sc, B, S, use_cvm, 2, pad_value,
                      need_filter, 0.2, 1.0, 0.96, 0)
    got = fused_embed_pool_cvm(vals, segs, sc, B, S, use_cvm, 2,
                               pad_value, need_filter, 0.2, 1.0, 0.96)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


def test_fused_embed_pool_cvm_empty_segments():
    # every key is padding → CVM of an all-zero pool (the PaddingZeros
    # contract) — and no uninitialized output block may leak through
    B, S = 3, 4
    vals = jnp.ones((64, 6), jnp.float32)
    segs = jnp.full((64,), B * S, jnp.int32)
    sc = jnp.ones((B, 2), jnp.float32)
    got = np.asarray(fused_embed_pool_cvm(vals, segs, sc, B, S))
    np.testing.assert_allclose(got, np.zeros((B, S, 6)), atol=1e-7)


@pytest.mark.parametrize("use_cvm,need_filter", [
    (True, False), (True, True), (False, False)])
def test_fused_embed_pool_cvm_grads_bitwise(use_cvm, need_filter):
    """custom_vjp grads vs jax.grad of the XLA composition: the
    transposed one-hot backward is bitwise a gather, so given the same
    upstream cotangent the pushed grads match EXACTLY."""
    B, S = 5, 3
    vals, segs, sc, composition = _fused_case(seed=4, zipf=True)
    rng = np.random.default_rng(5)
    out_shape = np.asarray(composition(
        vals, segs, sc, B, S, use_cvm, 2, 0.0, need_filter,
        0.2, 1.0, 0.96, 0)).shape
    w = jnp.asarray(rng.normal(size=out_shape).astype(np.float32))

    def f_ref(v):
        return jnp.sum(composition(v, segs, sc, B, S, use_cvm, 2, 0.0,
                                   need_filter, 0.2, 1.0, 0.96, 0) * w)

    def f_new(v):
        return jnp.sum(fused_embed_pool_cvm(
            v, segs, sc, B, S, use_cvm, 2, 0.0, need_filter,
            0.2, 1.0, 0.96) * w)

    g_ref = np.asarray(jax.grad(f_ref)(vals))
    g_new = np.asarray(jax.grad(f_new)(vals))
    np.testing.assert_array_equal(g_new, g_ref)


def test_fused_embed_pool_cvm_wide_cvm_offset_grads():
    """cvm_offset > 2 with use_cvm: the output head is still the TWO
    transformed columns, so the backward must slice at 2 (not at
    cvm_offset) — regression for the head-width crash."""
    B, S, K, d, co = 2, 2, 40, 6, 3
    rng = np.random.default_rng(11)
    vals = jnp.asarray(np.abs(rng.normal(size=(K, d))).astype(np.float32))
    segs = jnp.asarray(np.sort(rng.integers(0, B * S, size=K))
                       .astype(np.int32))
    sc = jnp.asarray(np.abs(rng.normal(size=(B, co))).astype(np.float32))
    out = fused_embed_pool_cvm(vals, segs, sc, B, S, True, co)
    assert out.shape == (B, S, 2 + d - co)
    g = np.asarray(jax.grad(
        lambda v: jnp.sum(fused_embed_pool_cvm(v, segs, sc, B, S, True,
                                               co)))(vals))
    assert g.shape == (K, d)
    ins = np.minimum(np.asarray(segs) // S, B - 1)
    np.testing.assert_allclose(g[:, :co], np.asarray(sc)[ins])  # head
    np.testing.assert_allclose(g[:, co:], 1.0)                  # embedx


def test_fused_pool_cvm_forward_modes():
    """Raw forward head modes against hand-built references."""
    rng = np.random.default_rng(6)
    B, S, d = 2, 2, 7
    k = 40
    vals = np.abs(rng.normal(size=(k, d))).astype(np.float32)
    segs = np.sort(rng.integers(0, B * S, size=k)).astype(np.int32)
    pooled = np.zeros((B * S, d), np.float32)
    np.add.at(pooled, segs, vals)
    pooled = pooled.reshape(B, S, d)
    j = lambda x: jnp.asarray(x)
    # CVM_SHOW (clk_filter): [log1p(show), embedx…]
    got = np.asarray(fused_pool_cvm_forward(
        j(vals), j(segs), None, B, S, cvm_mode=CVM_SHOW, cvm_offset=2))
    want = np.concatenate([np.log1p(pooled[..., :1]), pooled[..., 2:]],
                          axis=-1)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)
    # CVM_CONV: [log1p(show), log1p(clk), log1p(conv)-log1p(clk), …]
    got = np.asarray(fused_pool_cvm_forward(
        j(vals), j(segs), None, B, S, cvm_mode=CVM_CONV, cvm_offset=3))
    want = np.concatenate(
        [np.log1p(pooled[..., 0:1]), np.log1p(pooled[..., 1:2]),
         np.log1p(pooled[..., 2:3]) - np.log1p(pooled[..., 1:2]),
         pooled[..., 3:]], axis=-1)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)
    # CVM_NONE + ets: width cut only
    got = np.asarray(fused_pool_cvm_forward(
        j(vals), j(segs), None, B, S, cvm_mode=CVM_NONE, cvm_offset=2,
        ets=1))
    np.testing.assert_allclose(got, pooled[..., 3:], rtol=3e-5, atol=3e-5)
    assert CVM_FULL == 1


def test_fused_pool_cvm_keep_mask_folds_into_matmul():
    B, S, k, d = 2, 2, 24, 5
    rng = np.random.default_rng(7)
    vals = np.abs(rng.normal(size=(k, d))).astype(np.float32)
    segs = np.sort(rng.integers(0, B * S, size=k)).astype(np.int32)
    keep = (rng.random(k) < 0.5).astype(np.float32)
    got = np.asarray(fused_pool_cvm_forward(
        jnp.asarray(vals), jnp.asarray(segs), jnp.asarray(keep), B, S,
        cvm_mode=CVM_NONE, cvm_offset=0))
    pooled = np.zeros((B * S, d), np.float32)
    np.add.at(pooled, segs, vals * keep[:, None])
    np.testing.assert_allclose(got, pooled.reshape(B, S, d),
                               rtol=3e-5, atol=3e-5)


# ---------------------------------------------------------------------------
# satellites: dead-flag regression + DMA demotion (ISSUE 12)
# ---------------------------------------------------------------------------

def test_use_pallas_flags_referenced_outside_config():
    """Every use_pallas_* flag must be READ somewhere outside config.py
    — a defined-but-never-consumed dispatch flag is a silent no-op
    (the ISSUE 12 dead-flag class)."""
    import dataclasses
    import pathlib
    import re

    import paddlebox_tpu
    from paddlebox_tpu.config import Flags
    names = [f.name for f in dataclasses.fields(Flags)
             if f.name.startswith("use_pallas_")]
    assert names, "expected at least one use_pallas_* flag"
    pkg = pathlib.Path(paddlebox_tpu.__file__).parent
    text = "\n".join(p.read_text() for p in sorted(pkg.rglob("*.py"))
                     if p.name != "config.py")
    for n in names:
        assert re.search(rf"FLAGS\.{n}\b", text), \
            f"flag use_pallas flag {n!r} is never read outside config.py"


def test_dma_reference_paths_refuse_real_tpu(monkeypatch):
    """gather_rows_dma / scatter_rows_dma are demoted to interpret-only
    reference code: on a real TPU backend they must raise, not run the
    measured-1000x-off per-row DMA loop."""
    import paddlebox_tpu.ops.pallas_kernels as pk
    monkeypatch.setattr(pk, "_interpret", lambda: False)
    t = jnp.zeros((65, 16), jnp.float32)
    rows = jnp.zeros((32,), jnp.int32)
    vals = jnp.zeros((32, 16), jnp.float32)
    with pytest.raises(RuntimeError, match="interpret-mode reference"):
        pk.gather_rows_dma(t, rows)
    with pytest.raises(RuntimeError, match="interpret-mode reference"):
        pk.scatter_rows_dma(t, rows, vals)


def test_kernel_dispatch_counter_books():
    """EVERY dispatch seam books pbox_kernel_dispatch_total{kernel,impl}
    for both implementations — the seqpool seam (ISSUE 12), the three
    CTR-family seams (ISSUE 13), and the device key-index seam
    (ISSUE 19: index.assign/index.lookup with impls pallas|host)."""
    from paddlebox_tpu.obs import MemorySink
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    from paddlebox_tpu.ops import (batch_fc, cross_norm_hadamard,
                                   fused_seqpool_cvm,
                                   init_cross_norm_summary,
                                   rank_attention)
    reset_hub()
    hub = get_hub()
    hub.add_sink(MemorySink())
    try:
        vals = jnp.ones((8, 4), jnp.float32)
        segs = jnp.zeros((8,), jnp.int32)
        sc = jnp.ones((1, 2), jnp.float32)
        x_ra = jnp.ones((4, 3), jnp.float32)
        ro = jnp.asarray(np.tile(
            np.array([[1, 1, 0, 0, 0, 0, 0]], np.int32), (4, 1)))
        pm = jnp.ones((9, 3, 2), jnp.float32)
        x_fc = jnp.ones((2, 4, 3), jnp.float32)
        w_fc = jnp.ones((2, 3, 3), jnp.float32)
        b_fc = jnp.ones((2, 3), jnp.float32)
        x_cn = jnp.ones((4, 4), jnp.float32)
        summ = init_cross_norm_summary(1, 2)

        def run_all():
            fused_seqpool_cvm(vals, segs, sc, 1, 1)
            rank_attention(x_ra, ro, pm, 3)
            batch_fc(x_fc, w_fc, b_fc)
            cross_norm_hadamard(x_cn, summ, 1, 2)

        flags_on = dict(use_pallas_seqpool=True,
                        use_pallas_rank_attention=True,
                        use_pallas_batch_fc=True,
                        use_pallas_cross_norm=True)
        with flags_scope(**flags_on):
            run_all()
        with flags_scope(**{k: False for k in flags_on}):
            run_all()
        # the ISSUE 19 device key-index seam: impls are pallas/host —
        # the fallback is the authoritative host kv, not an XLA
        # formulation, and BOTH routing decisions must book
        from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
        st = ShardedEmbeddingTable(2, mf_dim=4, capacity_per_shard=64,
                                   req_bucket_min=8, serve_bucket_min=8)
        keys0 = np.arange(2, 20, 2, dtype=np.uint64)  # shard-0-owned
        with flags_scope(use_pallas_index=True):
            st._shard_rows(0, keys0, assign=True)    # index.assign/pallas
            st._shard_rows(0, keys0, assign=False)   # index.lookup/pallas
            st._dev_index_for(0).degrade("test: force host fallback")
            st._shard_rows(0, keys0, assign=True)    # index.assign/host
            st._shard_rows(0, keys0, assign=False)   # index.lookup/host
        c = hub.counter("pbox_kernel_dispatch_total")
        for kernel in ("fused_embed_pool_cvm", "rank_attention",
                       "batch_fc", "cross_norm"):
            for impl in ("pallas", "xla"):
                assert c.value(kernel=kernel, impl=impl) >= 1, \
                    f"seam {kernel!r} never booked impl={impl!r}"
        for kernel in ("index.assign", "index.lookup"):
            for impl in ("pallas", "host"):
                assert c.value(kernel=kernel, impl=impl) >= 1, \
                    f"seam {kernel!r} never booked impl={impl!r}"
    finally:
        reset_hub()


def test_kernel_microbench_smoke(tmp_path, monkeypatch):
    """scripts/profile_keypath.py --set kernels: rows emit, record to a
    trajectory, and perf_gate --check passes over them."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "profile_keypath", os.path.join(REPO_ROOT, "scripts",
                                        "profile_keypath.py"))
    pk = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pk)
    traj = tmp_path / "traj.json"
    monkeypatch.setenv("BENCH_TRAJECTORY", str(traj))
    pk.run_set_kernels("zipf", 1, record=True)
    import json
    data = json.loads(traj.read_text())
    metrics = {r["metric"] for r in data["rows"]}
    assert any(m.startswith("kernel.pool_cvm.zipf") for m in metrics)
    assert any(m.startswith("kernel.fused.zipf") for m in metrics)
    spec2 = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO_ROOT, "scripts", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(pg)
    assert pg.check(str(traj), ignore_live=True) == 0


@pytest.mark.skipif(
    tuple(int(v) for v in jax.__version__.split(".")[:2]) < (0, 6),
    reason=("pallas DMA interpret mode needs a newer jax API "
            "(pre-existing seed failure; passes on jax >= 0.6)"))
def test_dma_kernels_interpret_semantics():
    """gather_rows_dma / scatter_rows_dma (interpret mode off-TPU):
    OOB rows clamp to the sentinel; scatter is in-place on unique rows."""
    import jax.numpy as jnp
    from paddlebox_tpu.ops.pallas_kernels import (gather_rows_dma,
                                                  scatter_rows_dma)
    C, D, K = 64, 16, 32
    rng = np.random.default_rng(0)
    table = jnp.zeros((C + 1, D), jnp.float32)
    uq = np.unique(rng.integers(0, C, size=K).astype(np.int32))
    rows = np.concatenate([uq, C + 1 + np.arange(K - len(uq),
                                                 dtype=np.int32)])
    vals = rng.normal(size=(K, D)).astype(np.float32)
    out = np.asarray(scatter_rows_dma(table, jnp.asarray(rows),
                                      jnp.asarray(vals)))
    ref = np.zeros((C + 1, D), np.float32)
    ref[uq] = vals[:len(uq)]
    np.testing.assert_allclose(out[:C], ref[:C])  # row C is the racy pad bin
    got = np.asarray(gather_rows_dma(jnp.asarray(out).at[C].set(0.0),
                                     jnp.asarray(rows)))
    np.testing.assert_allclose(got[:len(uq)], vals[:len(uq)])
    np.testing.assert_allclose(got[len(uq):], 0.0)  # OOB → sentinel zeros
