"""Pallas kernel correctness vs XLA references (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.ops.pallas_kernels import (
    gather_rows, scatter_rows, segment_sum_mxu,
)


def test_gather_rows_matches_take():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 64, size=37).astype(np.int32))
    out = gather_rows(table, rows)
    np.testing.assert_allclose(np.asarray(out), np.asarray(table)[rows])


def test_gather_rows_wide():
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 256, size=500).astype(np.int32))
    out = gather_rows(table, rows)
    np.testing.assert_allclose(np.asarray(out), np.asarray(table)[rows])


def test_scatter_rows_matches_set():
    rng = np.random.default_rng(2)
    table = rng.normal(size=(64, 16)).astype(np.float32)
    rows = rng.permutation(64)[:20].astype(np.int32)
    vals = rng.normal(size=(20, 16)).astype(np.float32)
    out = scatter_rows(jnp.asarray(table), jnp.asarray(rows),
                       jnp.asarray(vals))
    want = table.copy()
    want[rows] = vals
    np.testing.assert_allclose(np.asarray(out), want)


def test_scatter_rows_under_jit():
    rng = np.random.default_rng(3)
    table = rng.normal(size=(32, 8)).astype(np.float32)
    rows = np.array([5, 9, 31], np.int32)
    vals = rng.normal(size=(3, 8)).astype(np.float32)
    f = jax.jit(scatter_rows)
    out = f(jnp.asarray(table), jnp.asarray(rows), jnp.asarray(vals))
    want = table.copy()
    want[rows] = vals
    np.testing.assert_allclose(np.asarray(out), want)


@pytest.mark.parametrize("k,s", [(100, 40), (700, 200), (7, 3), (1500, 3000)])
def test_segment_sum_mxu(k, s):
    rng = np.random.default_rng(4)
    vals = rng.normal(size=(k, 11)).astype(np.float32)
    # contract: segments nondecreasing (batch builder order); s > k cases
    # leave whole output blocks with no keys (must read back zero)
    segs = np.sort(rng.integers(0, s, size=k)).astype(np.int32)
    got = segment_sum_mxu(jnp.asarray(vals), jnp.asarray(segs), s)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(segs),
                               num_segments=s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_segment_sum_mxu_gap_blocks_zero():
    # keys only in the last segment range → earlier output blocks unvisited
    vals = jnp.ones((8, 4), jnp.float32)
    segs = jnp.full((8,), 999, jnp.int32)
    got = np.asarray(segment_sum_mxu(vals, segs, 1000))
    assert got[999].sum() == 32.0
    np.testing.assert_allclose(got[:999], 0.0)


def test_segment_sum_mxu_drop_negative():
    vals = jnp.ones((4, 3), jnp.float32)
    segs = jnp.asarray([0, 1, -1, -1], jnp.int32)
    got = segment_sum_mxu(vals, segs, 2)
    np.testing.assert_allclose(np.asarray(got), np.ones((2, 3)))


def test_segment_sum_mxu_leading_and_interleaved_drops():
    vals = jnp.asarray(np.arange(20, dtype=np.float32).reshape(5, 4))
    segs = jnp.asarray([-1, 0, -1, 0, 1], jnp.int32)
    got = segment_sum_mxu(vals, segs, 2)
    want = jax.ops.segment_sum(
        jnp.where(jnp.asarray([0, 1, 0, 1, 1], bool)[:, None], vals, 0),
        jnp.asarray([0, 0, 0, 0, 1], jnp.int32), num_segments=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_segment_sum_mxu_grad():
    rng = np.random.default_rng(6)
    vals = jnp.asarray(rng.normal(size=(50, 5)).astype(np.float32))
    segs = jnp.asarray(np.sort(rng.integers(0, 12, size=50)).astype(np.int32))
    w = jnp.asarray(rng.normal(size=(12, 5)).astype(np.float32))
    f = lambda v: (segment_sum_mxu(v, segs, 12) * w).sum()
    g = jax.grad(f)(vals)
    want = jax.grad(
        lambda v: (jax.ops.segment_sum(v, segs, num_segments=12) * w).sum()
    )(vals)
    np.testing.assert_allclose(np.asarray(g), np.asarray(want), rtol=1e-5)


def test_fused_seqpool_concat_grad_with_pallas():
    from paddlebox_tpu.ops import fused_seqpool_concat
    rng = np.random.default_rng(7)
    B, S, K = 3, 4, 30
    vals = jnp.asarray(rng.normal(size=(K, 6)).astype(np.float32))
    segs = jnp.asarray(np.sort(rng.integers(0, B * S, size=K)).astype(np.int32))
    f = lambda v: fused_seqpool_concat(v, segs, B, S).sum()
    want = jax.grad(f)(vals)
    with flags_scope(use_pallas_seqpool=True):
        got = jax.grad(f)(vals)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_seqpool_cvm_pallas_backend_matches():
    from paddlebox_tpu.ops import fused_seqpool_cvm
    rng = np.random.default_rng(5)
    B, S, MF, K = 4, 3, 8, 50
    vals = jnp.asarray(rng.normal(size=(K, 3 + MF)).astype(np.float32))
    segs = jnp.asarray(rng.integers(0, B * S, size=K).astype(np.int32))
    sc = jnp.asarray(np.abs(rng.normal(size=(B, 2))).astype(np.float32))
    ref = fused_seqpool_cvm(vals, segs, sc, B, S)
    with flags_scope(use_pallas_seqpool=True):
        got = fused_seqpool_cvm(vals, segs, sc, B, S)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_table_pull_push_with_pallas_flags():
    from paddlebox_tpu.data.batch import SlotBatch
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig

    def run(**flags):
        with flags_scope(**flags):
            t = EmbeddingTable(mf_dim=8, capacity=256,
                               cfg=SparseSGDConfig(), seed=7)
            keys = np.array([3, 9, 3, 77, 9, 1024], np.uint64)
            batch = SlotBatch(
                keys=keys, num_keys=len(keys),
                segments=np.arange(len(keys), dtype=np.int32),
                dense=np.zeros((2, 1), np.float32),
                label=np.zeros(2, np.float32),
                show=np.ones(2, np.float32), clk=np.zeros(2, np.float32),
                batch_size=2, num_slots=3)
            idx = t.prepare(batch)
            vals = t.pull(idx)
            g = jnp.ones((len(keys), 3 + 8), jnp.float32) * 0.1
            t.push(idx, g)
            return np.asarray(vals), np.asarray(t.pull(idx))

    v0, p0 = run()
    v1, p1 = run(use_pallas_gather=True)
    np.testing.assert_allclose(v0, v1, rtol=1e-6)
    np.testing.assert_allclose(p0, p1, rtol=1e-6)


@pytest.mark.skipif(
    tuple(int(v) for v in jax.__version__.split(".")[:2]) < (0, 6),
    reason=("pallas DMA interpret mode needs a newer jax API "
            "(pre-existing seed failure; passes on jax >= 0.6)"))
def test_dma_kernels_interpret_semantics():
    """gather_rows_dma / scatter_rows_dma (interpret mode off-TPU):
    OOB rows clamp to the sentinel; scatter is in-place on unique rows."""
    import jax.numpy as jnp
    from paddlebox_tpu.ops.pallas_kernels import (gather_rows_dma,
                                                  scatter_rows_dma)
    C, D, K = 64, 16, 32
    rng = np.random.default_rng(0)
    table = jnp.zeros((C + 1, D), jnp.float32)
    uq = np.unique(rng.integers(0, C, size=K).astype(np.int32))
    rows = np.concatenate([uq, C + 1 + np.arange(K - len(uq),
                                                 dtype=np.int32)])
    vals = rng.normal(size=(K, D)).astype(np.float32)
    out = np.asarray(scatter_rows_dma(table, jnp.asarray(rows),
                                      jnp.asarray(vals)))
    ref = np.zeros((C + 1, D), np.float32)
    ref[uq] = vals[:len(uq)]
    np.testing.assert_allclose(out[:C], ref[:C])  # row C is the racy pad bin
    got = np.asarray(gather_rows_dma(jnp.asarray(out).at[C].set(0.0),
                                     jnp.asarray(rows)))
    np.testing.assert_allclose(got[:len(uq)], vals[:len(uq)])
    np.testing.assert_allclose(got[len(uq):], 0.0)  # OOB → sentinel zeros
