"""Bench perf-regression gate (scripts/perf_gate.py): artifact folding,
trajectory append, the latest-vs-best check (synthetic degradation is
flagged, the repo's real trajectory passes), graceful no-file skip, and
the critical-path math smoke (ISSUE 10 tier-1 wiring)."""

import importlib.util
import json
import os

import pytest

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def perf_gate():
    return _load("perf_gate", os.path.join("scripts", "perf_gate.py"))


def _row(metric, value, source, **kw):
    return dict(metric=metric, value=value, source=source,
                unit="examples/sec/chip", **kw)


# ---- folding -----------------------------------------------------------
def test_parse_driver_wrapper_artifact(perf_gate, tmp_path):
    tail = "\n".join([
        "some log line",
        json.dumps({"metric": "m_a", "value": 100.0, "unit": "u",
                    "mode": "resident", "shape": "uniform",
                    "device_busy_frac": 0.5}),
        json.dumps({"not_a_bench_row": 1}),
        "{broken json",
        json.dumps({"metric": "m_b", "value": 7.5, "unit": "u"}),
    ])
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"n": 1, "cmd": "x", "rc": 0, "tail": tail}))
    rows = perf_gate.parse_bench_artifact(str(p))
    assert [r["metric"] for r in rows] == ["m_a", "m_b"]
    assert rows[0]["source"] == "BENCH_r01"
    assert rows[0]["device_busy_frac"] == 0.5
    assert rows[0]["mode"] == "resident"


def test_fold_builds_trajectory(perf_gate, tmp_path):
    for rnd, val in (("r01", 50.0), ("r02", 80.0)):
        (tmp_path / f"BENCH_{rnd}.json").write_text(json.dumps({
            "tail": json.dumps({"metric": "m", "value": val,
                                "unit": "u"})}))
    # the elastic-churn gate's artifact family folds in too (ISSUE 18)
    (tmp_path / "ELASTIC_r01.json").write_text(json.dumps({
        "tail": json.dumps({"metric": "elastic.reshard_stall_ms",
                            "value": 120.0, "unit": "ms"})}))
    out = str(tmp_path / "BENCH_trajectory.json")
    data = perf_gate.fold(repo_root=str(tmp_path), out_path=out)
    assert [r["value"] for r in data["rows"]] == [50.0, 80.0, 120.0]
    assert data["rows"][2]["source"] == "ELASTIC_r01"
    on_disk = json.load(open(out))
    assert on_disk["rows"] == data["rows"]


def test_fold_real_repo_artifacts_and_check_passes(perf_gate, tmp_path):
    """The REAL recorded rounds fold cleanly and pass the gate — the
    trajectory the repo commits must never itself trip the check."""
    out = str(tmp_path / "traj.json")
    data = perf_gate.fold(repo_root=REPO, out_path=out)
    metrics = {r["metric"] for r in data["rows"]}
    assert "deepfm_ctr_examples_per_sec_per_chip" in metrics
    failures, summary = perf_gate.check_rows(data["rows"])
    assert failures == [], failures
    assert summary
    assert perf_gate.check(out) == 0


def test_committed_trajectory_is_current_and_passes(perf_gate):
    """tier-1 wiring of `perf_gate.py --check`: the committed
    BENCH_trajectory.json exists and the gate passes on its RECORDED
    rounds (--ignore-live: rows bench.py appended from this dev box
    ride tunnel weather and are gated by the bench banner, not CI)."""
    path = perf_gate.default_trajectory_path()
    assert os.path.exists(path), \
        "BENCH_trajectory.json missing — run scripts/perf_gate.py --fold"
    assert perf_gate.main(["--check", "--trajectory", path,
                           "--ignore-live"]) == 0


# ---- the gate ----------------------------------------------------------
def test_check_flags_synthetic_degradation(perf_gate, tmp_path):
    rows = [_row("m", 100.0, "r01"), _row("m", 90.0, "r02"),
            _row("m", 40.0, "live")]   # 60% below best 100
    failures, _ = perf_gate.check_rows(rows, max_drop_frac=0.5)
    assert len(failures) == 1
    assert "PERF REGRESSION" in failures[0]
    assert "m" in failures[0] and "floor" in failures[0]
    # CLI exit code 1
    p = str(tmp_path / "t.json")
    perf_gate._write(p, {"version": 1, "rows": rows})
    assert perf_gate.main(["--check", "--trajectory", p]) == 1


def test_check_tolerates_drop_within_threshold(perf_gate):
    rows = [_row("m", 100.0, "r01"), _row("m", 60.0, "live")]
    failures, summary = perf_gate.check_rows(rows, max_drop_frac=0.5)
    assert failures == []
    assert len(summary) == 1
    # a tighter threshold flips it
    failures, _ = perf_gate.check_rows(rows, max_drop_frac=0.25)
    assert len(failures) == 1


def test_check_single_row_and_improvements_pass(perf_gate):
    rows = [_row("solo", 5.0, "r01"),
            _row("up", 10.0, "r01"), _row("up", 30.0, "live")]
    failures, summary = perf_gate.check_rows(rows)
    assert failures == []
    assert any("no history" in s for s in summary)


def test_check_reports_every_regressed_key_worst_first(perf_gate,
                                                       tmp_path):
    """One --check run over a round that regressed SEVERAL keys — the
    multichip scaling rows included — must name them all, ordered by
    drop severity, in one pass (ISSUE 11)."""
    rows = [
        _row("sharded.n4.uniform.ex_per_sec_per_chip", 1000.0, "r06",
             n_chips=4),
        _row("sharded.n4.uniform.ex_per_sec_per_chip", 100.0, "r07",
             n_chips=4),                                  # -90%
        _row("sharded.n8.uniform.scaling_efficiency", 0.8, "r06"),
        _row("sharded.n8.uniform.scaling_efficiency", 0.3, "r07"),  # -62%
        _row("m_fine", 50.0, "r06"), _row("m_fine", 49.0, "r07"),
    ]
    failures, summary = perf_gate.check_rows(rows, max_drop_frac=0.5)
    assert len(failures) == 2, failures
    # worst drop first
    assert "sharded.n4.uniform.ex_per_sec_per_chip" in failures[0]
    assert "sharded.n8.uniform.scaling_efficiency" in failures[1]
    assert any("m_fine" in s for s in summary)
    # CLI still exits 1 and prints both
    p = str(tmp_path / "t.json")
    perf_gate._write(p, {"version": 1, "rows": rows})
    assert perf_gate.main(["--check", "--trajectory", p]) == 1


# ---- lower-is-better latency keys (BENCH_MODE=serve — ISSUE 15) --------
def test_ms_keys_gate_lower_is_better(perf_gate):
    """``*_ms`` metrics (serving latency) regress when the latest
    value RISES past best*(1+frac): best is the LOWEST recorded row,
    improvements (lower latency) always pass."""
    assert perf_gate.lower_is_better("serving.uniform.p99_ms")
    assert not perf_gate.lower_is_better("serving.uniform.qps")
    rows = [_row("serving.uniform.p99_ms", 2.0, "SERVE_r01"),
            _row("serving.uniform.p99_ms", 2.4, "SERVE_r02"),
            _row("serving.uniform.p99_ms", 8.0, "live")]  # 4x the best
    failures, _ = perf_gate.check_rows(rows, max_drop_frac=0.5)
    assert len(failures) == 1
    assert "PERF REGRESSION" in failures[0]
    assert "ceiling" in failures[0]
    # within the ceiling: passes; an IMPROVEMENT (lower) always passes
    ok = [_row("serving.uniform.p99_ms", 2.0, "SERVE_r01"),
          _row("serving.uniform.p99_ms", 2.9, "live")]
    failures, summary = perf_gate.check_rows(ok, max_drop_frac=0.5)
    assert failures == [] and len(summary) == 1
    better = [_row("serving.uniform.p99_ms", 2.0, "SERVE_r01"),
              _row("serving.uniform.p99_ms", 0.5, "live")]
    failures, _ = perf_gate.check_rows(better, max_drop_frac=0.5)
    assert failures == []


def test_ms_regression_ranks_with_throughput_drops(perf_gate, tmp_path):
    """A mixed round (throughput drop + latency rise) reports BOTH,
    worst severity first, and the CLI exits 1."""
    rows = [
        _row("serving.uniform.p99_ms", 1.0, "SERVE_r01"),
        _row("serving.uniform.p99_ms", 4.0, "live"),      # +300%
        _row("serving.uniform.qps", 1000.0, "SERVE_r01"),
        _row("serving.uniform.qps", 400.0, "live"),       # -60%
    ]
    failures, _ = perf_gate.check_rows(rows, max_drop_frac=0.5)
    assert len(failures) == 2, failures
    assert "p99_ms" in failures[0]     # +300% outranks -60%
    assert "qps" in failures[1]
    p = str(tmp_path / "t.json")
    perf_gate._write(p, {"version": 1, "rows": rows})
    assert perf_gate.main(["--check", "--trajectory", p]) == 1


def test_multichip_extra_fields_ride_the_row(perf_gate, tmp_path):
    """n_chips / a2a_chunks / exchange_overlap_frac are first-class
    trajectory passthrough fields (EXTRA_FIELDS) on both the fold and
    the live-append paths."""
    tail = json.dumps({"metric": "sharded.n2.uniform.ex_per_sec_per_chip",
                       "value": 5000.0, "unit": "examples/sec/chip",
                       "mode": "multichip", "n_chips": 2,
                       "a2a_chunks": 2})
    p = tmp_path / "BENCH_r09.json"
    p.write_text(json.dumps({"n": 9, "cmd": "x", "rc": 0, "tail": tail}))
    rows = perf_gate.parse_bench_artifact(str(p))
    assert rows[0]["n_chips"] == 2 and rows[0]["a2a_chunks"] == 2
    traj = str(tmp_path / "traj.json")
    perf_gate.record_result(
        {"metric": "m_sharded", "value": 1.0, "unit": "u",
         "exchange_overlap_frac": 0.4, "n_chips": 4}, path=traj)
    live = json.load(open(traj))["rows"][-1]
    assert live["exchange_overlap_frac"] == 0.4 and live["n_chips"] == 4


def test_check_keys_are_per_metric(perf_gate):
    """The tiered metric regressing must flag even while resident is
    fine (per-mode/shape gating — the metric name carries both)."""
    rows = [_row("m_tiered", 28000.0, "r06"),
            _row("m_tiered", 8000.0, "live"),
            _row("m", 100000.0, "r06"), _row("m", 110000.0, "live")]
    failures, _ = perf_gate.check_rows(rows, max_drop_frac=0.5)
    assert len(failures) == 1
    assert "m_tiered" in failures[0]


def test_check_skips_gracefully_without_file(perf_gate, tmp_path):
    missing = str(tmp_path / "nope.json")
    assert perf_gate.main(["--check", "--trajectory", missing]) == 0


# ---- bench append hook -------------------------------------------------
def test_record_result_appends_and_gates(perf_gate, tmp_path, capsys):
    p = str(tmp_path / "traj.json")
    perf_gate._write(p, {"version": 1, "rows": [
        _row("m", 100.0, "r01")]})
    fails = perf_gate.record_result(
        {"metric": "m", "value": 95.0, "unit": "u", "mode": "resident",
         "shape": "uniform", "device_busy_frac": 0.9}, path=p,
        max_drop_frac=0.5)
    assert fails == []
    data = json.load(open(p))
    assert len(data["rows"]) == 2
    live = data["rows"][-1]
    assert live["source"] == "live" and "recorded_at" in live
    assert live["device_busy_frac"] == 0.9
    # a degraded live row is flagged loudly
    fails = perf_gate.record_result(
        {"metric": "m", "value": 10.0, "unit": "u"}, path=p,
        max_drop_frac=0.5)
    assert len(fails) == 1 and "PERF REGRESSION" in fails[0]
    assert "PERF REGRESSION" in capsys.readouterr().err


def test_record_result_never_raises(perf_gate, tmp_path):
    bad = str(tmp_path / "bad.json")
    open(bad, "w").write("{not json")
    assert perf_gate.record_result({"metric": "m", "value": 1.0},
                                   path=bad) == []


# ---- critical-path math smoke (deterministic synthetic events) --------
def test_critical_path_smoke_end_to_end():
    """The gate's sibling tier-1 requirement: deterministic synthetic
    pass parts → block math → report verdicts, no trainers involved."""
    from paddlebox_tpu.obs import trace
    tr = _load("telemetry_report",
               os.path.join("scripts", "telemetry_report.py"))
    # 4 device-bound passes, one fence-bound straggler
    events = []
    specs = [(1.0, {"build_wait": 0.05}), (1.0, {}),
             (0.8, {"fence_wait": 1.2}), (1.0, {"stage_wait": 0.02}),
             (1.0, {"evict_emergency": 0.4})]
    for i, (train, parts) in enumerate(specs):
        blk = trace.critical_path_block(train, parts)
        assert blk["wall_sec"] == pytest.approx(
            train + sum(parts.values()))
        events.append({"event": "pass", "ts": i, "seq": i, "proc": 0,
                       "kind": "train_pass_resident",
                       "pass_seq": i + 1, "batches": 1, "examples": 10,
                       "elapsed_sec": train,
                       "examples_per_sec": 10 / train,
                       "critical_path": blk})
    line = tr.critical_path_summary(events)
    assert "4/5 passes device-bound" in line
    assert "pass 3 fence_wait-bound: +1.200s" in line
    report = tr.render_report(events)
    assert "bottleneck" in report
    assert "fence_wait +1.200s" in report
