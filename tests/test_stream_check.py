"""Tier-1 wiring of scripts/stream_check.py — the deterministic
streaming-ingest gate (ISSUE 6): SIGTERM mid-stream + resume loses zero
completed-window records, replays exactly the open window
(at-least-once), and the killed run's checkpoint at the last common
window boundary matches the no-kill oracle's ``state_digest``. The
standalone script additionally runs the scenario twice and asserts the
outcome is byte-identical across identically-seeded runs."""

from scripts.stream_check import FILES, WINDOW, run_scenario


def test_stream_check_gate(tmp_path):
    out = run_scenario(str(tmp_path), seed=7, preempt_at=8)
    assert out["ok"]
    assert out["oracle_windows"] == FILES // WINDOW
    # the kill landed mid-window-2: one window completed, one open
    assert len(out["completed_at_kill"]) == WINDOW
    assert len(out["open_window"]) == WINDOW
    assert out["replayed_files"] == WINDOW
    assert out["resumed_windows"] == FILES // WINDOW - 1
    assert out["events"]["stream_replay"] >= 1
    assert out["fault_stats"]["preempt.signal:fail"]["fired"] == 1
