"""BASELINE ladder config #4: 1000+-slot fused seqpool pipeline (the Baidu
feed-log shape — reference fused_seqpool_cvm launches ONE kernel for 1000+
slots; here one segment_sum pools them all). Verifies the whole path —
columnar batch build → dedup → pull → fused_seqpool_cvm → model → push —
stays vectorized (no per-slot python) and numerically sane at S=1024."""

import time

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer

S = 1024
B = 64
N_REC = 512


def make_records(seed=0):
    """Variable-length slots: most slots 1 key, some empty, some multi —
    the ragged feed-log profile."""
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(N_REC):
        counts = rng.choice([0, 1, 1, 1, 2], size=S).astype(np.int64)
        offsets = np.zeros(S + 1, np.int32)
        np.cumsum(counts, out=offsets[1:])
        nk = int(offsets[-1])
        keys = (rng.integers(0, 97, nk).astype(np.uint64)
                + np.repeat(np.arange(S, dtype=np.uint64) * 97, counts))
        label = float(rng.random() < (0.2 + 0.4 * (keys[0] % 3 == 0)))
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offsets,
            dense=rng.normal(size=4).astype(np.float32),
            label=label, show=1.0, clk=label))
    return recs


@pytest.mark.slow
def test_thousand_slot_pipeline():
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                        key_bucket_min=1 << 10)
    ds = InMemoryDataset(desc)
    ds.records = make_records()
    ds.columnarize()

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 18,
                           cfg=cfg, unique_bucket_min=1 << 14)
    with flags_scope(log_period_steps=10 ** 6):
        tr = Trainer(CtrDnn(hidden=(64, 32)), table, desc,
                     tx=optax.adam(2e-3))
        r1 = tr.train_pass(ds)
        # host batch build + prep must stay vectorized: time a second
        # pass (compiled) and bound per-batch host+device time
        t0 = time.perf_counter()
        r2 = tr.train_pass(ds)
        per_batch = (time.perf_counter() - t0) / r2["batches"]
    assert np.isfinite(r2["last_loss"])
    assert r2["auc"] > 0.5
    assert table.feature_count > S  # every slot landed keys
    # ~66k keys/batch over 1024 slots; anything per-slot-python would be
    # seconds per batch — vectorized path stays well under one
    assert per_batch < 1.0, f"1000-slot batch path too slow: {per_batch:.2f}s"


@pytest.mark.slow
def test_thousand_slot_mesh_streaming_and_resident():
    """Rung-4 shape × the MESH: 1024 slots through the sharded routing
    plans (key%N owners, two all_to_alls) — streaming and resident
    passes agree; the resident wire's serve_slot encoding must WIDEN
    past u8 (1024 slot ids don't fit a byte; data_feed.h:2066-2287 is
    the 1000+-slot production feed)."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer
    assert len(jax.devices()) >= 8
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=16, label_slot="label",
                        key_bucket_min=1 << 10)
    ds = InMemoryDataset(desc)
    ds.records = make_records(seed=2)
    ds.columnarize()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)

    def mk():
        t = ShardedEmbeddingTable(8, mf_dim=4, capacity_per_shard=1 << 15,
                                  cfg=cfg, req_bucket_min=1 << 12,
                                  serve_bucket_min=1 << 12)
        with flags_scope(log_period_steps=10 ** 6):
            tr = ShardedTrainer(CtrDnn(hidden=(32,)), t, desc,
                                make_mesh(8), tx=optax.adam(1e-3), seed=3)
        return tr

    tr_s, tr_r = mk(), mk()
    rs = tr_s.train_pass(ds)
    rp = tr_r.build_resident_pass(ds)
    # >256 slot ids force the u16 serve_slot wire (u8 would truncate)
    assert rp.fmt["serve_slot"] == "u16", rp.fmt
    rr = tr_r.train_pass_resident(rp)
    assert rr["ins_num"] == rs["ins_num"] == N_REC
    assert np.isfinite(rr["auc"])
    assert abs(rr["auc"] - rs["auc"]) < 2e-3, (rr["auc"], rs["auc"])
    # every shard holds rows (1024 slots spray keys across all owners)
    assert all(len(ix) > 0 for ix in tr_r.table.indexes)


@pytest.mark.slow
def test_thousand_slot_multi_mf_mesh():
    """Multi-mf × thousand × mesh: 1024 slots in two dim classes through
    the per-class sharded routing plans (dims ride the slot config,
    feature_value.h:42-185) — trains, and per-class tables see only
    their slots' keys."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.multi_mf_sharded import MultiMfShardedTable
    from paddlebox_tpu.train.multi_mf_sharded import MultiMfShardedTrainer
    assert len(jax.devices()) >= 8
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=16, label_slot="label",
                        key_bucket_min=1 << 10)
    ds = InMemoryDataset(desc)
    ds.records = make_records(seed=3)
    ds.columnarize()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    dims = [4, 8] * (S // 2)   # two dim classes interleaved over 1024 slots
    table = MultiMfShardedTable(8, dims, capacity_per_shard=1 << 15,
                                cfg=cfg, req_bucket_min=1 << 11,
                                serve_bucket_min=1 << 11)
    with flags_scope(log_period_steps=10 ** 6):
        tr = MultiMfShardedTrainer(CtrDnn(hidden=(32,)), table, desc,
                                   make_mesh(8), tx=optax.adam(1e-3))
        res = tr.train_pass(ds)
    assert np.isfinite(res["last_loss"])
    assert res["ins_num"] == N_REC
    # both dim classes saw keys on every shard
    for c, t in enumerate(table.tables):
        assert sum(len(ix) for ix in t.indexes) > 0, f"class {c} empty"


@pytest.mark.slow
def test_thousand_slot_resident_pass():
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                        key_bucket_min=1 << 10)
    ds = InMemoryDataset(desc)
    ds.records = make_records(seed=1)
    ds.columnarize()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 18, cfg=cfg,
                           unique_bucket_min=1 << 14)
    with flags_scope(log_period_steps=10 ** 6):
        tr = Trainer(CtrDnn(hidden=(32,)), table, desc, tx=optax.adam(1e-3))
        res = tr.train_pass_resident(ds)  # non-trivial segments path
    assert np.isfinite(res["auc"]) and res["batches"] == N_REC // B
