"""BASELINE ladder config #4: 1000+-slot fused seqpool pipeline (the Baidu
feed-log shape — reference fused_seqpool_cvm launches ONE kernel for 1000+
slots; here one segment_sum pools them all). Verifies the whole path —
columnar batch build → dedup → pull → fused_seqpool_cvm → model → push —
stays vectorized (no per-slot python) and numerically sane at S=1024."""

import time

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer

S = 1024
B = 64
N_REC = 512


def make_records(seed=0):
    """Variable-length slots: most slots 1 key, some empty, some multi —
    the ragged feed-log profile."""
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(N_REC):
        counts = rng.choice([0, 1, 1, 1, 2], size=S).astype(np.int64)
        offsets = np.zeros(S + 1, np.int32)
        np.cumsum(counts, out=offsets[1:])
        nk = int(offsets[-1])
        keys = (rng.integers(0, 97, nk).astype(np.uint64)
                + np.repeat(np.arange(S, dtype=np.uint64) * 97, counts))
        label = float(rng.random() < (0.2 + 0.4 * (keys[0] % 3 == 0)))
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offsets,
            dense=rng.normal(size=4).astype(np.float32),
            label=label, show=1.0, clk=label))
    return recs


@pytest.mark.slow
def test_thousand_slot_pipeline():
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                        key_bucket_min=1 << 10)
    ds = InMemoryDataset(desc)
    ds.records = make_records()
    ds.columnarize()

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 18,
                           cfg=cfg, unique_bucket_min=1 << 14)
    with flags_scope(log_period_steps=10 ** 6):
        tr = Trainer(CtrDnn(hidden=(64, 32)), table, desc,
                     tx=optax.adam(2e-3))
        r1 = tr.train_pass(ds)
        # host batch build + prep must stay vectorized: time a second
        # pass (compiled) and bound per-batch host+device time
        t0 = time.perf_counter()
        r2 = tr.train_pass(ds)
        per_batch = (time.perf_counter() - t0) / r2["batches"]
    assert np.isfinite(r2["last_loss"])
    assert r2["auc"] > 0.5
    assert table.feature_count > S  # every slot landed keys
    # ~66k keys/batch over 1024 slots; anything per-slot-python would be
    # seconds per batch — vectorized path stays well under one
    assert per_batch < 1.0, f"1000-slot batch path too slow: {per_batch:.2f}s"


@pytest.mark.slow
def test_thousand_slot_resident_pass():
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=B, label_slot="label",
                        key_bucket_min=1 << 10)
    ds = InMemoryDataset(desc)
    ds.records = make_records(seed=1)
    ds.columnarize()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 18, cfg=cfg,
                           unique_bucket_min=1 << 14)
    with flags_scope(log_period_steps=10 ** 6):
        tr = Trainer(CtrDnn(hidden=(32,)), table, desc, tx=optax.adam(1e-3))
        res = tr.train_pass_resident(ds)  # non-trivial segments path
    assert np.isfinite(res["auc"]) and res["batches"] == N_REC // B
