"""Phase-5 pass lifecycle: host backing store, working-set promotion,
preload double-buffering, checkpoint deltas (SURVEY.md §3.3, §7 Phase 5)."""

import os

import numpy as np
import optax
import pytest

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import (BoxPSHelper, HostStore, PassScopedTable,
                              SparseSGDConfig)
from paddlebox_tpu.train import Trainer


def test_host_store_fetch_update_roundtrip():
    hs = HostStore(mf_dim=4, capacity=1 << 12, init_rows=8)
    keys = np.array([10, 20, 30], np.uint64)
    got = hs.fetch(keys)
    assert got["embed_w"].shape == (3,) and got["embedx_w"].shape == (3, 4)
    np.testing.assert_allclose(got["embed_w"], 0.0)  # unknown keys = zeros
    data = {f: np.full_like(v, 2.0) for f, v in got.items()}
    hs.update(keys, data)
    # growth past init_rows
    many = np.arange(100, 600, dtype=np.uint64)
    hs.update(many, {f: np.ones((500, 4) if f == "embedx_w" else (500,),
                                np.float32) for f in got})
    back = hs.fetch(keys)
    np.testing.assert_allclose(back["embed_w"], 2.0)
    assert len(hs) == 503


def test_host_store_save_delta_and_shrink(tmp_path):
    hs = HostStore(mf_dim=2, capacity=1 << 10)
    k1 = np.array([1, 2, 3], np.uint64)
    d = lambda n, v: {f: np.full((n, 2) if f == "embedx_w" else (n,), v,
                                 np.float32) for f in
                      ("show", "clk", "delta_score", "slot", "embed_w",
                       "embed_g2sum", "embedx_w", "embedx_g2sum", "mf_size")}
    hs.update(k1, d(3, 1.0))
    base = str(tmp_path / "base.npz")
    assert hs.save_base(base) == 3
    k2 = np.array([4, 5], np.uint64)
    hs.update(k2, d(2, 2.0))
    delta = str(tmp_path / "delta.npz")
    assert hs.save_delta(delta) == 2   # only rows touched since save_base
    # reload base then merge delta
    hs2 = HostStore(mf_dim=2, capacity=1 << 10)
    assert hs2.load(base) == 3
    assert hs2.load(delta, merge=True) == 2
    np.testing.assert_allclose(hs2.fetch(k2)["embed_w"], 2.0)
    # shrink: decayed score below threshold drops never-shown rows
    hs2.update(np.array([9], np.uint64), d(1, 0.0))
    freed = hs2.shrink(delete_threshold=0.05, decay=1.0)
    assert freed == 1 and len(hs2) == 5


def test_pass_scoped_table_promote_and_writeback():
    hs = HostStore(mf_dim=4, capacity=1 << 12)
    t = PassScopedTable(hs, pass_capacity=64, cfg=SparseSGDConfig())
    keys = np.array([7, 8, 9], np.uint64)
    t.begin_pass(keys)
    assert t.in_pass and t.feature_count == 3
    # simulate a jit update: bump show on the working set rows, marking
    # them touched as prepare()/apply_push do (end_pass writes back only
    # touched rows)
    rows = t.index.lookup(keys)
    st = t.state
    d = np.asarray(st.data).copy()
    d[rows, 0] = 5.0  # col 0 = show
    t.state = type(st).from_logical(d, st.capacity)
    t._touched[rows] = True
    t.end_pass()
    assert not t.in_pass
    np.testing.assert_allclose(hs.fetch(keys)["show"], 5.0)
    # second pass with overlapping keys sees the written-back values
    t.begin_pass(np.array([8, 9, 11], np.uint64))
    r = t.index.lookup(np.array([8], np.uint64))
    assert float(np.asarray(t.state.show)[r[0]]) == 5.0
    t.end_pass()


def test_pass_scoped_delta_staging():
    """Persistent window (single-chip mirror of the tiered delta
    staging, box_wrapper.cc:129-186): overlapping pass 2 stages only the
    NEW keys; resident rows keep their trained values without a host
    round-trip; stats report the delta."""
    from paddlebox_tpu.ps.table import FIELD_COL
    hs = HostStore(mf_dim=2, capacity=1 << 12)
    t = PassScopedTable(hs, pass_capacity=256, cfg=SparseSGDConfig())
    k1 = np.arange(0, 100, dtype=np.uint64)
    t.begin_pass(k1)
    assert t.last_pass_stats["staged"] == 100
    assert t.last_pass_stats["resident"] == 0
    rows = t.index.lookup(k1)
    d = np.asarray(t.state.data).copy()
    d[rows, FIELD_COL["embed_w"]] = 4.25
    t.state = type(t.state).from_logical(d, t.state.capacity)
    t._touched[rows] = True
    assert t.end_pass() == 100
    k2 = np.arange(50, 150, dtype=np.uint64)
    t.begin_pass(k2)
    st = t.last_pass_stats
    assert st["staged"] == 50 and st["resident"] == 50, st
    r60 = int(t.index.lookup(np.array([60], np.uint64))[0])
    assert float(np.asarray(t.state.data)[r60, FIELD_COL["embed_w"]]) \
        == 4.25  # resident row, no re-fetch
    t.end_pass()
    # untouched pass: nothing written back
    assert t.last_pass_stats["written_back"] == 0


def test_pass_capacity_guard():
    hs = HostStore(mf_dim=2, capacity=1 << 12)
    t = PassScopedTable(hs, pass_capacity=4)
    with pytest.raises(ValueError):
        t.begin_pass(np.arange(10, dtype=np.uint64))


def test_stage_guards():
    hs = HostStore(mf_dim=2, capacity=1 << 12)
    t = PassScopedTable(hs, pass_capacity=64)
    t.begin_pass(np.array([1, 2], np.uint64))
    # staging DURING an open pass is the overlap contract (missing keys
    # are outside the open window's write-back set) — legal; a second
    # concurrent stage is not
    t.stage(np.array([3], np.uint64), background=False)
    with pytest.raises(RuntimeError, match="already staging"):
        t.stage(np.array([4], np.uint64))
    t.end_pass()
    # begin_pass with keys differing from the staged set must refuse
    with pytest.raises(RuntimeError, match="differ"):
        t.begin_pass(np.array([1, 3], np.uint64))
    t._stage = None
    # drop_window while a pass is open is refused
    t.begin_pass(np.array([1, 2], np.uint64))
    with pytest.raises(RuntimeError, match="pass is open"):
        t.drop_window()
    t.end_pass()


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_pass")
    return generate_criteo_files(str(d), num_files=4, rows_per_file=2500,
                                 vocab_per_slot=40, seed=11)


def test_boxps_helper_multi_pass_training(criteo_files, tmp_path):
    """Two-day pipeline: preload day k+1 while day k trains; AUC improves
    across passes; delta saved at end_pass."""
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3,
                          learning_rate=0.1, mf_learning_rate=0.1)
    hs = HostStore(mf_dim=8, capacity=1 << 16)
    table = PassScopedTable(hs, pass_capacity=1 << 13, cfg=cfg,
                            unique_bucket_min=4096)
    tr = Trainer(CtrDnn(hidden=(32, 32)), table, desc, tx=optax.adam(2e-3))
    helper = BoxPSHelper(table, trainer=tr)

    def new_ds(files):
        ds = DatasetFactory().create_dataset("PaddleBoxDataset", desc)
        helper.attach(ds)
        ds.set_filelist(files)
        ds.set_thread(2)
        return ds

    ds1 = new_ds(criteo_files[:2])
    helper.read_data_to_memory(ds1)
    ds1.begin_pass()
    n1 = table.feature_count
    assert n1 > 50

    ds2 = new_ds(criteo_files[2:])
    helper.preload_into_memory(ds2)   # overlaps pass-1 training
    r1 = helper.train_pass(ds1)
    delta = str(tmp_path / "p1_delta.npz")
    helper.end_pass(ds1, need_save_delta=True, delta_path=delta)
    assert os.path.exists(delta)
    assert len(hs) >= n1

    helper.wait_feed_pass_done(ds2)
    ds2.begin_pass()
    tr.reset_metrics()
    r2 = helper.train_pass(ds2)
    ds2.end_pass()
    assert np.isfinite(r1["last_loss"]) and np.isfinite(r2["last_loss"])
    # same synthetic distribution → learned state (sparse rows written back
    # through the host store + dense params) carries across passes
    assert r2["auc"] > r1["auc"] > 0.5, (r1["auc"], r2["auc"])
    # full model dump contains the union of both passes' features
    base = str(tmp_path / "base.npz")
    assert helper.save_base(base) == len(hs)


def test_host_store_disk_tier(tmp_path):
    """spill_cold → load_from_disk roundtrip (the host-RAM↔SSD boundary:
    LoadSSD2Mem semantics; RAM state wins over stale spilled copies)."""
    hs = HostStore(mf_dim=2, capacity=1 << 12)
    keys = np.arange(1, 21, dtype=np.uint64)
    data = {f: (np.random.default_rng(0).normal(
        size=(20, 2)).astype(np.float32) if f == "embedx_w"
        else np.zeros(20, np.float32)) for f in
        ("show", "clk", "delta_score", "slot", "embed_w", "embed_g2sum",
         "embedx_g2sum", "mf_size", "embedx_w")}
    data["show"][:10] = 100.0   # hot rows
    data["clk"][:10] = 5.0
    data["embed_w"][:] = np.arange(20, dtype=np.float32) + 1
    hs.update(keys, data)

    ssd = str(tmp_path / "cold.npz")
    # touched (never-exported) rows refuse to spill
    assert hs.spill_cold(ssd, threshold=1.0) == 0
    hs.save_base(str(tmp_path / "b0.npz"))  # export → rows become spillable
    n = hs.spill_cold(ssd, threshold=1.0)
    assert n == 10 and len(hs) == 10
    # base exports stay COMPLETE while rows are spilled
    full = str(tmp_path / "full.npz")
    assert hs.save_base(full) == 20
    blob = np.load(full)
    assert len(np.unique(blob["keys"])) == 20
    # cold keys gone from RAM
    assert (hs.index.lookup(keys[10:]) == -1).all()

    # mutate a HOT row after the spill; promote everything back
    upd = {f: data[f][:1].copy() for f in data}
    upd["embed_w"][0] = 999.0
    hs.update(keys[:1], upd)
    got = hs.load_from_disk(ssd)
    assert got == 10 and len(hs) == 20
    vals = hs.fetch(keys)
    np.testing.assert_allclose(vals["embed_w"][0], 999.0)   # RAM wins
    np.testing.assert_allclose(vals["embed_w"][10:],
                               np.arange(10, 20) + 1)       # promoted

    # subset promotion: only the pass working set loads
    hs2 = HostStore(mf_dim=2, capacity=1 << 12)
    hs2.load_from_disk(ssd, keys=keys[10:13])
    assert len(hs2) == 3


def test_disk_tier_read_through_and_no_resurrection(tmp_path):
    """fetch() transparently promotes spilled keys (LoadSSD2Mem in the
    pass path); shrink-deleted keys never resurrect from spill files;
    duplicate spill paths refuse; load(merge=False) drops registration."""
    from paddlebox_tpu.ps.host_store import FIELDS
    hs = HostStore(mf_dim=2, capacity=1 << 12)
    keys = np.arange(1, 11, dtype=np.uint64)
    mk = lambda n, v: {f: (np.full((n, 2), v, np.float32)
                           if f == "embedx_w" else np.full(n, v, np.float32))
                       for f in FIELDS}
    hs.update(keys, mk(10, 3.0))
    hs.save_base(str(tmp_path / "b.npz"))        # flags clear → spillable
    ssd = str(tmp_path / "s1.npz")
    assert hs.spill_cold(ssd, threshold=1e9) == 10  # everything cold
    assert len(hs) == 0
    with pytest.raises(ValueError):              # duplicate path refused
        hs.spill_cold(ssd, threshold=1e9)
    # read-through: fetch promotes from disk instead of zero-filling
    got = hs.fetch(keys[:3])
    np.testing.assert_allclose(got["embed_w"], 3.0)
    assert len(hs) == 3
    # shrink a promoted key; it must not resurrect into the next base.
    # Lifecycle aging reaches the WHOLE tier stack (docs/ONLINE.md): a
    # gentle shrink keeps RAM and spilled rows alike...
    hs._arr["show"][hs.index.lookup(keys[:1])] = 0.0
    assert hs.shrink(delete_threshold=0.0, decay=1.0) == 0
    # ...a harsh one ages out the 3 promoted AND the 7 still-spilled
    assert hs.shrink(delete_threshold=10.0, decay=1.0) == 10
    full = str(tmp_path / "full.npz")
    n = hs.save_base(full)
    blob = np.load(full)
    assert keys[0] not in blob["keys"]           # no resurrection
    assert n == 0                                # nothing survives anywhere
    # reset-load forgets old spill registration
    hs.load(str(tmp_path / "b.npz"), merge=False)
    assert hs._spill_files == []


def test_spill_stale_copy_never_shadows_fresh_state(tmp_path):
    """A promoted-then-updated-then-respilled key's STALE copy in an old
    spill file must never load back (registry-filtered load)."""
    from paddlebox_tpu.ps.host_store import FIELDS
    hs = HostStore(mf_dim=2, capacity=1 << 12)
    mk = lambda n, v: {f: (np.full((n, 2), v, np.float32)
                           if f == "embedx_w" else np.full(n, v, np.float32))
                       for f in FIELDS}
    k12 = np.array([1, 2], np.uint64)
    hs.update(k12, mk(2, 1.0))
    hs.save_base(str(tmp_path / "b.npz"))
    f1 = str(tmp_path / "f1.npz")
    assert hs.spill_cold(f1, threshold=1e9) == 2      # {k1,k2} → f1
    hs.fetch(np.array([2], np.uint64))                # promote k2
    hs.update(np.array([2], np.uint64), mk(1, 7.0))   # fresh value
    hs.save_base(str(tmp_path / "b2.npz"))
    f2 = str(tmp_path / "f2.npz")
    assert hs.spill_cold(f2, threshold=1e9) == 1      # fresh k2 → f2
    got = hs.fetch(k12)                               # k1 via f1, k2 via f2
    np.testing.assert_allclose(got["embed_w"], [1.0, 7.0])


def test_slot_survives_pass_roundtrip_without_prepare():
    """Slot metadata must survive begin_pass -> end_pass untouched: the
    write-back sources slot from host metadata (slot_host), which
    begin_pass must seed from the staged values — a working-set row not
    re-visited by prepare()/record_slots during the window (eval-only
    passes, staged key supersets) must not write slot=0, or a stale row
    id's slot, back into the persistent HostStore."""
    hs = HostStore(mf_dim=4, capacity=1 << 12)
    keys = np.array([7, 8, 9], np.uint64)
    d = {f: np.zeros((3, 4) if f == "embedx_w" else (3,), np.float32)
         for f in ("show", "clk", "delta_score", "slot", "embed_w",
                   "embed_g2sum", "embedx_w", "embedx_g2sum", "mf_size")}
    d["slot"] = np.array([3.0, 4.0, 5.0], np.float32)
    hs.update(keys, d)
    t = PassScopedTable(hs, pass_capacity=64, cfg=SparseSGDConfig())
    t.begin_pass(keys)       # no prepare()/record_slots in the window
    t.end_pass()
    np.testing.assert_allclose(hs.fetch(keys)["slot"], [3.0, 4.0, 5.0])
    # a second pass over a DIFFERENT key set must not inherit stale
    # slot_host entries from the first pass's (rebuilt) row ids
    k2 = np.array([21, 22], np.uint64)
    t.begin_pass(k2)
    t.end_pass()
    np.testing.assert_allclose(hs.fetch(k2)["slot"], 0.0)
    np.testing.assert_allclose(hs.fetch(keys)["slot"], [3.0, 4.0, 5.0])


def test_pass_scoped_table_sparse_adam_state_survives():
    """SparseAdam through the pass lifecycle: the optimizer extension
    block (moments, beta powers) round-trips HostStore -> HBM ->
    HostStore, so Adam state is NOT reset at pass boundaries."""
    from paddlebox_tpu.ps import SparseAdamConfig
    from paddlebox_tpu.ps.sgd import opt_ext_width
    cfg = SparseAdamConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    ext = opt_ext_width(cfg, 4)
    hs = HostStore(mf_dim=4, capacity=1 << 12, opt_ext=ext)
    t = PassScopedTable(hs, pass_capacity=64, cfg=cfg)
    keys = np.array([7, 8, 9], np.uint64)
    t.begin_pass(keys)
    import jax
    rows = t.index.lookup(keys)
    st = t.state
    d = np.asarray(jax.device_get(st.data)).copy()
    mf_end = 8 + 4
    d[rows, mf_end + 1] = 0.81   # embed beta1 power after 2 steps
    t.state = type(st).from_logical(d, st.capacity, ext=ext)
    t._touched[rows] = True      # as apply_push's serve rows would be
    t.end_pass()
    # next pass sees the persisted optimizer state FROM THE HOST STORE
    # (drop_window forces a real re-stage, not window residency)
    t.drop_window()
    t.begin_pass(keys)
    d2 = np.asarray(jax.device_get(t.state.data))
    rows2 = t.index.lookup(keys)
    np.testing.assert_allclose(d2[rows2, mf_end + 1], 0.81)
    t.end_pass()
    # mismatched store is rejected loudly
    hs2 = HostStore(mf_dim=4, capacity=1 << 12)
    with pytest.raises(ValueError, match="extension block"):
        PassScopedTable(hs2, pass_capacity=64, cfg=cfg)
