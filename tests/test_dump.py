"""Dump subsystem: per-sample prediction lines + param dump
(boxps_worker.cc:1595-1858 semantics)."""

import glob

import numpy as np
import optax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory, SlotDef
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer
from paddlebox_tpu.utils.dump import DumpConfig, DumpWriter, dump_param


def make_ds(n=300, num_slots=3):
    rng = np.random.default_rng(0)
    desc = DataFeedDesc(
        slots=[SlotDef(name=f"s{i}") for i in range(num_slots)]
        + [SlotDef(name="d0", type="float", dim=2)],
        batch_size=64)
    desc.key_bucket_min = 512
    recs = []
    for i in range(n):
        keys = rng.integers(0, 40, size=num_slots).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=np.arange(num_slots + 1, dtype=np.int32),
            dense=rng.normal(size=2).astype(np.float32),
            label=float(i % 3 == 0), ins_id=f"ins_{i:05d}"))
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.records = recs
    return desc, ds


def test_dump_writer_lines(tmp_path):
    cfg = DumpConfig(str(tmp_path / "dump"), fields=["pred", "label"])
    w = DumpWriter(cfg)
    w.add_batch(["a", "b"], {"pred": np.array([0.25, 0.5]),
                             "label": np.array([1.0, 0.0])}, 2)
    w.add_batch(None, {"pred": np.array([0.75]),
                       "label": np.array([1.0])}, 1)
    assert w.close() == 3
    [f] = glob.glob(str(tmp_path / "dump.part-*"))
    lines = open(f).read().strip().split("\n")
    assert lines[0] == "a\tpred:0.25\tlabel:1"
    assert lines[2].startswith("2\tpred:0.75")  # auto id when no ins_id


def test_trainer_dump_pass(tmp_path):
    desc, ds = make_ds()
    table = EmbeddingTable(mf_dim=4, capacity=1 << 10,
                           cfg=SparseSGDConfig(), unique_bucket_min=512)
    tr = Trainer(CtrDnn(hidden=(16,)), table, desc, tx=optax.adam(1e-3))
    tr.set_dump(DumpConfig(str(tmp_path / "day1/preds"),
                           fields=["pred", "label", "clk"]))
    tr.train_pass(ds)
    [f] = glob.glob(str(tmp_path / "day1/preds.part-*"))
    lines = open(f).read().strip().split("\n")
    assert len(lines) == len(ds.records)
    first = lines[0].split("\t")
    assert first[0] == "ins_00000"
    kv = dict(p.split(":") for p in first[1:])
    assert set(kv) == {"pred", "label", "clk"}
    assert 0.0 <= float(kv["pred"]) <= 1.0
    # disable: next pass writes nothing new
    tr.set_dump(None)
    tr.train_pass(ds)
    assert len(glob.glob(str(tmp_path / "day1/preds.part-*"))) == 1


def test_dump_param(tmp_path):
    desc, ds = make_ds(n=64)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 10,
                           cfg=SparseSGDConfig(), unique_bucket_min=512)
    tr = Trainer(CtrDnn(hidden=(16,)), table, desc, tx=optax.adam(1e-3))
    path = str(tmp_path / "params.npz")
    n = tr.dump_param(path)
    assert n > 0
    blob = np.load(path)
    assert any("kernel" in k or "Dense" in k for k in blob.files)


def test_sharded_trainer_dump_pass(tmp_path):
    """Per-sample dump from the MESH trainer: every device row of every
    global batch dumps in worker order, tail-group fillers excluded
    (the every-worker DumpField role at pod scale)."""
    import jax
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer
    assert len(jax.devices()) >= 8
    desc, ds = make_ds(n=300)  # 300 records, bs 64 → tail filler batches
    table = ShardedEmbeddingTable(8, mf_dim=4, capacity_per_shard=256,
                                  cfg=SparseSGDConfig(),
                                  req_bucket_min=32, serve_bucket_min=32)
    tr = ShardedTrainer(CtrDnn(hidden=(16,)), table, desc,
                        make_mesh(8), tx=optax.adam(1e-3))
    tr.set_dump(DumpConfig(str(tmp_path / "mesh/preds"),
                           fields=["pred", "label"]))
    tr.train_pass(ds)
    # one part file per DEVICE row (the reference's per-worker dump
    # channel, boxps_worker.cc:1595); concatenated in device order the
    # parts cover every record exactly once
    files = sorted(glob.glob(str(tmp_path / "mesh/preds.part-*")))
    lines = [ln for f in files
             for ln in open(f).read().strip().split("\n") if ln]
    assert len(lines) == len(ds.records)  # every record exactly once
    ids = [ln.split("\t")[0] for ln in lines]
    assert ids[0] == "ins_00000" and len(set(ids)) == len(ids)
    for ln in lines[:5]:
        kv = dict(p.split(":") for p in ln.split("\t")[1:])
        assert 0.0 <= float(kv["pred"]) <= 1.0
    n_files = len(files)
    tr.set_dump(None)
    tr.train_pass(ds)
    assert len(glob.glob(str(tmp_path / "mesh/preds.part-*"))) == n_files
