"""Streaming ingest (ISSUE 6): windowed ``QueueDataset`` cursors,
at-least-once window replay, ``Trainer.train_stream`` arrival polling,
reader-lifecycle hardening (abandon cleanup, prompt error surfacing),
the pipeline hang deadline, and the stream/consensus quarantine
interplay."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import types

import numpy as np
import optax
import pytest

from paddlebox_tpu.config import FLAGS, flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.obs import MemorySink, get_hub, reset_hub
from paddlebox_tpu.resilience import preemption
from paddlebox_tpu.resilience.consensus import (DirConsensusStore,
                                                RestoreConsensus,
                                                sync_shared_quarantine)
from paddlebox_tpu.resilience.faults import FaultPlan, installed
from paddlebox_tpu.resilience.preemption import PreemptedError
from paddlebox_tpu.train.checkpoint import CheckpointManager


@pytest.fixture(autouse=True)
def clean_preempt_state():
    preemption.clear_stop()
    yield
    preemption.clear_stop()
    preemption.uninstall_signal_handlers()


@pytest.fixture()
def fresh_hub():
    hub = reset_hub()
    yield hub
    reset_hub()


def _files(tmp_path, n=4, rows=48, seed=11):
    return generate_criteo_files(str(tmp_path / "data"), num_files=n,
                                 rows_per_file=rows, vocab_per_slot=40,
                                 seed=seed)


def _qds(files, bs=16):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 2048
    ds = DatasetFactory().create_dataset("QueueDataset", desc)
    ds.set_filelist(files)
    return ds


def _reader_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("pbox-reader") and t.is_alive()]


def _consume(ds):
    """Drain a windowed stream the way the trainer does: report each
    batch consumed so windows fold (raw drains fold nothing)."""
    sizes = []
    for b in ds.batches():
        sizes.append(int((b.show > 0).sum()))
        ds.note_batches_consumed(len(sizes))
    ds.note_batches_consumed(len(sizes))  # tail-window fold
    return sizes


# ---- windowed batches: shape and completion ---------------------------
def test_windowed_batches_flush_at_window_boundary(tmp_path):
    files = _files(tmp_path, n=3, rows=40)  # 40 rows, bs 16 -> 2.5
    with flags_scope(stream_window_files=2, read_thread_num=1):
        ds = _qds(files)
        assert ds.supports_cursor_resume and ds.windowed
        sizes = _consume(ds)
    # window 1 = 80 records (16x5), window 2 = 40 (16,16,8): the tail
    # batch flushes SHORT at the window boundary — no record crosses it
    assert sizes == [16, 16, 16, 16, 16, 16, 16, 8]
    assert ds.files_completed == files
    assert ds.windows_completed == 2
    assert ds.pending_files() == []


def test_unwindowed_refusal_and_windowed_start_batch_refusal(tmp_path):
    files = _files(tmp_path, n=2)
    ds = _qds(files)
    assert not ds.supports_cursor_resume
    with pytest.raises(ValueError, match="deterministic"):
        next(ds.batches(start_batch=1))
    with flags_scope(stream_window_files=2):
        assert ds.supports_cursor_resume
        with pytest.raises(ValueError, match="FILE WINDOW"):
            next(ds.batches(start_batch=1))


def test_stream_cursor_tracks_consumption_not_readahead(tmp_path):
    """A window only counts completed once the CONSUMER reports its
    final batch trained — read-ahead (marks set by the generator) must
    never complete a half-trained window."""
    files = _files(tmp_path, n=4, rows=32)  # 2 batches/file
    with flags_scope(stream_window_files=2, read_thread_num=1):
        ds = _qds(files)
        it = ds.batches()
        for _ in range(5):  # pull 5 of 8: one past window 1's last
            next(it)
        # generator is at least one batch ahead; window 1's mark is 4
        s3 = ds.stream_cursor_state(3)   # 3 trained: window 1 open
        assert s3["files_completed"] == [] and \
            s3["window_files"] == files[:2]
        s4 = ds.stream_cursor_state(4)   # 4 trained: window 1 complete
        assert s4["files_completed"] == files[:2]
        assert s4["window_files"] == files[2:4]
        assert s4["windows_completed"] == 1
        it.close()
        # boundary state between passes reflects only FOLDED windows —
        # the abandoned pass folded nothing, both windows replay
        assert ds.stream_cursor_state(None)["files_completed"] == []


def test_adopt_stream_cursor_skips_completed_replays_window(tmp_path,
                                                            fresh_hub):
    files = _files(tmp_path, n=6, rows=32)
    with flags_scope(stream_window_files=2, read_thread_num=1):
        ds = _qds(files)
        ds.adopt_stream_cursor(
            {"windowed": True, "files_completed": files[:2],
             "window_files": files[2:4], "windows_completed": 1},
            quarantined=[files[4]])
        # completed skipped, quarantine preseeded (budget-free), open
        # window + the rest pending
        assert ds.pending_files() == files[2:4] + [files[5]]
        assert dict(ds.quarantined_files)[files[4]].startswith(
            "preseeded")
        sizes = _consume(ds)
        # replayed window (2 files x 2 batches) + the last file solo
        # (2 batches, flushed at its own window boundary)
        assert len(sizes) == 6
        assert ds.files_replayed == 2
        assert ds.files_completed == files[:4] + [files[5]]
        assert fresh_hub.counter(
            "pbox_stream_replayed_files_total").value() == 2


def test_windowed_quarantine_is_cross_window_sticky(tmp_path):
    """A file quarantined in window k stays quarantined for the rest of
    the stream (no _reset_quarantine between windows), is excluded from
    files_completed, and the preseeded skip set never consumes the
    poison budget."""
    files = _files(tmp_path, n=4, rows=32)
    bad = files[1]
    with open(bad, "w") as fh:
        fh.write("garbage\tnot\ta\trecord\n" * 10)
    with flags_scope(stream_window_files=2, read_thread_num=1,
                     poison_budget_files=1, poison_budget_records=0):
        ds = _qds(files)
        ds.preseed_quarantine(["/elsewhere/preseeded.txt"])
        sizes = _consume(ds)
        assert len(sizes) == 6  # 3 healthy files x 2 batches each
        quar = [p for p, _ in ds.quarantined_files]
        assert bad in quar and "/elsewhere/preseeded.txt" in quar
        assert bad not in ds.files_completed
        assert ds.files_completed == [files[0], files[2], files[3]]


def test_windowed_poison_budget_resets_per_load(tmp_path):
    """FLAGS.poison_budget_files is per LOAD (config.py), not per
    process lifetime: a bad file quarantined in an earlier windowed pass
    must not consume the budget of a later pass — an always-on stream
    survives bad files arriving far apart, while the decisions stay
    sticky."""
    files = _files(tmp_path, n=3, rows=32)
    with open(files[0], "w") as fh:
        fh.write("garbage\tnot\ta\trecord\n" * 10)
    with flags_scope(stream_window_files=1, read_thread_num=1,
                     poison_budget_files=1, poison_budget_records=0):
        ds = _qds(files)
        _consume(ds)
        assert [p for p, _ in ds.quarantined_files] == [files[0]]
        # a new bad arrival, consumed in a LATER pass: the prior
        # quarantine folds into the preseeded count, so the fresh
        # load's budget of 1 covers it
        late = str(tmp_path / "data" / "late_bad.txt")
        with open(late, "w") as fh:
            fh.write("garbage\tnot\ta\trecord\n" * 10)
        ds.set_filelist(ds.files_completed
                        + [p for p, _ in ds.quarantined_files] + [late])
        _consume(ds)
        quar = [p for p, _ in ds.quarantined_files]
        assert quar == [files[0], late]  # sticky + newly budgeted


# ---- reader lifecycle (satellites 1+2) --------------------------------
def test_abandoned_stream_leaves_no_reader_threads(tmp_path):
    files = _files(tmp_path, n=3, rows=200)
    for window in (0, 2):  # legacy and windowed paths both clean up
        with flags_scope(stream_window_files=window, read_thread_num=3,
                         channel_capacity=8):
            ds = _qds(files)
            it = ds.batches()
            next(it)
            assert _reader_threads(), "readers should be running"
            it.close()  # consumer abandons the generator
            deadline = time.monotonic() + 5
            while _reader_threads() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not _reader_threads(), \
                f"reader threads survived abandonment (window={window})"


def test_reader_error_surfaces_within_one_batch(tmp_path):
    """A reader that dies on file 1 must raise within a batch of the
    failure — not after the surviving readers drained the whole list
    (the old group.join()-at-stream-end behavior)."""
    files = _files(tmp_path, n=4, rows=120)  # ~30 batches total
    plan = FaultPlan.parse(
        f"reader.file:fail:nth=1,match=*{os.path.basename(files[0])}*")
    with flags_scope(read_thread_num=2), installed(plan):
        ds = _qds(files)
        n = 0
        with pytest.raises(Exception, match="injected fault"):
            for _ in ds.batches():
                n += 1
        assert n <= 5, f"error surfaced only after {n} batches"
    assert not _reader_threads()


# ---- pipeline hang deadline (satellite 3) -----------------------------
def test_epilogue_fence_hang_deadline(fresh_hub):
    from paddlebox_tpu.ps.epilogue import PassEpilogue, PipelineHangError
    ep = PassEpilogue("t")
    release = threading.Event()
    ep.submit(release.wait, label="wedged")
    with flags_scope(pipeline_wait_timeout_sec=0.3):
        with pytest.raises(PipelineHangError, match="endpass.writeback"):
            ep.fence()
    release.set()
    ep.fence()  # the un-wedged worker drains fine afterwards
    assert ep.stats()["pending"] == 0
    assert fresh_hub.counter("pbox_pipeline_hangs_total").value(
        stage="endpass.writeback") == 1


def test_preloader_wait_hang_deadline(fresh_hub):
    from paddlebox_tpu.ps.epilogue import PipelineHangError
    from paddlebox_tpu.train.device_pass import PassPreloader
    release = threading.Event()

    def build(ds):
        release.wait(10)
        return types.SimpleNamespace(upload=lambda **kw: None,
                                     nbytes=lambda: 0, dev=None)

    pre = PassPreloader(iter([1, 2]), build_fn=build, depth=1)
    pre.start_next()
    with flags_scope(pipeline_wait_timeout_sec=0.3):
        with pytest.raises(PipelineHangError, match="preload.build"):
            pre.wait()
    release.set()
    assert pre.wait() is not None  # build completes once un-wedged
    pre.drain()
    assert fresh_hub.counter("pbox_pipeline_hangs_total").value(
        stage="preload.build") == 1


def test_fence_slow_but_moving_pipeline_does_not_trip():
    from paddlebox_tpu.ps.epilogue import PassEpilogue
    ep = PassEpilogue("t")
    for _ in range(4):
        ep.submit(lambda: time.sleep(0.15))
    with flags_scope(pipeline_wait_timeout_sec=0.4):
        ep.fence()  # each job beats the deadline: progress resets it
    assert ep.stats()["pending"] == 0


# ---- consensus interplay ----------------------------------------------
def test_shared_quarantine_preseeds_windowed_stream(tmp_path):
    files = _files(tmp_path, n=4)
    store = DirConsensusStore(str(tmp_path / "consensus"))
    with flags_scope(stream_window_files=2):
        ds0, ds1 = _qds(files), _qds(files)
        ds0.quarantined_files.append((files[1], "IOError: local"))
        out = [None, None]

        def rank(i, ds):
            out[i] = sync_shared_quarantine(
                ds, RestoreConsensus(store, i, 2, timeout=20))

        ths = [threading.Thread(target=rank, args=(i, d))
               for i, d in enumerate([ds0, ds1])]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert out[0] == out[1] == [files[1]]
        # both ranks' future windows drop the same file
        assert ds0.pending_files() == ds1.pending_files()
        assert files[1] not in ds1.pending_files()

    # legacy unwindowed streams are still refused
    ds2 = _qds(files)
    with pytest.raises(TypeError, match="WINDOWED"):
        sync_shared_quarantine(ds2, RestoreConsensus(store, 0, 1,
                                                     timeout=5))


# ---- train_stream e2e --------------------------------------------------
def _mk_trainer(desc, seed=0):
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=2048)
    return Trainer(CtrDnn(hidden=(8,)), table, desc,
                   tx=optax.adam(1e-2), seed=seed)


def test_train_stream_arrivals_idle_and_boundary_ckpt(tmp_path,
                                                      fresh_hub):
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    files = _files(tmp_path, n=4, rows=32)
    root = str(tmp_path / "ckpt")
    polls = {"n": 0}

    def filelist_fn():
        polls["n"] += 1
        # files arrive two at a time, with an empty poll in between
        return files[:2] if polls["n"] < 3 else files

    with flags_scope(stream_window_files=2, read_thread_num=1,
                     stream_ckpt_every_windows=1,
                     retry_base_delay_sec=0.01,
                     retry_max_delay_sec=0.02):
        desc = DataFeedDesc.criteo(batch_size=16)
        desc.key_bucket_min = 2048
        tr = _mk_trainer(desc)
        ds = _qds(files[:2])
        cm = CheckpointManager(root)
        out = tr.train_stream(ds, cm, filelist_fn=filelist_fn,
                              max_idle_polls=3)
        assert out["windows"] == 2 and out["files"] == 4
        assert out["idle_polls"] >= 1
        assert ds.files_completed == files
        # the newest checkpoint is a STREAM BOUNDARY: completed files
        # recorded (older history compacted to count+fingerprint after
        # each boundary publish), open window empty — a rollback target
        cur = cm.load_cursor()
        assert cur["version"] == 2
        st = cur["stream"]
        assert st["files_completed"] == files[2:]
        assert st["files_folded"]["count"] == 2
        assert st["window_files"] == []
        assert cm.latest_boundary_step() == cm.latest_step()
        names = [e["event"] for e in sink.events]
        assert "stream_window" in names and "stream_idle" in names
        assert fresh_hub.counter("pbox_stream_windows_total").value() == 2


def test_train_stream_continues_across_calls(tmp_path):
    """max_windows bounds one call but must not lose the rest of the
    stream: each window pass narrows the dataset filelist to its
    consumption order, and train_stream restores the full known list on
    exit so a later call picks up where the first stopped."""
    files = _files(tmp_path, n=4, rows=32)
    with flags_scope(stream_window_files=2, read_thread_num=1):
        desc = DataFeedDesc.criteo(batch_size=16)
        desc.key_bucket_min = 2048
        tr = _mk_trainer(desc)
        ds = _qds(files)
        cm = CheckpointManager(str(tmp_path / "ckpt"))
        out1 = tr.train_stream(ds, cm, max_windows=1)
        assert out1["windows"] == 1
        assert ds.filelist == files  # full stream still visible
        assert ds.pending_files() == files[2:]
        out2 = tr.train_stream(ds, cm)
        assert out2["windows"] == 1
        assert ds.files_completed == files


def test_stream_cursor_history_compaction_bounded(tmp_path):
    """ISSUE 7 satellite (ROADMAP item 5): the boundary-checkpoint
    cadence folds completed-file history into a count + chained
    fingerprint, so cursor.json stops growing O(files consumed) — the
    serialized tail stays bounded by the checkpoint interval while the
    in-memory view keeps every name."""
    from paddlebox_tpu.data.dataset import chain_digest
    files = _files(tmp_path, n=8, rows=32)
    with flags_scope(stream_window_files=2, read_thread_num=1,
                     stream_ckpt_every_windows=1):
        desc = DataFeedDesc.criteo(batch_size=16)
        desc.key_bucket_min = 2048
        tr = _mk_trainer(desc)
        ds = _qds(files)
        cm = CheckpointManager(str(tmp_path / "ckpt"))
        out = tr.train_stream(ds, cm)
        assert out["windows"] == 4
        # in-memory history is complete; the SERIALIZED cursor carries
        # only the files since the previous boundary + the fingerprint
        assert ds.files_completed == files
        st = cm.load_cursor()["stream"]
        assert st["files_completed"] == files[6:]
        assert st["files_folded"]["count"] == 6
        assert st["files_folded"]["sha256"] == chain_digest("", files[:6])
        # every on-disk cursor of the run is bounded the same way
        for step in cm.steps():
            cur = cm.load_cursor(step)
            if cur is None or "stream" not in cur:
                continue
            assert len(cur["stream"]["files_completed"]) <= 2, cur


def test_folded_cursor_resume_skips_completed(tmp_path):
    """A restart from a cursor whose history is folded re-derives the
    folded prefix from the filelist (fingerprint-checked), skips it,
    and consumes only the remaining stream."""
    files = _files(tmp_path, n=8, rows=32)
    with flags_scope(stream_window_files=2, read_thread_num=1,
                     stream_ckpt_every_windows=1):
        desc = DataFeedDesc.criteo(batch_size=16)
        desc.key_bucket_min = 2048
        root = str(tmp_path / "ckpt")
        tr = _mk_trainer(desc)
        out1 = tr.train_stream(_qds(files), CheckpointManager(root),
                               max_windows=2)
        assert out1["windows"] == 2
        st = CheckpointManager(root).load_cursor()["stream"]
        assert st["files_folded"]["count"] == 2   # folded history
        # fresh process: restore, then stream the SAME filelist
        tr2 = _mk_trainer(desc)
        cm2 = CheckpointManager(root)
        assert cm2.restore(tr2) == tr.global_step
        ds2 = _qds(files)
        out2 = tr2.train_stream(ds2, cm2)
        assert out2["windows"] == 2          # only the remaining half
        assert out2["files"] == 4
        assert out2["replayed_files"] == 0   # boundary cursor: no window
        assert ds2.files_completed == files


def test_folded_cursor_filelist_mismatch_is_loud(tmp_path):
    """A filelist that no longer reproduces the folded fingerprint must
    refuse adoption with a clear error — never silently skip the wrong
    files."""
    from paddlebox_tpu.data.dataset import chain_digest
    files = _files(tmp_path, n=4, rows=32)
    with flags_scope(stream_window_files=2, read_thread_num=1):
        ds = _qds([files[1], files[0]] + files[2:])  # reordered prefix
        with pytest.raises(ValueError, match="folded"):
            ds.adopt_stream_cursor(
                {"windowed": True, "files_completed": [],
                 "window_files": files[2:4], "windows_completed": 1,
                 "files_folded": {
                     "count": 2,
                     "sha256": chain_digest("", files[:2])}})


@pytest.mark.chaos
def test_train_stream_window_fault_retries_and_replays(tmp_path,
                                                       fresh_hub):
    """The stream.window chaos seam: a transient fault on window 2's
    dispatch rolls back to the window-1 boundary checkpoint and replays
    window 2 — the stream completes with a pass retry, not a crash."""
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    files = _files(tmp_path, n=4, rows=32)
    plan = FaultPlan.parse("stream.window:fail:nth=2")
    with flags_scope(stream_window_files=2, read_thread_num=1,
                     stream_ckpt_every_windows=1, pass_retry_limit=1,
                     retry_base_delay_sec=0.01,
                     retry_max_delay_sec=0.02), installed(plan):
        desc = DataFeedDesc.criteo(batch_size=16)
        desc.key_bucket_min = 2048
        tr = _mk_trainer(desc)
        ds = _qds(files)
        cm = CheckpointManager(str(tmp_path / "ckpt"))
        out = tr.train_stream(ds, cm)
        assert out["windows"] == 2
        assert ds.files_completed == files
    assert plan.stats()["stream.window:fail"]["fired"] == 1
    names = [e["event"] for e in sink.events]
    # the retry restored the window-1 boundary and re-dispatched
    # window 2 in-process — a pass_retry, NOT a cursor_resume (the
    # dataset never lost its stream position)
    assert "pass_retry" in names
    assert "cursor_resume" not in names


# ---- real SIGTERM on a real streaming process (satellite 4) -----------
_STREAM_WORKER = textwrap.dedent("""
    import collections, json, os, signal, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    import optax

    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.resilience.preemption import PreemptedError
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    phase, data_dir, ckpt_root, counts_path, beacon = sys.argv[1:6]
    FLAGS.graceful_shutdown = True
    FLAGS.stream_window_files = 2
    FLAGS.stream_ckpt_every_windows = 1
    FLAGS.read_thread_num = 1

    desc = DataFeedDesc.criteo(batch_size=16)
    desc.key_bucket_min = 2048
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=2048)
    trainer = Trainer(CtrDnn(hidden=(8,)), table, desc,
                      tx=optax.adam(1e-2), seed=0)

    files = sorted(os.path.join(data_dir, f)
                   for f in os.listdir(data_dir))
    ds = DatasetFactory().create_dataset("QueueDataset", desc)
    ds.set_filelist(files)
    cm = CheckpointManager(ckpt_root)

    # per-record training counts, APPENDED per batch (crash-safe): one
    # record signature per line
    fh = open(counts_path, "a")
    def on_batch(b):
        n = int((b.show > 0).sum())
        S = b.num_slots
        keys = b.keys[:n * S].reshape(n, S)
        for i in range(n):
            fh.write(keys[i].tobytes().hex() + "\\n")
        fh.flush()
        if phase == "run" and trainer.global_step == 3:
            open(beacon, "w").write("mid-stream")
        if phase == "run":
            time.sleep(0.05)  # let the parent's SIGTERM land mid-window
    trainer.on_batch_trained = on_batch

    if phase == "resume":
        cm.restore(trainer)
    try:
        trainer.train_stream(ds, cm)
    except PreemptedError as e:
        sys.exit(preemption.EXIT_RESUME)
    sys.exit(0)
""")


@pytest.mark.slow
@pytest.mark.chaos
def test_real_sigterm_stream_resumes_at_least_once(tmp_path):
    """A real SIGTERM to a real windowed streaming process: graceful
    exit with EXIT_RESUME + a stream-cursor emergency checkpoint, and
    the restarted process trains every input record at-least-once with
    completed-window records exactly once (kept in the slow tier: two
    subprocess jax start-ups; scripts/stream_check.py gates the same
    contract in-process in tier-1)."""
    data_dir = str(tmp_path / "data")
    generate_criteo_files(data_dir, num_files=6, rows_per_file=48,
                          vocab_per_slot=40, seed=3)
    files = sorted(os.path.join(data_dir, f)
                   for f in os.listdir(data_dir))
    ckpt_root = str(tmp_path / "ckpt")
    counts = str(tmp_path / "counts.txt")
    beacon = str(tmp_path / "beacon")
    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as fh:
        fh.write(_STREAM_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))

    proc = subprocess.Popen(
        [sys.executable, worker, "run", data_dir, ckpt_root, counts,
         beacon],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    deadline = time.monotonic() + 180
    while not os.path.exists(beacon):
        assert proc.poll() is None, \
            f"worker died early:\n{proc.stdout.read()}"
        assert time.monotonic() < deadline, "beacon never appeared"
        time.sleep(0.02)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=180)
    assert proc.returncode == preemption.EXIT_RESUME, \
        f"rc={proc.returncode}\n{out}"
    cur = json.load(open(os.path.join(
        ckpt_root, sorted(n for n in os.listdir(ckpt_root)
                          if n.startswith("ckpt-"))[-1], "cursor.json")))
    open_window = cur["stream"]["window_files"]
    completed = cur["stream"]["files_completed"]
    assert open_window, "SIGTERM was meant to land mid-window"

    rc = subprocess.run(
        [sys.executable, worker, "resume", data_dir, ckpt_root, counts,
         beacon],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=300)
    assert rc.returncode == 0, rc.stdout

    trained = {}
    with open(counts) as fh:
        for line in fh:
            sig = line.strip()
            trained[sig] = trained.get(sig, 0) + 1
    # expected signatures per file, built the same way the worker does
    from paddlebox_tpu.data.parser import get_parser
    desc = DataFeedDesc.criteo(batch_size=16)
    done_files = set(completed) | (set(files) - set(open_window))
    for path in files:
        parser = get_parser(desc)
        with open(path) as f:
            for line in f:
                rec = parser.parse(line)
                sig = rec.keys.tobytes().hex()
                n = trained.get(sig, 0)
                assert n >= 1, f"record of {path} never trained"
                if path in done_files:
                    assert n == 1, (path, n)
                else:
                    assert n <= 2, (path, n)
