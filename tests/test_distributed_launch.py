"""Launcher + elastic manager tests (reference: fleet/elastic/manager.py,
distributed/launch.py — here exercised multi-process on localhost, the
same strategy the reference uses for its distributed tests, SURVEY §4)."""

import os
import sys
import time

import pytest

from paddlebox_tpu.distributed import (ElasticLevel, ElasticManager,
                                       FileKVStore, LaunchConfig,
                                       launch_local)


def test_file_kv_store_roundtrip(tmp_path):
    kv = FileKVStore(str(tmp_path))
    kv.put("a/b", b"1")
    kv.put("a/c", b"2")
    assert kv.get("a/b") == b"1"
    assert kv.get("missing") is None
    assert set(kv.list_prefix("a").values()) == {b"1", b"2"}
    kv.delete("a/b")
    assert kv.get("a/b") is None
    assert kv.mtime("a/c") > 0


def test_elastic_membership_and_scale_down(tmp_path):
    kv = FileKVStore(str(tmp_path))
    m1 = ElasticManager(kv, "job", "hostA", np=2, ttl=0.5,
                        heartbeat_period=0.1)
    m2 = ElasticManager(kv, "job", "hostB", np=2, ttl=0.5,
                        heartbeat_period=0.1)
    m1.register()
    m2.register()
    assert m1.wait_for_np(timeout=5.0) == ["hostA", "hostB"]
    assert m1.world_ok()
    assert m1.scale_event() is None  # no change yet

    # hostB dies: heartbeat stops, lease expires
    m2.deregister()
    time.sleep(0.7)
    ev = m1.scale_event()
    assert ev == ["hostA"]
    assert not m1.world_ok()  # FAULT_TOLERANCE needs np==2
    m1.deregister()


def test_elastic_level_window(tmp_path):
    kv = FileKVStore(str(tmp_path))
    m = ElasticManager(kv, "job2", "h0", np=4, min_np=2, max_np=4,
                       ttl=0.5, heartbeat_period=0.1)
    assert m.level == ElasticLevel.ELASTIC
    m.register()
    # only one host alive: below min_np
    assert not m.world_ok()
    with pytest.raises(TimeoutError):
        m.wait_for_np(timeout=0.4)
    # second host joins: inside [2,4] window
    m2 = ElasticManager(kv, "job2", "h1", np=4, min_np=2, max_np=4,
                        ttl=0.5, heartbeat_period=0.1)
    m2.register()
    assert m.wait_for_np(timeout=5.0) == ["h0", "h1"]
    m.deregister()
    m2.deregister()


def test_checkpoint_pointer(tmp_path):
    kv = FileKVStore(str(tmp_path))
    m = ElasticManager(kv, "job3", "h0", np=1)
    assert m.latest_checkpoint() is None
    m.publish_checkpoint("/models/delta_7", pass_id=7)
    ckpt = m.latest_checkpoint()
    assert ckpt == {"path": "/models/delta_7", "pass_id": 7}


def test_launch_local_ranks(tmp_path):
    out = tmp_path / "ranks"
    out.mkdir()
    code = (
        "import os, pathlib; "
        "pathlib.Path(os.environ['OUT'], os.environ['PBOX_RANK'])"
        ".write_text(os.environ['PBOX_WORLD_SIZE'])"
    )
    os.environ["OUT"] = str(out)
    try:
        rc = launch_local([sys.executable, "-c", code],
                          LaunchConfig(nproc=3))
    finally:
        del os.environ["OUT"]
    assert rc == 0
    got = sorted(os.listdir(out))
    assert got == ["0", "1", "2"]
    assert (out / "0").read_text() == "3"


def test_launch_elastic_restart_resumes_from_checkpoint(tmp_path):
    """First gang run fails; launcher restarts it with the published
    checkpoint path in PBOX_RESUME_CKPT; second run succeeds."""
    kvroot = tmp_path / "kv"
    marker = tmp_path / "attempts"
    marker.mkdir()
    kv = FileKVStore(str(kvroot))
    boot = ElasticManager(kv, "jobL", "seed", np=1)
    boot.publish_checkpoint(str(tmp_path / "ckpt_pass3"), pass_id=3)

    code = (
        "import os, pathlib, sys\n"
        "d = pathlib.Path(os.environ['MARK'])\n"
        "n = len(list(d.iterdir()))\n"
        "(d / str(n)).write_text(os.environ.get('PBOX_RESUME_CKPT', ''))\n"
        "sys.exit(1 if n == 0 else 0)\n"
    )
    os.environ["MARK"] = str(marker)
    try:
        rc = launch_local(
            [sys.executable, "-c", code],
            LaunchConfig(nproc=1, job_id="jobL",
                         elastic_root=str(kvroot), max_restarts=2))
    finally:
        del os.environ["MARK"]
    assert rc == 0
    # two attempts, both saw the checkpoint pointer
    assert (marker / "1").read_text().endswith("ckpt_pass3")


ELASTIC_WORKER = """
import json, os, pathlib, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.distributed import ElasticManager, TcpKVStore
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import BoxPSHelper, SparseSGDConfig
from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
from paddlebox_tpu.train.checkpoint import CheckpointManager
from paddlebox_tpu.train.sharded import ShardedTrainer

rank = int(os.environ["PBOX_RANK"])
table_kind = os.environ.get("TABLE_KIND", "sharded")
world = int(os.environ["PBOX_WORLD_SIZE"])
out_dir = pathlib.Path(sys.argv[1])
n_passes = int(os.environ["N_PASSES"])
kill_after = os.environ.get("KILL_RANK1_AFTER_PASS")
resume = os.environ.get("PBOX_RESUME_CKPT")
FLAGS.log_period_steps = 10 ** 9

# membership over the NETWORK KV (the etcd lease/watch flow); the
# worker MEMBERSHIP job is distinct from the launcher's own job, but
# checkpoint pointers publish to the LAUNCHER's job id ("jobE") — that
# is where launch_local reads the restart pointer from
kv = TcpKVStore(os.environ["KV_ENDPOINT"])
em = ElasticManager(kv, "jobE-workers", f"host{rank}", np=world,
                    min_np=world, ttl=5.0)
pub = ElasticManager(kv, "jobE", f"pub{rank}", np=1)  # not registered
em.register()
em.wait_for_np(timeout=60)

# per-rank data shard (generated, deterministic)
data_dir = out_dir / f"data_r{rank}"
files = generate_criteo_files(str(data_dir), num_files=1,
                              rows_per_file=600, vocab_per_slot=30,
                              seed=100 + rank)
desc = DataFeedDesc.criteo(batch_size=32)
desc.key_bucket_min = 1024
ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
ds.set_filelist(files)
ds.load_into_memory()

MESH_N = 4
cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                      learning_rate=0.1, mf_learning_rate=0.1)
if table_kind == "tiered":
    # the production topology: per-process host-tier stores fronting the
    # HBM pass windows — a replacement rank has EMPTY stores and must
    # rebuild them from the save_base/delta chain (box_wrapper.cc:1415)
    table = TieredShardedEmbeddingTable(
        MESH_N, mf_dim=4, capacity_per_shard=4096, cfg=cfg,
        req_bucket_min=128, serve_bucket_min=128)
else:
    table = ShardedEmbeddingTable(MESH_N, mf_dim=4, capacity_per_shard=4096,
                                  cfg=cfg, req_bucket_min=128,
                                  serve_bucket_min=128)
tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, make_mesh(MESH_N),
                    tx=optax.adam(2e-3), seed=7 + rank)
helper = BoxPSHelper(table, trainer=tr) if table_kind == "tiered" else None
nb_per_pass = sum(1 for _ in tr._group_iter(ds.batches()))

cm = CheckpointManager(str(out_dir / f"ckpt_r{rank}"), keep=10)
start_pass = 0
if resume:
    restored = cm.restore(tr)
    if restored is not None:
        start_pass = restored // nb_per_pass
        print(f"rank {rank}: resumed step {restored} -> pass {start_pass}",
              flush=True)

res = None
for p in range(start_pass, n_passes):
    if helper is not None:
        helper.begin_pass(ds)
    res = tr.train_pass(ds)
    if helper is not None:
        helper.end_pass(ds)
    if kill_after is not None and resume is None and rank == 1 \\
            and p == int(kill_after):
        # die WITHOUT checkpointing this pass: the work since the last
        # save is lost; the restarted gang must replay it from the
        # published pointer. Wait for rank 0 to have PUBLISHED a pointer
        # first — otherwise the restart also sees no pointer and this
        # rank kills itself again (raced in CI when rank 0 lagged)
        import time as _time
        deadline = _time.time() + 120
        reader = ElasticManager(kv, "jobE", f"rd{rank}", np=1)
        while _time.time() < deadline \\
                and reader.latest_checkpoint() is None:
            _time.sleep(0.2)
        os._exit(1)
    # tiered: exercise the base + DELTA chain (the xbox save pattern) —
    # restore must replay it into the rebuilt host stores
    cm.save(tr, delta=(table_kind == "tiered" and p > 0))
    if rank == 0:
        pub.publish_checkpoint(str(out_dir), pass_id=p)

if res is not None:
    params = np.concatenate([np.asarray(l).ravel()
                             for l in jax.tree.leaves(tr.state.params)])
    out = dict(rank=rank, auc=float(res["auc"]),
               last_loss=float(res["last_loss"]),
               global_step=int(tr.global_step),
               param_sum=float(np.abs(params).sum()),
               features=int(table.feature_count()))
    if table_kind == "tiered":
        # host-tier content fingerprint: the rebuilt-from-checkpoint
        # stores must match the uninterrupted run's
        hsum = 0.0
        for hs in table.hosts:
            ks, _ = hs.index.items()
            if len(ks):
                hsum += float(np.abs(
                    hs.fetch(np.sort(ks))["embed_w"]).sum())
        out["host_sum"] = hsum
    with open(out_dir / f"final_r{rank}.json", "w") as fh:
        json.dump(out, fh)
    np.save(out_dir / f"params_r{rank}.npy", params)
else:
    # this rank had already finished before a peer-triggered gang
    # restart — its final artifacts are on disk from the first attempt
    assert (out_dir / f"final_r{rank}.json").exists()
em.deregister()
"""


@pytest.mark.slow
@pytest.mark.parametrize("table_kind", ["sharded", "tiered"])
def test_elastic_restart_of_real_sharded_trainer(tmp_path, table_kind):
    """THE elastic flagship (fleet/elastic/manager.py:131,248-250): a
    2-process gang of REAL ShardedTrainers (4-dev virtual CPU mesh each),
    membership over TcpKVStore. Rank 1 is killed mid-run WITHOUT saving
    its in-flight pass; the launcher restarts the gang from the published
    checkpoint pointer; both ranks resume at their last pass boundary.
    The final AUC/loss/params must MATCH an uninterrupted run.

    ``tiered`` composes the gang restart with
    TieredShardedEmbeddingTable — the production topology where each
    process's host-tier stores are in-memory state: the replacement rank
    rebuilds them by replaying the base + DELTA checkpoint chain
    (LoadSSD2Mem on recovery, box_wrapper.cc:1415), runs the pass
    protocol (begin/end pass windows), and its final host-tier content
    must fingerprint-match the uninterrupted run's."""
    import json
    import subprocess
    import numpy as np
    from paddlebox_tpu.distributed import KVServer

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(ELASTIC_WORKER)
    n_passes = 4

    def run(out_dir, kill: bool, endpoint: str) -> int:
        out_dir.mkdir()
        env_extra = {
            "PBOX_WORLD_SIZE": "2", "KV_ENDPOINT": endpoint,
            "N_PASSES": str(n_passes), "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TABLE_KIND": table_kind,
            "PYTHONPATH": repo + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        }
        if kill:
            env_extra["KILL_RANK1_AFTER_PASS"] = "1"
        old = {k: os.environ.get(k) for k in env_extra}
        os.environ.update(env_extra)
        try:
            rc = launch_local(
                [sys.executable, str(worker), str(out_dir)],
                LaunchConfig(nproc=2, job_id="jobE",
                             elastic_endpoint=endpoint, max_restarts=2,
                             stop_grace_sec=15.0))
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return rc

    srv = KVServer()
    try:
        assert run(tmp_path / "killed", kill=True,
                   endpoint=srv.endpoint) == 0
    finally:
        srv.close()
    srv2 = KVServer()
    try:
        assert run(tmp_path / "clean", kill=False,
                   endpoint=srv2.endpoint) == 0
    finally:
        srv2.close()

    for r in range(2):
        a = json.load(open(tmp_path / "killed" / f"final_r{r}.json"))
        b = json.load(open(tmp_path / "clean" / f"final_r{r}.json"))
        assert a["global_step"] == b["global_step"], (a, b)
        assert a["features"] == b["features"], (a, b)
        assert np.isclose(a["auc"], b["auc"], atol=1e-6), (a, b)
        assert np.isclose(a["last_loss"], b["last_loss"],
                          atol=1e-6), (a, b)
        pa = np.load(tmp_path / "killed" / f"params_r{r}.npy")
        pb = np.load(tmp_path / "clean" / f"params_r{r}.npy")
        np.testing.assert_allclose(pa, pb, rtol=1e-6, atol=1e-7)
        if table_kind == "tiered":
            # host stores rebuilt from the base+delta chain match the
            # uninterrupted run's host-tier content
            assert np.isclose(a["host_sum"], b["host_sum"],
                              rtol=1e-6), (a, b)


def test_tcp_kv_store_matches_file_kv(tmp_path):
    """TcpKVStore speaks the full KVStore contract against a KVServer —
    drop-in for FileKVStore with no shared filesystem."""
    from paddlebox_tpu.distributed import KVServer, TcpKVStore
    srv = KVServer()
    try:
        kv = TcpKVStore(srv.endpoint)
        assert kv.get("a") is None
        assert kv.mtime("a") == 0.0
        kv.put("a", b"1")
        kv.put("jobs/x", b"xx")
        kv.put("jobs/y", b"yy")
        assert kv.get("a") == b"1"
        assert kv.mtime("a") > 0.0
        assert kv.list_prefix("jobs/") == {"jobs/x": b"xx",
                                           "jobs/y": b"yy"}
        t0 = kv.mtime("a")
        time.sleep(0.01)
        kv.put("a", b"2")   # overwrite bumps mtime
        assert kv.get("a") == b"2" and kv.mtime("a") > t0
        kv.delete("a")
        assert kv.get("a") is None
        # a second client sees the same state (it's a server, not files)
        kv2 = TcpKVStore(srv.endpoint)
        assert kv2.get("jobs/x") == b"xx"
        kv.close()
        kv2.close()
    finally:
        srv.close()


def test_elastic_kill_and_rejoin_over_tcp_kv():
    """ElasticManager over the NETWORK KV: two hosts register; one dies
    (lease expires); the survivor sees the scale-down event; the host
    rejoins and the world converges back — the etcd lease/watch flow of
    fleet/elastic/manager.py:131 without a shared filesystem."""
    from paddlebox_tpu.distributed import (ElasticManager, KVServer,
                                           TcpKVStore)
    srv = KVServer()
    try:
        kv_a = TcpKVStore(srv.endpoint)
        kv_b = TcpKVStore(srv.endpoint)
        mk = lambda kv, h: ElasticManager(
            kv, "jobk", h, np=2, min_np=1, max_np=2, ttl=0.4)
        m_a = mk(kv_a, "hostA")
        m_b = mk(kv_b, "hostB")
        m_a.register()
        m_b.register()
        assert sorted(m_a.wait_for_np(timeout=10)) == ["hostA", "hostB"]
        assert m_a.scale_event() is None  # steady state
        # hostB dies WITHOUT deregistering (kill): its lease expires
        m_b._stop.set()
        m_b._hb_thread.join()
        deadline = time.time() + 10
        ev = None
        while time.time() < deadline and ev is None:
            time.sleep(0.1)
            ev = m_a.scale_event()
        assert ev == ["hostA"], ev            # scale-down observed
        assert m_a.world_ok()                 # min_np=1 keeps the job up
        # hostB rejoins through a FRESH store/manager (process restart);
        # the survivor sees the scale-UP event (wait_for_np would consume
        # it — the rendezvous updates the watch baseline by design)
        kv_b2 = TcpKVStore(srv.endpoint)
        m_b2 = mk(kv_b2, "hostB")
        m_b2.register()
        deadline = time.time() + 10
        ev2 = None
        while time.time() < deadline and ev2 is None:
            time.sleep(0.1)
            ev2 = m_a.scale_event()
        assert ev2 == ["hostA", "hostB"]      # scale-up observed
        assert sorted(m_a.wait_for_np(timeout=10)) == ["hostA", "hostB"]
        m_a.deregister()
        m_b2.deregister()
    finally:
        srv.close()
