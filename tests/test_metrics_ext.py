"""Metric variant semantics vs sklearn-style numpy references
(fleet/metrics.h:198-567 behaviors)."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.metrics import MetricRegistry
from paddlebox_tpu.metrics_ext import (
    CmatchRankAucMetric, CmatchRankMaskAucMetric, ContinueValueMetric,
    MaskAucMetric, MultiTaskAucMetric, NanInfMetric, WuAucMetric,
    _tie_averaged_user_auc, parse_cmatch_rank_group,
)


def ref_auc(label, pred):
    """Exact Mann-Whitney AUC (tie-averaged)."""
    order = np.argsort(pred, kind="stable")
    p, l = pred[order], label[order]
    ranks = np.empty(len(p))
    i = 0
    while i < len(p):
        j = i
        while j < len(p) and p[j] == p[i]:
            j += 1
        ranks[i:j] = (i + j + 1) / 2.0
        i = j
    n_pos, n_neg = l.sum(), (1 - l).sum()
    return (ranks[l > 0].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_default_auc_bitmatches_f64_reference_calculator():
    """FLAGS.auc_device_reduce defaults to False: the default AUC path is
    the exact f64 host finalize — BasicAucCalculator::compute semantics
    (metrics.cc:288-304). Assert bit-equality against an independent numpy
    transcription of the bucket scan."""
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.metrics import (auc_add_batch, auc_compute,
                                       init_auc_state)
    assert FLAGS.auc_device_reduce is False  # parity by default
    rng = np.random.default_rng(7)
    nb = 4096
    st = init_auc_state(nb)
    for _ in range(3):
        pred = rng.random(512).astype(np.float32)
        label = (rng.random(512) < pred).astype(np.float32)
        st = auc_add_batch(st, jnp.asarray(pred), jnp.asarray(label),
                           jnp.ones(512, jnp.float32))
    got = auc_compute(st).auc
    # independent f64 bucket scan (metrics.cc BasicAucCalculator::compute)
    pos = np.asarray(st.pos, np.float64)
    neg = np.asarray(st.neg, np.float64)
    area = 0.0
    cum_neg = 0.0
    for i in range(nb):
        area += pos[i] * (cum_neg + 0.5 * neg[i])
        cum_neg += neg[i]
    want = area / (pos.sum() * neg.sum())
    assert got == want  # bit-exact, not approx


def test_parse_cmatch_rank_group():
    assert parse_cmatch_rank_group("401:0,402:1") == [(401, 0), (402, 1)]
    assert parse_cmatch_rank_group("7, 8") == [(7, 0), (8, 0)]


def test_cmatch_rank_filter():
    rng = np.random.default_rng(0)
    n = 2000
    pred = rng.random(n).astype(np.float32)
    label = (rng.random(n) < pred).astype(np.float32)
    cmatch = rng.choice([401, 402, 403], size=n).astype(np.int32)
    rank = rng.integers(0, 3, size=n).astype(np.int32)

    m = CmatchRankAucMetric("m", "401:0,402:1", nbins=100_000)
    m.add(jnp.asarray(pred), jnp.asarray(label),
          cmatch=jnp.asarray(cmatch), rank=jnp.asarray(rank))
    sel = ((cmatch == 401) & (rank == 0)) | ((cmatch == 402) & (rank == 1))
    got = m.compute()
    assert got["ins_num"] == sel.sum()
    assert abs(got["auc"] - ref_auc(label[sel], pred[sel])) < 2e-3

    m2 = CmatchRankAucMetric("m2", "401", ignore_rank=True, nbins=100_000)
    m2.add(jnp.asarray(pred), jnp.asarray(label),
           cmatch=jnp.asarray(cmatch), rank=jnp.asarray(rank))
    assert m2.compute()["ins_num"] == (cmatch == 401).sum()


def test_mask_and_combined_filter():
    rng = np.random.default_rng(1)
    n = 1000
    pred = rng.random(n).astype(np.float32)
    label = (rng.random(n) < pred).astype(np.float32)
    mask = rng.integers(0, 2, size=n).astype(np.int32)
    cmatch = rng.choice([7, 9], size=n).astype(np.int32)

    m = MaskAucMetric("m", nbins=100_000)
    m.add(jnp.asarray(pred), jnp.asarray(label), mask=jnp.asarray(mask))
    assert m.compute()["ins_num"] == mask.sum()

    mc = CmatchRankMaskAucMetric("mc", "7", ignore_rank=True, nbins=100_000)
    mc.add(jnp.asarray(pred), jnp.asarray(label),
           cmatch=jnp.asarray(cmatch), mask=jnp.asarray(mask))
    sel = (cmatch == 7) & (mask == 1)
    got = mc.compute()
    assert got["ins_num"] == sel.sum()
    assert abs(got["auc"] - ref_auc(label[sel], pred[sel])) < 4e-3


def test_multi_task_selects_head_by_cmatch():
    rng = np.random.default_rng(2)
    n, t = 1500, 3
    preds = rng.random((n, t)).astype(np.float32)
    cmatch = rng.choice([11, 12, 13, 99], size=n).astype(np.int32)
    task = np.select([cmatch == 11, cmatch == 12, cmatch == 13],
                     [0, 1, 2], default=-1)
    sel = task >= 0
    chosen = preds[np.arange(n), np.maximum(task, 0)]
    label = (rng.random(n) < chosen).astype(np.float32)

    m = MultiTaskAucMetric("mt", "11:0,12:1,13:2", nbins=100_000)
    m.add(jnp.asarray(preds), jnp.asarray(label), cmatch=jnp.asarray(cmatch))
    got = m.compute()
    assert got["ins_num"] == sel.sum()
    assert abs(got["auc"] - ref_auc(label[sel], chosen[sel])) < 2e-3


def test_continue_value():
    m = ContinueValueMetric("cv")
    pred = jnp.asarray([1.0, 2.0, 3.0])
    label = jnp.asarray([1.5, 2.0, 1.0])
    m.add(pred, label)
    got = m.compute()
    np.testing.assert_allclose(got["mae"], (0.5 + 0 + 2.0) / 3)
    np.testing.assert_allclose(got["rmse"], np.sqrt((0.25 + 4.0) / 3))


def test_nan_inf_counter():
    m = NanInfMetric("ni")
    m.add(jnp.asarray([0.1, np.nan, np.inf, -np.inf, 0.5]))
    got = m.compute()
    assert got["nan"] == 1 and got["inf"] == 2 and got["ins_num"] == 5


def test_wuauc_matches_per_user_reference():
    rng = np.random.default_rng(3)
    n = 3000
    uid = rng.integers(0, 40, size=n).astype(np.int64)
    pred = np.round(rng.random(n).astype(np.float64), 2)  # force ties
    label = (rng.random(n) < pred).astype(np.float64)

    wuauc, uauc, users = _tie_averaged_user_auc(uid, pred, label)
    # python reference: loop users
    aucs, weights = [], []
    for u in np.unique(uid):
        m = uid == u
        l, p = label[m], pred[m]
        if l.sum() in (0, len(l)):
            continue
        aucs.append(ref_auc(l, p))
        weights.append(m.sum())
    want_w = float(np.sum(np.array(aucs) * np.array(weights)) / np.sum(weights))
    assert users == len(aucs)
    np.testing.assert_allclose(wuauc, want_w, rtol=1e-10)
    np.testing.assert_allclose(uauc, np.mean(aucs), rtol=1e-10)


def test_wuauc_metric_batches():
    m = WuAucMetric("wu")
    m.add(np.array([0.9, 0.1]), np.array([1.0, 0.0]), uid=np.array([1, 1]))
    m.add(np.array([0.2, 0.8]), np.array([1.0, 0.0]), uid=np.array([2, 2]))
    got = m.compute()
    assert got["user_count"] == 2
    np.testing.assert_allclose(got["wuauc"], 0.5)  # user1 perfect, user2 inverted


def test_registry_dispatch_and_phase():
    reg = MetricRegistry()
    reg.init_metric("join_auc", method="auc", phase=1, nbins=1000)
    reg.init_metric("upd_auc", method="auc", phase=0, nbins=1000)
    reg.init_metric("wu", method="wuauc")
    assert set(reg.active()) == {"join_auc", "wu"}
    reg.flip_phase()
    assert set(reg.active()) == {"upd_auc", "wu"}
    with pytest.raises(ValueError):
        reg.init_metric("x", method="nope")
    msg = reg.get_metric_msg("wu")
    assert msg["ins_num"] == 0.0


def test_registry_auto_feed_through_trainer():
    """Registered metric variants accumulate automatically during
    train_pass (AddAucMonitor semantics) with batch side channels."""
    import optax
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.data.record import SlotRecord
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    rng = np.random.default_rng(0)
    S = 3
    recs = []
    for i in range(512):
        keys = (rng.integers(0, 40, S) + np.arange(S) * 40).astype(np.uint64)
        lbl = float(rng.random() < 0.3)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=np.arange(S + 1, dtype=np.int32),
            dense=rng.normal(size=2).astype(np.float32), label=lbl,
            show=1.0, clk=lbl, uid=int(i % 17),
            rank=int(rng.integers(1, 4)),
            cmatch=int(rng.choice([222, 223, 0]))))
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 2)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=64, label_slot="label")
    ds = InMemoryDataset(desc)
    ds.records = recs
    ds.columnarize()

    t = EmbeddingTable(mf_dim=2, capacity=1 << 12,
                       cfg=SparseSGDConfig(mf_create_thresholds=0.0))
    tr = Trainer(CtrDnn(hidden=(8,)), t, desc, tx=optax.adam(1e-2))
    tr.metrics.init_metric("all", method="auc")
    tr.metrics.init_metric("cm222", method="cmatch_rank_auc",
                           cmatch_rank_group="222:1,222:2,222:3")
    tr.metrics.init_metric("wu", method="wuauc")
    tr.train_pass(ds)

    msg_all = tr.metrics.get_metric_msg("all")
    msg_cm = tr.metrics.get_metric_msg("cm222")
    msg_wu = tr.metrics.get_metric_msg("wu")
    assert msg_all["ins_num"] == 512
    # cmatch 222 subset only
    n222 = sum(1 for r in recs if r.cmatch == 222)
    assert msg_cm["ins_num"] == n222 > 0
    assert np.isfinite(msg_wu["wuauc"])
    assert msg_wu["user_count"] == 17


def test_registry_skips_metric_missing_side_channel():
    """A registered metric whose REQUIRED side channel is absent from the
    feed is skipped with a warning, not a crash."""
    from paddlebox_tpu.metrics import MetricRegistry
    reg = MetricRegistry()
    reg.init_metric("m", method="mask_auc")      # needs mask — never fed
    reg.init_metric("a", method="auc")
    pred = jnp.asarray(np.array([0.2, 0.8], np.float32))
    label = np.array([0.0, 1.0], np.float32)
    reg.add_batch(pred, label, np.ones(2, np.float32))  # must not raise
    assert reg.get_metric_msg("a")["ins_num"] == 2
    assert reg.get_metric_msg("m")["ins_num"] == 0


@pytest.mark.slow  # seed-broken (no jax.shard_map) until the
# jax_compat shim; recovered, but heavy on the virtual-CPU mesh —
# out of the tier-1 wall budget, runs in the slow tier
def test_registry_on_sharded_trainer():
    """Metric variants accumulate on the MESH trainer: the per-device-row
    AddAucMonitor feed matches the single-chip trainer's registry on the
    same data (pod-scale init_metric/get_metric_msg)."""
    import jax
    import optax
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.sharded import ShardedTrainer
    import tempfile
    assert len(jax.devices()) >= 8
    tmp = tempfile.mkdtemp()
    files = generate_criteo_files(tmp, num_files=1, rows_per_file=1024,
                                  vocab_per_slot=40, seed=31)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    sh = ShardedEmbeddingTable(8, mf_dim=4, capacity_per_shard=2048,
                               cfg=cfg, req_bucket_min=128,
                               serve_bucket_min=128)
    tr_m = ShardedTrainer(DeepFM(hidden=(16, 8)), sh, desc, make_mesh(8),
                          tx=optax.adam(1e-2), seed=3)
    sc = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                        unique_bucket_min=1024)
    tr_s = Trainer(DeepFM(hidden=(16, 8)), sc, desc, tx=optax.adam(1e-2),
                   seed=3)
    for tr in (tr_m, tr_s):
        tr.metrics.init_metric("auc2", method="auc")
        tr.metrics.init_metric("wu", method="wuauc")
    tr_m.train_pass(ds)
    tr_s.train_pass(ds)
    mm = tr_m.metrics.get_metric_msg("auc2")
    ms = tr_s.metrics.get_metric_msg("auc2")
    # same data, same seeds — but mesh updates come per GLOBAL batch, so
    # predictions differ slightly; the registry wiring must agree closely
    assert abs(mm["auc"] - ms["auc"]) < 0.05, (mm, ms)
    assert mm["ins_num"] == ms["ins_num"] == 1024
    wm = tr_m.metrics.get_metric_msg("wu")
    ws = tr_s.metrics.get_metric_msg("wu")
    assert abs(wm["wuauc"] - ws["wuauc"]) < 0.08, (wm, ws)


@pytest.mark.slow  # same budget rationale as the sharded-trainer
# registry test above
def test_registry_on_mesh_resident_pass():
    """Metric variants accumulate in the MESH RESIDENT pass: predictions
    are collected inside the fori_loop (device-sharded [nb, N, B]) and
    replayed through the registry post-pass — the outputs must match the
    mesh STREAMING pass on identical data/seeds (boxps_worker.cc:1267,
    1337 accumulates monitors in every worker mode unconditionally)."""
    import jax
    import optax
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer
    import tempfile
    assert len(jax.devices()) >= 8
    tmp = tempfile.mkdtemp()
    files = generate_criteo_files(tmp, num_files=1, rows_per_file=1024,
                                  vocab_per_slot=40, seed=37)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)

    def mk():
        sh = ShardedEmbeddingTable(8, mf_dim=4, capacity_per_shard=2048,
                                   cfg=cfg, req_bucket_min=128,
                                   serve_bucket_min=128)
        tr = ShardedTrainer(DeepFM(hidden=(16, 8)), sh, desc,
                            make_mesh(8), tx=optax.adam(1e-2), seed=3)
        tr.metrics.init_metric("auc2", method="auc")
        tr.metrics.init_metric("wu", method="wuauc")
        return tr

    tr_s = mk()   # streaming
    tr_r = mk()   # resident
    rs = tr_s.train_pass(ds)
    rr = tr_r.train_pass_resident(ds)
    assert rr["ins_num"] == rs["ins_num"]
    ms, mr = (t.metrics.get_metric_msg("auc2") for t in (tr_s, tr_r))
    assert mr["ins_num"] == ms["ins_num"] == 1024
    assert abs(mr["auc"] - ms["auc"]) < 1e-5, (mr, ms)
    ws, wr = (t.metrics.get_metric_msg("wu") for t in (tr_s, tr_r))
    assert abs(wr["wuauc"] - ws["wuauc"]) < 1e-5, (wr, ws)
    assert wr["user_count"] == ws["user_count"]
