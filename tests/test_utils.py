import threading
import time

import pytest

from paddlebox_tpu.config import FLAGS, flags_scope
from paddlebox_tpu.utils import Channel, ChannelClosed, STATS, Timer, stat_add


def test_flags_scope_and_update():
    base = FLAGS.read_thread_num
    with flags_scope(read_thread_num=3):
        assert FLAGS.read_thread_num == 3
    assert FLAGS.read_thread_num == base
    with pytest.raises(AttributeError):
        FLAGS.update(no_such_flag=1)


def test_timer_pause_resume():
    t = Timer()
    t.start()
    time.sleep(0.01)
    t.pause()
    e1 = t.elapsed_sec()
    assert e1 >= 0.009
    time.sleep(0.01)
    assert t.elapsed_sec() == e1  # paused
    t.resume()
    time.sleep(0.005)
    t.pause()
    assert t.elapsed_sec() > e1
    assert t.count() == 2


def test_stat_registry():
    STATS.reset()
    stat_add("total_feasign_num_in_mem", 10)
    stat_add("total_feasign_num_in_mem", 5)
    assert STATS.get("total_feasign_num_in_mem") == 15
    STATS.reset("total_feasign_num_in_mem")
    assert STATS.get("total_feasign_num_in_mem") == 0


def test_channel_mpmc_and_close():
    ch = Channel(capacity=8, block_size=4)
    out = []

    def consumer():
        for x in ch:
            out.append(x)

    threads = [threading.Thread(target=consumer) for _ in range(2)]
    for th in threads:
        th.start()
    for i in range(100):
        ch.put(i)
    ch.close()
    for th in threads:
        th.join()
    assert sorted(out) == list(range(100))
    with pytest.raises(ChannelClosed):
        ch.put(1)


def test_channel_get_batch_drains_after_close():
    ch = Channel(capacity=4)
    ch.put(1)
    ch.put(2)
    ch.close()
    assert ch.get_batch() == [1, 2]
    assert ch.get_batch() == []
