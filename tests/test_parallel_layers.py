"""TP/PP layers on the 8-device CPU mesh vs dense references
(meta_parallel/parallel_layers semantics)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddlebox_tpu.parallel.layers import (
    column_parallel_linear, pipeline_run, row_parallel_linear,
    vocab_parallel_embedding,
)


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("mp",))


def test_vocab_parallel_embedding(mesh):
    rng = np.random.default_rng(0)
    vocab, dim = 64, 16
    w = rng.normal(size=(vocab, dim)).astype(np.float32)
    ids = rng.integers(0, vocab, size=(4, 7)).astype(np.int32)

    f = shard_map(
        functools.partial(vocab_parallel_embedding, axis="mp"),
        mesh=mesh, in_specs=(P(), P("mp", None)), out_specs=P())
    got = f(jnp.asarray(ids), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(got), w[ids], rtol=1e-6)


def test_column_then_row_parallel_mlp(mesh):
    """col(gather=False) → row: the canonical megatron MLP block."""
    rng = np.random.default_rng(1)
    b, din, dh, dout = 8, 12, 32, 6
    x = rng.normal(size=(b, din)).astype(np.float32)
    w1 = rng.normal(size=(din, dh)).astype(np.float32)
    b1 = rng.normal(size=(dh,)).astype(np.float32)
    w2 = rng.normal(size=(dh, dout)).astype(np.float32)
    b2 = rng.normal(size=(dout,)).astype(np.float32)

    def block(x, w1, b1, w2, b2):
        h = column_parallel_linear(x, w1, b1, gather_output=False)
        h = jax.nn.relu(h)
        return row_parallel_linear(h, w2, b2)

    f = shard_map(block, mesh=mesh,
                  in_specs=(P(), P(None, "mp"), P("mp"), P("mp", None), P()),
                  out_specs=P())
    got = f(*map(jnp.asarray, (x, w1, b1, w2, b2)))
    want = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_column_parallel_gather_output(mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 10)).astype(np.float32)
    w = rng.normal(size=(10, 24)).astype(np.float32)
    f = shard_map(
        functools.partial(column_parallel_linear, gather_output=True),
        mesh=mesh, in_specs=(P(), P(None, "mp")), out_specs=P(),
        check_rep=False)  # all_gather replication isn't statically inferred
    got = f(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(got), x @ w, rtol=1e-4,
                               atol=1e-5)


def test_pipeline_matches_sequential():
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("pp",))
    rng = np.random.default_rng(3)
    m, mb, d = 6, 5, 8
    x = rng.normal(size=(m, mb, d)).astype(np.float32)
    # 4 stages, each its own weight
    ws = rng.normal(size=(4, d, d)).astype(np.float32) * 0.5

    def stage(w, a):
        return jnp.tanh(a @ w)

    def run(x_micros, ws_sharded):
        out = pipeline_run(stage, ws_sharded[0], x_micros, axis="pp")
        return jax.lax.psum(out, "pp")  # only last stage is nonzero

    f = shard_map(run, mesh=mesh, in_specs=(P(), P("pp", None, None)),
                  out_specs=P())
    got = f(jnp.asarray(x), jnp.asarray(ws))

    want = x
    for i in range(4):
        want = np.tanh(want @ ws[i])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)
