"""TP/PP layers on the 8-device CPU mesh vs dense references
(meta_parallel/parallel_layers semantics)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddlebox_tpu.parallel.layers import (
    column_parallel_linear, pipeline_run, row_parallel_linear,
    vocab_parallel_embedding,
)


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("mp",))


def test_vocab_parallel_embedding(mesh):
    rng = np.random.default_rng(0)
    vocab, dim = 64, 16
    w = rng.normal(size=(vocab, dim)).astype(np.float32)
    ids = rng.integers(0, vocab, size=(4, 7)).astype(np.int32)

    f = shard_map(
        functools.partial(vocab_parallel_embedding, axis="mp"),
        mesh=mesh, in_specs=(P(), P("mp", None)), out_specs=P())
    got = f(jnp.asarray(ids), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(got), w[ids], rtol=1e-6)


def test_column_then_row_parallel_mlp(mesh):
    """col(gather=False) → row: the canonical megatron MLP block."""
    rng = np.random.default_rng(1)
    b, din, dh, dout = 8, 12, 32, 6
    x = rng.normal(size=(b, din)).astype(np.float32)
    w1 = rng.normal(size=(din, dh)).astype(np.float32)
    b1 = rng.normal(size=(dh,)).astype(np.float32)
    w2 = rng.normal(size=(dh, dout)).astype(np.float32)
    b2 = rng.normal(size=(dout,)).astype(np.float32)

    def block(x, w1, b1, w2, b2):
        h = column_parallel_linear(x, w1, b1, gather_output=False)
        h = jax.nn.relu(h)
        return row_parallel_linear(h, w2, b2)

    f = shard_map(block, mesh=mesh,
                  in_specs=(P(), P(None, "mp"), P("mp"), P("mp", None), P()),
                  out_specs=P())
    got = f(*map(jnp.asarray, (x, w1, b1, w2, b2)))
    want = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_column_parallel_gather_output(mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 10)).astype(np.float32)
    w = rng.normal(size=(10, 24)).astype(np.float32)
    f = shard_map(
        functools.partial(column_parallel_linear, gather_output=True),
        mesh=mesh, in_specs=(P(), P(None, "mp")), out_specs=P(),
        check_rep=False)  # all_gather replication isn't statically inferred
    got = f(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(got), x @ w, rtol=1e-4,
                               atol=1e-5)


def test_pipeline_matches_sequential():
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("pp",))
    rng = np.random.default_rng(3)
    m, mb, d = 6, 5, 8
    x = rng.normal(size=(m, mb, d)).astype(np.float32)
    # 4 stages, each its own weight
    ws = rng.normal(size=(4, d, d)).astype(np.float32) * 0.5

    def stage(w, a):
        return jnp.tanh(a @ w)

    def run(x_micros, ws_sharded):
        out = pipeline_run(stage, ws_sharded[0], x_micros, axis="pp")
        return jax.lax.psum(out, "pp")  # only last stage is nonzero

    f = shard_map(run, mesh=mesh, in_specs=(P(), P("pp", None, None)),
                  out_specs=P())
    got = f(jnp.asarray(x), jnp.asarray(ws))

    want = x
    for i in range(4):
        want = np.tanh(want @ ws[i])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


_LEGACY_JAX = tuple(int(v) for v in
                    jax.__version__.split(".")[:2]) < (0, 6)


@pytest.mark.skipif(_LEGACY_JAX, reason=(
    "fails on the legacy jax.experimental.shard_map line (pre-existing "
    "seed failure; passes on jax >= 0.6)"))
def test_hierarchical_allreduce_matches_flat_psum():
    """2-level [dcn, ici] allreduce (reduce-scatter → DCN sum →
    all-gather; boxps_worker.cc:1217-1234 ladder) must equal a flat psum
    over both axes — exercised on a 2x4 virtual mesh."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from paddlebox_tpu.parallel.mesh import (DCN_AXIS, ICI_AXIS,
                                             hierarchical_allreduce,
                                             make_hierarchical_mesh)
    mesh = make_hierarchical_mesh(n_slices=2)
    assert mesh.shape == {DCN_AXIS: 2, ICI_AXIS: 4}
    rng = np.random.default_rng(0)
    # odd length exercises the pad path (37 % 4 != 0)
    x = rng.normal(size=(8, 37)).astype(np.float32)

    def block(v):
        v = v.reshape(37)
        h = hierarchical_allreduce(v)
        f = jax.lax.psum(jax.lax.psum(v, ICI_AXIS), DCN_AXIS)
        return h[None], f[None]

    h, f = jax.jit(jax.shard_map(
        block, mesh=mesh,
        in_specs=P((DCN_AXIS, ICI_AXIS)),
        out_specs=(P((DCN_AXIS, ICI_AXIS)), P((DCN_AXIS, ICI_AXIS))),
        check_vma=False))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(h), np.asarray(f), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h)[0], x.sum(axis=0), rtol=1e-4)


@pytest.mark.skipif(_LEGACY_JAX, reason=(
    "fails on the legacy jax.experimental.shard_map line (pre-existing "
    "seed failure; passes on jax >= 0.6)"))
def test_pipeline_training_matches_sequential():
    """The pipeline must TRAIN, not just infer: several optimizer steps
    through pipeline_train_step must track sequential training of the
    same stacked model on the same data (GPipe is mathematically
    identical to sequential — grads accumulate over microbatches inside
    one step)."""
    import optax
    from paddlebox_tpu.parallel import pipeline_train_step

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("pp",))
    rng = np.random.default_rng(5)
    m, mb, d = 4, 6, 8
    x = rng.normal(size=(m, mb, d)).astype(np.float32)
    y = rng.normal(size=(m, mb, d)).astype(np.float32)
    ws0 = (rng.normal(size=(4, d, d)).astype(np.float32) * 0.3)

    def stage(w, a):
        return jnp.tanh(a @ w)

    def loss_fn(out, y_micros):
        # plain single-device-style loss: pipeline_train_step masks it
        # to the last stage
        return jnp.mean((out - y_micros) ** 2)

    tx = optax.sgd(0.2)

    def train_step(ws_sharded, opt_state, x_micros, y_micros):
        def body(w_local, o_local):
            loss, g = pipeline_train_step(stage, loss_fn, w_local[0],
                                          x_micros, y_micros, axis="pp")
            up, o2 = tx.update(g, o_local, w_local[0])
            return loss, (optax.apply_updates(w_local[0], up)[None], o2)

        return shard_map(
            body, mesh=mesh,
            in_specs=(P("pp", None, None), P("pp")),
            out_specs=(P(), (P("pp", None, None), P("pp"))))(
                ws_sharded, opt_state)

    # sequential reference: same model stacked, full-batch mse
    def seq_loss(ws, xx, yy):
        a = xx
        for i in range(4):
            a = jnp.tanh(a @ ws[i])
        return jnp.mean((a - yy) ** 2)

    ws_pipe = jnp.asarray(ws0)
    opt_pipe = jax.vmap(tx.init)(ws_pipe)
    ws_seq = jnp.asarray(ws0)
    opt_seq = tx.init(ws_seq)
    xx = x.reshape(m * mb, d)
    yy = y.reshape(m * mb, d)
    losses_p, losses_s = [], []
    for step in range(5):
        lp, (ws_pipe, opt_pipe) = train_step(ws_pipe, opt_pipe,
                                             jnp.asarray(x),
                                             jnp.asarray(y))
        ls, gs = jax.value_and_grad(seq_loss)(ws_seq, xx, yy)
        up, opt_seq = tx.update(gs, opt_seq, ws_seq)
        ws_seq = optax.apply_updates(ws_seq, up)
        losses_p.append(float(lp))
        losses_s.append(float(ls))
    np.testing.assert_allclose(losses_p, losses_s, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ws_pipe), np.asarray(ws_seq),
                               rtol=1e-4, atol=1e-5)
    assert losses_p[-1] < losses_p[0] * 0.98  # it actually learns
