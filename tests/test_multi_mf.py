"""multi_mf_dim: per-slot embedding dims via dim-class tables
(feature_value.h:42-185, ps_gpu_wrapper.cc multi-mf build)."""

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import MultiMfEmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import MultiMfTrainer


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_mmf")
    return generate_criteo_files(str(d), num_files=2, rows_per_file=1500,
                                 vocab_per_slot=40, seed=11)


def _dims():
    # 26 criteo slots: first 10 narrow, next 10 medium, rest wide
    return [2] * 10 + [4] * 10 + [8] * 6


def _make(files):
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = MultiMfEmbeddingTable(_dims(), capacity=1 << 12, cfg=cfg,
                                  unique_bucket_min=1024)
    tr = MultiMfTrainer(CtrDnn(hidden=(16, 8)), table, desc,
                        tx=optax.adam(1e-2), seed=3)
    return tr, ds


def test_split_batch_routes_and_renumbers():
    from paddlebox_tpu.data.batch import SlotBatch
    dims = [2, 4, 2, 4]
    t = MultiMfEmbeddingTable(dims, capacity=256)
    b, s = 2, 4
    keys = np.arange(1, 9, dtype=np.uint64)          # one key per slot
    segs = np.arange(8, dtype=np.int32)              # trivial layout
    batch = SlotBatch(keys=keys, segments=segs, num_keys=8,
                      dense=np.zeros((b, 1), np.float32),
                      label=np.zeros(b, np.float32),
                      show=np.ones(b, np.float32),
                      clk=np.zeros(b, np.float32),
                      batch_size=b, num_slots=s)
    subs, gslots = t.split_batch(batch)
    assert len(subs) == 2
    # class 0 = dims 2 (slots 0, 2), class 1 = dims 4 (slots 1, 3)
    np.testing.assert_array_equal(subs[0].keys[:4], [1, 3, 5, 7])
    np.testing.assert_array_equal(subs[1].keys[:4], [2, 4, 6, 8])
    # segments renumbered: record r, class-rank q → r*2+q
    np.testing.assert_array_equal(subs[0].segments[:4], [0, 1, 2, 3])
    np.testing.assert_array_equal(subs[1].segments[:4], [0, 1, 2, 3])
    assert subs[0].num_slots == 2 and subs[1].num_slots == 2
    # trivial layout survives the split (sub-batch position == segment)
    assert subs[0].segments_trivial == batch.segments_trivial
    # global slot ids preserved for the persisted slot field
    np.testing.assert_array_equal(gslots[0], [0, 2, 0, 2])
    np.testing.assert_array_equal(gslots[1], [1, 3, 1, 3])


def test_multi_mf_e2e_learns(criteo_files):
    tr, ds = _make(criteo_files)
    first = tr.train_pass(ds)
    tr.reset_metrics()
    for _ in range(3):
        last = tr.train_pass(ds)
    assert np.isfinite(last["auc"])
    assert last["auc"] > max(first["auc"], 0.55)
    # all three class tables actually hold features
    assert all(t.feature_count > 0 for t in tr.table.tables)


def test_multi_mf_pull_per_slot_widths(criteo_files):
    tr, ds = _make(criteo_files)
    tr.train_pass(ds)
    col = ds.columnar
    keys = col.keys[:100].astype(np.uint64)
    slots = col.key_slot[:100]
    vals = tr.table.pull(keys, slots)
    assert vals.shape == (100, 3 + 8)  # padded to the max class width
    dims = np.asarray(_dims())
    for i in range(100):
        d = dims[slots[i]]
        # columns beyond the slot's width are zero
        np.testing.assert_allclose(vals[i, 3 + d:], 0.0)
    # show counters accumulated for seen keys
    assert (vals[:, 0] > 0).all()


def test_multi_mf_resident_matches_streaming(criteo_files):
    """Device-resident multi-mf pass (whole pass in one lax.fori_loop)
    == streaming pass: same AUC, same dense params, same per-key values
    (mf_initial_range=0 so rng paths can't diverge)."""
    tr_a, ds = _make(criteo_files)
    tr_b, _ = _make(criteo_files)
    ra = rb = None
    for _ in range(2):
        ra = tr_a.train_pass(ds)
        rb = tr_b.train_pass_resident(ds)
    assert rb["batches"] == ra["batches"]
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=2e-3), (rb["auc"], ra["auc"])
    for x, y in zip(jax.tree.leaves(tr_a.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-2, atol=2e-3)
    col = ds.columnar
    keys = col.keys[:100].astype(np.uint64)
    slots = col.key_slot[:100]
    np.testing.assert_allclose(tr_b.table.pull(keys, slots),
                               tr_a.table.pull(keys, slots),
                               rtol=2e-2, atol=2e-3)
    # a further resident pass keeps training
    tr_b.reset_metrics()
    rb2 = tr_b.train_pass_resident(ds)
    assert rb2["auc"] > rb["auc"] - 0.02


def test_multi_mf_serving_consumes_save(criteo_files, tmp_path):
    """MultiMfServingModel loads the multi-mf save format, serves
    per-slot-width lookups identical to the live table, and predicts."""
    import pickle
    from paddlebox_tpu.serving import MultiMfServingModel
    tr, ds = _make(criteo_files)
    for _ in range(4):
        tr.train_pass(ds)
    base = str(tmp_path / "srv_base")
    n = tr.table.save_base(base)
    dense = str(tmp_path / "dense.pkl")
    with open(dense, "wb") as fh:
        pickle.dump(jax.device_get(tr.state.params), fh)

    srv = MultiMfServingModel(CtrDnn(hidden=(16, 8)), tr.desc, _dims(),
                              capacity=1 << 12)
    assert srv.load_base(base) == n
    srv.load_dense(dense)

    col = ds.columnar
    keys = col.keys[:80].astype(np.uint64)
    slots = col.key_slot[:80]
    vals = srv.embed_lookup(keys, slots)
    np.testing.assert_allclose(vals, tr.table.pull(keys, slots),
                               rtol=1e-6, atol=1e-8)
    dims = np.asarray(_dims())
    for i in range(80):
        np.testing.assert_allclose(vals[i, 3 + dims[slots[i]]:], 0.0)
    assert srv.slot_width(0) == 3 + 2 and srv.slot_width(25) == 3 + 8

    # predictions: finite, batch-shaped, and predictive on trained data
    from paddlebox_tpu.metrics import init_auc_state, auc_add_batch, \
        auc_compute
    import jax.numpy as jnp
    auc = init_auc_state(4096)
    for i, batch in enumerate(ds.batches()):
        preds, valid = srv.predict(batch, return_valid=True)
        assert np.isfinite(preds).all()
        auc = auc_add_batch(auc, jnp.asarray(preds),
                            jnp.asarray(batch.label), jnp.asarray(valid))
        if i >= 5:
            break
    assert auc_compute(auc).auc > 0.55  # the loaded model predicts

    # delta application keeps serving in sync with further training
    tr.train_pass(ds)
    delta = str(tmp_path / "srv_delta")
    nd = tr.table.save_delta(delta)
    assert nd > 0
    assert srv.apply_delta(delta) == nd
    np.testing.assert_allclose(
        srv.embed_lookup(keys, slots), tr.table.pull(keys, slots),
        rtol=1e-6, atol=1e-8)


def test_multi_mf_save_load_roundtrip(criteo_files, tmp_path):
    tr, ds = _make(criteo_files)
    tr.train_pass(ds)
    path = str(tmp_path / "mmf_base")
    n = tr.table.save_base(path)
    assert n == tr.table.feature_count
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    t2 = MultiMfEmbeddingTable(_dims(), capacity=1 << 12, cfg=cfg)
    assert t2.load(path) == n
    col = ds.columnar
    keys = col.keys[:50].astype(np.uint64)
    slots = col.key_slot[:50]
    np.testing.assert_allclose(t2.pull(keys, slots),
                               tr.table.pull(keys, slots), rtol=1e-6)
