"""Resilience layer (paddlebox_tpu/resilience): retry/backoff policy
semantics, deterministic fault injection, CommandBackend hardening,
dataset quarantine + poison budgets, checkpoint checksums + mid-save
crash recovery, pass-level retry, watchdog escalation ladder, and the
prefetch producer-leak regression (ISSUE 2 acceptance surface)."""

import os
import sys
import threading
import time

import numpy as np
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.data.dataset import (PoisonBudgetExceeded,
                                        PoisonedFileError)
from paddlebox_tpu.obs import (LocalHeartbeatStore, MemorySink,
                               StragglerTimeout, StragglerWatchdog,
                               TelemetryHub, get_hub, reset_hub)
from paddlebox_tpu.obs.watchdog import (abort_with_checkpoint_action,
                                        requeue_pass_action)
from paddlebox_tpu.resilience.faults import (FaultPlan, InjectedCrash,
                                             TransientInjectedError,
                                             inject, installed)
from paddlebox_tpu.resilience.retry import (RetryExhausted, RetryPolicy,
                                            TransientError, is_retryable)
from paddlebox_tpu.utils.channel import Channel, ChannelClosed
from paddlebox_tpu.utils.file_mgr import (CommandBackend,
                                          TransientCommandError)
from paddlebox_tpu.utils.prefetch import prefetch_iter


@pytest.fixture()
def fresh_hub():
    hub = reset_hub()
    yield hub
    reset_hub()


def _nosleep_policy(**kw):
    kw.setdefault("base_delay", 0.001)
    kw.setdefault("sleep", lambda s: None)
    return RetryPolicy(**kw)


# ---- RetryPolicy -------------------------------------------------------
def test_retry_succeeds_after_transient():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("hiccup")
        return "ok"

    assert _nosleep_policy(max_attempts=4).call(flaky) == "ok"
    assert len(calls) == 3


def test_retry_non_retryable_propagates_untouched():
    def bad():
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        _nosleep_policy().call(bad)

    # deterministic fs outcomes never retry even where OSError does
    assert not is_retryable(FileNotFoundError("x"))
    assert is_retryable(ConnectionResetError("x"))
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        _nosleep_policy(retryable=(OSError,)).call(missing)
    assert len(calls) == 1


def test_retry_exhausts_attempts():
    calls = []

    def always():
        calls.append(1)
        raise TransientError("down")

    with pytest.raises(RetryExhausted) as ei:
        _nosleep_policy(max_attempts=3).call(always)
    assert len(calls) == 3
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, TransientError)
    assert isinstance(ei.value.__cause__, TransientError)


def test_retry_deadline_caps_wall_time():
    clk = {"t": 0.0}

    def clock():
        return clk["t"]

    def sleep(s):
        clk["t"] += s

    calls = []

    def always():
        calls.append(1)
        clk["t"] += 1.0
        raise TransientError("down")

    p = RetryPolicy(max_attempts=100, base_delay=1.0, max_delay=1.0,
                    deadline=3.5, jitter=0.0, sleep=sleep, clock=clock)
    with pytest.raises(RetryExhausted) as ei:
        p.call(always)
    assert "deadline" in str(ei.value)
    assert len(calls) < 5


def test_retry_jitter_deterministic_per_seed_and_site():
    a = list(RetryPolicy(site="s1", seed=7, max_attempts=6).delays())
    b = list(RetryPolicy(site="s1", seed=7, max_attempts=6).delays())
    c = list(RetryPolicy(site="s2", seed=7, max_attempts=6).delays())
    d = list(RetryPolicy(site="s1", seed=8, max_attempts=6).delays())
    assert a == b
    assert a != c and a != d
    # backoff grows and respects the cap
    nojit = list(RetryPolicy(site="s", jitter=0.0, max_attempts=8,
                             base_delay=0.05, max_delay=0.4).delays())
    assert nojit == [0.05, 0.1, 0.2, 0.4, 0.4, 0.4, 0.4]


def test_retry_counter_and_event(fresh_hub):
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise TransientError("hiccup")
        return "ok"

    _nosleep_policy(site="test.seam").call(flaky)
    assert fresh_hub.counter("pbox_retry_attempts_total").value(
        site="test.seam") == 1
    evs = [e for e in sink.events if e["event"] == "retry"]
    assert evs and evs[0]["site"] == "test.seam" and evs[0]["attempt"] == 1


# ---- FaultPlan ---------------------------------------------------------
def test_fault_plan_parse():
    plan = FaultPlan.parse(
        "seed=9; a.b:fail:nth=2,times=3,exc=crash; "
        "c.d:corrupt:match=*bad*; e.f:slow:delay=0.01")
    assert plan.seed == 9
    kinds = [(s.site, s.kind) for s in plan.specs]
    assert kinds == [("a.b", "fail"), ("c.d", "corrupt"), ("e.f", "slow")]
    assert plan.specs[0].nth == 2 and plan.specs[0].times == 3
    assert plan.specs[0].exc == "crash"
    with pytest.raises(ValueError):
        FaultPlan.parse("justasite")
    with pytest.raises(ValueError):
        FaultPlan.parse("a.b:explode")
    with pytest.raises(ValueError):
        FaultPlan.parse("a.b:fail:bogus=1")
    assert FaultPlan.parse("  ").specs == []


def test_fault_nth_times_and_match():
    plan = FaultPlan.parse("s:fail:nth=2,times=2")
    with installed(plan):
        inject("s")                      # call 1: no fire
        for _ in range(2):               # calls 2,3 fire
            with pytest.raises(TransientInjectedError):
                inject("s")
        inject("s")                      # call 4: past the window
    assert plan.stats()["s:fail"] == {"calls": 4, "fired": 2}

    plan2 = FaultPlan.parse("s:fail:match=*bad*,times=0")
    with installed(plan2):
        inject("s", path="/data/good.txt")   # no match, not even a call
        with pytest.raises(TransientInjectedError):
            inject("s", path="/data/bad.txt")
        with pytest.raises(TransientInjectedError):
            inject("s", path="/data/also_bad.txt")  # times=0: every call
    assert plan2.stats()["s:fail"]["fired"] == 2


def test_fault_corrupt_and_crash_kinds():
    plan = FaultPlan.parse("c:corrupt; k:fail:exc=crash")
    with installed(plan):
        got = inject("c", "hello line")
        assert got != "hello line" and "CORRUPT" in got
        with pytest.raises(InjectedCrash):
            inject("k")


def test_fault_install_scoping():
    outer = FaultPlan.parse("s:fail:nth=1")
    inner = FaultPlan.parse("")
    with installed(outer):
        with installed(inner):
            inject("s")  # inner (empty) plan shadows outer: no fire
        with pytest.raises(TransientInjectedError):
            inject("s")  # outer restored
    inject("s")  # nothing installed
    assert outer.stats()["s:fail"]["fired"] == 1


def test_fault_probability_deterministic():
    def run():
        plan = FaultPlan.parse("s:fail:p=0.5,times=0", seed=3)
        fired = []
        with installed(plan):
            for i in range(50):
                try:
                    inject("s")
                    fired.append(0)
                except TransientInjectedError:
                    fired.append(1)
        return fired

    a, b = run(), run()
    assert a == b and 0 < sum(a) < 50


# ---- CommandBackend hardening -----------------------------------------
def _shim(tmp_path, body: str) -> list:
    sh = tmp_path / "shim.py"
    sh.write_text("import os, shutil, sys\nargs = sys.argv[1:]\n" + body)
    return [sys.executable, str(sh)]


def test_command_transient_failure_retried(fresh_hub, tmp_path):
    plan = FaultPlan.parse("file_mgr.command:fail:nth=1")
    be = CommandBackend(["true"], retry=_nosleep_policy(
        site="file_mgr.command", max_attempts=3))
    with installed(plan):
        assert be.exists("afs://whatever") is True  # retried through fault
    assert plan.stats()["file_mgr.command:fail"]["fired"] == 1
    assert fresh_hub.counter("pbox_retry_attempts_total").value(
        site="file_mgr.command") == 1
    assert fresh_hub.counter("pbox_faults_injected_total").value(
        site="file_mgr.command", kind="fail") == 1


def test_command_timeout_is_transient(tmp_path):
    be = CommandBackend(["bash", "-c", "sleep 5", "shim"], timeout=0.2,
                        retry=_nosleep_policy(site="file_mgr.command",
                                              max_attempts=2))
    t0 = time.monotonic()
    with pytest.raises(RetryExhausted) as ei:
        be._run("-ls", "x")
    assert isinstance(ei.value.last, TransientCommandError)
    assert "timed out" in str(ei.value.last)
    assert time.monotonic() - t0 < 3.0


def test_exists_distinguishes_absent_from_failure(tmp_path):
    cmd = _shim(tmp_path,
                "if args[0] == '-test':\n"
                "    p = args[2]\n"
                "    sys.exit(1 if 'absent' in p else "
                "(0 if 'present' in p else 2))\n"
                "sys.exit(2)\n")
    be = CommandBackend(cmd, retry=_nosleep_policy(
        site="file_mgr.command", max_attempts=2))
    assert be.exists("afs://present/file") is True
    assert be.exists("afs://absent/file") is False
    # rc=2 (cluster trouble) must RAISE, never report "does not exist"
    with pytest.raises(RetryExhausted) as ei:
        be.exists("afs://broken/file")
    assert isinstance(ei.value.last, TransientCommandError)


def test_upload_puts_tmp_then_renames(tmp_path):
    oplog = tmp_path / "ops.log"
    cmd = _shim(tmp_path,
                f"open({str(oplog)!r}, 'a').write(' '.join(args) + '\\n')\n"
                "def strip(p):\n"
                "    assert p.startswith('afs://'), p\n"
                "    return p[len('afs://'):]\n"
                "if args[0] == '-put':\n"
                "    dst = strip(args[2])\n"
                "    os.makedirs(os.path.dirname(dst), exist_ok=True)\n"
                "    shutil.copy(args[1], dst); sys.exit(0)\n"
                "if args[0] == '-mv':\n"
                "    os.replace(strip(args[1]), strip(args[2]))\n"
                "    sys.exit(0)\n"
                "sys.exit(2)\n")
    be = CommandBackend(cmd, retry=_nosleep_policy(max_attempts=1))
    src = tmp_path / "model.bin"
    src.write_bytes(b"payload")
    dst = tmp_path / "remote" / "model.bin"
    assert be.upload(str(src), f"afs://{dst}")
    assert dst.read_bytes() == b"payload"
    ops = [l.split() for l in oplog.read_text().splitlines()]
    assert ops[0][0] == "-put" and ".tmp-" in ops[0][2]
    assert ops[1][0] == "-mv" and ops[1][2] == f"afs://{dst}"
    assert not any(".tmp-" in str(p) for p in (tmp_path / "remote").iterdir())


# ---- prefetch producer-leak regression --------------------------------
def test_prefetch_consumer_abandon_unblocks_producer():
    produced = []
    upstream_closed = threading.Event()

    def items():
        try:
            for i in range(1000):
                produced.append(i)
                yield i
        finally:
            upstream_closed.set()

    it = prefetch_iter(items(), lambda x: x, capacity=2)
    got = [next(it) for _ in range(3)]
    assert got == [0, 1, 2]
    it.close()  # consumer walks away (break/GeneratorExit path)
    # the fix: producer unblocks from ch.put and the upstream generator
    # is closed; before it, the producer thread blocked forever
    assert upstream_closed.wait(5.0), "producer thread leaked"
    assert len(produced) < 1000


def test_prefetch_chained_abandon_unwinds_transitively():
    inner_closed = threading.Event()

    def items():
        try:
            for i in range(1000):
                yield i
        finally:
            inner_closed.set()

    stage1 = prefetch_iter(items(), lambda x: x + 1, capacity=2)
    stage2 = prefetch_iter(stage1, lambda x: x * 2, capacity=2)
    assert next(stage2) == 2
    stage2.close()
    assert inner_closed.wait(5.0), "chained producer leaked"


def test_prefetch_normal_completion_and_error_still_work():
    assert list(prefetch_iter(range(10), lambda x: x * x,
                              capacity=3)) == [x * x for x in range(10)]

    def boom(x):
        if x == 3:
            raise RuntimeError("prepare failed")
        return x

    with pytest.raises(RuntimeError, match="prepare failed"):
        list(prefetch_iter(range(10), boom, capacity=2))


def test_channel_cancel_unblocks_blocked_put():
    ch = Channel(capacity=1)
    ch.put("a")
    state = {}

    def producer():
        try:
            ch.put("b")  # blocks: channel full
            state["out"] = "returned"
        except ChannelClosed:
            state["out"] = "closed"

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    time.sleep(0.05)
    ch.cancel()
    th.join(timeout=5.0)
    assert not th.is_alive()
    assert state["out"] == "closed"
    assert len(ch) == 0  # cancel drops queued items


# ---- dataset quarantine + poison budgets ------------------------------
def _mini_files(tmp_path, n=3, rows=40):
    return generate_criteo_files(str(tmp_path / "data"), num_files=n,
                                 rows_per_file=rows, vocab_per_slot=50,
                                 seed=11)


def _mk_ds(files, kind="InMemoryDataset", bs=16):
    desc = DataFeedDesc.criteo(batch_size=bs)
    ds = DatasetFactory().create_dataset(kind, desc)
    ds.set_filelist(files)
    return ds


@pytest.mark.chaos
def test_quarantine_isolates_corrupt_file(tmp_path):
    files = _mini_files(tmp_path)
    with open(files[1], "w") as fh:
        fh.write("this is not criteo at all\n" * 10)
    with flags_scope(native_parse=False, poison_budget_files=1,
                     poison_budget_records=0):
        ds = _mk_ds(files)
        ds.load_into_memory()
    assert [p for p, _ in ds.quarantined_files] == [files[1]]
    assert len(ds) == 80  # the two healthy files fully loaded
    # a second clean load resets the quarantine list
    with flags_scope(native_parse=False, poison_budget_files=1):
        ds.set_filelist([files[0]])
        ds.load_into_memory()
    assert ds.quarantined_files == []


def test_quarantine_disabled_aborts_on_corrupt_file(tmp_path):
    files = _mini_files(tmp_path)
    with open(files[1], "w") as fh:
        fh.write("garbage\n" * 5)
    with flags_scope(native_parse=False, poison_budget_files=0,
                     poison_budget_records=0):
        ds = _mk_ds(files)
        with pytest.raises(PoisonedFileError):
            ds.load_into_memory()


def test_record_budget_tolerates_within_limit(tmp_path):
    files = _mini_files(tmp_path, n=1)
    with open(files[0], "a") as fh:
        fh.write("bad line one\nbad line two\n")
    with flags_scope(native_parse=False, poison_budget_records=2):
        ds = _mk_ds(files)
        ds.load_into_memory()  # exactly at budget: tolerated
    assert len(ds) == 40 and ds.quarantined_files == []
    with flags_scope(native_parse=False, poison_budget_records=1,
                     poison_budget_files=0):
        ds2 = _mk_ds(files)
        with pytest.raises(PoisonedFileError):
            ds2.load_into_memory()


@pytest.mark.chaos
def test_quarantine_missing_file_survivors_drain(tmp_path):
    files = _mini_files(tmp_path)
    bad = str(tmp_path / "data" / "no_such_file.txt")
    filelist = [files[0], bad, files[1], files[2]]
    with flags_scope(native_parse=False, poison_budget_files=1):
        ds = _mk_ds(filelist)
        ds.load_into_memory()
    assert [p for p, _ in ds.quarantined_files] == [bad]
    assert len(ds) == 120  # surviving readers drained every healthy file


@pytest.mark.chaos
def test_queue_dataset_quarantines_midstream(tmp_path):
    files = _mini_files(tmp_path)
    with open(files[1], "w") as fh:
        fh.write("junk\n" * 8)
    with flags_scope(native_parse=False, poison_budget_files=1,
                     poison_budget_records=0):
        ds = _mk_ds(files, kind="QueueDataset", bs=16)
        n = sum(b.label.shape[0] for b in ds.batches())
    assert n == 80
    assert [p for p, _ in ds.quarantined_files] == [files[1]]


@pytest.mark.chaos
def test_fault_corrupt_record_quarantines_exact_file(tmp_path, fresh_hub):
    """ISSUE 2 acceptance: a seeded corrupt-record fault poisons exactly
    the targeted file; the quarantine list and counters are
    deterministic across runs with the same seed."""
    files = _mini_files(tmp_path)
    target = os.path.basename(files[2])

    def run():
        reset_hub()
        plan = FaultPlan.parse(
            f"parser.record:corrupt:match=*{target}*", seed=5)
        with flags_scope(native_parse=False, poison_budget_files=2,
                         poison_budget_records=0, read_thread_num=4):
            ds = _mk_ds(files)
            with installed(plan):
                ds.load_into_memory()
        return ([p for p, _ in ds.quarantined_files], len(ds),
                plan.stats())

    q1, n1, s1 = run()
    q2, n2, s2 = run()
    assert q1 == q2 == [files[2]]
    assert n1 == n2 == 80
    assert s1 == s2
    assert s1["parser.record:corrupt"]["fired"] >= 1
    assert get_hub().counter("pbox_files_quarantined_total").value() == 1


def test_poison_budget_exceeded_names_condition(tmp_path):
    """Blowing the FILE budget surfaces as PoisonBudgetExceeded (cause
    chained), not whichever error the last bad file happened to raise."""
    files = _mini_files(tmp_path)
    for f in (files[0], files[1]):
        with open(f, "w") as fh:
            fh.write("junk\n" * 3)
    with flags_scope(native_parse=False, poison_budget_files=1,
                     poison_budget_records=0):
        ds = _mk_ds(files)
        with pytest.raises(PoisonBudgetExceeded) as ei:
            ds.load_into_memory()
    assert isinstance(ei.value.__cause__, PoisonedFileError)
    assert len(ds.quarantined_files) == 1  # the budgeted one


@pytest.mark.chaos
def test_transient_open_fault_retried_not_quarantined(tmp_path,
                                                      fresh_hub):
    """An injected transient open failure exercises the dataset.open
    RetryPolicy and never reaches the quarantine budget."""
    files = _mini_files(tmp_path, n=1)
    plan = FaultPlan.parse("dataset.open:fail:nth=1")
    with flags_scope(native_parse=False, poison_budget_files=1,
                     retry_base_delay_sec=0.001, read_thread_num=2):
        ds = _mk_ds(files)
        with installed(plan):
            ds.load_into_memory()
    assert ds.quarantined_files == []
    assert len(ds) == 40
    assert fresh_hub.counter("pbox_retry_attempts_total").value(
        site="dataset.open") == 1


def test_native_load_quarantines_unreadable_file(tmp_path):
    """The native-columnar fast path isolates per-file failures too."""
    files = _mini_files(tmp_path)
    bad = str(tmp_path / "data" / "missing.txt")
    with flags_scope(poison_budget_files=1):
        ds = _mk_ds([files[0], bad, files[2]])
        ds.load_into_memory()
    assert [p for p, _ in ds.quarantined_files] == [bad]
    assert len(ds) == 80


# ---- trainer/checkpoint chaos -----------------------------------------
@pytest.fixture()
def trainer_setup(tmp_path):
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    files = generate_criteo_files(str(tmp_path / "data"), num_files=1,
                                  rows_per_file=200, vocab_per_slot=30,
                                  seed=3)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 2048
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    def mk():
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)
        t = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=2048)
        return Trainer(CtrDnn(hidden=(8,)), t, desc, tx=optax.adam(1e-2))

    return ds, mk, str(tmp_path / "ckpt")


@pytest.mark.chaos
def test_checkpoint_mid_save_crash_restores_last_consistent(trainer_setup):
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    ds, mk, root = trainer_setup
    tr = mk()
    cm = CheckpointManager(root)
    tr.train_pass(ds)
    cm.save(tr)
    good_step = tr.global_step
    tr.train_pass(ds)
    plan = FaultPlan.parse("checkpoint.save_commit:fail:nth=1,exc=crash")
    with installed(plan):
        with pytest.raises(InjectedCrash):
            cm.save(tr)
    # a fresh manager (the restarted process) recovers the last
    # consistent checkpoint; the torn temp dir is ignored
    cm2 = CheckpointManager(root)
    tr2 = mk()
    assert cm2.restore(tr2) == good_step
    assert tr2.global_step == good_step


def test_checkpoint_checksum_rejects_corruption(trainer_setup):
    from paddlebox_tpu.train.checkpoint import (CheckpointCorruptError,
                                                CheckpointManager)
    ds, mk, root = trainer_setup
    tr = mk()
    cm = CheckpointManager(root)
    tr.train_pass(ds)
    path = cm.save(tr)
    meta = cm._meta(tr.global_step)
    assert set(meta["checksums"]) == {"sparse.npz", "dense.pkl"}
    # flip bytes in the sparse payload → restore must refuse, loudly
    target = os.path.join(path, "sparse.npz")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(target, "wb") as fh:
        fh.write(bytes(blob))
    tr2 = mk()
    with pytest.raises(CheckpointCorruptError, match="corrupt"):
        cm.restore(tr2)


def test_checkpoint_without_checksums_still_restores(trainer_setup):
    """Pre-checksum checkpoints (no ``checksums`` key, no ``meta.sha256``
    sidecar — the pre-hardening era wrote neither) verify trivially."""
    import json
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    ds, mk, root = trainer_setup
    tr = mk()
    cm = CheckpointManager(root)
    tr.train_pass(ds)
    cm.save(tr)
    mp = os.path.join(cm._dir(tr.global_step), "meta.json")
    meta = json.load(open(mp))
    del meta["checksums"]
    with open(mp, "w") as fh:
        json.dump(meta, fh)
    os.unlink(os.path.join(cm._dir(tr.global_step), "meta.sha256"))
    tr2 = mk()
    assert cm.restore(tr2) == tr.global_step


@pytest.mark.chaos
def test_run_pass_retries_from_checkpoint(trainer_setup, fresh_hub):
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    ds, mk, root = trainer_setup
    sink = MemorySink()
    fresh_hub.add_sink(sink)
    tr = mk()
    cm = CheckpointManager(root)
    tr.run_pass(ds)
    cm.save(tr)
    saved_step = tr.global_step
    plan = FaultPlan.parse("trainer.pass:fail:nth=1")  # 1st attempt dies
    with installed(plan):
        out = tr.run_pass(ds, checkpoint=cm, max_retries=1)
    assert np.isfinite(out["last_loss"])
    # rollback happened: the retried pass re-ran from the saved step
    assert tr.global_step == saved_step + out["batches"]
    assert fresh_hub.counter("pbox_pass_retries_total").value() == 1
    evs = [e for e in sink.events if e["event"] == "pass_retry"]
    assert evs and evs[0]["attempt"] == 1
    # pass events carry the resilience counter block
    pevs = [e for e in sink.events if e["event"] == "pass"]
    assert pevs and pevs[-1]["resilience"]["pass_retries"] == 1


def test_run_pass_exhausted_budget_raises(trainer_setup):
    ds, mk, _ = trainer_setup
    tr = mk()
    plan = FaultPlan.parse("trainer.pass:fail:times=0")
    with installed(plan):
        with pytest.raises(TransientInjectedError):
            tr.run_pass(ds, max_retries=2)
    assert plan.stats()["trainer.pass:fail"]["fired"] == 3


def test_run_pass_non_recoverable_raises_immediately(trainer_setup):
    ds, mk, _ = trainer_setup
    tr = mk()
    plan = FaultPlan.parse("trainer.pass:fail:exc=crash")
    with installed(plan):
        with pytest.raises(InjectedCrash):
            tr.run_pass(ds, max_retries=5)
    assert plan.stats()["trainer.pass:fail"]["fired"] == 1


# ---- watchdog escalation ladder ---------------------------------------
class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _stalled_watchdog(clock, store, **kw):
    wd = StragglerWatchdog(store, process_index=0, num_processes=2,
                           step_lag=10, heartbeat_timeout=30.0,
                           clock=clock, hub=TelemetryHub(), **kw)
    store.publish(0, 100, clock())
    store.publish(1, 0, clock())  # 100 behind: permanent straggler
    return wd


def test_escalation_ladder_fires_in_order():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    fired = []
    saves = []
    wd = _stalled_watchdog(
        clock, store,
        escalations=[
            (10.0, requeue_pass_action(lambda reps: fired.append(
                ("requeue", reps[0].process)))),
            (20.0, abort_with_checkpoint_action(
                lambda: saves.append("ckpt"))),
        ])
    wd.poll_once()                     # detection at t0: no rung yet
    assert fired == [] and saves == []
    clock.t += 12
    wd.poll_once()                     # past rung 1 only
    assert fired == [("requeue", 1)] and saves == []
    assert wd._abort_exc is None
    clock.t += 10
    wd.poll_once()                     # past rung 2: snapshot then abort
    assert saves == ["ckpt"]
    with pytest.raises(StragglerTimeout):
        wd.beat(101)
    # rungs fire once per stall episode
    clock.t += 5
    wd.poll_once()
    assert fired == [("requeue", 1)] and saves == ["ckpt"]


def test_escalation_resets_when_stall_clears():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    fired = []
    wd = _stalled_watchdog(
        clock, store,
        escalations=[(10.0, requeue_pass_action(
            lambda reps: fired.append(clock.t)))])
    wd.poll_once()
    clock.t += 15
    wd.poll_once()
    assert len(fired) == 1
    store.publish(1, 95, clock())      # straggler catches up
    wd.poll_once()                     # healthy: ladder resets
    clock.t += 5
    store.publish(1, 0, clock())       # regression? no — step going
    store.publish(1, 0, clock())       # backwards reads as behind again
    store.publish(0, 200, clock())
    wd.poll_once()                     # new stall episode begins
    clock.t += 15
    wd.poll_once()
    assert len(fired) == 2


def test_legacy_abort_after_still_works():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    wd = _stalled_watchdog(clock, store, abort_after=20.0)
    wd.poll_once()
    wd.beat(101)
    clock.t += 25
    wd.poll_once()
    with pytest.raises(StragglerTimeout):
        wd.beat(102)


def test_escalation_emits_events():
    clock = FakeClock()
    store = LocalHeartbeatStore()
    hub = TelemetryHub()
    sink = MemorySink()
    hub.add_sink(sink)
    wd = StragglerWatchdog(store, 0, 2, step_lag=10, clock=clock, hub=hub,
                           escalations=[(5.0, requeue_pass_action(
                               lambda reps: None))])
    store.publish(0, 100, clock())
    store.publish(1, 0, clock())
    wd.poll_once()
    clock.t += 6
    wd.poll_once()
    evs = [e for e in sink.events if e["event"] == "straggler_escalation"]
    assert evs and evs[0]["action"] == "requeue_pass"
    assert hub.counter("pbox_straggler_escalations_total").value(
        action="requeue_pass") == 1
