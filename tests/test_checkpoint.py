"""CheckpointManager: base+delta chains, atomicity, retention, resume."""

import os

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer
from paddlebox_tpu.train.checkpoint import CheckpointManager


@pytest.fixture()
def setup(tmp_path):
    files = generate_criteo_files(str(tmp_path / "data"), num_files=1,
                                  rows_per_file=600, vocab_per_slot=40,
                                  seed=5)
    desc = DataFeedDesc.criteo(batch_size=64)
    desc.key_bucket_min = 2048
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    def mk():
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)
        t = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=2048)
        return Trainer(CtrDnn(hidden=(16,)), t, desc, tx=optax.adam(1e-2))

    return ds, mk, str(tmp_path / "ckpt")


def _params_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-7)


def test_base_save_restore_roundtrip(setup):
    ds, mk, root = setup
    tr = mk()
    tr.train_pass(ds)
    cm = CheckpointManager(root)
    cm.save(tr)
    step = tr.global_step

    tr2 = mk()
    got = cm.restore(tr2)
    assert got == step == tr2.global_step
    _params_equal(tr.state.params, tr2.state.params)
    assert tr2.table.feature_count == tr.table.feature_count
    # rows renumber on restore (fresh index): compare per-key contents
    keys, rows = tr.table.index.items()
    rows2 = tr2.table.index.lookup(keys)
    assert (rows2 >= 0).all()
    np.testing.assert_allclose(
        np.asarray(tr2.state.table.data)[rows2],
        np.asarray(tr.state.table.data)[rows], rtol=1e-6)
    # restored trainer keeps training without issue
    r = tr2.train_pass(ds)
    assert np.isfinite(r["last_loss"])


def test_delta_chain_restore(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds)
    cm.save(tr)                       # base
    tr.train_pass(ds)
    cm.save(tr, delta=True)           # delta 1
    tr.train_pass(ds)
    cm.save(tr, delta=True)           # delta 2
    final_step = tr.global_step

    tr2 = mk()
    assert cm.restore(tr2) == final_step
    _params_equal(tr.state.params, tr2.state.params)
    tr.sync_table()
    keys, rows = tr.table.index.items()
    rows2 = tr2.table.index.lookup(keys)
    assert (rows2 >= 0).all()
    d1 = np.asarray(tr.state.table.data)[rows]
    d2 = np.asarray(tr2.table.state.data)[rows2]
    np.testing.assert_allclose(d2, d1, rtol=1e-6)


def test_delta_without_base_raises(setup):
    ds, mk, root = setup
    tr = mk()
    tr.train_pass(ds)
    cm = CheckpointManager(root)
    with pytest.raises(ValueError):
        cm.save(tr, delta=True)


def test_retention_keeps_base_of_live_delta(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=2)
    tr.train_pass(ds)
    cm.save(tr)                        # base B
    base_step = tr.global_step
    for _ in range(3):
        tr.train_pass(ds)
        cm.save(tr, delta=True)        # deltas; retention keeps last 2
    steps = cm.steps()
    assert base_step in steps, "base evicted while deltas depend on it"
    # latest restorable after retention, with EXACT table contents — a
    # dropped intermediate delta would silently revert its rows
    tr2 = mk()
    assert cm.restore(tr2) == tr.global_step
    tr.sync_table()
    keys, rows = tr.table.index.items()
    rows2 = tr2.table.index.lookup(keys)
    assert (rows2 >= 0).all()
    np.testing.assert_allclose(
        np.asarray(tr2.table.state.data)[rows2],
        np.asarray(tr.state.table.data)[rows], rtol=1e-6)


def test_restore_empty_returns_none(setup):
    _, mk, root = setup
    cm = CheckpointManager(root)
    assert cm.restore(mk()) is None
    assert cm.latest_step() is None


def test_chain_gap_detected(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds); cm.save(tr)
    tr.train_pass(ds); cm.save(tr, delta=True)
    mid_step = tr.global_step
    tr.train_pass(ds); cm.save(tr, delta=True)
    # simulate the lost-intermediate-delta scenario
    import shutil
    shutil.rmtree(cm._dir(mid_step))
    with pytest.raises(FileNotFoundError):
        cm.restore(mk())


def test_interrupted_resave_recovers(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root)
    tr.train_pass(ds)
    cm.save(tr)
    step = tr.global_step
    # simulate a crash between the two renames of a re-save at the same
    # step: only the aside dir remains
    os.replace(cm._dir(step), cm._dir(step) + ".old-999")
    cm2 = CheckpointManager(root)           # init runs recovery
    assert cm2.latest_step() == step
    assert cm2.restore(mk()) == step


def test_delta_resave_same_step_no_loop(setup):
    """Re-saving a delta at the same step must inherit the OLD dir's
    predecessor link, not point at itself (infinite _chain loop)."""
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds); cm.save(tr)
    tr.train_pass(ds)
    cm.save(tr, delta=True)
    cm.save(tr, delta=True)     # retry at the SAME step
    meta = cm._meta(tr.global_step)
    assert meta["prev_step"] != tr.global_step
    tr2 = mk()
    assert cm.restore(tr2) == tr.global_step  # terminates, correct chain


def test_delta_includes_preloaded_pass_rows(setup):
    """A checkpoint save landing between a pass's PRELOAD (build) and its
    training must not erase the pass's rows from the next delta —
    regression for build-time touched marking."""
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    from paddlebox_tpu.train import ResidentPass
    rp1 = ResidentPass.build(ds, tr.table)   # preload pass 1
    rp2 = ResidentPass.build(ds, tr.table)   # preload pass 2 (same keys)
    tr.train_pass_resident(rp1)
    cm.save(tr)                              # base clears touched flags
    tr.train_pass_resident(rp2)              # trains rows built BEFORE save
    cm.save(tr, delta=True)
    meta = cm._meta(tr.global_step)
    assert meta["sparse_rows"] > 0, \
        "delta lost the preloaded pass's trained rows"
