"""CheckpointManager: base+delta chains, atomicity, retention, resume."""

import os

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer
from paddlebox_tpu.train.checkpoint import CheckpointManager


@pytest.fixture()
def setup(tmp_path):
    files = generate_criteo_files(str(tmp_path / "data"), num_files=1,
                                  rows_per_file=600, vocab_per_slot=40,
                                  seed=5)
    desc = DataFeedDesc.criteo(batch_size=64)
    desc.key_bucket_min = 2048
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    def mk():
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)
        t = EmbeddingTable(mf_dim=4, capacity=1 << 13, cfg=cfg,
                           unique_bucket_min=2048)
        return Trainer(CtrDnn(hidden=(16,)), t, desc, tx=optax.adam(1e-2))

    return ds, mk, str(tmp_path / "ckpt")


def _params_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-7)


def test_base_save_restore_roundtrip(setup):
    ds, mk, root = setup
    tr = mk()
    tr.train_pass(ds)
    cm = CheckpointManager(root)
    cm.save(tr)
    step = tr.global_step

    tr2 = mk()
    got = cm.restore(tr2)
    assert got == step == tr2.global_step
    _params_equal(tr.state.params, tr2.state.params)
    assert tr2.table.feature_count == tr.table.feature_count
    # rows renumber on restore (fresh index): compare per-key contents
    keys, rows = tr.table.index.items()
    rows2 = tr2.table.index.lookup(keys)
    assert (rows2 >= 0).all()
    np.testing.assert_allclose(
        np.asarray(tr2.state.table.data)[rows2],
        np.asarray(tr.state.table.data)[rows], rtol=1e-6)
    # restored trainer keeps training without issue
    r = tr2.train_pass(ds)
    assert np.isfinite(r["last_loss"])


def test_delta_chain_restore(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds)
    cm.save(tr)                       # base
    tr.train_pass(ds)
    cm.save(tr, delta=True)           # delta 1
    tr.train_pass(ds)
    cm.save(tr, delta=True)           # delta 2
    final_step = tr.global_step

    tr2 = mk()
    assert cm.restore(tr2) == final_step
    _params_equal(tr.state.params, tr2.state.params)
    tr.sync_table()
    keys, rows = tr.table.index.items()
    rows2 = tr2.table.index.lookup(keys)
    assert (rows2 >= 0).all()
    d1 = np.asarray(tr.state.table.data)[rows]
    d2 = np.asarray(tr2.table.state.data)[rows2]
    np.testing.assert_allclose(d2, d1, rtol=1e-6)


def test_delta_without_base_raises(setup):
    ds, mk, root = setup
    tr = mk()
    tr.train_pass(ds)
    cm = CheckpointManager(root)
    with pytest.raises(ValueError):
        cm.save(tr, delta=True)


def test_retention_keeps_base_of_live_delta(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=2)
    tr.train_pass(ds)
    cm.save(tr)                        # base B
    base_step = tr.global_step
    for _ in range(3):
        tr.train_pass(ds)
        cm.save(tr, delta=True)        # deltas; retention keeps last 2
    steps = cm.steps()
    assert base_step in steps, "base evicted while deltas depend on it"
    # latest restorable after retention, with EXACT table contents — a
    # dropped intermediate delta would silently revert its rows
    tr2 = mk()
    assert cm.restore(tr2) == tr.global_step
    tr.sync_table()
    keys, rows = tr.table.index.items()
    rows2 = tr2.table.index.lookup(keys)
    assert (rows2 >= 0).all()
    np.testing.assert_allclose(
        np.asarray(tr2.table.state.data)[rows2],
        np.asarray(tr.state.table.data)[rows], rtol=1e-6)


def test_restore_empty_returns_none(setup):
    _, mk, root = setup
    cm = CheckpointManager(root)
    assert cm.restore(mk()) is None
    assert cm.latest_step() is None


def test_chain_gap_detected(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds); cm.save(tr)
    tr.train_pass(ds); cm.save(tr, delta=True)
    mid_step = tr.global_step
    tr.train_pass(ds); cm.save(tr, delta=True)
    # simulate the lost-intermediate-delta scenario
    import shutil
    shutil.rmtree(cm._dir(mid_step))
    with pytest.raises(FileNotFoundError):
        cm.restore(mk())


def test_interrupted_resave_recovers(setup):
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root)
    tr.train_pass(ds)
    cm.save(tr)
    step = tr.global_step
    # simulate a crash between the two renames of a re-save at the same
    # step: only the aside dir remains
    os.replace(cm._dir(step), cm._dir(step) + ".old-999")
    cm2 = CheckpointManager(root)           # init runs recovery
    assert cm2.latest_step() == step
    assert cm2.restore(mk()) == step


def test_delta_resave_same_step_no_loop(setup):
    """Re-saving a delta at the same step must inherit the OLD dir's
    predecessor link, not point at itself (infinite _chain loop)."""
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    tr.train_pass(ds); cm.save(tr)
    tr.train_pass(ds)
    cm.save(tr, delta=True)
    cm.save(tr, delta=True)     # retry at the SAME step
    meta = cm._meta(tr.global_step)
    assert meta["prev_step"] != tr.global_step
    tr2 = mk()
    assert cm.restore(tr2) == tr.global_step  # terminates, correct chain


def test_delta_includes_preloaded_pass_rows(setup):
    """A checkpoint save landing between a pass's PRELOAD (build) and its
    training must not erase the pass's rows from the next delta —
    regression for build-time touched marking."""
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=10)
    from paddlebox_tpu.train import ResidentPass
    rp1 = ResidentPass.build(ds, tr.table)   # preload pass 1
    rp2 = ResidentPass.build(ds, tr.table)   # preload pass 2 (same keys)
    tr.train_pass_resident(rp1)
    cm.save(tr)                              # base clears touched flags
    tr.train_pass_resident(rp2)              # trains rows built BEFORE save
    cm.save(tr, delta=True)
    meta = cm._meta(tr.global_step)
    assert meta["sparse_rows"] > 0, \
        "delta lost the preloaded pass's trained rows"


# ---------------------------------------------------------------------------
# artifact/publishing layer integration (artifacts.py, ISSUE 14)
# ---------------------------------------------------------------------------

def test_retention_defers_leased_checkpoint(setup):
    """Satellite: _retain must not sweep a checkpoint a concurrent
    reader is mid-adopting — a held lease (cm.lease / restore's own)
    defers deletion; release lets the next sweep reclaim it."""
    ds, mk, root = setup
    tr = mk()
    cm = CheckpointManager(root, keep=1)
    tr.train_pass(ds)
    cm.save(tr)
    s1 = tr.global_step
    d1 = os.path.join(root, f"ckpt-{s1:012d}")
    lease = cm.lease(s1)                 # a reader mid-adoption
    try:
        tr.train_pass(ds)
        cm.save(tr)                      # keep=1 would sweep s1 …
        assert os.path.isdir(d1), (
            "retention swept a checkpoint under a held lease")
    finally:
        lease.release()
    tr.train_pass(ds)
    cm.save(tr)                          # lease gone: reclaimed now
    assert not os.path.isdir(d1)
    # the stale lease FENCES instead of pretending it still holds
    from paddlebox_tpu.artifacts import ArtifactLeaseLostError
    with pytest.raises(ArtifactLeaseLostError):
        lease.check()


def test_boundary_saves_publish_artifacts(setup):
    """Boundary checkpoints (no cursor, or a stream cursor whose open
    window is empty — train_stream's stream-boundary saves) publish
    into an attached ArtifactStore with parent lineage; mid-pass cursor
    saves stay checkpoint-only."""
    from paddlebox_tpu.artifacts import ArtifactStore
    ds, mk, root = setup
    store = ArtifactStore(root + "_art")
    tr = mk()
    cm = CheckpointManager(root, artifacts=store)
    tr.train_pass(ds)
    cm.save(tr)                               # base boundary → publishes
    assert len(store.versions()) == 1
    tr.train_pass(ds)
    cm.save(tr, delta=True,                   # MID-PASS cursor: no publish
            cursor={"pass_seq": 2, "batch_index": 3,
                    "global_step": int(tr.global_step)})
    assert len(store.versions()) == 1
    tr.train_pass(ds)
    cm.save(tr, delta=True)                   # boundary delta → publishes
    tr.train_pass(ds)
    cm.save(tr, delta=True,                   # STREAM boundary (empty
            cursor={"global_step": int(tr.global_step),   # open window)
                    "stream": {"window_files": [],        # → publishes
                               "files_completed": ["a", "b"],
                               "windows_completed": 2}})
    vs = store.versions()
    assert len(vs) == 3
    m_base = store.read_manifest(vs[0])
    m_delta = store.read_manifest(vs[1])
    m_stream = store.read_manifest(vs[2])
    assert m_base["kind"] == "base" and m_base["parent"] is None
    assert m_delta["kind"] == "delta" and m_delta["parent"] == vs[0]
    assert m_stream["parent"] == vs[1]
    assert m_stream["refs"]["cursor"]["files_completed"] == 2
    assert m_base["meta"]["producer"] == "checkpoint"
    assert "sparse.npz" in m_base["files"]
    assert "dense.pkl" in m_base["files"]


def test_artifact_publish_path_byte_identical(setup):
    """Acceptance: a batch job publishing through ArtifactStore
    produces a restore state_digest bit-identical to the pre-PR
    checkpoint path — both via CheckpointManager.restore and via
    artifact-only adoption (adopt_artifact)."""
    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.train.checkpoint import (adopt_artifact,
                                                state_digest)
    ds, mk, root = setup
    # pre-PR path: plain manager, no store attached
    tr1 = mk()
    tr1.train_pass(ds)
    cm1 = CheckpointManager(root + "_plain")
    cm1.save(tr1)
    tr1.train_pass(ds)
    cm1.save(tr1, delta=True)
    r1 = mk()
    CheckpointManager(root + "_plain").restore(r1)
    d_pre = state_digest(r1)
    # publish-enabled path: identical job with an ArtifactStore attached
    store = ArtifactStore(root + "_art2")
    tr2 = mk()
    tr2.train_pass(ds)
    cm2 = CheckpointManager(root + "_pub", artifacts=store)
    cm2.save(tr2)
    tr2.train_pass(ds)
    cm2.save(tr2, delta=True)
    r2 = mk()
    CheckpointManager(root + "_pub").restore(r2)
    assert state_digest(r2) == d_pre, (
        "attaching the artifact store changed the checkpoint path")
    # artifact-only restore: verify chain → base+delta replay
    r3 = mk()
    assert adopt_artifact(r3, store) == tr2.global_step
    assert state_digest(r3) == d_pre, (
        "artifact adoption diverges from the checkpoint restore")


def test_shared_store_roots_do_not_cross_link(setup, tmp_path):
    """Review regression: two jobs (different checkpoint roots) sharing
    ONE artifact store must keep their lineages apart — step counters
    overlap, so the lookup is scoped by root, never by step alone."""
    from paddlebox_tpu.artifacts import ArtifactStore
    ds, mk, root = setup
    store = ArtifactStore(str(tmp_path / "shared_art"))
    tra, trb = mk(), mk()
    cma = CheckpointManager(root + "_jobA", artifacts=store)
    cmb = CheckpointManager(root + "_jobB", artifacts=store)
    tra.train_pass(ds)
    cma.save(tra)                    # both jobs publish a base at the
    trb.train_pass(ds)
    cmb.save(trb)                    # SAME step number
    tra.train_pass(ds)
    cma.save(tra, delta=True)
    trb.train_pass(ds)
    cmb.save(trb, delta=True)
    roots = {}
    for aid in store.versions():
        m = store.read_manifest(aid)
        roots.setdefault(m["meta"]["root"], []).append(m)
    assert len(roots) == 2
    for chain in roots.values():    # each delta links to ITS OWN base
        base = [m for m in chain if m["kind"] == "base"]
        delta = [m for m in chain if m["kind"] == "delta"]
        assert len(base) == 1 and len(delta) == 1
        assert delta[0]["parent"] == base[0]["artifact"]


def test_restore_to_unpublished_step_backfills_chain(setup):
    """Review regression: a restore onto a step that never published
    (a mid-pass crash checkpoint) must neither halt publishing until
    the next base nor link past the gap — the missing chain links
    backfill from the checkpoint dirs, and the next boundary delta
    chains soundly on top."""
    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.train.checkpoint import (adopt_artifact,
                                                state_digest)
    ds, mk, root = setup
    store = ArtifactStore(root + "_art3")
    tr = mk()
    cm = CheckpointManager(root, artifacts=store)
    tr.train_pass(ds)
    cm.save(tr)                              # published base
    tr.train_pass(ds)
    mid_step = int(tr.global_step)
    cm.save(tr, delta=True,                  # mid-pass: NOT published
            cursor={"pass_seq": 2, "batch_index": 3,
                    "global_step": mid_step})
    assert len(store.versions()) == 1
    # crash + restart: fresh manager restores the mid-pass checkpoint
    tr2 = mk()
    cm2 = CheckpointManager(root, artifacts=store)
    assert cm2.restore(tr2) == mid_step
    # the restore BACKFILLED the unpublished chain link
    assert len(store.versions()) == 2
    backfilled = store.read_manifest(store.versions()[-1])
    assert backfilled["meta"]["step"] == mid_step
    assert backfilled["parent"] == store.versions()[0]
    assert "cursor" in backfilled["refs"]    # marked as a mid-pass link
    # ... but it is CHAIN-ONLY: an unpinned reader never lands on the
    # half-trained pass state — open(None) skips to the boundary base
    assert backfilled["adoptable"] is False
    with store.open() as h:
        assert h.aid == store.versions()[0]
    # the next boundary delta publishes and chains on the backfill
    tr2.train_pass(ds)
    cm2.save(tr2, delta=True)
    vs = store.versions()
    assert len(vs) == 3
    tip = store.read_manifest(vs[-1])
    assert tip["parent"] == vs[-2]
    # and the artifact chain reproduces the trainer bit-for-bit
    r = mk()
    assert adopt_artifact(r, store) == tr2.global_step
    assert state_digest(r) == state_digest(tr2)
