"""Artifact/publishing layer (artifacts.py, ISSUE 14): crash-safe
versioned publish, checksum-chain adoption, lease-fenced readers,
provably-stale reaping, lineage-aware retention — plus the
cross-process REAL-SIGKILL publisher round trip."""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddlebox_tpu.artifacts import (ArtifactCorruptError,
                                     ArtifactLeaseLostError,
                                     ArtifactLineageError, ArtifactStore,
                                     LeaseRegistry, MANIFEST)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dead_pid() -> int:
    """A pid that PROVABLY belonged to a dead same-host process."""
    proc = subprocess.Popen(["true"])
    proc.wait()
    return proc.pid


def _writer(payload: bytes):
    def write(p):
        with open(p, "wb") as fh:
            fh.write(payload)
    return write


@pytest.fixture()
def store(tmp_path):
    return ArtifactStore(str(tmp_path / "registry"))


# ---------------------------------------------------------------------------
# publish / manifest / adoption
# ---------------------------------------------------------------------------

def test_publish_roundtrip_and_manifest_schema(store):
    a1 = store.publish({"rows.bin": _writer(b"base" * 64)}, kind="base",
                       refs={"cursor": {"global_step": 7}},
                       meta={"step": 7})
    m = store.read_manifest(a1)
    assert m["artifact"] == a1 and m["epoch"] == 1
    assert m["kind"] == "base" and m["parent"] is None
    rec = m["files"]["rows.bin"]
    assert rec["bytes"] == 256
    assert rec["sha256"] == hashlib.sha256(b"base" * 64).hexdigest()
    assert m["refs"]["cursor"]["global_step"] == 7
    assert m["meta"]["step"] == 7
    with store.open() as h:
        assert h.aid == a1
        assert h.read("rows.bin") == b"base" * 64


def test_epochs_monotone_and_lineage_chain(store):
    a1 = store.publish({"f": _writer(b"1")}, kind="base")
    a2 = store.publish({"f": _writer(b"2")}, kind="delta", parent=a1)
    a3 = store.publish({"f": _writer(b"3")}, kind="delta", parent=a2)
    assert store.versions() == [a1, a2, a3]
    assert [store.epoch_of(a) for a in (a1, a2, a3)] == [1, 2, 3]
    with store.open() as h:
        assert [m["artifact"] for m in h.chain] == [a1, a2, a3]


def test_delta_requires_published_parent(store):
    with pytest.raises(ArtifactLineageError):
        store.publish({"f": _writer(b"x")}, kind="delta")
    with pytest.raises(ArtifactLineageError):
        store.publish({"f": _writer(b"x")}, kind="delta",
                      parent="v0000000099")


def test_existing_files_hardlinked(store, tmp_path):
    src = tmp_path / "payload.npz"
    src.write_bytes(b"precomputed")
    aid = store.publish({"payload.npz": str(src)}, kind="base")
    with store.open(aid) as h:
        assert h.read("payload.npz") == b"precomputed"


def test_corrupt_payload_refused_and_degrades(store):
    a1 = store.publish({"f": _writer(b"good-one")}, kind="base")
    a2 = store.publish({"f": _writer(b"good-two")}, kind="delta",
                       parent=a1)
    p = os.path.join(store.version_dir(a2), "f")
    with open(p, "wb") as fh:
        fh.write(b"good-tw0")   # flipped byte, same length
    with pytest.raises(ArtifactCorruptError):
        store.open(a2)          # explicit version: loud refusal
    with store.open() as h:     # unpinned: degrade to verifiable parent
        assert h.aid == a1


def test_torn_manifest_refused(store):
    a1 = store.publish({"f": _writer(b"ok")}, kind="base")
    a2 = store.publish({"f": _writer(b"ok2")}, kind="delta", parent=a1)
    mp = os.path.join(store.version_dir(a2), MANIFEST)
    with open(mp, "a") as fh:
        fh.write(" ")           # torn/edited manifest: sidecar mismatch
    with pytest.raises(ArtifactCorruptError):
        store.open(a2)
    with store.open() as h:
        assert h.aid == a1


def test_corrupt_parent_fails_whole_chain(store):
    """Adoption verifies the FULL lineage — a corrupt BASE under a
    healthy delta refuses the delta too (restoring through it would
    replay garbage rows)."""
    a1 = store.publish({"f": _writer(b"base")}, kind="base")
    store.publish({"f": _writer(b"delta")}, kind="delta", parent=a1)
    p = os.path.join(store.version_dir(a1), "f")
    with open(p, "wb") as fh:
        fh.write(b"b4se")
    with pytest.raises(ArtifactCorruptError):
        store.open()            # nothing verifiable left at all


# ---------------------------------------------------------------------------
# leases: fencing, reaping, retention
# ---------------------------------------------------------------------------

def test_lease_fences_after_reap_and_reader_reopens(store):
    """Satellite: stale-lease reaping must not rely on wall-clock
    alone — a paused reader whose lease was reaped detects the loss on
    its next read (ArtifactLeaseLostError) and re-opens, instead of
    serving from possibly-swept files."""
    a1 = store.publish({"f": _writer(b"v1")}, kind="base")
    h = store.open(a1)
    assert h.read("f") == b"v1"
    # a zero-TTL sweeper must NOT reap a same-host ALIVE holder — age
    # alone is no proof of death on the holder's own host
    sweeper = ArtifactStore(store.root, lease_ttl_sec=0.0, sweep=False)
    assert sweeper.lease_registry().reap_stale() == []
    assert h.lease.alive()
    # the reader "dies" (paused-then-reaped from the sweeper's view):
    # make the holder provably dead, then the reap takes the lease
    with open(h.lease.path) as fh:
        info = json.load(fh)
    info["pid"] = _dead_pid()
    with open(h.lease.path, "w") as fh:
        json.dump(info, fh)
    assert a1 in sweeper.lease_registry().reap_stale()
    # the paused reader resumes: every access now FENCES
    with pytest.raises(ArtifactLeaseLostError):
        h.path("f")
    with pytest.raises(ArtifactLeaseLostError):
        h.read("f")
    with pytest.raises(ArtifactLeaseLostError):
        h.heartbeat()           # cannot resurrect a reaped lease
    # re-open is the recovery path — the version still exists here
    with store.open() as h2:
        assert h2.aid == a1 and h2.read("f") == b"v1"


def test_reap_only_provably_stale(tmp_path):
    reg = LeaseRegistry(str(tmp_path / "leases"), ttl_sec=3600.0)
    fresh = reg.acquire("keep-me")
    # forge a lease from a dead same-host pid (a reaped subprocess
    # gives us a guaranteed-dead pid without guessing)
    pid = _dead_pid()
    dead_path = os.path.join(reg.root, f"dead-one.{pid}-cafe.lease")
    with open(dead_path, "w") as fh:
        json.dump({"name": "dead-one", "pid": pid,
                   "host": __import__("socket").gethostname(),
                   "created_unix": time.time()}, fh)
    reaped = reg.reap_stale()
    assert reaped == ["dead-one"]
    assert fresh.alive()
    assert reg.held("keep-me") and not reg.held("dead-one")
    # a FOREIGN-host lease can only be judged by heartbeat age
    foreign = os.path.join(reg.root, "far-away.12345-beef.lease")
    with open(foreign, "w") as fh:
        json.dump({"name": "far-away", "pid": 12345,
                   "host": "some-other-host"}, fh)
    assert reg.reap_stale() == []          # fresh heartbeat: kept
    old = time.time() - 7200
    os.utime(foreign, (old, old))          # idle past the TTL: reaped
    assert reg.reap_stale() == ["far-away"]
    fresh.release()


def test_heartbeat_refreshes_mtime(store):
    a1 = store.publish({"f": _writer(b"v1")}, kind="base")
    h = store.open(a1)
    old = os.stat(h.lease.path).st_mtime
    time.sleep(0.05)
    h.heartbeat()
    assert os.stat(h.lease.path).st_mtime >= old
    h.close()
    assert not h.lease.alive()


def test_retention_keeps_leased_and_lineage(store):
    a1 = store.publish({"f": _writer(b"1")}, kind="base")
    a2 = store.publish({"f": _writer(b"2")}, kind="delta", parent=a1)
    b1 = store.publish({"f": _writer(b"3")}, kind="base")
    b2 = store.publish({"f": _writer(b"4")}, kind="delta", parent=b1)
    h = store.open(a2)           # lease on the OLD chain's tip
    assert store.retain(keep=2) == []   # a2 leased; a1 is its lineage
    assert store.versions() == [a1, a2, b1, b2]
    h.close()
    assert store.retain(keep=2) == [a1, a2]
    assert store.versions() == [b1, b2]
    # b1 is b2's lineage parent: keep=1 still cannot remove it
    assert store.retain(keep=1) == []


def test_live_publisher_stage_not_swept(store):
    """The carcass sweep only takes PROVABLY dead writers' stages —
    a live same-host publisher's stage survives a concurrent open even
    past the TTL (a long multi-GB staging is not a carcass), with or
    without its marker file (the dir name carries the pid)."""
    stage = os.path.join(store.root, f".stage-{os.getpid()}-aa")
    os.makedirs(stage)
    with open(os.path.join(stage, "stage.json"), "w") as fh:
        json.dump({"pid": os.getpid(),
                   "host": __import__("socket").gethostname(),
                   "created_unix": time.time()}, fh)
    ArtifactStore(store.root, lease_ttl_sec=0.0)  # zero TTL: age says
    assert os.path.isdir(stage)                   # stale; pid says LIVE
    os.unlink(os.path.join(stage, "stage.json"))  # markerless (commit
    ArtifactStore(store.root, lease_ttl_sec=0.0)  # window): dirname pid
    assert os.path.isdir(stage)                   # still protects
    # a provably-dead writer's stage IS swept
    with open(os.path.join(stage, "stage.json"), "w") as fh:
        json.dump({"pid": _dead_pid(),
                   "host": __import__("socket").gethostname()}, fh)
    ArtifactStore(store.root)
    assert not os.path.isdir(stage)


# ---------------------------------------------------------------------------
# cross-process: REAL SIGKILL mid-publish (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

_PUBLISHER = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import jax
from paddlebox_tpu.artifacts import ArtifactStore
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.ps.table import FIELD_COL, TableState
from scripts.publish_check import table_digest

root = sys.argv[1]
store = ArtifactStore(root)
cfg = SparseSGDConfig(mf_create_thresholds=1e9)
t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
keys = np.arange(1, 201, dtype=np.uint64)
rows = t.index.assign(keys)
data = np.asarray(jax.device_get(t.state.data)).copy()
data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * 2.0
data[rows, FIELD_COL["show"]] = 1.0
t.state = TableState.from_logical(data, t.capacity)
t._touched[rows] = True
aid = store.publish({{"sparse.npz": lambda p: t.save_base(p)}},
                    kind="base", meta={{"step": 1}})
with open(os.path.join(root, "digest.txt"), "w") as fh:
    fh.write(aid + " " + table_digest(t))

# second publish: stage the payload, signal the parent, then HANG
# inside the writer — the parent SIGKILLs us mid-publish
def hang_writer(p):
    t._touched[rows] = True
    t.save_delta(p)
    with open(os.path.join(root, "STAGED"), "w") as fh:
        fh.write("1")
    time.sleep(600)

store.publish({{"sparse_delta.npz": hang_writer}}, kind="delta",
              parent=aid)
"""


def test_sigkill_mid_publish_reader_adopts_previous(tmp_path):
    """A subprocess publisher killed (real SIGKILL) mid-publish leaves
    only a stage carcass; a fresh reader sweeps it (dead pid ⇒ provably
    stale) and adopts the previous COMPLETE version with a
    bit-identical state digest."""
    from paddlebox_tpu.data.schema import DataFeedDesc
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.serving import ServingModel
    from scripts.publish_check import table_digest

    root = str(tmp_path / "registry")
    os.makedirs(root)
    proc = subprocess.Popen(
        [sys.executable, "-c", _PUBLISHER.format(repo=REPO), root],
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        staged = os.path.join(root, "STAGED")
        deadline = time.time() + 120
        while not os.path.isfile(staged):
            assert proc.poll() is None, "publisher died before staging"
            assert time.time() < deadline, "publisher never staged"
            time.sleep(0.05)
        os.kill(proc.pid, signal.SIGKILL)   # mid-publish, pre-rename
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    with open(os.path.join(root, "digest.txt")) as fh:
        v1, want_digest = fh.read().split()
    carcasses = [n for n in os.listdir(root) if n.startswith(".stage-")]
    assert carcasses, "SIGKILL left no stage carcass"
    store = ArtifactStore(root)      # dead-pid carcass swept on open
    assert not [n for n in os.listdir(root) if n.startswith(".stage-")]
    assert store.versions() == [v1], "half-publish leaked a version"
    srv = ServingModel(CtrDnn(hidden=(4,)),
                       DataFeedDesc.criteo(batch_size=16), mf_dim=4,
                       capacity=1 << 10)
    assert srv.adopt(store) == v1
    assert table_digest(srv.table) == want_digest, (
        "adopted state diverges from the publisher's recorded digest")
    srv.release()


def test_failed_publish_loses_no_delta_rows(store):
    """Review regression: publishing stages the delta with
    clear_touched=False and clears only AFTER the commit — a publish
    that dies pre-commit keeps every touched flag, so the retry's
    delta still carries the rows (they never silently vanish from the
    chain)."""
    import jax
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState
    from paddlebox_tpu.resilience.faults import (FaultPlan, InjectedCrash,
                                                 installed)
    from scripts.publish_check import table_digest

    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    helper = BoxPSHelper(t)

    def write(lo, hi, scale):
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = t.index.assign(keys)
        data = np.asarray(jax.device_get(t.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * scale
        t.state = TableState.from_logical(data, t.capacity)
        t._touched[rows] = True

    write(1, 51, 2.0)
    v1 = helper.publish_base(store)
    assert not t._touched.any(), "commit did not clear the flags"
    write(30, 81, 3.0)
    with installed(FaultPlan.parse("artifact.publish:fail:nth=1,"
                                   "exc=crash", seed=3)):
        with pytest.raises(InjectedCrash):
            helper.publish_delta(store)
    assert t._touched.any(), (
        "failed publish cleared the touched set — those rows would "
        "silently leave the delta chain")
    v2 = helper.publish_delta(store)     # retry carries every row
    # reader replay of the chain == the writer table, bit for bit
    reader = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    reader.load(os.path.join(store.version_dir(v1), "sparse.npz"))
    reader.load(os.path.join(store.version_dir(v2),
                             "sparse_delta.npz"), merge=True)
    assert table_digest(reader) == table_digest(t)
